!> Fortran example: dense 4x4x4 C2C round trip through the spfft_tpu C API
!> via the bind(C) interface module (include/spfft_tpu.f90).
!>
!> Role-equivalent of the reference Fortran example (reference:
!> examples/example.f90 — grid + transform creation, backward, forward on a
!> dense index set). Build (no gfortran in this container, so untested here;
!> tracks examples/example.c 1:1):
!>
!>   gfortran -I include example.f90 -L build -lspfft_tpu -o example_f
!>   SPFFT_TPU_PACKAGE_PATH=$PWD ./example_f
program example
  use iso_c_binding
  use spfft_tpu
  implicit none

  integer, parameter :: dim = 4
  integer, parameter :: n = dim * dim * dim
  integer(c_int), target :: triplets(3 * n)
  real(c_float), target :: values(2 * n), space(2 * n), roundtrip(2 * n)
  type(c_ptr) :: plan
  integer(c_int) :: status, x, y, z, i
  integer(c_long_long) :: num_values
  real(c_float) :: max_err
  character(len=256) :: package_path
  character(kind=c_char, len=257), target :: package_path_c

  i = 0
  do x = 0, dim - 1
    do y = 0, dim - 1
      do z = 0, dim - 1
        triplets(3 * i + 1) = x
        triplets(3 * i + 2) = y
        triplets(3 * i + 3) = z
        values(2 * i + 1) = real(i + 1)   ! real part
        values(2 * i + 2) = real(-i)      ! imaginary part
        i = i + 1
      end do
    end do
  end do

  call get_environment_variable("SPFFT_TPU_PACKAGE_PATH", package_path)
  package_path_c = trim(package_path) // c_null_char
  status = spfft_tpu_init(c_loc(package_path_c))
  if (status /= SPFFT_TPU_SUCCESS) stop "init failed"

  plan = c_null_ptr
  status = spfft_tpu_plan_create(plan, SPFFT_TPU_TRANS_C2C, dim, dim, dim, &
                                 int(n, c_long_long), triplets, &
                                 SPFFT_TPU_PREC_SINGLE, SPFFT_TPU_PALLAS_AUTO)
  if (status /= SPFFT_TPU_SUCCESS) stop "plan_create failed"

  status = spfft_tpu_plan_num_values(plan, num_values)
  if (status /= SPFFT_TPU_SUCCESS) stop "num_values failed"
  write (*, "(A,I0,A,I0,A,I0,A,I0,A)") "plan: ", num_values, &
    " frequency values on a ", dim, "x", dim, "x", dim, " grid"

  ! backward: frequency -> space (interleaved complex)
  status = spfft_tpu_backward(plan, c_loc(values), c_loc(space))
  if (status /= SPFFT_TPU_SUCCESS) stop "backward failed"

  ! forward with 1/N scaling must reproduce the input values
  status = spfft_tpu_forward(plan, c_loc(space), SPFFT_TPU_FULL_SCALING, &
                             c_loc(roundtrip))
  if (status /= SPFFT_TPU_SUCCESS) stop "forward failed"

  max_err = maxval(abs(roundtrip - values))
  write (*, "(A,ES10.3)") "max |roundtrip - values| = ", max_err
  if (max_err > 1.0e-3) stop "round trip mismatch"

  status = spfft_tpu_plan_destroy(plan)
  if (status /= SPFFT_TPU_SUCCESS) stop "plan_destroy failed"
  write (*, "(A)") "OK"
end program example

#!/usr/bin/env python
"""Multi-host distributed transform — how to run spfft_tpu on a TPU pod.

One process per host; each process contributes only its own shards' sparse
indices, the allgather-based plan build makes the identical global plan
everywhere (the reference's MPI stick-list exchange, indices.hpp:58-102),
and plan construction cross-checks parameters across hosts.

On a pod slice, launch with the standard JAX multi-process environment
(e.g. one process per host under a pod runtime), passing the coordinator:

    python examples/example_multihost.py --coordinator 10.0.0.1:8476 \
        --num-processes 4 --process-id $RANK

Run without arguments it degenerates to a single process and exercises the
same code path (this is what the test suite does).

STATUS: the multi-process launch path is UNTESTED on real multi-host
hardware — this container cannot start a >1-process JAX group (see
ROADMAP.md). The collective protocol behind it is unit-tested with 2- and
3-process stub worlds (tests/test_multihost.py), but treat the coordinator
invocation above as a recipe to validate on a pod, not a tested path.
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))
import spfft_tpu as sp  # noqa: E402
from spfft_tpu.parallel import multihost  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--coordinator", default=None,
                    help="coordinator address host:port (omit = 1 process)")
    ap.add_argument("--num-processes", type=int, default=None)
    ap.add_argument("--process-id", type=int, default=None)
    ap.add_argument("--dim", type=int, default=32)
    args = ap.parse_args()

    # MUST run before any other JAX call (like MPI_Init).
    multihost.initialize(args.coordinator, args.num_processes,
                         args.process_id)

    import jax
    from spfft_tpu.utils.workloads import (even_plane_split,
                                           round_robin_stick_partition,
                                           spherical_cutoff_triplets)

    n = args.dim
    n_shards = len(jax.devices())
    pidx, pcount = jax.process_index(), jax.process_count()
    shards_per_proc = n_shards // pcount

    # every process computes the same global partition, then keeps its own
    # shards — in a real application each process would know only its part
    triplets = spherical_cutoff_triplets(n)
    parts = round_robin_stick_partition(triplets, (n, n, n), n_shards)
    planes = even_plane_split(n, n_shards)
    mine = slice(pidx * shards_per_proc, (pidx + 1) * shards_per_proc)

    dist_plan = multihost.build_distributed_plan_multihost(
        sp.TransformType.C2C, n, n, n,
        local_triplets=parts[mine], local_planes=planes[mine])
    plan = sp.DistributedTransformPlan(dist_plan, precision="single")

    rng = np.random.default_rng(0)
    values = [
        (rng.uniform(-1, 1, len(p)) + 1j * rng.uniform(-1, 1, len(p)))
        .astype(np.complex64) for p in parts]
    out = plan.apply_pointwise(values, scaling=sp.Scaling.FULL)
    # Under multi-process, the result spans non-addressable devices;
    # each process may only read ITS devices' shards.
    err = 0.0
    for shard in out.addressable_shards:
        r = shard.index[0]
        r = r.start if isinstance(r, slice) else int(r)
        n_vals = dist_plan.shard_plans[r].num_values
        block = np.asarray(shard.data).reshape(-1, 2)[:n_vals]
        got = block[:, 0] + 1j * block[:, 1]
        if n_vals:
            err = max(err, float(np.abs(got - values[r]).max()))
    print(f"process {pidx}/{pcount}: {n_shards} shards, "
          f"round-trip max err over local shards = {err:.2e}")
    assert err < 1e-3
    print("OK")


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Spectral Poisson solver on the sparse frequency set.

Solves ∇²φ = -ρ on a periodic box the way plane-wave DFT codes do
(Hartree potential): forward-transform the density, scale each sparse
coefficient by 1/|G|² (the whole point of the sparse representation — the
multiplier is applied only to the stored coefficients, no dense cube
exists), and transform back.

Run: python examples/example_poisson.py
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))
import spfft_tpu as sp  # noqa: E402
from spfft_tpu.utils import as_complex_np  # noqa: E402
from spfft_tpu.utils.workloads import spherical_cutoff_triplets  # noqa: E402

n = 32
box = 2 * np.pi  # box length -> G vectors are integer frequencies
triplets = spherical_cutoff_triplets(n)  # centered indexing
plan = sp.make_local_plan(sp.TransformType.C2C, n, n, n, triplets,
                          precision="single")

# a density: two opposite Gaussian blobs (net neutral), dense on the grid
zz, yy, xx = np.meshgrid(*(np.linspace(0, box, n, endpoint=False),) * 3,
                         indexing="ij")
def blob(cx, cy, cz, sign):
    r2 = (xx - cx) ** 2 + (yy - cy) ** 2 + (zz - cz) ** 2
    return sign * np.exp(-r2 / 0.5)
rho = blob(2.0, 2.0, 2.0, +1.0) + blob(4.5, 4.5, 4.5, -1.0)
rho = rho.astype(np.complex64)

# forward: dense space field -> sparse coefficients (with 1/N scaling)
rho_g = as_complex_np(np.asarray(plan.forward(rho, sp.Scaling.FULL)))

# spectral solve: phi_G = rho_G / |G|^2, G=0 mode fixed to 0 (neutrality)
g2 = (triplets.astype(np.float64) ** 2).sum(axis=1)
phi_g = np.where(g2 > 0, rho_g / np.maximum(g2, 1), 0).astype(np.complex64)

# backward: sparse potential coefficients -> dense potential
phi = as_complex_np(np.asarray(plan.backward(phi_g)))

# residual check: -∇²φ computed spectrally must reproduce rho (within the
# cutoff sphere — the solver lives entirely in the sparse set)
lap_g = (-g2 * phi_g).astype(np.complex64)
lap = as_complex_np(np.asarray(plan.backward(lap_g.astype(np.complex64))))
rho_in_cutoff = as_complex_np(np.asarray(plan.backward(rho_g)))
err = np.abs(lap + rho_in_cutoff).max() / np.abs(rho_in_cutoff).max()
print(f"grid {n}^3, {len(triplets)} plane waves "
      f"({len(triplets) / n**3:.0%} of dense)")
print(f"max |∇²φ + ρ| / max|ρ| = {err:.2e}")
assert err < 1e-4
print("OK")

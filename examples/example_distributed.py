"""Distributed sparse transform over a device mesh: spherical-cutoff C2C on
8 shards (slab/pencil decomposition). Runs on any platform with >= 8 devices;
force a virtual CPU mesh with:

  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/example_distributed.py
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))
from spfft_tpu.utils.platform import force_virtual_cpu_devices  # noqa: E402

force_virtual_cpu_devices(8)

import spfft_tpu as sp  # noqa: E402
from spfft_tpu.utils.workloads import (even_plane_split,  # noqa: E402
                                       round_robin_stick_partition,
                                       spherical_cutoff_triplets)

n = 32
triplets = spherical_cutoff_triplets(n)
parts = round_robin_stick_partition(triplets, (n, n, n), 8)
planes = even_plane_split(n, 8)

plan = sp.make_distributed_plan(sp.TransformType.C2C, n, n, n, parts, planes,
                                mesh=sp.make_mesh(8), precision="single")
print(f"{plan.num_global_elements} sparse values over "
      f"{plan.mesh.devices.size} shards")

rng = np.random.default_rng(0)
values = [(rng.uniform(-1, 1, len(p)) + 1j * rng.uniform(-1, 1, len(p)))
          .astype(np.complex64) for p in parts]

space = plan.backward(values)                     # freq -> space, all-to-all inside
freq = plan.forward(space, sp.Scaling.FULL)       # space -> freq, scaled

round_trip = plan.unshard_values(freq)
err = max(np.abs(round_trip[r] - values[r]).max() for r in range(8))
print(f"round-trip max error: {err:.2e}")

#!/usr/bin/env python
"""SCF-style inner loop: apply a local potential in the space domain.

The workload SpFFT exists for (plane-wave DFT codes): each iteration takes
sparse frequency coefficients, transforms to real space, multiplies by a
potential field, and transforms back. Here the whole step is ONE fused
executable via ``apply_pointwise`` — the potential flows through ``fn_args``
as a traced argument, so updating it between iterations never recompiles.

Run: python examples/example_scf.py
"""

import os
import sys

import numpy as np
import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))
import spfft_tpu as sp  # noqa: E402
from spfft_tpu.utils.workloads import spherical_cutoff_triplets  # noqa: E402

n = 32
triplets = spherical_cutoff_triplets(n)
plan = sp.make_local_plan(sp.TransformType.C2C, n, n, n, triplets,
                          precision="single")

rng = np.random.default_rng(0)
coeffs = (rng.uniform(-1, 1, len(triplets))
          + 1j * rng.uniform(-1, 1, len(triplets))).astype(np.complex64)
coeffs = jnp.asarray(np.stack([coeffs.real, coeffs.imag], -1))


def apply_potential(space, potential):
    # space is (nz, ny, nx, 2) interleaved; the potential is real and
    # multiplies both components
    return space * potential[..., None]


potential = jnp.ones((n, n, n), jnp.float32)
for it in range(5):
    # one fused step: backward -> V*psi -> forward, scaled back to
    # coefficient convention
    coeffs = plan.apply_pointwise(coeffs, apply_potential, potential,
                                  scaling=sp.Scaling.FULL)
    # update the potential between steps (traced argument: no recompile)
    potential = potential * 0.99 + 0.01 * jnp.cos(
        jnp.linspace(0, np.pi, n))[None, None, :]
    norm = float(jnp.linalg.norm(coeffs))
    print(f"iter {it}: |coeffs| = {norm:.6f}, "
          f"compiled executables: {len(plan._pair_jits)}")

assert len(plan._pair_jits) == 1, "potential updates must not recompile"
print("OK")

/*
 * C example: dense 4x4x4 C2C round trip through the spfft_tpu C API.
 *
 * Role-equivalent of the reference C example (reference: examples/example.c
 * — grid + transform creation, backward, forward on a dense index set).
 * Build and run: `make example-c` at the repository root. The embedded
 * interpreter must be able to import spfft_tpu; pass the repository path
 * to spfft_tpu_init (here via the SPFFT_TPU_PACKAGE_PATH env var).
 */

#include <math.h>
#include <stdio.h>
#include <stdlib.h>

#include <spfft_tpu.h>

#define DIM 4
#define CHECK(expr)                                                        \
  do {                                                                     \
    int code_ = (expr);                                                    \
    if (code_ != SPFFT_TPU_SUCCESS) {                                      \
      fprintf(stderr, "%s -> %s\n", #expr, spfft_tpu_error_string(code_)); \
      return 1;                                                            \
    }                                                                      \
  } while (0)

int main(void) {
  const int n = DIM * DIM * DIM;
  int triplets[DIM * DIM * DIM * 3];
  float values[2 * DIM * DIM * DIM];
  float space[2 * DIM * DIM * DIM];
  float roundtrip[2 * DIM * DIM * DIM];
  float pair[2 * DIM * DIM * DIM];

  int i = 0;
  for (int x = 0; x < DIM; ++x) {
    for (int y = 0; y < DIM; ++y) {
      for (int z = 0; z < DIM; ++z) {
        triplets[3 * i] = x;
        triplets[3 * i + 1] = y;
        triplets[3 * i + 2] = z;
        values[2 * i] = (float)(i + 1);
        values[2 * i + 1] = (float)(-i);
        ++i;
      }
    }
  }

  CHECK(spfft_tpu_init(getenv("SPFFT_TPU_PACKAGE_PATH")));

  SpfftTpuPlan plan = NULL;
  CHECK(spfft_tpu_plan_create(&plan, SPFFT_TPU_TRANS_C2C, DIM, DIM, DIM, n,
                              triplets, SPFFT_TPU_PREC_SINGLE,
                              SPFFT_TPU_PALLAS_AUTO));

  long long num_values = 0;
  CHECK(spfft_tpu_plan_num_values(plan, &num_values));
  printf("plan: %lld frequency values on a %dx%dx%d grid\n", num_values, DIM,
         DIM, DIM);

  CHECK(spfft_tpu_backward(plan, values, space));
  /* forward with 1/N scaling must reproduce the input values */
  CHECK(spfft_tpu_forward(plan, space, SPFFT_TPU_FULL_SCALING, roundtrip));

  double max_err = 0.0;
  for (i = 0; i < 2 * n; ++i) {
    double err = fabs((double)roundtrip[i] - (double)values[i]);
    if (err > max_err) max_err = err;
  }
  printf("round-trip max abs error: %.3e\n", max_err);

  /* the fused pair (ONE device program) must agree with the separate
   * backward+forward round trip */
  CHECK(spfft_tpu_execute_pair(plan, values, SPFFT_TPU_FULL_SCALING, pair));
  double pair_err = 0.0;
  for (i = 0; i < 2 * n; ++i) {
    double err = fabs((double)pair[i] - (double)roundtrip[i]);
    if (err > pair_err) pair_err = err;
  }
  printf("fused pair vs backward+forward max abs error: %.3e\n", pair_err);

  CHECK(spfft_tpu_plan_destroy(plan));
  if (max_err > 1e-3 || pair_err > 1e-3) {
    fprintf(stderr, "FAIL: round-trip error too large\n");
    return 1;
  }
  printf("OK\n");
  return 0;
}

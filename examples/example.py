"""Dense 2x2x2 C2C round-trip through the Grid/Transform API — the
reference's example program (reference: examples/example.cpp, also embedded
in README.md:73-159), in Python."""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))
import spfft_tpu as sp  # noqa: E402

dim_x = dim_y = dim_z = 2
print(f"Dimensions: x = {dim_x}, y = {dim_y}, z = {dim_z}\n")

# use all frequency elements, like the reference example
indices = np.array([(x, y, z)
                    for x in range(dim_x)
                    for y in range(dim_y)
                    for z in range(dim_z)], np.int32)
num_elements = len(indices)
values = np.arange(num_elements) * (1.0 - 1.0j)

print("Input:")
for v in values:
    print(f"{v.real}, {v.imag}")

grid = sp.Grid(dim_x, dim_y, dim_z, dim_x * dim_y, sp.ProcessingUnit.DEVICE)
transform = grid.create_transform(
    sp.ProcessingUnit.DEVICE, sp.TransformType.C2C, dim_x, dim_y, dim_z,
    local_z_length=dim_z, num_local_elements=num_elements,
    index_format=sp.IndexFormat.TRIPLETS, indices=indices)

space = transform.backward(values)
print("\nAfter backward transform:")
for v in np.asarray(space).reshape(-1, 2):
    print(f"{v[0]}, {v[1]}")

freq = transform.forward(scaling=sp.Scaling.NONE)
print("\nAfter forward transform (without scaling):")
for v in np.asarray(freq):
    print(f"{v[0]}, {v[1]}")

#!/usr/bin/env python
"""Round-5 probe: what would PERFECT compression fusion buy?

Times pair variants with stages replaced by shape-correct no-ops
(results are wrong; traffic is the point):

  A. real pair                      (reference point)
  B. pair, decompress -> broadcast  (values ignored; sticks faked from
                                     a cheap slice-free reshape)
  C. pair, compress -> slice        (values faked by slicing sticks)
  D. both replaced                  (the DFT+transpose core alone)

A-D bounds the total compression cost including boundaries; comparing
with the standalone stage numbers separates scheduling overlap from
real stage time. Decides whether a merged gather+DFT kernel is worth
building.

Usage: DIM=256 python scripts/probe_r5_ceiling.py
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

from spfft_tpu import TransformType, make_local_plan
from spfft_tpu.utils.benchtime import diff_estimate_seconds
from spfft_tpu.utils.workloads import spherical_cutoff_triplets

DIM = int(os.environ.get("DIM", 256))


def sync(out):
    leaf = jax.tree_util.tree_leaves(out)[0]
    return float(np.asarray(jnp.real(leaf).ravel()[0]))


def measure(f, *args, reps=16):
    g = jax.jit(f)
    sync(g(*args))

    def grp(k):
        t0 = time.perf_counter()
        o = None
        for _ in range(k):
            o = g(*args)
        sync(o)
        return time.perf_counter() - t0
    return diff_estimate_seconds(grp, reps=reps).seconds


def main():
    tri = spherical_cutoff_triplets(DIM)
    plan = make_local_plan(TransformType.C2C, DIM, DIM, DIM, tri)
    p = plan.index_plan
    tabs = plan._tables_hot
    n = p.num_values
    rng = np.random.default_rng(7)
    vals = (rng.uniform(-1, 1, n) + 1j * rng.uniform(-1, 1, n)).astype(
        np.complex64)
    vil = jax.device_put(plan._coerce_values(vals))
    s_pad, Z = plan._s_pad, p.dim_z
    nslots = s_pad * Z

    def fake_dec(v):
        # values (n, 2) -> (s_pad, Z) x2 without a gather: tile the
        # first rows cyclically via cheap reshape of a padded slice
        flat = v.reshape(-1)
        rep = nslots * 2 // flat.size + 1
        big = jnp.concatenate([flat] * rep)[:nslots * 2].reshape(-1, 2)
        return big[:, 0].reshape(s_pad, Z), big[:, 1].reshape(s_pad, Z)

    def fake_cmp(sr, si):
        flat = jnp.stack([sr.reshape(-1), si.reshape(-1)], axis=-1)
        return flat[:n]

    def pair_real(v):
        return plan._forward_impl(plan._backward_impl(v, tabs), tabs,
                                  scaled=False)

    def bw_core(sr, si):
        out = plan._backward_rest_tp(sr, si, tabs)
        return jnp.stack([out[0], out[1]], axis=-1)

    def pair_nodec(v):
        sr, si = fake_dec(v)
        space = bw_core(sr, si)
        return plan._forward_impl(space, tabs, scaled=False)

    def pair_nocmp(v):
        space = bw_core(*plan._decompress_planar(v, tabs))
        sp = (space[..., 0], space[..., 1])
        sr, si = plan._forward_head_tp(sp, tabs, None)
        return fake_cmp(sr, si)

    def pair_neither(v):
        sr, si = fake_dec(v)
        space = bw_core(sr, si)
        sp = (space[..., 0], space[..., 1])
        sr2, si2 = plan._forward_head_tp(sp, tabs, None)
        return fake_cmp(sr2, si2)

    for name, f in [("A real pair     ", pair_real),
                    ("B no decompress ", pair_nodec),
                    ("C no compress   ", pair_nocmp),
                    ("D neither       ", pair_neither)]:
        t = measure(f, vil)
        print(f"{name}: {t*1e3:7.3f} ms", flush=True)


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Round-5 probe: fused 2D (y-DFT + transpose + x-DFT) Pallas kernel.

The pipeline's xy tail is pdft_last(y) -> swapaxes (a materialized
grid-sized transpose pass) -> pdft_last(x). A per-plane-batch kernel
does dot / in-VMEM transpose / dot with one HBM read and one write.
A/B against the XLA three-pass form with the shared estimator.

Usage: python scripts/probe_r5_fused2d.py
"""
import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from spfft_tpu.ops import dft
from spfft_tpu.utils.benchtime import diff_estimate_seconds

_HI = jax.lax.Precision.HIGHEST
_DN = (((1,), (0,)), ((), ()))


def _dotk(a, c):
    return jax.lax.dot_general(a, c, _DN, precision=_HI,
                               preferred_element_type=jnp.float32)


def _karatsuba(ar, ai, cr, ci, cs):
    p1 = _dotk(ar, cr)
    p2 = _dotk(ai, ci)
    p3 = _dotk(ar + ai, cs)
    return p1 - p2, p3 - p1 - p2


def make_fused2d(ny_mats, nx_mats, tp=4):
    ycr, yci, ycs = (np.asarray(m) for m in ny_mats)
    xcr, xci, xcs = (np.asarray(m) for m in nx_mats)
    ny, nyo = ycr.shape
    nx, nxo = xcr.shape

    def kernel(xr_ref, xi_ref, ycr_ref, yci_ref, ycs_ref,
               xcr_ref, xci_ref, xcs_ref, or_ref, oi_ref):
        tp_, nx_, ny_ = xr_ref.shape
        a = xr_ref[...].reshape(tp_ * nx_, ny_)
        b = xi_ref[...].reshape(tp_ * nx_, ny_)
        gr, gi = _karatsuba(a, b, ycr_ref[...], yci_ref[...],
                            ycs_ref[...])                 # (tp*nx, nyo)
        gr = gr.reshape(tp_, nx_, nyo)
        gi = gi.reshape(tp_, nx_, nyo)
        gr = jnp.swapaxes(gr, -1, -2).reshape(tp_ * nyo, nx_)
        gi = jnp.swapaxes(gi, -1, -2).reshape(tp_ * nyo, nx_)
        hr, hi = _karatsuba(gr, gi, xcr_ref[...], xci_ref[...],
                            xcs_ref[...])                 # (tp*nyo, nxo)
        or_ref[...] = hr.reshape(tp_, nyo, nxo)
        oi_ref[...] = hi.reshape(tp_, nyo, nxo)

    mats = tuple(jnp.asarray(m) for m in (ycr, yci, ycs, xcr, xci, xcs))

    def apply(xr, xi):
        p = xr.shape[0]
        grid = (pl.cdiv(p, tp),)
        mspecs = [pl.BlockSpec((ycr.shape[0], nyo), lambda i: (0, 0))] * 3 \
            + [pl.BlockSpec((nx, nxo), lambda i: (0, 0))] * 3
        return pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[pl.BlockSpec((tp, nx, ny), lambda i: (i, 0, 0))] * 2
            + mspecs,
            out_specs=[pl.BlockSpec((tp, nyo, nxo), lambda i: (i, 0, 0))] * 2,
            out_shape=[jax.ShapeDtypeStruct((p, nyo, nxo), jnp.float32)] * 2,
        )(xr, xi, *mats)
    return apply


def xla_ref(xr, xi, ny_mats, nx_mats):
    gr, gi = dft.pdft_last(xr, xi, ny_mats)
    gr = jnp.swapaxes(gr, -1, -2)
    gi = jnp.swapaxes(gi, -1, -2)
    return dft.pdft_last(gr, gi, nx_mats)


def sync(pair):
    return float(np.asarray(jnp.real(pair[0]).ravel()[0]))


def bench(g, xr, xi, chain=3, reps=16):
    def body(a, b):
        o = g(a, b)
        for _ in range(chain - 1):
            o = g(o[0], o[1])
        return o
    f = jax.jit(body)
    sync(f(xr, xi))

    def grp(k):
        t0 = time.perf_counter()
        o = (xr, xi)
        for _ in range(k):
            o = f(xr, xi)
        sync(o)
        return time.perf_counter() - t0
    return diff_estimate_seconds(grp, reps=reps).seconds / chain


def main():
    n = int(os.environ.get("N", 256))
    p = int(os.environ.get("P", 256))
    rng = np.random.default_rng(5)
    xr64 = rng.standard_normal((p, n, n))
    xi64 = rng.standard_normal((p, n, n))
    ny_mats = dft.c2c_mats(n, dft.BACKWARD)
    nx_mats = dft.c2c_mats(n, dft.BACKWARD)
    xr = jnp.asarray(xr64, jnp.float32)
    xi = jnp.asarray(xi64, jnp.float32)

    ref = np.asarray(
        jax.jit(lambda a, b: xla_ref(a, b, ny_mats, nx_mats))(xr, xi)[0],
        np.float64)

    for tp in (2, 4, 8):
        fused = make_fused2d(ny_mats, nx_mats, tp=tp)
        try:
            got = np.asarray(jax.jit(fused)(xr, xi)[0], np.float64)
            err = np.linalg.norm(got - ref) / np.linalg.norm(ref)
            t = bench(fused, xr, xi)
            gb = (4 * p * n * n * 4) / 1e9
            print(f"fused2d tp={tp}: {t*1e3:7.3f} ms  vs-xla rel {err:.3e}  "
                  f"eff {(gb/t):6.1f} GB/s", flush=True)
        except Exception as e:  # noqa: BLE001
            print(f"fused2d tp={tp} FAILED: {type(e).__name__}: "
                  f"{str(e).splitlines()[0][:140]}", flush=True)

    t = bench(lambda a, b: xla_ref(a, b, ny_mats, nx_mats), xr, xi)
    print(f"xla 3-pass    : {t*1e3:7.3f} ms", flush=True)


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Two-process `jax.distributed` smoke of the multihost plan build — the
real-wire analogue of the stub-world tests in tests/test_multihost.py (the
reference's equivalent is running its MPI tests under real ranks,
reference: tests/run_mpi_tests.cpp:14-20).

Parent mode (no args): spawns two worker processes on a localhost
coordinator and reports their combined verdict. Worker mode
(``--worker <pid>``): initialises the process group, builds the
distributed plan collectively (fingerprint allgather cross-check), runs
one backward+forward on this process's mesh slice, and prints
``worker <pid>: ok``.

Usage:  python scripts/multihost_smoke.py
Exit 0 = both workers completed the collective plan build and a transform.
Any failure prints the worker logs (this is a smoke harness, not a test —
the container may not support multi-process XLA groups; ROADMAP.md records
the observed result).
"""
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

PORT = int(os.environ.get("SPFFT_SMOKE_PORT", "12355"))
NPROC = 2


def worker(pid: int) -> None:
    # Each worker must be CPU-intent BEFORE jax loads a backend; the
    # spawned interpreter inherits env from the parent below.
    from spfft_tpu.utils.platform import force_virtual_cpu_devices
    force_virtual_cpu_devices(1)

    import numpy as np
    import jax
    from spfft_tpu import (Scaling, TransformType, initialize_multihost,
                           make_mesh)
    from spfft_tpu.parallel.dist import DistributedTransformPlan
    from spfft_tpu.parallel.multihost import build_distributed_plan_multihost
    from spfft_tpu.utils.workloads import (even_plane_split,
                                           round_robin_stick_partition,
                                           spherical_cutoff_triplets)

    initialize_multihost(coordinator_address=f"127.0.0.1:{PORT}",
                         num_processes=NPROC, process_id=pid)
    assert jax.process_count() == NPROC, jax.process_count()
    n_dev = len(jax.devices())
    print(f"worker {pid}: process group up, {n_dev} global devices",
          flush=True)

    n = 8
    triplets = spherical_cutoff_triplets(n)
    parts = round_robin_stick_partition(triplets, (n, n, n), n_dev)
    planes = even_plane_split(n, n_dev)
    # Collective build: each process contributes ITS shards only (one
    # device per process here); the builder allgathers the stick lists and
    # validates the blake2b fingerprint across processes (the reference's
    # plan-time Allreduce mismatch check, grid_internal.cpp:148-167).
    local = slice(pid, pid + 1)
    dist = build_distributed_plan_multihost(
        TransformType.C2C, n, n, n, parts[local], planes[local])
    plan = DistributedTransformPlan(dist, mesh=make_mesh(n_dev),
                                    precision="single")
    rng = np.random.default_rng(0)
    values = [(rng.uniform(-1, 1, len(p))
               + 1j * rng.uniform(-1, 1, len(p))).astype(np.complex64)
              for p in parts]
    out = plan.forward(plan.backward(values), Scaling.FULL)
    out.block_until_ready()
    print(f"worker {pid}: ok", flush=True)


def main() -> int:
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               PALLAS_AXON_POOL_IPS="",
               XLA_FLAGS="--xla_force_host_platform_device_count=1")
    procs = []
    for pid in range(NPROC):
        procs.append(subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--worker",
             str(pid)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True))
    deadline = time.time() + 300
    outs = [None] * NPROC
    for i, p in enumerate(procs):
        try:
            outs[i], _ = p.communicate(timeout=max(1, deadline - time.time()))
        except subprocess.TimeoutExpired:
            p.kill()
            outs[i], _ = p.communicate()
            outs[i] += "\n<timed out>"
    ok = all(p.returncode == 0 and f"worker {i}: ok" in (outs[i] or "")
             for i, p in enumerate(procs))
    for i, o in enumerate(outs):
        print(f"--- worker {i} (rc={procs[i].returncode}) ---")
        print(o)
    print("MULTIHOST SMOKE:", "OK" if ok else "FAILED")
    return 0 if ok else 1


if __name__ == "__main__":
    if len(sys.argv) > 2 and sys.argv[1] == "--worker":
        worker(int(sys.argv[2]))
    else:
        sys.exit(main())

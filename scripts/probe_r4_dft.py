#!/usr/bin/env python
"""Round-4 probe: matmul-DFT stages vs XLA's conv-FFT in the 256^3 pair.

XLA:TPU lowers jnp.fft to DFT convolutions at operand_precision=highest
plus layout-change copies for the non-minor axis. This probe swaps every
FFT stage for an explicit dot_general against DFT-matrix constants:
  - y axis contracted in place ('ky,zyx->zkx') — no transposes,
  - x axis as '...x,xk->...k',
  - z axis on sticks as 'sz,zk->sk',
at both HIGHEST (f32) and HIGH (bf16_3x) precision, 4-mult complex vs
3-mult Karatsuba. Accuracy via the FULL-scaled identity round trip
(out == in for an exact pipeline). Timing via bench.py's difference
estimator on real apply-style dispatches.

Usage: DIM=256 python scripts/probe_r4_dft.py
"""
import os
import sys
import time
import functools

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

from spfft_tpu import TransformType, make_local_plan
from spfft_tpu.utils.benchtime import diff_estimate_seconds
from spfft_tpu.utils.workloads import spherical_cutoff_triplets

P_HI = jax.lax.Precision.HIGHEST
P_H3 = jax.lax.Precision.HIGH


def dftmat(n, sign, scale=1.0):
    k = np.arange(n)
    return (np.exp(sign * 2j * np.pi * np.outer(k, k) / n) * scale)


def cmats(n, sign, scale=1.0):
    m = dftmat(n, sign, scale)
    return (np.ascontiguousarray(m.real.astype(np.float32)),
            np.ascontiguousarray(m.imag.astype(np.float32)))


def cmul_mm(xr, xi, cr, ci, contract, prec, karatsuba=False):
    """Complex matmul via real dot_generals; ``contract`` is a function
    (a, b) -> dot_general(a, b) for the wanted axis structure."""
    if karatsuba:
        p1 = contract(xr, cr, prec)
        p2 = contract(xi, ci, prec)
        p3 = contract(xr + xi, cr + ci, prec)
        return p1 - p2, p3 - p1 - p2
    return (contract(xr, cr, prec) - contract(xi, ci, prec),
            contract(xr, ci, prec) + contract(xi, cr, prec))


def c_last(x, mats, prec, karatsuba):
    """DFT along the last axis: '...x,xk->...k'."""
    cr, ci = mats
    f = lambda a, c, p: jax.lax.dot_general(
        a, c, (((a.ndim - 1,), (0,)), ((), ())), precision=p)
    yr, yi = cmul_mm(jnp.real(x), jnp.imag(x), jnp.asarray(cr),
                     jnp.asarray(ci), f, prec, karatsuba)
    return yr + 1j * yi


def c_mid(x, mats, prec, karatsuba):
    """DFT along axis -2 of (z, y, x): 'ky,zyx->zkx' — x stays minor."""
    cr, ci = mats

    def f(a, c, p):
        # dot_general: lhs c (k, y), rhs a (z, y, x); contract y
        out = jax.lax.dot_general(c, a, (((1,), (1,)), ((), ())),
                                  precision=p)  # (k, z, x)
        return jnp.moveaxis(out, 0, 1)  # (z, k, x)

    yr, yi = cmul_mm(jnp.real(x), jnp.imag(x), jnp.asarray(cr),
                     jnp.asarray(ci), f, prec, karatsuba)
    return yr + 1j * yi


def main(n: int):
    triplets = spherical_cutoff_triplets(n)
    plan = make_local_plan(TransformType.C2C, n, n, n, triplets,
                           precision="single")
    p = plan.index_plan
    N = p.num_values
    tables = plan._tables
    from spfft_tpu.ops import stages
    print(f"== dim={n} values={N} ==", flush=True)

    rng = np.random.default_rng(0)
    values = (rng.uniform(-1, 1, N)
              + 1j * rng.uniform(-1, 1, N)).astype(np.complex64)
    values_il = jax.device_put(plan._coerce_values(values))

    def sync(arr):
        return float(np.asarray(arr.ravel()[0]))

    def make_pair(prec, karatsuba, scaled):
        # backward: ifft_z * Z ; ifft2 * (y x)  [scale folded into mats]
        mz_b = cmats(n, +1, 1.0)     # ifft*Z = conj-DFT (no 1/Z)
        my_b = cmats(n, +1, 1.0)
        mx_b = cmats(n, +1, 1.0)
        s = 1.0 / (n ** 3) if scaled else 1.0
        mz_f = cmats(n, -1, s)       # fold FULL scaling into the z-DFT
        my_f = cmats(n, -1, 1.0)
        mx_f = cmats(n, -1, 1.0)

        def pair(v):
            sticks = plan._decompress(v, tables)
            sticks = c_last(sticks, mz_b, prec, karatsuba)
            grid = stages.sticks_to_grid(sticks, tables["col_inv"],
                                         p.dim_y, p.dim_x_freq)
            grid = c_mid(grid, my_b, prec, karatsuba)
            grid = c_last(grid, mx_b, prec, karatsuba)
            # forward
            grid = c_last(grid, mx_f, prec, karatsuba)
            grid = c_mid(grid, my_f, prec, karatsuba)
            sticks = stages.grid_to_sticks(grid, tables["scatter_cols"])
            sticks = c_last(sticks, mz_f, prec, karatsuba)
            return plan._compress(sticks, tables, None)
        return jax.jit(pair)

    def timed_ms(fn, arg):
        def grp(g):
            t0 = time.perf_counter()
            o = None
            for _ in range(g):
                o = fn(arg)
            sync(o)
            return time.perf_counter() - t0
        est = diff_estimate_seconds(grp, reps=20)
        return est.seconds * 1e3

    # reference: current pair
    cur = jax.jit(functools.partial(plan._pair_impl, scaled=False, fn=None))
    o = cur(values_il, plan._tables); sync(o)
    print(f"current pair (XLA fft):      {timed_ms(lambda v: cur(v, plan._tables), values_il):8.3f} ms", flush=True)

    for prec, pname in [(P_HI, "HIGHEST"), (P_H3, "HIGH")]:
        for kara in (False, True):
            f = make_pair(prec, kara, scaled=False)
            o = f(values_il); sync(o)
            t = timed_ms(f, values_il)
            # accuracy: scaled pair should reproduce the input
            fa = make_pair(prec, kara, scaled=True)
            out = np.asarray(fa(values_il))
            got = out[..., 0] + 1j * out[..., 1]
            rel = np.linalg.norm(got - values) / np.linalg.norm(values)
            print(f"matmul-DFT {pname:7s} kara={int(kara)}: {t:8.3f} ms   "
                  f"roundtrip rel err {rel:.2e}", flush=True)


if __name__ == "__main__":
    print(f"devices: {jax.devices()}", flush=True)
    main(int(os.environ.get("DIM", "256")))

#!/usr/bin/env python
"""Round-5 single-chip envelope: the fused identity pair at each grid
size, measured with the sync-robust median estimator (the ≥320³ rows of
the round-4 table used probe-amortised timing — VERDICT r4 weak #5).

Usage: DIMS="320 384 512 768" python scripts/envelope_r05.py
Large grids build multi-minute plans; each dim runs in-process
sequentially with progress marks so a stall is attributable.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax

from spfft_tpu import TransformType, make_local_plan
from spfft_tpu.utils.benchtime import diff_estimate_seconds
from spfft_tpu.utils.workloads import spherical_cutoff_triplets


def sync_one(out):
    first = out[(0,) * (out.ndim - 1)][:1]
    return float(np.asarray(first).ravel()[0])


def main():
    dims = [int(d) for d in os.environ.get("DIMS", "320 384 512").split()]
    reps = int(os.environ.get("REPS", "12"))
    print(f"devices: {jax.devices()}", flush=True)
    for n in dims:
        t0 = time.perf_counter()
        triplets = spherical_cutoff_triplets(n)
        rng = np.random.default_rng(42)
        values = (rng.uniform(-1, 1, len(triplets))
                  + 1j * rng.uniform(-1, 1, len(triplets))
                  ).astype(np.complex64)
        print(f"[{n}] triplets {len(triplets)} "
              f"({time.perf_counter()-t0:.0f}s)", flush=True)
        t0 = time.perf_counter()
        try:
            plan = make_local_plan(TransformType.C2C, n, n, n, triplets,
                                   precision="single")
            vil = jax.device_put(plan._coerce_values(values))
            out = plan.apply_pointwise(vil)
            sync_one(out)
        except Exception as exc:
            print(f"[{n}] FAILED: {type(exc).__name__}: "
                  f"{str(exc)[:300]}", flush=True)
            continue
        print(f"[{n}] plan+compile {time.perf_counter()-t0:.0f}s "
              f"(pallas={plan._pallas_active} pair_io={plan.pair_values_io}"
              f" mdft={plan._use_mdft})", flush=True)

        def grp(g):
            t0 = time.perf_counter()
            o = None
            for _ in range(g):
                o = plan.apply_pointwise(vil)
            sync_one(o)
            return time.perf_counter() - t0

        est = diff_estimate_seconds(grp, reps=reps)
        gbs = ((2 * plan.index_plan.num_values
                + 8 * plan.index_plan.num_sticks * n + 6 * n ** 3) * 8
               / est.seconds / 1e9)
        print(f"[{n}] pair {est.seconds*1e3:.2f} ms  ({est.label})  "
              f"effective {gbs:.0f} GB/s", flush=True)


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Round-5 probe: time structure of the device's fast/slow modes.

Round-4 treated the ~1.3x bimodality as fixed per process; round-5
trials saw 8.6-9.5 ms mins INSIDE otherwise-12.5 ms sessions. This
prints every group's per-pair time over a long run to show dwell times
and transition structure, deciding how bench.py should catch the fast
mode (VERDICT r4 task 6).

Usage: DIM=256 GROUPS=40 python scripts/probe_r5_mode.py
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax

from spfft_tpu import TransformType, make_local_plan
from spfft_tpu.utils.workloads import spherical_cutoff_triplets


def sync(a):
    return float(np.asarray(jax.numpy.real(a).ravel()[0]))


def main():
    n = int(os.environ.get("DIM", "256"))
    groups = int(os.environ.get("GROUPS", "40"))
    g = int(os.environ.get("G", "10"))
    print(f"devices: {jax.devices()}", flush=True)
    triplets = spherical_cutoff_triplets(n)
    rng = np.random.default_rng(42)
    N = len(triplets)
    values = (rng.uniform(-1, 1, N)
              + 1j * rng.uniform(-1, 1, N)).astype(np.complex64)
    plan = make_local_plan(TransformType.C2C, n, n, n, triplets,
                           precision="single")
    vil = jax.device_put(plan._coerce_values(values))
    sync(plan.apply_pointwise(vil))

    # Per-group pipelined time, g pairs + 1 sync each. The sync constant
    # (~80-120 ms tunnel readback) inflates all groups equally, so MODE
    # CONTRAST survives even though absolute values are biased by
    # sync/g. Also prints the rolling diff-pair estimate (g2-g1 pairs
    # of adjacent groups are the same, so adjacent-group differences
    # don't apply; use contrast only).
    ts = []
    for i in range(groups):
        t0 = time.perf_counter()
        o = None
        for _ in range(g):
            o = plan.apply_pointwise(vil)
        sync(o)
        dt = (time.perf_counter() - t0) / g
        ts.append(dt)
        print(f"group {i:3d}: {dt*1e3:7.3f} ms/pair (incl sync/g)",
              flush=True)
    arr = np.asarray(ts) * 1e3
    print(f"min {arr.min():.3f} med {np.median(arr):.3f} "
          f"max {arr.max():.3f}", flush=True)


if __name__ == "__main__":
    main()

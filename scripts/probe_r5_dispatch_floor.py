#!/usr/bin/env python
"""Round-5 probe: per-dispatch floor vs compute scaling.

probe_r5_fused_stage measured ONE 256-point stage at ~11 ms — the same
wall-clock as the whole 6-stage fused pair. If a fixed per-dispatch cost
(axon tunnel round trip) dominates, chaining k stages inside one jit
should stay nearly flat in k; if compute dominates, it should scale
linearly. Also times a trivial add dispatch as the floor reference.

Usage: python scripts/probe_r5_dispatch_floor.py
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

from spfft_tpu.ops import dft

N = 256
M = 256 * 256


def bench(g, args, inner=5, reps=12):
    out = g(*args)
    jax.block_until_ready(out)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(inner):
            out = g(*args)
        jax.block_until_ready(out)
        best = min(best, (time.perf_counter() - t0) / inner)
    return best


def main():
    rng = np.random.default_rng(5)
    xr = jnp.asarray(rng.standard_normal((M, N)), jnp.float32)
    xi = jnp.asarray(rng.standard_normal((M, N)), jnp.float32)
    mats = dft.c2c_mats(N, dft.BACKWARD)

    t = bench(jax.jit(lambda a, b: (a + 1.0, b)), (xr, xi))
    print(f"trivial add dispatch : {t*1e3:7.3f} ms", flush=True)

    for k in (1, 2, 4, 8):
        def chain(a, b, k=k):
            for _ in range(k):
                a, b = dft.pdft_last(a, b, mats)
            return a, b
        t = bench(jax.jit(chain), (xr, xi))
        print(f"{k} chained stages    : {t*1e3:7.3f} ms "
              f"({t*1e3/k:6.3f} ms/stage)", flush=True)


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Recorded 8/16/32-shard scaling projection (VERDICT r3 item 4).

For S in {8, 16, 32} x {uniform, stick-skew, plane-skew} x {padded
(BUFFERED), COMPACT_BUFFERED, UNBUFFERED}: build the REAL distributed
plan on an S-device virtual CPU mesh, read aggregate + busiest-link wire
bytes from the plan's HLO-verified model, and CROSS-CHECK them against
the byte counts of the collectives in the actually-lowered SPMD module
(the same extraction tests/test_compact_exchange.py pins at S=4).

Time model (parameters printed with the output; all knobs adjustable):
  T_pair(S) = pair_1chip / S            (per-shard FFT+gather work)
            + 2 * busiest_link_bytes / BW_ICI   (two exchanges per pair)
            + n_ops * T_LAUNCH                  (collective launches)

Usage:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=32 \
      python scripts/scaling_model.py [--dim 128] [--pair-ms 10.2] \
      [--bw-gbps 100] [--out FILE.json]
"""
import argparse
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# The script only LOWERS plans (never executes); force the real ragged
# collective off-TPU so the compact mechanism's launch structure is the
# one a TPU pod would run (XLA:CPU can lower it, just not execute it).
os.environ.setdefault("SPFFT_TPU_FORCE_RAGGED_OP", "1")

import numpy as np


def scenarios(S):
    """stick weights, plane weights per scenario."""
    ramp = list(range(1, S + 1))
    return {
        "uniform": ([1] * S, [1] * S),
        "stick_skew": (ramp, [1] * S),          # stick ownership ramps 1..S
        "plane_skew": ([1] * S, ramp),          # slab heights ramp 1..S
    }


from spfft_tpu.utils.hlo_inspect import hlo_wire_bytes as _shared


def hlo_wire_bytes(txt, S):
    total, sent, recv = _shared(txt, S)
    import numpy as _np
    return total, int(_np.maximum(sent, recv).max())


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dim", type=int, default=128)
    ap.add_argument("--pair-ms", type=float, default=12.4,
                    help="measured single-chip 256^3 pair (BENCH_r05, "
                         "sync-robust estimator)")
    ap.add_argument("--bw-gbps", type=float, default=100.0,
                    help="assumed per-link ICI bandwidth (v5e-class)")
    ap.add_argument("--launch-us", type=float, default=2.0,
                    help="assumed per-collective launch cost")
    ap.add_argument("--shards", type=int, nargs="+", default=[8, 16, 32])
    ap.add_argument("--hlo-check", type=int, nargs="+",
                    default=[8, 16, 32],
                    help="shard counts whose plans are also LOWERED and "
                         "cross-checked against the HLO byte counts")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    from spfft_tpu import ExchangeType, TransformType
    from spfft_tpu.parallel import make_distributed_plan, make_mesh
    from spfft_tpu.utils.platform import force_virtual_cpu_devices
    from spfft_tpu.utils.workloads import spherical_cutoff_triplets
    import jax

    force_virtual_cpu_devices(max(args.shards))
    n = args.dim
    triplets = spherical_cutoff_triplets(n)
    rows = []
    mechs = [("padded", ExchangeType.BUFFERED),
             ("compact", ExchangeType.COMPACT_BUFFERED),
             ("unbuffered", ExchangeType.UNBUFFERED)]
    for S in args.shards:
        for scen, (sw, pw) in scenarios(S).items():
            # weighted stick split + weighted plane split
            sticks = {}
            for t in triplets:
                sticks.setdefault((t[0], t[1]), []).append(t)
            keys = sorted(sticks)
            cum = np.cumsum(sw, dtype=np.float64)
            bound = cum / cum[-1] * len(keys)
            parts = [[] for _ in range(S)]
            r = 0
            for i, k in enumerate(keys):
                while i >= bound[r] and r < S - 1:
                    r += 1
                parts[r].extend(sticks[k])
            parts = [np.asarray(p, np.int64).reshape(-1, 3) if p
                     else np.empty((0, 3), np.int64) for p in parts]
            cump = np.cumsum(pw, dtype=np.float64)
            edges = np.round(cump / cump[-1] * n).astype(int)
            planes = np.diff(np.concatenate([[0], edges])).tolist()
            for mname, mech in mechs:
                plan = make_distributed_plan(
                    TransformType.C2C, n, n, n, parts, planes,
                    mesh=make_mesh(S), precision="single", exchange=mech)
                total = plan.exchange_wire_bytes()
                link = plan.exchange_busiest_link_bytes()
                hlo_note = ""
                if S in args.hlo_check:
                    vals = plan.shard_values(
                        [np.zeros(len(p), np.complex64) for p in parts])
                    txt = plan._backward_jit.lower(
                        vals, *plan._device_tables).as_text()
                    if mname == "compact":
                        # ragged wire traffic is data-dependent (not in
                        # static HLO shapes): verify the LAUNCH structure
                        # in the lowering and the byte model against an
                        # independent exact-Alltoallv recompute
                        n_ragged = len(re.findall(r"ragged_all_to_all",
                                                  txt))
                        assert n_ragged == 1, (scen, S, n_ragged)
                        assert "all_gather" not in txt
                        assert "stablehlo.all_to_all" not in txt
                        dpp = plan.dist_plan
                        nss = [sp.num_sticks for sp in dpp.shard_plans]
                        npp = list(dpp.num_planes)
                        exact = sum(nss[j] * npp[d] * 8
                                    for j in range(S) for d in range(S)
                                    if j != d)
                        assert exact == total, (scen, exact, total)
                        hlo_note = "hlo-verified(1-collective)"
                    else:
                        h_total, h_link = hlo_wire_bytes(txt, S)
                        assert h_total == total, (scen, mname, h_total,
                                                  total)
                        assert h_link == link, (scen, mname, h_link, link)
                        hlo_note = "hlo-verified"
                # compact = the one-collective ragged exchange since r5
                n_ops = 1 if mname == "compact" \
                    else (S - 1 if mname == "unbuffered" else 1)
                t_model = (args.pair_ms * 1e-3 * (n / 256) ** 0 / S
                           + 2 * link / (args.bw_gbps * 1e9)
                           + 2 * n_ops * args.launch_us * 1e-6)
                rows.append({
                    "shards": S, "scenario": scen, "mechanism": mname,
                    "wire_total_mb": round(total / 1e6, 3),
                    "busiest_link_mb": round(link / 1e6, 3),
                    "n_collectives": int(n_ops),
                    "t_model_ms": round(t_model * 1e3, 3),
                    "hlo": hlo_note,
                })
                print(f"S={S:2d} {scen:11s} {mname:10s} "
                      f"total {total / 1e6:9.3f} MB  link "
                      f"{link / 1e6:8.3f} MB  ops {n_ops:3d}  "
                      f"t_model {t_model * 1e3:7.3f} ms  {hlo_note}",
                      flush=True)
    if args.out:
        with open(args.out, "w") as f:
            json.dump({"parameters": vars(args), "rows": rows}, f,
                      indent=2)
        print(f"wrote {args.out}")


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Round-5 probe: on-device high-accuracy DFT attempts (verdict item 5).

Two candidate schemes for >f32 accuracy on a chip with no f64:

A. Compensated double-single (the verdict's sketch): values and
   matrices split hi+lo f32, y = xh@Ch + (xh@Cl + xl@Ch), dots guarded
   by optimization_barrier. PREDICTION: the correction removes INPUT
   quantization but each f32 dot still rounds its accumulator at
   ~eps_f32, so the error should stay ~1e-7 — measured here to close
   the item with evidence either way.

B. Ozaki-style exact-sliced dot: operands sliced into beta-bit limbs
   with beta chosen so every partial dot is EXACT in the f32
   accumulator (beta_x + beta_c + log2(n) <= 24); partial results are
   combined hi-to-lo with two-float (TwoSum) arithmetic. 5x5 slices of
   8 bits cover ~40 significant bits — enough for the 1e-10 target.

Usage: N=256 python scripts/probe_r5_ds.py
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

N = int(os.environ.get("N", "256"))
ROWS = int(os.environ.get("ROWS", "4096"))
HI = jax.lax.Precision.HIGHEST


def split_host(x64, k=2):
    """f64 -> k f32 limbs (hi, lo, ...) on host."""
    out = []
    r = x64.copy()
    for _ in range(k):
        h = r.astype(np.float32)
        out.append(h)
        r = r - h.astype(np.float64)
    return out


def slice_host(x64, beta, s):
    """f64 -> s slices of beta significant bits each (Ozaki splitting),
    relative to the per-array max exponent (power-of-two scales only, so
    slicing is exact)."""
    slices = []
    r = x64.copy()
    mx = np.max(np.abs(r))
    e0 = np.floor(np.log2(mx)) + 1 if mx > 0 else 0
    for i in range(s):
        sc = 2.0 ** (e0 - beta * (i + 1))
        q = np.round(r / sc) * sc
        # keep each slice exactly representable in beta+1 bits
        slices.append(q.astype(np.float32))
        r = r - q
    return slices


def main():
    print(f"devices: {jax.devices()}  N={N} ROWS={ROWS}", flush=True)
    rng = np.random.default_rng(0)
    x = rng.standard_normal((ROWS, N))
    k = np.arange(N)
    C = np.cos(-2 * np.pi * np.outer(k, k) / N)  # real DFT part, f64
    y_ref = x @ C

    # plain f32 baseline
    yb = np.asarray(jax.jit(
        lambda a, b: jax.lax.dot_general(a, b, (((1,), (0,)), ((), ())),
                                         precision=HI))(
        jnp.asarray(x.astype(np.float32)), jnp.asarray(C.astype(np.float32))))
    rel = np.linalg.norm(yb - y_ref) / np.linalg.norm(y_ref)
    print(f"plain f32 dot rel: {rel:.2e}", flush=True)

    # A: compensated double-single, 3 dots + barrier
    xh, xl = split_host(x)
    ch, cl = split_host(C)

    @jax.jit
    def ds_dot(xh, xl, ch, cl):
        xh, xl, ch, cl = jax.lax.optimization_barrier((xh, xl, ch, cl))
        d = lambda a, b: jax.lax.dot_general(
            a, b, (((1,), (0,)), ((), ())), precision=HI)
        main = d(xh, ch)
        corr = d(xh, cl) + d(xl, ch)
        return main + corr

    ya = np.asarray(ds_dot(*map(jnp.asarray, (xh, xl, ch, cl))))
    rel = np.linalg.norm(ya - y_ref) / np.linalg.norm(y_ref)
    print(f"A compensated 3-dot rel: {rel:.2e}", flush=True)

    # B: Ozaki exact-sliced dots
    logn = int(np.ceil(np.log2(N)))
    # exactness needs (beta_x+1) + (beta_c+1) + logn <= 24 for the f32
    # accumulator: beta = (24 - logn - 2) // 2 = 7 at n=256; beta=8 was
    # measured to plateau at 2.5e-8 (inexact partial dots)
    for s, beta in ((6, 7), (7, 6), (9, 6)):
        xs = slice_host(x, beta, s)
        cs = slice_host(C, beta, s)

        @jax.jit
        def oz_dot(xs, cs):
            xs = jax.lax.optimization_barrier(tuple(xs))
            cs = jax.lax.optimization_barrier(tuple(cs))
            d = lambda a, b: jax.lax.dot_general(
                a, b, (((1,), (0,)), ((), ())), precision=HI)
            # partial dots grouped by total slice order i+j (descending
            # magnitude); combine with two-float accumulation
            sh = jnp.zeros((xs[0].shape[0], cs[0].shape[1]), jnp.float32)
            sl = jnp.zeros_like(sh)
            for o in range(2 * s - 1):
                for i in range(s):
                    j = o - i
                    if 0 <= j < s:
                        p = d(xs[i], cs[j])
                        # Knuth TwoSum (exact for any f32 pair) —
                        # barrier t so the algebraic simplifier cannot
                        # rewrite (sh+p)-p -> sh and kill the error term
                        t = jax.lax.optimization_barrier(sh + p)
                        bv = t - sh
                        av = t - bv
                        e = (sh - av) + (p - bv)
                        sh = t
                        sl = sl + e
            return sh, sl

        yh, yl = oz_dot(tuple(map(jnp.asarray, xs)),
                        tuple(map(jnp.asarray, cs)))
        yB = np.asarray(yh).astype(np.float64) \
            + np.asarray(yl).astype(np.float64)
        rel = np.linalg.norm(yB - y_ref) / np.linalg.norm(y_ref)
        print(f"B ozaki s={s} beta={beta} ({s*s} dots) rel: {rel:.2e}",
              flush=True)

    # timing: plain vs ozaki s=5
    def timeit(f, *args, reps=20):
        o = f(*args)
        jax.tree_util.tree_leaves(o)[0].block_until_ready()
        float(np.asarray(jax.tree_util.tree_leaves(o)[0].ravel()[0]))
        t0 = time.perf_counter()
        for _ in range(reps):
            o = f(*args)
        float(np.asarray(jax.tree_util.tree_leaves(o)[0].ravel()[0]))
        return (time.perf_counter() - t0) / reps

    tp = timeit(jax.jit(lambda a, b: jax.lax.dot_general(
        a, b, (((1,), (0,)), ((), ())), precision=HI)),
        jnp.asarray(x.astype(np.float32)), jnp.asarray(C.astype(np.float32)))
    s_t, beta_t = 6, 7
    xs = slice_host(x, beta_t, s_t)
    cs = slice_host(C, beta_t, s_t)

    @jax.jit
    def oz_dot_t(xs, cs):
        xs = jax.lax.optimization_barrier(tuple(xs))
        cs = jax.lax.optimization_barrier(tuple(cs))
        d = lambda a, b: jax.lax.dot_general(
            a, b, (((1,), (0,)), ((), ())), precision=HI)
        sh = jnp.zeros((xs[0].shape[0], cs[0].shape[1]), jnp.float32)
        sl = jnp.zeros_like(sh)
        for o in range(2 * s_t - 1):
            for i in range(s_t):
                j = o - i
                if 0 <= j < s_t:
                    pp = d(xs[i], cs[j])
                    t = jax.lax.optimization_barrier(sh + pp)
                    bv = t - sh
                    av = t - bv
                    e = (sh - av) + (pp - bv)
                    sh = t
                    sl = sl + e
        return sh, sl

    to = timeit(oz_dot_t, tuple(map(jnp.asarray, xs)),
                tuple(map(jnp.asarray, cs)))
    print(f"timing: plain {tp*1e3:.3f} ms  ozaki({s_t}x{s_t}) "
          f"{to*1e3:.3f} ms ({to/tp:.1f}x)", flush=True)


if __name__ == "__main__":
    main()

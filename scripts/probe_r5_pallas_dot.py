#!/usr/bin/env python
"""Round-5 probe: does Mosaic honor f32 matmul precision inside Pallas?

The fused-stage kernel plan (pdft_last in one Pallas pass) only works if
a dot inside the kernel can match XLA's HIGHEST-precision (multi-pass
bf16) f32 matmul accuracy. Measures rel error of a 256-point DFT row
pass vs numpy f64 for: XLA dot at HIGHEST/HIGH/DEFAULT, and Pallas dots
with precision=HIGHEST / preferred_element_type=f32.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import functools
import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

N = 256
rng = np.random.default_rng(3)
a64 = rng.standard_normal((512, N))
c64 = np.cos(2 * np.pi * np.outer(np.arange(N), np.arange(N)) / N)
ref = a64 @ c64
a = jnp.asarray(a64, jnp.float32)
c = jnp.asarray(c64, jnp.float32)


def rel(x):
    x = np.asarray(x, np.float64)
    return np.linalg.norm(x - ref) / np.linalg.norm(ref)


for name, prec in [("HIGHEST", jax.lax.Precision.HIGHEST),
                   ("HIGH", jax.lax.Precision.HIGH),
                   ("DEFAULT", jax.lax.Precision.DEFAULT)]:
    y = jax.jit(lambda a, c, p=prec: jax.lax.dot_general(
        a, c, (((1,), (0,)), ((), ())), precision=p))(a, c)
    print(f"XLA    {name:8s} rel {rel(y):.3e}", flush=True)


def kernel(prec, a_ref, c_ref, o_ref):
    o_ref[...] = jax.lax.dot_general(
        a_ref[...], c_ref[...], (((1,), (0,)), ((), ())),
        precision=prec, preferred_element_type=jnp.float32)


for name, prec in [("HIGHEST", jax.lax.Precision.HIGHEST),
                   ("HIGH", jax.lax.Precision.HIGH),
                   ("DEFAULT", jax.lax.Precision.DEFAULT),
                   ("None", None)]:
    try:
        f = pl.pallas_call(
            functools.partial(kernel, prec),
            out_shape=jax.ShapeDtypeStruct((512, N), jnp.float32))
        y = jax.jit(f)(a, c)
        print(f"PALLAS {name:8s} rel {rel(y):.3e}", flush=True)
    except Exception as e:  # noqa: BLE001
        print(f"PALLAS {name:8s} FAILED: {type(e).__name__}: "
              f"{str(e).splitlines()[0][:120]}", flush=True)

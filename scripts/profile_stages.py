#!/usr/bin/env python
"""Per-stage wall-clock + achieved-GB/s breakdown of the north-star pipeline
on the real device, against a measured device-copy floor.

Dispatch through this remote-attached platform costs ~10 ms per call, which
swamps per-stage device time at any size — so each stage is timed as ONE
executable running R scanned iterations (carry = the stage input, perturbed
by a cheap elementwise pass each step so XLA cannot hoist the loop-invariant
stage out of the scan). The perturbation pass is measured by a calibration
scan and subtracted. Hard-synced via host readback (``block_until_ready``
returns early here, see bench.py).

GB/s is *effective*: the stage's logical bytes (elements read + written
once, c64=8B) over device time — FFT stages do more internal passes, so
their effective number understates hardware traffic; the copy-floor row
calibrates what "bandwidth-bound" means on this chip.

Usage: DIM=256 python scripts/profile_stages.py   (or DIMS="64 128 256")
"""
import sys
import os
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

from spfft_tpu import TransformType, make_local_plan
from spfft_tpu.ops import stages
from spfft_tpu.utils.workloads import spherical_cutoff_triplets

C64 = 8  # bytes
R = int(os.environ.get("REPS", 20))


def sync(out):
    leaf = jax.tree_util.tree_leaves(out)[0]
    float(np.asarray(jax.numpy.real(leaf).ravel()[0]))


def _perturb(x):
    return x * x.dtype.type(1.0 + 1e-7)


def _consume(y):
    """Reduce the WHOLE output to a scalar: consuming a single element
    would let XLA dead-code-eliminate most of a gather stage (and parts
    of FFTs), faking near-zero stage times."""
    leaf = jax.tree_util.tree_leaves(y)[0]
    if jnp.iscomplexobj(leaf):
        return jnp.mean(jnp.real(leaf)) + jnp.mean(jnp.imag(leaf))
    return jnp.mean(leaf)


def _scan_seconds(body, x, reps=3):
    """Wall-clock of ONE dispatch of R scanned body(x) steps (the carry is
    perturbed each step so nothing hoists; the full output is reduced so
    nothing DCEs)."""
    def run(x0):
        def step(c, _):
            xp = _perturb(c)
            return xp, _consume(body(xp))
        _, ys = jax.lax.scan(step, x0, None, length=R)
        return ys
    f = jax.jit(run)
    out = f(x)
    sync(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = f(x)
    sync(out)
    return (time.perf_counter() - t0) / reps


def scan_time(name, body, x, nbytes, calib_s):
    """Per-step stage seconds: scanned time minus the calibration scan
    (perturbation pass + consume reduction + scan overhead), divided by R.
    Stages cheaper than ~15% of the calibration scan are below the
    subtraction noise floor and reported as such."""
    total = _scan_seconds(body, x)
    dt = (total - calib_s) / R
    noise = 0.15 * calib_s / R
    if dt < noise:
        print(f"{name:24s} {'<'+format(noise*1e3, '.3f'):>9s} ms   "
              f"(below noise floor; {nbytes/1e6:8.1f} MB logical)",
              flush=True)
        return max(dt, 0.0)
    gbs = nbytes / dt / 1e9 if nbytes else 0.0
    print(f"{name:24s} {dt*1e3:8.3f} ms   {gbs:7.1f} GB/s "
          f"({nbytes/1e6:8.1f} MB logical)", flush=True)
    return dt


def calibration(x):
    """The scan with an identity body: measures the perturbation pass, the
    consume reduction and scan overhead."""
    return _scan_seconds(lambda xp: xp, x)


def copy_floor(n_elems_c64: int):
    """Device copy floor from the calibration scan itself: each step reads
    the carry, writes the perturbed carry, and reads it again for the mean
    (XLA fuses any extra elementwise multiply into the same pass, so a
    separate 'body' would measure nothing) — three array traversals of
    n elements per step."""
    x = jnp.ones((n_elems_c64, 2), jnp.float32)
    dt = calibration(x) / R
    return 3 * n_elems_c64 * C64 / dt / 1e9, dt


def profile(n: int):
    triplets = spherical_cutoff_triplets(n)
    plan = make_local_plan(TransformType.C2C, n, n, n, triplets,
                           precision="single")
    p = plan.index_plan
    N, S, Z = p.num_values, p.num_sticks, p.dim_z
    SZ, G = S * Z, n * n * n
    print(f"\n== dim={n} values={N} sticks={S} "
          f"pallas={plan._pallas_active} (R={R} scanned steps/stage) ==",
          flush=True)
    floor_gbs, _ = copy_floor(G)
    print(f"{'copy floor (n^3 c64)':24s} {'':8s}      {floor_gbs:7.1f} GB/s",
          flush=True)

    rng = np.random.default_rng(0)
    values = (rng.uniform(-1, 1, N)
              + 1j * rng.uniform(-1, 1, N)).astype(np.complex64)
    # the plan's own coercion produces the correct boundary layout
    # (interleaved rows, or planar pair for >=16M-value plans)
    values_il = jax.device_put(plan._coerce_values(values))
    tables = plan._tables

    total_bytes = 0
    total_time = 0.0

    def stage(name, body, arg, nbytes, calib_s):
        nonlocal total_bytes, total_time
        dt = scan_time(name, body, arg, nbytes, calib_s)
        total_bytes += nbytes
        total_time += dt

    # calibration per carry shape (the perturbation pass scales with it)
    cal_values = calibration(values_il)
    sticks0 = jax.jit(lambda v: plan._decompress(v, tables))(values_il)
    cal_sticks = calibration(sticks0)
    grid0 = jax.jit(lambda s: stages.sticks_to_grid(
        s, tables["col_inv"], p.dim_y, p.dim_x_freq))(sticks0)
    cal_grid = calibration(grid0)

    stage("decompress", lambda v: plan._decompress(v, tables), values_il,
          (N + SZ) * C64, cal_values)
    stage("z_backward (ifft)", stages.z_backward, sticks0,
          2 * SZ * C64, cal_sticks)
    stage("sticks_to_grid", lambda s: stages.sticks_to_grid(
        s, tables["col_inv"], p.dim_y, p.dim_x_freq), sticks0,
        (SZ + G) * C64, cal_sticks)
    stage("xy_backward (ifft2)", stages.xy_backward_c2c, grid0,
          2 * G * C64, cal_grid)
    stage("xy_forward (fft2)", stages.xy_forward_c2c, grid0,
          2 * G * C64, cal_grid)
    stage("grid_to_sticks", lambda g: stages.grid_to_sticks(
        g, tables["scatter_cols"]), grid0, (G + SZ) * C64, cal_grid)
    stage("z_forward (fft)", stages.z_forward, sticks0,
          2 * SZ * C64, cal_sticks)
    stage("compress", lambda s: plan._compress(s, tables, None), sticks0,
          (SZ + N) * C64, cal_sticks)

    if total_time > 0:
        print(f"{'sum of stages':24s} {total_time*1e3:8.2f} ms   "
              f"{total_bytes/total_time/1e9:7.1f} GB/s", flush=True)
    else:
        print(f"{'sum of stages':24s} below noise floor at this size",
              flush=True)

    # the fused pair, scanned through iterate-style composition
    pair_t = scan_time(
        "FULL fused pair",
        lambda v: plan._forward_impl(plan._backward_impl(v, tables), tables,
                                     scaled=False),
        values_il, total_bytes, cal_values)
    print(f"{'vs stage sum':24s} {(total_time-pair_t)*1e3:8.2f} ms "
          f"({(1 - pair_t/max(total_time,1e-12))*100:.0f}% saved by fusion)",
          flush=True)


if __name__ == "__main__":
    dims = os.environ.get("DIMS") or os.environ.get("DIM", "256")
    print(f"devices: {jax.devices()}", flush=True)
    for d in dims.split():
        profile(int(d))

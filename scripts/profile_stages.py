#!/usr/bin/env python
"""Per-stage wall-clock breakdown of the 256^3 north-star pipeline on the
real device — identifies which phase dominates the backward+forward pair."""
import sys
import os
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

from spfft_tpu import TransformType, make_local_plan
from spfft_tpu.ops import stages
from spfft_tpu.utils.workloads import spherical_cutoff_triplets
from spfft_tpu.utils import as_interleaved

n = int(os.environ.get("DIM", 256))
triplets = spherical_cutoff_triplets(n)
plan = make_local_plan(TransformType.C2C, n, n, n, triplets,
                       precision="single")
p = plan.index_plan
print(f"dim={n} num_values={p.num_values} num_sticks={p.num_sticks} "
      f"pallas_active={plan._pallas_active}")

rng = np.random.default_rng(0)
values = (rng.uniform(-1, 1, len(triplets))
          + 1j * rng.uniform(-1, 1, len(triplets))).astype(np.complex64)
values_il = jnp.asarray(as_interleaved(values, "single"))
tables = plan._tables


def timeit(name, fn, *args, reps=5):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / reps
    print(f"{name:24s} {dt*1e3:8.2f} ms")
    return out


# backward stages
dec = jax.jit(lambda v: plan._decompress(v, tables))
sticks = timeit("decompress", dec, values_il)
zb = jax.jit(stages.z_backward)
sticks_z = timeit("z_backward (ifft)", zb, sticks)
s2g = jax.jit(lambda s: stages.sticks_to_grid(s, tables["col_inv"], p.dim_y,
                                              p.dim_x_freq))
grid = timeit("sticks_to_grid", s2g, sticks_z)
xyb = jax.jit(stages.xy_backward_c2c)
space = timeit("xy_backward (ifft2)", xyb, grid)

# forward stages
xyf = jax.jit(stages.xy_forward_c2c)
gridf = timeit("xy_forward (fft2)", xyf, space)
g2s = jax.jit(lambda g: stages.grid_to_sticks(g, tables["scatter_cols"]))
sticksf = timeit("grid_to_sticks", g2s, gridf)
zf = jax.jit(stages.z_forward)
sticks_zf = timeit("z_forward (fft)", zf, sticksf)
cmp_ = jax.jit(lambda s: plan._compress(s, tables, None))
vals = timeit("compress", cmp_, sticks_zf)

# full fused pair
pair = jax.jit(lambda v: plan._forward_impl(
    plan._backward_impl(v, tables), tables, scaled=False))
timeit("FULL fused pair", pair, values_il)

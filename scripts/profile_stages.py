#!/usr/bin/env python
"""Per-stage wall-clock + achieved-GB/s breakdown of the north-star pipeline
on the real device, against a measured device-copy floor.

Timing is hard-synced (host readback of one element — ``block_until_ready``
returns early on this remote-attached platform, see bench.py). GB/s is
*effective*: the stage's logical bytes (elements read + written once, c64=8B)
over wall-clock — FFT stages do more internal passes, so their effective
number understates the hardware traffic; the copy floor row calibrates what
"bandwidth-bound" means on this chip+tunnel.

Usage: DIM=256 python scripts/profile_stages.py   (or DIMS="64 128 256")
"""
import sys
import os
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

from spfft_tpu import TransformType, make_local_plan
from spfft_tpu.ops import stages
from spfft_tpu.utils.workloads import spherical_cutoff_triplets
from spfft_tpu.utils import as_interleaved

C64 = 8  # bytes


def sync(out):
    leaf = jax.tree_util.tree_leaves(out)[0]
    float(np.asarray(jax.numpy.real(leaf).ravel()[0]))


def timeit(name, fn, *args, reps=10, nbytes=0):
    out = fn(*args)
    sync(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    sync(out)
    dt = (time.perf_counter() - t0) / reps
    gbs = nbytes / dt / 1e9 if nbytes else 0.0
    print(f"{name:24s} {dt*1e3:8.2f} ms   {gbs:7.1f} GB/s "
          f"({nbytes/1e6:8.1f} MB logical)", flush=True)
    return out, dt


def copy_floor(n_elems_c64: int, reps=10):
    """Device copy floor: out = in + 0 on an n-element c64 array (one read +
    one write per element, no compute)."""
    x = jnp.zeros((n_elems_c64, 2), jnp.float32)
    f = jax.jit(lambda a: a + jnp.float32(0))
    out = f(x)
    sync(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = f(out)
    sync(out)
    dt = (time.perf_counter() - t0) / reps
    return 2 * n_elems_c64 * C64 / dt / 1e9, dt


def profile(n: int):
    triplets = spherical_cutoff_triplets(n)
    plan = make_local_plan(TransformType.C2C, n, n, n, triplets,
                           precision="single")
    p = plan.index_plan
    N, S, Z = p.num_values, p.num_sticks, p.dim_z
    SZ, G = S * Z, n * n * n
    print(f"\n== dim={n} values={N} sticks={S} "
          f"pallas={plan._pallas_active} ==", flush=True)
    floor_gbs, _ = copy_floor(G)
    print(f"{'copy floor (n^3 c64)':24s} {'':8s}      {floor_gbs:7.1f} GB/s",
          flush=True)

    rng = np.random.default_rng(0)
    values = (rng.uniform(-1, 1, N)
              + 1j * rng.uniform(-1, 1, N)).astype(np.complex64)
    values_il = jax.device_put(np.asarray(as_interleaved(values, "single")))
    tables = plan._tables

    total_bytes = 0
    total_time = 0.0

    def stage(name, fn, arg, nbytes):
        nonlocal total_bytes, total_time
        out, dt = timeit(name, fn, arg, nbytes=nbytes)
        total_bytes += nbytes
        total_time += dt
        return out

    dec = jax.jit(lambda v: plan._decompress(v, tables))
    sticks = stage("decompress", dec, values_il, (N + SZ) * C64)
    zb = jax.jit(stages.z_backward)
    sticks_z = stage("z_backward (ifft)", zb, sticks, 2 * SZ * C64)
    s2g = jax.jit(lambda s: stages.sticks_to_grid(
        s, tables["col_inv"], p.dim_y, p.dim_x_freq))
    grid = stage("sticks_to_grid", s2g, sticks_z, (SZ + G) * C64)
    xyb = jax.jit(stages.xy_backward_c2c)
    space = stage("xy_backward (ifft2)", xyb, grid, 2 * G * C64)

    xyf = jax.jit(stages.xy_forward_c2c)
    gridf = stage("xy_forward (fft2)", xyf, space, 2 * G * C64)
    g2s = jax.jit(lambda g: stages.grid_to_sticks(g, tables["scatter_cols"]))
    sticksf = stage("grid_to_sticks", g2s, gridf, (G + SZ) * C64)
    zf = jax.jit(stages.z_forward)
    sticks_zf = stage("z_forward (fft)", zf, sticksf, 2 * SZ * C64)
    cmp_ = jax.jit(lambda s: plan._compress(s, tables, None))
    stage("compress", cmp_, sticks_zf, (SZ + N) * C64)

    print(f"{'sum of stages':24s} {total_time*1e3:8.2f} ms   "
          f"{total_bytes/total_time/1e9:7.1f} GB/s", flush=True)

    pair = jax.jit(lambda v: plan._forward_impl(
        plan._backward_impl(v, tables), tables, scaled=False))
    _, dt = timeit("FULL fused pair", pair, values_il, nbytes=total_bytes)
    print(f"{'fusion saving':24s} {(total_time-dt)*1e3:8.2f} ms "
          f"({(1 - dt/total_time)*100:.0f}% vs stage sum)", flush=True)


if __name__ == "__main__":
    dims = os.environ.get("DIMS") or os.environ.get("DIM", "256")
    print(f"devices: {jax.devices()}", flush=True)
    for d in dims.split():
        profile(int(d))

#!/usr/bin/env python
"""Same-session interleaved A/B for the overlap pipeline: monolithic
(K=1) vs chunked (K in {2, 4}) distributed exchange — the multichip
bench lane the ISSUE-4 acceptance criteria record in BENCHMARKS.md
"Round-9".

Protocol: ONE backend session builds every (exchange, K) plan on the
same mesh and the measurement rounds INTERLEAVE across plans (A/B/A/B),
so session-state drift (compile caches, allocator warmup) hits every
variant equally — the ab_interleaved.py lesson applied within a
session. Per variant the script reports the median-of-rounds pair time
plus the structural HLO evidence: collective launch count (K per
direction when chunked) and the async start/done split of the COMPILED
module (non-zero only on backends whose scheduler overlaps collectives
— XLA:TPU; zero on XLA:CPU, where the numbers below are mechanism
overhead only, not overlap wins).

  python scripts/bench_overlap_ab.py [--shards 8] [--dim 48] \
      [--reps 10] [--rounds 5] [--cpu] [-o overlap_ab.json]

On a CPU container pass ``--cpu`` to force a virtual --shards-device
platform (same as the test conftest); on a TPU pod slice run it bare.
"""
import argparse
import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--shards", type=int, default=8)
    ap.add_argument("--dim", type=int, default=48)
    ap.add_argument("--reps", type=int, default=10,
                    help="pairs per measurement group")
    ap.add_argument("--rounds", type=int, default=5,
                    help="interleaved rounds per variant")
    ap.add_argument("--chunks", type=int, nargs="+", default=[1, 2, 4])
    ap.add_argument("--cpu", action="store_true",
                    help="force a virtual CPU platform with --shards "
                         "devices")
    ap.add_argument("-o", "--output", default=None, metavar="FILE.json")
    args = ap.parse_args(argv)

    if args.cpu:
        from spfft_tpu.utils.platform import force_virtual_cpu_devices
        force_virtual_cpu_devices(args.shards)

    import numpy as np
    import jax

    from spfft_tpu import ExchangeType, TransformType
    from spfft_tpu.parallel import make_distributed_plan, make_mesh
    from spfft_tpu.utils.hlo_inspect import (collective_async_split,
                                             count_collectives)
    from spfft_tpu.utils.workloads import (even_plane_split,
                                           round_robin_stick_partition,
                                           spherical_cutoff_triplets)

    n, S = args.dim, args.shards
    tr = spherical_cutoff_triplets(n)
    parts = round_robin_stick_partition(tr, (n, n, n), S)
    planes = even_plane_split(n, S)
    mesh = make_mesh(S)
    rng = np.random.default_rng(42)
    vals_np = [(rng.uniform(-1, 1, len(p))
                + 1j * rng.uniform(-1, 1, len(p))).astype(np.complex64)
               for p in parts]

    variants = []  # (label, plan, device values, hlo evidence)
    for exch, ename in ((ExchangeType.DEFAULT, "buffered"),
                        (ExchangeType.COMPACT_BUFFERED, "ragged")):
        for k in args.chunks:
            plan = make_distributed_plan(
                TransformType.C2C, n, n, n, parts, planes, mesh=mesh,
                exchange=exch, overlap_chunks=k)
            v = plan.shard_values(vals_np)
            lowered = plan._backward_jit.lower(v, *plan._device_tables)
            launches = sum(count_collectives(lowered.as_text()).values())
            split = collective_async_split(lowered.compile().as_text())
            variants.append({
                "label": f"{ename}-k{plan.overlap_chunks}",
                "exchange": ename, "k": plan.overlap_chunks,
                "plan": plan, "values": v,
                "collectives_bwd": launches,
                "async_starts": split["starts"],
                "wire_total_bytes": int(plan.exchange_wire_bytes()),
                "times": []})

    def sync(a):
        jax.block_until_ready(a)
        np.asarray(jax.tree_util.tree_leaves(a)[-1]).ravel()[:1]

    for var in variants:  # warm every executable before any timing
        sync(var["plan"].apply_pointwise(var["values"]))
    for _ in range(args.rounds):
        for var in variants:  # interleaved: one group per variant
            t0 = time.perf_counter()
            out = None
            for _ in range(args.reps):
                out = var["plan"].apply_pointwise(var["values"])
            sync(out)
            var["times"].append((time.perf_counter() - t0) / args.reps)

    backend = jax.default_backend()
    rows = []
    base_ms = {}
    for var in variants:
        ms = sorted(t * 1e3 for t in var["times"])
        med = statistics.median(ms)
        if var["k"] == 1:
            base_ms[var["exchange"]] = med
        rows.append({k: var[k] for k in
                     ("label", "exchange", "k", "collectives_bwd",
                      "async_starts", "wire_total_bytes")}
                    | {"pair_ms_median": round(med, 3),
                       "pair_ms_min": round(ms[0], 3),
                       "vs_k1": round(base_ms[var["exchange"]] / med, 3)})
    payload = {
        "backend": backend, "shards": S, "dim": n,
        "num_values": int(len(tr)), "reps": args.reps,
        "rounds": args.rounds,
        "overlap_meaningful": backend == "tpu",
        "note": ("async_starts == 0 on this backend: the scheduler "
                 "runs collectives synchronously, so K>1 measures "
                 "chunking overhead, not overlap wins — run on TPU "
                 "for the real A/B" if backend != "tpu" else
                 "async start/done split active"),
        "rows": rows,
    }
    print(json.dumps(payload, indent=2))
    if args.output:
        with open(args.output, "w") as f:
            json.dump(payload, f, indent=2)
        print(f"wrote {args.output}")
    return 0


if __name__ == "__main__":
    sys.exit(main())

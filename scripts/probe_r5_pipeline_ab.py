#!/usr/bin/env python
"""Round-5 probe: fused identity pair, matmul-DFT pipeline vs the
jnp.fft pipeline, same session, alternating diff-estimator blocks.

profile_stages.py's stage-sum for the jnp.fft pipeline (7.7 ms) came in
UNDER the mdft fused pair (11.6 ms) at 256^3 — but scanned stage bodies
overlap differently than a fused dispatch, so this measures the real
thing: two plans, two fused executables, one session.

Usage: DIM=256 python scripts/probe_r5_pipeline_ab.py
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax

from spfft_tpu import TransformType, make_local_plan
from spfft_tpu.utils.benchtime import diff_estimate_seconds
from spfft_tpu.utils.workloads import spherical_cutoff_triplets


def sync(a):
    return float(np.asarray(jax.numpy.real(a).ravel()[0]))


def measure(plan, vil, reps=20):
    def grp(g):
        t0 = time.perf_counter()
        o = None
        for _ in range(g):
            o = plan.apply_pointwise(vil)
        sync(o)
        return time.perf_counter() - t0
    return diff_estimate_seconds(grp, reps=reps)


def main():
    n = int(os.environ.get("DIM", "256"))
    print(f"devices: {jax.devices()}", flush=True)
    triplets = spherical_cutoff_triplets(n)
    rng = np.random.default_rng(42)
    N = len(triplets)
    values = (rng.uniform(-1, 1, N)
              + 1j * rng.uniform(-1, 1, N)).astype(np.complex64)

    plan_mdft = make_local_plan(TransformType.C2C, n, n, n, triplets,
                                precision="single")
    os.environ["SPFFT_TPU_NO_MATMUL_DFT"] = "1"
    try:
        plan_fft = make_local_plan(TransformType.C2C, n, n, n, triplets,
                                   precision="single")
    finally:
        del os.environ["SPFFT_TPU_NO_MATMUL_DFT"]
    assert plan_mdft._use_mdft and not plan_fft._use_mdft

    vil = jax.device_put(plan_mdft._coerce_values(values))

    out_a = np.asarray(plan_mdft.apply_pointwise(vil))
    out_b = np.asarray(plan_fft.apply_pointwise(vil))
    rel = np.linalg.norm(out_a - out_b) / np.linalg.norm(out_a)
    print(f"mdft-vs-fft output rel diff: {rel:.2e}", flush=True)

    sync(plan_fft.apply_pointwise(vil))
    sync(plan_mdft.apply_pointwise(vil))
    for it in range(3):
        ea = measure(plan_mdft, vil)
        eb = measure(plan_fft, vil)
        print(f"block {it}: mdft {ea.seconds*1e3:.3f} ms "
              f"(med {ea.median*1e3:.3f})   fft {eb.seconds*1e3:.3f} ms "
              f"(med {eb.median*1e3:.3f})", flush=True)


if __name__ == "__main__":
    main()

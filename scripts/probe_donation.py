#!/usr/bin/env python
"""Peak-HBM effect of input donation on the fused pair at large grids.

Builds the spherical-cutoff C2C plan twice — donate_inputs False/True —
runs the fused pair on device-resident values, and reports the device
peak_bytes_in_use around each run (the TPU form of the reference's
two-array in-place buffer economy, grid_internal.cpp:75-98).

Usage: DIM=384 python scripts/probe_donation.py
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax

from spfft_tpu import TransformType, make_local_plan
from spfft_tpu.utils import as_interleaved
from spfft_tpu.utils.workloads import spherical_cutoff_triplets


def peak_mb():
    stats = jax.devices()[0].memory_stats() or {}
    return stats.get("peak_bytes_in_use", 0) / 1e6


def run(n: int, donate: bool, triplets, values):
    plan = make_local_plan(TransformType.C2C, n, n, n, triplets,
                           precision="single", donate_inputs=donate)
    vi = jax.device_put(plan._coerce_values(values))
    out = plan.apply_pointwise(vi)   # compile + run (vi consumed if donate)
    out.block_until_ready()
    p0 = peak_mb()
    vi2 = jax.device_put(plan._coerce_values(values))
    t0 = time.perf_counter()
    out = plan.apply_pointwise(vi2)
    out.block_until_ready()
    dt = time.perf_counter() - t0
    print(f"donate={donate}: peak {peak_mb():.0f} MB "
          f"(pre-run {p0:.0f}), pair {dt * 1e3:.1f} ms", flush=True)
    del out, vi2
    return None


def main():
    n = int(os.environ.get("DIM", "384"))
    triplets = spherical_cutoff_triplets(n)
    rng = np.random.default_rng(0)
    values = (rng.uniform(-1, 1, len(triplets))
              + 1j * rng.uniform(-1, 1, len(triplets))).astype(np.complex64)
    values = np.asarray(as_interleaved(values, "single"))
    donate = os.environ.get("DONATE", "0") == "1"
    print(f"dim={n}, values={len(triplets)}, donate={donate}", flush=True)
    # peak_bytes_in_use is a process-lifetime high-water mark: run ONE
    # configuration per process (drive both via the DONATE env var).
    run(n, donate, triplets, values)


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Round-5 probe: forced (kp, K) sweep of the 256^3 compress gather.

The auto-chosen wide tables for the compress direction DMA K=192-row
windows (kp=12), reading ~385 MB for a 105 MB source (3.7x overfetch).
Sweeps forced sub-window/DMA-window heights and times the bare kernel;
if a tighter config wins, the builder's cost model gets re-calibrated.

Usage: python scripts/probe_r5_cmp_sweep.py
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

from spfft_tpu import TransformType, make_local_plan
from spfft_tpu.ops import gather_kernel as gk
from spfft_tpu.utils.benchtime import diff_estimate_seconds
from spfft_tpu.utils.workloads import spherical_cutoff_triplets

DIM = int(os.environ.get("DIM", 256))


def sync(out):
    leaf = jax.tree_util.tree_leaves(out)[0]
    return float(np.asarray(jnp.real(leaf).ravel()[0]))


def measure(f, *args, reps=14):
    g = jax.jit(f)
    sync(g(*args))

    def grp(k):
        t0 = time.perf_counter()
        o = None
        for _ in range(k):
            o = g(*args)
        sync(o)
        return time.perf_counter() - t0
    return diff_estimate_seconds(grp, reps=reps).seconds


def main():
    tri = spherical_cutoff_triplets(DIM)
    plan = make_local_plan(TransformType.C2C, DIM, DIM, DIM, tri)
    plan._finalize()
    p = plan.index_plan
    vi = p.value_indices.astype(np.int64)
    num_slots = plan._s_pad * p.dim_z
    (_, _), (cmp_idx, cmp_valid) = gk.compression_gather_inputs(
        vi, num_slots)

    rng = np.random.default_rng(3)
    src_rows_flat = -(-num_slots // 128)
    re = jax.device_put(jnp.asarray(
        rng.standard_normal((src_rows_flat, 128)), jnp.float32))
    im = jax.device_put(jnp.asarray(
        rng.standard_normal((src_rows_flat, 128)), jnp.float32))

    ref = None
    configs = [(0, 0)] + [(kp, 0) for kp in (8, 10, 12, 16, 20, 24)] \
        + [(12, 96), (12, 128), (12, 160), (16, 128), (10, 128), (8, 96),
           (8, 64), (10, 96)]
    seen = set()
    for kp, K in configs:
        if (kp, K) in seen:
            continue
        seen.add((kp, K))
        try:
            t = gk.build_wide_gather_tables(cmp_idx, cmp_valid, num_slots,
                                            kp_rows=kp, k_rows=K)
        except Exception as e:  # noqa: BLE001
            print(f"kp={kp:3d} K={K:3d}: build failed {e}", flush=True)
            continue
        if t is None:
            print(f"kp={kp:3d} K={K:3d}: builder refused", flush=True)
            continue
        dev = gk.gather_device_tables(t)
        out = jax.jit(lambda a, b: gk.run_gather(a, b, dev, t))(re, im)
        got = np.asarray(out[0].reshape(-1)[:t.num_out])
        if ref is None:
            ref = got
        else:
            assert np.array_equal(got, ref), "config changed results!"
        sec = measure(lambda a, b: gk.run_gather(a, b, dev, t), re, im)
        traffic = (t.row0.shape[0] * t.span_rows * 128 * 4 * 2
                   + t.num_out * 4 * 2) / 1e9
        print(f"kp={kp:3d} K={K:3d}: chunks={t.row0.shape[0]:5d} "
              f"span={t.span_rows:3d} segs={len(t.segs) if t.segs else 1} "
              f"-> {sec*1e3:7.3f} ms ({traffic/sec:5.0f} GB/s modeled)",
              flush=True)


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Round-5 device fuzz: fused-stage kernels vs dense oracle at random
awkward shapes.

The interpret-mode CPU tests pin the kernels' tiling logic, and ci-tpu
covers 32/64/320-class shapes; this sweep drives REAL Mosaic codegen
over randomly drawn dims (odd, prime, non-tile-aligned, rectangular),
C2C and R2C, sparse stick subsets (split-x windows included), comparing
backward against the dense numpy oracle and the round trip against the
inputs. Run on demand after kernel changes:

    SEEDS=12 python scripts/fuzz_fused_r05.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from spfft_tpu import Scaling, TransformType, make_local_plan

TOL = 2e-6


def one_case(rng, k):
    dims = [int(rng.integers(3, 97)) for _ in range(3)]
    nx, ny, nz = dims
    r2c = bool(rng.integers(0, 2))
    xmax = nx // 2 + 1 if r2c else nx
    # random stick subset; sometimes a narrow x window (split-x path)
    narrow = rng.integers(0, 3) == 0
    xs = np.arange(min(xmax, max(1, int(rng.integers(1, 4))))) if narrow \
        else np.arange(xmax)
    sticks = {(x, y) for x in xs for y in range(ny)
              if rng.random() < 0.7}
    if r2c and nx % 2 == 0:
        # CONTRACT (reference details.rst "Real-To-Complex"): the
        # either/or mirror tolerance applies to the x=0 plane ONLY.
        # Nyquist-plane sticks (x = nx/2, self-mirrored in x) must come
        # with their (-y) mirror present, or the input is outside the
        # hermitian contract (neither the reference nor this library
        # completes that plane — first fuzz run produced exactly those
        # invalid sets and 4e-2 'failures').
        for (x, y) in list(sticks):
            if x == nx // 2:
                sticks.add((x, (-y) % ny))
    sticks = sorted(sticks)
    if not sticks:
        sticks = [(0, 0)]
    tri = np.array([(x, y, z) for (x, y) in sticks for z in range(nz)],
                   np.int64)
    tt = TransformType.R2C if r2c else TransformType.C2C
    if r2c:
        # hermitian-consistent values: sample a real field's spectrum
        field = rng.standard_normal((nz, ny, nx)).astype(np.float32)
        freq = np.fft.fftn(field)
        vals = freq[tri[:, 2], tri[:, 1], tri[:, 0]].astype(np.complex64)
    else:
        vals = (rng.standard_normal(len(tri))
                + 1j * rng.standard_normal(len(tri))).astype(np.complex64)
    plan = make_local_plan(tt, nx, ny, nz, tri, precision="single")
    space = np.asarray(plan.backward(vals))
    cube = np.zeros((nz, ny, nx), np.complex64)
    cube[tri[:, 2], tri[:, 1], tri[:, 0]] = vals
    if r2c:
        # dense oracle: place the half-spectrum values, complete the
        # implied hermitian mirrors (provided entries win, matching the
        # library's nonzero-guarded completion), real inverse
        dense = cube.copy()
        mx, my, mz = ((-tri[:, 0]) % nx, (-tri[:, 1]) % ny,
                      (-tri[:, 2]) % nz)
        mirror_ok = dense[mz, my, mx] != 0
        dense[mz, my, mx] = np.where(mirror_ok, dense[mz, my, mx],
                                     np.conj(vals))
        oracle = np.real(np.fft.ifftn(dense)) * dense.size
        got = space
    else:
        oracle = np.fft.ifftn(cube) * cube.size
        got = space[..., 0] + 1j * space[..., 1]
    rel = (np.linalg.norm((got - oracle).ravel())
           / max(np.linalg.norm(oracle.ravel()), 1e-30))
    out = np.asarray(plan.forward(space, Scaling.FULL))
    rt = np.linalg.norm(out[:, 0] + 1j * out[:, 1] - vals) \
        / max(np.linalg.norm(vals), 1e-30)
    tag = f"{nx}x{ny}x{nz} {'r2c' if r2c else 'c2c'}" \
        + (" split" if plan._split_x is not None else "")
    ok = rel < TOL and rt < TOL
    print(f"[{k:02d}] {tag:24s} n={len(tri):6d} bwd {rel:.2e} rt {rt:.2e}"
          f" {'OK' if ok else 'FAIL'}", flush=True)
    return ok


def main():
    seeds = int(os.environ.get("SEEDS", 12))
    rng = np.random.default_rng(2025)
    bad = 0
    for k in range(seeds):
        bad += 0 if one_case(rng, k) else 1
    print(f"{seeds - bad}/{seeds} cases pass", flush=True)
    sys.exit(1 if bad else 0)


if __name__ == "__main__":
    main()

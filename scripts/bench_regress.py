#!/usr/bin/env python
"""Bench regression guard: machine-check the perf trajectory.

Five rounds of BENCH_r*.json were compared by eyeball; this script
makes the comparison a nonzero-exit mechanism (``make bench-check``):

    python bench.py | grep '^{' | tail -1 > build/bench_fresh.json
    python scripts/bench_regress.py --fresh build/bench_fresh.json

* ``--fresh`` — a fresh measurement: either the single JSON line
  ``bench.py`` prints ({"metric", "value", "unit", ...}) or a driver
  BENCH_r*.json ({"parsed": {...}}).
* ``--against`` — the reference (same formats). Default: the
  highest-numbered BENCH_r*.json in the repo root; with none present
  the check reports "no reference" and exits 0 (a fresh repo cannot
  regress against nothing).
* ``--threshold`` — the noise allowance (default 0.15: the r05 session
  spread is sub-1%, but cross-session/container variance has measured
  excursions near 10%; 15% flags real cliffs without crying wolf on
  backend jitter).

Beyond the primary measurement, any named SUB-ROW present in BOTH
files is compared with the same rules: a ``"fused"`` entry (the fused
compression+z-DFT path's pair time, ``benchmark.py --fused`` —
expected from BENCH_r06.json on) regresses the exit code exactly like
the primary row. A row present on only one side is reported as
``row-no-reference`` and never fails (a fresh repo cannot regress
against nothing; an older reference predates the row).

Direction is inferred from the unit: seconds-like units regress when
the fresh value is HIGHER, rate-like units (req/s, GB/s, ...) when it
is LOWER. Exit codes: 0 within threshold (or improved), 1 regression,
2 usage/parse error. Prints one JSON verdict line per compared row
(the bench.py convention).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

#: Units where SMALLER is better; anything else is treated as a rate.
LOWER_IS_BETTER_UNITS = ("s", "ms", "us", "ns", "seconds", "bytes",
                         "rel-l2")

#: Named sub-measurements compared alongside the primary row whenever
#: both files carry them (e.g. {"fused": {"value": ..., "unit": "s"}}).
#: cold_start_ms/warm_start_ms (benchmark.py --store-dir, recorded
#: from BENCH_r06.json on) guard the zero-cold-start trajectory the
#: round-13 plan-artifact store opened; "ms" units regress when the
#: fresh value is higher, like every seconds-like row.
#: wire_bytes_r2c (unit "bytes", lower is better) is the hermitian-
#: trimmed R2C distributed exchange's table-derived aggregate wire on
#: the flagship spherical workload — deterministic accounting, so any
#: growth past threshold means the trimming regressed. fused_r2c
#: (unit "seams", higher is better) counts the ACTIVE r2c fused seams
#: on the interpret lane (local kernel + distributed twin, 2 when the
#: hermitian_completion decline stays lifted); a drop below 2 trips
#: the rate-direction comparison. pod_wire (unit "us", lower is
#: better, recorded from BENCH_r06.json round 19 on) is the median
#: TCP-vs-loopback rpc_submit round-trip overhead through an
#: in-process localhost HostAgent — growth past threshold means the
#: frame protocol or lane client got slower on the wire.
#: pod_wire_pooled (unit "us", lower is better, recorded from
#: BENCH_r06.json round 20 on) is the same probe over the KEPT-ALIVE
#: pooled lane (net.transport._SocketPool) — growth past threshold
#: means keep-alive reuse regressed toward connect-per-RPC cost.
#: spmd_coalesce (unit "req/round", higher is better, recorded from
#: BENCH_r06.json round 20 on) is the pod SPMD coalescer's
#: requests-per-collective-round on a deterministic 12-request burst —
#: a drop means the coalescing window splinters rounds.
#: fused_dist (unit "directions",
#: higher is better) counts the distributed fused directions active
#: under the K=2 overlap pipeline (chunk-sliceable backward + forward
#: twin; 2 = fusion and overlap compose both ways) — a drop means a
#: gate regressed to declining the composition. pod_routing (unit
#: "x", higher is better) is the round-18 pod frontend's skewed-trace
#: imbalance reduction (rr completed-work skew / p2c skew over the
#: seeded discrete-event replay of the live load_score) — a drop past
#: threshold means the routing policy stopped spreading the skewed
#: load. wire_bytes_int8 (unit "bytes", lower is better, recorded from
#: BENCH_r06.json round 22 on) is the compressed-wire ladder's int8
#: rung on the 256^3 spherical C2C padded block layout, per-stick f32
#: scales INCLUDED — deterministic accounting, so growth past
#: threshold means the quantized packing (or its sidecar) bloated.
#: wire_error_int8 (unit "rel-l2", lower is better) is the measured
#: end-to-end error of a real 2-shard int8-wire backward vs its rung-0
#: twin on a seeded adversarial spectrum — growth past threshold means
#: the quantizer lost accuracy. recorder_overhead (unit "us", lower is
#: better, recorded from BENCH_r06.json round 23 on) is the flight
#: recorder's ARMED per-request hot-path cost — journal + tail
#: retention minus the disarmed path, from the deterministic
#: obs.recorder.overhead_probe micro A/B — growth past threshold
#: means instrumenting the serve pipeline got more expensive (the
#: disarmed path's <= 1% budget is tier-1's job). All emitted by
#: bench.py every run.
SUB_ROWS = ("fused", "cold_start_ms", "warm_start_ms",
            "wire_bytes_r2c", "fused_r2c", "fused_dist", "pod_routing",
            "pod_wire", "pod_wire_pooled", "spmd_coalesce",
            "wire_bytes_int8", "wire_error_int8", "recorder_overhead")


def load_payload(path: str) -> dict:
    """The measurement dict from either bench.py's single JSON line or
    a driver BENCH_r*.json wrapper."""
    with open(path) as f:
        payload = json.load(f)
    if "parsed" in payload and isinstance(payload["parsed"], dict):
        payload = payload["parsed"]
    return payload


def measurement(payload: dict, path: str, row: str = None):
    """(value, unit, metric) of the primary row, or of sub-row ``row``
    (None when the payload does not carry that row)."""
    if row is not None:
        payload = payload.get(row)
        if not isinstance(payload, dict) or "value" not in payload:
            return None
    if "value" not in payload:
        raise ValueError(f"{path}: no 'value' field (not a bench "
                         f"measurement)")
    return (float(payload["value"]), str(payload.get("unit", "")),
            str(payload.get("metric", "")))


def load_measurement(path: str):
    """(value, unit, metric) from either bench.py's single JSON line or
    a driver BENCH_r*.json wrapper."""
    return measurement(load_payload(path), path)


def latest_reference(root: str):
    """The highest-numbered BENCH_r*.json under ``root`` that carries a
    parsed value, or None."""
    best = None
    for path in glob.glob(os.path.join(root, "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if not m:
            continue
        try:
            load_measurement(path)
        except (ValueError, OSError, json.JSONDecodeError):
            continue
        n = int(m.group(1))
        if best is None or n > best[0]:
            best = (n, path)
    return best[1] if best else None


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python scripts/bench_regress.py",
        description="compare a fresh benchmark JSON against the "
                    "recorded baseline; nonzero exit on regression")
    ap.add_argument("--fresh", required=True,
                    help="fresh measurement JSON (bench.py line or "
                         "BENCH_r*.json format)")
    ap.add_argument("--against", default=None,
                    help="reference JSON (default: latest BENCH_r*.json "
                         "in the repo root)")
    ap.add_argument("--threshold", type=float, default=0.15,
                    help="fractional noise allowance (default 0.15)")
    ap.add_argument("--root", default=os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))),
        help="repo root to scan for BENCH_r*.json")
    args = ap.parse_args(argv)
    if not 0.0 <= args.threshold < 1.0:
        print("error: --threshold must be in [0, 1)", file=sys.stderr)
        return 2
    try:
        fresh_payload = load_payload(args.fresh)
        fresh_v, fresh_unit, fresh_metric = measurement(fresh_payload,
                                                        args.fresh)
    except (ValueError, OSError, json.JSONDecodeError) as exc:
        print(f"error: cannot read --fresh: {exc}", file=sys.stderr)
        return 2
    against = args.against or latest_reference(args.root)
    if against is None:
        print(json.dumps({"ok": True, "verdict": "no-reference",
                          "fresh": fresh_v, "unit": fresh_unit}))
        print("no BENCH_r*.json reference found — nothing to regress "
              "against", file=sys.stderr)
        return 0
    try:
        ref_payload = load_payload(against)
        ref_v, ref_unit, ref_metric = measurement(ref_payload, against)
    except (ValueError, OSError, json.JSONDecodeError) as exc:
        print(f"error: cannot read reference {against}: {exc}",
              file=sys.stderr)
        return 2

    def compare_row(row, fresh_m, ref_m):
        fresh_v, fresh_unit, fresh_metric = fresh_m
        ref_v, ref_unit, ref_metric = ref_m
        if fresh_unit and ref_unit and fresh_unit != ref_unit:
            print(f"error: unit mismatch: fresh '{fresh_unit}' vs "
                  f"reference '{ref_unit}' — not comparable",
                  file=sys.stderr)
            return 2
        unit = fresh_unit or ref_unit
        lower_better = unit in LOWER_IS_BETTER_UNITS
        if ref_v == 0:
            ratio = 1.0
        elif lower_better:
            ratio = fresh_v / ref_v      # > 1: slower
        else:
            ratio = ref_v / fresh_v      # > 1: fewer per second
        regressed = ratio > 1.0 + args.threshold
        change = (fresh_v / ref_v - 1.0) * 100 if ref_v else 0.0
        verdict = {
            "ok": not regressed,
            "verdict": "regression" if regressed else "within-threshold",
            "row": row,
            "unit": unit,
            "direction": "lower-is-better" if lower_better
            else "higher-is-better",
            "fresh": fresh_v,
            "reference": ref_v,
            "reference_file": against,
            "change_pct": round(change, 2),
            "threshold_pct": round(args.threshold * 100, 2),
        }
        print(json.dumps(verdict))
        tag = "REGRESSION" if regressed else "OK"
        print(f"{tag} [{row}]: {fresh_v:g} {unit} vs {ref_v:g} {unit} "
              f"({change:+.1f}%, threshold ±{args.threshold * 100:.0f}%, "
              f"{verdict['direction']}) "
              f"[ref: {os.path.basename(against)}]",
              file=sys.stderr)
        if regressed:
            print(f"  fresh metric: {fresh_metric[:160]}",
                  file=sys.stderr)
            print(f"  ref metric:   {ref_metric[:160]}", file=sys.stderr)
        return 1 if regressed else 0

    rc = compare_row("primary", (fresh_v, fresh_unit, fresh_metric),
                     (ref_v, ref_unit, ref_metric))
    if rc == 2:
        return 2
    for row in SUB_ROWS:
        fresh_row = measurement(fresh_payload, args.fresh, row=row)
        ref_row = measurement(ref_payload, against, row=row)
        if fresh_row is None and ref_row is None:
            continue
        if fresh_row is None or ref_row is None:
            side = "fresh" if fresh_row is None else "reference"
            print(json.dumps({"ok": True, "verdict": "row-no-reference",
                              "row": row, "missing": side}))
            print(f"NOTE [{row}]: no {side} measurement — skipped "
                  f"(one-sided rows never fail; they start comparing "
                  f"once both files carry them)",
                  file=sys.stderr)
            continue
        rc = max(rc, compare_row(row, fresh_row, ref_row))
        if rc == 2:
            return 2
    return rc


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Performance sweep on the current device: dims x path x kernel.

Produces the table recorded in BENCHMARKS.md. Uses the sync-cancelling
difference estimator (see bench.py): the tunnel readback costs 80-120 ms
per sync, so each number is min over 3 trials of
(T(g2) - T(g1)) / (g2 - g1) with one hard sync per group.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax

import spfft_tpu as sp
from spfft_tpu.utils import as_interleaved
from spfft_tpu.utils.workloads import (even_plane_split,
                                       round_robin_stick_partition,
                                       spherical_cutoff_triplets)

REPS = int(os.environ.get("SWEEP_REPS", "20"))
DIMS = [int(d) for d in os.environ.get("SWEEP_DIMS", "64,128,256").split(",")]

probe = jax.jit(lambda x: x.reshape(-1)[:8].sum())


def timeit(fn):
    """Shared sync-cancelling estimator (spfft_tpu.utils.benchtime) —
    identical methodology to bench.py so BENCHMARKS.md numbers from
    different scripts are comparable."""
    from spfft_tpu.utils.benchtime import diff_estimate_seconds

    float(np.asarray(probe(fn())))  # warm-up + compile

    def timed(g):
        t0 = time.perf_counter()
        for _ in range(g):
            out = fn()
        float(np.asarray(probe(out)))
        return time.perf_counter() - t0

    sec, _, fallback = diff_estimate_seconds(timed, reps=REPS, trials=3)
    if fallback:
        print("  (diff estimator below noise — pipelined median reported)",
              flush=True)
    return sec


def main():
    rows = []
    for n in DIMS:
        trip = spherical_cutoff_triplets(n)
        rng = np.random.default_rng(0)
        v = (rng.uniform(-1, 1, len(trip))
             + 1j * rng.uniform(-1, 1, len(trip))).astype(np.complex64)
        vil = jax.device_put(np.asarray(as_interleaved(v, "single")))
        for path in ("local", "dist1"):
            for pallas in (True, False):
                if path == "local":
                    plan = sp.make_local_plan(
                        sp.TransformType.C2C, n, n, n, trip,
                        precision="single", use_pallas=bool(pallas))
                    if pallas and not plan._pallas_active:
                        continue
                    fn = (lambda p=plan: p.apply_pointwise(
                        vil, scaling=sp.Scaling.FULL))
                else:
                    parts = round_robin_stick_partition(trip, (n, n, n), 1)
                    plan = sp.make_distributed_plan(
                        sp.TransformType.C2C, n, n, n, parts,
                        even_plane_split(n, 1), mesh=sp.make_mesh(1),
                        precision="single",
                        use_pallas=True if pallas else False)
                    if pallas and (plan._pallas_dist is None
                                   or plan._pallas_interpret):
                        continue  # no compiled kernel on this backend
                    vdev = plan.shard_values([v])
                    fn = (lambda p=plan, w=vdev: p.apply_pointwise(
                        w, scaling=sp.Scaling.FULL))
                ms = timeit(fn) * 1e3
                rows.append({"dim": n, "path": path, "pallas": pallas,
                             "pair_ms": round(ms, 2)})
                print(json.dumps(rows[-1]), flush=True)
    print(json.dumps({"device": str(jax.devices()[0]), "reps": REPS,
                      "rows": rows}))


if __name__ == "__main__":
    main()

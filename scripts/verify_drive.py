#!/usr/bin/env python
"""Canonical verify drive (see .claude/skills/verify/SKILL.md).

Runs on whatever platform jax selects (TPU when the axon tunnel is up;
set PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu to force CPU)."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax

import spfft_tpu as sp
from spfft_tpu.utils import as_complex_np
from spfft_tpu.utils.workloads import spherical_cutoff_triplets

print("platform:", jax.default_backend(), jax.devices())

# 1. dense 2x2x2 C2C round trip (reference example.cpp equivalent)
n = 2
triplets = np.array([[x, y, z] for x in range(n) for y in range(n)
                     for z in range(n)])
plan = sp.make_local_plan(sp.TransformType.C2C, n, n, n, triplets,
                          precision="single")
rng = np.random.default_rng(0)
v = (rng.uniform(-1, 1, len(triplets))
     + 1j * rng.uniform(-1, 1, len(triplets))).astype(np.complex64)
space = plan.backward(v)
freq = as_complex_np(np.asarray(plan.forward(space)))
assert np.allclose(freq, v * n**3, atol=1e-4), "dense round trip failed"
print("1. dense 2^3 round trip: OK")

# 2. R2C vs numpy oracle: random real field, fftn coefficients at the
# non-redundant hermitian triplets; unnormalised backward returns field * N.
n = 8
herm = [(x, y, z) for x in range(n // 2 + 1) for y in range(n)
        for z in range(n)]
herm = np.asarray(herm)
field = rng.uniform(-1, 1, (n, n, n))
cube = np.fft.fftn(field)  # cube[z, y, x] with positive storage indexing
vals = np.array([cube[t[2], t[1], t[0]] for t in herm], np.complex64)
rplan = sp.make_local_plan(sp.TransformType.R2C, n, n, n, herm,
                           precision="single")
got = np.asarray(rplan.backward(vals))
err = np.abs(got - field * n**3).max()
assert err < 1e-2, f"r2c backward mismatch {err}"
print("2. R2C vs numpy oracle: OK")

# 3. error surface
try:
    sp.make_local_plan(sp.TransformType.C2C, 4, 4, 4, np.array([[9, 0, 0]]))
    raise SystemExit("expected InvalidIndicesError")
except sp.InvalidIndicesError:
    pass
try:
    plan.backward(v[:3])
    raise SystemExit("expected InvalidParameterError")
except sp.InvalidParameterError:
    pass
print("3. error surface: OK")

# 4. scale probe: 128^3 spherical cutoff
n = 128
t0 = time.perf_counter()
trip = spherical_cutoff_triplets(n)
plan = sp.make_local_plan(sp.TransformType.C2C, n, n, n, trip,
                          precision="single")
plan_s = time.perf_counter() - t0
vals = (rng.uniform(-1, 1, len(trip))
        + 1j * rng.uniform(-1, 1, len(trip))).astype(np.complex64)
jax.block_until_ready(plan.forward(plan.backward(vals), sp.Scaling.FULL))
t0 = time.perf_counter()
reps = 5
for _ in range(reps):
    out = plan.forward(plan.backward(vals), sp.Scaling.FULL)
jax.block_until_ready(out)
per = (time.perf_counter() - t0) / reps
got = as_complex_np(np.asarray(out))
err = np.abs(got - vals).max()
assert err < 1e-4, f"128^3 roundtrip err {err}"
print(f"4. 128^3 probe: OK — plan {plan_s:.2f}s, pair {per*1e3:.1f} ms/iter, "
      f"pallas={plan._pallas_active}, err={err:.2e}")

# 5. batched (vmapped) execution: drive the fused executable DIRECTLY
# (multi_transform_* may legitimately route shared-plan batches to the
# per-transform path when the Pallas kernel is active, so calling it would
# not cover the vmap lowering on TPU), then the multi_transform wrapper.
from spfft_tpu.grid import Transform
from spfft_tpu import multi_transform_backward, multi_transform_forward

vals_b = [(rng.uniform(-1, 1, len(trip))
           + 1j * rng.uniform(-1, 1, len(trip))).astype(np.complex64)
          for _ in range(3)]
t0 = time.perf_counter()
stacked = plan.backward_batched(vals_b)
jax.block_until_ready(stacked)
per_b = (time.perf_counter() - t0) / 3
ref1 = np.asarray(plan.backward(vals_b[1]))
err = np.abs(np.asarray(stacked[1]) - ref1).max()
assert err < 1e-4, f"batched backward mismatch {err}"
fw = plan.forward_batched(list(np.asarray(stacked)), sp.Scaling.FULL)
gotf = as_complex_np(np.asarray(fw[2]))
err = np.abs(gotf - vals_b[2]).max()
assert err < 1e-4, f"batched roundtrip mismatch {err}"
base = Transform(plan)
clones = [base.clone() for _ in range(3)]
outs = multi_transform_backward(clones, vals_b)
err = np.abs(np.asarray(outs[1]) - ref1).max()
assert err < 1e-4, f"multi_transform backward mismatch {err}"
fouts = multi_transform_forward(clones, [np.asarray(o) for o in outs],
                                [sp.Scaling.FULL] * 3)
err = np.abs(as_complex_np(np.asarray(fouts[2])) - vals_b[2]).max()
assert err < 1e-4, f"multi_transform roundtrip mismatch {err}"
print(f"5. batched vmapped executable (B=3, incl. compile "
      f"{per_b*1e3:.1f} ms/transform) + multi_transform wrapper: OK")

# 6. distributed shard_map path on the real chip: a 1-device mesh compiles
# and runs the same SPMD program (collectives included) as a pod slice.
from spfft_tpu.utils.workloads import (even_plane_split,
                                       round_robin_stick_partition)
n6 = 32
trip6 = spherical_cutoff_triplets(n6)
parts6 = round_robin_stick_partition(trip6, (n6, n6, n6), 1)
planes6 = even_plane_split(n6, 1)
dplan = sp.make_distributed_plan(sp.TransformType.C2C, n6, n6, n6, parts6,
                                 planes6, mesh=sp.make_mesh(1),
                                 precision="single")
vals6 = [(rng.uniform(-1, 1, len(p))
          + 1j * rng.uniform(-1, 1, len(p))).astype(np.complex64)
         for p in parts6]
out6 = dplan.apply_pointwise(vals6, scaling=sp.Scaling.FULL)
err = max(np.abs(g - v).max()
          for g, v in zip(dplan.unshard_values(out6), vals6))
assert err < 1e-3, f"distributed-on-TPU roundtrip err {err}"
print(f"6. distributed shard_map path on TPU (1-device mesh): OK "
      f"err={err:.2e}")

# 7. split xy path + Pallas kernel together on the compiled path: a
# narrow-x cutoff set above the Pallas auto threshold must match the
# dense-path result.
from spfft_tpu.benchmark import cutoff_stick_triplets
trip7 = cutoff_stick_triplets(128, 128, 128, 0.25, False)
plan7 = sp.make_local_plan(sp.TransformType.C2C, 128, 128, 128, trip7,
                           precision="single")
assert plan7._split_x is not None and plan7._pallas_active, \
    (plan7._split_x, plan7._pallas_active)
plan7d = sp.make_local_plan(sp.TransformType.C2C, 128, 128, 128, trip7,
                            precision="single")
plan7d._split_x = None
plan7d._pair_jits = {}
v7 = (rng.uniform(-1, 1, len(trip7))
      + 1j * rng.uniform(-1, 1, len(trip7))).astype(np.complex64)
a7 = np.asarray(plan7.apply_pointwise(v7, scaling=sp.Scaling.FULL))
b7 = np.asarray(plan7d.apply_pointwise(v7, scaling=sp.Scaling.FULL))
err = np.abs(a7 - b7).max()
assert err < 1e-4, f"split-vs-dense mismatch {err}"
print(f"7. split xy + Pallas on TPU (x width {plan7._split_x[1]}/128): OK "
      f"max diff vs dense {err:.2e}")
print("VERIFY DRIVE: ALL OK")

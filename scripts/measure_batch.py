#!/usr/bin/env python
"""On-chip measurement: fused shared-plan batch vs sequential dispatches,
local and distributed (1-shard mesh on the single available chip).

Criterion (VERDICT round-1 item 4): B=3 shared-plan distributed batch must
not exceed sequential dispatch wall-clock."""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def sync(arr):
    import jax
    leaf = jax.tree_util.tree_leaves(arr)[0]
    first = leaf[(0,) * (leaf.ndim - 1)][:1]  # no device-side ravel
    float(np.asarray(jax.numpy.real(first)).ravel()[0])


def bench(fn, reps=10):
    """Shared sync-cancelling estimator (spfft_tpu.utils.benchtime) —
    identical methodology to bench.py."""
    from spfft_tpu.utils.benchtime import diff_estimate_seconds

    out = fn()
    sync(out)

    def timed(g):
        t0 = time.perf_counter()
        for _ in range(g):
            out = fn()
        sync(out)
        return time.perf_counter() - t0

    sec, _, fallback = diff_estimate_seconds(timed, reps=reps, trials=3)
    if fallback:
        print("  (diff estimator below noise — pipelined median reported)",
              flush=True)
    return sec * 1e3


def main() -> None:
    import jax
    from spfft_tpu import TransformType, make_local_plan
    from spfft_tpu.parallel import make_distributed_plan, make_mesh
    from spfft_tpu.utils import as_interleaved
    from spfft_tpu.utils.workloads import spherical_cutoff_triplets

    n = int(os.environ.get("DIM", "128"))
    B = int(os.environ.get("B", "3"))
    print(f"devices: {jax.devices()}  dim={n} B={B}", flush=True)
    rng = np.random.default_rng(0)
    triplets = spherical_cutoff_triplets(n)
    vals = [(rng.uniform(-1, 1, len(triplets))
             + 1j * rng.uniform(-1, 1, len(triplets))).astype(np.complex64)
            for _ in range(B)]

    # local
    plan = make_local_plan(TransformType.C2C, n, n, n, triplets,
                           precision="single")
    ils = [jax.device_put(np.asarray(as_interleaved(v, "single")))
           for v in vals]
    stacked = jax.device_put(np.stack([np.asarray(i) for i in ils]))
    t_seq = bench(lambda: [plan.backward(v) for v in ils])
    t_bat = bench(lambda: plan.backward_batched(stacked))
    print(f"local   backward: sequential {t_seq:8.2f} ms   "
          f"fused batch {t_bat:8.2f} ms   "
          f"({t_seq / t_bat:.2f}x, pallas={plan.pallas_active})", flush=True)

    # distributed (1-shard mesh: the only real-chip mesh available)
    dplan = make_distributed_plan(TransformType.C2C, n, n, n, [triplets],
                                  [n], mesh=make_mesh(1),
                                  precision="single")
    dvals = [dplan.shard_values([v]) for v in vals]
    dstacked = dplan.shard_values_batch(dvals)
    t_seq = bench(lambda: [dplan.backward(v) for v in dvals])
    t_bat = bench(lambda: dplan.backward_batched(dstacked))
    print(f"dist(1) backward: sequential {t_seq:8.2f} ms   "
          f"fused batch {t_bat:8.2f} ms   ({t_seq / t_bat:.2f}x)",
          flush=True)


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Round-5 probe: decompose the backward unpack (sticks_to_grid) and
forward compress costs at 256^3 shapes.

stagecost measured unpack+xy at ~5.1 ms marginal while the fused xy
kernel alone is 1.62 — sticks_to_grid_padded is `sticks[col_inv].T`,
i.e. a row gather PLUS a full grid transpose per channel. This probe
times the pieces standalone: gather only, transpose only, gather+T,
and the compress sub-pieces (planar pad/reshape, gather kernel,
interleave).

Usage: python scripts/probe_r5_unpack.py
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

from spfft_tpu import TransformType, make_local_plan
from spfft_tpu.utils.benchtime import diff_estimate_seconds
from spfft_tpu.utils.workloads import spherical_cutoff_triplets

DIM = int(os.environ.get("DIM", 256))


def sync(out):
    leaf = jax.tree_util.tree_leaves(out)[0]
    return float(np.asarray(jnp.real(leaf).ravel()[0]))


def measure(f, *args, reps=16):
    g = jax.jit(f)
    sync(g(*args))

    def grp(k):
        t0 = time.perf_counter()
        o = None
        for _ in range(k):
            o = g(*args)
        sync(o)
        return time.perf_counter() - t0
    return diff_estimate_seconds(grp, reps=reps).seconds


def main():
    tri = spherical_cutoff_triplets(DIM)
    plan = make_local_plan(TransformType.C2C, DIM, DIM, DIM, tri)
    p = plan.index_plan
    tabs = plan._tables_hot
    col = tabs["col_inv_t"]
    rng = np.random.default_rng(3)
    s_pad = plan._s_pad
    sr = jax.device_put(jnp.asarray(
        rng.standard_normal((s_pad, p.dim_z)), jnp.float32))
    si = jax.device_put(jnp.asarray(
        rng.standard_normal((s_pad, p.dim_z)), jnp.float32))
    xf = p.dim_x_freq

    t = measure(lambda a, b: (a[col], b[col]), sr, si)
    print(f"row gather only (both ch)   : {t*1e3:7.3f} ms", flush=True)

    ga = jax.device_put(jnp.asarray(
        rng.standard_normal((xf * p.dim_y, p.dim_z)), jnp.float32))
    gb = jax.device_put(jnp.asarray(
        rng.standard_normal((xf * p.dim_y, p.dim_z)), jnp.float32))
    t = measure(lambda a, b: (a.T.reshape(p.dim_z, xf, p.dim_y),
                              b.T.reshape(p.dim_z, xf, p.dim_y)), ga, gb)
    print(f"grid transpose only (both)  : {t*1e3:7.3f} ms", flush=True)

    t = measure(lambda a, b: (a[col].T.reshape(p.dim_z, xf, p.dim_y),
                              b[col].T.reshape(p.dim_z, xf, p.dim_y)),
                sr, si)
    print(f"gather + T (current unpack) : {t*1e3:7.3f} ms", flush=True)

    # forward pack mirror: minor-axis gather + T
    fr = jax.device_put(jnp.asarray(
        rng.standard_normal((p.dim_z, xf * p.dim_y)), jnp.float32))
    cols = tabs["scatter_cols_t"]
    t = measure(lambda a: a[:, cols].T, fr)
    print(f"pack: minor gather + T (1ch): {t*1e3:7.3f} ms", flush=True)

    # compress pieces
    vil = jax.device_put(plan._coerce_values(
        (rng.standard_normal(p.num_values)
         + 1j * rng.standard_normal(p.num_values)).astype(np.complex64)))
    t = measure(lambda v: plan._decompress_planar(v, tabs), vil)
    print(f"decompress (planar)         : {t*1e3:7.3f} ms", flush=True)
    t = measure(lambda a, b: plan._compress_planar(a, b, tabs), sr, si)
    print(f"compress (full)             : {t*1e3:7.3f} ms", flush=True)
    from spfft_tpu.ops import gather_kernel as gk
    tt = plan._pallas["cmp"]
    pad = tt.src_rows * 128 - sr.size
    re = jnp.pad(sr.reshape(-1), (0, pad)).reshape(tt.src_rows, 128)
    im = jnp.pad(si.reshape(-1), (0, pad)).reshape(tt.src_rows, 128)
    re, im = jax.device_put(re), jax.device_put(im)
    t = measure(lambda a, b: gk.run_gather(a, b, tabs["cmp_tabs"], tt),
                re, im)
    print(f"compress bare kernel        : {t*1e3:7.3f} ms", flush=True)


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Round-2 verify drive: the canonical checks from .claude/skills/verify on
the real TPU, plus the new batched entry points."""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    import jax
    from spfft_tpu import (InvalidIndicesError, InvalidParameterError,
                           Scaling, TransformType, make_local_plan)
    from spfft_tpu.utils import as_complex_np, as_interleaved
    from spfft_tpu.utils.workloads import spherical_cutoff_triplets

    print(f"devices: {jax.devices()}", flush=True)

    # 1. dense 2x2x2 C2C round trip (reference example.cpp equivalent)
    n = 2
    triplets = np.array([(x, y, z) for x in range(n) for y in range(n)
                         for z in range(n)], np.int32)
    plan = make_local_plan(TransformType.C2C, n, n, n, triplets,
                           precision="single")
    rng = np.random.default_rng(0)
    v = (rng.uniform(-1, 1, 8) + 1j * rng.uniform(-1, 1, 8)).astype(
        np.complex64)
    space = plan.backward(v)
    out = as_complex_np(np.asarray(plan.forward(space, Scaling.FULL)))
    assert np.allclose(out, v, atol=1e-4), "2x2x2 round trip failed"
    print("1. dense 2x2x2 C2C round trip OK", flush=True)

    # 2. R2C vs numpy oracle
    dims = (8, 6, 10)
    space_ref = rng.uniform(-1, 1, (dims[2], dims[1], dims[0])).astype(
        np.float64)
    freq = np.fft.fftn(space_ref)
    trips = np.asarray([(x, y, z) for x in range(dims[0] // 2 + 1)
                        for y in range(dims[1]) for z in range(dims[2])],
                       np.int32)
    rplan = make_local_plan(TransformType.R2C, *dims, trips,
                            precision="single")
    st = trips
    vals = freq[st[:, 2], st[:, 1], st[:, 0]].astype(np.complex64)
    got = np.asarray(rplan.backward(vals))
    ref = space_ref * space_ref.size
    err = np.abs(got - ref).max() / np.abs(ref).max()
    assert err < 1e-4, f"R2C backward error {err}"
    print(f"2. R2C oracle OK (rel err {err:.2e})", flush=True)

    # 3. error surface
    try:
        make_local_plan(TransformType.C2C, 4, 4, 4, np.array([[9, 0, 0]]))
        raise AssertionError("expected InvalidIndicesError")
    except InvalidIndicesError:
        pass
    try:
        plan.backward(np.zeros(3, np.complex64))
        raise AssertionError("expected InvalidParameterError")
    except InvalidParameterError:
        pass
    print("3. error surface OK", flush=True)

    # 4. scale probe: 128^3 sphere, timed pairs + batched path
    n = 128
    trips = spherical_cutoff_triplets(n)
    t0 = time.perf_counter()
    plan = make_local_plan(TransformType.C2C, n, n, n, trips,
                           precision="single")
    t_plan = time.perf_counter() - t0
    v = (rng.uniform(-1, 1, len(trips))
         + 1j * rng.uniform(-1, 1, len(trips))).astype(np.complex64)
    v_il = jax.device_put(np.asarray(as_interleaved(v, "single")))
    out = plan.apply_pointwise(v_il, scaling=Scaling.FULL)
    float(np.asarray(out.ravel()[0]))
    t0 = time.perf_counter()
    for _ in range(5):
        out = plan.apply_pointwise(v_il, scaling=Scaling.FULL)
    float(np.asarray(out.ravel()[0]))
    pair_ms = (time.perf_counter() - t0) / 5 * 1e3
    got = np.asarray(out)
    err = np.abs(got[:, 0] + 1j * got[:, 1] - v).max()
    assert err < 1e-3, f"128^3 round trip err {err}"
    print(f"4. 128^3 sphere: plan {t_plan:.2f}s, pair {pair_ms:.2f} ms, "
          f"pallas_active={plan.pallas_active}, err {err:.2e}", flush=True)

    # 5. batched path on chip (new this round): B=3 fused == singles
    batch = [np.roll(v, i) for i in range(3)]
    t0 = time.perf_counter()
    stacked = plan.backward_batched([as_interleaved(b, "single")
                                     for b in batch])
    float(np.asarray(stacked.ravel()[0]))
    t_b3 = time.perf_counter() - t0
    single = np.asarray(plan.backward(batch[1]))
    err = np.abs(np.asarray(stacked[1]) - single).max()
    assert err < 1e-3, f"batched vs single err {err}"
    t0 = time.perf_counter()
    stacked = plan.backward_batched(stacked_in := jax.device_put(
        np.stack([np.asarray(as_interleaved(b, "single")) for b in batch])))
    float(np.asarray(stacked.ravel()[0]))
    t_warm = time.perf_counter() - t0
    print(f"5. batched B=3 on chip OK (compile+run {t_b3:.2f}s, "
          f"warm {t_warm * 1e3:.1f} ms, err {err:.2e})", flush=True)

    print("VERIFY OK", flush=True)


if __name__ == "__main__":
    main()

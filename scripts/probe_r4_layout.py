#!/usr/bin/env python
"""Round-4 probe: where does the 256^3 fused pair spend its 12 ms?

Three questions, all on the real device with scanned-iteration timing
(see scripts/profile_stages.py for why single dispatches can't resolve
per-stage times through the axon tunnel):

1. Reproduce the round-3 fused pair (interleaved (N, 2) boundary).
2. Time the same pair with a PLANAR (rows, 128) value boundary — the
   interleaved<->planar conversion passes around the gather kernels
   removed (VERDICT round-3 item 1).
3. Bisect the pipeline with incremental prefix compositions to locate
   the gap between the stage sum (~7.9 ms) and the fused pair (12 ms).

Usage: DIM=256 python scripts/probe_r4_layout.py
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

from spfft_tpu import TransformType, make_local_plan
from spfft_tpu.ops import stages
from spfft_tpu.ops import gather_kernel as gk
from spfft_tpu.utils.workloads import spherical_cutoff_triplets

R = int(os.environ.get("REPS", 20))


def sync(out):
    leaf = jax.tree_util.tree_leaves(out)[0]
    float(np.asarray(jax.numpy.real(leaf).ravel()[0]))


def _perturb(x):
    return jax.tree_util.tree_map(lambda v: v * v.dtype.type(1.0 + 1e-7), x)


def _consume(y):
    leaves = jax.tree_util.tree_leaves(y)
    tot = 0.0
    for leaf in leaves:
        if jnp.iscomplexobj(leaf):
            tot = tot + jnp.mean(jnp.real(leaf)) + jnp.mean(jnp.imag(leaf))
        else:
            tot = tot + jnp.mean(leaf)
    return tot


def _scan_seconds(body, x, reps=3):
    def run(x0):
        def step(c, _):
            xp = _perturb(c)
            return xp, _consume(body(xp))
        _, ys = jax.lax.scan(step, x0, None, length=R)
        return ys
    f = jax.jit(run)
    out = f(x)
    sync(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = f(x)
    sync(out)
    return (time.perf_counter() - t0) / reps


def timeit(name, body, x, calib_s):
    total = _scan_seconds(body, x)
    dt = (total - calib_s) / R
    print(f"{name:44s} {dt*1e3:8.3f} ms", flush=True)
    return dt


def main(n: int):
    triplets = spherical_cutoff_triplets(n)
    plan = make_local_plan(TransformType.C2C, n, n, n, triplets,
                           precision="single")
    p = plan.index_plan
    N, S, Z = p.num_values, p.num_sticks, p.dim_z
    assert plan._pallas_active
    dec_t = plan._pallas["dec"]
    cmp_t = plan._pallas["cmp"]
    tables = plan._tables
    print(f"== dim={n} values={N} sticks={S} dec_segs={len(dec_t.segs)} "
          f"cmp_segs={len(cmp_t.segs)} dec_rows={dec_t.src_rows} "
          f"cmp_rows={cmp_t.src_rows} R={R} ==", flush=True)

    rng = np.random.default_rng(0)
    values = (rng.uniform(-1, 1, N)
              + 1j * rng.uniform(-1, 1, N)).astype(np.complex64)
    values_il = jax.device_put(plan._coerce_values(values))

    Rv = dec_t.src_rows  # planar value rows (dec source)
    re0 = jnp.asarray(np.pad(values.real.astype(np.float32),
                             (0, Rv * 128 - N)).reshape(Rv, 128))
    im0 = jnp.asarray(np.pad(values.imag.astype(np.float32),
                             (0, Rv * 128 - N)).reshape(Rv, 128))

    cal_il = _scan_seconds(lambda v: v, values_il)
    cal_pl = _scan_seconds(lambda v: v, (re0, im0))
    print(f"calib interleaved {cal_il/R*1e3:.3f} ms/step, "
          f"planar {cal_pl/R*1e3:.3f} ms/step", flush=True)

    # 1. round-3 pair, interleaved boundary
    timeit("pair interleaved (round-3)",
           lambda v: plan._forward_impl(plan._backward_impl(v, tables),
                                        tables, scaled=False),
           values_il, cal_il)

    # 2. planar-boundary pair
    def dec_planar(re, im):
        out_re, out_im = gk.run_gather(re, im, tables["dec_tabs"], dec_t)
        flat = (out_re.reshape(-1)[:dec_t.num_out]
                + 1j * out_im.reshape(-1)[:dec_t.num_out])
        return flat.reshape(S, Z)

    def cmp_planar(sticks):
        re, im = gk.planar_from_complex(sticks, cmp_t.src_rows)
        out_re, out_im = gk.run_gather(re, im, tables["cmp_tabs"], cmp_t)
        rows = out_re.shape[0] * 8
        re_f = out_re.reshape(rows, 128)
        im_f = out_im.reshape(rows, 128)
        if rows < Rv:
            re_f = jnp.pad(re_f, ((0, Rv - rows), (0, 0)))
            im_f = jnp.pad(im_f, ((0, Rv - rows), (0, 0)))
        else:
            re_f, im_f = re_f[:Rv], im_f[:Rv]
        return re_f, im_f

    def pair_planar(c):
        re, im = c
        sticks = dec_planar(re, im)
        space = plan._backward_rest(sticks, tables)
        sticks2 = plan._forward_head(space, tables)
        return cmp_planar(sticks2)

    timeit("pair planar boundary", pair_planar, (re0, im0), cal_pl)

    # 3. conversion passes in isolation
    timeit("conv: interleaved->planar (dec input)",
           lambda v: gk.planar_from_interleaved(v, dec_t.src_rows),
           values_il, cal_il)

    def conv_out(c):
        re, im = c
        return gk.interleaved_from_planar(re, im, N)
    timeit("conv: planar->interleaved (cmp output)", conv_out,
           (re0, im0), cal_pl)

    # 4. incremental prefix compositions, planar boundary
    def pfx1(c):
        return gk.run_gather(c[0], c[1], tables["dec_tabs"], dec_t)

    def pfx2(c):
        return dec_planar(*c)

    def pfx3(c):
        return stages.z_backward(dec_planar(*c))

    def pfx4(c):
        s = stages.z_backward(dec_planar(*c))
        return stages.sticks_to_grid(s, tables["col_inv"], p.dim_y,
                                     p.dim_x_freq)

    def pfx5(c):
        s = stages.z_backward(dec_planar(*c))
        g = stages.sticks_to_grid(s, tables["col_inv"], p.dim_y,
                                  p.dim_x_freq)
        return stages.xy_backward_c2c(g)

    def pfx6(c):
        s = stages.z_backward(dec_planar(*c))
        g = stages.sticks_to_grid(s, tables["col_inv"], p.dim_y,
                                  p.dim_x_freq)
        return stages.xy_forward_c2c(stages.xy_backward_c2c(g))

    def pfx7(c):
        s = stages.z_backward(dec_planar(*c))
        g = stages.sticks_to_grid(s, tables["col_inv"], p.dim_y,
                                  p.dim_x_freq)
        g = stages.xy_forward_c2c(stages.xy_backward_c2c(g))
        return stages.grid_to_sticks(g, tables["scatter_cols"])

    def pfx8(c):
        s = stages.z_backward(dec_planar(*c))
        g = stages.sticks_to_grid(s, tables["col_inv"], p.dim_y,
                                  p.dim_x_freq)
        g = stages.xy_forward_c2c(stages.xy_backward_c2c(g))
        return stages.z_forward(stages.grid_to_sticks(
            g, tables["scatter_cols"]))

    prev = 0.0
    for name, fn in [("dec kernel only", pfx1),
                     ("+ complex sticks", pfx2),
                     ("+ z ifft", pfx3),
                     ("+ unpack", pfx4),
                     ("+ xy ifft2", pfx5),
                     ("+ xy fft2", pfx6),
                     ("+ pack", pfx7),
                     ("+ z fft", pfx8),
                     ("+ compress (full planar pair)", pair_planar)]:
        dt = timeit(f"prefix {name}", fn, (re0, im0), cal_pl)
        print(f"{'':46s} delta {max(dt-prev, 0)*1e3:8.3f} ms", flush=True)
        prev = dt


if __name__ == "__main__":
    print(f"devices: {jax.devices()}", flush=True)
    main(int(os.environ.get("DIM", "256")))

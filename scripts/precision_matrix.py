#!/usr/bin/env python
"""Measured accuracy matrix: relative l2 of the on-device single-precision
backward transform vs a dense float64 oracle (pocketfft), across grid
sizes, C2C/R2C, and centered/positive indexing.

The reference's accuracy contract is 1e-6 absolute against dense FFTW with
unit-magnitude values (reference: tests/test_util/test_check_values.hpp:
46-50); its default precision is f64 end-to-end. TPU f64 is emulated, so
this framework's on-device path is f32 — this matrix documents where that
meets the 1e-6 bar (docs/precision.md records the results; the CPU backend
with precision="double" reproduces the reference's f64 contract exactly).

Usage: DIMS="64 128 256" python scripts/precision_matrix.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def rel_l2(got, want):
    return float(np.linalg.norm((got - want).ravel())
                 / np.linalg.norm(want.ravel()))


def measure(n: int, transform: str, centered: bool) -> float:
    from scipy import fft as sfft
    from spfft_tpu import TransformType, make_local_plan
    from spfft_tpu.utils.workloads import spherical_cutoff_triplets

    tt = TransformType.C2C if transform == "c2c" else TransformType.R2C
    trip = spherical_cutoff_triplets(n)
    if tt is TransformType.R2C:
        x, y, z = trip[:, 0], trip[:, 1], trip[:, 2]
        half = (x > 0) | ((x == 0) & ((y > 0) | ((y == 0) & (z >= 0))))
        trip = trip[half]
    if not centered:
        trip = trip % n
    rng = np.random.default_rng(7)
    vals = (rng.uniform(-1, 1, len(trip))
            + 1j * rng.uniform(-1, 1, len(trip)))
    cube = np.zeros((n, n, n), np.complex128)
    st = np.where(trip < 0, trip + n, trip)
    cube[st[:, 2], st[:, 1], st[:, 0]] = vals
    if tt is TransformType.R2C:
        # mirror the hermitian half so the oracle backward is real
        mz, my, mx = [(-st[:, i]) % n for i in (2, 1, 0)]
        cube[mz, my, mx] = np.conj(vals)
        zero_self = (st[:, 2] == mz) & (st[:, 1] == my) & (st[:, 0] == mx)
        cube[st[zero_self, 2], st[zero_self, 1], st[zero_self, 0]] = \
            vals[zero_self].real
        vals = cube[st[:, 2], st[:, 1], st[:, 0]]
    oracle = sfft.ifftn(cube, workers=-1) * cube.size
    plan = make_local_plan(tt, n, n, n, trip, precision="single")
    got = np.asarray(plan.backward(vals.astype(np.complex64)))
    if tt is TransformType.C2C:
        got = got[..., 0] + 1j * got[..., 1]
        return rel_l2(got, oracle)
    return rel_l2(got, oracle.real)


def main():
    dims = [int(d) for d in os.environ.get("DIMS", "64 128 256").split()]
    print(f"{'dim':>5} {'transform':>9} {'indexing':>9} {'rel_l2':>10} "
          f"{'<=1e-6':>7}", flush=True)
    worst = 0.0
    for n in dims:
        # centered vs positive indexing measured bit-identical at 64-128
        # (same arithmetic, different storage labels) — large dims run
        # centered only to keep the f64 oracle cost bounded
        indexings = (False, True) if n <= 128 else (True,)
        transforms = os.environ.get("TRANSFORMS", "c2c r2c").split()
        for transform in transforms:
            for centered in indexings:
                err = measure(n, transform, centered)
                worst = max(worst, err)
                print(f"{n:>5} {transform:>9} "
                      f"{'centered' if centered else 'positive':>9} "
                      f"{err:>10.2e} {'yes' if err <= 1e-6 else 'NO':>7}",
                      flush=True)
    print(f"worst: {worst:.2e}")


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Measured accuracy matrix: relative l2 of the on-device single-precision
backward transform vs a dense float64 oracle (pocketfft), across grid
sizes, C2C/R2C, and centered/positive indexing.

The reference's accuracy contract is 1e-6 absolute against dense FFTW with
unit-magnitude values (reference: tests/test_util/test_check_values.hpp:
46-50); its default precision is f64 end-to-end. TPU f64 is emulated, so
this framework's on-device path is f32 — this matrix documents where that
meets the 1e-6 bar (docs/precision.md records the results; the CPU backend
with precision="double" reproduces the reference's f64 contract exactly).

Usage: DIMS="64 128 256" python scripts/precision_matrix.py
       PRECISION=double DIMS="64 128" ...   # on-device double rows
       ADVERSARIAL=1 ...                    # hostile cases
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def rel_l2(got, want):
    return float(np.linalg.norm((got - want).ravel())
                 / np.linalg.norm(want.ravel()))


def measure(n: int, transform: str, centered: bool) -> float:
    from scipy import fft as sfft
    from spfft_tpu import TransformType, make_local_plan
    from spfft_tpu.utils.workloads import spherical_cutoff_triplets

    tt = TransformType.C2C if transform == "c2c" else TransformType.R2C
    trip = spherical_cutoff_triplets(n)
    if tt is TransformType.R2C:
        x, y, z = trip[:, 0], trip[:, 1], trip[:, 2]
        half = (x > 0) | ((x == 0) & ((y > 0) | ((y == 0) & (z >= 0))))
        trip = trip[half]
    if not centered:
        trip = trip % n
    rng = np.random.default_rng(7)
    vals = (rng.uniform(-1, 1, len(trip))
            + 1j * rng.uniform(-1, 1, len(trip)))
    cube = np.zeros((n, n, n), np.complex128)
    st = np.where(trip < 0, trip + n, trip)
    cube[st[:, 2], st[:, 1], st[:, 0]] = vals
    if tt is TransformType.R2C:
        # mirror the hermitian half so the oracle backward is real
        mz, my, mx = [(-st[:, i]) % n for i in (2, 1, 0)]
        cube[mz, my, mx] = np.conj(vals)
        zero_self = (st[:, 2] == mz) & (st[:, 1] == my) & (st[:, 0] == mx)
        cube[st[zero_self, 2], st[zero_self, 1], st[zero_self, 0]] = \
            vals[zero_self].real
        vals = cube[st[:, 2], st[:, 1], st[:, 0]]
    oracle = sfft.ifftn(cube, workers=-1) * cube.size
    precision = os.environ.get("PRECISION", "single")
    plan = make_local_plan(tt, n, n, n, trip, precision=precision)
    v_in = vals if precision == "double" else vals.astype(np.complex64)
    got = np.asarray(plan.backward(v_in))
    if tt is TransformType.C2C:
        got = got[..., 0] + 1j * got[..., 1]
        return rel_l2(got, oracle)
    return rel_l2(got, oracle.real)  # R2C returns the real slab


def measure_adversarial(case: str) -> tuple:
    """Adversarial rows (VERDICT r3 item 2): high dynamic range, awkward
    prime-factor dims, R2C hermitian edge sticks. Returns
    (label, rel_l2)."""
    from scipy import fft as sfft
    from spfft_tpu import TransformType, make_local_plan
    from spfft_tpu.utils.workloads import spherical_cutoff_triplets

    rng = np.random.default_rng(13)
    if case == "dynamic_range":
        # unit-phase values with magnitudes spanning 1e-6..1e+6
        n = 128
        trip = spherical_cutoff_triplets(n)
        mag = 10.0 ** rng.uniform(-6, 6, len(trip))
        ph = rng.uniform(0, 2 * np.pi, len(trip))
        vals = (mag * np.exp(1j * ph))
        dims = (n, n, n)
        tt = TransformType.C2C
        label = f"{n}^3 c2c, |v| in 1e±6"
    elif case == "prime_dims":
        # dims with factors 7 * 11 * 13 (the reference's 'optimal sizing'
        # guidance excludes these; matmul-DFT handles any length)
        dims = (77, 91, 143)
        xs, ys, zs = dims
        trip = np.array([(x, y, z) for x in range(xs) for y in range(ys)
                         for z in range(zs)
                         if (x * 3 + y * 5 + z * 7) % 4 == 0], np.int64)
        vals = (rng.uniform(-1, 1, len(trip))
                + 1j * rng.uniform(-1, 1, len(trip)))
        tt = TransformType.C2C
        label = "77x91x143 c2c (7·11·13 factors)"
    elif case == "r2c_edges":
        # ONLY the hermitian-special planes. x=0: one of each ±y stick
        # pair plus the half-z (0,0) stick — everything flows through the
        # stick/plane completion paths. x=nx/2 (self-conjugate for even
        # n): supplied FULLY — the completion contract covers x=0 only
        # (reference symmetry_kernels.cu applies plane symmetry at x=0;
        # details.rst requires other sticks complete), so a half-supplied
        # edge plane is out of contract for the reference too.
        n = 64
        dims = (n, n, n)
        trip = [(0, y, z) for y in range(1, n // 2 + 1) for z in range(n)]
        trip += [(0, 0, z) for z in range(n // 2 + 1)]
        trip += [(n // 2, y, z) for y in range(n) for z in range(n)]
        trip = np.array(sorted(set(trip)), np.int64)
        field = rng.standard_normal((n, n, n))
        spec = np.fft.fftn(field)
        vals = spec[trip[:, 2], trip[:, 1], trip[:, 0]]
        tt = TransformType.R2C
        label = f"{n}^3 r2c edge sticks (x=0, x=n/2 only)"
    else:
        raise ValueError(case)
    nx, ny, nz = dims
    cube = np.zeros((nz, ny, nx), np.complex128)
    st = np.where(trip < 0, trip + np.array([nx, ny, nz]), trip)
    cube[st[:, 2], st[:, 1], st[:, 0]] = vals
    if tt is TransformType.R2C:
        mz, my, mx = [(-st[:, i]) % d for i, d in ((2, nz), (1, ny),
                                                   (0, nx))]
        cube[mz, my, mx] = np.conj(vals)
        self_conj = (st[:, 2] == mz) & (st[:, 1] == my) & (st[:, 0] == mx)
        cube[st[self_conj, 2], st[self_conj, 1], st[self_conj, 0]] = \
            vals[self_conj].real
        vals = cube[st[:, 2], st[:, 1], st[:, 0]]
    oracle = sfft.ifftn(cube, workers=-1) * cube.size
    plan = make_local_plan(tt, nx, ny, nz, trip, precision="single")
    got = np.asarray(plan.backward(vals.astype(np.complex64)))
    if tt is TransformType.C2C:
        got = got[..., 0] + 1j * got[..., 1]
        return label, rel_l2(got, oracle)
    return label, rel_l2(got, oracle.real)


def main():
    if os.environ.get("ADVERSARIAL") == "1":
        print(f"{'case':>38} {'rel_l2':>10} {'<=1e-6':>7}", flush=True)
        worst = 0.0
        for case in ("dynamic_range", "prime_dims", "r2c_edges"):
            label, err = measure_adversarial(case)
            worst = max(worst, err)
            print(f"{label:>38} {err:>10.2e} "
                  f"{'yes' if err <= 1e-6 else 'NO':>7}", flush=True)
        print(f"worst adversarial: {worst:.2e}")
        return
    dims = [int(d) for d in os.environ.get("DIMS", "64 128 256").split()]
    bar = 1e-6 if os.environ.get("PRECISION", "single") == "single" \
        else 2e-11  # the device-double contract envelope
    print(f"{'dim':>5} {'transform':>9} {'indexing':>9} {'rel_l2':>10} "
          f"{'<=bar':>7}   (bar {bar:.0e})", flush=True)
    worst = 0.0
    for n in dims:
        # centered vs positive indexing measured bit-identical at 64-128
        # (same arithmetic, different storage labels) — large dims run
        # centered only to keep the f64 oracle cost bounded
        indexings = (False, True) if n <= 128 else (True,)
        transforms = os.environ.get("TRANSFORMS", "c2c r2c").split()
        for transform in transforms:
            for centered in indexings:
                err = measure(n, transform, centered)
                worst = max(worst, err)
                print(f"{n:>5} {transform:>9} "
                      f"{'centered' if centered else 'positive':>9} "
                      f"{err:>10.2e} {'yes' if err <= bar else 'NO':>7}",
                      flush=True)
    print(f"worst: {worst:.2e}")


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Sweep wide-kernel geometry (P, kp, K) per direction at north-star scale.

Reports min-of-N scanned measurements (tunnel variance makes single runs
unreliable — VERDICT r2). DIM=256, N=3 by default.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

from spfft_tpu.ops import gather_kernel as gk
from spfft_tpu.indexing import build_index_plan
from spfft_tpu.types import TransformType
from spfft_tpu.utils.workloads import spherical_cutoff_triplets

R = int(os.environ.get("REPS", 20))
N = int(os.environ.get("N", 3))


def sync(x):
    float(np.asarray(jnp.real(jax.tree_util.tree_leaves(x)[0]).ravel()[0]))


def scan_seconds_min(body, x):
    def run(x0):
        def step(c, _):
            xp = jax.tree_util.tree_map(
                lambda a: a * a.dtype.type(1.0 + 1e-7), c)
            out = body(xp)
            return xp, sum(jnp.mean(o) for o in jax.tree_util.tree_leaves(out))
        _, ys = jax.lax.scan(step, x0, None, length=R)
        return ys
    f = jax.jit(run)
    out = f(x); sync(out)
    best = np.inf
    for _ in range(N):
        t0 = time.perf_counter()
        out = f(x); sync(out)
        best = min(best, time.perf_counter() - t0)
    return best


def bench(name, idx, valid, num_src, combos):
    rng = np.random.default_rng(1)
    src = rng.standard_normal(num_src).astype(np.float32)
    srci = rng.standard_normal(num_src).astype(np.float32)
    want = np.where(valid, src[np.clip(idx, 0, num_src - 1)], 0)
    results = []
    for (P, kp, K) in combos:
        try:
            t = gk.build_wide_gather_tables(idx, valid, num_src, p_tiles=P,
                                            kp_rows=kp, k_rows=K)
        except Exception as e:
            print(f"{name} P={P} kp={kp} K={K}: build fail {e}")
            continue
        if t is None:
            print(f"{name} P={P} kp={kp} K={K}: tables=None")
            continue
        dev = gk.gather_device_tables(t)
        pad = t.src_rows * 128 - num_src
        re = jnp.asarray(np.pad(src, (0, pad)).reshape(t.src_rows, 128))
        im = jnp.asarray(np.pad(srci, (0, pad)).reshape(t.src_rows, 128))
        try:
            out = gk.run_gather(re, im, dev, t)
            got = np.asarray(out[0]).reshape(-1)[:t.num_out]
            ok = np.allclose(got, want, atol=1e-5)
            cal = scan_seconds_min(lambda x: (x[0], x[1]), (re, im))
            tot = scan_seconds_min(
                lambda x: gk.run_gather(x[0], x[1], dev, t), (re, im))
            dt = (tot - cal) / R
        except Exception as e:
            print(f"{name} P={P} kp={kp} K={t.span_rows}: run fail "
                  f"{type(e).__name__} {str(e)[:150]}")
            continue
        C = t.row0.shape[0]
        print(f"{name} P={P} kp={t.kp_rows} K={t.span_rows}: "
              f"{'OK' if ok else 'MISMATCH'} C={C} -> {dt*1e3:.3f} ms "
              f"({dt/C*1e9:.0f} ns/step)", flush=True)
        results.append((dt, P, t.kp_rows, t.span_rows))
    if results:
        best = min(results)
        print(f"{name} BEST: {best[0]*1e3:.3f} ms at P={best[1]} "
              f"kp={best[2]} K={best[3]}", flush=True)


def main():
    n = int(os.environ.get("DIM", "256"))
    triplets = spherical_cutoff_triplets(n)
    p = build_index_plan(TransformType.C2C, n, n, n, triplets)
    vi = p.value_indices.astype(np.int64)
    num_slots = p.num_sticks * p.dim_z
    print(f"dim={n} values={p.num_values} slots={num_slots}", flush=True)
    (dec_idx, occ), (cmp_idx, cmp_valid) = gk.compression_gather_inputs(
        vi, num_slots)
    bench("decompress", dec_idx, occ, p.num_values,
          [(8, 12, 0), (8, 16, 0), (16, 12, 0), (16, 16, 0), (8, 8, 0),
           (16, 8, 0)])
    bench("compress", cmp_idx, cmp_valid, num_slots,
          [(8, 12, 0), (8, 12, 128), (8, 16, 128), (16, 12, 0),
           (16, 16, 0), (8, 24, 0)])


if __name__ == "__main__":
    print("devices:", jax.devices(), flush=True)
    main()

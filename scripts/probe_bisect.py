#!/usr/bin/env python
"""Bisect the pair compile blow-up: compile growing prefixes of the real
backward/forward pipeline at a given dim with the real plan tables."""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp


def timed_compile(name, fn, *args):
    t0 = time.perf_counter()
    compiled = jax.jit(fn).lower(*args).compile()
    tc = time.perf_counter() - t0
    print(f"{name:35s} compile {tc:8.2f}s", flush=True)
    return compiled


def main() -> None:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 320
    from spfft_tpu import TransformType, make_local_plan
    from spfft_tpu.ops import stages
    from spfft_tpu.utils import as_interleaved
    from spfft_tpu.utils.workloads import spherical_cutoff_triplets

    triplets = spherical_cutoff_triplets(n)
    plan = make_local_plan(TransformType.C2C, n, n, n, triplets,
                           precision="single", use_pallas=False)
    p = plan.index_plan
    print(f"n={n} sticks={p.num_sticks} values={p.num_values}", flush=True)

    rng = np.random.default_rng(42)
    values = (rng.uniform(-1, 1, p.num_values)
              + 1j * rng.uniform(-1, 1, p.num_values)).astype(np.complex64)
    values_il = jax.device_put(np.asarray(as_interleaved(values, "single")))
    tables = plan._tables

    timed_compile("1 decompress",
                  lambda v, t: stages.decompress(
                      v, t["slot_src"], p.num_sticks, p.dim_z),
                  values_il, tables)
    timed_compile("2 +z_backward",
                  lambda v, t: stages.z_backward(stages.decompress(
                      v, t["slot_src"], p.num_sticks, p.dim_z)),
                  values_il, tables)
    timed_compile("3 +sticks_to_grid",
                  lambda v, t: stages.sticks_to_grid(
                      stages.z_backward(stages.decompress(
                          v, t["slot_src"], p.num_sticks, p.dim_z)),
                      t["col_inv"], p.dim_y, p.dim_x_freq),
                  values_il, tables)
    timed_compile("4 full backward",
                  lambda v, t: plan._backward_impl(v, t, pallas=False),
                  values_il, tables)

    space = plan.backward(values_il)
    timed_compile("5 fwd xy only",
                  lambda s: stages.xy_forward_c2c(
                      (s[..., 0] + 1j * s[..., 1])), space)
    timed_compile("6 fwd xy+pack",
                  lambda s, t: stages.grid_to_sticks(
                      stages.xy_forward_c2c(s[..., 0] + 1j * s[..., 1]),
                      t["scatter_cols"]),
                  space, tables)
    timed_compile("7 full forward",
                  lambda s, t: plan._forward_impl(s, t, scaled=False,
                                                  pallas=False),
                  space, tables)
    timed_compile("8 full pair",
                  lambda v, t: plan._pair_impl(v, t, scaled=False, fn=None),
                  values_il, tables)


if __name__ == "__main__":
    main()

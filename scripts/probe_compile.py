#!/usr/bin/env python
"""Micro-probe: compile-time of each pipeline op in isolation at a given dim,
to attribute the envelope's compile blow-up to a specific XLA op."""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np


def timed_compile(name, fn, *args):
    t0 = time.perf_counter()
    compiled = jax.jit(fn).lower(*args).compile()
    tc = time.perf_counter() - t0
    t0 = time.perf_counter()
    out = compiled(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    out = compiled(*args)
    jax.block_until_ready(out)
    te = time.perf_counter() - t0
    print(f"{name:30s} compile {tc:8.2f}s  exec {te * 1e3:8.2f}ms", flush=True)


def main() -> None:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 320
    num_sticks = int(np.pi * (n // 2) ** 2)
    nxf = n
    print(f"n={n} sticks={num_sticks}", flush=True)

    sticks = jnp.ones((num_sticks, n), jnp.complex64)
    grid = jnp.ones((n, n, n), jnp.complex64)
    col_inv = jnp.zeros((n * nxf,), jnp.int32)
    slot_src = jnp.zeros((num_sticks * n,), jnp.int32)
    values = jnp.ones((num_sticks * n // 2, 2), jnp.float32)

    timed_compile("z ifft (sticks,n)",
                  lambda s: jnp.fft.ifft(s, axis=1), sticks)
    timed_compile("xy ifft2 (n,n,n)",
                  lambda g: jnp.fft.ifft2(g, axes=(1, 2)), grid)
    timed_compile("gather sticks_to_grid",
                  lambda s, ci: jnp.take(
                      jnp.concatenate(
                          [s.T.reshape(n, -1),
                           jnp.zeros((n, 1), s.dtype)], axis=1),
                      ci, axis=1).reshape(n, n, nxf),
                  sticks, col_inv)
    timed_compile("gather decompress",
                  lambda v, ss: jnp.take(
                      jnp.concatenate([v, jnp.zeros((1, 2), v.dtype)]),
                      ss, axis=0),
                  values, slot_src)


if __name__ == "__main__":
    main()

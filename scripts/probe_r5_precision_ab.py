#!/usr/bin/env python
"""Round-5 probe: is the 256^3 fused pair MXU-bound?

A/B the fused identity pair (apply_pointwise) with the matmul-DFT dots
at HIGHEST (6-pass bf16 on f32 operands) vs HIGH (3-pass) vs DEFAULT
(1-pass): if the pair is MXU-bound the HIGH variant should recover a
large chunk of the dot time; if movement-bound it barely moves.
Accuracy is the pair round-trip error ||pair(v)/size - v|| / ||v||
(backward+forward with no scaling multiplies by the global size), which
bounds the per-direction error without any dense-oracle host copy.

Shipping setting is HIGHEST (probe_r4_dft.py measured lower settings
missing the 1e-6 contract per pass); this re-checks the tradeoff at the
whole-pair level under the round-5 sync-robust estimator.

Usage: DIM=256 python scripts/probe_r5_precision_ab.py

NOTE (post fused kernels): the sweep monkeypatches dft._HIGHEST, which
only reaches the XLA stage forms — the Pallas kernels hardcode HIGHEST.
The probe therefore forces SPFFT_TPU_FUSED_STAGE=0 so the A/B varies
what it claims to (its recorded numbers predate the kernels).
"""
import os
import sys
import time

os.environ.setdefault("SPFFT_TPU_FUSED_STAGE", "0")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax

from spfft_tpu import TransformType, make_local_plan
from spfft_tpu.ops import dft
from spfft_tpu.utils.benchtime import diff_estimate_seconds
from spfft_tpu.utils.workloads import spherical_cutoff_triplets

DIM = int(os.environ.get("DIM", 256))
REPS = int(os.environ.get("REPS", 16))


def sync(a):
    return float(np.asarray(jax.numpy.real(a).ravel()[0]))


def measure(plan, vil):
    def grp(g):
        t0 = time.perf_counter()
        o = None
        for _ in range(g):
            o = plan.apply_pointwise(vil)
        sync(o)
        return time.perf_counter() - t0
    return diff_estimate_seconds(grp, reps=REPS)


def main():
    tri = spherical_cutoff_triplets(DIM)
    rng = np.random.default_rng(7)
    n = len(tri)
    vals = (rng.uniform(-1, 1, n) + 1j * rng.uniform(-1, 1, n)).astype(np.complex64)
    size = float(DIM) ** 3

    for name, prec in [("HIGHEST", jax.lax.Precision.HIGHEST),
                       ("HIGH", jax.lax.Precision.HIGH),
                       ("DEFAULT", jax.lax.Precision.DEFAULT)]:
        dft._HIGHEST = prec
        dft._dft_mats.cache_clear()
        plan = make_local_plan(TransformType.C2C, DIM, DIM, DIM, tri)
        vil = jax.device_put(plan._coerce_values(vals))
        out = np.asarray(plan.apply_pointwise(vil))
        got = out[..., 0] + 1j * out[..., 1] if out.ndim == 2 else out
        err = np.linalg.norm(got / size - vals) / np.linalg.norm(vals)
        est = measure(plan, vil)
        print(f"{name:8s} pair {est.seconds*1e3:7.2f} ms (med {est.median*1e3:7.2f})"
              f"  roundtrip rel l2 {err:.3e}", flush=True)
        del plan, vil
    dft._HIGHEST = jax.lax.Precision.HIGHEST


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Round-4 probe: static analysis of the compiled 256^3 fused-pair HLO.

Parses the optimized HLO of the apply_pointwise executable: convolution
shapes (the DFT-matmul FFT lowering) with cycle estimates, every copy /
transpose / concatenate over 10 MB, and fusion count — to locate the gap
between the ~9 ms component estimate and the measured 12.5 ms pair.
"""
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

from spfft_tpu import TransformType, make_local_plan
from spfft_tpu.utils.workloads import spherical_cutoff_triplets

DT_BYTES = {"f32": 4, "c64": 8, "s32": 4, "s16": 2, "pred": 1, "f64": 8,
            "c128": 16, "s64": 8, "u32": 4, "bf16": 2, "s8": 1, "u8": 1}


def shape_bytes(s):
    m = re.match(r"(\w+)\[([\d,]*)\]", s)
    if not m:
        return 0
    dt, dims = m.group(1), m.group(2)
    n = 1
    for d in dims.split(","):
        if d:
            n *= int(d)
    return n * DT_BYTES.get(dt, 4)


def main(n=256):
    triplets = spherical_cutoff_triplets(n)
    plan = make_local_plan(TransformType.C2C, n, n, n, triplets,
                           precision="single")
    import functools
    fn = jax.jit(functools.partial(plan._pair_impl, scaled=False, fn=None))
    rng = np.random.default_rng(0)
    N = plan.index_plan.num_values
    values = (rng.uniform(-1, 1, N)
              + 1j * rng.uniform(-1, 1, N)).astype(np.complex64)
    values_il = plan._coerce_values(values)
    lowered = fn.lower(values_il, plan._tables)
    compiled = lowered.compile()
    txt = compiled.as_text()
    print(f"HLO: {len(txt)} chars")
    try:
        ma = compiled.memory_analysis()
        print(f"peak memory: temp={ma.temp_size_in_bytes/1e6:.0f} MB "
              f"args={ma.argument_size_in_bytes/1e6:.0f} MB "
              f"out={ma.output_size_in_bytes/1e6:.0f} MB")
    except Exception as e:
        print("memory_analysis:", e)

    convs = []
    big = []
    fusions = 0
    pallas = 0
    for line in txt.splitlines():
        ls = line.strip()
        m = re.match(r"%?\S+ = (\S+) (\w[\w-]*)\(", ls)
        if not m:
            continue
        shape, op = m.group(1), m.group(2)
        nbytes = shape_bytes(shape)
        if op == "convolution":
            # operand shapes
            ops_shapes = re.findall(r"\(([^)]*)\)", ls)
            convs.append((shape, ls[:160]))
        elif op == "fusion":
            fusions += 1
        elif op == "custom-call" and "tpu_custom_call" in ls:
            pallas += 1
        if op in ("copy", "transpose", "concatenate", "reshape",
                  "bitcast-convert", "slice", "pad") and nbytes > 10e6:
            big.append((nbytes, op, shape, ls[:130]))

    print(f"\n{len(convs)} convolutions, {fusions} fusions, "
          f"{pallas} pallas custom-calls")
    for shape, ls in convs:
        print(f"  conv out={shape}")
        print(f"    {ls}")
    print(f"\nlarge data-movement ops (>10MB):")
    tot = 0
    for nbytes, op, shape, ls in sorted(big, reverse=True):
        tot += nbytes
        print(f"  {op:12s} {nbytes/1e6:8.1f} MB out  {shape}")
    print(f"  total large-op output bytes: {tot/1e6:.0f} MB "
          f"(~{tot*2/819e9*1e3:.2f} ms at HBM peak, r+w)")


if __name__ == "__main__":
    main(int(os.environ.get("DIM", "256")))

#!/usr/bin/env python
"""Round-5 probe: fused Pallas matmul-DFT stage vs the XLA 3-dot form.

The XLA pdft_last materializes p1/p2/p3/(xr+xi) as grid-sized HBM
intermediates around the combine (three matmuls cannot share one fused
elementwise chain). A Pallas kernel does the three dots + combine per
row tile entirely in VMEM: one read of (xr, xi), one write of (yr, yi).
Measured with the shared sync-cancelling estimator (block_until_ready
does NOT block on the axon platform — probe_r5_dispatch_floor.py).

Usage: python scripts/probe_r5_fused_stage.py
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from spfft_tpu.ops import dft
from spfft_tpu.utils.benchtime import diff_estimate_seconds

_HI = jax.lax.Precision.HIGHEST


def _kernel(xr_ref, xi_ref, cr_ref, ci_ref, cs_ref, yr_ref, yi_ref):
    a = xr_ref[...]
    b = xi_ref[...]
    dn = (((1,), (0,)), ((), ()))
    p1 = jax.lax.dot_general(a, cr_ref[...], dn, precision=_HI,
                             preferred_element_type=jnp.float32)
    p2 = jax.lax.dot_general(b, ci_ref[...], dn, precision=_HI,
                             preferred_element_type=jnp.float32)
    p3 = jax.lax.dot_general(a + b, cs_ref[...], dn, precision=_HI,
                             preferred_element_type=jnp.float32)
    yr_ref[...] = p1 - p2
    yi_ref[...] = p3 - p1 - p2


def fused_pdft_last(xr, xi, mats, tm=1024):
    cr, ci, cs = (jnp.asarray(m) for m in mats)
    k, mo = cr.shape
    lead = xr.shape[:-1]
    m = int(np.prod(lead)) if lead else 1
    xr2 = xr.reshape(m, k)
    xi2 = xi.reshape(m, k)
    grid = (pl.cdiv(m, tm),)
    yr, yi = pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((tm, k), lambda i: (i, 0)),
            pl.BlockSpec((tm, k), lambda i: (i, 0)),
            pl.BlockSpec((k, mo), lambda i: (0, 0)),
            pl.BlockSpec((k, mo), lambda i: (0, 0)),
            pl.BlockSpec((k, mo), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((tm, mo), lambda i: (i, 0)),
            pl.BlockSpec((tm, mo), lambda i: (i, 0)),
        ],
        out_shape=[jax.ShapeDtypeStruct((m, mo), jnp.float32)] * 2,
    )(xr2, xi2, cr, ci, cs)
    return yr.reshape(lead + (mo,)), yi.reshape(lead + (mo,))


def sync(pair):
    return float(np.asarray(jnp.real(pair[0]).ravel()[0]))


def bench(fn, xr, xi, mats, chain=4, reps=16):
    def body(a, b):
        for _ in range(chain):
            a, b = fn(a, b, mats)
        return a, b
    g = jax.jit(body)
    sync(g(xr, xi))

    def grp(k):
        t0 = time.perf_counter()
        o = (xr, xi)
        for _ in range(k):
            o = g(xr, xi)
        sync(o)
        return time.perf_counter() - t0
    est = diff_estimate_seconds(grp, reps=reps)
    return est.seconds / chain


def main():
    n = int(os.environ.get("N", 256))
    m = int(os.environ.get("M", 256 * 256))
    rng = np.random.default_rng(5)
    xr64 = rng.standard_normal((m, n))
    xi64 = rng.standard_normal((m, n))
    mats = dft.c2c_mats(n, dft.BACKWARD)
    cr64, ci64 = np.asarray(mats[0], np.float64), np.asarray(mats[1], np.float64)
    ref_r = xr64 @ cr64 - xi64 @ ci64

    xr = jnp.asarray(xr64, jnp.float32)
    xi = jnp.asarray(xi64, jnp.float32)

    for label, fn in [("xla ", dft.pdft_last),
                      ("plls", fused_pdft_last)]:
        yr, yi = jax.jit(lambda a, b: fn(a, b, mats))(xr, xi)
        err = np.linalg.norm(np.asarray(yr, np.float64) - ref_r) / \
            np.linalg.norm(ref_r)
        t = bench(fn, xr, xi, mats)
        gb = (4 * m * n * 4) / 1e9
        print(f"{label} N={n} M={m}: {t*1e3:7.3f} ms/stage  rel {err:.3e}  "
              f"eff {(gb/t):6.1f} GB/s", flush=True)

    # awkward shapes: M not tile-aligned, K != Mout (r2c-like sub-rows)
    for (mm, kk) in [(51471, 256), (33333, 129)]:
        xr64 = rng.standard_normal((mm, kk))
        xi64 = rng.standard_normal((mm, kk))
        sub = dft.sub_rows_mats(n, dft.BACKWARD, tuple(range(kk))) \
            if kk != n else dft.c2c_mats(n, dft.BACKWARD)
        cr64, ci64 = (np.asarray(sub[0], np.float64),
                      np.asarray(sub[1], np.float64))
        ref_r = xr64 @ cr64 - xi64 @ ci64
        a = jnp.asarray(xr64, jnp.float32)
        b = jnp.asarray(xi64, jnp.float32)
        yr, yi = jax.jit(lambda p, q: fused_pdft_last(p, q, sub))(a, b)
        err = np.linalg.norm(np.asarray(yr, np.float64) - ref_r) / \
            np.linalg.norm(ref_r)
        print(f"plls M={mm} K={kk}: rel {err:.3e}", flush=True)


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Round-5 probe: PREFIX timing of the fused mdft pair at 256^3.

probe_r5_mdft_stages.py's per-carrier calibration went negative on
grid-sized carriers (the identity scan pays layout copies the real body
doesn't), so stage costs come instead from differences of scanned
PREFIXES of the actual fused pipeline — every prefix runs on the same
values carrier, so the scan/perturb/consume constant cancels in the
difference and each stage is measured in its fused context.

Usage: DIM=256 python scripts/probe_r5_prefix.py
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

from spfft_tpu import TransformType, make_local_plan
from spfft_tpu.ops import dft, stages
from spfft_tpu.utils.workloads import spherical_cutoff_triplets

R = int(os.environ.get("REPS", 20))


def sync(out):
    leaf = jax.tree_util.tree_leaves(out)[0]
    float(np.asarray(jax.numpy.real(leaf).ravel()[0]))


def _consume(y):
    leaves = jax.tree_util.tree_leaves(y)
    return sum(jnp.mean(jnp.real(x)) for x in leaves)


def _scan_seconds(body, x, reps=4):
    def run(x0):
        def step(c, _):
            xp = c * c.dtype.type(1.0 + 1e-7)
            return xp, _consume(body(xp))
        _, ys = jax.lax.scan(step, x0, None, length=R)
        return ys
    f = jax.jit(run)
    out = f(x)
    sync(out)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = f(x)
        sync(out)
        best = min(best, time.perf_counter() - t0)
    return best


def main():
    n = int(os.environ.get("DIM", "256"))
    print(f"devices: {jax.devices()}", flush=True)
    triplets = spherical_cutoff_triplets(n)
    plan = make_local_plan(TransformType.C2C, n, n, n, triplets,
                           precision="single")
    p = plan.index_plan
    tables = plan._tables
    rng = np.random.default_rng(0)
    N = p.num_values
    values = (rng.uniform(-1, 1, N)
              + 1j * rng.uniform(-1, 1, N)).astype(np.complex64)
    vil = jax.device_put(plan._coerce_values(values))

    S, Z = plan._s_pad, p.dim_z
    xf = p.dim_x_freq
    col_tab = tables["col_inv_t"]
    cols_tab = tables["scatter_cols_t"]
    unpack = stages.sticks_to_grid_padded if S > p.num_sticks \
        else stages.sticks_to_grid
    zb = dft.c2c_mats(Z, dft.BACKWARD)
    yb = dft.c2c_mats(p.dim_y, dft.BACKWARD)
    xb = dft.c2c_mats(p.dim_x, dft.BACKWARD)
    xf_m = dft.c2c_mats(p.dim_x, dft.FORWARD)
    yf = dft.c2c_mats(p.dim_y, dft.FORWARD)
    zf = dft.c2c_mats(Z, dft.FORWARD)

    def s_dec(v):
        return plan._decompress_planar(v, tables)

    def s_z(st):
        return dft.pdft_last(st[0], st[1], zb)

    def s_unpack(st):
        return (unpack(st[0], col_tab, xf, p.dim_y),
                unpack(st[1], col_tab, xf, p.dim_y))

    def s_y(g):
        return dft.pdft_last(g[0], g[1], yb)

    def s_swap(g):
        return (jnp.swapaxes(g[0], -1, -2), jnp.swapaxes(g[1], -1, -2))

    def s_x(g):
        return dft.pdft_last(g[0], g[1], xb)

    def f_x(g):
        return dft.pdft_last(g[0], g[1], xf_m)

    def f_swap(g):
        return (jnp.swapaxes(g[0], -1, -2), jnp.swapaxes(g[1], -1, -2))

    def f_y(g):
        return dft.pdft_last(g[0], g[1], yf)

    def f_pack(g):
        return (stages.grid_to_sticks(g[0], cols_tab),
                stages.grid_to_sticks(g[1], cols_tab))

    def f_z(st):
        return dft.pdft_last(st[0], st[1], zf)

    def f_cmp(st):
        return plan._compress_planar(st[0], st[1], tables)

    chain = [("decompress", s_dec), ("z bwd", s_z), ("unpack", s_unpack),
             ("y bwd", s_y), ("swap", s_swap), ("x bwd", s_x),
             ("x fwd", f_x), ("swap2", f_swap), ("y fwd", f_y),
             ("pack", f_pack), ("z fwd", f_z), ("compress", f_cmp)]

    base = _scan_seconds(lambda v: v, vil)
    print(f"{'(identity)':18s} {base/R*1e3:8.3f} ms/step", flush=True)
    prev = base
    for k in range(1, len(chain) + 1):
        def body(v, _k=k):
            out = v
            for _, fn in chain[:_k]:
                out = fn(out)
            return out
        t = _scan_seconds(body, vil)
        name = chain[k - 1][0]
        print(f"+{name:17s} {t/R*1e3:8.3f} ms/step  (Δ {(t-prev)/R*1e3:+7.3f})",
              flush=True)
        prev = t


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Round-5 probe: does forcing the planar PAIR (2, N) value boundary on a
sub-threshold plan (256^3, 8.78M values) cut the fused identity pair?

The rows (N, 2) boundary pays gather_kernel.planar_from_interleaved /
interleaved_from_planar conversions inside every fused executable; the
pair boundary reduces them to row slices / a (2, N) stack. Same-session
A/B (alternating diff-estimator groups) — ratios are mode-invariant
(BENCHMARKS.md 'Session discipline'); cross-check any win with
scripts/ab_interleaved.py before committing a default change.

Usage: DIM=256 python scripts/probe_r5_pairio.py
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax

import spfft_tpu.plan as plan_mod
from spfft_tpu import TransformType, make_local_plan
from spfft_tpu.utils.benchtime import diff_estimate_seconds
from spfft_tpu.utils.workloads import spherical_cutoff_triplets


def sync(a):
    return float(np.asarray(jax.numpy.real(a).ravel()[0]))


def measure(plan, vil, reps=20):
    def grp(g):
        t0 = time.perf_counter()
        o = None
        for _ in range(g):
            o = plan.apply_pointwise(vil)
        sync(o)
        return time.perf_counter() - t0
    return diff_estimate_seconds(grp, reps=reps)


def main():
    n = int(os.environ.get("DIM", "256"))
    print(f"devices: {jax.devices()}", flush=True)
    triplets = spherical_cutoff_triplets(n)
    rng = np.random.default_rng(42)
    N = len(triplets)
    values = (rng.uniform(-1, 1, N)
              + 1j * rng.uniform(-1, 1, N)).astype(np.complex64)

    plan_rows = make_local_plan(TransformType.C2C, n, n, n, triplets,
                                precision="single")
    saved = plan_mod.PAIR_IO_THRESHOLD
    plan_mod.PAIR_IO_THRESHOLD = 1
    try:
        plan_pair = make_local_plan(TransformType.C2C, n, n, n, triplets,
                                    precision="single")
    finally:
        plan_mod.PAIR_IO_THRESHOLD = saved
    assert not plan_rows.pair_values_io and plan_pair.pair_values_io

    vil_rows = jax.device_put(plan_rows._coerce_values(values))
    vil_pair = jax.device_put(plan_pair._coerce_values(values))

    # correctness cross-check before timing
    out_rows = np.asarray(plan_rows.apply_pointwise(vil_rows))
    out_pair = np.asarray(plan_pair.apply_pointwise(vil_pair)).T
    rel = (np.linalg.norm(out_rows - out_pair)
           / np.linalg.norm(out_rows))
    print(f"rows-vs-pair output rel diff: {rel:.2e}", flush=True)

    # warm both executables, then alternate measurement blocks
    sync(plan_pair.apply_pointwise(vil_pair))
    sync(plan_rows.apply_pointwise(vil_rows))
    for it in range(3):
        er = measure(plan_rows, vil_rows)
        ep = measure(plan_pair, vil_pair)
        print(f"block {it}: rows {er.seconds*1e3:.3f} ms "
              f"(med {er.median*1e3:.3f})   pair {ep.seconds*1e3:.3f} ms "
              f"(med {ep.median*1e3:.3f})", flush=True)


if __name__ == "__main__":
    main()

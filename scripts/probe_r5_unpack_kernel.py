#!/usr/bin/env python
"""Round-5 probe: unpack (sticks -> grid placement) through the
existing Pallas windowed element-gather + the cheap transpose.

probe_r5_unpack measured the XLA row gather at 3.56 ms (both channels)
with the transpose at 0.41 — the gather dominates. The unpack map in
FLAT element space (out q = r*Z + z <- src col_inv[r]*Z + z) has
256-element consecutive runs, exactly the window locality the
compression gather kernel is built for. This builds tables for that
map at 256^3 and times [kernel gather + reshape + T] vs the current
`sticks[col_inv].T`.

Usage: python scripts/probe_r5_unpack_kernel.py
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

from spfft_tpu import TransformType, make_local_plan
from spfft_tpu.ops import gather_kernel as gk
from spfft_tpu.utils.benchtime import diff_estimate_seconds
from spfft_tpu.utils.workloads import spherical_cutoff_triplets

DIM = int(os.environ.get("DIM", 256))


def sync(out):
    leaf = jax.tree_util.tree_leaves(out)[0]
    return float(np.asarray(jnp.real(leaf).ravel()[0]))


def measure(f, *args, reps=16):
    g = jax.jit(f)
    sync(g(*args))

    def grp(k):
        t0 = time.perf_counter()
        o = None
        for _ in range(k):
            o = g(*args)
        sync(o)
        return time.perf_counter() - t0
    return diff_estimate_seconds(grp, reps=reps).seconds


def main():
    tri = spherical_cutoff_triplets(DIM)
    plan = make_local_plan(TransformType.C2C, DIM, DIM, DIM, tri)
    p = plan.index_plan
    tabs = plan._tables_hot
    col = np.asarray(tabs["col_inv_t"])
    s_pad = plan._s_pad
    Z = p.dim_z
    R = col.shape[0]

    t0 = time.time()
    valid = col < p.num_sticks  # sentinel == num_sticks -> zero output
    # forward-fill sentinel rows so windows stay local (the idiom of
    # compression_gather_inputs' decompress side)
    filled = np.maximum.accumulate(
        np.where(valid, col.astype(np.int64), 0))
    # element map: out q = r*Z + z <- src col[r]*Z + z
    idx = (filled[:, None] * Z
           + np.arange(Z, dtype=np.int64)[None, :]).reshape(-1)
    vmask = np.repeat(valid, Z)
    t = gk.build_best_gather_tables(idx, vmask, s_pad * Z)
    print(f"table build: {time.time()-t0:.2f} s -> "
          f"{type(t).__name__ if t is not None else None}", flush=True)
    if t is None:
        return
    dev = gk.gather_device_tables(t)

    rng = np.random.default_rng(3)
    sr = jax.device_put(jnp.asarray(
        rng.standard_normal((s_pad, Z)), jnp.float32))
    si = jax.device_put(jnp.asarray(
        rng.standard_normal((s_pad, Z)), jnp.float32))
    xf = p.dim_x_freq

    def kernel_unpack(a, b):
        src_re = a.reshape(-1, 128)
        src_im = b.reshape(-1, 128)
        o_re, o_im = gk.run_gather(src_re, src_im, dev, t)
        gr = o_re.reshape(-1)[:R * Z].reshape(R, Z)
        gi = o_im.reshape(-1)[:R * Z].reshape(R, Z)
        return (gr.T.reshape(Z, xf, p.dim_y),
                gi.T.reshape(Z, xf, p.dim_y))

    def xla_unpack(a, b):
        cj = jnp.asarray(col)
        return (a[cj].T.reshape(Z, xf, p.dim_y),
                b[cj].T.reshape(Z, xf, p.dim_y))

    ka = jax.jit(kernel_unpack)(sr, si)
    xa = jax.jit(xla_unpack)(sr, si)
    d = np.linalg.norm(np.asarray(ka[0]) - np.asarray(xa[0]))
    print(f"kernel-vs-xla diff: {d:.3e}", flush=True)

    tk = measure(kernel_unpack, sr, si)
    tx = measure(xla_unpack, sr, si)
    print(f"kernel unpack: {tk*1e3:7.3f} ms   xla unpack: {tx*1e3:7.3f} ms",
          flush=True)


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Interleaved multi-process A/B for pair-time experiments.

Round 5 resolved the round-4 "bimodal device" as bimodal SYNC cost
(~88 vs ~128 ms per readback — scripts/probe_r5_mode.py), now cancelled
inside the estimator itself (utils/benchtime.py median differencing).
Per-session compile/backend variance remains, so a single-session A/B
can still report a 'win' that is session state: two round-4
optimisations were committed on single-session evidence and reverted
under this harness. This script stays the required protocol for ANY
tuning decision:

  python scripts/ab_interleaved.py /root/repo /path/to/other [--rounds 4]

Each round launches one fresh subprocess per variant (alternating), each
measuring the 256^3 identity pair through the public API with the
sync-cancelling difference estimator. Compares MIN and MEDIAN per
variant and refuses a verdict when the distributions overlap.
"""
import argparse
import os
import statistics
import subprocess
import sys

WORKER = r'''
import os, sys, time
sys.path.insert(0, sys.argv[1])
import numpy as np, jax
from spfft_tpu import TransformType, make_local_plan
from spfft_tpu.utils.benchtime import diff_estimate_seconds
from spfft_tpu.utils.workloads import spherical_cutoff_triplets
n = int(os.environ.get("AB_DIM", "256"))
triplets = spherical_cutoff_triplets(n)
rng = np.random.default_rng(42)
N = len(triplets)
values = (rng.uniform(-1, 1, N)
          + 1j * rng.uniform(-1, 1, N)).astype(np.complex64)
plan = make_local_plan(TransformType.C2C, n, n, n, triplets,
                       precision="single")
vil = jax.device_put(plan._coerce_values(values))
def sync(a):
    return float(np.asarray(jax.numpy.real(a).ravel()[0]))
o = plan.apply_pointwise(vil); sync(o)
def grp(g):
    t0 = time.perf_counter(); o = None
    for _ in range(g):
        o = plan.apply_pointwise(vil)
    sync(o)
    return time.perf_counter() - t0
est = diff_estimate_seconds(grp, reps=20)
print(f"ABRESULT {est.seconds * 1e3:.3f}")
'''


def run_one(path: str) -> float:
    proc = subprocess.run([sys.executable, "-c", WORKER, path],
                          capture_output=True, text=True)
    for line in reversed(proc.stdout.splitlines()):
        if line.startswith("ABRESULT"):
            return float(line.split()[1])
    sys.stderr.write(proc.stdout[-1500:] + proc.stderr[-1500:])
    raise SystemExit(f"worker for {path} produced no result")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("a", help="first repo checkout (e.g. /root/repo)")
    ap.add_argument("b", help="second checkout (e.g. a git worktree)")
    ap.add_argument("--rounds", type=int, default=4)
    args = ap.parse_args()
    samples = {args.a: [], args.b: []}
    for r in range(args.rounds):
        for path in (args.a, args.b):
            ms = run_one(path)
            samples[path].append(ms)
            print(f"round {r} {path}: {ms:.3f} ms", flush=True)
    print()
    stats = {}
    for path, xs in samples.items():
        stats[path] = (min(xs), statistics.median(xs))
        print(f"{path}: min {min(xs):.3f}  median "
              f"{statistics.median(xs):.3f}  samples "
              f"{[round(x, 2) for x in xs]}")
    (a_min, a_med), (b_min, b_med) = stats[args.a], stats[args.b]
    if (a_min < b_min) == (a_med < b_med) and \
            abs(a_med - b_med) > 0.05 * max(a_med, b_med):
        win = args.a if a_med < b_med else args.b
        print(f"VERDICT: {win} is faster (min and median agree, "
              f"median gap > 5%)")
    else:
        print("VERDICT: inconclusive — min/median disagree or the gap is "
              "inside the noise; add rounds before deciding")


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Round-4 probe 2: four-step (radix-2, 256=2x128) matmul-DFT at HIGHEST.

The direct matmul-DFT matched XLA's conv-FFT (same FLOPs); HIGH precision
halves MXU time but fails the 1e-6 bar. Four-step halves the MXU FLOPs at
full f32 accuracy: DFT_256 = butterfly o twiddle o two DFT_128 matmuls on
contiguous halves, with the even/odd input (DIT) or output (DIF)
permutation ABSORBED into the plan's gather tables at plan time.

Timing here uses an unpermuted stand-in (identical cost, wrong values);
correctness of the permuted math is asserted separately at small scale.

Pipeline shape probed: all minor-axis DFTs + 2 grid transposes
(z,y,x)<->(z,x,y) instead of XLA fft2's 4 internal layout copies.

Usage: DIM=256 python scripts/probe_r4_dft2.py
"""
import os
import sys
import time
import functools

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

from spfft_tpu import TransformType, make_local_plan
from spfft_tpu.utils.benchtime import diff_estimate_seconds
from spfft_tpu.utils.workloads import spherical_cutoff_triplets

P_HI = jax.lax.Precision.HIGHEST
P_H3 = jax.lax.Precision.HIGH


def dftmat_ri(n, sign, scale=1.0):
    k = np.arange(n)
    m = np.exp(sign * 2j * np.pi * np.outer(k, k) / n) * scale
    return (np.ascontiguousarray(m.real.astype(np.float32)),
            np.ascontiguousarray(m.imag.astype(np.float32)))


def _mm_last(xr, xi, cr, ci, prec):
    f = lambda a, c: jax.lax.dot_general(
        a, c, (((a.ndim - 1,), (0,)), ((), ())), precision=prec)
    return (f(xr, cr) - f(xi, ci), f(xr, ci) + f(xi, cr))


def direct_last(x, mats, prec):
    yr, yi = _mm_last(jnp.real(x), jnp.imag(x), jnp.asarray(mats[0]),
                      jnp.asarray(mats[1]), prec)
    return yr + 1j * yi


def make_fourstep_last(n, sign, scale=1.0, permute_input=True):
    """Radix-2 DIT along the minor axis: input is [evens; odds] halves
    (``permute_input=False`` treats the given halves as already split —
    the table-absorbed form), output natural. Returns f(x)->y."""
    h = n // 2
    cr, ci = dftmat_ri(h, sign, scale)
    w = np.exp(sign * 2j * np.pi * np.arange(h) / n).astype(np.complex64)
    wr = jnp.asarray(np.ascontiguousarray(w.real))
    wi = jnp.asarray(np.ascontiguousarray(w.imag))

    def f(x):
        if permute_input:
            x = jnp.concatenate([x[..., 0::2], x[..., 1::2]], axis=-1)
        xr, xi = jnp.real(x), jnp.imag(x)
        er, ei = _mm_last(xr[..., :h], xi[..., :h], jnp.asarray(cr),
                          jnp.asarray(ci), P_HI)
        orr, oi = _mm_last(xr[..., h:], xi[..., h:], jnp.asarray(cr),
                           jnp.asarray(ci), P_HI)
        tr = orr * wr - oi * wi
        ti = orr * wi + oi * wr
        o = tr + 1j * ti
        e = er + 1j * ei
        return jnp.concatenate([e + o, e - o], axis=-1)
    return f


def main(n: int):
    # correctness of the permuted four-step first (small, CPU-checkable)
    f4 = make_fourstep_last(n, -1, permute_input=True)
    rng = np.random.default_rng(3)
    xs = (rng.standard_normal((500, n)) + 1j
          * rng.standard_normal((500, n))).astype(np.complex64)
    xs_dev = jax.jit(lambda a, b: a + 1j * b)(
        jnp.asarray(xs.real.copy()), jnp.asarray(xs.imag.copy()))
    take = jax.jit(lambda s: jnp.stack([jnp.real(s), jnp.imag(s)]))
    got = np.asarray(take(jax.jit(f4)(xs_dev)))
    ref = np.fft.fft(xs, axis=-1)
    rel = np.linalg.norm((got[0] + 1j * got[1]) - ref) / np.linalg.norm(ref)
    print(f"four-step DIT rel err vs numpy fft: {rel:.2e}", flush=True)

    triplets = spherical_cutoff_triplets(n)
    plan = make_local_plan(TransformType.C2C, n, n, n, triplets,
                           precision="single")
    p = plan.index_plan
    N = p.num_values
    tables = plan._tables
    from spfft_tpu.ops import stages
    print(f"== dim={n} values={N} ==", flush=True)

    values = (rng.uniform(-1, 1, N)
              + 1j * rng.uniform(-1, 1, N)).astype(np.complex64)
    values_il = jax.device_put(plan._coerce_values(values))

    def sync(arr):
        return float(np.asarray(arr.ravel()[0]))

    def timed_ms(fn, arg):
        def grp(g):
            t0 = time.perf_counter()
            o = None
            for _ in range(g):
                o = fn(arg)
            sync(o)
            return time.perf_counter() - t0
        est = diff_estimate_seconds(grp, reps=20)
        return est.seconds * 1e3

    cur = jax.jit(functools.partial(plan._pair_impl, scaled=False, fn=None))
    o = cur(values_il, plan._tables); sync(o)
    print(f"current pair (XLA fft):                  "
          f"{timed_ms(lambda v: cur(v, plan._tables), values_il):8.3f} ms",
          flush=True)

    db = dftmat_ri(n, +1)      # direct backward (unnormalised inverse)
    df = dftmat_ri(n, -1)      # direct forward
    f4b = make_fourstep_last(n, +1, permute_input=False)  # table-absorbed
    f4f = make_fourstep_last(n, -1, permute_input=False)

    def make_pair(zf, yf, xf, zb, yb, xb):
        def pair(v):
            sticks = plan._decompress(v, tables)
            sticks = zb(sticks)
            grid = stages.sticks_to_grid(sticks, tables["col_inv"],
                                         p.dim_y, p.dim_x_freq)
            # pretend (z,x,y): minor DFT = y pass
            grid = yb(grid)
            grid = jnp.swapaxes(grid, -1, -2)
            grid = xb(grid)            # space (z, y-ish, x) natural minor
            grid = xf(grid)
            grid = jnp.swapaxes(grid, -1, -2)
            grid = yf(grid)
            sticks = stages.grid_to_sticks(grid, tables["scatter_cols"])
            sticks = zf(sticks)
            return plan._compress(sticks, tables, None)
        return jax.jit(pair)

    d = lambda m: (lambda x: direct_last(x, m, P_HI))
    pairs = {
        "direct matmul minor + 2 transposes": make_pair(
            d(df), d(df), d(df), d(db), d(db), d(db)),
        "four-step minor + 2 transposes": make_pair(
            f4f, f4f, f4f, f4b, f4b, f4b),
        "four-step xy, direct z": make_pair(
            d(df), f4f, f4f, d(db), f4b, f4b),
    }
    for name, f in pairs.items():
        o = f(values_il); sync(o)
        print(f"{name:40s} {timed_ms(f, values_il):8.3f} ms", flush=True)


if __name__ == "__main__":
    print(f"devices: {jax.devices()}", flush=True)
    main(int(os.environ.get("DIM", "256")))

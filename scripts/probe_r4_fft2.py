#!/usr/bin/env python
"""Round-4 probe: true FFT stage costs (non-collapsible consume) + a
four-step DFT decomposition candidate.

XLA:TPU lowers jnp.fft to DFT *convolutions* (O(N^2) matmuls on the MXU),
so at 256^3 the xy FFTs are MXU-bound. A linear consume (mean) lets the
compiler commute the reduction through the convolution and fake sub-ms
FFTs — every stage here is consumed through mean(x*x) instead.

Four-step candidate: 256 = 2 x 128. DFT_128 as an einsum against a
(128,128) DFT matrix (perfect MXU shape) + twiddle + radix-2 butterfly
= half the MXU cycles of the direct 256-point DFT convolution.

Usage: DIM=256 python scripts/probe_r4_fft2.py
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

R = int(os.environ.get("REPS", 20))


def sync(out):
    leaf = jax.tree_util.tree_leaves(out)[0]
    float(np.asarray(jax.numpy.real(leaf).ravel()[0]))


def _perturb(x):
    return jax.tree_util.tree_map(lambda v: v * v.dtype.type(1.0 + 1e-7), x)


def _consume(y):
    tot = 0.0
    for leaf in jax.tree_util.tree_leaves(y):
        if jnp.iscomplexobj(leaf):
            r, i = jnp.real(leaf), jnp.imag(leaf)
            tot = tot + jnp.mean(r * r) + jnp.mean(i * i)
        else:
            tot = tot + jnp.mean(leaf * leaf)
    return tot


def _scan_seconds(body, x, reps=4):
    def run(x0):
        def step(c, _):
            xp = _perturb(c)
            return xp, _consume(body(xp))
        _, ys = jax.lax.scan(step, x0, None, length=R)
        return ys
    f = jax.jit(run)
    out = f(x)
    sync(out)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = f(x)
        sync(out)
        best = min(best, time.perf_counter() - t0)
    return best


def timeit(name, body, x, calib_s):
    total = _scan_seconds(body, x)
    dt = (total - calib_s) / R
    print(f"{name:52s} {dt*1e3:8.3f} ms", flush=True)
    return dt


def _device_complex(arr):
    """Commit a complex numpy array to device via its real/imag parts
    (complex host->device transfers are UNIMPLEMENTED on this platform)."""
    return jax.jit(lambda a, b: a + 1j * b)(
        jnp.asarray(np.ascontiguousarray(arr.real.astype(np.float32))),
        jnp.asarray(np.ascontiguousarray(arr.imag.astype(np.float32))))


def dft_matrix(n, sign, dtype=np.complex64):
    k = np.arange(n)
    return np.exp(sign * 2j * np.pi * np.outer(k, k) / n).astype(dtype)


def make_fourstep(n, sign):
    """1D DFT of size n = 2*h along the MINOR axis via
    butterfly(radix-2) o twiddle o DFT_h-einsum. sign=-1 forward."""
    h = n // 2
    F = dft_matrix(h, sign)  # host constants: XLA embeds them in-module
    w = np.exp(sign * 2j * np.pi * np.arange(h) / n).astype(np.complex64)

    def fft1(x):  # (..., n) -> (..., n)
        shp = x.shape
        # decimation in time: even/odd interleave on the minor axis
        xe = x[..., 0::2]
        xo = x[..., 1::2]
        Ye = jnp.einsum("...i,ik->...k", xe, F)
        Yo = jnp.einsum("...i,ik->...k", xo, F) * w
        return jnp.concatenate([Ye + Yo, Ye - Yo], axis=-1).reshape(shp)
    return fft1


def main(n: int):
    re = jnp.asarray(np.random.default_rng(0)
                     .standard_normal((n, n, n)).astype(np.float32))
    im = jnp.asarray(np.random.default_rng(1)
                     .standard_normal((n, n, n)).astype(np.float32))
    g0 = jax.jit(lambda a, b: a + 1j * b)(re, im)  # complex built on device
    g0.block_until_ready()
    sticks0 = jax.jit(lambda g: g.reshape(-1, n)[:51431])(g0)

    cal_g = _scan_seconds(lambda g: g, g0)
    cal_s = _scan_seconds(lambda s: s, sticks0)
    print(f"calib grid {cal_g/R*1e3:.3f} ms/step, "
          f"sticks {cal_s/R*1e3:.3f} ms/step", flush=True)

    ifft1 = make_fourstep(n, +1)
    fft1 = make_fourstep(n, -1)

    # correctness spot-check first
    take = jax.jit(lambda s: jnp.stack([jnp.real(s[:64]), jnp.imag(s[:64])]))
    s64 = np.asarray(take(sticks0))
    ref = np.fft.fft(s64[0] + 1j * s64[1], axis=-1)
    gotp = np.asarray(take(jax.jit(fft1)(sticks0)))
    got = gotp[0] + 1j * gotp[1]
    rel = np.linalg.norm(got - ref) / np.linalg.norm(ref)
    print(f"four-step fft rel err vs numpy: {rel:.2e}", flush=True)

    timeit("xla ifft minor axis (grid)",
           lambda g: jnp.fft.ifft(g, axis=-1), g0, cal_g)
    timeit("xla ifft axis=-2 (grid)",
           lambda g: jnp.fft.ifft(g, axis=-2), g0, cal_g)
    timeit("swapaxes(-1,-2) copy",
           lambda g: jnp.swapaxes(g, -1, -2), g0, cal_g)
    timeit("xla ifft2 (grid)",
           lambda g: jnp.fft.ifft2(g, axes=(-2, -1)), g0, cal_g)
    timeit("xla ifft2+fft2 chain",
           lambda g: jnp.fft.fft2(jnp.fft.ifft2(g, axes=(-2, -1)),
                                  axes=(-2, -1)), g0, cal_g)
    timeit("fourstep ifft minor (grid)", ifft1, g0, cal_g)
    timeit("fourstep ifft2 = minor+swap+minor+swap",
           lambda g: jnp.swapaxes(ifft1(jnp.swapaxes(ifft1(g), -1, -2)),
                                  -1, -2), g0, cal_g)
    timeit("fourstep pair chain (ifft2 then fft2)",
           lambda g: jnp.swapaxes(
               fft1(jnp.swapaxes(
                   fft1(jnp.swapaxes(
                       ifft1(jnp.swapaxes(ifft1(g), -1, -2)), -1, -2)
                   ), -1, -2)), -1, -2),
           g0, cal_g)
    # round trip leaving the middle in swapped layout (saves 2 transposes:
    # ifft_x, swap, ifft_y -> space in (z,x,y) -> fft_y, swap, fft_x)
    timeit("fourstep pair chain, swapped-middle",
           lambda g: fft1(jnp.swapaxes(
               fft1(ifft1(jnp.swapaxes(ifft1(g), -1, -2))), -1, -2)),
           g0, cal_g)
    timeit("xla z ifft (sticks)",
           lambda s: jnp.fft.ifft(s, axis=-1), sticks0, cal_s)
    timeit("fourstep z ifft (sticks)", ifft1, sticks0, cal_s)


if __name__ == "__main__":
    print(f"devices: {jax.devices()}", flush=True)
    main(int(os.environ.get("DIM", "256")))

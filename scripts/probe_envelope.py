#!/usr/bin/env python
"""Probe the single-chip grid-size envelope: build/compile/run one pair at a
given dim with per-step progress prints, so a stall is attributable to a
specific step (plan build, table transfer, compile, execute)."""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 320
    use_pallas = None if "--no-pallas" not in sys.argv else False
    stage = "pair"
    for a in sys.argv[2:]:
        if a.startswith("--stage="):
            stage = a.split("=", 1)[1]
    import jax
    from spfft_tpu import TransformType, make_local_plan
    from spfft_tpu.utils.workloads import spherical_cutoff_triplets

    t = time.perf_counter()

    def mark(msg):
        nonlocal t
        now = time.perf_counter()
        print(f"[{now - t:8.2f}s] {msg}", flush=True)
        t = now

    print(f"devices: {jax.devices()}", flush=True)
    triplets = spherical_cutoff_triplets(n)
    mark(f"triplets built: {len(triplets)} values")
    rng = np.random.default_rng(42)
    values = (rng.uniform(-1, 1, len(triplets))
              + 1j * rng.uniform(-1, 1, len(triplets))).astype(np.complex64)
    mark("values built")

    plan = make_local_plan(TransformType.C2C, n, n, n, triplets,
                           precision="single", use_pallas=use_pallas)
    mark(f"plan built (pallas_active={plan._pallas_active}, "
         f"split_x={plan._split_x})")

    # the plan's own coercion produces the correct boundary layout
    values_il = jax.device_put(plan._coerce_values(values))
    values_il.block_until_ready()
    mark("values on device")

    for name, table in plan._tables.items():
        for leaf in jax.tree_util.tree_leaves(table):
            leaf.block_until_ready()
    mark("tables on device")

    def sync_one(out):
        # index a single element WITHOUT ravel: a device-side ravel of a
        # trailing-2 array launches a standalone relayout that tiles the
        # minor dim 2 -> 128 (64x memory; OOM at 512^3)
        first = out[(0,) * (out.ndim - 1)][:1]
        return float(np.asarray(first).ravel()[0])

    if stage == "pair":
        run = lambda: plan.apply_pointwise(values_il)
    elif stage == "backward":
        run = lambda: plan.backward(values_il)
    elif stage == "forward":
        space = plan.backward(values_il)
        sync_one(space)
        mark("backward done (forward-stage setup)")
        run = lambda: plan.forward(space)
    else:
        raise SystemExit(f"unknown stage {stage}")
    out = run()
    sync_one(out)
    mark(f"{stage} compiled + first run")

    reps = 5
    t0 = time.perf_counter()
    for _ in range(reps):
        out = run()
    sync_one(out)
    mark(f"{stage} x{reps}: "
         f"{(time.perf_counter() - t0) / reps * 1e3:.2f} ms each")


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Probe: Mosaic support + cost for the round-3 "wide" gather kernel design.

Questions:
  1. Can a kernel read a (kp, 128) sub-window of VMEM scratch at a TRACED
     sublane offset (``sc[slot, chan, pl.ds(sub, kp)]``)? Aligned (multiple
     of 8) and unaligned variants.
  2. What is the per-grid-step cost of the wide structure (P tiles/step,
     one K-row DMA, P * kp select rows) vs the narrow kernel's measured
     ~450-500 ns/step?

Run on the TPU: ``python scripts/probe_wide_kernel.py``.
"""
import functools
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def probe_dynamic_slice(aligned: bool):
    """Tiny kernel: out[i] = win[sub + i] for a traced sub read from SMEM."""
    K, kp = 64, 16

    def kernel(sub_ref, x_ref, o_ref):
        sub = sub_ref[0]
        o_ref[...] = x_ref[pl.ds(sub, kp), :]

    x = jnp.arange(K * 128, dtype=jnp.float32).reshape(K, 128)
    sub = jnp.array([8 if aligned else 5], jnp.int32)
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(1,),
            in_specs=[pl.BlockSpec((K, 128), lambda g, s: (0, 0))],
            out_specs=pl.BlockSpec((kp, 128), lambda g, s: (0, 0)),
        ),
        out_shape=jax.ShapeDtypeStruct((kp, 128), jnp.float32),
    )(sub, x)
    want = np.asarray(x)[int(sub[0]):int(sub[0]) + kp]
    ok = np.array_equal(np.asarray(out), want)
    return ok


def probe_dynamic_row(aligned: bool):
    """Per-row variant: read single rows at traced offsets."""
    K, kp = 64, 16

    def kernel(sub_ref, x_ref, o_ref):
        sub = sub_ref[0]
        acc = jnp.zeros((kp, 128), jnp.float32)
        for k in range(kp):
            acc = acc.at[k].set(x_ref[sub + k, :])
        o_ref[...] = acc

    x = jnp.arange(K * 128, dtype=jnp.float32).reshape(K, 128)
    sub = jnp.array([8 if aligned else 5], jnp.int32)
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(1,),
            in_specs=[pl.BlockSpec((K, 128), lambda g, s: (0, 0))],
            out_specs=pl.BlockSpec((kp, 128), lambda g, s: (0, 0)),
        ),
        out_shape=jax.ShapeDtypeStruct((kp, 128), jnp.float32),
    )(sub, x)
    want = np.asarray(x)[int(sub[0]):int(sub[0]) + kp]
    return np.array_equal(np.asarray(out), want)


def time_step_structure(P: int, kp: int, K: int, C: int, reps: int = 20):
    """A skeleton of the wide kernel: C grid steps, each DMAs K rows from
    HBM, does P * kp select-gather rows, accumulates into P output tiles.
    Tables are trivial (identity-ish) — measures structure cost only."""

    TILE_SUB, TILE_LANE = 8, 128

    def kernel(row0_ref, sub_ref, packed_ref, re_hbm, o_ref, sc, sem):
        g = pl.program_id(0)
        n_g = pl.num_programs(0)

        def dma(gg, slot):
            return pltpu.make_async_copy(
                re_hbm.at[pl.ds(row0_ref[gg], K), :], sc.at[slot],
                sem.at[slot])

        @pl.when(g == 0)
        def _():
            dma(0, 0).start()

        @pl.when(g + 1 < n_g)
        def _():
            dma(g + 1, jax.lax.rem(g + 1, jnp.int32(2))).start()

        slot = jax.lax.rem(g, jnp.int32(2))
        dma(g, slot).wait()

        for p in range(P):
            word = sub_ref[g, p // 4]
            sub = (word >> (8 * (p % 4))) & 0xFF
            t = packed_ref[0, p]
            lane = t & 127
            row = (t >> 7) & 0x1FFF
            m = (t >> 20).astype(jnp.float32)
            acc = jnp.zeros((TILE_SUB, TILE_LANE), jnp.float32)
            win = sc[slot, pl.ds(sub, kp), :]
            for k in range(kp):
                sel = row == k
                src = jnp.broadcast_to(win[k][None, :],
                                       (TILE_SUB, TILE_LANE))
                acc += jnp.where(sel, jnp.take_along_axis(src, lane, axis=1),
                                 0)
            o_ref[p] = acc * m

    rng = np.random.default_rng(0)
    src_rows = C + K + 8
    re = jnp.asarray(rng.standard_normal((src_rows, 128)), jnp.float32)
    row0 = jnp.asarray(np.arange(C, dtype=np.int32))
    sub = jnp.asarray(rng.integers(0, min(8, K - kp), (C, 2)).astype(np.int32))
    packed = jnp.asarray(
        (rng.integers(0, 128, (C, P, 8, 128))
         | (rng.integers(0, kp, (C, P, 8, 128)) << 7)
         | (1 << 20)).astype(np.int32))

    f = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(C,),
            in_specs=[
                pl.BlockSpec((1, P, 8, 128), lambda g, r0, s: (g, 0, 0, 0)),
                pl.BlockSpec(memory_space=pltpu.ANY),
            ],
            out_specs=pl.BlockSpec((P, 8, 128), lambda g, r0, s: (g, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((2, K, 128), jnp.float32),
                pltpu.SemaphoreType.DMA((2,)),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((C * P, 8, 128), jnp.float32),
    )
    # Dispatch through the tunnel costs ~8-10 ms/call: time R scanned
    # kernel steps inside ONE executable, subtract a calibration scan
    # (perturb + consume only), exactly as scripts/profile_stages.py does.
    R = 20

    def scan_seconds(body):
        def run(x0):
            def step(c, _):
                xp = c * jnp.float32(1.0 + 1e-7)
                return xp, jnp.mean(body(xp))
            _, ys = jax.lax.scan(step, x0, None, length=R)
            return ys
        h = jax.jit(run)
        out = h(re)
        float(np.asarray(out.ravel()[0]))
        t0 = time.perf_counter()
        for _ in range(3):
            out = h(re)
        float(np.asarray(out.ravel()[0]))
        return (time.perf_counter() - t0) / 3

    calib = scan_seconds(lambda xp: xp)
    total = scan_seconds(lambda xp: f(row0, sub, packed, xp))
    dt = (total - calib) / R
    return dt, dt / C


if __name__ == "__main__":
    for name, fn in (("dyn-slice aligned", lambda: probe_dynamic_slice(True)),
                     ("dyn-slice unaligned",
                      lambda: probe_dynamic_slice(False)),
                     ("dyn-row aligned", lambda: probe_dynamic_row(True)),
                     ("dyn-row unaligned", lambda: probe_dynamic_row(False))):
        try:
            ok = fn()
            print(f"{name}: {'OK' if ok else 'WRONG RESULT'}")
        except Exception as e:
            print(f"{name}: FAIL — {type(e).__name__}: {str(e)[:300]}")

    for P, kp, K, C in ((8, 16, 80, 1600), (8, 10, 80, 1600),
                        (16, 10, 160, 800), (4, 10, 48, 3200),
                        (8, 16, 80, 100)):
        try:
            dt, per = time_step_structure(P, kp, K, C)
            print(f"P={P} kp={kp} K={K} C={C}: total {dt*1e3:.3f} ms, "
                  f"{per*1e9:.0f} ns/step, "
                  f"{C*P*1024/dt/1e9:.2f} Gslot/s")
        except Exception as e:
            print(f"P={P} kp={kp} K={K} C={C}: FAIL — "
                  f"{type(e).__name__}: {str(e)[:300]}")

#!/usr/bin/env python
"""Round-5 probe: per-stage scan-timed breakdown of the ACTUAL matmul-DFT
planar pipeline (profile_stages.py times the legacy jnp.fft stage set, so
its numbers don't localise the mdft pair's cost).

Stages mirror plan._backward_rest_tp / _forward_head_tp exactly, on planar
carriers. Usage: DIM=256 python scripts/probe_r5_mdft_stages.py
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

from spfft_tpu import TransformType, make_local_plan
from spfft_tpu.ops import dft, stages
from spfft_tpu.utils.workloads import spherical_cutoff_triplets

R = int(os.environ.get("REPS", 20))
C64 = 8


def sync(out):
    leaf = jax.tree_util.tree_leaves(out)[0]
    float(np.asarray(jax.numpy.real(leaf).ravel()[0]))


def _perturb(t):
    if isinstance(t, tuple):
        return tuple(x * x.dtype.type(1.0 + 1e-7) for x in t)
    return t * t.dtype.type(1.0 + 1e-7)


def _consume(y):
    leaves = jax.tree_util.tree_leaves(y)
    return sum(jnp.mean(jnp.real(x)) + (jnp.mean(jnp.imag(x))
               if jnp.iscomplexobj(x) else 0.0) for x in leaves)


def _scan_seconds(body, x, reps=3):
    def run(x0):
        def step(c, _):
            xp = _perturb(c)
            return xp, _consume(body(xp))
        _, ys = jax.lax.scan(step, x0, None, length=R)
        return ys
    f = jax.jit(run)
    out = f(x)
    sync(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = f(x)
    sync(out)
    return (time.perf_counter() - t0) / reps


def main():
    n = int(os.environ.get("DIM", "256"))
    print(f"devices: {jax.devices()}", flush=True)
    triplets = spherical_cutoff_triplets(n)
    plan = make_local_plan(TransformType.C2C, n, n, n, triplets,
                           precision="single")
    p = plan.index_plan
    assert plan._use_mdft and plan._pallas_active
    tables = plan._tables
    rng = np.random.default_rng(0)
    N = p.num_values
    values = (rng.uniform(-1, 1, N)
              + 1j * rng.uniform(-1, 1, N)).astype(np.complex64)
    vil = jax.device_put(plan._coerce_values(values))

    S, Z = plan._s_pad, p.dim_z
    xf = p.dim_x_freq
    if plan._split_x is not None:
        x0w, w = plan._split_x
        col_tab = tables["col_inv_sub_t"]
        cols_tab = tables["scatter_cols_sub_t"]
        rows = tuple(int(r) for r in (x0w + np.arange(w)) % xf)
        cols = rows
    else:
        w = xf
        col_tab = tables["col_inv_t"]
        cols_tab = tables["scatter_cols_t"]
        rows = None
    print(f"n={n} N={N} sticks={p.num_sticks} s_pad={S} split_x={plan._split_x}",
          flush=True)

    unpack = stages.sticks_to_grid_padded if S > p.num_sticks \
        else stages.sticks_to_grid

    # carriers
    sticks_p = jax.jit(lambda v: plan._decompress_planar(v, tables))(vil)
    grid_p = jax.jit(lambda sp: (unpack(sp[0], col_tab, w, p.dim_y),
                                 unpack(sp[1], col_tab, w, p.dim_y)))(sticks_p)
    swapped = jax.jit(lambda gp: (jnp.swapaxes(gp[0], -1, -2),
                                  jnp.swapaxes(gp[1], -1, -2)))(grid_p)

    cal_v = _scan_seconds(lambda x: x, vil)
    cal_s = _scan_seconds(lambda x: x, sticks_p)
    cal_g = _scan_seconds(lambda x: x, grid_p)
    cal_w = _scan_seconds(lambda x: x, swapped)

    G = Z * w * p.dim_y

    def stage(name, body, x, cal, nbytes):
        t = _scan_seconds(body, x)
        dt = (t - cal) / R
        noise = 0.15 * cal / R
        flag = " (below noise)" if dt < noise else ""
        gbs = nbytes / max(dt, 1e-9) / 1e9
        print(f"{name:26s} {dt*1e3:8.3f} ms  {gbs:7.1f} GB/s{flag}",
              flush=True)
        return max(dt, 0.0)

    tot = 0.0
    tot += stage("decompress_planar",
                 lambda v: plan._decompress_planar(v, tables), vil, cal_v,
                 (N + S * Z) * C64)
    zb = dft.c2c_mats(Z, dft.BACKWARD)
    tot += stage("z pdft bwd",
                 lambda sp: dft.pdft_last(sp[0], sp[1], zb),
                 sticks_p, cal_s, 2 * S * Z * C64)
    tot += stage("unpack (sticks->grid)",
                 lambda sp: (unpack(sp[0], col_tab, w, p.dim_y),
                             unpack(sp[1], col_tab, w, p.dim_y)),
                 sticks_p, cal_s, (S * Z + G) * C64)
    yb = dft.c2c_mats(p.dim_y, dft.BACKWARD)
    tot += stage("y pdft bwd",
                 lambda gp: dft.pdft_last(gp[0], gp[1], yb),
                 grid_p, cal_g, 2 * G * C64)
    tot += stage("swap",
                 lambda gp: (jnp.swapaxes(gp[0], -1, -2),
                             jnp.swapaxes(gp[1], -1, -2)),
                 grid_p, cal_g, 2 * G * C64)
    xmats = dft.c2c_mats(p.dim_x, dft.BACKWARD) if rows is None \
        else dft.sub_rows_mats(p.dim_x, dft.BACKWARD, rows)
    tot += stage("x pdft bwd",
                 lambda gp: dft.pdft_last(gp[0], gp[1], xmats),
                 swapped, cal_w,
                 (G + Z * p.dim_y * p.dim_x) * C64)
    # forward
    space = jax.jit(lambda gp: dft.pdft_last(gp[0], gp[1], xmats))(swapped)
    cal_sp = _scan_seconds(lambda x: x, space)
    xf_mats = dft.c2c_mats(p.dim_x, dft.FORWARD) if rows is None \
        else dft.sub_cols_mats(p.dim_x, dft.FORWARD, cols)
    tot += stage("x pdft fwd",
                 lambda sp: dft.pdft_last(sp[0], sp[1], xf_mats),
                 space, cal_sp, (Z * p.dim_y * p.dim_x + G) * C64)
    tot += stage("swap (fwd)",
                 lambda gp: (jnp.swapaxes(gp[0], -1, -2),
                             jnp.swapaxes(gp[1], -1, -2)),
                 grid_p, cal_g, 2 * G * C64)
    yf = dft.c2c_mats(p.dim_y, dft.FORWARD)
    tot += stage("y pdft fwd",
                 lambda gp: dft.pdft_last(gp[0], gp[1], yf),
                 grid_p, cal_g, 2 * G * C64)
    tot += stage("pack (grid->sticks)",
                 lambda gp: (stages.grid_to_sticks(gp[0], cols_tab),
                             stages.grid_to_sticks(gp[1], cols_tab)),
                 grid_p, cal_g, (G + S * Z) * C64)
    zf = dft.c2c_mats(Z, dft.FORWARD)
    tot += stage("z pdft fwd",
                 lambda sp: dft.pdft_last(sp[0], sp[1], zf),
                 sticks_p, cal_s, 2 * S * Z * C64)
    tot += stage("compress_planar",
                 lambda sp: plan._compress_planar(sp[0], sp[1], tables),
                 sticks_p, cal_s, (S * Z + N) * C64)
    print(f"{'sum of stages':26s} {tot*1e3:8.2f} ms", flush=True)

    pair = _scan_seconds(
        lambda v: plan._pair_impl(v, tables, scaled=False, fn=None), vil, 3)
    print(f"{'FULL fused pair':26s} {(pair - cal_v) / R * 1e3:8.3f} ms",
          flush=True)


if __name__ == "__main__":
    main()

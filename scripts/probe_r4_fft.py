#!/usr/bin/env python
"""Round-4 probe: why do the xy FFTs cost 2.4+1.9 ms in the fused pair but
1.1+0.85 isolated? Tries optimization-barrier placements and FFT
decompositions on the 256^3 pair. Uses min-of-reps (the tunnel can stall
for seconds mid-measurement; see the 417 ms artifact in probe_r4_layout).

Usage: DIM=256 python scripts/probe_r4_fft.py
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

from spfft_tpu import TransformType, make_local_plan
from spfft_tpu.ops import stages
from spfft_tpu.ops import gather_kernel as gk
from spfft_tpu.utils.workloads import spherical_cutoff_triplets

R = int(os.environ.get("REPS", 20))
BAR = jax.lax.optimization_barrier


def sync(out):
    leaf = jax.tree_util.tree_leaves(out)[0]
    float(np.asarray(jax.numpy.real(leaf).ravel()[0]))


def _perturb(x):
    return jax.tree_util.tree_map(lambda v: v * v.dtype.type(1.0 + 1e-7), x)


def _consume(y):
    leaves = jax.tree_util.tree_leaves(y)
    tot = 0.0
    for leaf in leaves:
        if jnp.iscomplexobj(leaf):
            tot = tot + jnp.mean(jnp.real(leaf)) + jnp.mean(jnp.imag(leaf))
        else:
            tot = tot + jnp.mean(leaf)
    return tot


def _scan_seconds(body, x, reps=4):
    def run(x0):
        def step(c, _):
            xp = _perturb(c)
            return xp, _consume(body(xp))
        _, ys = jax.lax.scan(step, x0, None, length=R)
        return ys
    f = jax.jit(run)
    out = f(x)
    sync(out)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = f(x)
        sync(out)
        best = min(best, time.perf_counter() - t0)
    return best


def timeit(name, body, x, calib_s):
    total = _scan_seconds(body, x)
    dt = (total - calib_s) / R
    print(f"{name:52s} {dt*1e3:8.3f} ms", flush=True)
    return dt


def main(n: int):
    triplets = spherical_cutoff_triplets(n)
    plan = make_local_plan(TransformType.C2C, n, n, n, triplets,
                           precision="single")
    p = plan.index_plan
    N, S, Z = p.num_values, p.num_sticks, p.dim_z
    dec_t = plan._pallas["dec"]
    cmp_t = plan._pallas["cmp"]
    tables = plan._tables
    print(f"== dim={n} values={N} sticks={S} R={R} min-of-reps ==",
          flush=True)

    rng = np.random.default_rng(0)
    values = (rng.uniform(-1, 1, N)
              + 1j * rng.uniform(-1, 1, N)).astype(np.complex64)
    values_il = jax.device_put(plan._coerce_values(values))
    cal_il = _scan_seconds(lambda v: v, values_il)
    print(f"calib {cal_il/R*1e3:.3f} ms/step", flush=True)

    def dec(v):
        return plan._decompress(v, tables)

    def cmp_(s):
        return plan._compress(s, tables, None)

    def unpack(s):
        return stages.sticks_to_grid(s, tables["col_inv"], p.dim_y,
                                     p.dim_x_freq)

    def pack(g):
        return stages.grid_to_sticks(g, tables["scatter_cols"])

    scale = np.float32(n * n)

    def pair(v, *, bar_pre=False, bar_post=False, split1d=False,
             bar_unpack=False):
        s = stages.z_backward(dec(v))
        g = unpack(s)
        if bar_unpack:
            g = BAR(g)
        # xy backward
        if split1d:
            g = jnp.fft.ifft(BAR(g) if bar_pre else g, axis=-2)
            g = jnp.fft.ifft(BAR(g) if bar_pre else g, axis=-1) * scale
        else:
            g = jnp.fft.ifft2(BAR(g) if bar_pre else g,
                              axes=(-2, -1)) * scale
        if bar_post:
            g = BAR(g)
        # xy forward
        if split1d:
            g = jnp.fft.fft(BAR(g) if bar_pre else g, axis=-1)
            g = jnp.fft.fft(BAR(g) if bar_pre else g, axis=-2)
        else:
            g = jnp.fft.fft2(BAR(g) if bar_pre else g, axes=(-2, -1))
        if bar_post:
            g = BAR(g)
        return cmp_(stages.z_forward(pack(g)))

    import functools
    timeit("pair base (no barriers at 256^3)",
           functools.partial(pair), values_il, cal_il)
    timeit("pair bar before xy FFT operands",
           functools.partial(pair, bar_pre=True), values_il, cal_il)
    timeit("pair bar after unpack only",
           functools.partial(pair, bar_unpack=True), values_il, cal_il)
    timeit("pair bar pre+post xy FFTs",
           functools.partial(pair, bar_pre=True, bar_post=True),
           values_il, cal_il)
    timeit("pair xy as 1D ffts (no bar)",
           functools.partial(pair, split1d=True), values_il, cal_il)
    timeit("pair xy as 1D ffts + bar_pre",
           functools.partial(pair, split1d=True, bar_pre=True),
           values_il, cal_il)

    # isolated xy ffts on a materialised grid, for reference
    grid0 = jax.jit(lambda v: unpack(stages.z_backward(dec(v))))(values_il)
    cal_g = _scan_seconds(lambda g: g, grid0)
    timeit("isolated ifft2 (materialised operand)",
           lambda g: jnp.fft.ifft2(g, axes=(-2, -1)) * scale, grid0, cal_g)
    timeit("isolated ifft2+fft2 chain",
           lambda g: jnp.fft.fft2(jnp.fft.ifft2(g, axes=(-2, -1)) * scale,
                                  axes=(-2, -1)), grid0, cal_g)
    timeit("isolated ifft2+fft2 chain, bar between",
           lambda g: jnp.fft.fft2(BAR(jnp.fft.ifft2(g, axes=(-2, -1))
                                      * scale), axes=(-2, -1)),
           grid0, cal_g)


if __name__ == "__main__":
    print(f"devices: {jax.devices()}", flush=True)
    main(int(os.environ.get("DIM", "256")))

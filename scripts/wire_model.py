#!/usr/bin/env python
"""Wire-bytes model at scale: padded all_to_all vs the exact-count compact
schedule for the 256^3 spherical-cutoff workload over many shards (plan-time
computation only — no devices needed; the distributed analogue runs on a pod).

Two metrics per layout: TOTAL off-shard bytes (aggregate ICI traffic,
summed over shards) and the BUSIEST LINK (max over shards of
max(sent, received) — the bottleneck; a shard owning most of the slab
receives that payload under any exact layout, so plane-skew savings show
up in the aggregate, not here). The padded layout ships
(S-1) * max_sticks * max_planes complex elements per shard regardless of
distribution; the compact schedule's size-classed exact ops track the true
per-pair Alltoallv counts (reference
transpose_mpi_compact_buffered_host.cpp:83-105)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from spfft_tpu.parallel.dist import build_distributed_plan
from spfft_tpu.parallel.exchange import build_compact_schedule
from spfft_tpu.types import TransformType
from spfft_tpu.utils.workloads import (even_plane_split,
                                       round_robin_stick_partition,
                                       spherical_cutoff_triplets)


def skewed_plane_split(dim_z, S):
    """First shard owns half the planes, the rest split evenly — the skewed
    slab layout of a DFT code mixing a dense rank with light ranks."""
    first = dim_z // 2
    rest = even_plane_split(dim_z - first, S - 1)
    return [first] + rest


def model(n, S, skew):
    triplets = spherical_cutoff_triplets(n)
    parts = round_robin_stick_partition(triplets, (n, n, n), S)
    planes = skewed_plane_split(n, S) if skew else even_plane_split(n, S)
    dp = build_distributed_plan(TransformType.C2C, n, n, n, parts, planes)
    sched = build_compact_schedule(dp)
    pad_total = S * (S - 1) * dp.max_sticks * dp.max_planes * 8
    pad_link = (S - 1) * dp.max_sticks * dp.max_planes * 8
    c_total = sched.wire_elements() * 8
    c_link = sched.busiest_link_elements() * 8
    name = "skewed-planes" if skew else "uniform"
    print(f"| {n}^3, S={S:2d}, {name:13s} | {pad_total/1e6:9.2f} "
          f"| {c_total/1e6:9.2f} | {100*(1-c_total/max(pad_total,1)):5.1f}% "
          f"| {pad_link/1e6:8.2f} | {c_link/1e6:8.2f} |",
          flush=True)


if __name__ == "__main__":
    print("| workload | padded total MB | compact total MB | saved "
          "| padded link MB | compact link MB |")
    print("|---|---|---|---|---|---|")
    for n in (128, 256):
        for S in (8, 32):
            for skew in (False, True):
                model(n, S, skew)

#!/usr/bin/env python
"""Wide vs narrow gather kernel on the real chip, at north-star scale.

Builds the 256^3 spherical-cutoff compression inputs (decompress and
compress directions), runs both kernels, checks results against the XLA
gather, and times each with the scanned-executable methodology
(scripts/profile_stages.py). DIM=256 by default.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

from spfft_tpu.ops import gather_kernel as gk
from spfft_tpu.indexing import build_index_plan
from spfft_tpu.types import TransformType
from spfft_tpu.utils.workloads import spherical_cutoff_triplets

R = int(os.environ.get("REPS", 20))


def sync(x):
    float(np.asarray(jnp.real(jax.tree_util.tree_leaves(x)[0]).ravel()[0]))


def scan_seconds(body, x, reps=3):
    def run(x0):
        def step(c, _):
            xp = jax.tree_util.tree_map(
                lambda a: a * a.dtype.type(1.0 + 1e-7), c)
            out = body(xp)
            return xp, sum(jnp.mean(o) for o in jax.tree_util.tree_leaves(out))
        _, ys = jax.lax.scan(step, x0, None, length=R)
        return ys
    f = jax.jit(run)
    out = f(x)
    sync(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = f(x)
    sync(out)
    return (time.perf_counter() - t0) / reps


def bench_direction(name, idx, valid, num_src):
    rng = np.random.default_rng(1)
    src = rng.standard_normal(num_src).astype(np.float32)
    srci = rng.standard_normal(num_src).astype(np.float32)

    wide = gk.build_wide_gather_tables(idx, valid, num_src)
    narrow = gk.build_monotone_gather_tables(idx, valid, num_src)
    want = np.where(valid, src[np.clip(idx, 0, num_src - 1)], 0)

    for label, t in (("wide", wide), ("narrow", narrow)):
        if t is None:
            print(f"{name} {label}: tables=None")
            continue
        dev = gk.gather_device_tables(t)
        pad = t.src_rows * 128 - num_src
        re = jnp.asarray(np.pad(src, (0, pad)).reshape(t.src_rows, 128))
        im = jnp.asarray(np.pad(srci, (0, pad)).reshape(t.src_rows, 128))

        out = gk.run_gather(re, im, dev, t)
        got = np.asarray(out[0]).reshape(-1)[:t.num_out]
        ok = np.allclose(got, want, atol=1e-5)
        C = t.row0.shape[0]
        cal = scan_seconds(lambda x: (x[0], x[1]), (re, im))
        tot = scan_seconds(lambda x: gk.run_gather(x[0], x[1], dev, t),
                           (re, im))
        dt = (tot - cal) / R
        extra = (f"kp={t.kp_rows} " if isinstance(t, gk.WideGatherTables)
                 else "")
        print(f"{name} {label}: {'OK' if ok else 'MISMATCH'} C={C} "
              f"K={t.span_rows} {extra}-> {dt*1e3:.3f} ms "
              f"({dt/C*1e9:.0f} ns/step)", flush=True)


def main():
    n = int(os.environ.get("DIM", "256"))
    triplets = spherical_cutoff_triplets(n)
    p = build_index_plan(TransformType.C2C, n, n, n, triplets)
    vi = p.value_indices.astype(np.int64)
    num_slots = p.num_sticks * p.dim_z
    print(f"dim={n} values={p.num_values} slots={num_slots}", flush=True)
    (dec_idx, occ), (cmp_idx, cmp_valid) = gk.compression_gather_inputs(
        vi, num_slots)
    bench_direction("decompress", dec_idx, occ, p.num_values)
    bench_direction("compress", cmp_idx, cmp_valid, num_slots)


if __name__ == "__main__":
    print("devices:", jax.devices(), flush=True)
    main()

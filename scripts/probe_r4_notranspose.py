#!/usr/bin/env python
"""Round-4 probe: can the grid stages drop the pack/unpack transposes?

Candidate pipeline (timing-faithful, tables reused where layouts allow):
  backward: dec -> z-ifft (minor) -> unpack WITHOUT .T: (Y, XF, Z)
            -> y-DFT as axis-0 GEMM 'ky,y(xz)' -> (KY, XF, Z)
            -> transpose to (XF, KY, Z)
            -> x-DFT as axis-0 GEMM -> space (X, Y, Z)   [reversed]
  forward:  x-DFT axis-0 -> (KX, Y, Z) -> transpose (Y, KX, Z)
            -> y-DFT axis-0 -> (KY, KX, Z) -> reshape (cols, Z)
            -> pack row gather (no .T) -> z-fft -> cmp
vs the current T-layout pipeline. Identity-pair timing only (values are
numerically wrong where tables assume other layouts — cost-faithful).
"""
import os
import sys
import time
import functools

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

from spfft_tpu import TransformType, make_local_plan
from spfft_tpu.ops import dft
from spfft_tpu.utils.benchtime import diff_estimate_seconds
from spfft_tpu.utils.workloads import spherical_cutoff_triplets


def main(n: int):
    triplets = spherical_cutoff_triplets(n)
    plan = make_local_plan(TransformType.C2C, n, n, n, triplets,
                           precision="single")
    plan._finalize()
    p = plan.index_plan
    N = p.num_values
    tables = plan._tables  # full set (col_inv y-major + T tables)
    rng = np.random.default_rng(0)
    values = (rng.uniform(-1, 1, N)
              + 1j * rng.uniform(-1, 1, N)).astype(np.complex64)
    vil = jax.device_put(plan._coerce_values(values))

    def sync(a):
        return float(np.asarray(a.ravel()[0]))

    def timed_ms(fn, *args):
        o = fn(*args); sync(o)
        def grp(g):
            t0 = time.perf_counter(); o = None
            for _ in range(g):
                o = fn(*args)
            sync(o)
            return time.perf_counter() - t0
        return diff_estimate_seconds(grp, reps=20).seconds * 1e3

    # current pipeline
    cur = jax.jit(functools.partial(plan._pair_impl, scaled=False, fn=None))
    print(f"current planar T pair:      "
          f"{timed_ms(cur, vil, plan._tables_hot):8.3f} ms", flush=True)

    S_pad, Z, Y, XF = plan._s_pad, p.dim_z, p.dim_y, p.dim_x_freq
    mats_b = dft.c2c_mats(n, dft.BACKWARD)
    mats_f = dft.c2c_mats(n, dft.FORWARD)

    def gemm0(mats, g):
        """axis-0 contraction: (K, d0) x (d0, rest) as one GEMM, planar
        Karatsuba like pdft_last."""
        cr, ci, cs = mats
        sh = g[0].shape
        flat_r = g[0].reshape(sh[0], -1)
        flat_i = g[1].reshape(sh[0], -1)
        dot = lambda c, x: jax.lax.dot_general(
            jnp.asarray(c), x, (((1,), (0,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST)
        p1 = dot(cr, flat_r)
        p2 = dot(ci, flat_i)
        p3 = dot(cs, flat_r + flat_i)
        out_shape = (cr.shape[0],) + sh[1:]
        return ((p1 - p2).reshape(out_shape),
                (p3 - p1 - p2).reshape(out_shape))

    col_inv = np.asarray(tables["col_inv"])          # y-major (Y*XF,)
    col_inv_dev = jnp.asarray(col_inv)
    scat = jnp.asarray(np.asarray(tables["scatter_cols"]))  # (S_pad,)

    def pair_nt(v):
        sr, si = plan._decompress_planar(v, tables)
        sr, si = dft.pdft_last(sr, si, dft.c2c_mats(Z, dft.BACKWARD))
        gr = sr[col_inv_dev].reshape(Y, XF, Z)   # unpack, NO transpose
        gi = si[col_inv_dev].reshape(Y, XF, Z)
        gr, gi = gemm0(mats_b, (gr, gi))          # y-DFT axis-0
        gr = jnp.swapaxes(gr, 0, 1)               # (XF, KY, Z)
        gi = jnp.swapaxes(gi, 0, 1)
        gr, gi = gemm0(mats_b, (gr, gi))          # x-DFT -> space (X,Y,Z)
        # forward
        gr, gi = gemm0(mats_f, (gr, gi))          # (KX, Y, Z)
        gr = jnp.swapaxes(gr, 0, 1)               # (Y, KX, Z)
        gi = jnp.swapaxes(gi, 0, 1)
        gr, gi = gemm0(mats_f, (gr, gi))          # (KY, KX, Z)
        fr = gr.reshape(Y * XF, Z)[scat]          # pack row gather, no .T
        fi = gi.reshape(Y * XF, Z)[scat]
        fr, fi = dft.pdft_last(fr, fi, dft.c2c_mats(Z, dft.FORWARD))
        return plan._compress_planar(fr, fi, tables)

    f = jax.jit(pair_nt)
    print(f"no-pack-transpose pair:     {timed_ms(f, vil):8.3f} ms",
          flush=True)


if __name__ == "__main__":
    print(f"devices: {jax.devices()}", flush=True)
    main(int(os.environ.get("DIM", "256")))

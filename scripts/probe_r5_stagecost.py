#!/usr/bin/env python
"""Round-5 probe: fused sub-pipeline costs after the DFT-stage kernels.

Times each growing sub-pipeline of the 256^3 backward and forward as
its own jitted executable with the shared estimator (no scan carrier —
the prefix probe's identity-scan baseline measured 5.6 ms/step of pure
carrier cost and +-1 ms rescheduling noise). Differences between rows
are the marginal fused cost of each stage in a dispatch context close
to the real pair.

Usage: DIM=256 python scripts/probe_r5_stagecost.py
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

from spfft_tpu import TransformType, make_local_plan
from spfft_tpu.ops import dft, stages
from spfft_tpu.utils.benchtime import diff_estimate_seconds
from spfft_tpu.utils.workloads import spherical_cutoff_triplets

DIM = int(os.environ.get("DIM", 256))
REPS = int(os.environ.get("REPS", 16))


def sync(out):
    leaf = jax.tree_util.tree_leaves(out)[0]
    return float(np.asarray(jnp.real(leaf).ravel()[0]))


def measure(f, *args):
    g = jax.jit(f)
    sync(g(*args))

    def grp(k):
        t0 = time.perf_counter()
        o = None
        for _ in range(k):
            o = g(*args)
        sync(o)
        return time.perf_counter() - t0
    return diff_estimate_seconds(grp, reps=REPS).seconds


def main():
    tri = spherical_cutoff_triplets(DIM)
    rng = np.random.default_rng(7)
    n = len(tri)
    vals = (rng.uniform(-1, 1, n) + 1j * rng.uniform(-1, 1, n)).astype(
        np.complex64)
    plan = make_local_plan(TransformType.C2C, DIM, DIM, DIM, tri)
    tabs = plan._tables_hot
    vil = jax.device_put(plan._coerce_values(vals))
    p = plan.index_plan

    # -- backward sub-pipelines ---------------------------------------
    def bw_dec(v):
        return plan._decompress_planar(v, tabs)

    def bw_z(v):
        sr, si = plan._decompress_planar(v, tabs)
        return dft.pdft_last_opt(sr, si, dft.c2c_mats(p.dim_z, dft.BACKWARD))

    def bw_full(v):
        return plan._backward_impl(v, tabs)

    # -- forward sub-pipelines (on the backward output space) ---------
    space = jax.device_put(jax.jit(bw_full)(vil))

    def fw_head(s):
        sp = (s[..., 0], s[..., 1])
        return plan._forward_head_tp(sp, tabs, None)

    def fw_full(s):
        return plan._forward_impl(s, tabs, scaled=False)

    def pair(v):
        return plan._forward_impl(plan._backward_impl(v, tabs), tabs,
                                  scaled=False)

    rows = [
        ("bw decompress          ", bw_dec, vil),
        ("bw decompress+z        ", bw_z, vil),
        ("bw full                ", bw_full, vil),
        ("fw head (xy+pack+z)    ", fw_head, space),
        ("fw full (head+compress)", fw_full, space),
        ("pair (fused)           ", pair, vil),
    ]
    res = {}
    for name, f, arg in rows:
        t = measure(f, arg)
        res[name] = t
        print(f"{name}: {t*1e3:7.3f} ms", flush=True)

    print(f"\nmarginals: z-bwd {1e3*(res['bw decompress+z        ']-res['bw decompress          ']):+.3f}"
          f"  unpack+xy {1e3*(res['bw full                ']-res['bw decompress+z        ']):+.3f}"
          f"  compress {1e3*(res['fw full (head+compress)']-res['fw head (xy+pack+z)    ']):+.3f}"
          f"  bw+fw-pair {1e3*(res['bw full                ']+res['fw full (head+compress)']-res['pair (fused)           ']):+.3f}",
          flush=True)


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Compile-only experiments: which composition of decompress-gather + 1D FFT
triggers the XLA compile blow-up at large sizes (no device execution)."""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

n = int(sys.argv[1]) if len(sys.argv) > 1 else 320
S = 80379
N = 17155322
SLOTS = S * n


def t(name, fn, *shapes):
    args = [jax.ShapeDtypeStruct(s, d) for s, d in shapes]
    t0 = time.perf_counter()
    jax.jit(fn).lower(*args).compile()
    print(f"{name:50s} {time.perf_counter() - t0:8.2f}s", flush=True)


# a) plain ifft
t("a: ifft (S,n) c64 param",
  lambda x: jnp.fft.ifft(x, axis=-1), ((S, n), jnp.complex64))

# b) complex construction from f32 param, no gather
t("b: f32(SLOTS,2) -> complex -> reshape -> ifft",
  lambda v: jnp.fft.ifft((v[:, 0] + 1j * v[:, 1]).reshape(S, n), axis=-1),
  ((SLOTS, 2), jnp.float32))

# c) gather -> complex -> ifft (the decompress composition)
def c_fn(v, idx):
    zero = jnp.zeros((1, 2), v.dtype)
    flat = jnp.concatenate([v, zero], axis=0)[idx]
    return jnp.fft.ifft((flat[:, 0] + 1j * flat[:, 1]).reshape(S, n),
                        axis=-1)
t("c: gather -> complex -> ifft", c_fn,
  ((N, 2), jnp.float32), ((SLOTS,), jnp.int32))

# d) same with optimization_barrier before the fft
def d_fn(v, idx):
    zero = jnp.zeros((1, 2), v.dtype)
    flat = jnp.concatenate([v, zero], axis=0)[idx]
    sticks = (flat[:, 0] + 1j * flat[:, 1]).reshape(S, n)
    sticks = jax.lax.optimization_barrier(sticks)
    return jnp.fft.ifft(sticks, axis=-1)
t("d: gather -> barrier -> ifft", d_fn,
  ((N, 2), jnp.float32), ((SLOTS,), jnp.int32))

# e) gather feeding an elementwise op instead of fft (control)
def e_fn(v, idx):
    zero = jnp.zeros((1, 2), v.dtype)
    flat = jnp.concatenate([v, zero], axis=0)[idx]
    return (flat[:, 0] + 1j * flat[:, 1]).reshape(S, n) * 2.0
t("e: gather -> complex -> mul (control)", e_fn,
  ((N, 2), jnp.float32), ((SLOTS,), jnp.int32))

#!/usr/bin/env python
"""Round-5 probe: column-blocked single-stage kernel for 384/512 axes.

At 384/512 the compile ceiling forces small row tiles (tm=512/256) and
the fused stage loses to XLA (matrix streaming dominates). A 2D grid
(row tiles x output-column blocks) shrinks the resident matrix slice so
tm can stay large; the input block is constant over the column steps
(Mosaic keeps it resident). Measures compile + time vs the XLA form and
the current 1D kernel.

Usage: python scripts/probe_r5_colblock.py
"""
import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from spfft_tpu.ops import dft, dft_kernel as dk
from spfft_tpu.utils.benchtime import diff_estimate_seconds

_HI = jax.lax.Precision.HIGHEST
_DN = (((1,), (0,)), ((), ()))


def colblock_pdft(xr, xi, mats, tm, mb):
    cr, ci, cs = (jnp.asarray(m) for m in mats)
    k, mo = cr.shape
    m = xr.shape[0]
    return pl.pallas_call(
        dk._stage_kernel,
        grid=(pl.cdiv(m, tm), pl.cdiv(mo, mb)),
        in_specs=[
            pl.BlockSpec((tm, k), lambda i, j: (i, 0)),
            pl.BlockSpec((tm, k), lambda i, j: (i, 0)),
            pl.BlockSpec((k, mb), lambda i, j: (0, j)),
            pl.BlockSpec((k, mb), lambda i, j: (0, j)),
            pl.BlockSpec((k, mb), lambda i, j: (0, j)),
        ],
        out_specs=[
            pl.BlockSpec((tm, mb), lambda i, j: (i, j)),
            pl.BlockSpec((tm, mb), lambda i, j: (i, j)),
        ],
        out_shape=[jax.ShapeDtypeStruct((m, mo), jnp.float32)] * 2,
    )(xr, xi, cr, ci, cs)


def sync(o):
    return float(np.asarray(jnp.real(o[0]).ravel()[0]))


def measure(g, xr, xi, chain=3, reps=14):
    def body(a, b):
        o = g(a, b)
        for _ in range(chain - 1):
            o = g(o[0], o[1])
        return o
    f = jax.jit(body)
    sync(f(xr, xi))

    def grp(kk):
        t0 = time.perf_counter()
        o = None
        for _ in range(kk):
            o = f(xr, xi)
        sync(o)
        return time.perf_counter() - t0
    return diff_estimate_seconds(grp, reps=reps).seconds / chain


def main():
    rng = np.random.default_rng(5)
    for n, m in ((384, 147456), (512, 262144)):
        mats = dft.c2c_mats(n, dft.BACKWARD)
        xr = jnp.asarray(rng.standard_normal((m, n)), jnp.float32)
        xi = jnp.asarray(rng.standard_normal((m, n)), jnp.float32)
        t = measure(lambda a, b, mm=mats: dft.pdft_last(a, b, mm), xr, xi)
        print(f"n={n} XLA stage         : {t*1e3:7.3f} ms", flush=True)
        t = measure(lambda a, b, mm=mats: dk.pdft_last(a, b, mm), xr, xi)
        print(f"n={n} kernel tm={dk._stage_tm(n, n):4d}    : {t*1e3:7.3f} ms",
              flush=True)
        for tm, mb in ((1024, 128), (1024, 256), (2048, 128)):
            try:
                t = measure(lambda a, b, mm=mats, t_=tm, b_=mb:
                            colblock_pdft(a, b, mm, t_, b_), xr, xi)
                print(f"n={n} colblock tm={tm} mb={mb}: {t*1e3:7.3f} ms",
                      flush=True)
            except Exception as e:  # noqa: BLE001
                print(f"n={n} colblock tm={tm} mb={mb}: FAIL "
                      f"{str(e).splitlines()[0][:60]}", flush=True)


if __name__ == "__main__":
    main()

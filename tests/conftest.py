"""Test configuration: run everything on a virtual 8-device CPU platform.

Mirrors the reference's testing approach of exercising distributed logic with
plain `mpirun -n N` on one machine (reference: tests/run_mpi_tests.cpp) — here
via XLA's forced host-platform device count, so `shard_map` sharding logic is
tested without TPU pod hardware (SURVEY.md §4 "TPU-build translation").

Double precision (the reference's default and its 1e-6 oracle tolerance,
tests/test_util/test_check_values.hpp:46-50) requires jax x64, which is
CPU-only — another reason tests pin JAX_PLATFORMS=cpu.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
# Python subprocesses spawned by tests (the C API example embeds an
# interpreter) must not register the axon TPU plugin: they are CPU-intent,
# and a wedged device tunnel would hang their interpreter start.
os.environ["PALLAS_AXON_POOL_IPS"] = ""
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

from spfft_tpu.utils.platform import force_virtual_cpu_devices  # noqa: E402

# The container's sitecustomize imports jax (axon TPU plugin) before this
# conftest runs and ignores the env vars above — force the platform through
# the live config as well (tests always run on the virtual CPU mesh).
force_virtual_cpu_devices(8)
jax.config.update("jax_enable_x64", True)

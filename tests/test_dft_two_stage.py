"""Two-stage Cooley-Tukey matmul-DFT (ops/dft.py TwoStageMats) vs numpy.

Covers the round-4 verdict item "fast path above 512-point axes": axes
above MATMUL_DFT_MAX factor as N = N1*N2 (both <= the cap) and run as
two dots plus a twiddle, replacing the conv-lowered jnp.fft fallback.
Reference bar: arbitrary-N FFTW plans
(reference: src/fft/fftw_plan_1d.hpp:74-94).
"""

import numpy as np
import jax.numpy as jnp
import pytest

import spfft_tpu.plan as plan_mod
from spfft_tpu import TransformType, make_local_plan
from spfft_tpu.ops import dft

LONG = [768, 1024, 600, 540, 1000]


def _rand(shape, seed=0):
    rng = np.random.default_rng(seed)
    return (rng.standard_normal(shape) + 1j
            * rng.standard_normal(shape)).astype(np.complex64)


@pytest.mark.parametrize("n", LONG)
def test_factorization(n):
    n1, n2 = dft.two_stage_factor(n)
    assert n1 * n2 == n
    assert n1 <= dft.MATMUL_DFT_MAX and n2 <= dft.MATMUL_DFT_MAX
    # balanced: no better pair exists (n1 is the largest divisor <= sqrt)
    for cand in range(n1 + 1, int(np.sqrt(n)) + 1):
        assert n % cand != 0


def test_factor_gates(monkeypatch):
    assert dft.two_stage_factor(256) is None      # direct form
    assert dft.two_stage_factor(521) is None      # prime above the cap
    assert dft.two_stage_factor(2 * 521) is None  # no pair <= cap
    # primes above the cap run the DIRECT fallback (round 5) up to
    # MATMUL_DFT_DIRECT_FALLBACK_MAX; 1042 = 2*521 exceeds it
    monkeypatch.setenv("SPFFT_TPU_FORCE_MATMUL_DFT", "1")
    assert dft.use_matmul_dft(521, jnp.complex64)
    assert not dft.use_matmul_dft(2 * 521, jnp.complex64)
    monkeypatch.delenv("SPFFT_TPU_FORCE_MATMUL_DFT")
    assert not dft.use_matmul_dft(521, jnp.complex64)  # CPU backend gate
    assert dft.matmul_dft_limit() == dft.MATMUL_DFT_MAX ** 2


@pytest.mark.parametrize("n", LONG)
def test_forward_c2c_long(n):
    x = _rand((5, n))
    got = np.asarray(dft.cdft_last(jnp.asarray(x),
                                   dft.c2c_mats(n, dft.FORWARD)))
    ref = np.fft.fft(x, axis=-1)
    rel = np.linalg.norm(got - ref) / np.linalg.norm(ref)
    assert rel < 5e-7, rel


@pytest.mark.parametrize("n", LONG)
def test_backward_unnormalised_long(n):
    x = _rand((4, n), seed=1)
    got = np.asarray(dft.cdft_last(jnp.asarray(x),
                                   dft.c2c_mats(n, dft.BACKWARD)))
    ref = np.fft.ifft(x, axis=-1) * n
    rel = np.linalg.norm(got - ref) / np.linalg.norm(ref)
    assert rel < 5e-7, rel


def test_scale_folds_into_stage_two():
    n = 768
    x = _rand((3, n), seed=2)
    got = np.asarray(dft.cdft_last(
        jnp.asarray(x), dft.c2c_mats(n, dft.FORWARD, scale=1.0 / n)))
    ref = np.fft.fft(x, axis=-1) / n
    rel = np.linalg.norm(got - ref) / np.linalg.norm(ref)
    assert rel < 5e-7, rel


def test_planar_matches_complex_long():
    n = 600
    x = _rand((4, n), seed=3)
    mats = dft.c2c_mats(n, dft.FORWARD)
    yr, yi = dft.pdft_last(jnp.asarray(x.real.copy()),
                           jnp.asarray(x.imag.copy()), mats)
    ref = np.asarray(dft.cdft_last(jnp.asarray(x), mats))
    np.testing.assert_allclose(np.asarray(yr) + 1j * np.asarray(yi), ref,
                               atol=1e-4, rtol=1e-4)


def test_batched_leading_dims():
    n = 768
    x = _rand((2, 3, n), seed=4)
    got = np.asarray(dft.cdft_last(jnp.asarray(x),
                                   dft.c2c_mats(n, dft.FORWARD)))
    ref = np.fft.fft(x, axis=-1)
    rel = np.linalg.norm(got - ref) / np.linalg.norm(ref)
    assert rel < 5e-7, rel


@pytest.fixture
def tiny_cap(monkeypatch):
    """Shrink the direct-form cap so a small full-pipeline plan runs the
    two-stage path (a real 768^3 dense oracle is not CPU-tractable in
    CI); caches keyed on lengths near the old cap are cleared."""
    monkeypatch.setenv("SPFFT_TPU_FORCE_MATMUL_DFT", "1")
    monkeypatch.setattr(dft, "MATMUL_DFT_MAX", 8)
    dft.two_stage_factor.cache_clear()
    dft._two_stage_mats.cache_clear()
    yield
    dft.two_stage_factor.cache_clear()
    dft._two_stage_mats.cache_clear()


def test_full_pipeline_two_stage_c2c(tiny_cap):
    """End-to-end C2C plan whose every axis (12 = 3*4) exceeds the
    shrunk direct cap: backward vs the dense oracle, then the fwd(bwd)
    round trip."""
    n = 12
    rng = np.random.default_rng(7)
    tr = np.stack(np.meshgrid(np.arange(n), np.arange(n), np.arange(n),
                              indexing="ij"), axis=-1).reshape(-1, 3)
    keep = rng.uniform(size=len(tr)) < 0.4
    tr = tr[keep]
    plan = make_local_plan(TransformType.C2C, n, n, n, tr,
                           precision="single")
    assert plan._use_mdft and plan._split_x is None
    vals = (rng.standard_normal(len(tr))
            + 1j * rng.standard_normal(len(tr))).astype(np.complex64)
    space = np.asarray(plan.backward(vals))
    got = space[..., 0] + 1j * space[..., 1]
    cube = np.zeros((n, n, n), np.complex64)
    cube[tr[:, 2], tr[:, 1], tr[:, 0]] = vals
    oracle = np.fft.ifftn(cube) * cube.size
    rel = np.linalg.norm(got - oracle) / np.linalg.norm(oracle)
    assert rel < 1e-5, rel
    from spfft_tpu.types import Scaling
    out = np.asarray(plan.forward(space, scaling=Scaling.FULL))
    got_v = out[:, 0] + 1j * out[:, 1]
    rel = np.linalg.norm(got_v - vals) / np.linalg.norm(vals)
    assert rel < 1e-5, rel


def test_r2c_long_x_mdft_coverage(tiny_cap, monkeypatch):
    """An R2C x-axis above the c2c cap still claims the matmul pipeline
    (the half-spectrum builders are plain direct matrices at any length
    up to the fallback cap — round 5); above the FALLBACK cap it must
    not."""
    n = 12
    tr = np.array([[0, 0, 0], [1, 2, 3], [2, 1, 0]])
    plan = make_local_plan(TransformType.R2C, n, n, n, tr,
                           precision="single")
    assert plan._use_mdft  # 12 > tiny cap 8, but <= the fallback cap
    monkeypatch.setattr(dft, "MATMUL_DFT_DIRECT_FALLBACK_MAX", 8)
    plan2 = make_local_plan(TransformType.R2C, n, n, n, tr,
                            precision="single")
    assert not plan2._use_mdft


def test_precision_model_penalises_uncalibrated_path():
    assert plan_mod.predicted_rel_error("single", 2 ** 19) \
        > 4 * plan_mod.predicted_rel_error("single", 512) \
        > plan_mod.predicted_rel_error("single", 256)

"""Serving benchmark CLI smoke tests (the harness is part of the
deliverable, like spfft_tpu.benchmark — SURVEY.md §6)."""

import json

import pytest

from spfft_tpu.serve.bench import main


@pytest.fixture(autouse=True)
def _obs_reset():
    """--trace-out flips the process-global tracer on; restore the
    default (off) state so later tests measure the disabled path."""
    yield
    from spfft_tpu import obs
    obs.disable()
    obs.GLOBAL_TRACER.reset()
    obs.GLOBAL_TRACER.set_sample_rate(1.0)


def _last_json(capsys):
    out = capsys.readouterr().out
    line = next(ln for ln in reversed(out.splitlines())
                if ln.startswith("{"))
    return json.loads(line), out


def test_serve_bench_runs_and_meets_bars(tmp_path, capsys):
    """The acceptance run: CPU, mixed signatures — throughput at least
    the serial-loop baseline, registry hit-rate >= 90% after warmup,
    and the JSON payload carries the serving metrics."""
    out_file = tmp_path / "serve.json"
    rc = main(["--dim", "16", "--requests", "64", "--signatures", "3",
               "--threads", "8", "-o", str(out_file)])
    assert rc == 0
    payload, text = _last_json(capsys)
    assert payload["unit"] == "req/s"
    assert payload["throughput_rps"] > 0
    assert payload["throughput_rps"] >= payload["serial_throughput_rps"]
    assert payload["registry_hit_rate"] >= 0.9
    snap = payload["serve_metrics"]
    assert snap["completed"] == 64
    assert snap["failed"] == 0
    assert snap["registry"]["builds"] == 3
    assert "p50" in snap["latency_seconds"]
    assert json.loads(out_file.read_text()) == payload
    assert "serial loop" in text and "executor" in text


def test_serve_bench_same_signature_beats_serial(capsys):
    """The same-signature trace of the acceptance criterion."""
    rc = main(["--dim", "16", "--requests", "64", "--signatures", "1",
               "--threads", "4"])
    assert rc == 0
    payload, _ = _last_json(capsys)
    assert payload["speedup_vs_serial"] >= 1.0
    assert payload["serve_metrics"]["fused_batches"] >= 1


def test_serve_bench_no_batching(capsys):
    rc = main(["--dim", "12", "--requests", "16", "--signatures", "1",
               "--threads", "2", "--no-batching"])
    assert rc == 0
    payload, _ = _last_json(capsys)
    assert payload["serve_metrics"]["fused_batches"] == 0
    assert payload["serve_metrics"]["completed"] == 16


def test_serve_bench_bad_args():
    assert main(["--requests", "0"]) == 2
    assert main(["--high-fraction", "1.5"]) == 2


def test_serve_bench_smoke_pins_and_drops_pad_rows(capsys):
    """The tier-1 smoke: deterministic stable-size waves activate the
    pinned exact-shape path and drive ladder pad rows to zero (the
    perf_opt acceptance observable), bit-exact throughout."""
    rc = main(["--smoke"])
    assert rc == 0
    payload, text = _last_json(capsys)
    assert payload["smoke"] and payload["ok"]
    assert payload["pinned_batches"] >= 1
    assert payload["padded_rows_per_wave"][-1] == 0
    assert payload["failures"] == []
    assert "pad rows per wave" in text


def test_serve_bench_fault_smoke(capsys):
    """The tier-1 failure-semantics smoke: scripted faults prove bucket
    isolation, bounded retry, quarantine/probation/readmission and the
    crash-proof dispatch supervisor — exit 1 on any violation."""
    rc = main(["--fault-smoke"])
    assert rc == 0
    payload, text = _last_json(capsys)
    assert payload["fault_smoke"] and payload["ok"]
    assert payload["failures"] == []
    assert "5_crash_fails_futures" in payload["phases"]
    assert "fault smoke" in text


def test_serve_bench_fault_rate_degrades_gracefully(capsys):
    """A 5% injected transient fault rate: the replay completes,
    recovery counters land in the JSON, and the service degrades
    (retries/fallbacks) rather than collapses (the overwhelming
    majority of requests still succeed)."""
    rc = main(["--dim", "12", "--requests", "32", "--signatures", "1",
               "--threads", "4", "--fault-rate", "0.05"])
    assert rc == 0
    payload, text = _last_json(capsys)
    assert payload["fault_rate"] == 0.05
    assert payload["faults"] is not None
    snap = payload["serve_metrics"]
    health = snap["health"]
    assert snap["completed"] + payload["failed_requests"] == 32
    assert payload["failed_requests"] <= health["retries_exhausted"] \
        + health["no_healthy_device"]
    assert snap["completed"] >= 24  # degradation, not collapse
    assert "recovery:" in text and "health:" in text


def test_serve_bench_bad_fault_args():
    assert main(["--fault-rate", "1.5"]) == 2


def test_serve_bench_smoke_trace_artifacts(tmp_path, capsys):
    """The trace-smoke acceptance criterion (make trace-smoke runs the
    same flags): --smoke with --trace-out/--prom-out produces a Chrome
    trace whose spans cover all eight request stages plus compile and
    exchange events with ZERO unclosed spans, and Prometheus text that
    round-trips the validating exposition parser."""
    from spfft_tpu import obs
    from spfft_tpu.obs.__main__ import (REQUEST_STAGES,
                                        validate_trace_payload)

    trace_file = tmp_path / "trace.json"
    prom_file = tmp_path / "metrics.prom"
    rc = main(["--smoke", "--trace-out", str(trace_file),
               "--prom-out", str(prom_file)])
    assert rc == 0
    payload, _ = _last_json(capsys)
    assert payload["ok"]
    assert payload["obs"]["open_spans"] == 0
    trace = json.loads(trace_file.read_text())
    # the conftest's 8-device virtual platform means the exchange demo
    # plan built, so exchange events are required too
    require = REQUEST_STAGES + ("serve.request",
                                "compile.registry_build",
                                "exchange.plan_build")
    assert validate_trace_payload(trace, require_names=require) == []
    names = {e["name"] for e in trace["traceEvents"]
             if e["ph"] in ("X", "i", "C")}
    assert "exchange.chunk_wire_bytes" in names  # per-chunk accounting
    series = obs.parse_prometheus_text(prom_file.read_text())
    assert series[("spfft_serve_completed_total", ())] == 30  # 6x5
    assert any(name == "spfft_exchange_wire_bytes"
               for name, _ in series)
    assert any(name == "spfft_compile_seconds_total"
               for name, _ in series)


def test_serve_bench_fault_smoke_zero_unclosed_spans(tmp_path, capsys):
    """The acceptance criterion's fault half: all six failure phases
    (poisoned bucket, injected faults, quarantine, probation, crash,
    restart) leave ZERO unclosed spans, with the trace exported."""
    trace_file = tmp_path / "fault_trace.json"
    rc = main(["--fault-smoke", "--trace-out", str(trace_file)])
    assert rc == 0
    payload, _ = _last_json(capsys)
    assert payload["ok"]
    assert payload["obs"]["open_spans"] == 0
    trace = json.loads(trace_file.read_text())
    errored = [e for e in trace["traceEvents"]
               if e["ph"] == "X" and e["args"].get("status") == "error"]
    assert errored, "failure phases must record error-status spans"
    assert all(e["args"].get("error") for e in errored)


def test_serve_bench_profile_dir(tmp_path, capsys):
    """--profile-dir captures a jax.profiler session around the
    measured window (the named_scope phase names become visible)."""
    profile_dir = tmp_path / "profile"
    rc = main(["--dim", "12", "--requests", "8", "--signatures", "1",
               "--threads", "2", "--profile-dir", str(profile_dir)])
    assert rc == 0
    _, text = _last_json(capsys)
    captured = list(profile_dir.rglob("*")) if profile_dir.exists() \
        else []
    # the capture is best-effort (warn-and-continue when the backend
    # has no profiler), but on this container's CPU backend it works
    assert any(p.is_file() for p in captured) \
        or "jax.profiler capture unavailable" in text


def test_serve_bench_priority_classes(capsys):
    """--high-fraction floods a deterministic subset through the high
    lane; per-class latency percentiles land in the payload."""
    rc = main(["--dim", "12", "--requests", "32", "--signatures", "1",
               "--threads", "4", "--high-fraction", "0.3"])
    assert rc == 0
    payload, text = _last_json(capsys)
    snap = payload["serve_metrics"]
    by_class = snap["latency_seconds_by_class"]
    assert set(by_class) == {"high", "normal"}
    counts = snap["completed_by_class"]
    assert counts["high"] + counts["normal"] == 32
    assert counts["high"] > 0
    assert "high  lane p50/p99" in text


def test_serve_bench_smoke_control_closes_the_loop(tmp_path, capsys):
    """The round-11 acceptance criterion, tier-1 (make control-smoke
    runs the same flags): the scripted queue-buildup trace causes a
    recorded, bounds-clamped batch_window decision — visible in the
    payload, as a control.retune trace annotation and as the
    spfft_control_decisions_total Prometheus counter — with bit-exact
    results throughout (including a post-retune wave) and ZERO SLO
    false positives on the healthy trace."""
    from spfft_tpu import obs
    from spfft_tpu.control import ServeConfig

    trace_file = tmp_path / "control_trace.json"
    prom_file = tmp_path / "control.prom"
    rc = main(["--smoke", "--control", "--trace-out", str(trace_file),
               "--prom-out", str(prom_file)])
    assert rc == 0
    payload, text = _last_json(capsys)
    assert payload["ok"] and payload["failures"] == []
    assert payload["obs"]["open_spans"] == 0
    ctl = payload["control"]
    moved = [d for d in ctl["decisions"]
             if d["knob"] == "batch_window"]
    assert moved, "no recorded batch_window decision"
    assert ctl["window_after"] < ctl["window_before"]
    lo, hi = ServeConfig.bounds("batch_window")
    assert lo <= ctl["window_after"] <= hi
    for knob, value in ctl["knobs"].items():
        klo, khi = ServeConfig.bounds(knob)
        assert klo <= value <= khi
    assert payload["slo"]["violations"] == []
    # the decision is visible in BOTH export formats
    trace = json.loads(trace_file.read_text())
    names = {e["name"] for e in trace["traceEvents"]
             if e["ph"] in ("X", "i")}
    assert "control.retune" in names
    series = obs.parse_prometheus_text(prom_file.read_text())
    decided = [v for (name, labels), v in series.items()
               if name == "spfft_control_decisions_total"
               and ("knob", "batch_window") in labels
               and ("source", "controller") in labels]
    assert decided and decided[0] >= 1
    assert any(name == "spfft_slo_burn_rate" for name, _ in series)
    assert any(name == "spfft_control_knob" for name, _ in series)
    assert "control:" in text


def test_serve_bench_loads_config_artifact(tmp_path, capsys):
    """--config boots the executor from a recommended-config artifact
    (the tuner's output format); explicit flags still win."""
    from spfft_tpu.control import ServeConfig

    cfg = ServeConfig()
    cfg.set("batch_window", 0.003, source="tuner")
    cfg.set("max_batch", 4, source="tuner")
    path = tmp_path / "recommended.json"
    cfg.save(str(path))
    rc = main(["--dim", "12", "--requests", "8", "--signatures", "1",
               "--threads", "2", "--config", str(path)])
    assert rc == 0
    _, text = _last_json(capsys)
    assert "window=3.0ms" in text and "max_batch=4" in text
    # explicit flag beats the artifact
    rc = main(["--dim", "12", "--requests", "8", "--signatures", "1",
               "--threads", "2", "--config", str(path),
               "--max-batch", "6"])
    assert rc == 0
    _, text = _last_json(capsys)
    assert "max_batch=6" in text and "window=3.0ms" in text


def test_serve_bench_metrics_port_serves_scrape_endpoint(capsys):
    """--metrics-port 0 binds an ephemeral scrape endpoint for the
    replay window and prints its URL."""
    rc = main(["--dim", "12", "--requests", "8", "--signatures", "1",
               "--threads", "2", "--metrics-port", "0"])
    assert rc == 0
    _, text = _last_json(capsys)
    assert "metrics endpoint: http://127.0.0.1:" in text


def test_serve_bench_slo_flag_reports(capsys):
    """--slo declares objectives; the JSON carries the watchdog verdict
    and a generous healthy-trace spec reports no violations."""
    rc = main(["--dim", "12", "--requests", "8", "--signatures", "1",
               "--threads", "2",
               "--slo", "p99_ms=60000,error_rate=0.5"])
    assert rc == 0
    payload, text = _last_json(capsys)
    assert payload["slo"]["violations"] == []
    assert payload["slo"]["objectives"]["latency_p99_s"] == 60.0
    assert "slo:" in text


def test_serve_bench_chaos_harness(capsys):
    """The tier-1 chaos twin (make chaos-smoke runs two more seeds):
    three scripted recovery-ladder phases plus seeded multi-seam fault
    storms — no hangs, typed failures only, bit-exact healthy requests,
    no torn artifacts, zero open spans — exit 1 on any violation."""
    from spfft_tpu import faults

    try:
        rc = main(["--chaos", "7"])
    finally:
        faults.disarm()
    assert rc == 0
    payload, text = _last_json(capsys)
    assert payload["chaos"] and payload["ok"]
    assert payload["failures"] == []
    assert payload["seed"] == 7
    assert "A_fused_demotion" in payload["phases"]
    assert "B_enospc_memory_only" in payload["phases"]
    assert "C_execute_watchdog" in payload["phases"]
    assert "G_flight_recorder" in payload["phases"]
    assert payload["phases"]["G_flight_recorder"]["bundles"] >= 1
    # the coverage floor the harness itself enforces, restated here so
    # a silent scope regression fails the tier-1 suite too
    assert len(payload["fired_sites"]) >= 8
    assert len(payload["subsystems"]) >= 4
    assert "chaos" in text

"""Mechanical validation of the Fortran bind(C) module against the C ABI.

This image ships no Fortran compiler, so include/spfft_tpu.f90 cannot be
compiled here (stated in the file). What CAN be checked without one:

* every C entry point declared in include/spfft_tpu.h has a bind(C)
  declaration in the Fortran module with the SAME argument count,
* every bound name exists as a symbol in the built libspfft_tpu.so,
* the enum/constant values mirror the header exactly.

The reference's Fortran module is likewise a declaration mirror of its C
API (reference: include/spfft/spfft.f90); drift between the two files is
the realistic failure mode, and this pins it.
"""

import ctypes
import os
import re
import shutil
import subprocess

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
HEADER = os.path.join(REPO, "include", "spfft_tpu.h")
F90 = os.path.join(REPO, "include", "spfft_tpu.f90")


def parse_header_functions():
    """{name: n_args} for every C prototype in the public header."""
    src = open(HEADER).read()
    src = re.sub(r"/\*.*?\*/", "", src, flags=re.S)
    out = {}
    # any return type — a future entry point with a new return type (or a
    # star attached to the name, C-style) must still be caught
    for m in re.finditer(
            r"^\s*\w[\w\s]*[\s*]\s*(spfft_tpu_\w+)\s*\(([^;]*?)\)\s*;",
            src, re.M | re.S):
        name, args = m.group(1), m.group(2)
        args = args.strip()
        n = 0 if args in ("", "void") else args.count(",") + 1
        out[name] = n
    return out


def parse_f90_functions():
    """{bound_name: n_args} for every bind(C) interface declaration."""
    src = open(F90).read()
    out = {}
    for m in re.finditer(
            r"function\s+\w+\s*\(([^)]*)\)\s*&?\s*\n?\s*"
            r"bind\(C,\s*name=\"(\w+)\"\)", src, re.S):
        args, name = m.group(1), m.group(2)
        args = args.strip()
        out[name] = 0 if not args else args.count(",") + 1
    return out


def test_fortran_declarations_match_header():
    hdr = parse_header_functions()
    f90 = parse_f90_functions()
    assert hdr, "header parse produced nothing"
    # error_string returns const char* — represented differently in
    # Fortran (c_ptr function); everything else must match exactly.
    missing = {n for n in hdr if n not in f90
               and n != "spfft_tpu_error_string"}
    assert not missing, f"C entry points missing from spfft_tpu.f90: " \
                        f"{sorted(missing)}"
    for name, n_args in f90.items():
        assert name in hdr, f"Fortran binds unknown symbol {name}"
        assert n_args == hdr[name], \
            f"{name}: {n_args} Fortran args vs {hdr[name]} C args"


def test_f90_constants_match_header():
    hdr = open(HEADER).read()
    f90 = open(F90).read()
    hdr_consts = dict(re.findall(r"(SPFFT_TPU_\w+)\s*=\s*(-?\d+)", hdr))
    hdr_consts.update(
        re.findall(r"#define\s+(SPFFT_TPU_\w+)\s+(-?\d+)", hdr))
    f90_consts = dict(re.findall(
        r"parameter\s*::\s*(SPFFT_TPU_\w+)\s*=\s*(-?\d+)", f90))
    assert f90_consts, "no constants parsed from spfft_tpu.f90"
    for name, val in f90_consts.items():
        assert name in hdr_consts, f"{name} not in the C header"
        assert val == hdr_consts[name], \
            f"{name}: f90 {val} vs header {hdr_consts[name]}"
    missing = set(hdr_consts) - set(f90_consts)
    assert not missing, f"header constants missing from f90: {missing}"


@pytest.mark.skipif(shutil.which("g++") is None, reason="no C++ compiler")
def test_bound_symbols_exist_in_library():
    subprocess.run(["make", "-s", "capi"], cwd=REPO, check=True,
                   capture_output=True, text=True)
    lib = ctypes.CDLL(os.path.join(REPO, "lib", "libspfft_tpu.so"))
    for name in parse_f90_functions():
        assert hasattr(lib, name), f"{name} not exported by libspfft_tpu.so"

"""Compute/communication overlap: chunked, pipelined distributed exchange
(spfft_tpu/parallel/overlap.py + the pipelined bodies in dist.py).

Two layers of guarantees:

1. SCHEDULE INVARIANTS (plan-time, pure numpy — property-tested over
   skewed random distributions): chunked sub-schedules conserve
   ``wire_elements()`` exactly, no chunk's busiest link exceeds the
   monolithic schedule's, and the union of the chunks' (src, dst,
   element) sets reproduces the monolithic payload exactly, both
   directions, both chunked kinds (ragged + compact-ppermute).

2. EXECUTION BIT-EXACTNESS (8-shard virtual CPU mesh): for every
   exchange mechanism (padded all_to_all, ppermute ring, ragged
   exact-count, ppermute compact, float-wire variants, R2C, batched,
   fused pair), ``overlap_chunks=K`` output is BIT-IDENTICAL to the
   monolithic plan — the overlap pipeline is pure data-movement
   restructuring, every element takes the same arithmetic path.

Plus the launch-structure checks: K chunks lower K collectives per
direction where the monolithic path lowers one (the shape XLA's
latency-hiding scheduler needs to overlap them — the start/done split
itself is asserted on the TPU lane, tests_tpu/test_tpu_ci.py), and
``overlap_chunks=1`` lowers IDENTICAL StableHLO to a plan built without
the knob.
"""

import numpy as np
import pytest

import jax

from spfft_tpu import ExchangeType, Scaling, TransformType
from spfft_tpu.errors import InvalidParameterError
from spfft_tpu.parallel import make_distributed_plan, make_mesh
from spfft_tpu.parallel.dist import build_distributed_plan
from spfft_tpu.parallel.exchange import (build_compact_schedule,
                                         build_ragged_schedule)
from spfft_tpu.parallel.overlap import (build_overlap_schedule,
                                        chunk_bounds)
from spfft_tpu.utils.hlo_inspect import count_collectives

from test_util import hermitian_triplets, random_sparse_triplets
from test_distributed import split_by_sticks, split_planes

DIMS = (11, 12, 13)

SKEWS = {
    "uniform": ([1, 1, 1, 1], [1, 1, 1, 1]),
    "stick_skew": ([5, 1, 2, 1], [1, 1, 1, 1]),
    "plane_skew": ([1, 1, 1, 1], [1, 4, 1, 2]),
    "empty_shards": ([1, 0, 2, 0], [0, 2, 0, 1]),
}


def _dist_plan(skew, seed=31):
    rng = np.random.default_rng(seed)
    triplets = random_sparse_triplets(rng, DIMS)
    parts = split_by_sticks(triplets, DIMS, SKEWS[skew][0])
    planes = split_planes(DIMS[2], SKEWS[skew][1])
    return build_distributed_plan(TransformType.C2C, *DIMS, parts, planes)


# -- the chunk partitioner ---------------------------------------------------
def test_chunk_bounds_partition_and_balance():
    counts = [20, 5, 10, 5]
    for k in (1, 2, 3, 4, 7):
        b = chunk_bounds(counts, 25, k)
        assert len(b) == k
        assert b[0][0] == 0 and b[-1][1] == 25
        for (lo, hi), (lo2, _) in zip(b, b[1:]):
            assert hi == lo2 and lo < hi  # contiguous, non-empty
    # balanced: with 40 true rows over 4 chunks no chunk carries more
    # than ~double the ideal share of true rows
    b = chunk_bounds(counts, 25, 4)
    shares = [sum(max(0, min(c, hi) - min(c, lo)) for c in counts)
              for lo, hi in b]
    assert sum(shares) == sum(counts)
    assert max(shares) <= 2 * (sum(counts) / 4)


def test_chunk_bounds_skewed_ingress_balances_dominant_shard():
    """Round-13 skew-aware partitioner: under heavily skewed stick
    ownership the DOMINANT shard's rows (= the per-chunk busiest link,
    since prefix-populated rows make the heaviest link the largest
    shard's) must split near-evenly across chunks, while total
    true-row balance (= every destination's ingress share) stays
    within the old bound."""
    counts = [10, 100]
    K = 2

    def dominant(b):
        m = max(counts)
        return [max(0, min(m, hi) - min(m, lo)) for lo, hi in b]

    def shares(b):
        return [sum(max(0, min(c, hi) - min(c, lo)) for c in counts)
                for lo, hi in b]

    legacy = chunk_bounds(counts, 100, K, skew_weight=0.0)
    skew = chunk_bounds(counts, 100, K)
    # the skew-aware split divides the dominant shard strictly more
    # evenly than the totals-only split ...
    assert max(dominant(skew)) - min(dominant(skew)) \
        < max(dominant(legacy)) - min(dominant(legacy))
    assert max(dominant(skew)) <= 1.1 * (max(counts) / K) + 1
    # ... without giving up the destination-ingress balance bound
    assert sum(shares(skew)) == sum(counts)
    assert max(shares(skew)) <= 2 * (sum(counts) / K)


def test_chunk_bounds_uniform_counts_match_legacy():
    """Uniform shards: both weights are proportional, so the
    skew-aware bounds reproduce the pre-round-13 partition exactly."""
    for counts, padded, k in (([7, 7, 7, 7], 8, 3),
                              ([20, 20], 25, 4), ([5], 5, 5)):
        assert chunk_bounds(counts, padded, k) \
            == chunk_bounds(counts, padded, k, skew_weight=0.0)


def test_chunk_bounds_rejects_bad_k():
    with pytest.raises(InvalidParameterError):
        chunk_bounds([3], 4, 0)
    with pytest.raises(InvalidParameterError):
        chunk_bounds([3], 4, 5)


# -- schedule invariants -----------------------------------------------------
@pytest.mark.parametrize("skew", sorted(SKEWS))
@pytest.mark.parametrize("kind", ["ragged", "compact"])
@pytest.mark.parametrize("k", [2, 3, 4])
def test_chunked_schedule_invariants(skew, kind, k):
    dp = _dist_plan(skew)
    mono = build_ragged_schedule(dp)       # exact accounting
    monoc = build_compact_schedule(dp)     # bucket-charged accounting
    ov = build_overlap_schedule(dp, k, kind)
    # conservation: chunk exact wire sums to the monolithic exact total
    assert ov.wire_elements() == mono.wire_elements()
    assert sum(ov.chunk_wire_elements(c, forward=True)
               for c in range(k)) == mono.wire_elements()
    # the whole-exchange bottleneck link is unchanged, and no chunk
    # exceeds it (monolithic bucket-charged accounting upper-bounds the
    # exact one, so the compact comparison holds a fortiori)
    assert ov.busiest_link_elements() == mono.busiest_link_elements()
    for c in range(k):
        for fwd in (False, True):
            assert (ov.chunk_busiest_link_elements(c, forward=fwd)
                    <= mono.busiest_link_elements())
            assert (ov.chunk_busiest_link_elements(c, forward=fwd)
                    <= monoc.busiest_link_elements())


@pytest.mark.parametrize("skew", sorted(SKEWS))
@pytest.mark.parametrize("kind", ["ragged", "compact"])
@pytest.mark.parametrize("k", [2, 3])
def test_chunk_union_reproduces_every_element(skew, kind, k):
    """The chunks' (src, dst, element) sets — read from the actual pack
    tables — must partition the monolithic schedule's payload exactly,
    in both directions."""
    dp = _dist_plan(skew)
    S = dp.num_shards
    ns = [p.num_sticks for p in dp.shard_plans]
    npl = list(dp.num_planes)
    off = list(dp.plane_offsets)
    dz, Y, Xe = dp.dim_z, dp.dim_y, dp.dim_x_freq
    exp_bwd, exp_fwd = {}, {}
    for j in range(S):
        for d in range(S):
            if ns[j] * npl[d]:
                i = np.arange(ns[j])[:, None]
                z = off[d] + np.arange(npl[d])[None, :]
                exp_bwd[(j, d)] = np.sort((i * dz + z).reshape(-1))
            if ns[d] * npl[j]:
                cols = np.asarray(dp.shard_plans[d].scatter_cols)
                p = np.arange(npl[j])[None, :]  # local slab rows
                exp_fwd[(j, d)] = np.sort(
                    (p * (Y * Xe) + cols[:, None]).reshape(-1))
    ov = build_overlap_schedule(dp, k, kind)
    for exp, getter in ((exp_bwd, ov.bwd_pair_elements),
                        (exp_fwd, ov.fwd_pair_elements)):
        got = {}
        for c in range(k):
            for pr, e in getter(c).items():
                got.setdefault(pr, []).append(e)
        got = {pr: np.sort(np.concatenate(v)) for pr, v in got.items()
               if sum(len(x) for x in v)}
        assert set(got) == set(exp)
        for pr in exp:  # exact partition: no loss, no duplication
            np.testing.assert_array_equal(got[pr], exp[pr])


# -- execution bit-exactness (8-shard virtual mesh) --------------------------
N8 = 16


def _eight_shard_case(ttype=TransformType.C2C, seed=0):
    from spfft_tpu.utils.workloads import (even_plane_split,
                                           round_robin_stick_partition)
    rng = np.random.default_rng(seed)
    if ttype == TransformType.R2C:
        tr = hermitian_triplets(rng, (N8, N8, N8))
    else:
        tr = random_sparse_triplets(rng, (N8, N8, N8))
    parts = round_robin_stick_partition(np.asarray(tr), (N8, N8, N8), 8)
    planes = even_plane_split(N8, 8)
    vals = [(rng.uniform(-1, 1, len(p))
             + 1j * rng.uniform(-1, 1, len(p))).astype(np.complex64)
            for p in parts]
    return parts, planes, vals


def _pair_arrays(plan, vals):
    space = plan.backward(vals)
    out = plan.forward(space, Scaling.FULL)
    return np.asarray(space), np.asarray(out)


@pytest.mark.parametrize("exchange", [
    ExchangeType.DEFAULT, ExchangeType.UNBUFFERED,
    ExchangeType.COMPACT_BUFFERED, ExchangeType.BUFFERED_FLOAT,
    ExchangeType.COMPACT_BUFFERED_FLOAT])
@pytest.mark.parametrize("k", [2, 4])
def test_overlap_bit_exact_vs_monolithic(exchange, k):
    parts, planes, vals = _eight_shard_case()
    mesh = make_mesh(8)
    p0 = make_distributed_plan(TransformType.C2C, N8, N8, N8, parts,
                               planes, mesh=mesh, exchange=exchange,
                               overlap_chunks=1)
    pk = make_distributed_plan(TransformType.C2C, N8, N8, N8, parts,
                               planes, mesh=mesh, exchange=exchange,
                               overlap_chunks=k)
    assert pk._overlap is not None and pk.overlap_chunks > 1
    s0, f0 = _pair_arrays(p0, vals)
    sk, fk = _pair_arrays(pk, vals)
    np.testing.assert_array_equal(s0, sk)
    np.testing.assert_array_equal(f0, fk)


@pytest.mark.parametrize("k", [2, 4])
def test_overlap_bit_exact_r2c(k):
    parts, planes, vals = _eight_shard_case(TransformType.R2C)
    mesh = make_mesh(8)
    p0 = make_distributed_plan(TransformType.R2C, N8, N8, N8, parts,
                               planes, mesh=mesh, overlap_chunks=1)
    pk = make_distributed_plan(TransformType.R2C, N8, N8, N8, parts,
                               planes, mesh=mesh, overlap_chunks=k)
    s0, f0 = _pair_arrays(p0, vals)
    sk, fk = _pair_arrays(pk, vals)
    np.testing.assert_array_equal(s0, sk)
    np.testing.assert_array_equal(f0, fk)


def test_overlap_bit_exact_ppermute_compact(monkeypatch):
    """The SPFFT_TPU_COMPACT_PPERMUTE=1 mechanism takes the chunked
    compact-op path (kind == 'compact')."""
    monkeypatch.setenv("SPFFT_TPU_COMPACT_PPERMUTE", "1")
    parts, planes, vals = _eight_shard_case()
    mesh = make_mesh(8)
    p0 = make_distributed_plan(TransformType.C2C, N8, N8, N8, parts,
                               planes, mesh=mesh,
                               exchange=ExchangeType.COMPACT_BUFFERED,
                               overlap_chunks=1)
    pk = make_distributed_plan(TransformType.C2C, N8, N8, N8, parts,
                               planes, mesh=mesh,
                               exchange=ExchangeType.COMPACT_BUFFERED,
                               overlap_chunks=2)
    assert pk._overlap is not None and pk._overlap.kind == "compact"
    s0, f0 = _pair_arrays(p0, vals)
    sk, fk = _pair_arrays(pk, vals)
    np.testing.assert_array_equal(s0, sk)
    np.testing.assert_array_equal(f0, fk)


@pytest.mark.parametrize("exchange", [ExchangeType.DEFAULT,
                                      ExchangeType.COMPACT_BUFFERED])
def test_overlap_bit_exact_batched_and_fused_pair(exchange):
    parts, planes, vals = _eight_shard_case()
    mesh = make_mesh(8)
    rng = np.random.default_rng(7)
    vb = [[(rng.uniform(-1, 1, len(p))
            + 1j * rng.uniform(-1, 1, len(p))).astype(np.complex64)
           for p in parts] for _ in range(3)]
    p0 = make_distributed_plan(TransformType.C2C, N8, N8, N8, parts,
                               planes, mesh=mesh, exchange=exchange,
                               overlap_chunks=1)
    pk = make_distributed_plan(TransformType.C2C, N8, N8, N8, parts,
                               planes, mesh=mesh, exchange=exchange,
                               overlap_chunks=2)
    b0 = p0.backward_batched(vb)
    bk = pk.backward_batched(vb)
    np.testing.assert_array_equal(np.asarray(b0), np.asarray(bk))
    np.testing.assert_array_equal(
        np.asarray(p0.forward_batched(b0, Scaling.FULL)),
        np.asarray(pk.forward_batched(bk, Scaling.FULL)))
    np.testing.assert_array_equal(
        np.asarray(p0.apply_pointwise(vals, scaling=Scaling.FULL)),
        np.asarray(pk.apply_pointwise(vals, scaling=Scaling.FULL)))


def test_overlap_bit_exact_split_x_window():
    """Overlap composes with the split-x occupied-window optimisation:
    sticks clustered in a narrow x band trigger the window, and the
    chunked tables must index the window layout."""
    rng = np.random.default_rng(5)
    n = 16
    tr = random_sparse_triplets(rng, (4, n, n))  # narrow x extent
    tr = np.asarray(tr)
    from spfft_tpu.utils.workloads import (even_plane_split,
                                           round_robin_stick_partition)
    parts = round_robin_stick_partition(tr, (n, n, n), 8)
    planes = even_plane_split(n, 8)
    vals = [(rng.uniform(-1, 1, len(p))
             + 1j * rng.uniform(-1, 1, len(p))).astype(np.complex64)
            for p in parts]
    mesh = make_mesh(8)
    for exchange in (ExchangeType.DEFAULT, ExchangeType.COMPACT_BUFFERED):
        p0 = make_distributed_plan(TransformType.C2C, n, n, n, parts,
                                   planes, mesh=mesh, exchange=exchange,
                                   overlap_chunks=1)
        assert p0._split_x is not None  # the window actually engaged
        pk = make_distributed_plan(TransformType.C2C, n, n, n, parts,
                                   planes, mesh=mesh, exchange=exchange,
                                   overlap_chunks=2)
        s0, f0 = _pair_arrays(p0, vals)
        sk, fk = _pair_arrays(pk, vals)
        np.testing.assert_array_equal(s0, sk)
        np.testing.assert_array_equal(f0, fk)


# -- launch structure / knob plumbing ----------------------------------------
def test_overlap_lowers_k_collectives_per_direction():
    """K chunks must lower K independent collectives (the structure the
    latency-hiding scheduler splits into start/done pairs on TPU); the
    monolithic plan lowers one."""
    parts, planes, vals = _eight_shard_case()
    mesh = make_mesh(8)
    for k in (1, 2):
        plan = make_distributed_plan(TransformType.C2C, N8, N8, N8,
                                     parts, planes, mesh=mesh,
                                     overlap_chunks=k)
        v = plan.shard_values(vals)
        txt = plan._backward_jit.lower(v, *plan._device_tables).as_text()
        assert count_collectives(txt)["all_to_all"] == k
        # ragged mechanism: the CPU emulation gathers once per chunk
        plan_r = make_distributed_plan(
            TransformType.C2C, N8, N8, N8, parts, planes, mesh=mesh,
            exchange=ExchangeType.COMPACT_BUFFERED, overlap_chunks=k)
        v = plan_r.shard_values(vals)
        txt = plan_r._backward_jit.lower(
            v, *plan_r._device_tables).as_text()
        assert count_collectives(txt)["all_gather"] == k


def test_overlap_chunks_one_is_identical_hlo():
    """overlap_chunks=1 must produce the IDENTICAL lowered module to a
    plan built without the knob (same code path, not merely the same
    numerics)."""
    parts, planes, vals = _eight_shard_case()
    mesh = make_mesh(8)
    p_default = make_distributed_plan(TransformType.C2C, N8, N8, N8,
                                      parts, planes, mesh=mesh)
    p_one = make_distributed_plan(TransformType.C2C, N8, N8, N8,
                                  parts, planes, mesh=mesh,
                                  overlap_chunks=1)
    v = p_default.shard_values(vals)
    t0 = p_default._backward_jit.lower(
        v, *p_default._device_tables).as_text()
    t1 = p_one._backward_jit.lower(v, *p_one._device_tables).as_text()
    assert t0 == t1


def test_overlap_knob_env_and_clamp(monkeypatch):
    parts, planes, _ = _eight_shard_case()
    mesh = make_mesh(8)
    monkeypatch.setenv("SPFFT_TPU_OVERLAP_CHUNKS", "2")
    plan = make_distributed_plan(TransformType.C2C, N8, N8, N8, parts,
                                 planes, mesh=mesh)
    assert plan.overlap_chunks == 2 and plan._overlap is not None
    monkeypatch.delenv("SPFFT_TPU_OVERLAP_CHUNKS")
    # clamped by max_planes (16 planes / 8 shards = 2 per shard)
    plan = make_distributed_plan(TransformType.C2C, N8, N8, N8, parts,
                                 planes, mesh=mesh, overlap_chunks=64)
    assert plan.overlap_chunks == min(
        plan.dist_plan.max_sticks, plan.dist_plan.max_planes)
    with pytest.raises(InvalidParameterError):
        make_distributed_plan(TransformType.C2C, N8, N8, N8, parts,
                              planes, mesh=mesh, overlap_chunks=0)


def test_overlap_wire_bytes_match_monolithic():
    """The wire-byte model is unchanged by chunking: exact mechanisms
    report the monolithic exact totals, padded mechanisms the padded
    ones."""
    parts, planes, _ = _eight_shard_case()
    mesh = make_mesh(8)
    for exchange in (ExchangeType.DEFAULT, ExchangeType.COMPACT_BUFFERED,
                     ExchangeType.COMPACT_BUFFERED_FLOAT):
        p0 = make_distributed_plan(TransformType.C2C, N8, N8, N8, parts,
                                   planes, mesh=mesh, exchange=exchange,
                                   overlap_chunks=1)
        pk = make_distributed_plan(TransformType.C2C, N8, N8, N8, parts,
                                   planes, mesh=mesh, exchange=exchange,
                                   overlap_chunks=2)
        assert pk.exchange_wire_bytes() == p0.exchange_wire_bytes()
        assert (pk.exchange_busiest_link_bytes()
                == p0.exchange_busiest_link_bytes())

"""Cross-request SPMD coalescing (spfft_tpu/serve/cluster.py
``SPMDCoalescer`` + parallel/dist.py ``coalesce_backward/forward``).

The contracts under test (docs/cluster.md "SPMD coalescing"): the
batched entry points are BIT-EXACT against per-request serial
execution across kinds, overlap depth K, c2c/r2c trimming, the fused
flag and every batch size including 1 and ``spmd_max_batch``; the
coalesced program lowers a B-invariant collective count (one exchange
round moves all N payloads — the whole point of the optimisation); the
coalescer drains same-signature queues EDF-ordered (high priority
first) inside a deadline-aware window that closes EARLY on an imminent
member deadline or a high-priority member, purges expired requests at
drain time (they never ride a collective round), emits exactly one
``cluster.spmd_execute`` span per round carrying every member's trace
id, and answers the ``cluster.spmd_window`` fault site typed; the
controller widens/narrows ``spmd_batch_window``/``spmd_max_batch``
from the coalescer's live signals; and PodFrontend routes remote
distributed requests with signature affinity so coalescing windows see
co-located company.
"""

import re
import threading
import time

import numpy as np
import pytest

from spfft_tpu import Scaling, TransformType, faults, obs
from spfft_tpu.benchmark import cutoff_stick_triplets
from spfft_tpu.control import Controller, ServeConfig
from spfft_tpu.control.config import global_config
from spfft_tpu.errors import DeadlineExpiredError
from spfft_tpu.faults import FaultPlan, InjectedFault
from spfft_tpu.parallel import make_distributed_plan, make_mesh
from spfft_tpu.serve.cluster import PodFrontend, SPMDCoalescer, _SPMDLane
from spfft_tpu.serve.executor import ServeExecutor
from spfft_tpu.serve.registry import PlanRegistry, signature_for
from spfft_tpu.utils.workloads import (even_plane_split,
                                       round_robin_stick_partition)

from test_util import (hermitian_triplets, random_sparse_triplets,
                       random_values)

SHARDS = 2


# ---------------------------------------------------------------------------
# plan builders (the 2-shard twins of test_batched's 4-shard scenarios)
# ---------------------------------------------------------------------------

def _c2c_plan(rng, **kw):
    from test_distributed import split_by_sticks, split_planes
    dims = (10, 9, 11)
    triplets = random_sparse_triplets(rng, dims)
    parts = split_by_sticks(triplets, dims, [2, 1])
    planes = split_planes(dims[2], [1, 2])
    plan = make_distributed_plan(TransformType.C2C, *dims, parts,
                                 planes, mesh=make_mesh(SHARDS),
                                 precision="double", **kw)

    def mkvals(batch):
        return [[random_values(rng, len(p)) for p in parts]
                for _ in range(batch)]

    return plan, mkvals


def _r2c_plan(rng, **kw):
    from test_distributed import split_by_sticks, split_planes
    dims = (8, 9, 10)
    triplets = hermitian_triplets(rng, dims)
    parts = split_by_sticks(triplets, dims, [1, 1])
    planes = split_planes(dims[2], [1, 1])
    plan = make_distributed_plan(TransformType.R2C, *dims, parts,
                                 planes, mesh=make_mesh(SHARDS),
                                 precision="double", **kw)

    def mkvals(batch):
        # hermitian-consistent values: sample a real field's spectrum
        # per batch entry (the test_batched r2c idiom)
        out = []
        for _ in range(batch):
            space = rng.standard_normal((dims[2], dims[1], dims[0]))
            freq = np.fft.fftn(space)
            row = []
            for p in parts:
                st = p.copy()
                for ax, d in enumerate(dims):
                    st[:, ax] = np.where(st[:, ax] < 0, st[:, ax] + d,
                                         st[:, ax])
                row.append(freq[st[:, 2], st[:, 1], st[:, 0]])
            out.append(row)
        return out

    return plan, mkvals


def _set_knobs(**kw):
    cfg = global_config()
    old = {k: cfg.get(k) for k in kw}
    for k, v in kw.items():
        cfg.set(k, v, source="test", reason="spmd coalesce test")
    return cfg, old


def _restore_knobs(cfg, old):
    for k, v in old.items():
        cfg.set(k, v, source="test", reason="restore after test")


def _counter_total(name):
    samples = obs.GLOBAL_COUNTERS.snapshot().get(
        name, {}).get("samples", {})
    return sum(samples.values())


# ---------------------------------------------------------------------------
# bit-exactness matrix: coalesced == serial, element for element
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("kind,K,fused", [
    ("c2c", 1, None),
    ("c2c", 1, False),
    ("c2c", 2, None),
    ("r2c", 1, None),
    ("r2c", 2, None),
])
def test_coalesce_bit_exact_matrix(kind, K, fused):
    """coalesce_backward / coalesce_forward demux to results that are
    BYTE-identical to per-request serial execution (np.array_equal, no
    tolerance) — the contract that lets the scheduler coalesce any
    interleaving it likes."""
    rng = np.random.default_rng(41 + 10 * K + (100 if kind == "r2c"
                                               else 0))
    build = _c2c_plan if kind == "c2c" else _r2c_plan
    plan, mkvals = build(rng, overlap_chunks=K, use_pallas=fused)
    for B in (1, 3):
        vals = mkvals(B)
        outs = plan.coalesce_backward(vals)
        assert len(outs) == B
        spaces = [plan.backward(v) for v in vals]
        for got, want in zip(outs, spaces):
            assert np.array_equal(np.asarray(got), np.asarray(want))
        fouts = plan.coalesce_forward(spaces, Scaling.FULL)
        for got, space in zip(fouts, spaces):
            want = np.asarray(plan.forward(space, Scaling.FULL))
            assert np.array_equal(np.asarray(got), want)


def test_coalesce_bit_exact_at_max_batch():
    """A full round at the default ``spmd_max_batch`` cap stays
    bit-exact (the largest batch the coalescer will ever form without
    a retune)."""
    rng = np.random.default_rng(42)
    plan, mkvals = _c2c_plan(rng)
    cap = int(ServeConfig.default("spmd_max_batch"))
    vals = mkvals(cap)
    outs = plan.coalesce_backward(vals)
    assert len(outs) == cap
    for got, v in zip(outs, vals):
        assert np.array_equal(np.asarray(got),
                              np.asarray(plan.backward(v)))


def test_coalesced_program_one_collective_round():
    """The collective count of the coalesced program is B-INVARIANT
    (the s8 fusion-proxy idiom): N coalesced requests ride a vmapped
    batch axis inside the SAME exchange collectives — one round per
    direction, not N — and the HLO grows sub-linearly in B."""
    import jax  # noqa: F401 — lowering requires an initialised backend

    rng = np.random.default_rng(43)
    plan, mkvals = _c2c_plan(rng)
    vals = mkvals(3)
    jitted = plan._batched_jits()["backward"]

    def lowered_text(B):
        batch = plan.shard_values_batch(vals[:B])
        return jitted.lower(batch, *plan._device_tables).as_text()

    def collectives(t):
        return len(re.findall(
            r"all_to_all|collective_permute|all_gather|all_reduce", t))

    t2, t3 = lowered_text(2), lowered_text(3)
    assert collectives(t2) == collectives(t3) > 0
    assert len(t3) < 1.5 * len(t2)


# ---------------------------------------------------------------------------
# the coalescing scheduler (duck-typed plans: scheduling, not math)
# ---------------------------------------------------------------------------

class _DuckPlan:
    """Duck-typed distributed plan recording how the lane executed it."""

    def __init__(self, block=None):
        self.rounds = []  # value-lists per coalesced round
        self.serial = []  # per-request fallback calls, in order
        self._block = block

    def coalesce_backward(self, values_list):
        if self._block is not None:
            self._block.wait(30)
        self.rounds.append(list(values_list))
        return [("out", v) for v in values_list]

    def coalesce_forward(self, space_list, scaling):
        self.rounds.append(list(space_list))
        return [("fwd", s, Scaling(scaling)) for s in space_list]


class _SerialPlan:
    """No batched entry points: the lane must fall back per-request."""

    def __init__(self, block_on=None, release=None):
        self.calls = []
        self._block_on = block_on
        self._release = release

    def backward(self, tag):
        if self._block_on is not None and tag == self._block_on:
            self._release.wait(30)
        self.calls.append(tag)
        return tag


def test_coalescer_n_requests_one_launch():
    """N same-signature requests inside one window drain into ONE
    launch: launches == 1, every request marked coalesced, the batch
    histogram records a single full round and the process counters
    agree."""
    before = _counter_total("spfft_cluster_spmd_coalesced_total")
    cfg, old = _set_knobs(spmd_batch_window=0.4)
    lane = SPMDCoalescer(max_workers=1)
    plan = _DuckPlan()
    try:
        futs = [lane.submit("sig-one-launch", plan, i, "backward",
                            Scaling.NONE, None) for i in range(3)]
        assert [f.result(timeout=30) for f in futs] == [
            ("out", 0), ("out", 1), ("out", 2)]
    finally:
        _restore_knobs(cfg, old)
        lane.close()
    assert plan.rounds == [[0, 1, 2]]
    s = lane.signals()
    assert s["spmd_launches"] == 1
    assert s["spmd_coalesced"] == 3
    assert s["spmd_batch_hist"] == {3: 1}
    assert s["spmd_queue_depth"] == 0
    assert s["spmd_launch_p50"] >= 0.0
    after = _counter_total("spfft_cluster_spmd_coalesced_total")
    assert after - before == 3


def test_coalescer_forward_round_carries_scaling():
    cfg, old = _set_knobs(spmd_batch_window=0.3)
    lane = SPMDCoalescer(max_workers=1)
    plan = _DuckPlan()
    try:
        futs = [lane.submit("sig-fwd", plan, i, "forward",
                            Scaling.FULL, None) for i in range(2)]
        got = [f.result(timeout=30) for f in futs]
        assert got == [("fwd", 0, Scaling.FULL), ("fwd", 1, Scaling.FULL)]
        assert plan.rounds == [[0, 1]]
    finally:
        _restore_knobs(cfg, old)
        lane.close()


def test_coalescer_edf_and_priority_ordering():
    """Queued requests drain high-priority first, then earliest
    deadline, then arrival — the executor's EDF discipline, re-aimed
    at the pod lane."""
    release = threading.Event()
    plan = _SerialPlan(block_on="first", release=release)
    cfg, old = _set_knobs(spmd_batch_window=0.0, spmd_max_batch=1)
    lane = SPMDCoalescer(max_workers=1)
    try:
        f0 = lane.submit("sig-edf", plan, "first", "backward",
                         Scaling.NONE, None)
        time.sleep(0.05)  # let the drainer block inside round 1
        f1 = lane.submit("sig-edf", plan, "late", "backward",
                         Scaling.NONE, None, timeout=30.0)
        f2 = lane.submit("sig-edf", plan, "soon", "backward",
                         Scaling.NONE, None, timeout=5.0)
        f3 = lane.submit("sig-edf", plan, "high", "backward",
                         Scaling.NONE, None, priority="high")
        release.set()
        for f in (f0, f1, f2, f3):
            f.result(timeout=30)
        assert plan.calls == ["first", "high", "soon", "late"]
    finally:
        release.set()
        _restore_knobs(cfg, old)
        lane.close()


def test_window_closes_early_on_member_deadline():
    """A member whose deadline lands inside the window closes it at
    the deadline instead of waiting the window out — the request is
    served, not expired."""
    cfg, old = _set_knobs(spmd_batch_window=5.0)
    lane = SPMDCoalescer(max_workers=1)
    plan = _DuckPlan()
    t0 = time.monotonic()
    try:
        fut = lane.submit("sig-deadline", plan, 7, "backward",
                          Scaling.NONE, None, timeout=0.25)
        assert fut.result(timeout=10) == ("out", 7)
        assert time.monotonic() - t0 < 3.0
    finally:
        _restore_knobs(cfg, old)
        lane.close()


def test_window_closes_early_on_high_priority():
    cfg, old = _set_knobs(spmd_batch_window=5.0)
    lane = SPMDCoalescer(max_workers=1)
    plan = _DuckPlan()
    t0 = time.monotonic()
    try:
        fut = lane.submit("sig-high", plan, 9, "backward",
                          Scaling.NONE, None, priority="high")
        assert fut.result(timeout=10) == ("out", 9)
        assert time.monotonic() - t0 < 3.0
    finally:
        _restore_knobs(cfg, old)
        lane.close()


def test_drain_time_purge_never_executes_expired():
    """A request whose deadline lapses while a round is in flight is
    purged at the NEXT drain (DeadlineExpiredError) and its payload
    never executes — the round-18 drain-time half of the deadline
    contract (admission used to check only at submit)."""
    release = threading.Event()
    plan = _SerialPlan(block_on="alive", release=release)
    cfg, old = _set_knobs(spmd_batch_window=0.0, spmd_max_batch=1)
    lane = SPMDCoalescer(max_workers=1)
    try:
        f1 = lane.submit("sig-purge", plan, "alive", "backward",
                         Scaling.NONE, None)
        time.sleep(0.05)  # round 1 is blocked inside execute
        f2 = lane.submit("sig-purge", plan, "doomed", "backward",
                         Scaling.NONE, None, timeout=0.02)
        time.sleep(0.1)  # f2's deadline lapses while f1 executes
        release.set()
        assert f1.result(timeout=30) == "alive"
        with pytest.raises(DeadlineExpiredError):
            f2.result(timeout=30)
        assert plan.calls == ["alive"]  # the doomed payload never ran
        assert lane.signals()["spmd_queue_depth"] == 0
    finally:
        release.set()
        _restore_knobs(cfg, old)
        lane.close()


def test_spmd_window_fault_site_fails_round_typed():
    """An armed ``cluster.spmd_window`` fault fails EVERY member of the
    round typed, and the lane serves the next round normally once the
    one-shot script is spent."""
    cfg, old = _set_knobs(spmd_batch_window=0.3)
    lane = SPMDCoalescer(max_workers=1)
    plan = _DuckPlan()
    faults.arm(FaultPlan(script="cluster.spmd_window@1"))
    try:
        futs = [lane.submit("sig-fault", plan, i, "backward",
                            Scaling.NONE, None) for i in range(2)]
        for f in futs:
            with pytest.raises(InjectedFault):
                f.result(timeout=30)
        assert plan.rounds == []  # the round died before the launch
        f3 = lane.submit("sig-fault", plan, 5, "backward",
                         Scaling.NONE, None, priority="high")
        assert f3.result(timeout=30) == ("out", 5)
    finally:
        faults.disarm()
        _restore_knobs(cfg, old)
        lane.close()
    assert lane.signals()["spmd_queue_depth"] == 0


def test_one_span_per_round_with_member_trace_ids():
    """One coalesced round emits exactly ONE ``cluster.spmd_execute``
    span, parented under the first traced member's root and carrying
    EVERY member's trace id in its args — the federated-telemetry view
    of 'two requests, one collective'."""
    obs.enable()
    tracer = obs.GLOBAL_TRACER
    tracer.reset()
    tracer.set_sample_rate(1.0)
    cfg, old = _set_knobs(spmd_batch_window=0.4)
    lane = SPMDCoalescer(max_workers=1)
    plan = _DuckPlan()
    roots = []
    try:
        for i in range(2):
            roots.append(tracer.begin(
                "cluster.request", cat="cluster",
                trace_id=tracer.new_trace_id(), track="pod"))
        futs = [lane.submit("sig-span", plan, i, "backward",
                            Scaling.NONE, roots[i]) for i in range(2)]
        for f in futs:
            f.result(timeout=30)
    finally:
        for root in roots:
            tracer.finish(root)
        _restore_knobs(cfg, old)
        lane.close()
        obs.disable()
    assert tracer.open_count() == 0, tracer.open_names()
    spans = [e for e in tracer.events() if isinstance(e, obs.Span)]
    execs = [s for s in spans if s.name == "cluster.spmd_execute"]
    assert len(execs) == 1
    ex = execs[0]
    assert ex.args["batch"] == 2
    assert ex.args["member_trace_ids"] == [r.trace_id for r in roots]
    assert ex.trace_id == roots[0].trace_id
    assert ex.parent_id == roots[0].span_id


def test_lane_alias_is_the_coalescer():
    """The round-19 `_SPMDLane` name still resolves (net/agent.py and
    older callers import it)."""
    assert _SPMDLane is SPMDCoalescer


# ---------------------------------------------------------------------------
# controller: spmd_batch_window / spmd_max_batch retune rules
# ---------------------------------------------------------------------------

def _signals(completed=0, launches=0, depth=0, p50=0.0, coalesced=0,
             hist=None):
    return {"completed": completed, "failed": 0, "queue_depth": 0,
            "max_queue_depth": 0, "queue_wait_p95": 0.0,
            "device_execute_p50": 0.0, "fused_rows": 0,
            "padded_rows": 0, "fused_hist": {}, "stage_s": 0.0,
            "dispatch_s": 0.0, "quarantines": 0,
            "rejected_queue_full": 0, "exchange_s": 0.0,
            "exchange_compute_s": 0.0, "latency_p99": 0.0,
            "spmd_launches": launches, "spmd_queue_depth": depth,
            "spmd_launch_p50": p50, "spmd_coalesced": coalesced,
            "spmd_batch_hist": hist or {}}


def test_controller_widens_spmd_window_on_backlog():
    """Depth >= 2 with window < launch p50 over two consecutive
    distributed steps doubles ``spmd_batch_window`` (arrivals during a
    launch keep missing the next window)."""
    cfg = ServeConfig()
    ctl = Controller(cfg, cooldown_steps=0)
    ctl.step(_signals(launches=1))  # calibration baseline
    d1 = ctl.step(_signals(launches=2, depth=3, p50=0.05))
    assert not [d for d in d1 if d.knob == "spmd_batch_window"]
    d2 = ctl.step(_signals(launches=3, depth=3, p50=0.05))
    moved = [d for d in d2 if d.knob == "spmd_batch_window"]
    assert len(moved) == 1
    assert moved[0].new == pytest.approx(0.004)
    assert "SPMD backlog" in moved[0].reason
    assert cfg.get("spmd_batch_window") == pytest.approx(0.004)


def test_controller_decays_fruitless_spmd_window():
    """A window above default that coalesced NOTHING this step halves
    back toward the default."""
    cfg = ServeConfig()
    cfg.set("spmd_batch_window", 0.008, source="test",
            reason="pre-widened window")
    ctl = Controller(cfg, cooldown_steps=0)
    ctl.step(_signals(launches=1, coalesced=5))
    d = ctl.step(_signals(launches=2, depth=0, coalesced=5))
    moved = [x for x in d if x.knob == "spmd_batch_window"]
    assert len(moved) == 1
    assert moved[0].new == pytest.approx(0.004)
    assert "coalesced nothing" in moved[0].reason


def test_controller_doubles_spmd_max_batch_when_rounds_full():
    cfg = ServeConfig()
    ctl = Controller(cfg, cooldown_steps=0)
    mb = cfg.get("spmd_max_batch")
    ctl.step(_signals(launches=1))
    d = ctl.step(_signals(launches=3, depth=2, hist={mb: 2}))
    moved = [x for x in d if x.knob == "spmd_max_batch"]
    assert len(moved) == 1
    assert moved[0].new == mb * 2
    assert "full collective rounds" in moved[0].reason


def test_controller_halves_oversized_spmd_max_batch():
    cfg = ServeConfig()
    cfg.set("spmd_max_batch", 32, source="test", reason="elevated cap")
    ctl = Controller(cfg, cooldown_steps=0)
    ctl.step(_signals(launches=1))
    d = ctl.step(_signals(launches=2, hist={2: 3}))
    moved = [x for x in d if x.knob == "spmd_max_batch"]
    assert len(moved) == 1
    assert moved[0].new == 16
    assert "far below cap" in moved[0].reason


def test_controller_idle_decays_spmd_knobs():
    """Idle steps (no serving AND no collective launches) retrace
    both spmd knobs toward their defaults."""
    cfg = ServeConfig()
    cfg.set("spmd_batch_window", 0.008, source="test", reason="widened")
    cfg.set("spmd_max_batch", 16, source="test", reason="doubled")
    ctl = Controller(cfg, cooldown_steps=0)
    ctl.step(_signals(launches=1))
    d = ctl.step(_signals(launches=1))  # launches delta 0 -> idle
    knobs = {x.knob: x.new for x in d}
    assert knobs.get("spmd_batch_window") == pytest.approx(0.004)
    assert knobs.get("spmd_max_batch") == 8
    assert all("idle" in x.reason for x in d)


def test_controller_ignores_spmd_rule_without_launches():
    """Steps where serving continued but no collective launched move
    neither spmd knob (the rule gates on the launches delta)."""
    cfg = ServeConfig()
    ctl = Controller(cfg, cooldown_steps=0)
    ctl.step(_signals(completed=1, launches=1))
    d = ctl.step(_signals(completed=5, launches=1, depth=4, p50=0.05))
    assert not [x for x in d
                if x.knob in ("spmd_batch_window", "spmd_max_batch")]


# ---------------------------------------------------------------------------
# PodFrontend integration: real distributed plan, real coalescing
# ---------------------------------------------------------------------------

N = 12
DIMS = (N, N, N)


@pytest.fixture(scope="module")
def pod_plans():
    trip = cutoff_stick_triplets(N, N, N, 0.9, hermitian=False)
    reg = PlanRegistry()
    sig, plan = reg.get_or_build(TransformType.C2C, *DIMS, trip,
                                 precision="double")
    parts = round_robin_stick_partition(trip, DIMS, SHARDS)
    planes = even_plane_split(DIMS[2], SHARDS)
    dplan = make_distributed_plan(TransformType.C2C, *DIMS, parts,
                                  planes, mesh=make_mesh(SHARDS),
                                  precision="double")
    dsig = signature_for(TransformType.C2C, *DIMS, trip,
                         precision="double", device_count=SHARDS)
    return {"sig": sig, "plan": plan, "dsig": dsig, "dplan": dplan,
            "parts": parts}


def _make_pod(p, hosts=("h0", "h1")):
    lanes = []
    for host in hosts:
        reg = PlanRegistry()
        reg.put(p["sig"], p["plan"])
        reg.put(p["dsig"], p["dplan"])
        lanes.append((host, ServeExecutor(reg)))
    return PodFrontend(lanes)


def _close_all(pod):
    pod.close()
    for lane in pod._lanes:
        lane.executor.close()


def _dvalues(p, rng):
    return [random_values(rng, len(part)) for part in p["parts"]]


def test_pod_concurrent_distributed_requests_coalesce(pod_plans):
    """Two concurrent same-signature distributed submits through the
    FRONTEND provably share one collective round: both bit-exact vs
    the serial oracle, the coalesced counter moves, and ONE
    ``cluster.spmd_execute`` span serves BOTH request roots."""
    p = pod_plans
    rng = np.random.default_rng(7)
    vals = [_dvalues(p, rng), _dvalues(p, rng)]
    oracle = [np.asarray(p["dplan"].backward(v)) for v in vals]
    # warm the batched jit outside the timed window so the coalescing
    # window is not raced by a first-call compile
    p["dplan"].coalesce_backward(vals)
    before = _counter_total("spfft_cluster_spmd_coalesced_total")
    obs.enable()
    tracer = obs.GLOBAL_TRACER
    tracer.reset()
    tracer.set_sample_rate(1.0)
    cfg, old = _set_knobs(spmd_batch_window=0.5)
    pod = _make_pod(p)
    try:
        futs = [pod.submit(p["dsig"], v) for v in vals]
        got = [np.asarray(f.result(timeout=60)) for f in futs]
    finally:
        _restore_knobs(cfg, old)
        _close_all(pod)
        obs.disable()
    for g, want in zip(got, oracle):
        assert np.array_equal(g, want)
    assert _counter_total("spfft_cluster_spmd_coalesced_total") \
        - before == 2
    assert tracer.open_count() == 0, tracer.open_names()
    spans = [e for e in tracer.events() if isinstance(e, obs.Span)]
    roots = [s for s in spans if s.name == "cluster.request"]
    execs = [s for s in spans if s.name == "cluster.spmd_execute"]
    assert len(roots) == 2
    assert len(execs) == 1
    assert execs[0].args["batch"] == 2
    assert sorted(execs[0].args["member_trace_ids"]) == \
        sorted(r.trace_id for r in roots)


def test_pod_affinity_routing_is_sticky(pod_plans):
    """Signature-affinity candidate ordering is deterministic per
    signature (same host leads every time) while still listing every
    alive lane as a failover candidate — remote coalescing windows
    only merge what routing co-locates."""
    p = pod_plans
    pod = _make_pod(p, hosts=("h0", "h1", "h2"))
    try:
        first = pod._affinity_candidates(p["dsig"])
        assert [l.host for l in first] \
            == [l.host for l in pod._affinity_candidates(p["dsig"])]
        assert sorted(l.host for l in first) == ["h0", "h1", "h2"]
        other = pod._affinity_candidates(p["sig"])
        assert sorted(l.host for l in other) == ["h0", "h1", "h2"]
    finally:
        _close_all(pod)

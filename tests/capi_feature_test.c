/*
 * C-API feature drive: exchange-type selection, pallas routing knob,
 * extended getter surface, and batched multi-transform execution — the
 * round-3 parity additions (reference: spfft_grid_create_distributed's
 * exchangeType parameter, grid.h:60-118; spfft_multi_transform_*,
 * multi_transform.h:37-72; the transform.h:84-245 getter set).
 *
 * Compiled and run by tests/test_capi.py::test_c_feature_drive. Prints
 * "OK" and exits 0 on success.
 */
#include <stdio.h>
#include <stdlib.h>
#include <math.h>

#include "spfft_tpu.h"

#define CHECK(expr)                                                       \
  do {                                                                    \
    int _c = (expr);                                                      \
    if (_c != SPFFT_TPU_SUCCESS) {                                        \
      fprintf(stderr, "%s failed: %s (%d)\n", #expr,                      \
              spfft_tpu_error_string(_c), _c);                            \
      return 1;                                                           \
    }                                                                     \
  } while (0)

#define DIM 8
#define SHARDS 4
#define BATCH 3

int main(void) {
  /* Fail loudly on header/library ABI skew before any call that would
   * otherwise read garbage trailing arguments. */
  if (spfft_tpu_abi_version() != SPFFT_TPU_ABI_VERSION) {
    fprintf(stderr, "ABI mismatch: header %d vs library %d\n",
            SPFFT_TPU_ABI_VERSION, spfft_tpu_abi_version());
    return 1;
  }
  CHECK(spfft_tpu_init(getenv("SPFFT_TPU_PACKAGE_PATH")));

  /* Dense stick set, split round-robin by stick id over SHARDS shards. */
  static int triplets[DIM * DIM * DIM][3];
  long long vps[SHARDS] = {0, 0, 0, 0};
  int pps[SHARDS];
  int n = 0;
  for (int r = 0; r < SHARDS; ++r) {
    for (int x = 0; x < DIM; ++x) {
      for (int y = 0; y < DIM; ++y) {
        if ((x * DIM + y) % SHARDS != r) continue;
        for (int z = 0; z < DIM; ++z) {
          triplets[n][0] = x;
          triplets[n][1] = y;
          triplets[n][2] = z;
          ++n;
        }
        vps[r] += DIM;
      }
    }
    pps[r] = DIM / SHARDS;
  }

  /* Distributed plan on the COMPACT_BUFFERED (Alltoallv-analogue)
   * exchange, auto pallas routing. */
  SpfftTpuPlan dplan = NULL;
  CHECK(spfft_tpu_plan_create_distributed(
      &dplan, SPFFT_TPU_TRANS_C2C, DIM, DIM, DIM, SHARDS, vps,
      &triplets[0][0], pps, SPFFT_TPU_PREC_SINGLE,
      SPFFT_TPU_EXCH_COMPACT_BUFFERED, SPFFT_TPU_PALLAS_AUTO));

  int exch = -1;
  CHECK(spfft_tpu_plan_exchange_type(dplan, &exch));
  if (exch != SPFFT_TPU_EXCH_COMPACT_BUFFERED) {
    fprintf(stderr, "exchange getter: got %d\n", exch);
    return 1;
  }
  long long gsize = 0, gelem = 0;
  CHECK(spfft_tpu_plan_global_size(dplan, &gsize));
  CHECK(spfft_tpu_plan_num_global_elements(dplan, &gelem));
  if (gsize != (long long)DIM * DIM * DIM || gelem != n) {
    fprintf(stderr, "global getters: %lld %lld\n", gsize, gelem);
    return 1;
  }
  int z_total = 0;
  long long elem_total = 0;
  for (int r = 0; r < SHARDS; ++r) {
    int off = -1, len = -1;
    long long slice = 0, elems = 0;
    CHECK(spfft_tpu_plan_local_z_offset(dplan, r, &off));
    CHECK(spfft_tpu_plan_local_z_length(dplan, r, &len));
    CHECK(spfft_tpu_plan_local_slice_size(dplan, r, &slice));
    CHECK(spfft_tpu_plan_num_local_elements(dplan, r, &elems));
    if (off != z_total || len != pps[r] ||
        slice != (long long)DIM * DIM * len || elems != vps[r]) {
      fprintf(stderr, "shard %d getters: off=%d len=%d slice=%lld "
              "elems=%lld\n", r, off, len, slice, elems);
      return 1;
    }
    z_total += len;
    elem_total += elems;
  }
  if (z_total != DIM || elem_total != n) return 1;
  /* out-of-range shard -> invalid parameter */
  int dummy;
  if (spfft_tpu_plan_local_z_offset(dplan, SHARDS, &dummy) !=
      SPFFT_TPU_INVALID_PARAMETER_ERROR) {
    fprintf(stderr, "shard range check missing\n");
    return 1;
  }

  /* Fused pair on the compact plan: identity under FULL scaling. */
  static float vals[DIM * DIM * DIM][2];
  static float out[DIM * DIM * DIM][2];
  for (int i = 0; i < n; ++i) {
    vals[i][0] = sinf(0.1f * i) * 0.5f;
    vals[i][1] = cosf(0.2f * i) * 0.5f;
  }
  CHECK(spfft_tpu_execute_pair(dplan, vals, SPFFT_TPU_FULL_SCALING, out));
  for (int i = 0; i < n; ++i) {
    if (fabsf(out[i][0] - vals[i][0]) > 1e-4f ||
        fabsf(out[i][1] - vals[i][1]) > 1e-4f) {
      fprintf(stderr, "compact pair mismatch at %d\n", i);
      return 1;
    }
  }
  CHECK(spfft_tpu_plan_destroy(dplan));

  /* Batched execution: BATCH value sets through ONE local plan handle
   * (fused batch), backward then forward, identity check. */
  SpfftTpuPlan lplan = NULL;
  CHECK(spfft_tpu_plan_create(&lplan, SPFFT_TPU_TRANS_C2C, DIM, DIM, DIM,
                              n, &triplets[0][0], SPFFT_TPU_PREC_SINGLE,
                              SPFFT_TPU_PALLAS_AUTO));
  int pallas = -1;
  CHECK(spfft_tpu_plan_pallas_active(lplan, &pallas));
  if (pallas != 0 && pallas != 1) return 1;

  static float bvals[BATCH][DIM * DIM * DIM][2];
  static float bspace[BATCH][DIM * DIM * DIM][2];
  static float bout[BATCH][DIM * DIM * DIM][2];
  for (int b = 0; b < BATCH; ++b) {
    for (int i = 0; i < n; ++i) {
      bvals[b][i][0] = sinf(0.05f * (i + 7 * b));
      bvals[b][i][1] = cosf(0.03f * (i + 11 * b));
    }
  }
  SpfftTpuPlan plans[BATCH] = {lplan, lplan, lplan};
  const void* vptrs[BATCH] = {bvals[0], bvals[1], bvals[2]};
  void* sptrs[BATCH] = {bspace[0], bspace[1], bspace[2]};
  const void* csptrs[BATCH] = {bspace[0], bspace[1], bspace[2]};
  void* optrs[BATCH] = {bout[0], bout[1], bout[2]};
  CHECK(spfft_tpu_multi_backward(BATCH, plans, vptrs, sptrs));
  CHECK(spfft_tpu_multi_forward(BATCH, plans, csptrs,
                                SPFFT_TPU_FULL_SCALING, optrs));
  for (int b = 0; b < BATCH; ++b) {
    for (int i = 0; i < n; ++i) {
      if (fabsf(bout[b][i][0] - bvals[b][i][0]) > 1e-4f ||
          fabsf(bout[b][i][1] - bvals[b][i][1]) > 1e-4f) {
        fprintf(stderr, "batch %d mismatch at %d\n", b, i);
        return 1;
      }
    }
  }
  CHECK(spfft_tpu_plan_destroy(lplan));

  printf("OK\n");
  return 0;
}

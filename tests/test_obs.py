"""Unit tests for spfft_tpu.obs: tracer lifecycle, sampling, bounded
buffer, counters, and both exporters (Chrome trace JSON structure,
Prometheus text round-tripped through the validating parser)."""

import json

import pytest

from spfft_tpu import obs
from spfft_tpu.obs import counters as obs_counters
from spfft_tpu.obs import trace as obs_trace
from spfft_tpu.obs.__main__ import REQUEST_STAGES, validate_trace_payload


@pytest.fixture(autouse=True)
def _obs_clean():
    yield
    obs.disable()
    obs.GLOBAL_TRACER.reset()
    obs.GLOBAL_TRACER.set_sample_rate(1.0)


# -- tracer -----------------------------------------------------------------

def test_span_begin_finish_lifecycle():
    t = obs_trace.Tracer()
    sp = t.begin("work", track="lane:normal")
    assert t.open_count() == 1
    t.finish(sp)
    assert t.open_count() == 0
    assert sp.t1 is not None and sp.t1 >= sp.t0
    assert sp.status == "ok"
    events = t.events()
    assert len(events) == 1 and events[0] is sp


def test_finish_is_idempotent():
    t = obs_trace.Tracer()
    sp = t.begin("work")
    t.finish(sp, status="error", error="Boom")
    t.finish(sp)  # second close: no-op, status keeps the first outcome
    assert sp.status == "error" and sp.error == "Boom"
    assert len(t.events()) == 1
    assert t.stats()["closed"] == 1


def test_span_context_manager_captures_error():
    t = obs_trace.Tracer()
    with pytest.raises(ValueError):
        with t.span("broken"):
            raise ValueError("no")
    (sp,) = t.events()
    assert sp.status == "error" and sp.error == "ValueError"
    assert t.open_count() == 0


def test_complete_records_measured_interval():
    t = obs_trace.Tracer()
    sp = t.complete("compile.plan_build", 1.0, 3.5, cat="compile",
                    track="compile")
    assert sp.duration == 2.5
    assert t.open_count() == 0
    assert t.stats()["started"] == t.stats()["closed"] == 1


def test_deterministic_sampling_rate():
    t = obs_trace.Tracer()
    t.set_sample_rate(0.25)
    hits = sum(t.sample() for _ in range(100))
    assert hits == 25
    t.set_sample_rate(0.0)
    assert not any(t.sample() for _ in range(10))
    t.set_sample_rate(1.0)
    assert all(t.sample() for _ in range(10))


def test_bounded_buffer_drops_oldest():
    t = obs_trace.Tracer(max_events=4)
    for i in range(6):
        t.instant(f"e{i}")
    assert len(t.events()) == 4
    assert t.stats()["dropped"] == 2
    assert t.events()[0]["name"] == "e2"  # oldest dropped first


def test_request_trace_close_settles_everything():
    t = obs_trace.Tracer()
    rt = obs_trace.RequestTrace(t, "high")
    rt.begin("serve.submit")
    rt.begin("serve.queue_wait")
    rt.finish("serve.submit")
    assert t.open_count() == 2  # root + queue_wait
    rt.close("error", "DeadlineExpiredError")
    assert t.open_count() == 0
    by_name = {s.name: s for s in t.events()}
    assert by_name["serve.submit"].status == "ok"
    assert by_name["serve.queue_wait"].status == "error"
    assert by_name["serve.request"].error == "DeadlineExpiredError"
    # trace ids are unique and shared within the request
    assert {s.trace_id for s in t.events()} == {rt.trace_id}
    rt.close()  # idempotent


def test_trace_ids_unique():
    t = obs_trace.Tracer()
    ids = {obs_trace.RequestTrace(t, "normal").trace_id
           for _ in range(32)}
    assert len(ids) == 32


# -- counters ---------------------------------------------------------------

def test_counters_inc_set_get():
    c = obs_counters.Counters()
    c.inc("spfft_x_total", 2, kind="a")
    c.inc("spfft_x_total", 3, kind="a")
    c.inc("spfft_x_total", 1, kind="b")
    c.set("spfft_g", 7.5)
    assert c.get("spfft_x_total", kind="a") == 5
    assert c.get("spfft_x_total", kind="b") == 1
    assert c.get("spfft_g") == 7.5
    assert c.get("spfft_missing") == 0.0
    snap = c.snapshot()
    assert snap["spfft_x_total"]["type"] == "counter"
    assert snap["spfft_g"]["type"] == "gauge"


def test_counters_reject_bad_names_and_type_conflicts():
    c = obs_counters.Counters()
    with pytest.raises(ValueError):
        c.inc("bad name")
    with pytest.raises(ValueError):
        c.inc("spfft_ok", **{"bad-label": 1})
    c.inc("spfft_dual")
    with pytest.raises(ValueError):
        c.set("spfft_dual", 1.0)


# -- prometheus exporter + parser -------------------------------------------

def test_prometheus_text_round_trips_counters():
    c = obs_counters.Counters()
    c.inc("spfft_demo_total", 4, help="Demo counter.", kind="x")
    c.set("spfft_demo_gauge", 1.5, help='Tricky "help" \\ text.')
    text = obs.prometheus_text(counters=c, timer=_EmptyTimer(),
                               tracer=obs_trace.Tracer())
    series = obs.parse_prometheus_text(text)
    assert series[("spfft_demo_total", (("kind", "x"),))] == 4
    assert series[("spfft_demo_gauge", ())] == 1.5
    # tracer lifecycle gauges always present
    assert ("spfft_trace_spans_open", ()) in series


def test_prometheus_text_covers_serve_metrics_and_timing():
    from spfft_tpu import timing
    from spfft_tpu.serve.metrics import ServeMetrics

    m = ServeMetrics()
    m.record_enqueue(3)
    m.record_batch(4, True, padded_rows=2, pinned=True,
                   stage_s=0.01, dispatch_s=0.02)
    for _ in range(5):
        m.record_request_done(0.005, priority="normal")
    m.record_request_done(0.009, failed=True)
    m.record_retry("high")
    timer = timing.Timer()
    with timer.scoped("backward"):
        with timer.scoped("fft"):
            pass
    text = obs.prometheus_text(metrics=m, timer=timer,
                               counters=obs_counters.Counters(),
                               tracer=obs_trace.Tracer())
    series = obs.parse_prometheus_text(text)
    assert series[("spfft_serve_completed_total", ())] == 5
    assert series[("spfft_serve_failed_total", ())] == 1
    assert series[("spfft_serve_padded_rows_total", ())] == 2
    assert series[("spfft_serve_batch_size_total",
                   (("path", "fused"), ("size", "4")))] == 1
    assert series[("spfft_serve_retries_total", ())] == 1
    assert series[("spfft_serve_retries_by_class_total",
                   (("class", "high"),))] == 1
    assert series[("spfft_serve_health", (("state", "healthy"),))] == 1
    assert series[("spfft_serve_latency_seconds",
                   (("quantile", "p50"),))] > 0
    assert series[("spfft_timing_calls_total",
                   (("scope", "backward/fft"),))] == 1


def test_prometheus_parser_rejects_bad_text():
    with pytest.raises(ValueError):
        obs.parse_prometheus_text("no_type_declared 1\n")
    with pytest.raises(ValueError):
        obs.parse_prometheus_text(
            "# TYPE spfft_a counter\nspfft_a{unclosed 1\n")
    with pytest.raises(ValueError):
        obs.parse_prometheus_text(
            "# TYPE spfft_a counter\nspfft_a 1\nspfft_a 2\n")
    with pytest.raises(ValueError):
        obs.parse_prometheus_text(
            "# TYPE spfft_a bogus\nspfft_a 1\n")


class _EmptyTimer:
    def process(self):
        class _R:
            @staticmethod
            def json():
                return '{"timings": []}'
        return _R()


# -- chrome trace exporter + validation -------------------------------------

def test_export_trace_structure(tmp_path):
    t = obs_trace.Tracer()
    rt = obs_trace.RequestTrace(t, "normal")
    rt.begin("serve.submit")
    rt.finish("serve.submit")
    rt.close()
    t.instant("serve.retry", track="lane:normal", args={"attempt": 1})
    t.counter("exchange.chunk_wire_bytes", {"bwd": 100, "fwd": 50},
              track="exchange")
    path = tmp_path / "t.json"
    payload = obs.export_trace(str(path), tracer=t)
    on_disk = json.loads(path.read_text())
    assert on_disk == payload
    phases = {e["ph"] for e in payload["traceEvents"]}
    assert phases == {"M", "X", "i", "C"}
    xs = [e for e in payload["traceEvents"] if e["ph"] == "X"]
    root = next(e for e in xs if e["name"] == "serve.request")
    child = next(e for e in xs if e["name"] == "serve.submit")
    assert child["args"]["parent_span_id"] == root["args"]["span_id"]
    assert child["args"]["trace_id"] == root["args"]["trace_id"]
    # track metadata names the lane
    threads = {e["args"]["name"] for e in payload["traceEvents"]
               if e["ph"] == "M" and e["name"] == "thread_name"}
    assert "lane:normal" in threads and "exchange" in threads
    assert validate_trace_payload(payload) == []


def test_validate_trace_payload_catches_problems():
    assert validate_trace_payload({}) == ["traceEvents missing or empty"]
    bad = {"traceEvents": [{"ph": "X", "name": "a", "ts": 0,
                            "dur": -1, "pid": 1, "tid": 1}]}
    assert any("bad dur" in f for f in validate_trace_payload(bad))
    ok = {"traceEvents": [{"ph": "X", "name": "a", "ts": 0, "dur": 1,
                           "pid": 1, "tid": 1}]}
    assert any("missing from trace" in f for f in
               validate_trace_payload(ok, require_names=["b"]))
    leaky = {"traceEvents": ok["traceEvents"],
             "otherData": {"tracer": {"open": 2}}}
    assert any("unclosed" in f for f in validate_trace_payload(leaky))


def test_request_stages_constant_covers_the_pipeline():
    assert len(REQUEST_STAGES) == 8
    assert REQUEST_STAGES[0] == "serve.submit"
    assert REQUEST_STAGES[-1] == "serve.resolve"


# -- recorder helpers -------------------------------------------------------

def test_record_compile_counters_and_span():
    obs.GLOBAL_TRACER.reset()
    before = obs.GLOBAL_COUNTERS.get("spfft_compile_events_total",
                                     kind="unit_test")
    obs.record_compile("unit_test", 0.5, batch=4)
    assert obs.GLOBAL_COUNTERS.get("spfft_compile_events_total",
                                   kind="unit_test") == before + 1
    assert obs.GLOBAL_COUNTERS.get("spfft_compile_seconds_total",
                                   kind="unit_test") >= 0.5
    # span only when tracing is enabled
    assert not [e for e in obs.GLOBAL_TRACER.events()
                if getattr(e, "name", None) == "compile.unit_test"]
    obs.enable()
    obs.record_compile("unit_test", 0.25, batch=8)
    spans = [e for e in obs.GLOBAL_TRACER.events()
             if getattr(e, "name", None) == "compile.unit_test"]
    assert len(spans) == 1 and spans[0].args["batch"] == 8
    assert abs(spans[0].duration - 0.25) < 1e-6


def test_record_hlo_counts_surfaces_metrics():
    txt = ("stablehlo.all_to_all foo\nstablehlo.all_to_all bar\n"
           "stablehlo.collective_permute baz\n")
    compiled = "all-to-all-start x\nall-to-all-done x\n"
    out = obs.record_hlo_counts("unit", lowered_text=txt,
                                compiled_text=compiled)
    assert out["collectives"]["all_to_all"] == 2
    assert out["collectives"]["collective_permute"] == 1
    assert out["async_split"]["starts"] == 1
    assert obs.GLOBAL_COUNTERS.get("spfft_hlo_collectives",
                                   label="unit", op="all_to_all") == 2
    assert obs.GLOBAL_COUNTERS.get("spfft_hlo_async_starts",
                                   label="unit") == 1

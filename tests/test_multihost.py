"""Multi-host plan construction and cross-host consistency validation.

The multi-process collectives degenerate to local computation with one
process; these tests exercise (a) the single-process fallbacks end-to-end,
(b) the digest/mismatch logic directly with synthetic multi-process inputs —
mirroring the reference's allreduce parameter-mismatch detection tests
(reference: grid_internal.cpp:148-167, parameters.cpp:81-109)."""

import numpy as np
import pytest

from spfft_tpu import (ParameterMismatchError, TransformType,
                       build_distributed_plan,
                       build_distributed_plan_multihost, plan_fingerprint,
                       validate_consistent)
from spfft_tpu.parallel import multihost

from test_util import random_sparse_triplets


def _split_triplets(rng, dims, shards):
    triplets = random_sparse_triplets(rng, dims)
    # group by stick (z-sticks must stay whole, README.md:8)
    keys = triplets[:, 0] * dims[1] + triplets[:, 1]
    uniq = np.unique(keys)
    assign = rng.integers(0, shards, len(uniq))
    return [triplets[np.isin(keys, uniq[assign == s])] for s in range(shards)]


def test_multihost_build_single_process_matches_local():
    rng = np.random.default_rng(3)
    dims = (11, 12, 13)
    parts = _split_triplets(rng, dims, 4)
    planes = [4, 3, 3, 3]
    a = build_distributed_plan(TransformType.C2C, *dims, parts, planes)
    b = build_distributed_plan_multihost(TransformType.C2C, *dims, parts,
                                         planes)
    assert plan_fingerprint(a) == plan_fingerprint(b)
    validate_consistent(b)  # no-op single-process, must not raise


def test_fingerprint_sensitivity():
    rng = np.random.default_rng(4)
    dims = (11, 12, 13)
    parts = _split_triplets(rng, dims, 2)
    a = build_distributed_plan(TransformType.C2C, *dims, parts, [7, 6])
    b = build_distributed_plan(TransformType.C2C, *dims, parts, [6, 7])
    assert plan_fingerprint(a) != plan_fingerprint(b)
    # moving a stick between shards changes the digest
    c = build_distributed_plan(TransformType.C2C, *dims,
                               [parts[1], parts[0]], [7, 6])
    assert plan_fingerprint(a) != plan_fingerprint(c)
    # identical rebuild is stable
    a2 = build_distributed_plan(TransformType.C2C, *dims, parts, [7, 6])
    assert plan_fingerprint(a) == plan_fingerprint(a2)


def test_digest_mismatch_detection():
    local = bytes(range(16))
    same = np.tile(np.frombuffer(local, np.uint8), (3, 1))
    multihost._check_digests(same, local)  # all agree
    bad = same.copy()
    bad[1, 0] ^= 0xFF
    with pytest.raises(ParameterMismatchError, match=r"\[1\]"):
        multihost._check_digests(bad, local)


def test_pad_gather_roundtrip():
    t0 = np.array([[0, 0, 0], [1, 2, 3]])
    t1 = np.zeros((0, 3), np.int64)
    block = multihost._pad_gather_triplets([t0, t1], 5)
    assert block.shape == (2, 5, 4)
    rec0 = block[0][block[0, :, 3] == 1][:, :3]
    np.testing.assert_array_equal(rec0, t0)
    assert (block[1, :, 3] == 0).all()


def test_shards_per_process_mismatch():
    rng = np.random.default_rng(5)
    dims = (8, 8, 8)
    parts = _split_triplets(rng, dims, 2)
    with pytest.raises(ParameterMismatchError):
        build_distributed_plan_multihost(TransformType.C2C, *dims, parts,
                                         [4, 4], shards_per_process=3)


def test_initialize_single_process_noop():
    multihost.initialize()  # no coordinator -> no-op

"""Multi-host plan construction and cross-host consistency validation.

The multi-process collectives degenerate to local computation with one
process; these tests exercise (a) the single-process fallbacks end-to-end,
(b) the digest/mismatch logic directly with synthetic multi-process inputs —
mirroring the reference's allreduce parameter-mismatch detection tests
(reference: grid_internal.cpp:148-167, parameters.cpp:81-109)."""

import threading

import numpy as np
import pytest

from spfft_tpu import (ParameterMismatchError, TransformType,
                       build_distributed_plan,
                       build_distributed_plan_multihost, plan_fingerprint,
                       validate_consistent)
from spfft_tpu.parallel import multihost

from test_util import random_sparse_triplets


class StubWorld:
    """A P-process world for the injectable multihost collective: each
    simulated process runs on its own thread; ``allgather`` is a
    barrier-synchronised stack of every process's contribution — the same
    lockstep semantics as ``multihost_utils.process_allgather``."""

    def __init__(self, num_processes: int):
        self.num_processes = num_processes
        self._barrier = threading.Barrier(num_processes, timeout=30)
        self._slots = [None] * num_processes

    def collective(self, process_index: int):
        def allgather(x):
            self._slots[process_index] = np.asarray(x)
            self._barrier.wait()  # everyone wrote
            out = np.stack([np.asarray(s) for s in self._slots])
            self._barrier.wait()  # everyone read before the next round
            return out
        return (allgather, self.num_processes, process_index)

    def run(self, fn):
        """Run ``fn(process_index, collective)`` on every process; returns
        the per-process result or raised exception."""
        results = [None] * self.num_processes

        def worker(p):
            try:
                results[p] = ("ok", fn(p, self.collective(p)))
            except Exception as e:  # noqa: BLE001 - surfaced to the test
                results[p] = ("err", e)

        threads = [threading.Thread(target=worker, args=(p,))
                   for p in range(self.num_processes)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
        return results


def _split_triplets(rng, dims, shards):
    triplets = random_sparse_triplets(rng, dims)
    # group by stick (z-sticks must stay whole, README.md:8)
    keys = triplets[:, 0] * dims[1] + triplets[:, 1]
    uniq = np.unique(keys)
    assign = rng.integers(0, shards, len(uniq))
    return [triplets[np.isin(keys, uniq[assign == s])] for s in range(shards)]


def test_multihost_build_single_process_matches_local():
    rng = np.random.default_rng(3)
    dims = (11, 12, 13)
    parts = _split_triplets(rng, dims, 4)
    planes = [4, 3, 3, 3]
    a = build_distributed_plan(TransformType.C2C, *dims, parts, planes)
    b = build_distributed_plan_multihost(TransformType.C2C, *dims, parts,
                                         planes)
    assert plan_fingerprint(a) == plan_fingerprint(b)
    validate_consistent(b)  # no-op single-process, must not raise


def test_fingerprint_sensitivity():
    rng = np.random.default_rng(4)
    dims = (11, 12, 13)
    parts = _split_triplets(rng, dims, 2)
    a = build_distributed_plan(TransformType.C2C, *dims, parts, [7, 6])
    b = build_distributed_plan(TransformType.C2C, *dims, parts, [6, 7])
    assert plan_fingerprint(a) != plan_fingerprint(b)
    # moving a stick between shards changes the digest
    c = build_distributed_plan(TransformType.C2C, *dims,
                               [parts[1], parts[0]], [7, 6])
    assert plan_fingerprint(a) != plan_fingerprint(c)
    # identical rebuild is stable
    a2 = build_distributed_plan(TransformType.C2C, *dims, parts, [7, 6])
    assert plan_fingerprint(a) == plan_fingerprint(a2)


def test_digest_mismatch_detection():
    local = bytes(range(16))
    same = np.tile(np.frombuffer(local, np.uint8), (3, 1))
    multihost._check_digests(same, local)  # all agree
    bad = same.copy()
    bad[1, 0] ^= 0xFF
    with pytest.raises(ParameterMismatchError, match=r"\[1\]"):
        multihost._check_digests(bad, local)


def test_pad_gather_roundtrip():
    t0 = np.array([[0, 0, 0], [1, 2, 3]])
    t1 = np.zeros((0, 3), np.int64)
    block = multihost._pad_gather_triplets([t0, t1], 5)
    assert block.shape == (2, 5, 4)
    rec0 = block[0][block[0, :, 3] == 1][:, :3]
    np.testing.assert_array_equal(rec0, t0)
    assert (block[1, :, 3] == 0).all()


def test_shards_per_process_mismatch():
    rng = np.random.default_rng(5)
    dims = (8, 8, 8)
    parts = _split_triplets(rng, dims, 2)
    with pytest.raises(ParameterMismatchError):
        build_distributed_plan_multihost(TransformType.C2C, *dims, parts,
                                         [4, 4], shards_per_process=3)


def test_initialize_single_process_noop():
    multihost.initialize()  # no coordinator -> no-op


@pytest.mark.parametrize("num_processes,shards_per_process",
                         [(2, 2), (3, 1)])
def test_multihost_build_stub_world_matches_global(num_processes,
                                                   shards_per_process):
    """2- and 3-process builds through the real lockstep protocol (stub
    collective): every process ends with the identical global plan, equal
    to the single-process build over all shards."""
    rng = np.random.default_rng(7)
    dims = (11, 12, 13)
    shards = num_processes * shards_per_process
    parts = _split_triplets(rng, dims, shards)
    base, extra = divmod(dims[2], shards)
    planes = [base + (1 if s < extra else 0) for s in range(shards)]
    expect = build_distributed_plan(TransformType.C2C, *dims, parts, planes)

    def one_process(p, collective):
        lo = p * shards_per_process
        hi = lo + shards_per_process
        return build_distributed_plan_multihost(
            TransformType.C2C, *dims, parts[lo:hi], planes[lo:hi],
            collective=collective)

    results = StubWorld(num_processes).run(one_process)
    for status, plan in results:
        assert status == "ok", plan
        assert plan_fingerprint(plan) == plan_fingerprint(expect)


def test_multihost_build_stub_world_empty_shard():
    """A process owning only an empty shard (zero sticks) is valid — the
    reference supports empty ranks (execution guarded on numLocalZSticks>0,
    execution_host.cpp:167-179)."""
    rng = np.random.default_rng(8)
    dims = (8, 9, 10)
    parts = _split_triplets(rng, dims, 1) + [np.zeros((0, 3), np.int64)]
    planes = [6, 4]
    expect = build_distributed_plan(TransformType.C2C, *dims, parts, planes)

    def one_process(p, collective):
        return build_distributed_plan_multihost(
            TransformType.C2C, *dims, [parts[p]], [planes[p]],
            collective=collective)

    results = StubWorld(2).run(one_process)
    for status, plan in results:
        assert status == "ok", plan
        assert plan_fingerprint(plan) == plan_fingerprint(expect)


def test_multihost_build_stub_world_unequal_shard_counts():
    """Unequal shards_per_process across processes fails fast on EVERY
    process, before any data-shaped collective (which would hang)."""
    rng = np.random.default_rng(9)
    dims = (8, 9, 10)
    parts = _split_triplets(rng, dims, 3)

    def one_process(p, collective):
        mine = [parts[0], parts[1]] if p == 0 else [parts[2]]
        planes = [5, 5] if p == 0 else [10]
        return build_distributed_plan_multihost(
            TransformType.C2C, *dims, mine, planes, collective=collective)

    results = StubWorld(2).run(one_process)
    for status, err in results:
        assert status == "err"
        assert isinstance(err, ParameterMismatchError)
        assert "shards_per_process differs" in str(err)


def test_multihost_build_stub_world_mismatched_dims():
    """A process passing different dims builds a different global plan; the
    digest validation raises on every process, naming the disagreement
    (reference: grid_internal.cpp:148-167)."""
    rng = np.random.default_rng(10)
    dims = (8, 9, 10)
    parts = _split_triplets(rng, dims, 2)

    def one_process(p, collective):
        my_dims = dims if p == 0 else (8, 9, 11)
        planes = 5 if p == 0 else 6
        return build_distributed_plan_multihost(
            TransformType.C2C, *my_dims, [parts[p]], [planes],
            collective=collective)

    results = StubWorld(2).run(one_process)
    # process 1's plan has a different dim_z: at least the digest check
    # must catch it on every process (plane-sum validation may fire first
    # on either side — both are ParameterMismatchError by design)
    for status, err in results:
        assert status == "err"
        assert isinstance(err, ParameterMismatchError)


def test_validate_consistent_stub_world_mismatch():
    rng = np.random.default_rng(11)
    dims = (8, 9, 10)
    parts = _split_triplets(rng, dims, 2)
    plans = [
        build_distributed_plan(TransformType.C2C, *dims, parts, [5, 5]),
        build_distributed_plan(TransformType.C2C, *dims, parts, [6, 4]),
    ]

    def one_process(p, collective):
        return validate_consistent(plans[p], collective=collective)

    results = StubWorld(2).run(one_process)
    for p, (status, err) in enumerate(results):
        assert status == "err"
        assert isinstance(err, ParameterMismatchError)
        other = 1 - p
        assert f"[{other}]" in str(err)


def test_validate_consistent_stub_world_agreement():
    rng = np.random.default_rng(12)
    dims = (8, 9, 10)
    parts = _split_triplets(rng, dims, 2)
    plan = build_distributed_plan(TransformType.C2C, *dims, parts, [5, 5])

    def one_process(p, collective):
        validate_consistent(plan, collective=collective)
        return True

    for status, ok in StubWorld(3).run(one_process):
        assert status == "ok" and ok


def test_zero_shards_per_process_rejected():
    with pytest.raises(ParameterMismatchError, match=">= 1"):
        build_distributed_plan_multihost(
            TransformType.C2C, 8, 8, 8, [], [], shards_per_process=0)
    with pytest.raises(ParameterMismatchError, match=">= 1"):
        build_distributed_plan_multihost(TransformType.C2C, 8, 8, 8, [], [])

"""Shared fixtures: randomized sparse index generation and dense FFT oracles.

Reimplements the semantics of the reference test fixtures
(reference: tests/test_util/generate_indices.hpp:38-136 — seeded random stick
sets with ~0.7 stick fraction and ~0.7 z-fill, optional centered conversion)
and the dense-oracle comparison strategy
(reference: tests/test_util/test_transform.hpp:40-47 — every sparse transform
is checked against a dense 3D FFT of the same cube; here numpy.fft instead of
FFTW).

Layouts: dense cubes and space-domain slabs are indexed [z, y, x] (the
reference's memory order (z*Ny + y)*Nx + x, docs/source/details.rst
"Indexing"); triplets are (x, y, z).
"""

from __future__ import annotations

import numpy as np


def random_sparse_triplets(rng: np.random.Generator, dims,
                           stick_fraction: float = 0.7,
                           fill_fraction: float = 0.7) -> np.ndarray:
    """Random C2C sparse set: a subset of (x, y) sticks, each with a random
    subset of z values (reference: generate_indices.hpp:38-85)."""
    nx, ny, nz = dims
    num_keys = nx * ny
    num_sticks = max(1, int(round(stick_fraction * num_keys)))
    keys = rng.choice(num_keys, size=num_sticks, replace=False)
    triplets = []
    for key in np.sort(keys):
        x, y = int(key) // ny, int(key) % ny
        num_z = max(1, int(round(fill_fraction * nz)))
        for z in np.sort(rng.choice(nz, size=num_z, replace=False)):
            triplets.append((x, y, int(z)))
    return np.asarray(triplets, np.int32)


def center_triplets(triplets: np.ndarray, dims) -> np.ndarray:
    """Convert storage triplets to centered (negative-frequency) indexing
    (reference: generate_indices.hpp:87-99): i -> i - n for i > n/2."""
    out = triplets.astype(np.int64).copy()
    for axis, n in enumerate(dims):
        col = out[:, axis]
        out[:, axis] = np.where(col > n // 2, col - n, col)
    return out.astype(np.int32)


def storage_triplets(triplets: np.ndarray, dims) -> np.ndarray:
    """Map possibly-centered triplets to storage indices."""
    out = triplets.astype(np.int64).copy()
    for axis, n in enumerate(dims):
        col = out[:, axis]
        out[:, axis] = np.where(col < 0, col + n, col)
    return out.astype(np.int64)


def dense_cube_from_values(triplets: np.ndarray, values: np.ndarray,
                           dims) -> np.ndarray:
    """Place sparse values into a dense [z, y, x] frequency cube."""
    nx, ny, nz = dims
    cube = np.zeros((nz, ny, nx), np.complex128)
    st = storage_triplets(triplets, dims)
    cube[st[:, 2], st[:, 1], st[:, 0]] = values
    return cube


def dense_backward(cube: np.ndarray) -> np.ndarray:
    """Unnormalised inverse DFT of the dense cube — the backward-transform
    oracle (details.rst "Transform Definition": e^{+2πi k n / N}, no
    normalisation)."""
    return np.fft.ifftn(cube) * cube.size


def dense_forward(space: np.ndarray) -> np.ndarray:
    """Forward DFT oracle (no scaling)."""
    return np.fft.fftn(space)


def sample_cube(cube: np.ndarray, triplets: np.ndarray, dims) -> np.ndarray:
    """Gather dense-cube values at sparse triplet positions."""
    st = storage_triplets(triplets, dims)
    return cube[st[:, 2], st[:, 1], st[:, 0]]


def random_values(rng: np.random.Generator, n: int) -> np.ndarray:
    return (rng.uniform(-1, 1, n) + 1j * rng.uniform(-1, 1, n))


def tolerance_for(precision: str, oracle: np.ndarray) -> float:
    """Comparison tolerance scaled to the oracle magnitude. The reference
    checks 1e-6 absolute in double (test_check_values.hpp:46-50); single
    precision gets a proportionally looser bound."""
    scale = max(1.0, float(np.max(np.abs(oracle))) if oracle.size else 1.0)
    return (1e-9 if precision == "double" else 3e-5) * scale


def hermitian_triplets(rng: np.random.Generator, dims,
                       mirror_some_columns: bool = True):
    """Full R2C stick set following the hermitian provision rules
    (details.rst "Real-To-Complex Transforms", reference
    test_transform.hpp:221-276):

    * all sticks with x in [1, nx//2] (full z columns),
    * at x = 0: one z-column per ±y pair — the +y storage column, or (if
      ``mirror_some_columns``) randomly the mirror ny-y column instead,
    * at x = 0, y = 0: only z in [0, nz//2] (redundant half omitted).
    """
    nx, ny, nz = dims
    triplets = []
    # x = 0, y = 0 stick: non-redundant half only
    for z in range(nz // 2 + 1):
        triplets.append((0, 0, z))
    # x = 0, y != 0: one column per pair
    seen = set()
    for y in range(1, ny):
        pair = frozenset((y, (ny - y) % ny))
        if pair in seen:
            continue
        seen.add(pair)
        y_pick = y
        if mirror_some_columns and (ny - y) % ny != y and rng.random() < 0.5:
            y_pick = (ny - y) % ny
        for z in range(nz):
            triplets.append((0, y_pick, z))
    # x in [1, nx//2]: full sticks
    for x in range(1, nx // 2 + 1):
        for y in range(ny):
            for z in range(nz):
                triplets.append((x, y, z))
    return np.asarray(triplets, np.int32)

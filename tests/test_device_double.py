"""On-device double precision (ops/dsdft.py + the plan's _ds mode).

The CPU suite forces the mode with SPFFT_TPU_DEVICE_DOUBLE=force — the
double-single arithmetic is pure f32 and bit-identical across backends;
tests_tpu/ re-runs the oracle check on the real chip. Reference bar:
f64 as the default precision with the 1e-6 oracle tolerance
(reference: tests/test_util/test_check_values.hpp:46-50) — this mode
measures ~1e-13-class, four orders below the 2e-11 contract envelope.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from spfft_tpu import Scaling, TransformType, make_local_plan
from spfft_tpu.errors import InvalidParameterError
from spfft_tpu.ops import dft, dsdft
from spfft_tpu.plan import predicted_rel_error


@pytest.fixture
def force_ds(monkeypatch):
    monkeypatch.setenv("SPFFT_TPU_DEVICE_DOUBLE", "force")


def _sparse(n, rng, frac=0.4):
    tr = np.stack(np.meshgrid(*[np.arange(n)] * 3, indexing="ij"),
                  -1).reshape(-1, 3)
    return tr[rng.uniform(size=len(tr)) < frac]


def test_ds_cdft_matches_f64_oracle():
    rng = np.random.default_rng(0)
    for n in (13, 100, 256):
        x = (rng.standard_normal((23, n))
             + 1j * rng.standard_normal((23, n)))
        m = dsdft.ds_c2c_mats(n, dft.FORWARD, 1.0 / n)
        rh, rl = dsdft.split_host_f64(x.real)
        ih, il = dsdft.split_host_f64(x.imag)
        yrh, yrl, yih, yil = dsdft.ds_cdft_last(
            *map(jnp.asarray, (rh, rl, ih, il)), m)
        got = (dsdft.combine_host_f64(yrh, yrl)
               + 1j * dsdft.combine_host_f64(yih, yil))
        ref = np.fft.fft(x, axis=-1) / n
        rel = np.linalg.norm(got - ref) / np.linalg.norm(ref)
        assert rel < 5e-13, (n, rel)


def test_two_sum_exact_under_jit():
    """The Knuth TwoSum must survive jit + the algebraic simplifier
    (the unbarriered form measured a 2.5e-8 plateau)."""
    import jax
    a = jnp.asarray([1.0, 1e-8, -1.0], jnp.float32)
    b = jnp.asarray([1e-8, 1.0, 1.0000001], jnp.float32)
    t, e = jax.jit(dsdft._two_sum)(a, b)
    exact = (np.asarray(a, np.float64) + np.asarray(b, np.float64))
    got = np.asarray(t, np.float64) + np.asarray(e, np.float64)
    np.testing.assert_array_equal(got, exact)


def test_full_plan_round_trip(force_ds):
    rng = np.random.default_rng(1)
    n = 12
    tr = _sparse(n, rng)
    plan = make_local_plan(TransformType.C2C, n, n, n, tr,
                           precision="double")
    assert plan._ds
    vals = rng.standard_normal(len(tr)) + 1j * rng.standard_normal(len(tr))
    space = plan.backward(vals)
    assert space.dtype == np.float64
    got = space[..., 0] + 1j * space[..., 1]
    cube = np.zeros((n, n, n), np.complex128)
    cube[tr[:, 2], tr[:, 1], tr[:, 0]] = vals
    oracle = np.fft.ifftn(cube) * cube.size
    assert np.linalg.norm(got - oracle) / np.linalg.norm(oracle) < 1e-13
    out = plan.forward(space, Scaling.FULL)
    gv = out[:, 0] + 1j * out[:, 1]
    assert np.linalg.norm(gv - vals) / np.linalg.norm(vals) < 1e-13
    fused = plan.apply_pointwise(vals, scaling=Scaling.FULL)
    # fused skips the host combine/re-split between halves, so its ds
    # channels are non-canonical: same f64 values to the slice-ladder
    # floor (~2^-42), not bit-identical
    np.testing.assert_allclose(fused, out, atol=1e-12, rtol=0)


def test_centered_indexing_and_batched(force_ds):
    rng = np.random.default_rng(2)
    n = 10
    tr = _sparse(n, rng)
    trc = tr.copy()
    trc[trc > n // 2] -= n
    plan = make_local_plan(TransformType.C2C, n, n, n, trc,
                           precision="double")
    assert plan._ds
    vals = [rng.standard_normal(len(tr)) + 1j * rng.standard_normal(len(tr))
            for _ in range(2)]
    stacked = plan.backward_batched(vals)
    assert stacked.dtype == np.float64
    for i, v in enumerate(vals):
        single = plan.backward(v)
        np.testing.assert_allclose(stacked[i], single, atol=1e-15, rtol=0)



def test_ds_beats_single_by_orders_of_magnitude(force_ds):
    """The point of the mode: same plan single vs double, > 1e4x."""
    rng = np.random.default_rng(3)
    n = 16
    tr = _sparse(n, rng)
    vals = rng.standard_normal(len(tr)) + 1j * rng.standard_normal(len(tr))
    cube = np.zeros((n, n, n), np.complex128)
    cube[tr[:, 2], tr[:, 1], tr[:, 0]] = vals
    oracle = np.fft.ifftn(cube) * cube.size

    def rel(precision):
        plan = make_local_plan(TransformType.C2C, n, n, n, tr,
                               precision=precision)
        v = vals if precision == "double" else vals.astype(np.complex64)
        s = np.asarray(plan.backward(v))
        got = s[..., 0] + 1j * s[..., 1]
        return np.linalg.norm(got - oracle) / np.linalg.norm(oracle)

    assert rel("double") < 1e-13
    assert rel("double") < rel("single") / 1e4


def test_pointwise_fn_rejected_with_guidance(force_ds):
    rng = np.random.default_rng(4)
    n = 8
    tr = _sparse(n, rng)
    plan = make_local_plan(TransformType.C2C, n, n, n, tr,
                           precision="double")
    assert plan._ds
    vals = rng.standard_normal(len(tr)) + 1j * rng.standard_normal(len(tr))
    with pytest.raises(InvalidParameterError, match="f32"):
        plan.apply_pointwise(vals, lambda s: s)
    with pytest.raises(InvalidParameterError, match="f32"):
        plan.iterate_pointwise(vals, lambda s: s, steps=2)


def test_gating(force_ds, monkeypatch):
    rng = np.random.default_rng(5)
    n = 8
    tr = _sparse(n, rng)
    # kill switch
    monkeypatch.setenv("SPFFT_TPU_DEVICE_DOUBLE", "0")
    plan = make_local_plan(TransformType.C2C, n, n, n, tr,
                           precision="double")
    assert not plan._ds


def test_r2c_full_half_spectrum(force_ds):
    """R2C on-device double: full half-spectrum set vs an f64 field
    oracle, both directions."""
    rng = np.random.default_rng(8)
    n = 10
    field = rng.standard_normal((n, n, n))
    freq = np.fft.fftn(field)
    tr = np.asarray([(x, y, z) for x in range(n // 2 + 1)
                     for y in range(n) for z in range(n)], np.int64)
    vals = freq[tr[:, 2], tr[:, 1], tr[:, 0]]
    plan = make_local_plan(TransformType.R2C, n, n, n, tr,
                           precision="double")
    assert plan._ds
    space = plan.backward(vals)
    assert space.dtype == np.float64 and space.shape == (n, n, n)
    rel = (np.linalg.norm(space - field * field.size)
           / np.linalg.norm(field * field.size))
    assert rel < 1e-13, rel
    out = plan.forward(space, Scaling.FULL)
    gv = out[:, 0] + 1j * out[:, 1]
    # self-conjugate bins round-trip to Re(v) (docs/precision.md) — the
    # oracle set is hermitian-consistent, so exact recovery holds
    rel = np.linalg.norm(gv - vals) / np.linalg.norm(vals)
    assert rel < 1e-13, rel


def test_r2c_zero_stick_completion(force_ds):
    """R2C DS with only the non-negative-z half of the (0,0) stick
    supplied: the completion must reconstruct the mirrored half (the
    reference StickSymmetry semantics)."""
    rng = np.random.default_rng(9)
    n = 8
    field = rng.standard_normal((n, n, n))
    freq = np.fft.fftn(field)
    tr = []
    for x in range(n // 2 + 1):
        for y in range(n):
            for z in range(n):
                if x == 0 and y == 0 and z > n // 2:
                    continue  # drop the mirrored half of the (0,0) stick
                tr.append((x, y, z))
    tr = np.asarray(tr, np.int64)
    vals = freq[tr[:, 2], tr[:, 1], tr[:, 0]]
    plan = make_local_plan(TransformType.R2C, n, n, n, tr,
                           precision="double")
    assert plan._ds and plan.index_plan.zero_stick_id is not None
    space = plan.backward(vals)
    rel = (np.linalg.norm(space - field * field.size)
           / np.linalg.norm(field * field.size))
    assert rel < 1e-13, rel


def test_precision_model_covers_ds():
    # the device-double envelope sits between single and CPU f64 and
    # accepts the 1e-10 class the verdict asked for
    assert predicted_rel_error("double", 256, device_double=True) < 1e-10
    assert predicted_rel_error("double", 256, device_double=True) \
        > predicted_rel_error("double", 256)
    assert predicted_rel_error("double", 256, device_double=True) \
        < predicted_rel_error("single", 256)


def test_ds_disables_pair_io(force_ds, monkeypatch):
    """The double-single (N, 4) host-f64 boundary replaces the planar
    pair layout — pair_values_io must report False however large the
    plan (review r5)."""
    import spfft_tpu.plan as plan_mod
    monkeypatch.setattr(plan_mod, "PAIR_IO_THRESHOLD", 1)
    rng = np.random.default_rng(6)
    n = 8
    tr = _sparse(n, rng)
    plan = make_local_plan(TransformType.C2C, n, n, n, tr,
                           precision="double")
    assert plan._ds and not plan.pair_values_io
    vals = rng.standard_normal(len(tr)) + 1j * rng.standard_normal(len(tr))
    out = plan.forward(plan.backward(vals), Scaling.FULL)
    assert out.shape == (len(tr), 2) and out.dtype == np.float64


def test_dist_comm1_delegate_keeps_contract(force_ds):
    """The distributed comm-size-1 local delegate must NOT engage the
    on-device double mode: the distributed API promises sharded device
    arrays and pointwise fns (review r5)."""
    from spfft_tpu.parallel import make_distributed_plan, make_mesh
    rng = np.random.default_rng(7)
    n = 8
    tr = _sparse(n, rng)
    plan = make_distributed_plan(TransformType.C2C, n, n, n, [tr], [n],
                                 mesh=make_mesh(1), precision="double")
    if plan._local1 is not None:
        assert not plan._local1._ds
    vals = [rng.standard_normal(len(tr))
            + 1j * rng.standard_normal(len(tr))]
    out = plan.apply_pointwise(vals, lambda s: s)  # fn must still work
    assert out is not None


def test_ds_dynamic_range(force_ds):
    """Adversarial 1e±6 value magnitudes (the reference-contract
    adversarial case, docs/precision.md): the PER-ROW slice ladders
    must keep relative l2 inside the 2e-11 contract envelope even when
    spectra concentrate (the global-anchor design measured 2.5e-8 on
    exactly this failure shape)."""
    rng = np.random.default_rng(10)
    n = 12
    tr = _sparse(n, rng)
    mags = 10.0 ** rng.uniform(-6, 6, len(tr))
    vals = mags * np.exp(2j * np.pi * rng.uniform(size=len(tr)))
    plan = make_local_plan(TransformType.C2C, n, n, n, tr,
                           precision="double")
    assert plan._ds
    space = plan.backward(vals)
    got = space[..., 0] + 1j * space[..., 1]
    cube = np.zeros((n, n, n), np.complex128)
    cube[tr[:, 2], tr[:, 1], tr[:, 0]] = vals
    oracle = np.fft.ifftn(cube) * cube.size
    rel = np.linalg.norm(got - oracle) / np.linalg.norm(oracle)
    assert rel < 2e-11, rel
    out = plan.forward(space, Scaling.FULL)
    gv = out[:, 0] + 1j * out[:, 1]
    rel = np.linalg.norm(gv - vals) / np.linalg.norm(vals)
    assert rel < 2e-11, rel

"""Timing subsystem tests (reference: src/timing/rt_graph.{hpp,cpp} and the
HOST_TIMING macro gating, src/timing/timing.hpp:44-62)."""

import json
import threading
import time

import numpy as np

from spfft_tpu import TransformType, make_local_plan, timing


def test_scope_tree_and_stats():
    t = timing.Timer()
    for _ in range(3):
        with t.scoped("outer"):
            with t.scoped("inner"):
                time.sleep(0.001)
    res = t.process()
    rows = res._rows()
    labels = [(r["label"], r["depth"], r["count"]) for r in rows]
    assert ("outer", 0, 3) in labels
    assert ("inner", 1, 3) in labels
    inner = next(r for r in rows if r["label"] == "inner")
    assert inner["min"] >= 0.001
    assert inner["median"] <= inner["max"]
    # json export parses and mirrors the tree
    data = json.loads(res.json())
    assert data["timings"][0]["label"] == "outer"
    assert data["timings"][0]["sub"][0]["label"] == "inner"


def test_disabled_by_default_and_gated():
    timing.GlobalTimer.reset()
    plan = make_local_plan(TransformType.C2C, 4, 4, 4,
                           np.array([[0, 0, 0]]), precision="double")
    plan.backward(np.ones(1, np.complex128))
    assert not timing.GlobalTimer.process()._rows()  # off by default

    timing.enable()
    try:
        plan.backward(np.ones(1, np.complex128))
        plan.forward(plan.backward(np.ones(1, np.complex128)))
        rows = timing.GlobalTimer.process()._rows()
        labels = {r["label"]: r["count"] for r in rows}
        assert labels["backward"] == 2
        assert labels["forward"] == 1
    finally:
        timing.disable()
        timing.GlobalTimer.reset()


def test_print_does_not_crash(capsys):
    t = timing.Timer()
    with t.scoped("a"):
        pass
    t.process().print()
    out = capsys.readouterr().out
    assert "a" in out and "count" in out


def test_scoped_stack_is_thread_local():
    """Concurrency regression (obs round): nested scopes entered from
    many threads concurrently must keep their OWN call paths — with the
    old shared scope stack, interleaved enter/exit corrupted the tree
    (inner scopes landed under other threads' nodes, counts drifted,
    pops unbalanced the stack). The thread-local stack keeps the
    structure exact: one outer -> inner chain, with every sample
    accounted for."""
    t = timing.Timer()
    N, ITERS = 8, 40
    barrier = threading.Barrier(N)
    errors = []

    def worker():
        try:
            barrier.wait()
            for _ in range(ITERS):
                with t.scoped("outer"):
                    with t.scoped("inner"):
                        pass
        except Exception as exc:  # pragma: no cover - the regression
            errors.append(exc)

    threads = [threading.Thread(target=worker) for _ in range(N)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert not errors
    rows = t.process()._rows()
    shape = {(r["label"], r["depth"]): r["count"] for r in rows}
    assert shape == {("outer", 0): N * ITERS, ("inner", 1): N * ITERS}


def test_record_and_scoped_interleave_across_threads():
    """Timer.record (dispatcher threads) and scoped (caller threads)
    running concurrently: every sample lands, the tree stays sane."""
    t = timing.Timer()
    ITERS = 200

    def recorder():
        for _ in range(ITERS):
            t.record("serve.request", 0.001)

    def scoper():
        for _ in range(ITERS):
            with t.scoped("backward"):
                pass

    threads = [threading.Thread(target=recorder),
               threading.Thread(target=scoper)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    rows = {r["label"]: r["count"] for r in t.process()._rows()}
    assert rows == {"serve.request": ITERS, "backward": ITERS}


def test_multi_transform_batch_timing():
    """Batched execution records one batch scope, not per-transform scopes
    (per-transform blocking would serialise the batch)."""
    from spfft_tpu import (Grid, ProcessingUnit, multi_transform_backward)
    grid = Grid(4, 4, 4, 16, precision="double")
    t = grid.create_transform(ProcessingUnit.HOST, TransformType.C2C,
                              4, 4, 4, indices=np.array([[0, 0, 0]]))
    ts = [t.clone() for _ in range(3)]
    timing.GlobalTimer.reset()
    timing.enable()
    try:
        multi_transform_backward(ts, [np.ones(1, np.complex128)] * 3)
        rows = timing.GlobalTimer.process()._rows()
        labels = {r["label"]: r["count"] for r in rows}
        assert labels == {"multi_backward": 1}
    finally:
        timing.disable()
        timing.GlobalTimer.reset()

"""Fused Pallas DFT stage kernels — interpret-mode correctness on CPU.

The kernels only RUN on TPU (ops.dft.pdft_last_opt and friends gate on
the backend); interpret mode executes the same kernel program with
plain JAX ops, so these tests pin the tiling/transpose/index logic —
odd plane counts, non-tile-aligned row counts, rectangular matrices —
against the XLA stage forms. Device-level equivalence (real Mosaic
codegen, HIGHEST-precision dots) is tests_tpu/test_tpu_ci.py::
test_fused_stage_matches_xla. Mirrors the reference's transpose-layer
unit tests (reference: tests/mpi_tests/test_transpose.cpp:122-183) one
level down, at the kernel boundary.
"""

import numpy as np
import pytest
import jax.numpy as jnp

from spfft_tpu.ops import dft
from spfft_tpu.ops import dft_kernel as dk

RTOL = 2e-6


def _rand(shape, seed):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.standard_normal(shape), jnp.float32)


def _close(a, b):
    a = np.asarray(a, np.float64)
    b = np.asarray(b, np.float64)
    assert np.linalg.norm(a - b) <= RTOL * max(np.linalg.norm(b), 1e-30)


@pytest.mark.parametrize("m,n", [(96, 16), (130, 13), (1, 12)])
def test_stage_kernel_matches_xla_form(m, n):
    xr, xi = _rand((m, n), 1), _rand((m, n), 2)
    mats = dft.c2c_mats(n, dft.BACKWARD)
    want = dft.pdft_last(xr, xi, mats)
    got = dk.pdft_last(xr, xi, mats, interpret=True)
    _close(got[0], want[0])
    _close(got[1], want[1])


def test_stage_kernel_rectangular_mats():
    # sub-rows selection: input length 5 != output length 12
    n, rows = 12, (0, 2, 3, 7, 11)
    xr, xi = _rand((33, 5), 3), _rand((33, 5), 4)
    mats = dft.sub_rows_mats(n, dft.BACKWARD, rows)
    want = dft.pdft_last(xr, xi, mats)
    got = dk.pdft_last(xr, xi, mats, interpret=True)
    _close(got[0], want[0])
    _close(got[1], want[1])


@pytest.mark.parametrize("p,a,b", [(5, 12, 16), (1, 7, 9), (8, 16, 16)])
def test_pdft2_matches_three_pass(p, a, b):
    xr, xi = _rand((p, a, b), 5), _rand((p, a, b), 6)
    m1 = dft.c2c_mats(b, dft.BACKWARD)
    m2 = dft.c2c_mats(a, dft.FORWARD)
    wr, wi = dft.pdft_last(xr, xi, m1)
    wr, wi = jnp.swapaxes(wr, -1, -2), jnp.swapaxes(wi, -1, -2)
    want = dft.pdft_last(wr, wi, m2)
    got = dk.pdft2(xr, xi, m1, m2, interpret=True)
    _close(got[0], want[0])
    _close(got[1], want[1])


def test_prdft2_matches_three_pass():
    p, a, b = 5, 10, 12
    x = _rand((p, a, b), 7)
    m1 = dft.r2c_mats(b)
    m2 = dft.c2c_mats(a, dft.FORWARD)
    wr, wi = dft.prdft_last(x, m1)
    wr, wi = jnp.swapaxes(wr, -1, -2), jnp.swapaxes(wi, -1, -2)
    want = dft.pdft_last(wr, wi, m2)
    got = dk.prdft2(x, m1, m2, interpret=True)
    _close(got[0], want[0])
    _close(got[1], want[1])


def test_pdft2_cr_matches_three_pass():
    p, a, b = 3, 12, 14
    xf = a // 2 + 1
    xr, xi = _rand((p, xf, b), 8), _rand((p, xf, b), 9)
    m1 = dft.c2c_mats(b, dft.BACKWARD)
    m2 = dft.c2r_mats(a)
    wr, wi = dft.pdft_last(xr, xi, m1)
    wr, wi = jnp.swapaxes(wr, -1, -2), jnp.swapaxes(wi, -1, -2)
    want = dft.pirdft_last(wr, wi, m2)
    got = dk.pdft2_cr(xr, xi, m1, m2, interpret=True)
    _close(got, want)


def test_dispatchers_fall_back_off_tpu():
    """On the CPU backend the dispatchers must produce the XLA result
    bit-for-bit (no kernel involved)."""
    xr, xi = _rand((4, 6, 8), 10), _rand((4, 6, 8), 11)
    m1 = dft.c2c_mats(8, dft.BACKWARD)
    m2 = dft.c2c_mats(6, dft.BACKWARD)
    wr, wi = dft.pdft_last(xr, xi, m1)
    wr, wi = jnp.swapaxes(wr, -1, -2), jnp.swapaxes(wi, -1, -2)
    want = dft.pdft_last(wr, wi, m2)
    got = dft.pdft2_minor(xr, xi, m1, m2)
    assert np.array_equal(np.asarray(got[0]), np.asarray(want[0]))
    assert np.array_equal(np.asarray(got[1]), np.asarray(want[1]))


def test_two_stage_mats_take_xla_form():
    """TwoStageMats (axes > MATMUL_DFT_MAX) must route through the XLA
    Cooley-Tukey path, not the kernel, regardless of backend."""
    n = 768
    mats = dft.c2c_mats(n, dft.BACKWARD)
    assert isinstance(mats, dft.TwoStageMats)
    xr, xi = _rand((3, n), 12), _rand((3, n), 13)
    want = dft.pdft_last(xr, xi, mats)
    got = dft.pdft_last_opt(xr, xi, mats)
    assert np.array_equal(np.asarray(got[0]), np.asarray(want[0]))


def test_vmem_gate():
    assert dk.fits2("cc", 256, 256, 256, 256)
    assert not dk.fits2("cc", 512, 512, 512, 512)
    assert dk.plane_tp(256, 256, 256, 256, 2, 2,
                       6 * 256 * 256) in (1, 2, 4)


def test_pdft2_swapped_matches_three_pass():
    p, a, b = 5, 12, 16
    xr, xi = _rand((p, a, b), 20), _rand((p, a, b), 21)
    m1 = dft.c2c_mats(b, dft.BACKWARD)
    m2 = dft.c2c_mats(a, dft.BACKWARD)
    wr, wi = dft.pdft_last(xr, xi, m1)
    wr, wi = jnp.swapaxes(wr, -1, -2), jnp.swapaxes(wi, -1, -2)
    wr, wi = dft.pdft_last(wr, wi, m2)
    wr, wi = jnp.swapaxes(wr, -1, -2), jnp.swapaxes(wi, -1, -2)
    got = dk.pdft2_swapped(xr, xi, m1, m2, interpret=True)
    _close(got[0], wr)
    _close(got[1], wi)


def test_cdft2_xy_fallback_off_tpu():
    """On CPU the complex dispatcher must reproduce the two-stage XLA
    form bit-for-bit (it IS that form when the kernel is ineligible)."""
    p, a, b = 4, 10, 12
    rng = np.random.default_rng(22)
    x = jnp.asarray(rng.standard_normal((p, a, b))
                    + 1j * rng.standard_normal((p, a, b)), jnp.complex64)
    m1 = dft.c2c_mats(b, dft.FORWARD)
    m2 = dft.c2c_mats(a, dft.FORWARD)
    want = dft.cdft_last(x, m1)
    want = dft.cdft_last(jnp.swapaxes(want, -1, -2), m2)
    want = jnp.swapaxes(want, -1, -2)
    got = dft.cdft2_xy(x, m1, m2)
    assert np.array_equal(np.asarray(got), np.asarray(want))


# -- round-6 satellite coverage: dynamic caps + VMEM ineligibility ----------

def test_max_dim_tracks_retuned_cap(monkeypatch):
    """dft.MATMUL_DFT_MAX is read per call (module-attribute access),
    so a monkeypatched/retuned cap propagates to kernel eligibility
    instead of staying frozen at import-time (r05 advisor finding)."""
    assert dk.max_dim() == min(dk._EMPIRICAL_MAX, dft.MATMUL_DFT_MAX)
    monkeypatch.setattr(dft, "MATMUL_DFT_MAX", 8)
    assert dk.max_dim() == 8
    mats16 = dft._build_dft_mats(16, -1, 1.0)
    assert not dk.eligible_mats(mats16)  # 16 > the retuned cap
    monkeypatch.setattr(dft, "MATMUL_DFT_MAX", 4096)
    assert dk.max_dim() == dk._EMPIRICAL_MAX


def test_stage_tm_none_when_matrices_overflow_budget(monkeypatch):
    """_stage_tm returns None (not a bogus minimum tile) when even
    tm=128 exceeds the VMEM budget, and fits1 reports ineligible —
    the fits2/plane_tp pattern, preventing a Mosaic compile crash at
    retuned caps (r05 advisor finding)."""
    assert dk._stage_tm(256, 256) is not None
    assert dk.fits1(256, 256)
    assert dk._stage_tm(2048, 2048) is None
    assert not dk.fits1(2048, 2048)
    monkeypatch.setattr(dk, "_VMEM_BUDGET", 1024)
    assert dk._stage_tm(64, 64) is None
    assert not dk.fits1(64, 64)


def test_pdft_last_opt_falls_back_when_unfit(monkeypatch):
    """The dispatcher takes the XLA form (same math) instead of the
    kernel when fits1 says the shape cannot tile."""
    monkeypatch.setattr(dk, "_VMEM_BUDGET", 1024)
    monkeypatch.setenv("SPFFT_TPU_FUSED_STAGE", "1")
    xr, xi = _rand((6, 16), 30), _rand((6, 16), 31)
    mats = dft.c2c_mats(16, dft.FORWARD)
    got = dft.pdft_last_opt(xr, xi, mats)
    want = dft.pdft_last(xr, xi, mats)
    assert np.array_equal(np.asarray(got[0]), np.asarray(want[0]))
    assert np.array_equal(np.asarray(got[1]), np.asarray(want[1]))


def test_dft_mats_byte_lru_bounded():
    """_dft_mats evicts oldest-first past its byte budget (prime
    fallback triples at n > 512 must not pin ~400 MB in long-lived
    servers — r05 advisor finding), keeps hit identity, and supports
    cache_clear (probe scripts rely on it)."""
    lru = dft._ByteLRU(dft._build_dft_mats, max_entries=32,
                       max_bytes=2 * (3 * 64 * 64 * 4))  # two n=64 triples
    a = lru(64, -1, 1.0)
    assert lru(64, -1, 1.0) is a  # hit returns the same object
    lru(64, +1, 1.0)
    assert lru.cache_bytes == 2 * (3 * 64 * 64 * 4)
    lru(64, -1, 0.5)  # third entry: evicts the oldest
    assert lru.cache_bytes == 2 * (3 * 64 * 64 * 4)
    assert lru(64, -1, 1.0) is not a  # rebuilt after eviction
    lru.cache_clear()
    assert lru.cache_bytes == 0


def test_dft_mats_entry_cap_still_applies():
    lru = dft._ByteLRU(dft._build_dft_mats, max_entries=2,
                       max_bytes=1 << 40)
    for scale in (1.0, 0.5, 0.25):
        lru(8, -1, scale)
    assert len(lru._store) == 2

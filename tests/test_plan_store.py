"""Persistent plan-artifact store: round trips, the registry disk
tier, poisoned-artifact robustness, concurrency, manifest prewarm and
the CLI.

The safety contract under test (docs/artifact_cache.md): a warm load
is bit-exact with a cold build and counts ZERO builds; a poisoned
artifact (corrupt bytes, version mismatch, stale index digest,
truncated payload, racing writers) NEVER loads — the typed reason is
counted (``spfft_store_rejects_total{reason}``) and the caller falls
back to a clean rebuild.
"""

import dataclasses
import hashlib
import json
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

from spfft_tpu import obs
from spfft_tpu.errors import PlanArtifactError
from spfft_tpu.plan import TransformPlan, restore_plan
from spfft_tpu.indexing import build_index_plan
from spfft_tpu.serve.registry import PlanRegistry
from spfft_tpu.serve import store as store_mod
from spfft_tpu.serve.store import (MAGIC, PLAN_MANIFEST_ENV,
                                   PlanArtifactStore, load_manifest,
                                   parse_artifact, serialize_artifact,
                                   signature_key)
from spfft_tpu.types import Scaling, TransformType
from spfft_tpu.utils.workloads import (sort_triplets_stick_major,
                                       spherical_cutoff_triplets)

DIM = 20


def _triplets(dim=DIM, r2c=False):
    tr = sort_triplets_stick_major(spherical_cutoff_triplets(dim),
                                   (dim, dim, dim))
    if r2c:
        tr = tr[tr[:, 0] >= 0]
    return tr


def _values(plan, seed=7):
    rng = np.random.default_rng(seed)
    return rng.standard_normal(
        (plan.index_plan.num_values, 2)).astype(np.float32)


def _build_store(tmp_path, dim=DIM, **kwargs):
    store = PlanArtifactStore(str(tmp_path / "store"))
    reg = PlanRegistry(store=store)
    tr = _triplets(dim)
    sig, plan = reg.get_or_build(TransformType.C2C, dim, dim, dim, tr,
                                 **kwargs)
    store.drain()
    return store, reg, tr, sig, plan


def _rewrite(path, header, payload):
    """Re-assemble an artifact file from (possibly tampered) parts,
    keeping the length/checksum fields consistent with ``payload``."""
    header = dict(header)
    header["payload_sha256"] = hashlib.sha256(payload).hexdigest()
    header["payload_len"] = len(payload)
    hbytes = json.dumps(header, sort_keys=True).encode()
    with open(path, "wb") as f:
        f.write(b"".join([MAGIC, b"%016x\n" % len(hbytes), hbytes,
                          payload]))


def _split_artifact(path):
    data = open(path, "rb").read()
    off = len(MAGIC)
    hlen = int(data[off:off + 16], 16)
    off += 17
    return json.loads(data[off:off + hlen]), data[off + hlen:]


# -- round trips -------------------------------------------------------------
def test_artifact_roundtrip_bit_exact(tmp_path):
    store, reg, tr, sig, plan = _build_store(tmp_path)
    vals = _values(plan)
    want_b = np.asarray(plan.backward(vals))
    want_f = np.asarray(plan.forward(want_b, scaling=Scaling.FULL))

    got = PlanArtifactStore(store.root).load_signature(sig)
    assert got is not None
    sig2, plan2 = got
    assert sig2 == sig
    assert plan2._build_thread is None  # no background build ever ran
    assert np.array_equal(np.asarray(plan2.backward(vals)), want_b)
    assert np.array_equal(
        np.asarray(plan2.forward(want_b, scaling=Scaling.FULL)), want_f)


def test_warm_registry_resolves_with_zero_builds(tmp_path):
    store, reg, tr, sig, plan = _build_store(tmp_path)
    assert reg.stats()["builds"] == 1
    assert reg.stats()["store_spills"] == 1
    vals = _values(plan)
    want = np.asarray(plan.backward(vals))

    before = {
        kind: obs.GLOBAL_COUNTERS.get("spfft_compile_events_total",
                                      kind=kind)
        for kind in ("registry_build", "compression_tables")}
    reg2 = PlanRegistry(store=PlanArtifactStore(store.root))
    sig2, plan2 = reg2.get_or_build(TransformType.C2C, DIM, DIM, DIM,
                                    tr)
    stats = reg2.stats()
    assert sig2 == sig
    assert stats["builds"] == 0
    assert stats["store_hits"] == 1
    # no index-table build, no background table-build span
    for kind, was in before.items():
        assert obs.GLOBAL_COUNTERS.get("spfft_compile_events_total",
                                       kind=kind) == was
    assert np.array_equal(np.asarray(plan2.backward(vals)), want)


def test_wrapped_spelling_resolves_via_signature_tier(tmp_path):
    """A request spelled with wrapped (non-negative) indices misses the
    raw-bytes alias but lands on the SAME canonical signature — the
    registry's signature read-through then loads the artifact instead
    of constructing a plan."""
    store, reg, tr, sig, plan = _build_store(tmp_path)
    wrapped = np.where(tr < 0, tr + DIM, tr).astype(tr.dtype)
    assert not np.array_equal(wrapped, tr)
    reg2 = PlanRegistry(store=PlanArtifactStore(store.root))
    sig2, plan2 = reg2.get_or_build(TransformType.C2C, DIM, DIM, DIM,
                                    wrapped)
    assert sig2 == sig
    assert reg2.stats()["builds"] == 0
    assert reg2.stats()["store_hits"] == 1


def test_pallas_tables_roundtrip(tmp_path):
    """use_pallas=True builds the kernel tables on CPU; the artifact
    must carry them and the restored plan must reuse them (and stay
    bit-exact) without any cover build."""
    store, reg, tr, sig, plan = _build_store(tmp_path, use_pallas=True)
    assert plan._pallas_box is not None
    vals = _values(plan)
    want = np.asarray(plan.backward(vals))
    got = PlanArtifactStore(store.root).load_signature(
        sig, plan_kwargs={"use_pallas": True})
    assert got is not None
    _, plan2 = got
    assert plan2._pallas_box is not None
    assert plan2._pallas_box["dec"] is not None
    assert plan2._build_thread is None
    assert np.array_equal(np.asarray(plan2.backward(vals)), want)


def test_use_pallas_demand_without_tables_rebuilds(tmp_path):
    """An artifact spilled without kernel tables cannot honour
    use_pallas=True — the load declines (typed 'incompatible') and the
    registry rebuilds with the tables."""
    store, reg, tr, sig, plan = _build_store(tmp_path,
                                             use_pallas=False)
    reg2 = PlanRegistry(store=PlanArtifactStore(store.root))
    sig2, plan2 = reg2.get_or_build(TransformType.C2C, DIM, DIM, DIM,
                                    tr, use_pallas=True)
    assert reg2.stats()["builds"] == 1
    assert plan2._pallas is not None   # property joins the fresh build
    # both load attempts (raw alias, then signature tier) declined
    assert reg2.store.stats()["rejects"].get("incompatible", 0) >= 1


def test_aot_executables_install_and_disable(tmp_path, monkeypatch):
    store, reg, tr, sig, plan = _build_store(tmp_path)
    got = PlanArtifactStore(store.root).load_signature(sig)
    assert got is not None
    _, plan2 = got
    # this container's jax has jax.export for the CPU platform
    assert plan2._aot is not None
    assert set(plan2._aot) == {"backward", "forward_none",
                               "forward_full", "batched_backward",
                               "batched_forward_none",
                               "batched_forward_full", "pair_none",
                               "pair_full"}
    # disabled: the spilled artifact carries no AOT blobs at all
    monkeypatch.setenv("SPFFT_TPU_PLAN_STORE_AOT", "0")
    store2 = PlanArtifactStore(str(tmp_path / "store2"))
    store2.save_plan(sig, plan, triplets=tr)
    got2 = store2.load_signature(sig)
    assert got2 is not None
    assert got2[1]._aot is None


def test_aot_call_failure_falls_back_to_jit(tmp_path):
    """An AOT executable that disagrees with this process's table
    pytree must never fail a request: the call falls back to the jit
    path permanently (counted, bit-exact)."""
    store, reg, tr, sig, plan = _build_store(tmp_path)
    got = PlanArtifactStore(store.root).load_signature(sig)
    _, plan2 = got

    class Broken:
        def call(self, *a, **k):
            raise RuntimeError("pytree mismatch")

    plan2._aot["backward"] = Broken()
    vals = _values(plan)
    want = np.asarray(plan.backward(vals))
    before = obs.GLOBAL_COUNTERS.get("spfft_store_aot_skipped_total",
                                     reason="call_failed")
    assert np.array_equal(np.asarray(plan2.backward(vals)), want)
    assert "backward" not in plan2._aot   # dropped permanently
    assert obs.GLOBAL_COUNTERS.get("spfft_store_aot_skipped_total",
                                   reason="call_failed") == before + 1
    # later calls go straight through the jit path
    assert np.array_equal(np.asarray(plan2.backward(vals)), want)


def test_aot_batched_and_pair_roundtrip_bit_exact(tmp_path):
    """The batched (symbolic leading batch dim) and identity fused-pair
    executables round-trip through the store and serve requests
    bit-exactly against a fresh-jit plan — at MULTIPLE batch sizes, so
    one exported module demonstrably covers every B."""
    store, reg, tr, sig, plan = _build_store(tmp_path)
    got = PlanArtifactStore(store.root).load_signature(sig)
    assert got is not None
    _, plan2 = got
    for key in ("batched_backward", "batched_forward_none",
                "batched_forward_full", "pair_none", "pair_full"):
        assert key in plan2._aot, key

    rng = np.random.default_rng(11)
    for b in (1, 3):
        vals_b = rng.standard_normal(
            (b, plan.index_plan.num_values, 2)).astype(np.float32)
        want_b = np.asarray(plan.backward_batched(vals_b))
        assert np.array_equal(
            np.asarray(plan2.backward_batched(vals_b)), want_b)
        for scaling in (Scaling.NONE, Scaling.FULL):
            assert np.array_equal(
                np.asarray(plan2.forward_batched(want_b,
                                                 scaling=scaling)),
                np.asarray(plan.forward_batched(want_b,
                                                scaling=scaling)))
    # the AOT entries survived every dispatch (no silent call_failed
    # fallback ate them)
    for key in ("batched_backward", "batched_forward_none",
                "batched_forward_full"):
        assert key in plan2._aot, key

    vals = _values(plan, seed=12)
    for scaling in (Scaling.NONE, Scaling.FULL):
        assert np.array_equal(
            np.asarray(plan2.apply_pointwise(vals, scaling=scaling)),
            np.asarray(plan.apply_pointwise(vals, scaling=scaling)))
    assert "pair_none" in plan2._aot and "pair_full" in plan2._aot


# -- poisoned artifacts ------------------------------------------------------
def _reject_count(store, reason):
    return store.stats()["rejects"].get(reason, 0)


def test_corrupt_artifact_never_loads_and_rebuilds(tmp_path):
    store, reg, tr, sig, plan = _build_store(tmp_path)
    path = store.artifact_path(signature_key(sig))
    data = open(path, "rb").read()
    with open(path, "wb") as f:  # flip bytes inside the payload
        f.write(data[:-64] + b"\x00" * 64)
    reg2 = PlanRegistry(store=PlanArtifactStore(store.root))
    sig2, plan2 = reg2.get_or_build(TransformType.C2C, DIM, DIM, DIM,
                                    tr)
    assert sig2 == sig                      # clean rebuild, same plan
    assert reg2.stats()["builds"] == 1
    assert _reject_count(reg2.store, "corrupt") >= 1
    vals = _values(plan)
    assert np.array_equal(np.asarray(plan2.backward(vals)),
                          np.asarray(plan.backward(vals)))


def test_truncated_artifact_rejected(tmp_path):
    store, reg, tr, sig, plan = _build_store(tmp_path)
    path = store.artifact_path(signature_key(sig))
    data = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(data[: len(data) // 2])
    assert PlanArtifactStore(store.root).load_signature(sig) is None


def test_garbage_file_rejected(tmp_path):
    store, reg, tr, sig, plan = _build_store(tmp_path)
    path = store.artifact_path(signature_key(sig))
    with open(path, "wb") as f:
        f.write(b"not an artifact at all")
    s2 = PlanArtifactStore(store.root)
    assert s2.load_signature(sig) is None
    assert _reject_count(s2, "corrupt") == 1


def test_version_header_mismatch_rejected(tmp_path):
    store, reg, tr, sig, plan = _build_store(tmp_path)
    path = store.artifact_path(signature_key(sig))
    header, payload = _split_artifact(path)
    header["format_version"] = 999
    _rewrite(path, header, payload)
    s2 = PlanArtifactStore(store.root)
    assert s2.load_signature(sig) is None
    assert _reject_count(s2, "version_mismatch") == 1


def test_table_schema_mismatch_rejected(tmp_path):
    store, reg, tr, sig, plan = _build_store(tmp_path)
    path = store.artifact_path(signature_key(sig))
    header, payload = _split_artifact(path)
    header["table_schema"] = 0
    _rewrite(path, header, payload)
    s2 = PlanArtifactStore(store.root)
    assert s2.load_signature(sig) is None
    assert _reject_count(s2, "version_mismatch") == 1


def test_stale_index_digest_rejected(tmp_path):
    """A payload whose checksum is VALID but whose tables no longer
    digest to the signature they claim (the hand-edited/stale-artifact
    case) must reject as digest_mismatch, never load."""
    import io
    store, reg, tr, sig, plan = _build_store(tmp_path)
    path = store.artifact_path(signature_key(sig))
    header, payload = _split_artifact(path)
    with np.load(io.BytesIO(payload)) as z:
        arrays = {k: z[k] for k in z.files}
    arrays["stick_keys"] = arrays["stick_keys"].copy()
    arrays["stick_keys"][0] += 1       # different sparse set
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    _rewrite(path, header, buf.getvalue())   # checksum recomputed OK
    s2 = PlanArtifactStore(store.root)
    assert s2.load_signature(sig) is None
    assert _reject_count(s2, "digest_mismatch") == 1


def test_parse_artifact_reports_typed_reasons():
    from spfft_tpu.serve.store import StoreReject
    with pytest.raises(StoreReject) as exc:
        parse_artifact(b"garbage")
    assert exc.value.reason == "corrupt"


def test_concurrent_writer_race_stays_loadable(tmp_path):
    """Many threads spilling the same artifact concurrently (the
    multi-process analogue runs through the same atomic os.replace):
    whatever interleaving wins, the surviving file parses and loads."""
    store, reg, tr, sig, plan = _build_store(tmp_path)
    errs = []

    def spill():
        try:
            store.save_plan(sig, plan, triplets=tr)
        except Exception as exc:  # pragma: no cover
            errs.append(exc)

    threads = [threading.Thread(target=spill) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    with open(store.artifact_path(signature_key(sig)), "rb") as f:
        parse_artifact(f.read())  # must not raise
    assert PlanArtifactStore(store.root).load_signature(sig) is not None
    leftovers = [n for n in os.listdir(store._dir("artifacts"))
                 if n.startswith(".tmp-")]
    assert not leftovers


# -- registry fuzz with the disk tier ----------------------------------------
def test_registry_fuzz_with_disk_tier(tmp_path):
    """8 threads hammering a store-backed registry across two shapes:
    every result bit-exact vs a serial oracle, one build per shape
    (singleflight holds with the disk tier in the path), and a fresh
    registry over the same store then resolves both with zero builds."""
    store = PlanArtifactStore(str(tmp_path / "store"))
    reg = PlanRegistry(store=store)
    shapes = {16: _triplets(16), 20: _triplets(20)}
    oracles = {}
    for dim, tr in shapes.items():
        ip = build_index_plan(TransformType.C2C, dim, dim, dim, tr)
        p = TransformPlan(ip)
        vals = np.random.default_rng(dim).standard_normal(
            (ip.num_values, 2)).astype(np.float32)
        oracles[dim] = (vals, np.asarray(p.backward(vals)))

    results, errors = [], []

    def worker(tid):
        try:
            for i in range(6):
                dim = 16 if (tid + i) % 2 == 0 else 20
                tr = shapes[dim]
                sig, plan = reg.get_or_build(TransformType.C2C, dim,
                                             dim, dim, tr)
                vals, want = oracles[dim]
                got = np.asarray(plan.backward(vals))
                results.append(np.array_equal(got, want))
        except Exception as exc:  # pragma: no cover
            errors.append(exc)

    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    assert all(results)
    assert reg.stats()["builds"] == 2
    store.drain()

    reg2 = PlanRegistry(store=PlanArtifactStore(store.root))
    for dim, tr in shapes.items():
        sig, plan = reg2.get_or_build(TransformType.C2C, dim, dim, dim,
                                      tr)
        vals, want = oracles[dim]
        assert np.array_equal(np.asarray(plan.backward(vals)), want)
    assert reg2.stats()["builds"] == 0
    assert reg2.stats()["store_hits"] == 2


# -- gc / manifest / prewarm -------------------------------------------------
def test_gc_evicts_oldest_and_sweeps_aliases(tmp_path):
    store = PlanArtifactStore(str(tmp_path / "store"), max_bytes=0)
    reg = PlanRegistry(store=store)
    for dim in (16, 20):
        reg.get_or_build(TransformType.C2C, dim, dim, dim,
                         _triplets(dim))
    store.drain()
    files = store._artifact_files()
    assert len(files) == 2
    os.utime(files[0][0], (1, 1))  # make one clearly oldest
    keep_bytes = os.path.getsize(files[1][0])
    removed = store.gc(max_bytes=keep_bytes)
    assert len(removed) == 1
    assert len(store._artifact_files()) == 1
    # the surviving artifact's alias still resolves; the evicted one's
    # alias was swept
    live = {os.path.basename(p)[:-5] for p, _, _ in
            store._artifact_files()}
    for name in os.listdir(store._dir("requests")):
        with open(os.path.join(store._dir("requests"), name)) as f:
            assert json.load(f)["artifact"] in live


def test_manifest_warmup_and_strict_failure(tmp_path):
    store, reg, tr, sig, plan = _build_store(tmp_path)
    mpath = str(tmp_path / "manifest.json")
    m = store.write_manifest(mpath)
    assert len(m["entries"]) == 1

    reg2 = PlanRegistry(store=PlanArtifactStore(store.root))
    sigs = reg2.warmup_manifest(mpath, compile=True)
    assert sigs == [sig]
    assert reg2.stats()["builds"] == 0
    assert reg2.get(sig) is not None

    # a poisoned artifact fails strict prewarm loudly ...
    path = store.artifact_path(signature_key(sig))
    open(path, "wb").write(b"junk")
    reg3 = PlanRegistry(store=PlanArtifactStore(store.root))
    with pytest.raises(PlanArtifactError):
        reg3.warmup_manifest(mpath)
    # ... and is skipped (reason counted) when strict=False
    reg4 = PlanRegistry(store=PlanArtifactStore(store.root))
    assert reg4.warmup_manifest(mpath, strict=False) == []
    assert _reject_count(reg4.store, "corrupt") == 1


def test_live_manifest_auto_refresh_on_spill(tmp_path, monkeypatch):
    """With ``SPFFT_TPU_PLAN_MANIFEST`` set, every spill merges its
    entry into the live manifest — deduped on the artifact key — and a
    replacement registry prewarms from it with zero builds."""
    mpath = str(tmp_path / "live-manifest.json")
    monkeypatch.setenv(PLAN_MANIFEST_ENV, mpath)
    before = obs.GLOBAL_COUNTERS.get(
        "spfft_store_manifest_refreshes_total")
    store, reg, tr, sig, plan = _build_store(tmp_path)

    m = load_manifest(mpath)
    assert [e["artifact"] for e in m["entries"]] \
        == [signature_key(sig)]
    assert m["entries"][0]["signature"] == dataclasses.asdict(sig)
    assert m["entries"][0]["num_values"] == plan.index_plan.num_values

    # re-spilling the same plan replaces, never duplicates
    store.save_plan(sig, plan, tr)
    assert len(load_manifest(mpath)["entries"]) == 1

    # a second signature appends
    dim2 = 16
    sig2, _ = reg.get_or_build(TransformType.C2C, dim2, dim2, dim2,
                               _triplets(dim2))
    store.drain()
    m = load_manifest(mpath)
    assert {e["artifact"] for e in m["entries"]} \
        == {signature_key(sig), signature_key(sig2)}
    assert obs.GLOBAL_COUNTERS.get(
        "spfft_store_manifest_refreshes_total") >= before + 3

    reg2 = PlanRegistry(store=PlanArtifactStore(store.root))
    assert set(reg2.warmup_manifest(mpath)) == {sig, sig2}
    assert reg2.stats()["builds"] == 0


def test_live_manifest_concurrent_appends_atomic(tmp_path):
    """16 threads hammering ``append_manifest_entry`` (with key
    collisions) leave one valid, complete, duplicate-free manifest and
    no temp droppings — the read/merge/replace cycle is atomic."""
    store = PlanArtifactStore(str(tmp_path / "store"))
    mpath = str(tmp_path / "live-manifest.json")
    keys = [f"art-{i % 12:02d}" for i in range(16)]  # 12 distinct

    def append(key):
        store.append_manifest_entry(mpath, {
            "artifact": key, "signature": {"k": key}, "bytes": 1})

    threads = [threading.Thread(target=append, args=(k,))
               for k in keys]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    m = load_manifest(mpath)
    got = [e["artifact"] for e in m["entries"]]
    assert sorted(got) == sorted(set(keys))  # all present, none twice
    assert not [n for n in os.listdir(str(tmp_path))
                if n.startswith(".tmp-")]


def test_live_manifest_invalid_file_never_clobbered(tmp_path,
                                                    monkeypatch):
    """An existing-but-invalid manifest is an error for the direct
    append, a counted non-fatal reject for the spill path — and its
    bytes survive untouched either way."""
    from spfft_tpu.errors import InvalidParameterError
    mpath = str(tmp_path / "live-manifest.json")
    open(mpath, "w").write("not a manifest")
    monkeypatch.setenv(PLAN_MANIFEST_ENV, mpath)

    store, reg, tr, sig, plan = _build_store(tmp_path)
    with pytest.raises(InvalidParameterError):
        store.append_manifest_entry(mpath, {"artifact": "x"})
    # the spill itself (hook included) already ran and must not have
    # failed: the artifact landed, the manifest stayed as-is
    assert store.load_signature(sig) is not None
    assert open(mpath).read() == "not a manifest"
    assert _reject_count(store, "io") >= 1


def test_executor_boot_prewarm_from_manifest_env(tmp_path, monkeypatch):
    from spfft_tpu.serve import ServeExecutor
    store, reg, tr, sig, plan = _build_store(tmp_path)
    mpath = str(tmp_path / "manifest.json")
    store.write_manifest(mpath)
    monkeypatch.setenv("SPFFT_TPU_PLAN_MANIFEST", mpath)
    reg2 = PlanRegistry(store=PlanArtifactStore(store.root))
    with ServeExecutor(reg2, batching=False) as ex:
        assert reg2.stats()["builds"] == 0
        assert reg2.get(sig) is not None   # warm before traffic
        vals = _values(plan)
        fut = ex.submit(sig, vals)
        got = np.asarray(fut.result(timeout=60))
    assert np.array_equal(got, np.asarray(plan.backward(vals)))


# -- default-store resolution ------------------------------------------------
def test_env_var_attaches_default_store(tmp_path, monkeypatch):
    import spfft_tpu.serve.store as sm
    monkeypatch.setenv("SPFFT_TPU_PLAN_STORE",
                       str(tmp_path / "envstore"))
    monkeypatch.setattr(sm, "_DEFAULT_STORES", {})
    reg = PlanRegistry()
    assert reg.store is not None
    assert reg.store.root == str(tmp_path / "envstore")
    # store=False forces the tier off regardless of the env
    assert PlanRegistry(store=False).store is None


def test_config_path_setting_roundtrip(tmp_path):
    from spfft_tpu.control.config import ServeConfig
    cfg = ServeConfig()
    assert cfg.plan_store_path == ""
    cfg.set_path("plan_store_path", str(tmp_path / "s"))
    art = str(tmp_path / "cfg.json")
    cfg.save(art)
    cfg2 = ServeConfig.load(art)
    assert cfg2.plan_store_path == str(tmp_path / "s")
    assert cfg2.get("plan_store_max_bytes") \
        == ServeConfig.default("plan_store_max_bytes")


# -- CLI ---------------------------------------------------------------------
def test_cli_seed_manifest_prewarm_verify_gc(tmp_path, capsys):
    root = str(tmp_path / "cli")
    assert store_mod.main(["seed", root, "--dim", "16",
                           "--reference", "--json"]) == 0
    seed = json.loads(capsys.readouterr().out)
    assert seed["builds"] == 1 and seed["store"]["spills"] == 1

    assert store_mod.main(["manifest", root]) == 0
    capsys.readouterr()
    assert store_mod.main(["prewarm", root, "--compile",
                           "--check-reference", "--strict",
                           "--json"]) == 0
    warm = json.loads(capsys.readouterr().out)
    assert warm["ok"] and warm["builds"] == 0
    assert warm["reference_bit_exact"] is True
    assert warm["compile_events"]["registry_build"] == 0
    assert warm["compile_events"]["compression_tables"] == 0

    assert store_mod.main(["verify", root, "--json"]) == 0
    rows = json.loads(capsys.readouterr().out)["rows"]
    assert rows and all(r["ok"] for r in rows)

    # poison it: verify and strict prewarm both go red
    store = PlanArtifactStore(root)
    key = rows[0]["key"]
    open(store.artifact_path(key), "wb").write(b"junk")
    assert store_mod.main(["verify", root, "--json"]) == 1
    capsys.readouterr()
    assert store_mod.main(["prewarm", root, "--strict", "--json"]) == 1
    capsys.readouterr()

    assert store_mod.main(["gc", root, "--max-bytes", "1"]) == 0


def test_store_smoke_cross_process(tmp_path):
    """The make store-smoke contract, as a test: process A builds and
    spills, process B (a genuinely fresh interpreter) warm-loads with
    builds==0, no table-build spans, and bit-exact outputs against the
    recorded reference."""
    root = str(tmp_path / "xproc")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    a = subprocess.run(
        [sys.executable, "-m", "spfft_tpu.serve.store", "seed", root,
         "--dim", "16", "--reference", "--json"],
        capture_output=True, text=True, env=env, timeout=300)
    assert a.returncode == 0, a.stderr
    b = subprocess.run(
        [sys.executable, "-m", "spfft_tpu.serve.store", "prewarm",
         root, "--compile", "--check-reference", "--strict", "--json"],
        capture_output=True, text=True, env=env, timeout=300)
    assert b.returncode == 0, b.stderr
    report = json.loads(b.stdout.strip().splitlines()[-1])
    assert report["builds"] == 0
    assert report["reference_bit_exact"] is True
    assert report["compile_events"]["compression_tables"] == 0


# -- degradation ladder: disk faults at the write seams ---------------------

def _no_tmp_files(store):
    leftovers = []
    for dirpath, _, names in os.walk(store.root):
        leftovers += [os.path.join(dirpath, n) for n in names
                      if n.startswith(".tmp-")]
    return leftovers


def test_store_transient_io_retry_succeeds(tmp_path, monkeypatch):
    """A transient OSError (EINTR-shaped) during the atomic write gets
    the bounded retry and the spill SUCCEEDS — no degradation, the
    retry is counted, and the artifact round-trips."""
    import errno

    store, reg, tr, sig, plan = _build_store(tmp_path)
    key = signature_key(sig)
    os.unlink(store.artifact_path(key))

    real_fsync = os.fsync
    fails = {"n": 0}

    def flaky_fsync(fd):
        if fails["n"] == 0:
            fails["n"] += 1
            raise OSError(errno.EINTR, "Interrupted system call")
        return real_fsync(fd)

    monkeypatch.setattr(store_mod.os, "fsync", flaky_fsync)
    assert store.save_plan(sig, plan) == key
    monkeypatch.undo()

    health = store.health()
    assert health["state"] == "ok"
    assert health["io_retries"] >= 1
    assert fails["n"] == 1
    assert os.path.exists(store.artifact_path(key))
    got = store.load_key(key)
    assert got is not None and signature_key(got[0]) == key
    assert not _no_tmp_files(store)


def test_store_enospc_mid_spill_degrades_to_memory_only(tmp_path):
    """An injected ENOSPC at the spill seam flips the store to the
    memory-only tier: the failing save raises typed OSError and leaves
    NO artifact and NO temp file; subsequent saves are skipped and
    counted under rejects{degraded}; a forced re-probe on a healthy
    volume lifts the degradation and spills resume."""
    from spfft_tpu import faults

    store, reg, tr, sig, plan = _build_store(tmp_path)
    key = signature_key(sig)
    os.unlink(store.artifact_path(key))
    try:
        faults.arm(faults.FaultPlan(script="store.spill@1:enospc"))
        with pytest.raises(OSError):
            store.save_plan(sig, plan)
    finally:
        faults.disarm()

    assert store.degraded
    health = store.health()
    assert health["state"] == "degraded" and health["mode"] == "memory-only"
    assert "InjectedDiskFull" in health["reason"]
    assert not os.path.exists(store.artifact_path(key))
    assert not _no_tmp_files(store)

    # degraded: the next save is skipped, typed-counted, still no file
    assert store.save_plan(sig, plan) == key
    assert store.stats()["rejects"].get("degraded", 0) >= 1
    assert not os.path.exists(store.artifact_path(key))

    # volume is actually fine (the fault was injected): a due re-probe
    # lifts the degradation and the same save goes to disk
    store._reprobe_at = 0.0
    assert store.save_plan(sig, plan) == key
    assert not store.degraded
    assert os.path.exists(store.artifact_path(key))
    got = store.load_key(key)
    assert got is not None and signature_key(got[0]) == key


def test_store_torn_write_leaves_no_partial_artifact(tmp_path):
    """A disk-full at the replace seam — after the temp file is fully
    written but before it lands — must never leave either a torn
    artifact or the orphan temp: the cleanup unlinks the temp, the
    store degrades, and verify() stays clean."""
    from spfft_tpu import faults

    store, reg, tr, sig, plan = _build_store(tmp_path)
    key = signature_key(sig)
    os.unlink(store.artifact_path(key))
    try:
        faults.arm(faults.FaultPlan(script="store.replace@1:enospc"))
        with pytest.raises(OSError):
            store.save_plan(sig, plan)
    finally:
        faults.disarm()

    assert store.degraded
    assert not os.path.exists(store.artifact_path(key))
    assert not _no_tmp_files(store)
    assert not [row for row in store.verify() if not row.get("ok")]


def test_store_read_only_directory_degrades_and_serving_continues(
        tmp_path, monkeypatch):
    """EROFS (a genuinely read-only volume, simulated at os.replace
    because tests run as root and chmod is advisory) classifies as a
    PERSISTENT disk fault: the store degrades, and the registry keeps
    building and serving plans from memory with spills skipped."""
    import errno

    store, reg, tr, sig, plan = _build_store(tmp_path)

    def erofs(src, dst):
        raise OSError(errno.EROFS, "Read-only file system")

    monkeypatch.setattr(store_mod.os, "replace", erofs)
    with pytest.raises(OSError):
        store.save_plan(sig, plan)
    monkeypatch.undo()

    assert store.degraded
    assert "Read-only" in store.health()["reason"]

    # serving continues: a fresh build succeeds, its spill is skipped
    sig2, plan2 = reg.get_or_build(TransformType.C2C, 16, 16, 16,
                                   _triplets(16))
    store.drain()
    assert reg.get(sig2) is plan2
    assert plan2.index_plan.num_values > 0
    assert store.stats()["rejects"].get("degraded", 0) >= 1
    assert not os.path.exists(store.artifact_path(signature_key(sig2)))


def test_gc_during_load_is_typed_and_rebuilds_clean(tmp_path):
    """Concurrent GC racing readers: every load_key result is either a
    full (signature, plan) or a clean miss (None) — never an exception,
    never a torn read — and after GC empties the store the registry
    rebuilds from scratch to the same signature."""
    store, reg, tr, sig, plan = _build_store(tmp_path)
    key = signature_key(sig)
    results, errors = [], []
    stop = threading.Event()

    def loader():
        for _ in range(500):
            if stop.is_set():
                break
            try:
                results.append(store.load_key(key))
            except BaseException as exc:  # noqa: BLE001 - the assertion
                errors.append(exc)
                break

    threads = [threading.Thread(target=loader) for _ in range(4)]
    for t in threads:
        t.start()
    try:
        for _ in range(10):
            store.gc(max_bytes=1)
            store.save_plan(sig, plan)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=60)
    assert not errors, errors
    assert results
    for got in results:
        assert got is None or signature_key(got[0]) == key

    # empty the store for real: a miss, then a bit-exact clean rebuild
    store.gc(max_bytes=1)
    assert store.load_key(key) is None
    reg2 = PlanRegistry(store=store)
    sig3, plan3 = reg2.get_or_build(TransformType.C2C, DIM, DIM, DIM, tr)
    assert signature_key(sig3) == key
    np.testing.assert_array_equal(
        plan.index_plan.slot_src, plan3.index_plan.slot_src)

"""Re-run the whole local-transform oracle matrix through the forced
matmul-DFT path (ops/dft.py + the plan's T-layout pipeline).

The suite runs on CPU, where the backend gate would route every FFT to
jnp.fft; this module forces the matmul path for all tests it re-imports
so CI exercises the TPU pipeline structure without a TPU. Double-
precision cases inside still fall back (the gate respects dtype), which
is itself the behavior under test.
"""

import pytest


@pytest.fixture(autouse=True)
def _force_matmul_dft(monkeypatch):
    monkeypatch.setenv("SPFFT_TPU_FORCE_MATMUL_DFT", "1")


from tests.test_local_transform import *  # noqa: F401,F403,E402


# ---------------------------------------------------------------------------
# Round-5: unfactorable axes above MATMUL_DFT_MAX run the DIRECT matmul
# form up to MATMUL_DFT_DIRECT_FALLBACK_MAX (primes have no two-stage
# split and the jnp.fft fallback is the conv-lowered O(N^2) TPU path;
# reference covers any N via FFTW, fftw_plan_1d.hpp:74-94)
# ---------------------------------------------------------------------------

import numpy as np  # noqa: E402

from spfft_tpu import Scaling, TransformType, make_local_plan  # noqa: E402
from spfft_tpu.ops import dft as _dft  # noqa: E402


def test_prime_axis_direct_fallback_c2c():
    assert _dft.use_matmul_dft(521, np.complex64)
    mats = _dft.c2c_mats(521, _dft.BACKWARD)
    assert not isinstance(mats, _dft.TwoStageMats)
    nx, ny, nz = 6, 5, 521
    rng = np.random.default_rng(3)
    tr = np.unique(np.stack([rng.integers(0, nx, 900),
                             rng.integers(0, ny, 900),
                             rng.integers(0, nz, 900)], -1), axis=0)
    plan = make_local_plan(TransformType.C2C, nx, ny, nz, tr,
                           precision="single")
    assert plan._use_mdft
    vals = (rng.standard_normal(len(tr))
            + 1j * rng.standard_normal(len(tr))).astype(np.complex64)
    space = np.asarray(plan.backward(vals))
    cube = np.zeros((nz, ny, nx), np.complex64)
    cube[tr[:, 2], tr[:, 1], tr[:, 0]] = vals
    want = np.fft.ifftn(cube) * cube.size
    got = space[..., 0] + 1j * space[..., 1]
    rel = np.linalg.norm(got - want) / np.linalg.norm(want)
    assert rel < 1e-6, rel
    out = np.asarray(plan.forward(space, Scaling.FULL))
    rt = np.linalg.norm(out[:, 0] + 1j * out[:, 1] - vals) \
        / np.linalg.norm(vals)
    assert rt < 1e-6, rt


def test_r2c_prime_x_direct_fallback():
    """Hermitian x-axis above the cap (613 prime): the half-spectrum
    matrices are direct at any length, so the plan is mdft-covered."""
    nx, ny, nz = 613, 4, 4
    rng = np.random.default_rng(5)
    field = rng.standard_normal((nz, ny, nx)).astype(np.float32)
    freq = np.fft.fftn(field)
    tr = np.asarray([(x, y, z) for x in range(nx // 2 + 1)
                     for y in range(ny) for z in range(nz)], np.int64)
    vals = freq[tr[:, 2], tr[:, 1], tr[:, 0]].astype(np.complex64)
    plan = make_local_plan(TransformType.R2C, nx, ny, nz, tr,
                           precision="single")
    assert plan._use_mdft
    space = np.asarray(plan.backward(vals))
    rel = np.linalg.norm(space - field * field.size) \
        / np.linalg.norm(field * field.size)
    assert rel < 1e-6, rel


def test_split_x_with_prime_fallback_axis():
    """Prime x-axis above the cap (521) with a narrow occupied window:
    the split-x optimization stays ENABLED (direct row/column-selected
    matrices exist for prime-fallback lengths; only two-stage composite
    axes run dense)."""
    nx, ny, nz = 521, 6, 6
    rng = np.random.default_rng(7)
    xs = [0, 1, 2, 520]  # wrapped narrow window
    tr = np.asarray([(x, y, z) for x in xs for y in range(ny)
                     for z in range(nz) if rng.random() < 0.8], np.int64)
    plan = make_local_plan(TransformType.C2C, nx, ny, nz, tr,
                           precision="single")
    assert plan._use_mdft
    assert plan._split_x is not None
    vals = (rng.standard_normal(len(tr))
            + 1j * rng.standard_normal(len(tr))).astype(np.complex64)
    space = np.asarray(plan.backward(vals))
    cube = np.zeros((nz, ny, nx), np.complex64)
    cube[tr[:, 2], tr[:, 1], tr[:, 0]] = vals
    want = np.fft.ifftn(cube) * cube.size
    got = space[..., 0] + 1j * space[..., 1]
    rel = np.linalg.norm(got - want) / np.linalg.norm(want)
    assert rel < 1e-6, rel
    out = np.asarray(plan.forward(space, Scaling.FULL))
    rt = np.linalg.norm(out[:, 0] + 1j * out[:, 1] - vals) \
        / np.linalg.norm(vals)
    assert rt < 1e-6, rt


def test_r2c_composite_x_above_cap_direct_any():
    """Composite R2C x-axis above the cap (768 = 2^8*3): the
    half-spectrum builders are plain direct matrices at any length, so
    the plan is mdft-covered even though c2c_mats(768) would be
    TwoStageMats (round-5 review follow-up)."""
    nx, ny, nz = 768, 4, 4
    rng = np.random.default_rng(8)
    field = rng.standard_normal((nz, ny, nx)).astype(np.float32)
    freq = np.fft.fftn(field)
    tr = np.asarray([(x, y, z) for x in range(nx // 2 + 1)
                     for y in range(ny) for z in range(nz)], np.int64)
    vals = freq[tr[:, 2], tr[:, 1], tr[:, 0]].astype(np.complex64)
    plan = make_local_plan(TransformType.R2C, nx, ny, nz, tr,
                           precision="single")
    assert plan._use_mdft
    space = np.asarray(plan.backward(vals))
    rel = np.linalg.norm(space - field * field.size) \
        / np.linalg.norm(field * field.size)
    assert rel < 1e-6, rel
    out = np.asarray(plan.forward(space, Scaling.FULL))
    rt = np.linalg.norm(out[:, 0] + 1j * out[:, 1] - vals) \
        / np.linalg.norm(vals)
    assert rt < 1e-6, rt

"""Re-run the whole local-transform oracle matrix through the forced
matmul-DFT path (ops/dft.py + the plan's T-layout pipeline).

The suite runs on CPU, where the backend gate would route every FFT to
jnp.fft; this module forces the matmul path for all tests it re-imports
so CI exercises the TPU pipeline structure without a TPU. Double-
precision cases inside still fall back (the gate respects dtype), which
is itself the behavior under test.
"""

import pytest


@pytest.fixture(autouse=True)
def _force_matmul_dft(monkeypatch):
    monkeypatch.setenv("SPFFT_TPU_FORCE_MATMUL_DFT", "1")


from tests.test_local_transform import *  # noqa: F401,F403,E402

"""Unit tests for the Pallas windowed-gather kernel (interpret mode on CPU)
and its plan-time chunked table builder, including non-monotone index
orders (the generalized decomposition) and the disorder fallback."""

import numpy as np
import pytest

import jax.numpy as jnp

from spfft_tpu.ops import gather_kernel as gk


def run_gather(src: np.ndarray, idx: np.ndarray, valid: np.ndarray,
               k_rows: int = 0):
    t = gk.build_monotone_gather_tables(idx, valid, len(src), k_rows=k_rows)
    assert t is not None
    out = gk.run_monotone_gather(jnp.asarray(src, jnp.float32), t,
                                 interpret=True)
    return np.asarray(out), t


def test_expansion_pattern():
    """Decompress-style: masked slots, increments <= 1."""
    rng = np.random.default_rng(0)
    L = 3000
    mask = rng.random(L) < 0.6
    n_src = int(mask.sum())
    src = rng.random((n_src, 2)).astype(np.float32)
    idx = np.maximum(np.cumsum(mask) - 1, 0)
    out, _ = run_gather(src, idx, mask)
    ref = np.zeros((L, 2), np.float32)
    ref[mask] = src
    np.testing.assert_array_equal(out, ref)


def test_compaction_pattern():
    """Compress-style: strictly increasing indices with gaps."""
    rng = np.random.default_rng(1)
    M = 5000
    idx = np.sort(rng.choice(M, 2500, replace=False)).astype(np.int64)
    src = rng.random((M, 2)).astype(np.float32)
    out, _ = run_gather(src, idx, np.ones(len(idx), bool))
    np.testing.assert_array_equal(out, src[idx])


def test_single_tile_and_exact_tile():
    rng = np.random.default_rng(2)
    for L in (100, gk.TILE):
        idx = np.arange(L)
        src = rng.random((L, 2)).astype(np.float32)
        out, _ = run_gather(src, idx, np.ones(L, bool))
        np.testing.assert_array_equal(out, src)


def test_large_span_chunks():
    """A tile whose source span exceeds one K-row window splits into several
    accumulation chunks instead of falling back (the spherical-cutoff edge
    case: sparsely-filled sticks with regular gaps)."""
    rng = np.random.default_rng(3)
    idx = np.arange(gk.TILE) * 16  # gaps of 16 elements: 128-row span
    n_src = int(idx[-1]) + 1
    src = rng.random((n_src, 2)).astype(np.float32)
    out, t = run_gather(src, idx, np.ones(len(idx), bool), k_rows=8)
    assert len(t.row0) > t.num_tiles  # really multi-chunk
    np.testing.assert_array_equal(out, src[idx])


def test_extreme_gaps_fall_back():
    """~0.4% DMA efficiency (one useful value per two 128-lane rows) is past
    the chunk ceiling: the builder declines and the XLA gather runs."""
    idx = np.arange(gk.TILE) * 2 * gk.TILE_LANE
    n_src = int(idx[-1]) + 1
    assert gk.build_monotone_gather_tables(
        idx, np.ones(len(idx), bool), n_src, k_rows=8) is None


def test_chunking_across_k_choices():
    """The result is invariant to the chosen window height."""
    rng = np.random.default_rng(4)
    M = 40000
    idx = np.sort(rng.choice(M, 3000, replace=False)).astype(np.int64)
    src = rng.random((M, 2)).astype(np.float32)
    ref = src[idx]
    for k in (8, 32, 128):
        out, _ = run_gather(src, idx, np.ones(len(idx), bool), k_rows=k)
        np.testing.assert_array_equal(out, ref)


def test_non_monotone_small_supported():
    """Non-monotone indices within one window are handled directly."""
    idx = np.array([5, 3, 7])
    src = np.arange(20, dtype=np.float32).reshape(10, 2)
    out, _ = run_gather(src, idx, np.ones(3, bool))
    np.testing.assert_array_equal(out, src[idx])


def test_block_shuffled_order_supported():
    """Sticks visited in shuffled order (z-sorted within each) — the
    realistic unsorted layout: per-tile windows stay bounded, the kernel
    path stays active, results match."""
    rng = np.random.default_rng(11)
    n_sticks, dim_z = 80, 64
    order = rng.permutation(n_sticks)
    idx = (order[:, None] * dim_z + np.arange(dim_z)[None, :]).reshape(-1)
    src = rng.random((n_sticks * dim_z, 2)).astype(np.float32)
    out, t = run_gather(src, idx, np.ones(len(idx), bool))
    np.testing.assert_array_equal(out, src[idx])


def test_fully_random_large_order_falls_back():
    """A big fully-shuffled index set exceeds the chunk ceiling and the
    builder declines (the XLA gather is the better program there)."""
    rng = np.random.default_rng(12)
    L = 1 << 17
    idx = rng.permutation(L).astype(np.int64)
    assert gk.build_monotone_gather_tables(idx, np.ones(L, bool), L) is None


def test_gather_inputs_unsorted_values():
    """compression_gather_inputs for an unsorted value order: decompress
    indices point at each slot's position in the USER order; round-trip
    through both directions reproduces the values."""
    rng = np.random.default_rng(13)
    num_slots = 400
    vi = rng.choice(num_slots, 120, replace=False)  # unsorted, unique
    (dec_idx, occ), (cmp_idx, cmp_valid) = \
        gk.compression_gather_inputs(vi, num_slots)
    vals = rng.random(120)
    slots = np.where(occ, vals[dec_idx], 0.0)
    expect = np.zeros(num_slots)
    expect[vi] = vals
    np.testing.assert_array_equal(slots, expect)
    np.testing.assert_array_equal(slots[cmp_idx][cmp_valid], vals)


def test_plan_pallas_path_interpret():
    """The plan's Pallas decompress tables reproduce the XLA scatter result
    when run through the kernel in interpret mode."""
    from spfft_tpu import TransformType, make_local_plan
    rng = np.random.default_rng(3)
    n = 16
    triplets = []
    for x in range(n):
        for y in range(n):
            if (x * n + y) % 3 == 0:
                for z in range(n):
                    triplets.append((x, y, z))
    triplets = np.asarray(triplets, np.int32)
    vals = (rng.uniform(-1, 1, len(triplets))
            + 1j * rng.uniform(-1, 1, len(triplets))).astype(np.complex64)
    pl_plan = make_local_plan(TransformType.C2C, n, n, n, triplets,
                              precision="single", use_pallas=True)
    if pl_plan._pallas is None:
        pytest.skip("pallas tables unavailable for this index set")
    t = pl_plan._pallas["dec"]
    src_il = np.stack([vals.real, vals.imag], axis=-1).astype(np.float32)
    sticks = np.asarray(gk.run_monotone_gather(jnp.asarray(src_il), t,
                                               interpret=True))
    ip = pl_plan.index_plan
    # tables cover the padded stick rows (plan._s_pad); pad slots are zero
    expect = np.zeros((pl_plan._s_pad * n, 2), np.float32)
    expect[ip.value_indices] = src_il
    np.testing.assert_array_equal(sticks, expect)


def test_plan_compress_tables_interpret():
    """The compress-direction tables invert decompress: gathering occupied
    slots returns the original values."""
    from spfft_tpu import TransformType, make_local_plan
    rng = np.random.default_rng(7)
    n = 16
    # gappy sticks: only a couple of z values per stick — the edge-stick
    # pattern that used to overflow the fixed span bound
    triplets = []
    for x in range(n):
        for y in range(0, n, 2):
            for z in (0, 1, n - 1):
                triplets.append((x, y, z))
    triplets = np.asarray(triplets, np.int32)
    pl_plan = make_local_plan(TransformType.C2C, n, n, n, triplets,
                              precision="single", use_pallas=True)
    assert pl_plan._pallas is not None and pl_plan._pallas["cmp"] is not None
    ip = pl_plan.index_plan
    vals_il = rng.random((ip.num_values, 2)).astype(np.float32)
    slots = np.zeros((ip.num_sticks * n, 2), np.float32)
    slots[ip.value_indices] = vals_il
    out = np.asarray(gk.run_monotone_gather(
        jnp.asarray(slots), pl_plan._pallas["cmp"], interpret=True))
    np.testing.assert_array_equal(out, vals_il)


def test_plan_shuffled_triplets_kernel_path():
    """Shuffled triplet order (not stick-major) still builds Pallas tables
    via the generalized windowed decomposition; both direction kernels
    reproduce the XLA scatter/gather semantics for the USER's order."""
    from spfft_tpu import TransformType, make_local_plan
    rng = np.random.default_rng(21)
    n = 12
    triplets = [(x, y, z) for x in range(n) for y in range(n)
                if (x + y) % 2 == 0 for z in range(n)]
    triplets = np.asarray(triplets, np.int32)
    triplets = triplets[rng.permutation(len(triplets))]
    plan = make_local_plan(TransformType.C2C, n, n, n, triplets,
                           precision="single", use_pallas=True)
    assert plan._pallas is not None
    assert plan._pallas["dec"] is not None
    assert plan._pallas["cmp"] is not None
    ip = plan.index_plan
    vals_il = rng.random((ip.num_values, 2)).astype(np.float32)
    # decompress: slots in plan storage order from user-order values
    sticks = np.asarray(gk.run_monotone_gather(
        jnp.asarray(vals_il), plan._pallas["dec"], interpret=True))
    expect = np.zeros((plan._s_pad * n, 2), np.float32)
    expect[ip.value_indices] = vals_il
    np.testing.assert_array_equal(sticks, expect)
    # compress: user-order values back out of the slots
    out = np.asarray(gk.run_monotone_gather(
        jnp.asarray(expect), plan._pallas["cmp"], interpret=True))
    np.testing.assert_array_equal(out, vals_il)


def test_src_rows_covers_whole_source():
    """Regression: compress-direction tables must cover the full source
    array even when the last referenced index is far before its end
    (planar_from_interleaved zero-pads to src_rows * 128)."""
    # values only at the start of a 2048-slot source
    idx = np.arange(50)
    t = gk.build_monotone_gather_tables(idx, np.ones(50, bool), 2048)
    assert t is not None
    assert t.src_rows * gk.TILE_LANE >= 2048
    src = np.random.default_rng(0).random((2048, 2)).astype(np.float32)
    out = np.asarray(gk.run_monotone_gather(jnp.asarray(src), t,
                                            interpret=True))
    np.testing.assert_array_equal(out, src[idx])


def test_segmented_launch_matches_single(monkeypatch):
    """Past the SMEM chunk budget the gather splits into tile-aligned
    launches; force a tiny limit and check the concatenated segments equal
    the single-launch result."""
    monkeypatch.setattr(gk, "SEG_CHUNK_LIMIT", 7)
    rng = np.random.default_rng(41)
    M = 30000
    idx = np.sort(rng.choice(M, 15000, replace=False)).astype(np.int64)
    src = rng.random((M, 2)).astype(np.float32)
    t = gk.build_monotone_gather_tables(idx, np.ones(len(idx), bool), M)
    assert t is not None and len(t.segs) >= 2
    # segments are tile-aligned and cover everything exactly once
    assert t.segs[0][0] == 0 and t.segs[-1][1] == len(t.row0)
    assert t.segs[0][2] == 0 and t.segs[-1][3] == t.num_tiles
    for (a, b) in zip(t.segs, t.segs[1:]):
        assert a[1] == b[0] and a[3] == b[2]
    out = np.asarray(gk.run_monotone_gather(jnp.asarray(src), t,
                                            interpret=True))
    np.testing.assert_array_equal(out, src[idx])
    # batched source through the same segments: per-batch results equal
    src_b = np.stack([src, src * 2, src[::-1]])
    re, im = gk.planar_from_interleaved(jnp.asarray(src_b), t.src_rows)
    out_re, out_im = gk.monotone_gather(
        re, im, jnp.asarray(t.row0), jnp.asarray(t.out_tile),
        jnp.asarray(t.first), jnp.asarray(t.packed),
        span_rows=t.span_rows, src_rows=t.src_rows,
        num_tiles=t.num_tiles, interpret=True, segs=t.segs)
    out_b = np.asarray(gk.interleaved_from_planar(out_re, out_im,
                                                  t.num_out))
    for b in range(3):
        np.testing.assert_array_equal(out_b[b], src_b[b][idx])
    # distributed builds refuse segmentation (uniform stacked tables)
    assert gk.build_monotone_gather_tables(
        idx, np.ones(len(idx), bool), M, allow_segments=False) is None


def test_forced_pallas_on_double_rejected():
    from spfft_tpu import InvalidParameterError, TransformType, make_local_plan
    with pytest.raises(InvalidParameterError):
        make_local_plan(TransformType.C2C, 4, 4, 4, np.array([[0, 0, 0]]),
                        precision="double", use_pallas=True)


# -- wide-kernel (P tiles per grid step) tests --------------------------------

def run_wide(src: np.ndarray, idx: np.ndarray, valid: np.ndarray, **kw):
    t = gk.build_wide_gather_tables(idx, valid, len(src), **kw)
    assert t is not None
    out = gk.run_gather_values(jnp.asarray(src, jnp.float32), t,
                               interpret=True)
    return np.asarray(out), t


@pytest.mark.parametrize("fill", [0.55, 0.9])
def test_wide_expansion_pattern(fill):
    """Decompress-style: masked slots, increments <= 1 — two fill levels
    exercise different kp/K auto choices."""
    rng = np.random.default_rng(10)
    L = 40_000
    mask = rng.random(L) < fill
    n_src = int(mask.sum())
    src = rng.random((n_src, 2)).astype(np.float32)
    idx = np.maximum(np.cumsum(mask) - 1, 0)
    out, t = run_wide(src, idx, mask)
    assert isinstance(t, gk.WideGatherTables)
    ref = np.zeros((L, 2), np.float32)
    ref[mask] = src
    np.testing.assert_array_equal(out, ref)


def test_wide_compaction_pattern():
    rng = np.random.default_rng(11)
    M = 80_000
    idx = np.sort(rng.choice(M, 40_000, replace=False)).astype(np.int64)
    src = rng.random((M, 2)).astype(np.float32)
    out, t = run_wide(src, idx, np.ones(len(idx), bool))
    np.testing.assert_array_equal(out, src[idx])


def test_wide_multi_round_cover():
    """Per-tile spans exceeding kp force multiple rounds per super-tile
    (the revisiting-accumulation path)."""
    rng = np.random.default_rng(12)
    L = 3 * gk.WIDE_P * gk.TILE
    idx = (np.arange(L, dtype=np.int64) * 7) % (L // 2)  # scattered-ish
    idx = np.sort(idx.reshape(-1, gk.TILE), axis=1).reshape(-1)
    src = rng.random((L // 2, 2)).astype(np.float32)
    t = gk.build_wide_gather_tables(idx, np.ones(L, bool), L // 2,
                                    kp_rows=8)
    if t is None:
        pytest.skip("cover declined for this pattern")
    assert t.row0.shape[0] > t.num_super  # at least one multi-chunk tile
    out = np.asarray(gk.run_gather_values(
        jnp.asarray(src, jnp.float32), t, interpret=True))
    np.testing.assert_array_equal(out, src[idx])


def test_wide_block_shuffled_order():
    """Locally-coherent but globally shuffled order stays on the wide path."""
    rng = np.random.default_rng(13)
    M = 120_000
    n = 57_344  # 14 * 4096
    idx = np.sort(rng.choice(M, n, replace=False)).astype(np.int64)
    idx = idx.reshape(-1, 4096)[rng.permutation(n // 4096)].reshape(-1)
    src = rng.random((M, 2)).astype(np.float32)
    out, t = run_wide(src, idx, np.ones(n, bool))
    np.testing.assert_array_equal(out, src[idx])


def test_wide_random_order_falls_back():
    rng = np.random.default_rng(14)
    idx = rng.integers(0, 2_000_000, 60_000)
    assert gk.build_wide_gather_tables(
        idx, np.ones(len(idx), bool), 2_000_000) is None
    # build_best falls through to narrow, then None
    assert gk.build_best_gather_tables(
        idx, np.ones(len(idx), bool), 2_000_000) is None


def test_wide_no_valid_slots_zeroes_output():
    out, t = run_wide(np.ones((64, 2), np.float32),
                      np.zeros(5000, np.int64), np.zeros(5000, bool))
    np.testing.assert_array_equal(out, np.zeros((5000, 2), np.float32))


def test_wide_duplicate_indices():
    rng = np.random.default_rng(15)
    idx = np.repeat(np.arange(3000), 3)[:8192]
    src = rng.random((3000, 2)).astype(np.float32)
    out, _ = run_wide(src, idx, np.ones(8192, bool))
    np.testing.assert_array_equal(out, src[idx])


def test_wide_forced_geometry_rebuild():
    """Forcing common (kp, K) — the distributed uniformity pass — keeps
    results exact."""
    rng = np.random.default_rng(16)
    M = 60_000
    idx = np.sort(rng.choice(M, 30_000, replace=False)).astype(np.int64)
    src = rng.random((M, 2)).astype(np.float32)
    t0 = gk.build_wide_gather_tables(idx, np.ones(len(idx), bool), M)
    t1 = gk.build_wide_gather_tables(idx, np.ones(len(idx), bool), M,
                                     kp_rows=min(t0.kp_rows + 4, 32),
                                     k_rows=t0.span_rows + 8)
    # out-of-range forced kp is rejected, not silently overflowed into the
    # packed word's valid bit
    with pytest.raises(ValueError):
        gk.build_wide_gather_tables(idx, np.ones(len(idx), bool), M,
                                    kp_rows=40)
    out = np.asarray(gk.run_gather_values(jnp.asarray(src, jnp.float32),
                                          t1, interpret=True))
    np.testing.assert_array_equal(out, src[idx])


def test_wide_padded_tables_dummy_block():
    """pad_wide_tables_to appends no-op chunks targeting a dummy super-tile;
    running with num_super + 1 leaves the real output prefix unchanged."""
    rng = np.random.default_rng(17)
    M = 40_000
    idx = np.sort(rng.choice(M, 20_000, replace=False)).astype(np.int64)
    src = rng.random((M, 2)).astype(np.float32)
    t = gk.build_wide_gather_tables(idx, np.ones(len(idx), bool), M)
    padded = gk.pad_wide_tables_to(t, t.row0.shape[0] + 7)
    re, im = gk.planar_from_interleaved(jnp.asarray(src, jnp.float32),
                                        t.src_rows)
    out_re, out_im = gk.wide_gather(
        re, im, *(jnp.asarray(a) for a in padded), span_rows=t.span_rows,
        kp_rows=t.kp_rows, p_tiles=t.p_tiles, src_rows=t.src_rows,
        num_super=t.num_super + 1, interpret=True)
    got = gk.interleaved_from_planar(out_re, out_im, t.num_out)
    np.testing.assert_array_equal(np.asarray(got), src[idx])


def test_wide_segments():
    """Chunk counts past WIDE_SEG_CHUNK_LIMIT run as multiple tile-aligned
    launches (the compile-crash workaround) with identical results."""
    rng = np.random.default_rng(18)
    L = 12 * gk.WIDE_P * gk.TILE
    idx = np.arange(L, dtype=np.int64)
    src = rng.random((L, 2)).astype(np.float32)
    old = gk.WIDE_SEG_CHUNK_LIMIT
    gk.WIDE_SEG_CHUNK_LIMIT = 5
    try:
        t = gk.build_wide_gather_tables(idx, np.ones(L, bool), L)
    finally:
        gk.WIDE_SEG_CHUNK_LIMIT = old
    assert t is not None and len(t.segs) >= 2
    out = np.asarray(gk.run_gather_values(jnp.asarray(src, jnp.float32), t,
                                          interpret=True))
    np.testing.assert_array_equal(out, src)


def test_wide_batched_split_over_step_budget():
    """A batched launch whose B*C exceeds the chunk limit splits into
    per-slab launches (total-grid-step compile-crash guard)."""
    rng = np.random.default_rng(19)
    L = 4 * gk.WIDE_P * gk.TILE
    idx = np.arange(L, dtype=np.int64)
    src = rng.random((3, L, 2)).astype(np.float32)
    t = gk.build_wide_gather_tables(idx, np.ones(L, bool), L)
    assert t is not None
    re, im = gk.planar_from_interleaved(jnp.asarray(src), t.src_rows)
    old = gk.WIDE_SEG_CHUNK_LIMIT
    gk.WIDE_SEG_CHUNK_LIMIT = 2 * t.row0.shape[0]  # B=3 crosses, C alone not
    try:
        out_re, out_im = gk.run_gather(re, im, gk.gather_device_tables(t),
                                       t, interpret=True)
    finally:
        gk.WIDE_SEG_CHUNK_LIMIT = old
    got = np.asarray(gk.interleaved_from_planar(out_re, out_im, t.num_out))
    np.testing.assert_array_equal(got, src)

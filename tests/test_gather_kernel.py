"""Unit tests for the Pallas monotone-gather kernel (interpret mode on CPU)
and its plan-time table builder."""

import numpy as np
import pytest

import jax.numpy as jnp

from spfft_tpu.ops import gather_kernel as gk


def run_gather(src: np.ndarray, idx: np.ndarray, valid: np.ndarray):
    t = gk.build_monotone_gather_tables(idx, valid, len(src))
    assert t is not None
    re, im = gk.planar_from_interleaved(jnp.asarray(src, jnp.float32),
                                        t.src_rows)
    out_re, out_im = gk.monotone_gather(
        re, im, jnp.asarray(t.row0), jnp.asarray(t.lane_sel),
        jnp.asarray(t.row_sel), jnp.asarray(t.mask),
        span_rows=t.span_rows, src_rows=t.src_rows, interpret=True)
    return np.asarray(gk.interleaved_from_planar(out_re, out_im, t.num_out))


def test_expansion_pattern():
    """Decompress-style: masked slots, increments <= 1."""
    rng = np.random.default_rng(0)
    L = 3000
    mask = rng.random(L) < 0.6
    n_src = int(mask.sum())
    src = rng.random((n_src, 2)).astype(np.float32)
    idx = np.maximum(np.cumsum(mask) - 1, 0)
    out = run_gather(src, idx, mask)
    ref = np.zeros((L, 2), np.float32)
    ref[mask] = src
    np.testing.assert_array_equal(out, ref)


def test_compaction_pattern():
    """Compress-style: strictly increasing indices with gaps."""
    rng = np.random.default_rng(1)
    M = 5000
    idx = np.sort(rng.choice(M, 2500, replace=False)).astype(np.int64)
    src = rng.random((M, 2)).astype(np.float32)
    out = run_gather(src, idx, np.ones(len(idx), bool))
    np.testing.assert_array_equal(out, src[idx])


def test_single_tile_and_exact_tile():
    rng = np.random.default_rng(2)
    for L in (100, gk.TILE):
        idx = np.arange(L)
        src = rng.random((L, 2)).astype(np.float32)
        out = run_gather(src, idx, np.ones(L, bool))
        np.testing.assert_array_equal(out, src)


def test_span_bound_rejected():
    """A tile whose source span exceeds MAX_SPAN_ROWS returns None (caller
    falls back to the XLA gather)."""
    idx = np.arange(gk.TILE) * 2 * gk.TILE_LANE  # gaps of 256 elements
    t = gk.build_monotone_gather_tables(idx, np.ones(len(idx), bool),
                                        int(idx[-1]) + 1)
    assert t is None


def test_non_monotone_rejected():
    idx = np.array([5, 3, 7])
    assert gk.build_monotone_gather_tables(idx, np.ones(3, bool), 10) is None


def test_plan_pallas_path_interpret():
    """The plan's Pallas path (forced on, interpret via CPU backend check is
    bypassed by use_pallas=True) matches the XLA path."""
    from spfft_tpu import TransformType, make_local_plan
    rng = np.random.default_rng(3)
    n = 16
    triplets = []
    for x in range(n):
        for y in range(n):
            if (x * n + y) % 3 == 0:
                for z in range(n):
                    triplets.append((x, y, z))
    triplets = np.asarray(triplets, np.int32)
    vals = (rng.uniform(-1, 1, len(triplets))
            + 1j * rng.uniform(-1, 1, len(triplets))).astype(np.complex64)
    ref_plan = make_local_plan(TransformType.C2C, n, n, n, triplets,
                               precision="single", use_pallas=False)
    ref = np.asarray(ref_plan.backward(vals))
    # CPU backend: pallas only via interpret mode — exercise kernel directly
    # through the plan tables
    pl_plan = make_local_plan(TransformType.C2C, n, n, n, triplets,
                              precision="single", use_pallas=True)
    if pl_plan._pallas is None:
        pytest.skip("pallas tables unavailable for this index set")
    t = pl_plan._pallas["dec"]
    src_il = np.stack([vals.real, vals.imag], axis=-1).astype(np.float32)
    re, im = gk.planar_from_interleaved(jnp.asarray(src_il), t.src_rows)
    out_re, out_im = gk.monotone_gather(
        re, im, jnp.asarray(t.row0), jnp.asarray(t.lane_sel),
        jnp.asarray(t.row_sel), jnp.asarray(t.mask),
        span_rows=t.span_rows, src_rows=t.src_rows, interpret=True)
    sticks = np.asarray(gk.interleaved_from_planar(out_re, out_im, t.num_out))
    ip = pl_plan.index_plan
    expect = np.zeros((ip.num_sticks * n, 2), np.float32)
    expect[ip.value_indices] = src_il
    np.testing.assert_array_equal(sticks, expect)
    del ref  # oracle comparison covered by test_local_transform on all paths


def test_src_rows_covers_whole_source():
    """Regression: compress-direction tables must cover the full source
    array even when the last referenced index is far before its end
    (planar_from_interleaved zero-pads to src_rows * 128)."""
    # values only at the start of a 2048-slot source
    idx = np.arange(50)
    t = gk.build_monotone_gather_tables(idx, np.ones(50, bool), 2048)
    assert t is not None
    assert t.src_rows * gk.TILE_LANE >= 2048
    src = np.random.default_rng(0).random((2048, 2)).astype(np.float32)
    re, im = gk.planar_from_interleaved(jnp.asarray(src), t.src_rows)
    out_re, out_im = gk.monotone_gather(
        re, im, jnp.asarray(t.row0), jnp.asarray(t.lane_sel),
        jnp.asarray(t.row_sel), jnp.asarray(t.mask),
        span_rows=t.span_rows, src_rows=t.src_rows, interpret=True)
    out = np.asarray(gk.interleaved_from_planar(out_re, out_im, t.num_out))
    np.testing.assert_array_equal(out, src[idx])


def test_forced_pallas_on_double_rejected():
    from spfft_tpu import InvalidParameterError, TransformType, make_local_plan
    with pytest.raises(InvalidParameterError):
        make_local_plan(TransformType.C2C, 4, 4, 4, np.array([[0, 0, 0]]),
                        precision="double", use_pallas=True)

"""Plan registry: canonical signatures, byte-aware LRU, warmup.

The registry's contract (spfft_tpu/serve/registry.py): equal signatures
MUST be answerable by one plan (the executor's batching invariant), the
resident byte total stays under the configured budget, and every
lookup/build is counted.
"""

import threading
import time

import numpy as np
import pytest

from spfft_tpu import Scaling, TransformType
from spfft_tpu.errors import InvalidParameterError
from spfft_tpu.serve import (PlanRegistry, PlanSignature, index_digest,
                             signature_for)

from test_util import hermitian_triplets, random_sparse_triplets

DIMS = (12, 13, 11)


def _triplets(seed=3):
    return random_sparse_triplets(np.random.default_rng(seed), DIMS)


def test_signature_canonical_across_representations():
    """Centered and wrapped index representations of the SAME sparse set
    digest identically (both canonicalise through the index plan's
    storage tables)."""
    t = _triplets()
    centered = t.astype(np.int64).copy()
    for axis, n in enumerate(DIMS):
        col = centered[:, axis]
        centered[:, axis] = np.where(col > n // 2, col - n, col)
    a = signature_for(TransformType.C2C, *DIMS, t)
    b = signature_for(TransformType.C2C, *DIMS, centered.astype(np.int32))
    assert a == b
    assert hash(a) == hash(b)


def test_signature_order_sensitive():
    """Caller order is part of the identity: the value array is
    positional, so a permuted triplet set is a DIFFERENT plan."""
    t = _triplets()
    perm = t[::-1].copy()
    assert signature_for(TransformType.C2C, *DIMS, t) \
        != signature_for(TransformType.C2C, *DIMS, perm)


def test_signature_fields_distinguish():
    t = _triplets()
    base = signature_for(TransformType.C2C, *DIMS, t)
    assert base != signature_for(TransformType.C2C, *DIMS, t,
                                 precision="double")
    assert base != signature_for(TransformType.C2C, *DIMS, t,
                                 scaling=Scaling.FULL)
    assert base != signature_for(TransformType.C2C, *DIMS, t,
                                 device_count=4)


def test_get_or_build_counts_and_reuses():
    reg = PlanRegistry()
    t = _triplets()
    sig1, plan1 = reg.get_or_build(TransformType.C2C, *DIMS, t,
                                   precision="double")
    sig2, plan2 = reg.get_or_build(TransformType.C2C, *DIMS, t,
                                   precision="double")
    assert sig1 == sig2
    assert plan1 is plan2
    stats = reg.stats()
    assert stats["builds"] == 1
    assert stats["hits"] == 1
    assert stats["misses"] == 1
    assert stats["bytes_in_use"] > 0
    assert reg.hit_rate == 0.5


def test_signature_of_plan_matches_get_or_build():
    reg = PlanRegistry()
    t = _triplets()
    sig, plan = reg.get_or_build(TransformType.C2C, *DIMS, t,
                                 precision="double")
    assert PlanSignature.of_plan(plan) == sig
    assert sig.index_digest == index_digest(plan.index_plan)


def test_byte_aware_eviction():
    """A byte budget below two plans' footprint keeps at most one
    resident (the newest), counting evictions."""
    reg = PlanRegistry(max_bytes=1)  # everything over-budget
    tA = _triplets(1)
    tB = _triplets(2)
    sigA, planA = reg.get_or_build(TransformType.C2C, *DIMS, tA,
                                   precision="double")
    assert len(reg) == 1  # the inserted entry itself survives
    sigB, _ = reg.get_or_build(TransformType.C2C, *DIMS, tB,
                               precision="double")
    assert len(reg) == 1
    assert reg.stats()["evictions"] == 1
    assert reg.get(sigA) is None  # evicted oldest-first
    assert reg.get(sigB) is not None


def test_max_plans_eviction_lru_order():
    reg = PlanRegistry(max_plans=2)
    sigs = []
    for seed in (1, 2, 3):
        sig, _ = reg.get_or_build(TransformType.C2C, *DIMS,
                                  _triplets(seed), precision="double")
        sigs.append(sig)
    assert len(reg) == 2
    assert reg.get(sigs[0]) is None
    assert reg.get(sigs[1]) is not None
    assert reg.get(sigs[2]) is not None
    # refreshing sigs[1] makes sigs[2] the eviction candidate
    reg.get(sigs[1])
    sig4, _ = reg.get_or_build(TransformType.C2C, *DIMS, _triplets(4),
                               precision="double")
    assert reg.get(sigs[1]) is not None
    assert reg.get(sigs[2]) is None


def test_warmup_builds_and_hits():
    reg = PlanRegistry()
    specs = [dict(transform_type=TransformType.C2C, dim_x=DIMS[0],
                  dim_y=DIMS[1], dim_z=DIMS[2], triplets=_triplets(s),
                  precision="double") for s in (1, 2)]
    sigs = reg.warmup(specs, compile=True)
    assert len(sigs) == 2 and sigs[0] != sigs[1]
    assert reg.stats()["builds"] == 2
    # post-warmup traffic hits
    for _ in range(20):
        for sig in sigs:
            assert reg.get(sig) is not None
    assert reg.hit_rate >= 0.9  # the acceptance bar


def test_warmup_r2c_single():
    """R2C + single precision warmup executes its zero-valued compile
    pass without shape errors."""
    reg = PlanRegistry()
    t = hermitian_triplets(np.random.default_rng(5), DIMS)
    sigs = reg.warmup([dict(transform_type=TransformType.R2C,
                            dim_x=DIMS[0], dim_y=DIMS[1], dim_z=DIMS[2],
                            triplets=t, precision="single")],
                      compile=True)
    plan = reg.get(sigs[0])
    assert plan is not None and plan.index_plan.hermitian


def test_registry_rejects_bad_bounds():
    with pytest.raises(InvalidParameterError):
        PlanRegistry(max_plans=0)


# -- get_or_build hot path (zero-rebuild resolution) ------------------------
def test_fast_path_skips_index_plan_build(monkeypatch):
    """A repeated raw request shape resolves through the bytes -> sig
    memo without touching build_index_plan (the cost the fast path
    exists to skip)."""
    import spfft_tpu.serve.registry as regmod
    calls = {"n": 0}
    real = regmod.build_index_plan

    def counting(*a, **k):
        calls["n"] += 1
        return real(*a, **k)

    monkeypatch.setattr(regmod, "build_index_plan", counting)
    reg = PlanRegistry()
    t = _triplets()
    sig1, plan1 = reg.get_or_build(TransformType.C2C, *DIMS, t,
                                   precision="double")
    assert calls["n"] == 1
    sig2, plan2 = reg.get_or_build(TransformType.C2C, *DIMS, t,
                                   precision="double")
    assert calls["n"] == 1  # memo hit: no index-plan rebuild
    assert sig1 == sig2 and plan1 is plan2
    assert reg.stats()["fast_hits"] == 1


def test_memo_two_spellings_resolve_one_plan():
    """Centered and wrapped spellings of one sparse set occupy two memo
    slots but resolve to the SAME canonical signature and plan — one
    build total."""
    reg = PlanRegistry()
    t = _triplets()
    centered = t.astype(np.int64).copy()
    for axis, n in enumerate(DIMS):
        col = centered[:, axis]
        centered[:, axis] = np.where(col > n // 2, col - n, col)
    sig1, plan1 = reg.get_or_build(TransformType.C2C, *DIMS, t,
                                   precision="double")
    sig2, plan2 = reg.get_or_build(TransformType.C2C, *DIMS,
                                   centered.astype(np.int32),
                                   precision="double")
    assert sig1 == sig2 and plan1 is plan2
    assert reg.stats()["builds"] == 1
    assert reg.stats()["sig_memo_entries"] == 2


def test_singleflight_concurrent_misses_build_once():
    """N threads racing the same cold shape: exactly one TransformPlan
    construction (the dogpile guard), every caller gets the same
    object."""
    reg = PlanRegistry()
    t = _triplets()
    n_threads = 8
    results = [None] * n_threads
    barrier = threading.Barrier(n_threads)

    def worker(i):
        barrier.wait()
        results[i] = reg.get_or_build(TransformType.C2C, *DIMS, t,
                                      precision="double")

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(n_threads)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    sig0, plan0 = results[0]
    assert all(sig == sig0 and plan is plan0 for sig, plan in results)
    stats = reg.stats()
    assert stats["builds"] == 1
    assert stats["misses"] == 1
    assert stats["hits"] == n_threads - 1


def test_singleflight_builder_failure_releases_followers():
    """A failing build doesn't wedge the per-shape lock: followers
    retry and one of them becomes the builder."""
    import spfft_tpu.serve.registry as regmod
    reg = PlanRegistry()
    t = _triplets()
    real = regmod.build_index_plan
    state = {"fail_next": True}

    def flaky(*a, **k):
        if state["fail_next"]:
            state["fail_next"] = False
            raise RuntimeError("injected build failure")
        return real(*a, **k)

    orig = regmod.build_index_plan
    regmod.build_index_plan = flaky
    try:
        with pytest.raises(RuntimeError):
            reg.get_or_build(TransformType.C2C, *DIMS, t,
                             precision="double")
        sig, plan = reg.get_or_build(TransformType.C2C, *DIMS, t,
                                     precision="double")
    finally:
        regmod.build_index_plan = orig
    assert plan is not None
    assert reg.stats()["builds"] == 1
    assert reg.stats()["build_failures"] == 1


def test_singleflight_failure_released_to_all_waiters_at_once(
        monkeypatch):
    """Waiters parked behind one failing build all get the builder's
    exception from the SHARED flight — exactly one build attempt, no
    serial re-building wedge. Sequenced deterministically: the builder
    blocks inside the (patched) build until both waiters are observed
    entering the flight's wait."""
    import spfft_tpu.serve.registry as regmod
    reg = PlanRegistry()
    t = _triplets()
    attempts = {"n": 0}
    waiters_parked = threading.Semaphore(0)
    release = threading.Event()

    class SpyFlight(regmod._BuildFlight):
        """Flight whose waiters announce themselves before blocking."""

        class _SpyEvent(threading.Event):
            def wait(self, *a, **k):
                waiters_parked.release()
                return super().wait(*a, **k)

        def __init__(self):
            super().__init__()
            self.done = self._SpyEvent()

    def slow_flaky(*a, **k):
        attempts["n"] += 1
        release.wait(timeout=30)  # held until waiters are parked
        raise RuntimeError("injected build failure")

    real = regmod.build_index_plan
    monkeypatch.setattr(regmod, "_BuildFlight", SpyFlight)
    monkeypatch.setattr(regmod, "build_index_plan", slow_flaky)
    results = [None, None, None]

    def worker(i):
        try:
            results[i] = reg.get_or_build(TransformType.C2C, *DIMS, t,
                                          precision="double")
        except RuntimeError as exc:
            results[i] = exc

    builder = threading.Thread(target=worker, args=(0,))
    builder.start()
    while attempts["n"] == 0:  # builder is inside the flight
        time.sleep(0.001)
    waiters = [threading.Thread(target=worker, args=(i,))
               for i in (1, 2)]
    for th in waiters:
        th.start()
    for _ in (1, 2):  # both waiters joined the flight's wait
        assert waiters_parked.acquire(timeout=30)
    release.set()
    for th in [builder] + waiters:
        th.join(timeout=30)
    assert attempts["n"] == 1  # one failing build, not one per waiter
    assert all(isinstance(r, RuntimeError) for r in results)
    assert reg.stats()["build_failures"] == 1
    monkeypatch.setattr(regmod, "build_index_plan", real)
    sig, plan = reg.get_or_build(TransformType.C2C, *DIMS, t,
                                 precision="double")
    assert plan is not None and reg.stats()["builds"] == 1


# -- background-builder death -----------------------------------------------
# The BACKGROUND half of the plan.build seam is ambient call #2 (call
# #1 is the foreground construction); use_pallas=True spawns the
# builder thread even on CPU. The hang variant (sleep-then-fail) makes
# the death land AFTER construction returns, pinning down exactly which
# later checkpoint must surface it.

def test_builder_death_surfaces_at_get_or_build_resolution():
    """A builder that dies IMMEDIATELY is surfaced typed at registry
    resolution: either the owner-path check_build catches it inside the
    building get_or_build, or (when the race goes the other way) the
    sticky TableBuildError surfaces on the very next fast-path hit —
    never on a request."""
    from spfft_tpu import faults
    from spfft_tpu.errors import TableBuildError

    t = _triplets()
    reg = PlanRegistry()
    try:
        faults.arm(faults.FaultPlan(script="plan.build@2"))
        try:
            sig, plan = reg.get_or_build(TransformType.C2C, *DIMS, t,
                                         use_pallas=True)
        except TableBuildError:
            return  # owner path saw the dead builder — done
        with pytest.raises(TableBuildError):
            plan.check_build(wait=True)
        # the error is sticky: the memoized fast path refuses to hand
        # the doomed plan out
        with pytest.raises(TableBuildError):
            reg.get_or_build(TransformType.C2C, *DIMS, t,
                             use_pallas=True)
    finally:
        faults.disarm()


def test_builder_death_surfaces_in_warmup():
    """warmup() is the blocking pre-traffic path: it JOINS the build,
    so a builder doomed to die later still fails the warmup call
    itself, not the first request."""
    from spfft_tpu import faults
    from spfft_tpu.errors import TableBuildError

    t = _triplets()
    reg = PlanRegistry()
    spec = {"transform_type": TransformType.C2C, "dim_x": DIMS[0],
            "dim_y": DIMS[1], "dim_z": DIMS[2], "triplets": t,
            "use_pallas": True}
    try:
        faults.arm(faults.FaultPlan(script="plan.build@2:hang",
                                    hang_seconds=0.2))
        with pytest.raises(TableBuildError):
            reg.warmup([spec])
    finally:
        faults.disarm()


def test_builder_death_surfaces_in_executor_prewarm():
    """Executor prewarm joins the background build before compiling:
    a plan whose builder dies after registration fails prewarm with the
    typed TableBuildError instead of poisoning the first routed
    request."""
    from spfft_tpu import faults
    from spfft_tpu.errors import TableBuildError
    from spfft_tpu.serve import ServeExecutor

    t = _triplets()
    reg = PlanRegistry()
    try:
        faults.arm(faults.FaultPlan(script="plan.build@2:hang",
                                    hang_seconds=0.2))
        # the builder sleeps 0.2 s before dying, so registration and
        # executor construction see a live (not-yet-failed) build
        sig, plan = reg.get_or_build(TransformType.C2C, *DIMS, t,
                                     use_pallas=True)
        assert reg.get(sig) is plan
        ex = ServeExecutor(reg, autostart=False)
        try:
            with pytest.raises(TableBuildError):
                ex.prewarm(sig)
        finally:
            ex.close()
    finally:
        faults.disarm()

"""Transpose/exchange-layer unit tests, below the FFT pipeline.

Mirrors reference tests/mpi_tests/test_transpose.cpp: drive the pack →
exchange → unpack mechanism directly against the plan's distribution
tables, checking (a) the freq→space→freq round trip restores every true
stick, (b) stick segments land at the correct (z, y, x) grid positions —
for both the fused all_to_all and the ppermute-ring mechanisms."""

import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from spfft_tpu import TransformType
from spfft_tpu.parallel import make_distributed_plan, make_mesh
from spfft_tpu.parallel.mesh import shard_map
from spfft_tpu.parallel.exchange import (all_to_all_blocks,
                                         pack_freq_to_blocks,
                                         pack_space_to_blocks,
                                         ring_exchange_blocks,
                                         unpack_blocks_to_grid,
                                         unpack_blocks_to_sticks)

from test_util import random_sparse_triplets
from test_distributed import split_by_sticks, split_planes

DIMS = (11, 12, 13)


def _make_plan(exchange_weights=([2, 1, 0, 1], [1, 3, 1, 2])):
    rng = np.random.default_rng(31)
    triplets = random_sparse_triplets(rng, DIMS)
    parts = split_by_sticks(triplets, DIMS, exchange_weights[0])
    planes = split_planes(DIMS[2], exchange_weights[1])
    plan = make_distributed_plan(TransformType.C2C, *DIMS, parts, planes,
                                 mesh=make_mesh(4), precision="double")
    return plan


@pytest.mark.parametrize("mechanism", [all_to_all_blocks,
                                       ring_exchange_blocks])
def test_exchange_round_trip_restores_sticks(mechanism):
    plan = _make_plan()
    dp = plan.dist_plan
    rng = np.random.default_rng(32)
    S, ms, dz = dp.num_shards, dp.max_sticks, dp.dim_z
    sticks = np.zeros((S, ms, dz), np.complex128)
    for r in range(S):
        n = dp.shard_plans[r].num_sticks
        sticks[r, :n] = (rng.standard_normal((n, dz))
                         + 1j * rng.standard_normal((n, dz)))

    def body(sticks, zmap, col_inv, cols_flat, z_src):
        blocks = pack_freq_to_blocks(sticks[0], zmap)
        blocks = mechanism(blocks, plan.axis_name, None)
        grid = unpack_blocks_to_grid(blocks, col_inv, dp.dim_y,
                                     dp.dim_x_freq)
        blocks2 = pack_space_to_blocks(grid, cols_flat, S, ms)
        blocks2 = mechanism(blocks2, plan.axis_name, None)
        return unpack_blocks_to_sticks(blocks2, z_src)[None]

    shmap = shard_map(
        body, mesh=plan.mesh,
        in_specs=(P(plan.axis_name), P(), P(), P(), P()),
        out_specs=P(plan.axis_name))
    got = np.asarray(jax.jit(shmap)(
        jax.device_put(sticks, NamedSharding(plan.mesh, P(plan.axis_name))),
        plan._zmap, plan._col_inv, plan._cols_flat, plan._z_src))
    for r in range(S):
        n = dp.shard_plans[r].num_sticks
        np.testing.assert_allclose(got[r, :n], sticks[r, :n], atol=0,
                                   rtol=0)


def test_exchange_grid_placement():
    """After the backward exchange, each shard's grid must hold stick
    (x, y) of shard r at [z_local, y, x] for each of its true planes —
    checked against a dense oracle built from the plan metadata."""
    plan = _make_plan()
    dp = plan.dist_plan
    rng = np.random.default_rng(33)
    S, ms, dz = dp.num_shards, dp.max_sticks, dp.dim_z
    sticks = np.zeros((S, ms, dz), np.complex128)
    for r in range(S):
        n = dp.shard_plans[r].num_sticks
        sticks[r, :n] = (rng.standard_normal((n, dz))
                         + 1j * rng.standard_normal((n, dz)))

    def body(sticks, zmap, col_inv):
        blocks = pack_freq_to_blocks(sticks[0], zmap)
        blocks = all_to_all_blocks(blocks, plan.axis_name, None)
        return unpack_blocks_to_grid(blocks, col_inv, dp.dim_y,
                                     dp.dim_x_freq)[None]

    shmap = shard_map(
        body, mesh=plan.mesh, in_specs=(P(plan.axis_name), P(), P()),
        out_specs=P(plan.axis_name))
    grids = np.asarray(jax.jit(shmap)(
        jax.device_put(sticks, NamedSharding(plan.mesh, P(plan.axis_name))),
        plan._zmap, plan._col_inv))

    # oracle: dense (dim_z, dim_y, dim_x_freq) built from stick tables
    dense = np.zeros((dz, dp.dim_y, dp.dim_x_freq), np.complex128)
    for r in range(S):
        sp = dp.shard_plans[r]
        for i in range(sp.num_sticks):
            key = int(sp.stick_keys[i])
            x, y = key // dp.dim_y, key % dp.dim_y
            dense[:, y, x] = sticks[r, i]
    for r in range(S):
        off, n_pl = dp.plane_offsets[r], dp.num_planes[r]
        np.testing.assert_allclose(grids[r, :n_pl],
                                   dense[off:off + n_pl], atol=0, rtol=0)

"""Parity tests: native C++ planner vs the NumPy reference semantics.

The NumPy path in spfft_tpu.indexing is the executable specification of the
reference index conversion (reference: src/compression/indices.hpp:120-186);
the native library must agree bit-for-bit on valid inputs and raise the same
exception types on invalid ones.
"""

import numpy as np
import pytest

from spfft_tpu import native
from spfft_tpu.errors import InvalidIndicesError
from spfft_tpu.indexing import (build_index_plan, inverse_col_map,
                                inverse_slot_map)
from spfft_tpu.types import TransformType

from test_util import center_triplets, random_sparse_triplets


def _make_triplets(rng, dims, centered, hermitian):
    """Random triplet set valid for the given mode: hermitian restricts
    storage x to [0, dim_x//2]; centered converts to negative-frequency
    indexing (x stays non-negative for hermitian)."""
    t = random_sparse_triplets(rng, dims)
    if hermitian:
        t = t[t[:, 0] <= dims[0] // 2]
        if t.shape[0] == 0:
            t = np.array([[0, 0, 0]], np.int32)
    if centered:
        c = center_triplets(t, dims)
        if hermitian:
            c[:, 0] = t[:, 0]
        t = c
    return t

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native planner unavailable")


def _numpy_reference(hermitian, dims, triplets):
    """The pure-NumPy conversion, bypassing the native fast path."""
    dim_x, dim_y, dim_z = dims
    x, y, z = (triplets[:, 0].astype(np.int64),
               triplets[:, 1].astype(np.int64),
               triplets[:, 2].astype(np.int64))
    xs = np.where(x < 0, x + dim_x, x)
    ys = np.where(y < 0, y + dim_y, y)
    zs = np.where(z < 0, z + dim_z, z)
    keys = xs * dim_y + ys
    stick_keys, stick_ids = np.unique(keys, return_inverse=True)
    value_indices = stick_ids.astype(np.int64) * dim_z + zs
    return value_indices.astype(np.int32), stick_keys.astype(np.int32)


DIMS = [(1, 1, 1), (2, 3, 4), (11, 12, 13), (13, 11, 12), (32, 32, 32),
        (100, 13, 2)]


@pytest.mark.parametrize("dims", DIMS)
@pytest.mark.parametrize("centered", [False, True])
@pytest.mark.parametrize("hermitian", [False, True])
def test_plan_indices_parity(dims, centered, hermitian):
    rng = np.random.default_rng(hash((dims, centered, hermitian)) % 2**32)
    triplets = _make_triplets(rng, dims, centered, hermitian)
    res = native.plan_indices(hermitian, *dims, triplets)
    assert res is not None
    vi, keys, got_centered = res
    ref_vi, ref_keys = _numpy_reference(hermitian, dims, triplets)
    np.testing.assert_array_equal(vi, ref_vi)
    np.testing.assert_array_equal(keys, ref_keys)
    assert got_centered == bool((triplets < 0).any())


def test_plan_indices_empty():
    res = native.plan_indices(False, 4, 4, 4,
                              np.empty((0, 3), np.int64))
    vi, keys, centered = res
    assert vi.size == 0 and keys.size == 0 and not centered


@pytest.mark.parametrize("bad", [
    np.array([[4, 0, 0]]),    # x beyond dim-1
    np.array([[0, -3, 0]]),   # centered y below floor(4/2) - 4 + 1 = -1
    np.array([[0, 0, 99]]),   # z far out of range
])
def test_plan_indices_bounds(bad):
    with pytest.raises(InvalidIndicesError):
        build_index_plan(TransformType.C2C, 4, 4, 4, bad)


def test_hermitian_negative_x_folds_onto_mirror():
    # round 15: negative-x r2c triplets are no longer rejected — they
    # fold onto the conjugate mirror stick (value_conj marks the read
    # as conjugated), so full-sphere inputs build trimmed plans
    p = build_index_plan(TransformType.R2C, 8, 8, 8,
                         np.array([[-1, 0, 0]]))
    assert p.stick_x.tolist() == [1] and p.stick_y.tolist() == [0]
    assert p.value_conj is not None and p.value_conj.tolist() == [True]
    # out-of-range x is still a bounds error after the fold
    with pytest.raises(InvalidIndicesError):
        build_index_plan(TransformType.R2C, 8, 8, 8,
                         np.array([[-5, 0, 0]]))


def test_inverse_map_parity():
    rng = np.random.default_rng(7)
    n_slots = 1000
    idx = rng.choice(n_slots, size=300, replace=False).astype(np.int32)
    got = native.inverse_map(idx, n_slots, 300)
    ref = np.full(n_slots, 300, np.int32)
    ref[idx] = np.arange(300, dtype=np.int32)
    np.testing.assert_array_equal(got, ref)


def test_inverse_map_duplicates_last_wins():
    idx = np.array([5, 5, 2, 5], np.int32)
    got = native.inverse_map(idx, 8, 4)
    assert got[5] == 3 and got[2] == 2
    assert all(got[i] == 4 for i in (0, 1, 3, 4, 6, 7))


def test_full_plan_through_native_matches_numpy(monkeypatch):
    """build_index_plan with and without the native path must agree."""
    rng = np.random.default_rng(3)
    dims = (12, 13, 11)
    triplets = random_sparse_triplets(rng, dims)
    plan_native = build_index_plan(TransformType.C2C, *dims, triplets)
    monkeypatch.setenv("SPFFT_TPU_NO_NATIVE", "1")
    plan_numpy = build_index_plan(TransformType.C2C, *dims, triplets)
    np.testing.assert_array_equal(plan_native.value_indices,
                                  plan_numpy.value_indices)
    np.testing.assert_array_equal(plan_native.stick_keys,
                                  plan_numpy.stick_keys)
    np.testing.assert_array_equal(plan_native.slot_src, plan_numpy.slot_src)
    np.testing.assert_array_equal(plan_native.col_inv, plan_numpy.col_inv)


def test_native_wide_tables_parity(monkeypatch):
    """The C++ wide-gather cover produces IDENTICAL tables to the NumPy
    builder (the executable specification) — geometry choice, chunk order,
    packed words, byte-packed sub offsets, everything."""
    from spfft_tpu import native
    from spfft_tpu.ops import gather_kernel as gk

    if not native.available():
        pytest.skip("native library unavailable")
    rng = np.random.default_rng(44)

    def cases():
        L, num_src = 50_000, 34_000
        steps = (rng.random(L) < 0.67).astype(np.int64)
        yield ("decompress", np.minimum(np.cumsum(steps) - steps,
                                        num_src - 1),
               steps.astype(bool), num_src, {})
        idx2 = np.sort(rng.choice(120_000, 60_000, replace=False))
        yield ("compress", idx2, np.ones(60_000, bool), 120_000, {})
        n = 57_344
        idx3 = np.sort(rng.choice(99_000, n, replace=False))
        idx3 = idx3.reshape(-1, 4096)[rng.permutation(n // 4096)].reshape(-1)
        yield ("block-shuffled", idx3, np.ones(n, bool), 99_000, {})
        yield ("tiny", np.arange(100), np.ones(100, bool), 100, {})
        yield ("forced", idx2, np.ones(60_000, bool), 120_000,
               {"kp_rows": 16, "k_rows": 128})

    for name, idx, valid, num_src, kw in cases():
        t_nat = gk.build_wide_gather_tables(idx, valid, num_src, **kw)
        with monkeypatch.context() as m:
            m.setattr(native, "wide_gather_tables",
                      lambda *a, **k: None)
            t_py = gk.build_wide_gather_tables(idx, valid, num_src, **kw)
        assert (t_nat is None) == (t_py is None), name
        if t_nat is None:
            continue
        for field in ("num_out", "num_super", "src_rows", "span_rows",
                      "kp_rows", "p_tiles", "segs"):
            assert getattr(t_nat, field) == getattr(t_py, field), \
                f"{name}.{field}"
        for field in ("row0", "sub", "out_tile", "first", "packed"):
            a, b = getattr(t_nat, field), getattr(t_py, field)
            assert a.dtype == b.dtype, f"{name}.{field} dtype"
            np.testing.assert_array_equal(a, b, err_msg=f"{name}.{field}")


def test_native_wide_tables_blowup_parity(monkeypatch):
    """Random order falls back identically (native raises the internal
    blowup signal exactly where the NumPy cover returns None)."""
    from spfft_tpu import native
    from spfft_tpu.ops import gather_kernel as gk

    if not native.available():
        pytest.skip("native library unavailable")
    rng = np.random.default_rng(45)
    idx = rng.integers(0, 2_000_000, 60_000)
    assert gk.build_wide_gather_tables(idx, np.ones(60_000, bool),
                                       2_000_000) is None
    with monkeypatch.context() as m:
        m.setattr(native, "wide_gather_tables", lambda *a, **k: None)
        assert gk.build_wide_gather_tables(idx, np.ones(60_000, bool),
                                           2_000_000) is None


def test_native_compression_inputs_parity(monkeypatch):
    """Native occupied/forward-fill matches the NumPy specification,
    including duplicates (last wins), leading gaps, and empty slots."""
    from spfft_tpu import native
    from spfft_tpu.ops import gather_kernel as gk

    if not native.available():
        pytest.skip("native library unavailable")
    rng = np.random.default_rng(46)
    for trial in range(5):
        num_slots = int(rng.integers(50, 5000))
        n = int(rng.integers(1, num_slots))
        vi = rng.integers(0, num_slots, n)  # duplicates likely
        nat = gk.compression_gather_inputs(vi, num_slots)
        with monkeypatch.context() as m:
            m.setattr(native, "compression_inputs", lambda *a: None)
            py = gk.compression_gather_inputs(vi, num_slots)
        for got, want in zip(nat, py):
            np.testing.assert_array_equal(got[0], want[0])
            np.testing.assert_array_equal(got[1], want[1])

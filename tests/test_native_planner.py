"""Parity tests: native C++ planner vs the NumPy reference semantics.

The NumPy path in spfft_tpu.indexing is the executable specification of the
reference index conversion (reference: src/compression/indices.hpp:120-186);
the native library must agree bit-for-bit on valid inputs and raise the same
exception types on invalid ones.
"""

import numpy as np
import pytest

from spfft_tpu import native
from spfft_tpu.errors import InvalidIndicesError
from spfft_tpu.indexing import (build_index_plan, inverse_col_map,
                                inverse_slot_map)
from spfft_tpu.types import TransformType

from test_util import center_triplets, random_sparse_triplets


def _make_triplets(rng, dims, centered, hermitian):
    """Random triplet set valid for the given mode: hermitian restricts
    storage x to [0, dim_x//2]; centered converts to negative-frequency
    indexing (x stays non-negative for hermitian)."""
    t = random_sparse_triplets(rng, dims)
    if hermitian:
        t = t[t[:, 0] <= dims[0] // 2]
        if t.shape[0] == 0:
            t = np.array([[0, 0, 0]], np.int32)
    if centered:
        c = center_triplets(t, dims)
        if hermitian:
            c[:, 0] = t[:, 0]
        t = c
    return t

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native planner unavailable")


def _numpy_reference(hermitian, dims, triplets):
    """The pure-NumPy conversion, bypassing the native fast path."""
    dim_x, dim_y, dim_z = dims
    x, y, z = (triplets[:, 0].astype(np.int64),
               triplets[:, 1].astype(np.int64),
               triplets[:, 2].astype(np.int64))
    xs = np.where(x < 0, x + dim_x, x)
    ys = np.where(y < 0, y + dim_y, y)
    zs = np.where(z < 0, z + dim_z, z)
    keys = xs * dim_y + ys
    stick_keys, stick_ids = np.unique(keys, return_inverse=True)
    value_indices = stick_ids.astype(np.int64) * dim_z + zs
    return value_indices.astype(np.int32), stick_keys.astype(np.int32)


DIMS = [(1, 1, 1), (2, 3, 4), (11, 12, 13), (13, 11, 12), (32, 32, 32),
        (100, 13, 2)]


@pytest.mark.parametrize("dims", DIMS)
@pytest.mark.parametrize("centered", [False, True])
@pytest.mark.parametrize("hermitian", [False, True])
def test_plan_indices_parity(dims, centered, hermitian):
    rng = np.random.default_rng(hash((dims, centered, hermitian)) % 2**32)
    triplets = _make_triplets(rng, dims, centered, hermitian)
    res = native.plan_indices(hermitian, *dims, triplets)
    assert res is not None
    vi, keys, got_centered = res
    ref_vi, ref_keys = _numpy_reference(hermitian, dims, triplets)
    np.testing.assert_array_equal(vi, ref_vi)
    np.testing.assert_array_equal(keys, ref_keys)
    assert got_centered == bool((triplets < 0).any())


def test_plan_indices_empty():
    res = native.plan_indices(False, 4, 4, 4,
                              np.empty((0, 3), np.int64))
    vi, keys, centered = res
    assert vi.size == 0 and keys.size == 0 and not centered


@pytest.mark.parametrize("bad", [
    np.array([[4, 0, 0]]),    # x beyond dim-1
    np.array([[0, -3, 0]]),   # centered y below floor(4/2) - 4 + 1 = -1
    np.array([[0, 0, 99]]),   # z far out of range
])
def test_plan_indices_bounds(bad):
    with pytest.raises(InvalidIndicesError):
        build_index_plan(TransformType.C2C, 4, 4, 4, bad)


def test_hermitian_negative_x_rejected():
    with pytest.raises(InvalidIndicesError):
        build_index_plan(TransformType.R2C, 8, 8, 8,
                         np.array([[-1, 0, 0]]))


def test_inverse_map_parity():
    rng = np.random.default_rng(7)
    n_slots = 1000
    idx = rng.choice(n_slots, size=300, replace=False).astype(np.int32)
    got = native.inverse_map(idx, n_slots, 300)
    ref = np.full(n_slots, 300, np.int32)
    ref[idx] = np.arange(300, dtype=np.int32)
    np.testing.assert_array_equal(got, ref)


def test_inverse_map_duplicates_last_wins():
    idx = np.array([5, 5, 2, 5], np.int32)
    got = native.inverse_map(idx, 8, 4)
    assert got[5] == 3 and got[2] == 2
    assert all(got[i] == 4 for i in (0, 1, 3, 4, 6, 7))


def test_full_plan_through_native_matches_numpy(monkeypatch):
    """build_index_plan with and without the native path must agree."""
    rng = np.random.default_rng(3)
    dims = (12, 13, 11)
    triplets = random_sparse_triplets(rng, dims)
    plan_native = build_index_plan(TransformType.C2C, *dims, triplets)
    monkeypatch.setenv("SPFFT_TPU_NO_NATIVE", "1")
    plan_numpy = build_index_plan(TransformType.C2C, *dims, triplets)
    np.testing.assert_array_equal(plan_native.value_indices,
                                  plan_numpy.value_indices)
    np.testing.assert_array_equal(plan_native.stick_keys,
                                  plan_numpy.stick_keys)
    np.testing.assert_array_equal(plan_native.slot_src, plan_numpy.slot_src)
    np.testing.assert_array_equal(plan_native.col_inv, plan_numpy.col_inv)

"""Outlier-session hygiene in the north-star bench (bench.py).

The r05 best-of-4 line disclosed a 274.74 ms session next to 10.6-11 ms
ones; the best-of statistic was immune but the mixed list distorted
trajectory comparisons. The split helper must flag exactly such hiccups
and never flag healthy spread."""

import importlib.util
import os

spec = importlib.util.spec_from_file_location(
    "bench_root", os.path.join(os.path.dirname(__file__), os.pardir,
                               "bench.py"))
bench = importlib.util.module_from_spec(spec)
spec.loader.exec_module(bench)


def test_r05_hiccup_is_flagged():
    values = [0.01076, 0.01068, 0.27474, 0.01067]
    kept, outliers = bench.split_outlier_sessions(values)
    assert outliers == [0.27474]
    assert sorted(kept) == sorted([0.01076, 0.01068, 0.01067])


def test_healthy_spread_not_flagged():
    values = [0.0119, 0.0123, 0.0129, 0.0131]
    kept, outliers = bench.split_outlier_sessions(values)
    assert outliers == [] and len(kept) == 4


def test_small_sample_never_flagged():
    assert bench.split_outlier_sessions([0.01, 0.5]) \
        == ([0.01, 0.5], [])


def test_min_session_survives():
    """The best-of value can never be dropped: outliers are high-side
    only (cut is above the median)."""
    values = [0.009, 0.011, 0.012, 0.3]
    kept, _ = bench.split_outlier_sessions(values)
    assert min(values) in kept

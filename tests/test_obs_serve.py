"""End-to-end request tracing through the serving executor
(spfft_tpu.obs wired into spfft_tpu.serve).

The load-bearing guarantees, each tested deterministically on CPU:

* COVERAGE — a traced request produces spans for all eight pipeline
  stages (submit, queue-wait, bucket-formation, stage, dispatch,
  device-execute, materialise, resolve) under one trace id, correctly
  parented and time-nested;
* ZERO UNCLOSED SPANS under faults — for EVERY FaultPlan site
  (stage / dispatch / materialise / loop / device-N) and for deadline
  expiry, queue-full rejection and no-drain close, the tracer ends the
  test with zero open spans and failed requests' root spans carry the
  typed error name;
* CONCURRENCY — the 8-thread mixed-priority fuzz keeps trace ids
  unique, parent/child links valid, and leaks nothing;
* SAMPLING — rate 0 traces nothing; the disabled path records nothing.
"""

import threading

import numpy as np
import pytest

import jax

from spfft_tpu import TransformType, obs
from spfft_tpu.serve import FaultPlan, PlanRegistry, ServeExecutor

from test_util import random_sparse_triplets

DIMS = (12, 13, 11)


@pytest.fixture(autouse=True)
def _traced():
    obs.enable()
    obs.GLOBAL_TRACER.reset()
    obs.GLOBAL_TRACER.set_sample_rate(1.0)
    yield
    obs.disable()
    obs.GLOBAL_TRACER.reset()
    obs.GLOBAL_TRACER.set_sample_rate(1.0)


def _registry():
    reg = PlanRegistry()
    rng = np.random.default_rng(7)
    t = random_sparse_triplets(rng, DIMS)
    sig, _ = reg.get_or_build(TransformType.C2C, *DIMS, t,
                              precision="double")
    return reg, sig


def _values(reg, sig, rng):
    n = reg.get(sig).index_plan.num_values
    return rng.uniform(-1, 1, n) + 1j * rng.uniform(-1, 1, n)


def _spans():
    return [e for e in obs.GLOBAL_TRACER.events()
            if isinstance(e, obs.Span)]


STAGES = ("serve.submit", "serve.queue_wait", "serve.bucket_formation",
          "serve.stage", "serve.dispatch", "serve.device_execute",
          "serve.materialise", "serve.resolve")


def test_traced_request_covers_all_eight_stages():
    reg, sig = _registry()
    rng = np.random.default_rng(0)
    ex = ServeExecutor(reg, autostart=False, batch_window=0.0)
    futs = [ex.submit(sig, _values(reg, sig, rng)) for _ in range(4)]
    ex._drain_once()
    for f in futs:
        f.result(timeout=30)
    ex.close()
    assert obs.GLOBAL_TRACER.open_count() == 0, \
        obs.GLOBAL_TRACER.open_names()
    spans = _spans()
    names = {s.name for s in spans}
    for stage in STAGES:
        assert stage in names, f"missing stage span {stage}"
    roots = [s for s in spans if s.name == "serve.request"]
    assert len(roots) == 4
    assert all(r.status == "ok" for r in roots)
    # registry build recorded on the compile track
    assert "compile.registry_build" in names


def test_span_nesting_and_parents_valid():
    reg, sig = _registry()
    rng = np.random.default_rng(1)
    ex = ServeExecutor(reg, autostart=False, batch_window=0.0)
    futs = [ex.submit(sig, _values(reg, sig, rng)) for _ in range(3)]
    ex._drain_once()
    for f in futs:
        f.result(timeout=30)
    ex.close()
    spans = _spans()
    by_id = {s.span_id: s for s in spans}
    checked = 0
    for s in spans:
        if s.parent_id is None:
            continue
        parent = by_id.get(s.parent_id)
        assert parent is not None, f"{s.name}: dangling parent"
        assert parent.trace_id == s.trace_id
        # clean-path spans nest strictly inside their parent interval
        assert s.t0 >= parent.t0 - 1e-6, f"{s.name} starts before parent"
        assert s.t1 <= parent.t1 + 1e-6, f"{s.name} ends after parent"
        checked += 1
    assert checked >= 3 * 3  # at least per-request stage spans


@pytest.mark.parametrize("script", [
    "stage@1", "dispatch@1", "materialise@1", "loop@1:permanent",
    "stage@1:permanent", "dispatch@*:permanent",
])
def test_zero_unclosed_spans_under_faults(script):
    """For each FaultPlan site: every span closes, and requests that
    ultimately fail carry the typed error on their root span."""
    reg, sig = _registry()
    rng = np.random.default_rng(2)
    ex = ServeExecutor(reg, autostart=False, batch_window=0.0,
                       max_dispatch_restarts=0,
                       fault_plan=FaultPlan(script=script))
    futs = [ex.submit(sig, _values(reg, sig, rng)) for _ in range(4)]
    if script.startswith("loop"):
        ex.start()
    else:
        ex._drain_once()
    failed = 0
    for f in futs:
        try:
            f.result(timeout=30)
        except Exception:
            failed += 1
    ex.close()
    assert obs.GLOBAL_TRACER.open_count() == 0, \
        f"{script}: unclosed {obs.GLOBAL_TRACER.open_names()}"
    roots = [s for s in _spans() if s.name == "serve.request"]
    assert len(roots) == 4
    error_roots = [r for r in roots if r.status == "error"]
    assert len(error_roots) == failed
    for r in error_roots:
        assert r.error, "failed request's root span lost its error"


def test_device_scoped_fault_zero_unclosed():
    pool = jax.devices()
    if len(pool) < 2:
        pytest.skip("needs a multi-device pool")
    reg, sig = _registry()
    rng = np.random.default_rng(3)
    ex = ServeExecutor(reg, autostart=False, devices=pool[:2],
                       quarantine_after=1, quarantine_backoff=30.0,
                       fault_plan=FaultPlan(script="device0@*"))
    for i in range(6):
        f = ex.submit(sig, _values(reg, sig, rng))
        ex._drain_once()
        f.result(timeout=30)  # pool keeps serving around the sick dev
    ex.close()
    assert obs.GLOBAL_TRACER.open_count() == 0
    instants = [e for e in obs.GLOBAL_TRACER.events()
                if isinstance(e, dict) and e.get("type") == "instant"]
    assert any(e["name"] == "serve.quarantine" for e in instants)
    assert any(e["name"] == "serve.retry" for e in instants)


def test_failed_paths_close_spans_with_typed_errors():
    """Deadline expiry, queue-full rejection and no-drain close all
    settle their request traces with the right error name."""
    reg, sig = _registry()
    rng = np.random.default_rng(4)
    ex = ServeExecutor(reg, autostart=False, batch_window=0.0,
                       max_queue=2)
    v = _values(reg, sig, rng)
    ex.submit(sig, v, timeout=-1.0)  # already expired
    ex.submit(sig, v)
    with pytest.raises(Exception) as exc_info:
        ex.submit(sig, v)  # queue full (expired was purged, live fills)
        ex.submit(sig, v)
        ex.submit(sig, v)
    ex.close(drain=False)
    assert obs.GLOBAL_TRACER.open_count() == 0, \
        obs.GLOBAL_TRACER.open_names()
    roots = [s for s in _spans() if s.name == "serve.request"]
    errors = {r.error for r in roots if r.status == "error"}
    assert errors  # every unresolved request closed typed
    assert errors <= {"DeadlineExpiredError", "QueueFullError",
                      "ServeError"}
    assert exc_info is not None


def test_fuzz_trace_ids_unique_and_nothing_leaks():
    """8 submitter threads, mixed priorities, live dispatcher: trace ids
    unique, parent links valid, zero open spans after close."""
    reg, sig = _registry()
    N_THREADS, PER = 8, 6
    ex = ServeExecutor(reg, batch_window=0.0005)
    results = [[] for _ in range(N_THREADS)]

    def submitter(i):
        rng = np.random.default_rng(100 + i)
        for k in range(PER):
            pr = "high" if (i + k) % 3 == 0 else "normal"
            results[i].append(
                ex.submit(sig, _values(reg, sig, rng), priority=pr))

    threads = [threading.Thread(target=submitter, args=(i,))
               for i in range(N_THREADS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for lane in results:
        for f in lane:
            f.result(timeout=60)
    ex.close()
    assert obs.GLOBAL_TRACER.open_count() == 0, \
        obs.GLOBAL_TRACER.open_names()
    spans = _spans()
    roots = [s for s in spans if s.name == "serve.request"]
    assert len(roots) == N_THREADS * PER
    ids = [r.trace_id for r in roots]
    assert len(set(ids)) == len(ids), "trace ids not unique"
    by_id = {s.span_id: s for s in spans}
    for s in spans:
        if s.parent_id is not None:
            parent = by_id[s.parent_id]
            assert parent.trace_id == s.trace_id
            assert s.t0 >= parent.t0 - 1e-6
    # both priority lanes produced tracks
    tracks = {s.track for s in roots}
    assert "lane:high" in tracks and "lane:normal" in tracks


def test_sample_rate_zero_traces_nothing():
    obs.GLOBAL_TRACER.set_sample_rate(0.0)
    reg, sig = _registry()
    rng = np.random.default_rng(5)
    ex = ServeExecutor(reg, autostart=False, batch_window=0.0)
    futs = [ex.submit(sig, _values(reg, sig, rng)) for _ in range(3)]
    ex._drain_once()
    for f in futs:
        f.result(timeout=30)
    ex.close()
    assert not [s for s in _spans() if s.name.startswith("serve.")]
    assert obs.GLOBAL_TRACER.open_count() == 0


def test_disabled_tracing_records_nothing():
    obs.disable()
    obs.GLOBAL_TRACER.reset()
    reg, sig = _registry()
    rng = np.random.default_rng(6)
    ex = ServeExecutor(reg, autostart=False, batch_window=0.0)
    futs = [ex.submit(sig, _values(reg, sig, rng)) for _ in range(3)]
    ex._drain_once()
    for f in futs:
        f.result(timeout=30)
    ex.close()
    assert obs.GLOBAL_TRACER.events() == []
    assert obs.GLOBAL_TRACER.open_count() == 0


def test_distributed_plan_records_exchange_metrics():
    """Building a chunked distributed plan surfaces the exact per-chunk
    wire accounting as counters + exchange-track events."""
    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices")
    from spfft_tpu.parallel import make_distributed_plan, make_mesh
    from spfft_tpu.utils.workloads import (even_plane_split,
                                           round_robin_stick_partition)
    n = 12
    rng = np.random.default_rng(8)
    tr = random_sparse_triplets(rng, (n, n, n))
    parts = round_robin_stick_partition(tr, (n, n, n), 2)
    planes = even_plane_split(n, 2)
    plan = make_distributed_plan(TransformType.C2C, n, n, n, parts,
                                 planes, mesh=make_mesh(2),
                                 overlap_chunks=2)
    labels = {"exchange": plan.exchange.value, "shards": "2",
              "chunks": str(plan.overlap_chunks)}
    assert obs.GLOBAL_COUNTERS.get("spfft_exchange_plans_total",
                                   **labels) >= 1
    assert obs.GLOBAL_COUNTERS.get("spfft_exchange_wire_bytes",
                                   **labels) \
        == plan.exchange_wire_bytes()
    ev = [e for e in obs.GLOBAL_TRACER.events()
          if isinstance(e, obs.Span) and e.name == "exchange.plan_build"]
    assert ev, "exchange.plan_build span missing"
    per_chunk = ev[-1].args.get("per_chunk")
    if plan.overlap_chunks > 1:
        assert per_chunk and len(per_chunk) == plan.overlap_chunks
        # per-chunk accounting is EXACT elements; it sums to the
        # schedule's own exact total (the padded block layout's
        # exchange_wire_bytes() may charge more — that's the point of
        # surfacing both)
        total = sum(c["bwd_bytes"] for c in per_chunk)
        exact = (plan._overlap.wire_elements()
                 * plan._wire_elem_bytes())
        assert total == exact <= plan.exchange_wire_bytes()

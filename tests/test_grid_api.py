"""Grid/Transform public API tests (reference: grid.hpp, transform.hpp,
multi_transform.hpp; multi-transform behavior mirrors
tests/mpi_tests/test_multi_transform.cpp)."""

import numpy as np
import pytest

from spfft_tpu import (Grid, InvalidParameterError, ProcessingUnit, Scaling,
                       TransformType, make_mesh, multi_transform_backward,
                       multi_transform_forward)
from spfft_tpu.utils import as_complex_np

from test_util import (dense_backward, dense_cube_from_values,
                       random_sparse_triplets, random_values, sample_cube)


def test_local_grid_example_flow():
    """The reference examples/example.cpp flow: dense 2x2x2 C2C."""
    dims = (2, 2, 2)
    triplets = np.array([(x, y, z) for x in range(2) for y in range(2)
                         for z in range(2)], np.int32)
    values = np.arange(8) * (1.0 - 1.0j)

    grid = Grid(2, 2, 2, 4, ProcessingUnit.HOST, precision="double")
    t = grid.create_transform(ProcessingUnit.HOST, TransformType.C2C,
                              2, 2, 2, 2, 8, indices=triplets)
    assert t.local_slice_size() == 8
    assert t.global_size == 8
    assert t.num_local_elements() == 8

    space = t.backward(values)
    assert t.space_domain_data() is space
    cube = dense_cube_from_values(triplets, values, dims)
    np.testing.assert_allclose(as_complex_np(np.asarray(space)),
                               dense_backward(cube), atol=1e-12)

    # forward consumes the stored space-domain data (example.cpp:79-81)
    out = as_complex_np(np.asarray(t.forward()))
    np.testing.assert_allclose(out, values * 8, atol=1e-12)


def test_flat_interleaved_indices():
    """C-API style flat x1,y1,z1,x2,y2,z2 index array (grid.h)."""
    grid = Grid(4, 4, 4, 16, precision="double")
    flat = np.array([0, 0, 0, 1, 2, 3])
    t = grid.create_transform(ProcessingUnit.HOST, TransformType.C2C,
                              4, 4, 4, indices=flat)
    assert t.num_local_elements() == 2


def test_grid_limits_enforced():
    # reference: transform_internal.cpp:52-83
    grid = Grid(4, 4, 4, 1, precision="double")
    with pytest.raises(InvalidParameterError):
        grid.create_transform(ProcessingUnit.HOST, TransformType.C2C,
                              8, 4, 4, indices=np.array([[0, 0, 0]]))
    with pytest.raises(InvalidParameterError):
        # two sticks > max_num_local_z_sticks == 1
        grid.create_transform(ProcessingUnit.HOST, TransformType.C2C,
                              4, 4, 4, indices=np.array([[0, 0, 0],
                                                         [1, 1, 0]]))


def test_forward_without_space_data_raises():
    grid = Grid(4, 4, 4, 16, precision="double")
    t = grid.create_transform(ProcessingUnit.HOST, TransformType.C2C,
                              4, 4, 4, indices=np.array([[0, 0, 0]]))
    with pytest.raises(InvalidParameterError):
        t.forward()


def test_distributed_grid():
    dims = (8, 8, 8)
    rng = np.random.default_rng(2)
    triplets = random_sparse_triplets(rng, dims)
    values = random_values(rng, len(triplets))
    cube = dense_cube_from_values(triplets, values, dims)

    # round-robin sticks over 2 shards
    keys = triplets[:, 0].astype(np.int64) * 8 + triplets[:, 1]
    uk = np.unique(keys)
    own = {k: i % 2 for i, k in enumerate(uk.tolist())}
    parts = [triplets[np.array([own[k] == r for k in keys])] for r in range(2)]

    grid = Grid(8, 8, 8, 64, mesh=make_mesh(2), precision="double")
    t = grid.create_transform(ProcessingUnit.DEVICE, TransformType.C2C,
                              8, 8, 8, triplets_per_shard=parts,
                              planes_per_shard=[4, 4])
    assert t.distributed
    assert t.local_z_offset(1) == 4
    vparts = [sample_cube(cube, p, dims) for p in parts]
    space = t.backward(vparts)
    got = np.concatenate(t.plan.unshard_space(space), axis=0)
    np.testing.assert_allclose(got, dense_backward(cube), atol=1e-10)


def test_multi_transform():
    """Three cloned transforms, constant values each, batched backward +
    forward, exact check (reference: test_multi_transform.cpp)."""
    dims = (6, 6, 6)
    triplets = np.asarray([(x, y, z) for x in range(6) for y in range(6)
                           for z in range(6)], np.int32)
    grid = Grid(6, 6, 6, 36, precision="double")
    base = grid.create_transform(ProcessingUnit.HOST, TransformType.C2C,
                                 6, 6, 6, indices=triplets)
    transforms = [base.clone() for _ in range(3)]
    batches = [np.full(len(triplets), complex(k + 1, -(k + 1)))
               for k in range(3)]

    spaces = multi_transform_backward(transforms, batches)
    outs = multi_transform_forward(transforms,
                                   scalings=[Scaling.FULL] * 3)
    for k in range(3):
        got = as_complex_np(np.asarray(outs[k]))
        np.testing.assert_allclose(got, batches[k], atol=1e-12)
        assert transforms[k].space_domain_data() is spaces[k]

    with pytest.raises(InvalidParameterError):
        multi_transform_backward(transforms, batches[:2])


def test_python_examples_run():
    """The shipped Python examples execute end-to-end (on the test CPU
    platform; the C example is exercised by test_capi.py)."""
    import os
    import subprocess
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    for name in ("example.py", "example_distributed.py", "example_scf.py",
                 "example_multihost.py",
                 "example_poisson.py"):
        out = subprocess.run(
            [sys.executable, os.path.join(repo, "examples", name)],
            env=env, capture_output=True, text=True, timeout=300)
        assert out.returncode == 0, f"{name}: {out.stderr[-2000:]}"


def test_transform_property_getters():
    """The reference transform.hpp:91-171 getter surface on both plan
    kinds."""
    import numpy as np
    from spfft_tpu import (ExchangeType, ProcessingUnit, TransformType,
                           make_local_plan)
    from spfft_tpu.grid import Transform
    from spfft_tpu.parallel import make_distributed_plan, make_mesh

    trip = np.array([[0, 0, 0], [1, 2, 3]])
    local = Transform(make_local_plan(TransformType.C2C, 4, 4, 4, trip,
                                      precision="double"))
    assert local.processing_unit == ProcessingUnit.DEVICE
    assert local.precision == "double"
    assert local.exchange_type == ExchangeType.DEFAULT
    assert local.num_shards == 1

    parts = [trip[:1], trip[1:], trip[:0], trip[:0]]
    dist = Transform(make_distributed_plan(
        TransformType.C2C, 4, 4, 4, parts, [1, 1, 1, 1],
        mesh=make_mesh(4), precision="double",
        exchange=ExchangeType.UNBUFFERED))
    assert dist.processing_unit == ProcessingUnit.DEVICE
    assert dist.precision == "double"
    assert dist.exchange_type == ExchangeType.UNBUFFERED
    assert dist.num_shards == 4
    assert isinstance(dist.device_id, int)
    assert dist.num_threads == 4
    assert local.num_threads == 1


def test_space_domain_data_location():
    import numpy as np
    from spfft_tpu import ProcessingUnit, TransformType, make_local_plan
    from spfft_tpu.grid import Transform

    trip = np.array([[0, 0, 0], [1, 2, 3]])
    t = Transform(make_local_plan(TransformType.C2C, 4, 4, 4, trip,
                                  precision="double"))
    assert t.space_domain_data() is None
    t.backward(np.array([1 + 1j, 2 - 1j]))
    host = t.space_domain_data(ProcessingUnit.HOST)
    assert isinstance(host, np.ndarray)
    np.testing.assert_array_equal(
        host, np.asarray(t.space_domain_data(ProcessingUnit.DEVICE)))


def test_space_domain_host_snapshot_is_readonly():
    """Ported reference code that writes into space_domain_data(HOST) must
    fail loudly, not silently no-op (the reference buffer is writable;
    VERDICT r2 missing item 5)."""
    n = 4
    trip = np.array([[x, y, z] for x in range(n) for y in range(n)
                     for z in range(n)], np.int32)
    grid = Grid(n, n, n, n * n)
    t = grid.create_transform(ProcessingUnit.DEVICE, TransformType.C2C,
                              n, n, n, indices=trip)
    vals = np.ones(len(trip), np.complex64)
    t.backward(vals)
    snap = t.space_domain_data(ProcessingUnit.HOST)
    with pytest.raises(ValueError):
        snap[0, 0, 0, 0] = 7.0
    # the documented mutation route still works
    writable = snap.copy()
    writable[0, 0, 0, 0] = 7.0
    t.set_space_domain_data(writable)


def test_space_domain_host_snapshot_does_not_alias_numpy_store():
    """A numpy array passed to set_space_domain_data must not share memory
    with the HOST snapshot (the snapshot promise; review r3)."""
    n = 4
    trip = np.array([[x, y, z] for x in range(n) for y in range(n)
                     for z in range(n)], np.int32)
    grid = Grid(n, n, n, n * n)
    t = grid.create_transform(ProcessingUnit.DEVICE, TransformType.C2C,
                              n, n, n, indices=trip)
    a = np.zeros((n, n, n, 2), np.float32)
    t.set_space_domain_data(a)
    snap = t.space_domain_data(ProcessingUnit.HOST)
    a[0, 0, 0, 0] = 7.0
    assert snap[0, 0, 0, 0] == 0.0  # true snapshot, no aliasing
    assert a.flags.writeable  # the caller's array is untouched


def test_grid_copy_is_independent():
    """Grid deep-copy parity (reference grid_internal.cpp:232-262): the
    copy carries the same limits, works through copy.copy/deepcopy, and
    transforms made from original and copy are fully isolated."""
    import copy as copy_mod

    n = 6
    trip = np.array([[x, y, z] for x in range(2) for y in range(2)
                     for z in range(n)], np.int32)
    grid = Grid(n, n, n, 4)
    for dup in (grid.copy(), copy_mod.copy(grid),
                copy_mod.deepcopy(grid)):
        assert dup is not grid
        assert dup.max_dim_x == grid.max_dim_x
        assert dup.max_dim_y == grid.max_dim_y
        assert dup.max_dim_z == grid.max_dim_z
        assert dup.max_num_local_z_columns == grid.max_num_local_z_columns
        assert dup.processing_unit == grid.processing_unit
        assert dup.distributed == grid.distributed
    dup = grid.copy()
    ta = grid.create_transform(ProcessingUnit.DEVICE, TransformType.C2C,
                               n, n, n, indices=trip)
    tb = dup.create_transform(ProcessingUnit.DEVICE, TransformType.C2C,
                              n, n, n, indices=trip)
    vals = np.arange(len(trip)).astype(np.complex64)
    np.testing.assert_allclose(np.asarray(ta.backward(vals)),
                               np.asarray(tb.backward(vals)),
                               atol=0, rtol=0)

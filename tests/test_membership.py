"""Self-healing pod membership (net/membership.py + the round-21
integration): the tier-1 twin of the pod smoke's self-healing phase.

The contracts under test (docs/cluster.md "Membership & liveness"):
leases renew via heartbeats and walk the suspected -> probed ->
evicted expiry ladder with an epoch bump per transition; epoch
fencing rejects stale work with the typed transient
``StaleEpochError`` and recovers on a view refetch; the coordinator
election is a deterministic pure function (lowest alive host id) and
a dead coordinator's heartbeat targets converge on the same
successor; views are signed and a tampered view is the permanent
``NetAuthError``; the frontend's resurrection ladder re-reconciles a
probed lane before readmission (a diverged plan set is BLOCKED, not
silently readmitted); frame auth (version-2 HMAC) round-trips and
every mismatch is typed; TCP connects retry with a counted backoff;
and the blob tier's ``req/`` journal GC sweeps oldest-first on both
backends. A two-frontend fuzz over a shared coordinator stays
bit-exact through kill/readmit churn with zero unclosed spans.
"""

import os
import socket
import threading
import time

import numpy as np
import pytest

from spfft_tpu import faults, obs
from spfft_tpu.benchmark import cutoff_stick_triplets
from spfft_tpu.control.config import global_config
from spfft_tpu.errors import (BlobStoreError, HostLaneError,
                              NetAuthError, StaleEpochError)
from spfft_tpu.faults import FaultPlan, InjectedFault
from spfft_tpu.net.agent import HostAgent
from spfft_tpu.net.blobstore import (FileBlobStore, gc_blobstore,
                                     serve_blobstore)
from spfft_tpu.net.frame import recv_frame, send_frame
from spfft_tpu.net.membership import (ALIVE, EVICTED, PROBED,
                                      SUSPECTED, MembershipNode,
                                      MembershipView, ViewCoordinator,
                                      elect_coordinator)
from spfft_tpu.net.transport import TcpHostLane
from spfft_tpu.serve.cluster import HostLane, PodFrontend
from spfft_tpu.serve.executor import ServeExecutor
from spfft_tpu.serve.registry import PlanRegistry
from spfft_tpu.types import TransformType

N = 8
DIMS = (N, N, N)
#: lease TTL every fake-clock test pins (never the live knob)
TTL = 2.0


@pytest.fixture(scope="module")
def mem_plans():
    """Two distinct single-device plans: the pod's serving plan plus a
    second signature the readmission-mismatch test withholds."""
    trip = cutoff_stick_triplets(N, N, N, 0.9, hermitian=False)
    reg = PlanRegistry()
    sig, plan = reg.get_or_build(TransformType.C2C, *DIMS, trip,
                                 precision="double")
    trip2 = cutoff_stick_triplets(N, N, N, 0.6, hermitian=False)
    sig2, plan2 = reg.get_or_build(TransformType.C2C, *DIMS, trip2,
                                   precision="double")
    return {"trip": trip, "sig": sig, "plan": plan,
            "sig2": sig2, "plan2": plan2}


def _values(p, rng):
    n = len(p["trip"])
    return rng.standard_normal(n) + 1j * rng.standard_normal(n)


# -- leases + expiry ladder ---------------------------------------------------
def test_lease_renewal_holds_and_expiry_walks_ladder():
    now = [0.0]
    vc = ViewCoordinator("c0", clock=lambda: now[0], lease_ttl_s=TTL,
                         secret=None)
    vc.heartbeat("a1", "127.0.0.1:1")
    e0 = vc.epoch
    # renewals inside the TTL keep the lease alive forever
    for _ in range(5):
        now[0] += 0.9 * TTL
        vc.heartbeat("a1")
        assert not vc.expire()
    assert vc.view().states()["a1"] == ALIVE
    # stop renewing: one scan per rung, each with its own epoch bump
    last = now[0]
    now[0] = last + 1.2 * TTL
    assert vc.expire() == [("a1", ALIVE, SUSPECTED)]
    now[0] = last + 1.8 * TTL
    assert vc.expire() == [("a1", SUSPECTED, PROBED)]
    now[0] = last + 2.8 * TTL
    assert vc.expire() == [("a1", PROBED, EVICTED)]
    assert vc.epoch == e0 + 3
    # the tombstone stays visible, and expiry never resurrects it
    assert vc.view().states()["a1"] == EVICTED
    assert not vc.expire()
    # a heartbeat from the evicted host readmits it with a bump
    ack = vc.heartbeat("a1")
    assert vc.view().states()["a1"] == ALIVE
    assert ack["epoch"] == vc.epoch == e0 + 4


def test_expiry_skips_rungs_for_a_long_dead_lease():
    now = [0.0]
    vc = ViewCoordinator("c0", clock=lambda: now[0], lease_ttl_s=TTL,
                         secret=None)
    vc.heartbeat("a1")
    now[0] = 10 * TTL  # way past EVICT_AFTER in a single scan
    assert vc.expire() == [("a1", ALIVE, EVICTED)]


def test_static_ensured_members_hold_no_lease_and_never_expire():
    """A loopback/frontend-embedded lane registered via ``ensure``
    has nothing heartbeating it: it must be exempt from lease expiry
    (views served long after init still say ALIVE), while a first
    heartbeat converts it to a normal leased member."""
    now = [0.0]
    vc = ViewCoordinator("c0", clock=lambda: now[0], lease_ttl_s=TTL,
                         secret=None)
    vc.ensure("h1", "127.0.0.1:1")
    e0 = vc.epoch
    now[0] = 100 * TTL  # far past every ladder rung
    assert vc.expire() == []
    assert vc.view().states()["h1"] == ALIVE
    assert vc.epoch == e0  # no phantom transitions, no epoch churn
    # explicit evict/readmit still work, and readmission does NOT
    # start a lease nothing will renew
    vc.evict("h1")
    vc.readmit("h1")
    now[0] = 200 * TTL
    assert vc.expire() == []
    assert vc.view().states()["h1"] == ALIVE
    # the first real heartbeat leases it: now expiry applies
    vc.heartbeat("h1")
    now[0] += 10 * TTL
    assert vc.expire() == [("h1", ALIVE, EVICTED)]


def test_heartbeat_fault_injection_is_typed_and_contained():
    vc = ViewCoordinator("c0", lease_ttl_s=TTL, secret=None)
    faults.arm(FaultPlan(script=["net.heartbeat@1"]))
    try:
        with pytest.raises(InjectedFault):
            vc.heartbeat("a1")
        ack = vc.heartbeat("a1")  # fault spent: renewal recovers
        assert ack["coordinator"] == "c0"
    finally:
        faults.disarm()


# -- epoch fencing ------------------------------------------------------------
def test_epoch_fencing_stale_typed_then_current_passes():
    vc = ViewCoordinator("c0", lease_ttl_s=TTL, secret=None)
    vc.heartbeat("a1")
    vc.evict("a1")
    current = vc.epoch
    before = obs.GLOBAL_COUNTERS.get("spfft_cluster_stale_epoch_total",
                                     node="c0")
    with pytest.raises(StaleEpochError) as ei:
        vc.check_epoch(current - 1)
    assert ei.value.stale == current - 1
    assert ei.value.current == current
    assert obs.GLOBAL_COUNTERS.get("spfft_cluster_stale_epoch_total",
                                   node="c0") == before + 1
    # the recovery path: refetch the view, retry with its epoch
    vc.check_epoch(vc.view().epoch)
    vc.check_epoch(None)  # unstamped work always passes
    vc.check_epoch(current + 5)  # ahead-of-view is not stale


# -- election -----------------------------------------------------------------
def test_elect_coordinator_is_pure_lowest_alive():
    assert elect_coordinator(
        {"h2": ALIVE, "h0": EVICTED, "h1": ALIVE}) == "h1"
    assert elect_coordinator({"h0": EVICTED}) is None
    assert elect_coordinator({}) is None


def test_coordinator_death_reelects_deterministically():
    """m0 dies; m1 (next-lowest) promotes itself after the failure
    streak, m2 independently re-elects the SAME winner, and the
    promoted coordinator's epoch moves past the dead one's."""
    now = [0.0]
    nodes, down = {}, set()

    def wire(addr, hdr):
        if addr in down:
            raise OSError(f"{addr} unreachable")
        return nodes[addr].on_heartbeat(str(hdr["host"]),
                                        hdr.get("address"))

    roster = {h: h for h in ("m0", "m1", "m2")}
    for h in roster:
        peers = {p: a for p, a in roster.items() if p != h}
        nodes[h] = MembershipNode(h, address=h, peers=peers,
                                  clock=lambda: now[0], secret=None)
    assert nodes["m0"].is_coordinator
    for h in ("m1", "m2"):
        assert nodes[h].tick(wire) == "ok"
    for h in ("m1", "m2"):
        nodes[h].adopt(nodes["m0"].on_view())
    pre = nodes["m0"].epoch
    down.add("m0")
    outcomes = [nodes["m1"].tick(wire) for _ in range(3)]
    assert outcomes == ["failed", "failed", "promoted"]
    assert nodes["m1"].is_coordinator
    assert nodes["m1"].epoch > pre
    outcomes = [nodes["m2"].tick(wire) for _ in range(4)]
    assert "re-elected" in outcomes and outcomes[-1] == "ok"
    assert not nodes["m2"].is_coordinator
    assert nodes["m2"].coordinator()[0] == "m1"
    nodes["m2"].adopt(nodes["m1"].on_view())
    assert nodes["m2"].epoch == nodes["m1"].epoch


def test_heartbeat_ack_carries_view_and_followers_adopt_it():
    """The renewal ack rides the coordinator's full signed view and
    ``tick`` adopts it — the production flow (nothing else calls
    ``adopt``) must leave followers holding real per-host states, or a
    coordinator death degenerates into every follower self-electing."""
    coord = MembershipNode("a0", address="a0", secret=None)
    nodes = {"a0": coord}

    def wire(addr, hdr):
        return nodes[addr].on_heartbeat(str(hdr["host"]),
                                        hdr.get("address"))

    f1 = MembershipNode("a1", address="a1", peers={"a0": "a0"},
                        secret=None)
    f2 = MembershipNode("a2", address="a2", peers={"a0": "a0"},
                        secret=None)
    assert f1.tick(wire) == "ok" and f2.tick(wire) == "ok"
    assert f1.tick(wire) == "ok"  # a1 re-renews: sees a2 in the view
    for node in (f1, f2):
        assert node._view is not None
        assert node._view.verify(None)  # adopted verbatim, signature ok
    assert f1._view.states() == {"a0": ALIVE, "a1": ALIVE, "a2": ALIVE}
    assert f1.epoch == coord.epoch


def test_follower_served_view_stays_verifiable_through_failover():
    """Locally suspecting a dead coordinator must NOT mutate the
    adopted signed view in place: ``on_view`` keeps serving a view
    whose signature verifies (the pre-fix bug re-served mutated
    members under the original signature — a permanent NetAuthError
    for every verifier mid-failover)."""
    nodes, down = {}, set()

    def wire(addr, hdr):
        if addr in down:
            raise OSError(f"{addr} unreachable")
        return nodes[addr].on_heartbeat(str(hdr["host"]),
                                        hdr.get("address"))

    roster = {h: h for h in ("m0", "m1", "m2")}
    for h in roster:
        peers = {p: a for p, a in roster.items() if p != h}
        nodes[h] = MembershipNode(h, address=h, peers=peers,
                                  secret=None)
    for h in ("m1", "m2"):
        assert nodes[h].tick(wire) == "ok"
        assert nodes[h].tick(wire) == "ok"  # both see the full pod
    down.add("m0")
    outcomes = [nodes["m2"].tick(wire) for _ in range(3)]
    assert outcomes == ["failed", "failed", "re-elected"]
    # the cached view still verifies — suspicion is an overlay, never
    # a mutation — so any frontend/agent fetching it mid-failover
    # adopts it cleanly instead of dying on NetAuthError
    served = nodes["m2"].on_view()
    assert MembershipView.from_wire(served).verify(None)
    fresh = MembershipNode("m9", peers={"m2": "m2"}, secret=None)
    assert fresh.adopt(served)
    # and the election overlay targets the real successor
    assert nodes["m2"].coordinator()[0] == "m1"


def test_wire_coordinator_kill_exactly_one_node_promotes():
    """Over REAL TCP with three agents: kill the coordinator and the
    survivors — whose views arrived solely via heartbeat acks, the
    production flow — converge with EXACTLY ONE promotion (the
    next-lowest alive id). The pre-fix failure mode was every follower
    promoting simultaneously into permanent multi-coordinator
    split-brain."""
    cfg = global_config()
    old_hb = cfg.heartbeat_interval_ms
    cfg.set("heartbeat_interval_ms", 100, source="test",
            reason="fast convergence for coordinator-kill test")
    agents: dict = {}
    exs = []
    try:
        for name in ("n0", "n1", "n2"):
            reg = PlanRegistry(store=False)
            ex = ServeExecutor(reg)
            exs.append(ex)
            peers = {h: f"127.0.0.1:{a.port}"
                     for h, a in agents.items()}
            agents[name] = HostAgent(name, ex,
                                     peers=peers or None).start()
        assert agents["n0"].membership.is_coordinator
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            if all(agents[h].membership._view is not None
                   and len(agents[h].membership._view.members) == 3
                   for h in ("n1", "n2")):
                break
            time.sleep(0.05)
        else:
            pytest.fail("followers never adopted the full pod view "
                        "from heartbeat acks")
        pre = agents["n0"].membership.epoch
        agents["n0"].close()  # kill -9 equivalent: refused connects
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            if agents["n1"].membership.is_coordinator \
                    and agents["n2"].membership.coordinator()[0] == "n1":
                break
            time.sleep(0.05)
        else:
            pytest.fail("survivors never converged on a successor")
        promoted = [h for h in ("n1", "n2")
                    if agents[h].membership.is_coordinator]
        assert promoted == ["n1"]  # exactly one, the next-lowest id
        assert agents["n1"].membership.epoch > pre
        view = MembershipView.from_wire(agents["n1"].membership.on_view())
        assert view.coordinator == "n1"
        assert view.states()["n0"] != ALIVE  # the dead node is suspect
    finally:
        cfg.set("heartbeat_interval_ms", old_hb, source="test",
                reason="restore after coordinator-kill test")
        for agent in agents.values():
            try:
                agent.close()
            except Exception:  # noqa: BLE001 - teardown best effort
                pass
        for ex in exs:
            ex.close(drain=False)


# -- signed views -------------------------------------------------------------
def test_view_sign_verify_and_tamper_rejection():
    vc = ViewCoordinator("c0", lease_ttl_s=TTL, secret=b"pod-secret")
    vc.heartbeat("a1", "127.0.0.1:1")
    view = vc.view()
    assert view.verify(b"pod-secret")
    assert not view.verify(b"wrong-secret")
    assert not view.verify(None)  # plain digest != HMAC
    tampered = view.to_wire()
    tampered = {**tampered,
                "members": {h: dict(r)
                            for h, r in tampered["members"].items()}}
    tampered["members"]["a1"]["state"] = EVICTED
    assert not MembershipView.from_wire(tampered).verify(b"pod-secret")
    node = MembershipNode("a1", peers={"c0": "c0"}, secret=b"pod-secret")
    with pytest.raises(NetAuthError):
        node.adopt(tampered)
    assert node.adopt(view.to_wire())  # the untampered view lands


def test_unsigned_views_still_carry_integrity_digest():
    vc = ViewCoordinator("c0", lease_ttl_s=TTL, secret=None)
    view = vc.view()
    assert view.verify(None)
    wire = view.to_wire()
    wire["epoch"] = view.epoch + 7
    assert not MembershipView.from_wire(wire).verify(None)


# -- frontend integration: fencing + resurrection ladder ---------------------
def _shared_pod_pair(p, mm, seed=0):
    """Two loopback frontends over the SAME executors and the SAME
    coordinator — each with its own lane objects (transport belief is
    per-frontend, the view is shared)."""
    regs = []
    for _ in range(2):
        reg = PlanRegistry(store=False)
        reg.put(p["sig"], p["plan"])
        regs.append(reg)
    exs = [ServeExecutor(r) for r in regs]
    fa = PodFrontend([HostLane("h0", exs[0]), HostLane("h1", exs[1])],
                     membership=mm, seed=seed)
    fb = PodFrontend([HostLane("h0", exs[0]), HostLane("h1", exs[1])],
                     membership=mm, seed=seed + 1)
    return fa, fb, exs


def test_stale_frontend_fenced_typed_then_recovers(mem_plans):
    p = mem_plans
    rng = np.random.default_rng(3)
    mm = ViewCoordinator("h0", lease_ttl_s=TTL, secret=None)
    fa, fb, exs = _shared_pod_pair(p, mm)
    try:
        e0 = fa.epoch
        assert fb.epoch == e0
        fa._mark_dead(fa._lanes[1])
        assert fa.epoch > e0
        before = obs.GLOBAL_COUNTERS.get(
            "spfft_cluster_stale_epoch_total", node="frontend")
        v = _values(p, rng)
        got = np.asarray(fb.submit(p["sig"], v).result(timeout=60))
        assert np.array_equal(got, np.asarray(p["plan"].backward(v)))
        assert obs.GLOBAL_COUNTERS.get(
            "spfft_cluster_stale_epoch_total",
            node="frontend") == before + 1
        assert fb.epoch == fa.epoch
        assert fa.view()["members"]["h1"]["state"] == EVICTED
    finally:
        fa.close()
        fb.close()
        for ex in exs:
            ex.close()


def test_readmission_blocked_on_reconcile_mismatch(mem_plans):
    """The readmission gate: a resurrected lane whose plan set lost a
    signature the incumbent still serves is BLOCKED (typed, counted,
    backoff deferred) — and readmitted once the set converges."""
    p = mem_plans
    mm = ViewCoordinator("h0", lease_ttl_s=TTL, secret=None)
    fa, fb, exs = _shared_pod_pair(p, mm)
    try:
        # the incumbent learns a plan the dying lane never had
        exs[0].registry.put(p["sig2"], p["plan2"])
        lane = fa._lanes[1]
        fa._mark_dead(lane)
        lane.transport.alive = True  # the simulated host is back up
        out = fa.probe_dead(force=True)
        assert out == {"h1": "blocked"}
        assert obs.GLOBAL_COUNTERS.get("spfft_cluster_readmits_total",
                                       host="h1",
                                       outcome="blocked") >= 1
        assert fa.view()["members"]["h1"]["state"] == EVICTED
        # plan sets converge: the next probe readmits warm
        exs[1].registry.put(p["sig2"], p["plan2"])
        out = fa.probe_dead(force=True)
        assert out == {"h1": "readmitted"}
        assert fa.view()["members"]["h1"]["state"] == ALIVE
        assert fb.view()["epoch"] == fa.epoch
        assert not fa._on_ladder("h1")
    finally:
        fa.close()
        fb.close()
        for ex in exs:
            ex.close()


def test_probe_respects_backoff_and_dead_host(mem_plans):
    p = mem_plans
    mm = ViewCoordinator("h0", lease_ttl_s=TTL, secret=None)
    fa, fb, exs = _shared_pod_pair(p, mm)
    try:
        lane = fa._lanes[1]
        fa._mark_dead(lane)
        # not yet due: the ladder answers backoff without probing
        assert fa.probe_dead(force=False) == {"h1": "backoff"}
        # due but the host is still down (loopback flag respected):
        # the probe fails and the deadline backs off exponentially
        out = fa.probe_dead(force=True)
        assert out == {"h1": "failed"}
        with fa._dead_lock:
            attempts, deadline = fa._dead["h1"]
        assert attempts == 1 and deadline > time.monotonic()
    finally:
        fa.close()
        fb.close()
        for ex in exs:
            ex.close()


def test_routing_schedules_probes_in_background(mem_plans):
    """The routing path only SCHEDULES a due probe — it must never
    block a live submit on the health RPC + readmission gate. A probe
    stalled inside the health call keeps the host on the ladder while
    submits keep serving from survivors; releasing it readmits with no
    further routing involvement."""
    p = mem_plans
    rng = np.random.default_rng(9)
    mm = ViewCoordinator("h0", lease_ttl_s=TTL, secret=None)
    fa, fb, exs = _shared_pod_pair(p, mm)
    entered = threading.Event()
    release = threading.Event()
    try:
        lane = fa._lanes[1]
        orig_health = lane.rpc_health

        def stalled_health():
            entered.set()
            release.wait(30)
            return orig_health()

        lane.rpc_health = stalled_health
        fa._mark_dead(lane)
        lane.transport.alive = True  # the host is back up
        with fa._dead_lock:
            fa._dead["h1"][1] = 0.0  # the probe is due NOW
        # this submit notices the due probe; it must return a served
        # result while the probe is still stalled in the background
        v = _values(p, rng)
        got = np.asarray(fa.submit(p["sig"], v).result(timeout=60))
        assert np.array_equal(got, np.asarray(p["plan"].backward(v)))
        assert entered.wait(10), "probe was never scheduled"
        assert fa._on_ladder("h1")  # served while the probe ran
        # a synchronous walk reports the in-flight probe, not a second
        assert fa.probe_dead(force=True).get("h1") == "probing"
        release.set()
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and fa._on_ladder("h1"):
            time.sleep(0.02)
        assert not fa._on_ladder("h1")
        assert fa.view()["members"]["h1"]["state"] == ALIVE
    finally:
        release.set()
        fa.close()
        fb.close()
        for ex in exs:
            ex.close()


# -- frame auth ---------------------------------------------------------------
def test_frame_auth_round_trip_and_mismatches():
    secret = b"wire-secret"
    a, b = socket.socketpair()
    try:
        send_frame(a, {"type": "ping"}, b"payload", secret=secret)
        header, payload = recv_frame(b, secret=secret)
        assert header == {"type": "ping"} and payload == b"payload"
        # wrong secret
        send_frame(a, {"type": "ping"}, b"x", secret=secret)
        with pytest.raises(NetAuthError):
            recv_frame(b, secret=b"other-secret")
        # authenticated frame into a plaintext endpoint
        send_frame(a, {"type": "ping"}, secret=secret)
        with pytest.raises(NetAuthError):
            recv_frame(b, secret=None)
        # plaintext frame into an authenticated endpoint
        send_frame(a, {"type": "ping"}, secret=None)
        with pytest.raises(NetAuthError):
            recv_frame(b, secret=secret)
    finally:
        a.close()
        b.close()


# -- connect retry ------------------------------------------------------------
def test_tcp_connect_retries_are_counted():
    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    port = probe.getsockname()[1]
    probe.close()  # nothing listens here any more
    before = obs.GLOBAL_COUNTERS.get("spfft_net_rpc_retries_total",
                                     verb="health")
    lane = TcpHostLane("hx", ("127.0.0.1", port))
    try:
        with pytest.raises(HostLaneError):
            lane.rpc_health()
    finally:
        lane.close()
    assert obs.GLOBAL_COUNTERS.get("spfft_net_rpc_retries_total",
                                   verb="health") >= before + 2


def test_tcp_connect_timeout_fails_fast(monkeypatch):
    """A blackholed/unreachable host costs ONE connect timeout before
    the lane is declared dead — only refused/reset-class errors spend
    the retry budget, so failover starts within a single connect
    timeout, not three of them plus backoff."""
    import spfft_tpu.net.transport as transport_mod

    calls = []

    def timed_out(address, timeout=None):
        calls.append(address)
        raise socket.timeout("connect timed out")

    monkeypatch.setattr(transport_mod.socket, "create_connection",
                        timed_out)
    before = obs.GLOBAL_COUNTERS.get("spfft_net_rpc_retries_total",
                                     verb="health")
    lane = TcpHostLane("hx", ("10.255.255.1", 9))
    try:
        with pytest.raises(HostLaneError):
            lane.rpc_health()
    finally:
        lane.close()
    assert len(calls) == 1  # no retry loop on a timing-out connect
    assert obs.GLOBAL_COUNTERS.get("spfft_net_rpc_retries_total",
                                   verb="health") == before


# -- blob journal GC ----------------------------------------------------------
def _seed_journal(store):
    now = time.time()
    for i, key in enumerate(("req/old", "req/mid", "req/new")):
        store.put(key, bytes(100))
    return now


def test_blob_gc_file_sweeps_oldest_first(tmp_path):
    store = FileBlobStore(str(tmp_path))
    _seed_journal(store)
    store.put("cfg/keep", bytes(100))  # other namespaces untouched
    base = time.time()
    for i, key in enumerate(("req/old", "req/mid", "req/new")):
        os.utime(os.path.join(str(tmp_path), key),
                 (base + i, base + i))
    out = gc_blobstore(store, max_bytes=150)
    assert out["removed"] == ["req/old", "req/mid"]
    assert out["bytes_in_use"] == 100 and out["errors"] == 0
    assert store.get("req/new") is not None
    assert store.get("cfg/keep") is not None
    # unbounded: nothing swept
    assert gc_blobstore(store, max_bytes=0)["removed"] == []


def test_blob_gc_http_stat_delete_and_sweep(tmp_path):
    server, thread = serve_blobstore(str(tmp_path))
    try:
        from spfft_tpu.net.blobstore import HttpBlobStore
        store = HttpBlobStore(
            f"http://127.0.0.1:{server.server_address[1]}")
        _seed_journal(store)
        st = store.stat("req/old")
        assert st is not None and st["size"] == 100
        assert store.stat("req/ghost") is None
        base = time.time()
        for i, key in enumerate(("req/old", "req/mid", "req/new")):
            os.utime(os.path.join(str(tmp_path), key),
                     (base + i, base + i))
        out = gc_blobstore(store, max_bytes=100)
        assert out["removed"] == ["req/old", "req/mid"]
        assert out["bytes_in_use"] == 100
        assert store.delete("req/new") is True
        assert store.delete("req/new") is False
    finally:
        server.shutdown()
        thread.join(timeout=10)


def test_blob_gc_per_key_failures_are_nonfatal(tmp_path):
    class FlakyStore(FileBlobStore):
        def stat(self, key):
            if key == "req/mid":
                raise BlobStoreError("injected stat failure")
            return super().stat(key)

    store = FlakyStore(str(tmp_path))
    _seed_journal(store)
    out = gc_blobstore(store, max_bytes=0x0)
    assert out["removed"] == []  # unbounded short-circuits first
    out = gc_blobstore(store, max_bytes=1)
    assert out["errors"] == 1  # the flaky key is skipped, not fatal
    assert "req/mid" not in out["removed"]
    assert len(out["removed"]) == 2


# -- two-frontend convergence fuzz -------------------------------------------
def test_two_frontend_convergence_fuzz(mem_plans):
    """8 threads hammer two frontends over a shared coordinator while
    the main thread churns h1 through kill -> probe -> readmit. Every
    request stays bit-exact (the fence refetches internally), the
    frontends converge on one epoch, and no span leaks."""
    p = mem_plans
    obs.enable()
    tracer = obs.GLOBAL_TRACER
    tracer.reset()
    tracer.set_sample_rate(1.0)
    mm = ViewCoordinator("h0", lease_ttl_s=TTL, secret=None)
    fa, fb, exs = _shared_pod_pair(p, mm, seed=11)
    stop = threading.Event()
    errors: list = []

    def hammer(front, seed):
        rng = np.random.default_rng(seed)
        while not stop.is_set():
            v = _values(p, rng)
            try:
                got = np.asarray(
                    front.submit(p["sig"], v).result(timeout=60))
                if not np.array_equal(
                        got, np.asarray(p["plan"].backward(v))):
                    errors.append("diverged result")
            except Exception as exc:  # noqa: BLE001 - fuzz verdict
                errors.append(f"{type(exc).__name__}: {exc}")

    threads = [threading.Thread(target=hammer,
                                args=(front, 100 + i), daemon=True)
               for i, front in enumerate([fa, fb] * 4)]
    for t in threads:
        t.start()
    try:
        for _ in range(3):
            time.sleep(0.15)
            fa._mark_dead(fa._lanes[1])
            time.sleep(0.15)
            fa._lanes[1].transport.alive = True
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                # either this walk readmits it, or a background probe
                # scheduled off a hammer submit already did
                if fa.probe_dead(force=True).get("h1") == "readmitted" \
                        or not fa._on_ladder("h1"):
                    break
                time.sleep(0.05)
            else:
                errors.append("churn round never readmitted h1")
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30)
        fa.close()
        fb.close()
        for ex in exs:
            ex.close()
    assert not errors, errors[:5]
    va, vb = fa.view(), fb.view()  # view() refreshes the stamp
    assert va["epoch"] == vb["epoch"] == mm.epoch
    assert fa.epoch == fb.epoch == mm.epoch
    assert va["members"]["h1"]["state"] == ALIVE
    assert tracer.open_count() == 0

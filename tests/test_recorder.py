"""Black-box flight recorder (spfft_tpu/obs/recorder.py): the tier-1
twin of chaos phase G.

The contracts under test (docs/observability.md "Flight recorder &
incidents"):

* the structured event journal records DECLARED kinds with their
  declared attrs, drops undeclared kinds/attrs counted-not-raised,
  and stays bounded (ring capacity, dropped counter);
* tail-based retention promotes errored / explicitly-flagged /
  p99-slow traces into the retained ring with head sampling OFF
  (enabling the recorder forces span recording so there is a tail);
* incident bundles are versioned, self-contained, atomically written
  (a faulted write leaves NO torn file), GC'd to ``keep``, and
  round-trip the schema validator; pod bundles merge host bundles
  into one host-labelled timestamp-ordered timeline and tolerate
  unreachable-host error stubs;
* the deterministic full loop: with head-sampling off, a seeded fault
  storm on a live 2-host pod (loopback + real TCP agent) auto-captures
  a pod bundle holding the errored request's tail-retained trace (one
  trace id across the socket), the fault-site firing, the lane-death
  and controller events in timestamp order — zero torn files, zero
  unclosed spans;
* ``/incidentz`` on the MetricsServer and ``python -m spfft_tpu.obs
  incident`` surface capture + validation;
* the recorder-DISARMED hot path stays within its <= 1% budget
  (``overhead_probe``'s off leg is a module-global read per
  checkpoint).
"""

import json
import os
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from spfft_tpu import faults, obs
from spfft_tpu.benchmark import cutoff_stick_triplets
from spfft_tpu.control.config import global_config
from spfft_tpu.errors import GenericError
from spfft_tpu.faults import FaultPlan
from spfft_tpu.net.agent import HostAgent
from spfft_tpu.net.transport import TcpHostLane
from spfft_tpu.obs import recorder
from spfft_tpu.obs.http import MetricsServer
from spfft_tpu.obs.recorder import EventJournal
from spfft_tpu.obs.trace import RequestTrace
from spfft_tpu.serve.cluster import PodFrontend
from spfft_tpu.serve.executor import ServeExecutor
from spfft_tpu.serve.metrics import ServeMetrics
from spfft_tpu.serve.registry import PlanRegistry
from spfft_tpu.types import TransformType

N = 8
DIMS = (N, N, N)


@pytest.fixture(autouse=True)
def recorder_isolation():
    """Every test starts and ends with the recorder disarmed and the
    journal + rings empty (the journal is process-global and always
    on — other test files' events must not leak in)."""
    obs.disable_recorder()
    recorder.reset_recorder()
    yield
    faults.disarm()
    obs.disable_recorder()
    recorder.reset_recorder()
    obs.GLOBAL_TRACER.set_sample_rate(1.0)
    obs.disable()


@pytest.fixture(scope="module")
def plans():
    trip = cutoff_stick_triplets(N, N, N, 0.9, hermitian=False)
    reg = PlanRegistry()
    sig, plan = reg.get_or_build(TransformType.C2C, *DIMS, trip,
                                 precision="double")
    return {"trip": trip, "sig": sig, "plan": plan}


def _values(p, rng):
    n = len(p["trip"])
    return rng.standard_normal(n) + 1j * rng.standard_normal(n)


# -- event journal ----------------------------------------------------------

def test_journal_records_declared_event():
    before = obs.GLOBAL_JOURNAL.stats()["seq"]
    obs.record_event("lane.death", host="h9")
    events = obs.GLOBAL_JOURNAL.snapshot()
    assert events[-1]["kind"] == "lane.death"
    assert events[-1]["cat"] == "cluster"
    assert events[-1]["attrs"] == {"host": "h9"}
    assert events[-1]["seq"] == before + 1
    assert isinstance(events[-1]["ts"], float)


def test_journal_drops_undeclared_kind_counted():
    dropped0 = obs.GLOBAL_COUNTERS.get(
        "spfft_recorder_events_dropped_total", reason="undeclared_kind")
    obs.record_event("nope.bogus", foo=1)
    assert all(e["kind"] != "nope.bogus"
               for e in obs.GLOBAL_JOURNAL.snapshot())
    assert obs.GLOBAL_COUNTERS.get(
        "spfft_recorder_events_dropped_total",
        reason="undeclared_kind") == dropped0 + 1


def test_journal_filters_undeclared_attrs_and_sanitises():
    obs.record_event("device.quarantine", device=np.int64(3),
                     backoff_s=1.5, bogus_attr="dropped")
    ev = obs.GLOBAL_JOURNAL.snapshot()[-1]
    assert ev["attrs"] == {"device": 3, "backoff_s": 1.5}
    assert isinstance(ev["attrs"]["device"], int)  # JSON-safe
    json.dumps(ev)  # the whole entry is JSON-clean


def test_journal_ring_bounded():
    j = EventJournal(capacity=16)
    for i in range(40):
        j.record("lane.probe", {"host": f"h{i}", "outcome": "ok"})
    st = j.stats()
    assert st["buffered"] == 16 and st["capacity"] == 16
    assert st["seq"] == 40 and st["dropped"] == 24
    hosts = [e["attrs"]["host"] for e in j.snapshot()]
    assert hosts == [f"h{i}" for i in range(24, 40)]  # oldest evicted
    assert len(j.snapshot(limit=4)) == 4


def test_event_specs_all_well_formed():
    """Runtime mirror of the event-registry analyzer: dotted lowercase
    kinds, (category, help, attrs) literals."""
    import re
    for kind, spec in obs.EVENT_SPECS.items():
        assert re.match(r"^[a-z][a-z0-9_]*\.[a-z][a-z0-9_]*$", kind)
        cat, help_, attrs = spec
        assert re.match(r"^[a-z][a-z0-9_]*$", cat)
        assert help_ and isinstance(help_, str)
        assert all(isinstance(a, str) for a in attrs)


# -- tail retention ---------------------------------------------------------

def _traced_request(status="ok", error=None, stages=("serve.stage",)):
    tr = RequestTrace(obs.GLOBAL_TRACER, "t0")
    for s in stages:
        tr.begin(s)
        tr.finish(s)
    tid = tr.trace_id
    tr.close(status=status, error=error)
    return tid


def test_errored_trace_promoted_ok_trace_held(plans):
    obs.enable()
    obs.GLOBAL_TRACER.reset()
    obs.GLOBAL_TRACER.set_sample_rate(0.0)  # head sampling OFF
    obs.enable_recorder(auto=False)
    ok_tid = _traced_request()
    err_tid = _traced_request(status="error", error="InjectedFault")
    retained = obs.retained_traces()
    assert [t["trace_id"] for t in retained] == [err_tid]
    assert retained[0]["reason"] == "error"
    assert retained[0]["status"] == "error"
    # the promoted entry carries the trace's Chrome-format events,
    # recorded despite the 0.0 head sample rate (forced sampling)
    names = {e["name"] for e in retained[0]["events"]}
    assert {"serve.request", "serve.stage"} <= names
    stats = recorder.recorder_stats()
    assert stats["holding"] == 2 and stats["retained"] == 1
    assert ok_tid != err_tid


def test_flag_trace_promotes_held_trace():
    obs.enable()
    obs.GLOBAL_TRACER.reset()
    obs.enable_recorder(auto=False)
    tid = _traced_request()
    assert obs.retained_traces() == []
    assert obs.flag_trace(tid, reason="operator")
    retained = obs.retained_traces()
    assert retained[0]["trace_id"] == tid
    assert retained[0]["reason"] == "flagged" or \
        retained[0]["reason"] == "operator"


def test_slow_trace_promoted_against_latency_source():
    obs.enable()
    obs.GLOBAL_TRACER.reset()
    obs.enable_recorder(auto=False)
    recorder.set_latency_source(lambda: 0.001)  # p99 = 1 ms
    try:
        tr = RequestTrace(obs.GLOBAL_TRACER, "t0")
        time.sleep(0.02)  # >> 3 x p99
        tr.close()
        retained = obs.retained_traces()
        assert retained and retained[-1]["reason"] == "slow"
    finally:
        recorder.set_latency_source(None)


def test_disarmed_recorder_retains_nothing():
    obs.enable()
    obs.GLOBAL_TRACER.reset()
    _traced_request(status="error", error="boom")
    assert obs.retained_traces() == []
    assert recorder.recorder_stats()["active"] is False


# -- incident bundles -------------------------------------------------------

def test_bundle_builds_and_validates(tmp_path):
    obs.enable_recorder(incident_dir=str(tmp_path), auto=False)
    obs.record_event("health.transition", state="degraded",
                     prev="healthy")
    bundle = obs.build_incident_bundle("unit", host="me")
    assert obs.validate_bundle(bundle) == []
    assert bundle["kind"] == "host" and bundle["host"] == "me"
    assert any(e["kind"] == "health.transition"
               for e in bundle["events"])
    assert "spfft_recorder_events_total" in bundle["prometheus"]
    assert "knobs" in bundle["config"]
    json.dumps(bundle)  # self-contained and JSON-clean


def test_capture_writes_atomically_and_gcs(tmp_path):
    obs.enable_recorder(incident_dir=str(tmp_path), keep=2,
                        auto=False)
    paths = [obs.capture_incident(f"unit-{i}") for i in range(4)]
    assert all(p is not None for p in paths)
    left = sorted(os.listdir(tmp_path))
    assert len(left) == 2  # GC'd down to keep
    assert all(n.startswith("incident-") and n.endswith(".json")
               for n in left)
    for n in left:
        with open(tmp_path / n) as f:
            assert obs.validate_bundle(json.load(f)) == []


def test_faulted_capture_contained_no_torn_file(tmp_path):
    obs.enable_recorder(incident_dir=str(tmp_path), auto=False)
    fails0 = obs.GLOBAL_COUNTERS.get(
        "spfft_recorder_incident_failures_total")
    faults.arm(FaultPlan(script="obs.capture@1"))
    try:
        assert obs.capture_incident("doomed") is None
    finally:
        faults.disarm()
    assert obs.GLOBAL_COUNTERS.get(
        "spfft_recorder_incident_failures_total") == fails0 + 1
    assert not any(n.endswith(".tmp") for n in os.listdir(tmp_path))
    # both the failure and the post-disarm success are journalled
    assert obs.capture_incident("healed") is not None
    outcomes = [e["attrs"]["outcome"]
                for e in obs.GLOBAL_JOURNAL.snapshot()
                if e["kind"] == "incident.capture"]
    assert any(o.startswith("failed") for o in outcomes)
    assert "written" in outcomes


def test_capture_without_dir_is_contained(tmp_path, monkeypatch):
    monkeypatch.delenv(recorder.INCIDENT_DIR_ENV, raising=False)
    obs.enable_recorder(auto=False)
    assert obs.capture_incident("nowhere") is None


def test_auto_capture_debounce_and_disarm(tmp_path):
    obs.enable_recorder(incident_dir=str(tmp_path),
                        min_interval_s=3600.0)
    assert obs.maybe_auto_capture("health_degraded") is not None
    # inside the debounce window: dropped
    assert obs.maybe_auto_capture("health_degraded") is None
    assert len(os.listdir(tmp_path)) == 1
    obs.disable_recorder()
    assert obs.maybe_auto_capture("health_degraded") is None


def test_health_transition_auto_triggers_capture(tmp_path):
    obs.enable_recorder(incident_dir=str(tmp_path),
                        min_interval_s=0.0)
    m = ServeMetrics()
    m.record_health("degraded")
    names = os.listdir(tmp_path)
    assert len(names) == 1
    with open(tmp_path / names[0]) as f:
        bundle = json.load(f)
    assert bundle["reason"].startswith("health_degraded")
    assert any(e["kind"] == "health.transition"
               and e["attrs"]["state"] == "degraded"
               for e in bundle["events"])
    # same-state is NOT a rising edge: no second capture
    m.record_health("degraded")
    assert len(os.listdir(tmp_path)) == 1


def test_merge_pod_bundle_timeline_and_stub_tolerance(tmp_path):
    obs.enable_recorder(incident_dir=str(tmp_path), auto=False)
    obs.record_event("lane.death", host="h1")
    a = obs.build_incident_bundle("unit", host="a")
    recorder.reset_recorder()
    obs.record_event("membership.elect", host="b", epoch=2)
    b = obs.build_incident_bundle("unit", host="b")
    pod = obs.merge_pod_bundle("unit", {
        "a": a, "b": b,
        "c": {"error": "HostLaneError: unreachable"}})
    assert obs.validate_bundle(pod) == []
    assert pod["kind"] == "pod"
    assert set(pod["hosts"]) == {"a", "b", "c"}
    tl = pod["timeline"]
    assert all(e["host"] in ("a", "b") for e in tl)
    assert [e["ts"] for e in tl] == sorted(e["ts"] for e in tl)
    kinds = {e["kind"] for e in tl}
    assert {"lane.death", "membership.elect"} <= kinds


def test_validator_rejects_malformed_bundles():
    assert obs.validate_bundle([]) == ["bundle is not a JSON object"]
    bad = obs.validate_bundle({"version": 99, "kind": "blob"})
    assert any("version" in m for m in bad)
    assert any("kind" in m for m in bad)
    good = obs.build_incident_bundle("unit")
    broken = dict(good)
    broken["events"] = "not-a-list"
    assert obs.validate_bundle(broken)


# -- overhead ---------------------------------------------------------------

def test_overhead_probe_disabled_path_budget():
    """The recorder-OFF leg is one module-global read per checkpoint:
    sub-microsecond per request on any machine — far inside the
    round-10 <= 1% budget against the >= 100 us serve hot path the
    recorder_overhead bench row gates the armed leg against."""
    probe = obs.overhead_probe(requests=500, repeats=3)
    assert set(probe) >= {"off_us", "on_us", "delta_us"}
    assert probe["off_us"] < 1.0  # 1% of a 100 us request
    assert probe["delta_us"] >= 0.0
    assert probe["on_us"] >= probe["off_us"]
    # the probe restores the disarmed state it measured
    assert not recorder.recorder_active()
    assert obs.retained_traces() == []


# -- the full loop: pod incident on a live 2-host pod -----------------------

def test_pod_incident_full_loop(plans, tmp_path):
    """ISSUE 20's acceptance loop: head-sampling OFF, a seeded fault
    storm on a live 2-host pod (loopback + REAL TCP agent), a typed
    failure whose trace is tail-retained end-to-end (one trace id
    across the socket), a lane death auto-capturing a pod bundle whose
    timeline holds the fault-site firing, the lane-death and the
    controller events in timestamp order — validating, with zero torn
    files and zero unclosed spans."""
    p = plans
    rng = np.random.default_rng(20)
    obs.enable()
    obs.GLOBAL_TRACER.reset()
    obs.GLOBAL_TRACER.set_sample_rate(0.0)  # head sampling OFF
    obs.enable_recorder(incident_dir=str(tmp_path), min_interval_s=0.0)

    regs = []
    for _ in range(2):
        reg = PlanRegistry()
        reg.put(p["sig"], p["plan"])
        regs.append(reg)
    # the seeded storm: a transient dispatch fault on each lane fires
    # (journalled via fault.fired) and recovers
    g_plans = [FaultPlan(script="dispatch@1") for _ in range(2)]
    loop_ex = ServeExecutor(regs[0], fault_plan=g_plans[0])
    tcp_ex = ServeExecutor(regs[1], fault_plan=g_plans[1])
    agent = HostAgent("r1", tcp_ex).start()
    lane = TcpHostLane("r1", ("127.0.0.1", agent.port))
    pod = PodFrontend([("r0", loop_ex), lane], policy="rr", seed=0)
    cfg = global_config()
    old_batch = cfg.max_batch
    try:
        # a controller event lands in the journal
        cfg.set("max_batch", max(2, old_batch - 1), source="test",
                reason="incident-test controller event")
        for _ in range(4):  # rr: both lanes serve, both faults fire
            v = _values(p, rng)
            got = np.asarray(pod.submit_backward(p["sig"], v)
                             .result(timeout=120))
            assert np.array_equal(got,
                                  np.asarray(p["plan"].backward(v)))
        # the poisoned request fails TYPED; its trace is the tail
        with pytest.raises(GenericError):
            pod.submit_backward(p["sig"],
                                np.zeros(3)).result(timeout=120)
        err = [t for t in obs.retained_traces()
               if t["reason"] == "error"]
        assert err, "typed failure's trace was not tail-retained"
        # end-to-end under ONE trace id: the retained entry holds the
        # frontend's cluster.request root AND the lane-side
        # serve.request span, all recorded despite the 0.0 head sample
        # rate (the armed recorder forces span recording)
        names = {e["name"] for e in err[0]["events"]}
        assert {"cluster.request", "serve.request"} <= names
        # and the id crosses the REAL socket: the TCP agent's
        # serve.request spans carry frontend root ids
        roots = {s.trace_id for s in obs.GLOBAL_TRACER.events()
                 if isinstance(s, obs.Span)
                 and s.name == "cluster.request"}
        served = [s for s in lane.rpc_spans()["spans"]
                  if s["name"] == "serve.request"]
        assert served and all(s["trace_id"] in roots for s in served)
        # lane death: the auto trigger captures a POD bundle
        pod.kill_host("r1")
        bundles = [n for n in os.listdir(tmp_path)
                   if n.startswith("incident-")
                   and n.endswith(".json")]
        assert bundles, "lane death auto-captured nothing"
        lane_death = None
        for n in sorted(bundles):
            with open(tmp_path / n) as f:
                b = json.load(f)
            assert obs.validate_bundle(b) == [], n
            if str(b.get("reason", "")).startswith("lane_death"):
                lane_death = b
        assert lane_death is not None
        assert lane_death["kind"] == "pod"
        tl = lane_death["timeline"]
        kinds = {e["kind"] for e in tl}
        assert {"control.knob", "fault.fired", "lane.death"} <= kinds
        assert [e["ts"] for e in tl] == sorted(e["ts"] for e in tl)
        dead = [e for e in tl if e["kind"] == "lane.death"]
        assert dead[-1]["attrs"]["host"] == "r1"
        fired = [e for e in tl if e["kind"] == "fault.fired"]
        assert any(e["attrs"]["site"] == "dispatch" for e in fired)
        # the errored request's retained trace rode into the bundle
        bundle_traces = [t for sub in lane_death["hosts"].values()
                         if isinstance(sub, dict)
                         for t in sub.get("traces", ())]
        assert any(t["trace_id"] == err[0]["trace_id"]
                   and t["reason"] == "error" for t in bundle_traces)
        # zero torn files, zero unclosed spans, survivor serves on
        assert not any(n.endswith(".tmp")
                       for n in os.listdir(tmp_path))
        assert obs.GLOBAL_TRACER.open_count() == 0
        v = _values(p, rng)
        got = np.asarray(pod.submit_backward(p["sig"], v)
                         .result(timeout=120))
        assert np.array_equal(got, np.asarray(p["plan"].backward(v)))
    finally:
        cfg.set("max_batch", old_batch, source="test",
                reason="restore after incident test")
        pod.close()
        lane.close()
        agent.close()
        tcp_ex.close(drain=False)
        loop_ex.close(drain=False)


def test_pod_capture_gathers_remote_host_over_the_wire(plans,
                                                       tmp_path):
    """PodFrontend.capture_incident pulls the ALIVE remote lane's
    bundle through the new ``incident`` ops verb and labels it by
    host in the merged pod bundle."""
    p = plans
    obs.enable_recorder(incident_dir=str(tmp_path), auto=False)
    reg0, reg1 = PlanRegistry(), PlanRegistry()
    reg0.put(p["sig"], p["plan"])
    reg1.put(p["sig"], p["plan"])
    loop_ex = ServeExecutor(reg0)
    tcp_ex = ServeExecutor(reg1)
    agent = HostAgent("w1", tcp_ex).start()
    lane = TcpHostLane("w1", ("127.0.0.1", agent.port))
    pod = PodFrontend([("w0", loop_ex), lane], policy="rr", seed=0)
    try:
        path = pod.capture_incident("manual")
        assert path is not None
        with open(path) as f:
            bundle = json.load(f)
        assert obs.validate_bundle(bundle) == []
        assert bundle["kind"] == "pod"
        assert "w1" in bundle["hosts"]  # gathered over real TCP
        assert bundle["hosts"]["w1"]["kind"] == "host"
        # the lane's own rpc surface answers too
        direct = lane.rpc_incident("direct")
        assert direct["kind"] == "host"
        assert direct["reason"] == "direct"
    finally:
        pod.close()
        lane.close()
        agent.close()
        tcp_ex.close(drain=False)
        loop_ex.close(drain=False)


# -- surfaces: /incidentz + CLI ---------------------------------------------

def _get(url):
    with urllib.request.urlopen(url, timeout=10) as resp:
        return resp.status, resp.read().decode()


def test_incidentz_route(tmp_path):
    obs.enable_recorder(incident_dir=str(tmp_path), auto=False)
    with MetricsServer(metrics=ServeMetrics(), port=0) as srv:
        status, body = _get(f"{srv.url}/incidentz")
        assert status == 200
        path = json.loads(body)["path"]
        with open(path) as f:
            assert obs.validate_bundle(json.load(f)) == []
    obs.disable_recorder()
    with MetricsServer(metrics=ServeMetrics(), port=0) as srv:
        with pytest.raises(urllib.error.HTTPError) as err:
            _get(f"{srv.url}/incidentz")
        assert err.value.code == 503


def test_incidentz_prefers_registered_capturer(tmp_path):
    obs.enable_recorder(incident_dir=str(tmp_path), auto=False)
    calls = []

    def capture(reason):
        calls.append(reason)
        return obs.capture_incident(reason)

    with MetricsServer(metrics=ServeMetrics(), port=0,
                       incident_fn=capture) as srv:
        status, body = _get(f"{srv.url}/incidentz")
        assert status == 200
    assert calls == ["http"]


def test_cli_incident_capture_and_validate(tmp_path, capsys):
    from spfft_tpu.obs.__main__ import main
    rc = main(["incident", "--dir", str(tmp_path),
               "--reason", "cli-test"])
    assert rc == 0
    out = capsys.readouterr().out
    path = out.strip().split()[-1]
    assert os.path.dirname(path) == str(tmp_path)
    rc = main(["incident", "--validate", path])
    assert rc == 0
    assert "ok:" in capsys.readouterr().out
    # a malformed file fails validation with exit 1
    bad = tmp_path / "broken.json"
    bad.write_text("{\"version\": 99}")
    assert main(["incident", "--validate", str(bad)]) == 1

"""Interpret-mode tests for the fused compression+z-DFT Pallas kernels
(ops/fused_kernel.py) and their plan dispatch: bit-exact (fp32) against
the unfused decompress -> pdft_last composition across c2c/r2c, batched,
shuffled-stick orders and sentinel/zero-stick edge cases, plus the
fallback gate (every unsupported case routes to the two-kernel path
with a recorded reason) and the HLO evidence that the dense gather-tile
intermediate is gone from the fused program."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from spfft_tpu import Scaling, TransformType, make_local_plan
from spfft_tpu.ops import dft
from spfft_tpu.ops import fused_kernel as fkm
from spfft_tpu.ops import gather_kernel as gk

DIM_Z = 128  # smallest fused-eligible z (dim_z % 128 == 0)


@pytest.fixture
def fused_env(monkeypatch):
    """The CPU fused lane: the mdft T pipeline forced on (the fused
    seam only exists there) and the fused kernels in interpret mode."""
    monkeypatch.setenv("SPFFT_TPU_FORCE_MATMUL_DFT", "1")
    monkeypatch.setenv("SPFFT_TPU_FUSED_INTERPRET", "1")


def _plan(triplets, nx=8, ny=6, nz=DIM_Z, ttype=TransformType.C2C,
          **kw):
    return make_local_plan(ttype, nx, ny, nz, np.asarray(triplets,
                                                         np.int32),
                           precision="single", use_pallas=True, **kw)


def _gappy_triplets(nx=8, ny=6, nz=DIM_Z, z_step=2):
    """Sparse sticks (every other z slot empty) — the sentinel/empty-
    slot edge the gather mask must zero before the DFT sees it."""
    return [(x, y, z) for x in range(nx) for y in range(ny)
            if (x + y) % 3 != 0 for z in range(0, nz, z_step)]


def _values(n, seed=0):
    rng = np.random.default_rng(seed)
    return (rng.standard_normal(n)
            + 1j * rng.standard_normal(n)).astype(np.complex64)


def _unfused_backward(plan, vals):
    return np.asarray(jax.jit(
        lambda v, t: plan._backward_impl(v, t, pallas=False))(
            plan._coerce_values(vals), plan._tables))


def _unfused_forward(plan, space, scaled):
    return np.asarray(jax.jit(
        lambda s, t: plan._forward_impl(s, t, scaled=scaled,
                                        pallas=False))(
            plan._coerce_space(space), plan._tables))


# -- kernel level ------------------------------------------------------------

def test_kernel_decompress_zdft_matches_composition():
    """run_decompress_zdft == windowed gather -> pdft_last, elementwise
    (fp32), on a sparse slot set."""
    rng = np.random.default_rng(0)
    s_pad, dim_z = 32, DIM_Z
    num_slots = s_pad * dim_z
    occ = rng.random(num_slots) < 0.6
    vi = np.flatnonzero(occ)
    (dec_idx, occupied), _ = gk.compression_gather_inputs(vi, num_slots)
    nt = gk.build_monotone_gather_tables(dec_idx, occupied, len(vi))
    assert nt is not None and not nt.segs
    ft = fkm.build_fused_decompress_tables(nt, dim_z, s_pad)
    assert not isinstance(ft, str)
    assert ft.r_sticks * dim_z == ft.p_tiles * gk.TILE

    vals = rng.standard_normal((len(vi), 2)).astype(np.float32)
    re, im = gk.planar_from_interleaved(jnp.asarray(vals), nt.src_rows)
    mats = dft.c2c_mats(dim_z, dft.BACKWARD)
    sr, si = fkm.run_decompress_zdft(
        re, im, fkm.decompress_device_tables(ft), fkm.commit_mats(mats),
        ft, interpret=True)

    o_re, o_im = gk.run_gather(re, im, gk.gather_device_tables(nt), nt,
                               interpret=True)
    ur = np.asarray(o_re).reshape(-1)[:num_slots].reshape(s_pad, dim_z)
    ui = np.asarray(o_im).reshape(-1)[:num_slots].reshape(s_pad, dim_z)
    wr, wi = dft.pdft_last(jnp.asarray(ur), jnp.asarray(ui), mats)
    np.testing.assert_allclose(np.asarray(sr)[:s_pad], np.asarray(wr),
                               rtol=2e-6, atol=2e-6)
    np.testing.assert_allclose(np.asarray(si)[:s_pad], np.asarray(wi),
                               rtol=2e-6, atol=2e-6)


def test_kernel_zdft_compress_matches_composition():
    """run_zdft_compress == pdft_last -> windowed gather, with the
    scale folded into the matrices (compile-time scaling)."""
    rng = np.random.default_rng(1)
    s_pad, dim_z = 32, DIM_Z
    num_slots = s_pad * dim_z
    vi = np.flatnonzero(rng.random(num_slots) < 0.5)
    _, (cmp_idx, cmp_valid) = gk.compression_gather_inputs(vi, num_slots)
    nt = gk.build_monotone_gather_tables(cmp_idx, cmp_valid, num_slots)
    assert nt is not None and not nt.segs
    ct = fkm.build_fused_compress_tables(nt, dim_z, s_pad)
    assert not isinstance(ct, str)

    sr = rng.standard_normal((s_pad, dim_z)).astype(np.float32)
    si = rng.standard_normal((s_pad, dim_z)).astype(np.float32)
    mats = dft.c2c_mats(dim_z, dft.FORWARD, scale=1.0 / num_slots)
    psr, psi = fkm.pad_sticks_planar(jnp.asarray(sr), jnp.asarray(si),
                                     ct.src_sticks)
    fo_re, fo_im = fkm.run_zdft_compress(
        psr, psi, fkm.compress_device_tables(ct), fkm.commit_mats(mats),
        ct, interpret=True)
    got_re = np.asarray(fo_re).reshape(-1)[:ct.num_out]
    got_im = np.asarray(fo_im).reshape(-1)[:ct.num_out]

    tr, ti = dft.pdft_last(jnp.asarray(sr), jnp.asarray(si), mats)
    pad = nt.src_rows * 128 - num_slots
    fre = jnp.pad(jnp.asarray(tr).reshape(-1),
                  (0, pad)).reshape(nt.src_rows, 128)
    fim = jnp.pad(jnp.asarray(ti).reshape(-1),
                  (0, pad)).reshape(nt.src_rows, 128)
    w_re, w_im = gk.run_gather(fre, fim, gk.gather_device_tables(nt), nt,
                               interpret=True)
    np.testing.assert_allclose(
        got_re, np.asarray(w_re).reshape(-1)[:nt.num_out],
        rtol=2e-6, atol=2e-6)
    np.testing.assert_allclose(
        got_im, np.asarray(w_im).reshape(-1)[:nt.num_out],
        rtol=2e-6, atol=2e-6)


def test_super_tile_geometry_invariant():
    for dz in (128, 256, 384, 512, 640):
        r, p = fkm.super_tile_geometry(dz)
        assert r * dz == p * gk.TILE
        assert p <= fkm.MAX_P_TILES


# -- plan level --------------------------------------------------------------

def test_plan_backward_forward_fused_bit_exact(fused_env):
    """Fused c2c round trip == the unfused two-kernel composition,
    elementwise, both scalings — the gappy (sentinel-heavy) stick set."""
    plan = _plan(_gappy_triplets())
    assert plan.fused_active
    assert plan.fused_fallback_reasons == {}
    vals = _values(plan.num_local_elements, seed=2)
    space = np.asarray(plan.backward(vals))
    np.testing.assert_allclose(space, _unfused_backward(plan, vals),
                               rtol=2e-6, atol=2e-6)
    for scaling, scaled in ((Scaling.NONE, False), (Scaling.FULL, True)):
        out = np.asarray(plan.forward(space, scaling))
        np.testing.assert_allclose(out,
                                   _unfused_forward(plan, space, scaled),
                                   rtol=2e-6, atol=2e-6)


def test_plan_fused_pair_round_trip(fused_env):
    """apply_pointwise (the benchmark's fused pair) through the fused
    kernels recovers the inputs at FULL scaling."""
    plan = _plan(_gappy_triplets())
    assert plan.fused_active
    vals = _values(plan.num_local_elements, seed=3)
    out = np.asarray(plan.apply_pointwise(vals, scaling=Scaling.FULL))
    np.testing.assert_allclose(out[:, 0] + 1j * out[:, 1], vals,
                               rtol=1e-4, atol=1e-5)


def test_plan_shuffled_stick_order_fused(fused_env):
    """Shuffled triplet order (locally-coherent but not stick-major)
    still passes the fused gate and stays bit-exact."""
    rng = np.random.default_rng(7)
    trip = np.asarray(_gappy_triplets(), np.int32)
    trip = trip[rng.permutation(len(trip))]
    plan = _plan(trip)
    assert plan.fused_active, plan.fused_fallback_reasons
    vals = _values(len(trip), seed=4)
    space = np.asarray(plan.backward(vals))
    np.testing.assert_allclose(space, _unfused_backward(plan, vals),
                               rtol=2e-6, atol=2e-6)
    out = np.asarray(plan.forward(space))
    np.testing.assert_allclose(out, _unfused_forward(plan, space, False),
                               rtol=2e-6, atol=2e-6)


def test_plan_batched_fused(fused_env):
    """The batched boundary runs the batched fused grids and matches
    per-slab unfused execution."""
    plan = _plan(_gappy_triplets())
    assert plan.fused_active
    rng = np.random.default_rng(8)
    B, N = 3, plan.num_local_elements
    vb = rng.standard_normal((B, N, 2)).astype(np.float32)
    got = np.asarray(plan.backward_batched(vb))
    for b in range(B):
        np.testing.assert_allclose(
            got[b], _unfused_backward(plan, vb[b]), rtol=2e-6, atol=2e-6)
    out = np.asarray(plan.forward_batched(got, Scaling.FULL))
    for b in range(B):
        np.testing.assert_allclose(
            out[b], _unfused_forward(plan, got[b], True),
            rtol=2e-6, atol=2e-6)


def test_plan_r2c_fused(fused_env):
    """R2C fuses BOTH directions whether or not the (0,0) stick is
    present: its hermitian completion now rides inside the backward
    kernel (the one-hot mirror contraction of
    fused_kernel._complete_zero_stick), so ``fused_active`` holds with
    no recorded reason and both variants stay bit-exact vs the unfused
    composition."""
    nx, ny = 8, 6
    no_zero = [(x, y, z) for x in range(nx // 2 + 1) for y in range(ny)
               if (x, y) != (0, 0) for z in range(0, DIM_Z, 2)]
    plan = _plan(no_zero, ttype=TransformType.R2C)
    assert plan.fused_active and plan.fused_fallback_reasons == {}
    assert plan._fused["dec"].zinfo is None  # no (0,0) stick to complete
    vals = _values(len(no_zero), seed=5)
    space = np.asarray(plan.backward(vals))
    np.testing.assert_allclose(space, _unfused_backward(plan, vals),
                               rtol=2e-6, atol=2e-6)
    out = np.asarray(plan.forward(space, Scaling.FULL))
    np.testing.assert_allclose(out, _unfused_forward(plan, space, True),
                               rtol=2e-6, atol=2e-6)

    with_zero = [(x, y, z) for x in range(nx // 2 + 1) for y in range(ny)
                 for z in range(0, DIM_Z, 2)]
    plan_z = _plan(with_zero, ttype=TransformType.R2C)
    assert plan_z.fused_active and plan_z.fused_fallback_reasons == {}
    assert plan_z._fused["dec"] is not None
    assert plan_z._fused["dec"].zinfo is not None
    assert plan_z._fused["cmp"] is not None
    vz = _values(len(with_zero), seed=6)
    sz = np.asarray(plan_z.backward(vz))
    np.testing.assert_array_equal(sz, _unfused_backward(plan_z, vz))
    oz = np.asarray(plan_z.forward(sz))
    np.testing.assert_allclose(oz, _unfused_forward(plan_z, sz, False),
                               rtol=2e-6, atol=2e-6)


def test_plan_r2c_fused_batched_zero_stick(fused_env):
    """The batched backward grid completes the (0,0) stick per slab,
    bit-exactly vs per-slab unfused execution."""
    nx, ny = 8, 6
    with_zero = [(x, y, z) for x in range(nx // 2 + 1) for y in range(ny)
                 for z in range(0, DIM_Z, 2)]
    plan = _plan(with_zero, ttype=TransformType.R2C)
    assert plan.fused_active, plan.fused_fallback_reasons
    rng = np.random.default_rng(21)
    B, N = 3, plan.num_local_elements
    vb = rng.standard_normal((B, N, 2)).astype(np.float32)
    got = np.asarray(plan.backward_batched(vb))
    for b in range(B):
        np.testing.assert_array_equal(got[b],
                                      _unfused_backward(plan, vb[b]))


def test_plan_empty_sticks_zeroed(fused_env):
    """Sticks whose slots carry no values at all come out as exact
    zeros of the z-DFT (the scratch zeroing + validity mask contract),
    and the round trip stays bit-exact."""
    # only 3 z-values per stick, most of each stick empty
    trip = [(x, y, z) for x in range(8) for y in range(6)
            if (x + y) % 2 == 0 for z in (0, 1, DIM_Z - 1)]
    plan = _plan(trip)
    assert plan.fused_active, plan.fused_fallback_reasons
    vals = _values(len(trip), seed=9)
    space = np.asarray(plan.backward(vals))
    np.testing.assert_allclose(space, _unfused_backward(plan, vals),
                               rtol=2e-6, atol=2e-6)


# -- fallback gate -----------------------------------------------------------

def test_gate_dimz_not_multiple_128(fused_env):
    trip = [(x, y, z) for x in range(8) for y in range(8)
            for z in range(96)]
    plan = _plan(trip, nx=8, ny=8, nz=96)
    assert not plan.fused_active
    assert plan.fused_fallback_reasons == {
        "dec": "dimz_not_multiple_128", "cmp": "dimz_not_multiple_128"}
    vals = _values(len(trip), seed=10)
    space = np.asarray(plan.backward(vals))  # two-kernel path still runs
    np.testing.assert_allclose(space, _unfused_backward(plan, vals),
                               rtol=2e-6, atol=2e-6)


def test_gate_oversized_z(fused_env):
    """dim_z above the fused-kernel axis cap (dft_kernel.max_dim)
    routes to the two-kernel path with the recorded reason."""
    from spfft_tpu.ops import dft_kernel as dk
    nz = 384
    assert nz % 128 == 0 and nz > dk.max_dim()
    trip = [(x, y, z) for x in range(4) for y in range(4)
            for z in range(0, nz, 2)]
    plan = _plan(trip, nx=4, ny=4, nz=nz)
    assert not plan.fused_active
    assert plan.fused_fallback_reasons == {
        "dec": "dimz_over_cap", "cmp": "dimz_over_cap"}


def test_gate_double_precision_never_fused(fused_env):
    """Double precision never reaches the fused gate (the Pallas
    compression path is single-only)."""
    trip = _gappy_triplets(nx=4, ny=4)
    plan = make_local_plan(TransformType.C2C, 4, 4, DIM_Z,
                           np.asarray(trip, np.int32),
                           precision="double")
    assert not plan.fused_active
    assert "fzd_tabs" not in plan._tables


def test_gate_env_disable(fused_env, monkeypatch):
    monkeypatch.setenv("SPFFT_TPU_FUSED_COMPRESS", "0")
    plan = _plan(_gappy_triplets())
    assert not plan.fused_active
    assert "fzd_tabs" not in plan._tables
    vals = _values(plan.num_local_elements, seed=11)
    space = np.asarray(plan.backward(vals))
    np.testing.assert_allclose(space, _unfused_backward(plan, vals),
                               rtol=2e-6, atol=2e-6)


def test_gate_recompute_blowup_model():
    """The forward cost model declines when window-overlap recompute
    exceeds RECOMPUTE_LIMIT x the unfused pass."""
    rng = np.random.default_rng(12)
    s_pad, dim_z = 32, DIM_Z
    num_slots = s_pad * dim_z
    vi = np.flatnonzero(rng.random(num_slots) < 0.5)
    _, (cmp_idx, cmp_valid) = gk.compression_gather_inputs(vi, num_slots)
    nt = gk.build_monotone_gather_tables(cmp_idx, cmp_valid, num_slots)
    rows = fkm.compress_recompute_rows(nt, dim_z)
    # a tiny stick count makes ANY recompute blow the model
    out = fkm.build_fused_compress_tables(nt, dim_z,
                                          num_sticks=max(1, int(
                                              rows / 100)))
    assert out == "recompute_blowup"


def test_fallback_counter_recorded(fused_env):
    from spfft_tpu import obs
    before = obs.GLOBAL_COUNTERS.get(
        "spfft_plan_pallas_fallback_total",
        stage="fused_decompress_zdft", reason="dimz_not_multiple_128")
    trip = [(x, y, z) for x in range(4) for y in range(4)
            for z in range(96)]
    plan = _plan(trip, nx=4, ny=4, nz=96)
    plan._finalize()
    after = obs.GLOBAL_COUNTERS.get(
        "spfft_plan_pallas_fallback_total",
        stage="fused_decompress_zdft", reason="dimz_not_multiple_128")
    assert after == before + 1


# -- the acceptance criterion: no dense gather-tile intermediate -------------

def test_fused_backward_hlo_drops_gather_intermediate(fused_env,
                                                      monkeypatch):
    """The fused backward program must not contain the unfused path's
    dense gather-output buffer (num_tiles, 8, 128) — the HBM
    intermediate this kernel exists to remove — while the forced
    UNFUSED kernel path does lower it."""
    import functools
    plan = _plan(_gappy_triplets())
    assert plan.fused_active
    dec = plan._pallas["dec"]
    vil = plan._coerce_values(_values(plan.num_local_elements, seed=13))
    shape = "%dx%dx%dxf32" % ((dec.num_super * dec.p_tiles)
                              if isinstance(dec, gk.WideGatherTables)
                              else dec.num_tiles, gk.TILE_SUB,
                              gk.TILE_LANE)
    fused_text = jax.jit(
        lambda v: plan._backward_impl(v, plan._tables_hot)).lower(
            vil).as_text()
    assert shape not in fused_text

    # contrast: the unfused kernel path (gather kernel in interpret,
    # fused dispatch off) materialises exactly that buffer
    monkeypatch.setattr(plan, "_fused_active_flag", False)
    monkeypatch.setattr(gk, "run_gather",
                        functools.partial(gk.run_gather, interpret=True))
    monkeypatch.setattr(plan, "_pallas_active", True)
    unfused_text = jax.jit(
        lambda v: plan._backward_impl(v, plan._tables)).lower(
            vil).as_text()
    assert shape in unfused_text


# -- distributed forward-twin gate rows (parallel/dist.py) -------------------

def _dist_plan(**kw):
    """A tiny 2-shard r2c plan on the virtual CPU mesh — the same
    flagship shape test_fused_dist.py fuzzes, here only to poke the
    dist gate matrix."""
    from spfft_tpu.parallel import make_distributed_plan, make_mesh
    from spfft_tpu.utils.workloads import sort_triplets_stick_major
    from test_distributed import split_by_sticks, split_planes
    from test_util import hermitian_triplets
    dims = (8, 6, DIM_Z)
    trips = hermitian_triplets(np.random.default_rng(11), dims)
    parts = [sort_triplets_stick_major(p, dims)
             for p in split_by_sticks(trips, dims, [2, 1])]
    return make_distributed_plan(
        TransformType.R2C, *dims, parts, split_planes(DIM_Z, [1, 1]),
        mesh=make_mesh(2), precision="single",
        use_pallas=kw.pop("use_pallas", True), **kw)


def test_dist_gate_no_matmul_dft(monkeypatch):
    """Without the mdft T pipeline both distributed twins decline with
    a recorded no_matmul_dft reason (the fused seam only exists on the
    matmul-DFT path)."""
    monkeypatch.delenv("SPFFT_TPU_FORCE_MATMUL_DFT", raising=False)
    monkeypatch.setenv("SPFFT_TPU_FUSED_INTERPRET", "1")
    plan = _dist_plan()
    assert not plan.fused_dist_active
    assert plan.fused_dist_fallback_reason == "no_matmul_dft"
    assert plan.fused_dist_fwd_fallback_reason == "no_matmul_dft"


def test_dist_fwd_twin_recompute_counter_recorded(fused_env):
    """The forward twin's recompute_blowup decline records under the
    dist_fused_zdft_compress stage (declared in METRIC_SPECS) and the
    series surfaces through the Prometheus exposition — the runtime
    coverage for the new fallback stage label."""
    from spfft_tpu import obs
    before = obs.GLOBAL_COUNTERS.get(
        "spfft_plan_pallas_fallback_total",
        stage="dist_fused_zdft_compress", reason="recompute_blowup")
    # at the default RECOMPUTE_LIMIT this workload's window-overlap
    # recompute blows the forward cost model (the backward stays active)
    plan = _dist_plan()
    assert plan.fused_dist_bwd_active
    assert plan.fused_dist_fwd_fallback_reason == "recompute_blowup"
    after = obs.GLOBAL_COUNTERS.get(
        "spfft_plan_pallas_fallback_total",
        stage="dist_fused_zdft_compress", reason="recompute_blowup")
    assert after == before + 1
    text = obs.prometheus_text()
    assert ('spfft_plan_pallas_fallback_total{reason="recompute_blowup"'
            ',stage="dist_fused_zdft_compress"}') in text


# -- runtime demotion ladder -------------------------------------------------

def test_runtime_launch_fault_demotes_one_direction(fused_env):
    """An injected kernel.launch fault during backward demotes EXACTLY
    the dec direction: the failing request itself succeeds on the
    unfused retry (bit-exact), the reason is recorded, forward stays
    fused, and the next backward runs unfused without re-failing."""
    from spfft_tpu import faults

    tr = _gappy_triplets()
    plan = _plan(tr)
    vals = _values(plan.index_plan.num_values)
    want = _unfused_backward(plan, vals)
    try:
        faults.arm(faults.FaultPlan(script="kernel.launch@1"))
        got = np.asarray(plan.backward(vals))
    finally:
        faults.disarm()
    np.testing.assert_array_equal(got, want)

    dem = plan.fused_demotions()
    assert set(dem) == {"dec"}
    assert "runtime" in dem["dec"]["reason"]
    assert "InjectedFault" in dem["dec"]["reason"]
    assert not dem["dec"]["permanent"]

    # the direction stays demoted and serving continues
    np.testing.assert_array_equal(np.asarray(plan.backward(vals)), want)
    assert plan.fused_demotions()["dec"]["unfused_ok"] >= 1


def test_runtime_demotion_reprobe_readmits(fused_env):
    """The bounded re-probe: a demoted direction banks
    FUSED_REPROBE_AFTER unfused successes, then the next dispatch runs
    the fused kernel again as a probe — success lifts the demotion."""
    from spfft_tpu import faults

    tr = _gappy_triplets()
    plan = _plan(tr)
    vals = _values(plan.index_plan.num_values)
    want = _unfused_backward(plan, vals)
    try:
        faults.arm(faults.FaultPlan(script="kernel.launch@1"))
        np.testing.assert_array_equal(
            np.asarray(plan.backward(vals)), want)
    finally:
        faults.disarm()
    assert set(plan.fused_demotions()) == {"dec"}

    for i in range(plan.FUSED_REPROBE_AFTER - 1):
        plan.backward(vals)
    rec = plan.fused_demotions()["dec"]
    assert rec["unfused_ok"] == plan.FUSED_REPROBE_AFTER - 1
    assert not rec["probing"]

    plan.backward(vals)  # banks the last unfused success
    assert plan.fused_demotions()["dec"]["probing"]

    # the probe call runs fused (no fault armed) and readmits
    got = np.asarray(plan.backward(vals))
    np.testing.assert_array_equal(got, want)
    assert plan.fused_demotions() == {}


def test_runtime_demotion_permanent_after_failed_probes(fused_env):
    """kernel.launch@* (the device really is broken): every re-probe
    fails, and after FUSED_REPROBE_MAX failed probes the demotion is
    permanent — no further probes, requests keep succeeding unfused."""
    from spfft_tpu import faults

    tr = _gappy_triplets()
    plan = _plan(tr)
    vals = _values(plan.index_plan.num_values)
    want = _unfused_backward(plan, vals)
    try:
        # @* only fires on FUSED dispatches; banked unfused calls never
        # reach the kernel.launch check, so the script stays armed
        faults.arm(faults.FaultPlan(script="kernel.launch@*"))
        np.testing.assert_array_equal(
            np.asarray(plan.backward(vals)), want)
        for probe in range(plan.FUSED_REPROBE_MAX):
            for _ in range(plan.FUSED_REPROBE_AFTER):
                plan.backward(vals)
            assert plan.fused_demotions()["dec"]["probing"]
            # the probe dispatch fails fused, re-demotes, serves unfused
            np.testing.assert_array_equal(
                np.asarray(plan.backward(vals)), want)
            rec = plan.fused_demotions()["dec"]
            assert rec["probes"] == probe + 1
    finally:
        faults.disarm()
    rec = plan.fused_demotions()["dec"]
    assert rec["permanent"]
    assert rec["probes"] == plan.FUSED_REPROBE_MAX

    # permanent: banking many successes never flips probing again
    for _ in range(plan.FUSED_REPROBE_AFTER + 1):
        plan.backward(vals)
    rec = plan.fused_demotions()["dec"]
    assert rec["permanent"] and not rec["probing"]
    np.testing.assert_array_equal(np.asarray(plan.backward(vals)), want)


def test_runtime_demotion_forward_direction_independent(fused_env):
    """Demoting cmp (forward) leaves dec (backward) fused: the ladder
    is strictly per-direction."""
    from spfft_tpu import faults

    tr = _gappy_triplets()
    plan = _plan(tr)
    vals = _values(plan.index_plan.num_values)
    space = plan.backward(vals)
    want = _unfused_forward(plan, space, scaled=False)
    try:
        faults.arm(faults.FaultPlan(script="kernel.launch@1"))
        got = np.asarray(plan.forward(space, scaling=Scaling.NONE))
    finally:
        faults.disarm()
    np.testing.assert_array_equal(got, want)
    assert set(plan.fused_demotions()) == {"cmp"}
    # backward still dispatches fused (no demotion recorded for dec)
    plan.backward(vals)
    assert set(plan.fused_demotions()) == {"cmp"}


def test_request_shaped_error_does_not_demote(fused_env):
    """A poisoned payload (request-attributed) must propagate untouched
    and never demote the kernel — demotion is for device faults only."""
    from spfft_tpu.errors import InvalidParameterError

    tr = _gappy_triplets()
    plan = _plan(tr)
    with pytest.raises(InvalidParameterError):
        plan.backward(np.zeros(3, np.complex64))
    assert plan.fused_demotions() == {}

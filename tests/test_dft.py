"""Matmul-DFT stage library vs numpy's FFT (the oracle the whole suite
uses — SURVEY.md §4's dense-FFTW-oracle pattern applied at the stage
level)."""

import numpy as np
import jax.numpy as jnp
import pytest

from spfft_tpu.ops import dft

DIMS = [1, 2, 3, 11, 12, 13, 100, 256]


def _rand(shape, seed=0):
    rng = np.random.default_rng(seed)
    return (rng.standard_normal(shape) + 1j
            * rng.standard_normal(shape)).astype(np.complex64)


@pytest.mark.parametrize("n", DIMS)
def test_forward_c2c(n):
    x = _rand((7, n))
    got = np.asarray(dft.cdft_last(jnp.asarray(x),
                                   dft.c2c_mats(n, dft.FORWARD)))
    ref = np.fft.fft(x, axis=-1)
    np.testing.assert_allclose(got, ref, atol=2e-4 * max(n, 1), rtol=2e-5)


@pytest.mark.parametrize("n", DIMS)
def test_backward_unnormalised(n):
    x = _rand((5, n), seed=1)
    got = np.asarray(dft.cdft_last(jnp.asarray(x),
                                   dft.c2c_mats(n, dft.BACKWARD)))
    ref = np.fft.ifft(x, axis=-1) * n
    np.testing.assert_allclose(got, ref, atol=2e-4 * max(n, 1), rtol=2e-5)


def test_scale_folding():
    n = 16
    x = _rand((3, n), seed=2)
    got = np.asarray(dft.cdft_last(
        jnp.asarray(x), dft.c2c_mats(n, dft.FORWARD, scale=1.0 / n)))
    ref = np.fft.fft(x, axis=-1) / n
    np.testing.assert_allclose(got, ref, atol=1e-5, rtol=1e-5)


def test_planar_matches_complex():
    n = 64
    x = _rand((4, n), seed=3)
    mats = dft.c2c_mats(n, dft.FORWARD)
    yr, yi = dft.pdft_last(jnp.asarray(x.real.copy()),
                           jnp.asarray(x.imag.copy()), mats)
    ref = np.asarray(dft.cdft_last(jnp.asarray(x), mats))
    np.testing.assert_allclose(np.asarray(yr) + 1j * np.asarray(yi), ref,
                               atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("n", DIMS)
def test_real_forward(n):
    rng = np.random.default_rng(4)
    x = rng.standard_normal((6, n)).astype(np.float32)
    yr, yi = dft.prdft_last(jnp.asarray(x), dft.r2c_mats(n))
    ref = np.fft.rfft(x, axis=-1)
    got = np.asarray(yr) + 1j * np.asarray(yi)
    np.testing.assert_allclose(got, ref, atol=2e-4 * max(n, 1), rtol=2e-5)


@pytest.mark.parametrize("n", DIMS)
def test_real_inverse_unnormalised(n):
    rng = np.random.default_rng(5)
    xf = n // 2 + 1
    y = (rng.standard_normal((6, xf)) + 1j
         * rng.standard_normal((6, xf))).astype(np.complex64)
    # make the self-conjugate bins real so y is a valid half spectrum
    y[:, 0] = y[:, 0].real
    if n % 2 == 0:
        y[:, -1] = y[:, -1].real
    got = np.asarray(dft.pirdft_last(jnp.asarray(y.real.copy()),
                                     jnp.asarray(y.imag.copy()),
                                     dft.c2r_mats(n)))
    ref = np.fft.irfft(y, n=n, axis=-1) * n
    np.testing.assert_allclose(got, ref, atol=2e-4 * max(n, 1), rtol=2e-5)


def test_sub_rows_window():
    """Row-selected matrices = DFT of a sparse input laid out in a
    (possibly wrapped) window — the split-x path's contraction."""
    n = 32
    rows = np.array([28, 29, 30, 31, 0, 1, 2])  # wrapped window
    xw = _rand((3, len(rows)), seed=6)
    full = np.zeros((3, n), np.complex64)
    full[:, rows] = xw
    mats = dft._sub_rows(dft.c2c_mats(n, dft.FORWARD), rows)
    got = np.asarray(dft.cdft_last(jnp.asarray(xw), mats))
    ref = np.fft.fft(full, axis=-1)
    np.testing.assert_allclose(got, ref, atol=1e-4, rtol=1e-5)


def test_sub_cols_window():
    n = 32
    cols = np.array([30, 31, 0, 1, 2])
    x = _rand((3, n), seed=7)
    mats = dft._sub_cols(dft.c2c_mats(n, dft.FORWARD), cols)
    got = np.asarray(dft.cdft_last(jnp.asarray(x), mats))
    ref = np.fft.fft(x, axis=-1)[:, cols]
    np.testing.assert_allclose(got, ref, atol=1e-4, rtol=1e-5)


@pytest.mark.parametrize("ttype,dims", [("c2c", (12, 13, 11)),
                                        ("r2c", (13, 12, 8))])
def test_plan_roundtrip_through_matmul_path(monkeypatch, ttype, dims):
    """End-to-end plan through the forced matmul-DFT stages (the suite
    runs on CPU where the backend gate would pick jnp.fft; CI keeps this
    path exercised without a TPU)."""
    monkeypatch.setenv("SPFFT_TPU_FORCE_MATMUL_DFT", "1")
    from spfft_tpu import TransformType, make_local_plan

    nx, ny, nz = dims
    tt = TransformType.C2C if ttype == "c2c" else TransformType.R2C
    if ttype == "c2c":
        tri = np.array([(x, y, z) for x in range(nx) for y in range(ny)
                        for z in range(nz)
                        if (x + y + z) % 3 != 0], np.int64)
    else:
        # R2C contract (details.rst "Real-To-Complex"): sticks at stick
        # granularity — x>0 sticks all z; x=0 sticks one of each +-y
        # pair; the (0,0) stick one of each +-z pair.
        tri = []
        for x in range(1, nx // 2 + 1):
            tri += [(x, y, z) for y in range(ny) for z in range(nz)
                    if (x + y + z) % 3 != 0]
        tri += [(0, y, z) for y in range(1, ny // 2 + 1)
                for z in range(nz)]
        tri += [(0, 0, z) for z in range(nz // 2 + 1)]
        tri = np.array(tri, np.int64)
    plan = make_local_plan(tt, nx, ny, nz, tri, precision="single")
    rng = np.random.default_rng(8)
    if ttype == "c2c":
        vals = (rng.standard_normal(len(tri)) + 1j
                * rng.standard_normal(len(tri))).astype(np.complex64)
        cube = np.zeros((nz, ny, nx), np.complex64)
        cube[tri[:, 2], tri[:, 1], tri[:, 0]] = vals
        oracle = np.fft.ifftn(cube) * cube.size
        got = np.asarray(plan.backward(vals))
        got = got[..., 0] + 1j * got[..., 1]
    else:
        # build values from a real field so the half spectrum is valid
        field = rng.standard_normal((nz, ny, nx)).astype(np.float32)
        spec = np.fft.fftn(field)
        vals = spec[tri[:, 2], tri[:, 1], tri[:, 0]].astype(np.complex64)
        cube = np.zeros((nz, ny, nx), np.complex128)
        # dense oracle: scatter the half-spectrum values + conjugates
        for (x, y, z), v in zip(tri, vals):
            cube[z, y, x] = v
            cube[(-z) % nz, (-y) % ny, (-x) % nx] = np.conj(v)
        oracle = np.fft.ifftn(cube).real * cube.size
        got = np.asarray(plan.backward(vals))
    err = np.linalg.norm(got - oracle) / max(np.linalg.norm(oracle), 1e-30)
    assert err < 2e-5, err


def test_use_matmul_dft_gating(monkeypatch):
    monkeypatch.setenv("SPFFT_TPU_FORCE_MATMUL_DFT", "1")
    assert dft.use_matmul_dft(256, jnp.complex64)
    # above the direct cap: composite lengths ride the two-stage split;
    # unfactorable (prime-class) lengths run the direct fallback up to
    # MATMUL_DFT_DIRECT_FALLBACK_MAX; beyond it, jnp.fft
    assert dft.use_matmul_dft(768, jnp.complex64)
    assert dft.use_matmul_dft(521, jnp.complex64)
    assert not dft.use_matmul_dft(2 * 521, jnp.complex64)  # 1042 > 1024
    monkeypatch.delenv("SPFFT_TPU_FORCE_MATMUL_DFT")
    monkeypatch.setenv("SPFFT_TPU_NO_MATMUL_DFT", "1")
    assert not dft.use_matmul_dft(256, jnp.complex64)

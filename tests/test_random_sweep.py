"""Randomized property sweep: backward/forward round trips on many random
configurations (dims, sparsity, precision, transform type, distribution),
seeded for reproducibility. The reference's randomized fixtures
(generate_indices.hpp) sweep the same space; this is the condensed
property-test form: forward(backward(v), FULL) == v at the sparse set."""

import numpy as np
import pytest

from spfft_tpu import Scaling, TransformType, make_local_plan
from spfft_tpu.parallel import make_distributed_plan, make_mesh
from spfft_tpu.utils import as_complex_np

from test_util import (center_triplets, hermitian_triplets,
                       random_sparse_triplets, random_values, tolerance_for)
from test_distributed import split_by_sticks, split_planes


@pytest.mark.parametrize("seed", range(8))
def test_local_round_trip_property(seed):
    rng = np.random.default_rng(1000 + seed)
    dims = tuple(int(d) for d in rng.integers(1, 20, 3))
    r2c = bool(rng.integers(0, 2)) and dims[0] > 1
    precision = ["double", "single"][int(rng.integers(0, 2))]
    if r2c:
        triplets = hermitian_triplets(rng, dims)
        ttype = TransformType.R2C
    else:
        triplets = random_sparse_triplets(rng, dims)
        if rng.integers(0, 2):
            triplets = center_triplets(triplets, dims)
        ttype = TransformType.C2C
    if len(triplets) == 0:
        pytest.skip("degenerate empty set")
    plan = make_local_plan(ttype, *dims, triplets, precision=precision)
    if r2c:
        # hermitian-consistent values: sample a real field's spectrum
        space = rng.standard_normal((dims[2], dims[1], dims[0]))
        freq = np.fft.fftn(space)
        st = triplets.copy()
        for ax, d in enumerate(dims):
            st[:, ax] = np.where(st[:, ax] < 0, st[:, ax] + d, st[:, ax])
        v = freq[st[:, 2], st[:, 1], st[:, 0]]
    else:
        v = random_values(rng, len(triplets))
    got = as_complex_np(np.asarray(
        plan.forward(plan.backward(v), Scaling.FULL)))
    tol = tolerance_for(precision, v)
    np.testing.assert_allclose(got, v, atol=tol, rtol=0)


@pytest.mark.parametrize("seed", range(4))
def test_distributed_round_trip_property(seed):
    rng = np.random.default_rng(2000 + seed)
    dims = tuple(int(d) for d in rng.integers(4, 16, 3))
    shards = int(rng.integers(2, 5))
    triplets = random_sparse_triplets(rng, dims)
    if len(triplets) == 0:
        pytest.skip("degenerate empty set")
    parts = split_by_sticks(triplets, dims,
                            rng.integers(0, 4, shards) + [1] * shards)
    planes = split_planes(dims[2], rng.integers(0, 4, shards) + 1)
    plan = make_distributed_plan(TransformType.C2C, *dims, parts, planes,
                                 mesh=make_mesh(shards), precision="double")
    values = [random_values(rng, len(p)) for p in parts]
    got = plan.unshard_values(
        plan.apply_pointwise(values, scaling=Scaling.FULL))
    for g, v in zip(got, values):
        np.testing.assert_allclose(g, v, atol=1e-10, rtol=0)


@pytest.mark.parametrize("seed", range(8))
def test_local_round_trip_property_device_double(seed, monkeypatch):
    """The on-device double pipeline over the same randomized space
    (degenerate dims of 1, primes, sparse/dense, C2C and R2C, centered
    and positive): forward(backward(v), FULL) == v within the 2e-11
    contract envelope."""
    monkeypatch.setenv("SPFFT_TPU_DEVICE_DOUBLE", "force")
    rng = np.random.default_rng(3000 + seed)
    dims = tuple(int(d) for d in rng.integers(1, 20, 3))
    r2c = bool(rng.integers(0, 2)) and dims[0] > 1
    if r2c:
        triplets = hermitian_triplets(rng, dims)
        ttype = TransformType.R2C
    else:
        triplets = random_sparse_triplets(rng, dims)
        if rng.integers(0, 2):
            triplets = center_triplets(triplets, dims)
        ttype = TransformType.C2C
    if len(triplets) == 0:
        pytest.skip("degenerate empty set")
    plan = make_local_plan(ttype, *dims, triplets, precision="double")
    assert plan._ds
    if r2c:
        # hermitian-consistent values from a real field's spectrum so
        # the round trip compares against an INDEPENDENT reference (an
        # idempotent-but-wrong transform would pass a fixed-point-only
        # check)
        field = rng.standard_normal((dims[2], dims[1], dims[0]))
        freq = np.fft.fftn(field)
        st = triplets.copy()
        for ax, d in enumerate(dims):
            st[:, ax] = np.where(st[:, ax] < 0, st[:, ax] + d,
                                 st[:, ax])
        vals = freq[st[:, 2], st[:, 1], st[:, 0]]
    else:
        vals = random_values(rng, len(triplets)).astype(np.complex128)
    space = plan.backward(vals)
    out = plan.forward(space, Scaling.FULL)
    got = as_complex_np(out)
    assert np.linalg.norm(got) > 0  # a zeroed forward must not pass
    rel = (np.linalg.norm(got - vals)
           / max(np.linalg.norm(vals), 1e-30))
    assert rel < 2e-11, (dims, ttype, rel)


@pytest.mark.parametrize("seed", range(6))
def test_distributed_ragged_round_trip_property(seed):
    """The one-collective ragged exchange over randomized skewed
    partitions — zero-stick and zero-plane shards included — through
    the fused pair. Half the seeds add the reduced-precision wire."""
    from spfft_tpu import ExchangeType

    rng = np.random.default_rng(4000 + seed)
    dims = tuple(int(d) for d in rng.integers(4, 16, 3))
    shards = int(rng.integers(2, 7))
    triplets = random_sparse_triplets(rng, dims)
    if len(triplets) == 0:
        pytest.skip("degenerate empty set")
    sw = rng.integers(0, 3, shards)
    if sw.sum() == 0:
        sw[0] = 1
    pw = rng.integers(0, 3, shards)
    if pw.sum() == 0:
        pw[-1] = 1
    parts = split_by_sticks(triplets, dims, sw)
    planes = split_planes(dims[2], pw)
    float_wire = bool(seed % 2)
    exchange = (ExchangeType.COMPACT_BUFFERED_FLOAT if float_wire
                else ExchangeType.COMPACT_BUFFERED)
    precision = "single" if float_wire else "double"
    plan = make_distributed_plan(TransformType.C2C, *dims, parts, planes,
                                 mesh=make_mesh(shards),
                                 precision=precision, exchange=exchange)
    assert plan._ragged is not None  # shards >= 2 always
    values = [random_values(rng, len(p)).astype(
        np.complex64 if precision == "single" else np.complex128)
        for p in parts]
    got = plan.unshard_values(
        plan.apply_pointwise(values, scaling=Scaling.FULL))
    # bf16 wire bounds the single-precision error; exact wire is f64
    tol = 3e-2 if float_wire else 1e-10
    for g, v in zip(got, values):
        if len(v):
            rel = np.linalg.norm(g - v) / max(np.linalg.norm(v), 1e-30)
            assert rel < tol, (dims, shards, rel)

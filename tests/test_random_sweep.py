"""Randomized property sweep: backward/forward round trips on many random
configurations (dims, sparsity, precision, transform type, distribution),
seeded for reproducibility. The reference's randomized fixtures
(generate_indices.hpp) sweep the same space; this is the condensed
property-test form: forward(backward(v), FULL) == v at the sparse set."""

import numpy as np
import pytest

from spfft_tpu import Scaling, TransformType, make_local_plan
from spfft_tpu.parallel import make_distributed_plan, make_mesh
from spfft_tpu.utils import as_complex_np

from test_util import (center_triplets, hermitian_triplets,
                       random_sparse_triplets, random_values, tolerance_for)
from test_distributed import split_by_sticks, split_planes


@pytest.mark.parametrize("seed", range(8))
def test_local_round_trip_property(seed):
    rng = np.random.default_rng(1000 + seed)
    dims = tuple(int(d) for d in rng.integers(1, 20, 3))
    r2c = bool(rng.integers(0, 2)) and dims[0] > 1
    precision = ["double", "single"][int(rng.integers(0, 2))]
    if r2c:
        triplets = hermitian_triplets(rng, dims)
        ttype = TransformType.R2C
    else:
        triplets = random_sparse_triplets(rng, dims)
        if rng.integers(0, 2):
            triplets = center_triplets(triplets, dims)
        ttype = TransformType.C2C
    if len(triplets) == 0:
        pytest.skip("degenerate empty set")
    plan = make_local_plan(ttype, *dims, triplets, precision=precision)
    if r2c:
        # hermitian-consistent values: sample a real field's spectrum
        space = rng.standard_normal((dims[2], dims[1], dims[0]))
        freq = np.fft.fftn(space)
        st = triplets.copy()
        for ax, d in enumerate(dims):
            st[:, ax] = np.where(st[:, ax] < 0, st[:, ax] + d, st[:, ax])
        v = freq[st[:, 2], st[:, 1], st[:, 0]]
    else:
        v = random_values(rng, len(triplets))
    got = as_complex_np(np.asarray(
        plan.forward(plan.backward(v), Scaling.FULL)))
    tol = tolerance_for(precision, v)
    np.testing.assert_allclose(got, v, atol=tol, rtol=0)


@pytest.mark.parametrize("seed", range(4))
def test_distributed_round_trip_property(seed):
    rng = np.random.default_rng(2000 + seed)
    dims = tuple(int(d) for d in rng.integers(4, 16, 3))
    shards = int(rng.integers(2, 5))
    triplets = random_sparse_triplets(rng, dims)
    if len(triplets) == 0:
        pytest.skip("degenerate empty set")
    parts = split_by_sticks(triplets, dims,
                            rng.integers(0, 4, shards) + [1] * shards)
    planes = split_planes(dims[2], rng.integers(0, 4, shards) + 1)
    plan = make_distributed_plan(TransformType.C2C, *dims, parts, planes,
                                 mesh=make_mesh(shards), precision="double")
    values = [random_values(rng, len(p)) for p in parts]
    got = plan.unshard_values(
        plan.apply_pointwise(values, scaling=Scaling.FULL))
    for g, v in zip(got, values):
        np.testing.assert_allclose(g, v, atol=1e-10, rtol=0)


@pytest.mark.parametrize("seed", range(8))
def test_local_round_trip_property_device_double(seed, monkeypatch):
    """The on-device double pipeline over the same randomized space
    (degenerate dims of 1, primes, sparse/dense, C2C and R2C, centered
    and positive): forward(backward(v), FULL) == v within the 2e-11
    contract envelope."""
    monkeypatch.setenv("SPFFT_TPU_DEVICE_DOUBLE", "force")
    rng = np.random.default_rng(3000 + seed)
    dims = tuple(int(d) for d in rng.integers(1, 20, 3))
    r2c = bool(rng.integers(0, 2)) and dims[0] > 1
    if r2c:
        triplets = hermitian_triplets(rng, dims)
        ttype = TransformType.R2C
    else:
        triplets = random_sparse_triplets(rng, dims)
        if rng.integers(0, 2):
            triplets = center_triplets(triplets, dims)
        ttype = TransformType.C2C
    if len(triplets) == 0:
        pytest.skip("degenerate empty set")
    plan = make_local_plan(ttype, *dims, triplets, precision="double")
    assert plan._ds
    vals = random_values(rng, len(triplets)).astype(np.complex128)
    space = plan.backward(vals)
    out = plan.forward(space, Scaling.FULL)
    got = as_complex_np(out)
    assert np.linalg.norm(got) > 0  # a zeroed forward must not pass
    if r2c:
        # self-conjugate bins recover Re(v) (docs/precision.md); compare
        # through a second round trip, which must be a fixed point
        space2 = plan.backward(got)
        out2 = plan.forward(space2, Scaling.FULL)
        got2 = as_complex_np(out2)
        ref = got
    else:
        got2, ref = got, vals
    rel = (np.linalg.norm(got2 - ref)
           / max(np.linalg.norm(ref), 1e-30))
    assert rel < 2e-11, (dims, ttype, rel)

"""The shared wall-clock estimator (utils/benchtime.py).

The regression locked in here is the round-5 finding
(scripts/probe_r5_mode.py): the hard-sync cost through the tunnel is
bimodal (~88 vs ~128 ms) and CONSTANT per group, so a min-of-single-
diffs statistic fabricates fast readings when the two group sizes catch
mismatched sync modes — that artifact was the round-4 "device fast
mode". The median-differencing estimator must be immune to it.
"""
import math

import pytest

from spfft_tpu.utils.benchtime import diff_estimate_seconds

PER_CALL = 0.0125
SLOW_SYNC = 0.128
FAST_SYNC = 0.088


def make_run_group(sync_sequence):
    syncs = iter(sync_sequence)

    def run_group(g):
        return g * PER_CALL + next(syncs)
    return run_group


def test_median_diff_cancels_constant_sync():
    est = diff_estimate_seconds(make_run_group([SLOW_SYNC] * 8), reps=20)
    assert not est.fallback
    assert est.seconds == pytest.approx(PER_CALL, rel=1e-9)
    assert est.median == est.seconds
    assert "sync-robust median" in est.label


def test_mismatched_sync_mode_does_not_bias_the_estimate():
    # trial 2's large group catches the fast sync while its small group
    # does not: the legacy per-trial min is biased ~3 ms/call low, the
    # median estimate is exact. Call order is g2 then g1 per trial.
    syncs = [SLOW_SYNC, SLOW_SYNC,   # trial 0: g2, g1
             SLOW_SYNC, SLOW_SYNC,   # trial 1
             FAST_SYNC, SLOW_SYNC,   # trial 2: mismatched pairing
             SLOW_SYNC, SLOW_SYNC]   # trial 3
    est = diff_estimate_seconds(make_run_group(syncs), reps=20)
    assert est.seconds == pytest.approx(PER_CALL, rel=1e-9)
    # the legacy statistic WOULD have reported the artifact:
    g1, g2 = 3, 17
    biased = PER_CALL - (SLOW_SYNC - FAST_SYNC) / (g2 - g1)
    assert est.minimum == pytest.approx(biased, rel=1e-9)
    assert est.minimum < 0.8 * est.seconds


def test_fallback_when_below_sync_noise():
    # per-call time of zero: every difference is the sync jitter, the
    # median diff is non-positive -> fallback reusing the collected g2
    # samples (NO extra group run — the iterator has exactly 8 entries)
    syncs = [SLOW_SYNC, SLOW_SYNC, FAST_SYNC, SLOW_SYNC,
             SLOW_SYNC, FAST_SYNC, SLOW_SYNC, SLOW_SYNC]

    def run_group(g):
        return next(it)
    it = iter(syncs)
    est = diff_estimate_seconds(run_group, reps=20)
    assert est.fallback
    assert math.isfinite(est.seconds)
    assert "pipelined median" in est.label
    assert est.seconds == pytest.approx(SLOW_SYNC / 17, rel=1e-9)


def test_even_split_stays_on_majority_mode():
    # 2-2 fast/slow split inside the g2 samples: a plain median would
    # average the modes and skew the estimate ~1.4 ms/call at bench
    # sizes; median_high is a real slow-mode sample, so the difference
    # still cancels exactly (review r5 finding).
    syncs = [FAST_SYNC, SLOW_SYNC,   # trial 0: g2, g1
             SLOW_SYNC, SLOW_SYNC,   # trial 1
             FAST_SYNC, SLOW_SYNC,   # trial 2
             SLOW_SYNC, SLOW_SYNC]   # trial 3
    est = diff_estimate_seconds(make_run_group(syncs), reps=20)
    assert est.seconds == pytest.approx(PER_CALL, rel=1e-9)


def test_unpacking_protocol_preserved():
    sec, spread, fallback = diff_estimate_seconds(
        make_run_group([SLOW_SYNC] * 8), reps=20)
    assert sec == pytest.approx(PER_CALL, rel=1e-9)
    assert spread == 0.0
    assert fallback is False

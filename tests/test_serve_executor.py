"""Serving executor: the bit-identical concurrency contract plus every
flow-control path (deadline, queue-full, degradation, shutdown).

The load-bearing test is the concurrency fuzz: 8 submitter threads x
mixed signatures against per-request serial oracles with EXACT equality
— any relaxation here would let the fused batched path drift from the
serial path silently. The fused path must also demonstrably engage
(at least one fused batch >= 2 in metrics).
"""

import threading
import time

import numpy as np
import pytest

import jax

from spfft_tpu import Scaling, TransformType
from spfft_tpu.errors import (DeadlineExpiredError, InvalidParameterError,
                              QueueFullError, ServeError)
from spfft_tpu.serve import (PlanRegistry, ServeExecutor, ServeMetrics,
                             percentile)

from test_util import hermitian_triplets, random_sparse_triplets

DIMS = (12, 13, 11)


def _registry_with(seeds, precision="double", ttype=TransformType.C2C):
    reg = PlanRegistry()
    sigs = []
    for s in seeds:
        rng = np.random.default_rng(s)
        t = (hermitian_triplets(rng, DIMS)
             if ttype == TransformType.R2C
             else random_sparse_triplets(rng, DIMS))
        sig, _ = reg.get_or_build(ttype, *DIMS, t, precision=precision)
        sigs.append(sig)
    return reg, sigs


def _values_for(reg, sig, rng):
    n = reg.get(sig).index_plan.num_values
    return (rng.uniform(-1, 1, n) + 1j * rng.uniform(-1, 1, n))


def test_single_request_matches_plan_backward():
    reg, (sig,) = _registry_with([1])
    rng = np.random.default_rng(0)
    v = _values_for(reg, sig, rng)
    with ServeExecutor(reg) as ex:
        got = np.asarray(ex.submit(sig, v).result())
    expect = np.asarray(reg.get(sig).backward(v))
    assert np.array_equal(got, expect)


def test_forward_request_with_scaling():
    reg, (sig,) = _registry_with([1])
    rng = np.random.default_rng(0)
    plan = reg.get(sig)
    space = np.asarray(plan.backward(_values_for(reg, sig, rng)))
    with ServeExecutor(reg) as ex:
        got = np.asarray(ex.submit_forward(sig, space,
                                           Scaling.FULL).result())
    expect = np.asarray(plan.forward(space, Scaling.FULL))
    assert np.array_equal(got, expect)


def test_fused_batch_bitexact_and_observed():
    """A staged full bucket executes fused (metrics prove it) and every
    result equals the serial per-request execution bit-for-bit."""
    reg, (sig,) = _registry_with([1])
    rng = np.random.default_rng(7)
    vals = [_values_for(reg, sig, rng) for _ in range(8)]
    plan = reg.get(sig)
    oracles = [np.asarray(plan.backward(v)) for v in vals]
    ex = ServeExecutor(reg, autostart=False, batch_window=0.0)
    futures = [ex.submit(sig, v) for v in vals]
    ex.start()
    results = [np.asarray(f.result()) for f in futures]
    ex.close()
    for got, expect in zip(results, oracles):
        assert np.array_equal(got, expect)
    assert ex.metrics.fused_batches >= 1
    assert ex.metrics.max_fused_batch_size >= 2


def test_concurrency_fuzz_mixed_signatures():
    """8 submitter threads x 96 mixed-signature requests == the serial
    oracle, exactly; >= 1 fused batch of >= 2 observed (acceptance
    criterion). Requests are staged before the dispatcher starts so
    full same-signature buckets are guaranteed to form, then submitted
    concurrently while the dispatcher drains — both the staged and the
    racing arrivals must hold the contract."""
    reg, sigs = _registry_with([1, 2, 3])
    rng = np.random.default_rng(42)
    requests = []  # (sig, kind, scaling, payload, oracle)
    for i in range(96):
        sig = sigs[int(rng.integers(len(sigs)))]
        plan = reg.get(sig)
        v = _values_for(reg, sig, rng)
        if rng.random() < 0.5:
            requests.append((sig, "backward", Scaling.NONE, v,
                             np.asarray(plan.backward(v))))
        else:
            space = np.asarray(plan.backward(v))
            scl = Scaling.FULL if rng.random() < 0.5 else Scaling.NONE
            requests.append((sig, "forward", scl, space,
                             np.asarray(plan.forward(space, scl))))

    ex = ServeExecutor(reg, autostart=False, batch_window=0.001)
    futures = [None] * len(requests)
    errors = []
    # stage the first third (guarantees formed buckets); the 8 threads
    # then race >= 64 submissions against the draining dispatcher
    for i in range(32):
        sig, kind, scl, payload, _ = requests[i]
        futures[i] = ex.submit(sig, payload, kind, scaling=scl)

    def submitter(indices):
        for i in indices:
            sig, kind, scl, payload, _ = requests[i]
            try:
                futures[i] = ex.submit(sig, payload, kind, scaling=scl)
            except Exception as exc:  # pragma: no cover
                errors.append(exc)

    threads = [threading.Thread(target=submitter,
                                args=(range(32 + k, 96, 8),))
               for k in range(8)]
    ex.start()
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert not errors
    for i, (sig, kind, scl, payload, oracle) in enumerate(requests):
        got = np.asarray(futures[i].result(timeout=60))
        assert np.array_equal(got, oracle), \
            f"request {i} ({kind}) diverged from its serial oracle"
    ex.close()
    assert ex.metrics.fused_batches >= 1
    assert ex.metrics.max_fused_batch_size >= 2
    snap = ex.metrics.snapshot(reg)
    assert snap["completed"] == 96
    assert snap["failed"] == 0


def test_batching_disabled_degrades_serial():
    reg, (sig,) = _registry_with([1])
    rng = np.random.default_rng(3)
    vals = [_values_for(reg, sig, rng) for _ in range(8)]
    plan = reg.get(sig)
    oracles = [np.asarray(plan.backward(v)) for v in vals]
    ex = ServeExecutor(reg, batching=False, autostart=False)
    futures = [ex.submit(sig, v) for v in vals]
    ex.start()
    for f, expect in zip(futures, oracles):
        assert np.array_equal(np.asarray(f.result()), expect)
    ex.close()
    assert ex.metrics.fused_batches == 0


def test_device_pool_results_bitexact():
    """Round-robin across the virtual CPU pool returns the same bits as
    default-device execution (same executable, different placement)."""
    reg, (sig,) = _registry_with([1])
    rng = np.random.default_rng(9)
    vals = [_values_for(reg, sig, rng) for _ in range(6)]
    plan = reg.get(sig)
    oracles = [np.asarray(plan.backward(v)) for v in vals]
    ex = ServeExecutor(reg, devices="all", batching=False,
                       autostart=False)
    assert len(ex._devices) == len(jax.devices())
    futures = [ex.submit(sig, v) for v in vals]
    ex.start()
    for f, expect in zip(futures, oracles):
        assert np.array_equal(np.asarray(f.result()), expect)
    ex.close()


def test_deadline_expired():
    reg, (sig,) = _registry_with([1])
    rng = np.random.default_rng(4)
    v = _values_for(reg, sig, rng)
    ex = ServeExecutor(reg, autostart=False)
    fut = ex.submit(sig, v, timeout=0.005)
    time.sleep(0.05)  # expires while the dispatcher is not running
    ex.start()
    with pytest.raises(DeadlineExpiredError):
        fut.result(timeout=30)
    ex.close()
    assert ex.metrics.snapshot()["expired_deadline"] == 1


def test_queue_full_backpressure():
    reg, (sig,) = _registry_with([1])
    rng = np.random.default_rng(5)
    v = _values_for(reg, sig, rng)
    ex = ServeExecutor(reg, max_queue=4, autostart=False)
    futures = [ex.submit(sig, v) for _ in range(4)]
    with pytest.raises(QueueFullError):
        ex.submit(sig, v)
    assert ex.metrics.snapshot()["rejected_queue_full"] == 1
    ex.start()
    for f in futures:
        f.result(timeout=30)
    ex.close()


def test_unknown_signature_rejected_at_submit():
    reg, sigs = _registry_with([1])
    other_reg, (foreign,) = _registry_with([2])
    with ServeExecutor(reg) as ex:
        with pytest.raises(InvalidParameterError):
            ex.submit(foreign, np.zeros(4))


def test_submit_after_close_raises_and_drain_completes():
    reg, (sig,) = _registry_with([1])
    rng = np.random.default_rng(6)
    v = _values_for(reg, sig, rng)
    ex = ServeExecutor(reg)
    fut = ex.submit(sig, v)
    ex.close()
    assert fut.done()
    with pytest.raises(ServeError):
        ex.submit(sig, v)


def test_close_without_drain_fails_pending():
    reg, (sig,) = _registry_with([1])
    rng = np.random.default_rng(6)
    ex = ServeExecutor(reg, autostart=False)
    fut = ex.submit(sig, _values_for(reg, sig, rng))
    ex.close(drain=False)
    with pytest.raises(ServeError):
        fut.result(timeout=5)


def test_bad_request_fails_future_not_executor():
    """A malformed payload fails ITS future; the executor keeps
    serving."""
    reg, (sig,) = _registry_with([1])
    rng = np.random.default_rng(8)
    good = _values_for(reg, sig, rng)
    with ServeExecutor(reg) as ex:
        bad = ex.submit(sig, np.zeros(3))  # wrong length
        with pytest.raises(Exception):
            bad.result(timeout=30)
        ok = ex.submit(sig, good)
        expect = np.asarray(reg.get(sig).backward(good))
        assert np.array_equal(np.asarray(ok.result(timeout=30)), expect)


def test_metrics_latency_and_timing_integration():
    from spfft_tpu import timing
    reg, (sig,) = _registry_with([1])
    rng = np.random.default_rng(2)
    timing.GlobalTimer.reset()
    timing.enable()
    try:
        with ServeExecutor(reg) as ex:
            for _ in range(4):
                ex.submit(sig, _values_for(reg, sig, rng)).result()
    finally:
        timing.disable()
    rows = timing.GlobalTimer.process()._rows()
    serve_rows = [r for r in rows if r["label"] == "serve.request"]
    assert serve_rows and serve_rows[0]["count"] == 4
    lat = ServeMetrics().latency_percentiles()
    assert lat == {"p50": 0.0, "p95": 0.0, "p99": 0.0}


def test_percentile_nearest_rank():
    samples = [1.0, 2.0, 3.0, 4.0]
    assert percentile(samples, 50.0) == 2.0
    assert percentile(samples, 99.0) == 4.0
    assert percentile([], 50.0) == 0.0


def test_padded_ladder():
    reg, _ = _registry_with([1])
    ex = ServeExecutor(reg, max_batch=8, autostart=False)
    assert [ex._padded_size(b) for b in (1, 2, 3, 5, 8)] == [2, 2, 4, 8, 8]
    ex.close()
    ex6 = ServeExecutor(reg, max_batch=6, autostart=False)
    assert ex6._padded_size(5) == 6
    ex6.close()

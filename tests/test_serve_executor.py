"""Serving executor: the bit-identical concurrency contract plus every
flow-control path (deadline, queue-full, degradation, shutdown) and the
adaptive dispatch machinery (priority lanes, EDF, batch-shape pinning,
staged host buffers).

The load-bearing test is the concurrency fuzz: 8 submitter threads x
mixed signatures x mixed PRIORITIES with aggressive pinning
(pin_after=1) against per-request serial oracles with EXACT equality —
any relaxation here would let the fused batched path (padded ladder OR
pinned exact shapes, staged through reusable host buffers) drift from
the serial path silently. The fused path must also demonstrably engage
(at least one fused batch >= 2 in metrics).
"""

import threading
import time

import numpy as np
import pytest

import jax

from spfft_tpu import Scaling, TransformType
from spfft_tpu.errors import (DeadlineExpiredError, InvalidParameterError,
                              QueueFullError, ServeError)
from spfft_tpu.serve import (PlanRegistry, ServeExecutor, ServeMetrics,
                             percentile)

from test_util import hermitian_triplets, random_sparse_triplets

DIMS = (12, 13, 11)


def _registry_with(seeds, precision="double", ttype=TransformType.C2C):
    reg = PlanRegistry()
    sigs = []
    for s in seeds:
        rng = np.random.default_rng(s)
        t = (hermitian_triplets(rng, DIMS)
             if ttype == TransformType.R2C
             else random_sparse_triplets(rng, DIMS))
        sig, _ = reg.get_or_build(ttype, *DIMS, t, precision=precision)
        sigs.append(sig)
    return reg, sigs


def _values_for(reg, sig, rng):
    n = reg.get(sig).index_plan.num_values
    return (rng.uniform(-1, 1, n) + 1j * rng.uniform(-1, 1, n))


def test_single_request_matches_plan_backward():
    reg, (sig,) = _registry_with([1])
    rng = np.random.default_rng(0)
    v = _values_for(reg, sig, rng)
    with ServeExecutor(reg) as ex:
        got = np.asarray(ex.submit(sig, v).result())
    expect = np.asarray(reg.get(sig).backward(v))
    assert np.array_equal(got, expect)


def test_forward_request_with_scaling():
    reg, (sig,) = _registry_with([1])
    rng = np.random.default_rng(0)
    plan = reg.get(sig)
    space = np.asarray(plan.backward(_values_for(reg, sig, rng)))
    with ServeExecutor(reg) as ex:
        got = np.asarray(ex.submit_forward(sig, space,
                                           Scaling.FULL).result())
    expect = np.asarray(plan.forward(space, Scaling.FULL))
    assert np.array_equal(got, expect)


def test_fused_batch_bitexact_and_observed():
    """A staged full bucket executes fused (metrics prove it) and every
    result equals the serial per-request execution bit-for-bit."""
    reg, (sig,) = _registry_with([1])
    rng = np.random.default_rng(7)
    vals = [_values_for(reg, sig, rng) for _ in range(8)]
    plan = reg.get(sig)
    oracles = [np.asarray(plan.backward(v)) for v in vals]
    ex = ServeExecutor(reg, autostart=False, batch_window=0.0)
    futures = [ex.submit(sig, v) for v in vals]
    ex.start()
    results = [np.asarray(f.result()) for f in futures]
    ex.close()
    for got, expect in zip(results, oracles):
        assert np.array_equal(got, expect)
    assert ex.metrics.fused_batches >= 1
    assert ex.metrics.max_fused_batch_size >= 2


def test_concurrency_fuzz_mixed_signatures():
    """8 submitter threads x 96 mixed-signature, mixed-PRIORITY requests
    == the serial oracle, exactly; >= 1 fused batch of >= 2 observed
    (acceptance criterion). ``pin_after=1`` makes the observer pin
    aggressively, so racy bucket sizes exercise the pinned exact-shape
    dispatch path alongside the pow2 ladder — neither may perturb
    results. Requests are staged before the dispatcher starts so full
    same-signature buckets are guaranteed to form, then submitted
    concurrently while the dispatcher drains — both the staged and the
    racing arrivals must hold the contract."""
    reg, sigs = _registry_with([1, 2, 3])
    rng = np.random.default_rng(42)
    requests = []  # (sig, kind, scaling, priority, payload, oracle)
    for i in range(96):
        sig = sigs[int(rng.integers(len(sigs)))]
        plan = reg.get(sig)
        v = _values_for(reg, sig, rng)
        prio = "high" if rng.random() < 0.3 else "normal"
        if rng.random() < 0.5:
            requests.append((sig, "backward", Scaling.NONE, prio, v,
                             np.asarray(plan.backward(v))))
        else:
            space = np.asarray(plan.backward(v))
            scl = Scaling.FULL if rng.random() < 0.5 else Scaling.NONE
            requests.append((sig, "forward", scl, prio, space,
                             np.asarray(plan.forward(space, scl))))

    ex = ServeExecutor(reg, autostart=False, batch_window=0.001,
                       pin_after=1)
    futures = [None] * len(requests)
    errors = []
    # stage the first third (guarantees formed buckets); the 8 threads
    # then race >= 64 submissions against the draining dispatcher
    for i in range(32):
        sig, kind, scl, prio, payload, _ = requests[i]
        futures[i] = ex.submit(sig, payload, kind, scaling=scl,
                               priority=prio)

    def submitter(indices):
        for i in indices:
            sig, kind, scl, prio, payload, _ = requests[i]
            try:
                futures[i] = ex.submit(sig, payload, kind, scaling=scl,
                                       priority=prio)
            except Exception as exc:  # pragma: no cover
                errors.append(exc)

    threads = [threading.Thread(target=submitter,
                                args=(range(32 + k, 96, 8),))
               for k in range(8)]
    ex.start()
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert not errors
    for i, (sig, kind, scl, prio, payload, oracle) in enumerate(requests):
        got = np.asarray(futures[i].result(timeout=60))
        assert np.array_equal(got, oracle), \
            f"request {i} ({kind}, {prio}) diverged from its serial oracle"
    ex.close()
    assert ex.metrics.fused_batches >= 1
    assert ex.metrics.max_fused_batch_size >= 2
    snap = ex.metrics.snapshot(reg)
    assert snap["completed"] == 96
    assert snap["failed"] == 0
    assert (snap["completed_by_class"]["high"]
            + snap["completed_by_class"]["normal"]) == 96
    assert snap["completed_by_class"]["high"] > 0


def test_batching_disabled_degrades_serial():
    reg, (sig,) = _registry_with([1])
    rng = np.random.default_rng(3)
    vals = [_values_for(reg, sig, rng) for _ in range(8)]
    plan = reg.get(sig)
    oracles = [np.asarray(plan.backward(v)) for v in vals]
    ex = ServeExecutor(reg, batching=False, autostart=False)
    futures = [ex.submit(sig, v) for v in vals]
    ex.start()
    for f, expect in zip(futures, oracles):
        assert np.array_equal(np.asarray(f.result()), expect)
    ex.close()
    assert ex.metrics.fused_batches == 0


def test_device_pool_results_bitexact():
    """Round-robin across the virtual CPU pool returns the same bits as
    default-device execution (same executable, different placement)."""
    reg, (sig,) = _registry_with([1])
    rng = np.random.default_rng(9)
    vals = [_values_for(reg, sig, rng) for _ in range(6)]
    plan = reg.get(sig)
    oracles = [np.asarray(plan.backward(v)) for v in vals]
    ex = ServeExecutor(reg, devices="all", batching=False,
                       autostart=False)
    assert len(ex._devices) == len(jax.devices())
    futures = [ex.submit(sig, v) for v in vals]
    ex.start()
    for f, expect in zip(futures, oracles):
        assert np.array_equal(np.asarray(f.result()), expect)
    ex.close()


def test_deadline_expired():
    reg, (sig,) = _registry_with([1])
    rng = np.random.default_rng(4)
    v = _values_for(reg, sig, rng)
    ex = ServeExecutor(reg, autostart=False)
    fut = ex.submit(sig, v, timeout=0.005)
    time.sleep(0.05)  # expires while the dispatcher is not running
    ex.start()
    with pytest.raises(DeadlineExpiredError):
        fut.result(timeout=30)
    ex.close()
    assert ex.metrics.snapshot()["expired_deadline"] == 1


def test_queue_full_backpressure():
    reg, (sig,) = _registry_with([1])
    rng = np.random.default_rng(5)
    v = _values_for(reg, sig, rng)
    ex = ServeExecutor(reg, max_queue=4, autostart=False)
    futures = [ex.submit(sig, v) for _ in range(4)]
    with pytest.raises(QueueFullError):
        ex.submit(sig, v)
    assert ex.metrics.snapshot()["rejected_queue_full"] == 1
    ex.start()
    for f in futures:
        f.result(timeout=30)
    ex.close()


def test_unknown_signature_rejected_at_submit():
    reg, sigs = _registry_with([1])
    other_reg, (foreign,) = _registry_with([2])
    with ServeExecutor(reg) as ex:
        with pytest.raises(InvalidParameterError):
            ex.submit(foreign, np.zeros(4))


def test_submit_after_close_raises_and_drain_completes():
    reg, (sig,) = _registry_with([1])
    rng = np.random.default_rng(6)
    v = _values_for(reg, sig, rng)
    ex = ServeExecutor(reg)
    fut = ex.submit(sig, v)
    ex.close()
    assert fut.done()
    with pytest.raises(ServeError):
        ex.submit(sig, v)


def test_close_without_drain_fails_pending():
    reg, (sig,) = _registry_with([1])
    rng = np.random.default_rng(6)
    ex = ServeExecutor(reg, autostart=False)
    fut = ex.submit(sig, _values_for(reg, sig, rng))
    ex.close(drain=False)
    with pytest.raises(ServeError):
        fut.result(timeout=5)


def test_bad_request_fails_future_not_executor():
    """A malformed payload fails ITS future; the executor keeps
    serving."""
    reg, (sig,) = _registry_with([1])
    rng = np.random.default_rng(8)
    good = _values_for(reg, sig, rng)
    with ServeExecutor(reg) as ex:
        bad = ex.submit(sig, np.zeros(3))  # wrong length
        with pytest.raises(Exception):
            bad.result(timeout=30)
        ok = ex.submit(sig, good)
        expect = np.asarray(reg.get(sig).backward(good))
        assert np.array_equal(np.asarray(ok.result(timeout=30)), expect)


def test_metrics_latency_and_timing_integration():
    from spfft_tpu import timing
    reg, (sig,) = _registry_with([1])
    rng = np.random.default_rng(2)
    timing.GlobalTimer.reset()
    timing.enable()
    try:
        with ServeExecutor(reg) as ex:
            for _ in range(4):
                ex.submit(sig, _values_for(reg, sig, rng)).result()
    finally:
        timing.disable()
    rows = timing.GlobalTimer.process()._rows()
    serve_rows = [r for r in rows if r["label"] == "serve.request"]
    assert serve_rows and serve_rows[0]["count"] == 4
    lat = ServeMetrics().latency_percentiles()
    assert lat == {"p50": 0.0, "p95": 0.0, "p99": 0.0}


def test_percentile_nearest_rank():
    samples = [1.0, 2.0, 3.0, 4.0]
    assert percentile(samples, 50.0) == 2.0
    assert percentile(samples, 99.0) == 4.0
    assert percentile([], 50.0) == 0.0


def test_padded_ladder():
    reg, _ = _registry_with([1])
    ex = ServeExecutor(reg, max_batch=8, autostart=False)
    assert [ex._padded_size(b) for b in (1, 2, 3, 5, 8)] == [2, 2, 4, 8, 8]
    ex.close()
    ex6 = ServeExecutor(reg, max_batch=6, autostart=False)
    assert ex6._padded_size(5) == 6
    ex6.close()


# -- priority lanes ---------------------------------------------------------
def test_priority_high_served_before_staged_normals():
    """A high-priority request staged AFTER a full normal bucket for a
    different signature resolves first — the high lane preempts shard
    selection."""
    reg, (sig_a, sig_b) = _registry_with([1, 2])
    rng = np.random.default_rng(11)
    order = []
    ex = ServeExecutor(reg, autostart=False, batch_window=0.0)
    futs_a = [ex.submit(sig_a, _values_for(reg, sig_a, rng))
              for _ in range(4)]
    fut_b = ex.submit(sig_b, _values_for(reg, sig_b, rng),
                      priority="high")
    for i, f in enumerate(futs_a):
        f.add_done_callback(lambda _f, i=i: order.append(("A", i)))
    fut_b.add_done_callback(lambda _f: order.append(("B",)))
    ex.start()
    for f in futs_a + [fut_b]:
        f.result(timeout=30)
    ex.close()
    assert order[0] == ("B",)


def test_deadline_edf_selection_order():
    """Within a lane, a deadlined request staged AFTER a deadline-less
    one is served first (EDF; deadline-less requests keep FIFO order
    behind every deadlined one)."""
    reg, (sig_a, sig_b) = _registry_with([1, 2])
    rng = np.random.default_rng(12)
    order = []
    ex = ServeExecutor(reg, autostart=False, batch_window=0.0)
    fut_a = ex.submit(sig_a, _values_for(reg, sig_a, rng))
    fut_b = ex.submit(sig_b, _values_for(reg, sig_b, rng), timeout=30)
    fut_a.add_done_callback(lambda _f: order.append("A"))
    fut_b.add_done_callback(lambda _f: order.append("B"))
    ex.start()
    fut_a.result(timeout=30)
    fut_b.result(timeout=30)
    ex.close()
    assert order[0] == "B"


def test_bad_priority_rejected():
    reg, (sig,) = _registry_with([1])
    with ServeExecutor(reg, autostart=False) as ex:
        with pytest.raises(InvalidParameterError):
            ex.submit(sig, np.zeros(4), priority="urgent")


def test_per_class_latency_recorded():
    reg, (sig,) = _registry_with([1])
    rng = np.random.default_rng(13)
    with ServeExecutor(reg) as ex:
        ex.submit(sig, _values_for(reg, sig, rng)).result(timeout=30)
        ex.submit(sig, _values_for(reg, sig, rng),
                  priority="high").result(timeout=30)
    snap = ex.metrics.snapshot()
    assert snap["completed_by_class"] == {"high": 1, "normal": 1}
    assert snap["latency_seconds_by_class"]["high"]["p50"] > 0
    assert snap["latency_seconds_by_class"]["normal"]["p50"] > 0


# -- adaptive batch-shape pinning -------------------------------------------
def _run_waves(ex, reg, sig, sizes, rng):
    """Stage exact-size waves and drain synchronously (deterministic
    bucket sizes), checking every result bit-exact against the serial
    oracle."""
    plan = reg.get(sig)
    for size in sizes:
        vals = [_values_for(reg, sig, rng) for _ in range(size)]
        oracles = [np.asarray(plan.backward(v)) for v in vals]
        futures = [ex.submit(sig, v) for v in vals]
        ex._drain_once()
        for f, expect in zip(futures, oracles):
            assert np.array_equal(np.asarray(f.result(timeout=30)),
                                  expect)


def test_pinning_stable_size_drops_pad_rows():
    """Five waves of 5 (not a power of two): the first pin_after=3
    waves pad 5 -> 8, then the exact shape pins and pad rows stop. All
    results stay bit-identical to the serial oracle (checked inside
    _run_waves)."""
    reg, (sig,) = _registry_with([1])
    rng = np.random.default_rng(21)
    ex = ServeExecutor(reg, autostart=False, batch_window=0.0,
                       pin_after=3)
    _run_waves(ex, reg, sig, [5, 5, 5, 5, 5], rng)
    ex.close()
    assert ex.metrics.padded_rows == 2 * 3  # waves 1-2 padded 5 -> 8
    assert ex.metrics.pinned_batches == 3   # waves 3-5 exact
    assert ex.pinned_shapes(sig) == (5,)
    # staging buffers were checked out and returned, not leaked: the
    # free-lists hold at most one buffer per (key, shape)
    assert all(len(v) <= 1 for v in ex._staging.values())


def test_pinning_churn_never_pins():
    """Alternating bucket sizes never build a streak, so the observer
    never pins and every bucket rides the pow2 ladder."""
    reg, (sig,) = _registry_with([1])
    rng = np.random.default_rng(22)
    ex = ServeExecutor(reg, autostart=False, batch_window=0.0,
                       pin_after=3)
    _run_waves(ex, reg, sig, [3, 5, 3, 5, 3, 5], rng)
    ex.close()
    assert ex.metrics.pinned_batches == 0
    assert ex.pinned_shapes(sig) == ()
    # 3 pads to 4 (1 row), 5 pads to 8 (3 rows)
    assert ex.metrics.padded_rows == 3 * 1 + 3 * 3


def test_pinning_disabled_keeps_ladder():
    reg, (sig,) = _registry_with([1])
    rng = np.random.default_rng(23)
    ex = ServeExecutor(reg, autostart=False, batch_window=0.0,
                       pin_after=0)
    _run_waves(ex, reg, sig, [5, 5, 5], rng)
    ex.close()
    assert ex.metrics.pinned_batches == 0
    assert ex.metrics.padded_rows == 3 * 3


def test_pinned_shape_lru_bounded():
    """More stable shapes than max_pinned_shapes: the LRU evicts the
    oldest pin; results stay exact throughout."""
    reg, (sig,) = _registry_with([1])
    rng = np.random.default_rng(24)
    ex = ServeExecutor(reg, autostart=False, batch_window=0.0,
                       pin_after=1, max_pinned_shapes=2)
    _run_waves(ex, reg, sig, [3, 5, 6, 7], rng)
    ex.close()
    pins = ex.pinned_shapes(sig)
    assert len(pins) == 2
    assert pins == (6, 7)  # 3 and 5 evicted oldest-first


def test_fused_serial_histograms_split():
    """A serial bucket of size >= 2 (batching off) lands in the serial
    histogram and cannot inflate max_fused_batch_size."""
    reg, (sig,) = _registry_with([1])
    rng = np.random.default_rng(25)
    ex = ServeExecutor(reg, autostart=False, batching=False)
    futures = [ex.submit(sig, _values_for(reg, sig, rng))
               for _ in range(4)]
    ex._drain_once()
    for f in futures:
        f.result(timeout=30)
    ex.close()
    assert ex.metrics.max_fused_batch_size == 0
    snap = ex.metrics.snapshot()
    assert snap["serial_batch_histogram"] == {"4": 1}
    assert snap["fused_batch_histogram"] == {}
    assert snap["batch_size_histogram"] == {"4": 1}


def test_latency_reservoir_bounded():
    from spfft_tpu.serve.metrics import ServeMetrics
    m = ServeMetrics(latency_window=8)
    for i in range(100):
        m.record_request_done(float(i + 1))
    snap = m.snapshot()
    assert snap["completed"] == 100           # lifetime counter exact
    assert snap["latency_count"] == 8         # reservoir bounded
    # percentiles read the recent window only (samples 93..100)
    assert m.latency_percentiles()["p50"] >= 93.0


def test_submit_distributed_plan_rejected_typed():
    """A DistributedTransformPlan in the registry is rejected AT SUBMIT
    with the typed DistributedPlanUnsupportedError (previously an
    undefined path failing deep in dispatch — ROADMAP: 'local plans
    only take the device-pool path')."""
    from spfft_tpu.errors import (DistributedPlanUnsupportedError,
                                  ErrorCode)
    from spfft_tpu.parallel import make_distributed_plan, make_mesh
    from spfft_tpu.serve import signature_for
    from spfft_tpu.utils.workloads import (even_plane_split,
                                           round_robin_stick_partition)

    rng = np.random.default_rng(0)
    t = random_sparse_triplets(rng, DIMS)
    S = 2
    parts = round_robin_stick_partition(t, DIMS, S)
    planes = even_plane_split(DIMS[2], S)
    dplan = make_distributed_plan(TransformType.C2C, *DIMS, parts, planes,
                                  mesh=make_mesh(S), precision="double")
    sig = signature_for(TransformType.C2C, *DIMS, t, precision="double",
                        device_count=S)
    reg = PlanRegistry()
    reg.put(sig, dplan)
    with ServeExecutor(reg, autostart=False) as ex:
        with pytest.raises(DistributedPlanUnsupportedError) as exc:
            ex.submit(sig, [np.zeros(p.num_values, np.complex128)
                            for p in dplan.dist_plan.shard_plans])
        assert exc.value.error_code() == ErrorCode.DISTRIBUTED_SUPPORT
        assert isinstance(exc.value, ServeError)
        # nothing was enqueued: the executor is still clean
        assert ex.metrics.snapshot()["completed"] == 0

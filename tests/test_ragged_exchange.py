"""The one-collective exact-count exchange (exchange.RaggedSchedule) —
COMPACT_BUFFERED's default mechanism since round 5.

The round-4 ppermute schedule paid up to 416 collective launches at
S=32 (its own scaling doc); ``jax.lax.ragged_all_to_all`` is the true
Alltoallv: ONE collective per direction at any shard count with exact
per-pair counts on the wire. XLA:CPU cannot execute the op, so off-TPU
the collective is emulated (all_gather + plan-time gather) through the
SAME pack/unpack tables — these tests cover numerics via the emulation,
the real op via lowering (launch-count invariance), and the wire model
at the table level.
"""

import re

import numpy as np
import pytest

import jax

from spfft_tpu import ExchangeType, Scaling, TransformType
from spfft_tpu.parallel import make_distributed_plan, make_mesh
from spfft_tpu.parallel.exchange import build_ragged_schedule

from test_distributed import SCENARIOS, split_by_sticks, split_planes
from test_util import (dense_backward, dense_cube_from_values,
                       random_sparse_triplets, random_values, sample_cube,
                       tolerance_for)


def _make_plan(dims, parts, planes, **kw):
    kw.setdefault("exchange", ExchangeType.COMPACT_BUFFERED)
    kw.setdefault("precision", "double")
    return make_distributed_plan(TransformType.C2C, *dims, parts, planes,
                                 mesh=make_mesh(len(parts)), **kw)


def _skewed_setup(rng, dims=(11, 12, 13), sw=(1, 3, 0, 2), pw=(4, 1, 1, 2)):
    triplets = random_sparse_triplets(rng, dims)
    parts = split_by_sticks(triplets, dims, list(sw))
    planes = split_planes(dims[2], list(pw))
    return triplets, parts, planes


def test_default_compact_is_ragged():
    rng = np.random.default_rng(1)
    _, parts, planes = _skewed_setup(rng)
    plan = _make_plan((11, 12, 13), parts, planes)
    assert plan._ragged is not None and plan._compact is None


def test_ragged_matches_ppermute_schedule(monkeypatch):
    """Same plan, both compact mechanisms: identical numerics on a
    skewed scenario (the emulated ragged collective and the ppermute
    schedule must be interchangeable implementations of Alltoallv)."""
    rng = np.random.default_rng(2)
    dims = (11, 12, 13)
    triplets, parts, planes = _skewed_setup(rng)
    values = [random_values(rng, len(p)) for p in parts]
    plan_r = _make_plan(dims, parts, planes)
    monkeypatch.setenv("SPFFT_TPU_COMPACT_PPERMUTE", "1")
    plan_p = _make_plan(dims, parts, planes)
    assert plan_p._compact is not None
    sr = plan_r.backward(values)
    sp = plan_p.backward(values)
    np.testing.assert_allclose(np.asarray(sr), np.asarray(sp),
                               atol=1e-12, rtol=0)
    vr = plan_r.unshard_values(plan_r.forward(sr, Scaling.FULL))
    vp = plan_p.unshard_values(plan_p.forward(sp, Scaling.FULL))
    for a, b in zip(vr, vp):
        np.testing.assert_allclose(a, b, atol=1e-12, rtol=0)


@pytest.mark.parametrize("scenario", sorted(SCENARIOS))
def test_ragged_round_trip_all_scenarios(scenario):
    """Oracle round trip through the ragged tables for every
    distribution scenario (incl. empty shards)."""
    rng = np.random.default_rng(3)
    dims = (11, 12, 13)
    sw, pw = SCENARIOS[scenario]
    triplets = random_sparse_triplets(rng, dims)
    parts = split_by_sticks(triplets, dims, sw)
    planes = split_planes(dims[2], pw)
    values = random_values(rng, len(triplets))
    cube = dense_cube_from_values(triplets, values, dims)
    plan = _make_plan(dims, parts, planes)
    if plan.dist_plan.num_shards > 1:
        assert plan._ragged is not None
    values_parts = [sample_cube(cube, p, dims) for p in parts]
    space = plan.backward(values_parts)
    got = np.concatenate([s for s in plan.unshard_space(space) if s.size],
                         axis=0)
    np.testing.assert_allclose(got, dense_backward(cube),
                               atol=tolerance_for("double", got), rtol=0)
    back = plan.unshard_values(plan.forward(space, Scaling.FULL))
    for g, v in zip(back, values_parts):
        np.testing.assert_allclose(g, v, atol=1e-10, rtol=0)


def test_launch_count_is_shard_invariant(monkeypatch):
    """THE launch-scalability property (round-4 verdict item 2): the
    fused pair program contains exactly ONE ragged_all_to_all per
    direction — 2 total — at S=4 and S=8 alike (the ppermute schedule
    grew as hops x size classes, up to 416 at S=32)."""
    monkeypatch.setenv("SPFFT_TPU_FORCE_RAGGED_OP", "1")
    rng = np.random.default_rng(4)
    counts = {}
    for S in (4, 8):
        dims = (10, 9, 16)
        triplets = random_sparse_triplets(rng, dims)
        parts = split_by_sticks(triplets, dims,
                                [2, 1, 1, 3, 1, 2, 1, 1][:S])
        planes = split_planes(dims[2], [1, 2, 1, 1, 2, 1, 1, 2][:S])
        plan = _make_plan(dims, parts, planes)
        values = plan.shard_values(
            [random_values(rng, len(p)) for p in parts])
        txt = plan._backward_jit.lower(values,
                                       *plan._device_tables).as_text()
        n_ragged = len(re.findall(r"ragged_all_to_all", txt))
        assert n_ragged == 1, f"S={S}: backward lowered {n_ragged} ragged ops"
        assert "all_gather" not in txt  # the real op, not the emulation
        assert "stablehlo.all_to_all" not in txt
        # the fused PAIR program (both directions): exactly 2 collectives
        import functools
        pair_jit = jax.jit(plan._pair_shmap(0)(functools.partial(
            plan._pair_body, scaled=True, fn=None)))
        pair_txt = pair_jit.lower(values, *plan._device_tables).as_text()
        assert len(re.findall(r"ragged_all_to_all", pair_txt)) == 2, \
            f"S={S}: pair program not 2 ragged collectives"
        counts[S] = n_ragged
    assert counts[4] == counts[8] == 1


def test_wire_model_is_exact_alltoallv():
    """RaggedSchedule.wire_elements == the exact per-pair Alltoallv sum
    (independent recompute from stick/plane counts) — no bucket factor,
    and never above the padded layout."""
    rng = np.random.default_rng(5)
    dims = (11, 12, 13)
    _, parts, planes = _skewed_setup(rng)
    plan = _make_plan(dims, parts, planes)
    sched = plan._ragged
    dp = plan.dist_plan
    ns = [p.num_sticks for p in dp.shard_plans]
    npl = list(dp.num_planes)
    S = dp.num_shards
    exact = sum(ns[j] * npl[d] for j in range(S) for d in range(S)
                if j != d)
    assert sched.wire_elements() == exact
    padded = S * (S - 1) * dp.max_sticks * dp.max_planes
    assert sched.wire_elements() <= padded
    busiest = max(max(sum(ns[j] * npl[d] for d in range(S) if d != j),
                      sum(ns[d] * npl[j] for d in range(S) if d != j))
                  for j in range(S))
    assert sched.busiest_link_elements() == busiest


def test_offset_tables_simulate_to_identity():
    """Numpy simulation of the documented ragged_all_to_all semantics
    over the schedule's offset vectors must land every element exactly
    where the emulation table puts it (the two table families are built
    independently along different index paths)."""
    rng = np.random.default_rng(6)
    dims = (10, 9, 11)
    _, parts, planes = _skewed_setup(rng, dims=dims)
    plan = _make_plan(dims, parts, planes)
    sched = plan._ragged
    S, cap, rcap = sched.num_shards, sched.send_cap, sched.recv_cap
    for offs, emu in ((sched.bwd_offsets, sched.emu_bwd),
                      (sched.fwd_offsets, sched.emu_fwd)):
        io, ss, oo, rs = (np.asarray(a, np.int64) for a in offs)
        sends = rng.standard_normal((S, cap))
        recv = np.zeros((S, rcap))
        for j in range(S):
            for d in range(S):
                n = ss[j, d]
                recv[d, oo[j, d]:oo[j, d] + n] = \
                    sends[j, io[j, d]:io[j, d] + n]
        flat = sends.reshape(-1)
        emu_recv = np.zeros((S, rcap))
        for d in range(S):
            valid = emu[d] < S * cap
            emu_recv[d, valid] = flat[emu[d][valid]]
        np.testing.assert_array_equal(recv, emu_recv)


def test_single_precision_and_float_wire():
    """bf16-wire single-precision ragged path stays within the float
    wire tolerance (reference *_FLOAT exchange class)."""
    rng = np.random.default_rng(7)
    dims = (11, 12, 13)
    triplets, parts, planes = _skewed_setup(rng)
    values = [random_values(rng, len(p)).astype(np.complex64)
              for p in parts]
    plan = _make_plan(dims, parts, planes, precision="single",
                      exchange=ExchangeType.COMPACT_BUFFERED_FLOAT)
    assert plan._ragged is not None and plan._wire_dtype is not None
    exact = _make_plan(dims, parts, planes, precision="single")
    sf = np.asarray(plan.backward(values))
    se = np.asarray(exact.backward(values))
    rel = np.linalg.norm(sf - se) / max(np.linalg.norm(se), 1e-30)
    assert rel < 2e-2  # bf16 wire

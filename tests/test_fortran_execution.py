"""EXECUTE the Fortran declarations (no Fortran compiler in this image):
parse every ``bind(C)`` interface in include/spfft_tpu.f90 and call the
library through ctypes with argtypes derived ONLY from the f90-declared
kinds and value/pointer semantics — a kind-width mistake in a declaration
(e.g. c_int where the C ABI takes long long) then marshals wrongly and
the end-to-end drive fails, instead of passing a string match
(tests/test_fortran_bindings.py remains the declaration-level pin).

Reference parity: the reference compiles examples/example.f90 against its
module (reference: include/spfft/spfft.f90); this is the closest
executable check available without gfortran.
"""

import ctypes
import os
import re
import shutil
import subprocess

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
F90 = os.path.join(REPO, "include", "spfft_tpu.f90")
LIB = os.path.join(REPO, "lib", "libspfft_tpu.so")

pytestmark = pytest.mark.skipif(shutil.which("g++") is None,
                                reason="no C++ compiler")

#: f90 declaration -> (ctypes argtype, "value" | "out" | "array") —
#: exactly how a Fortran compiler would marshal each form.
_KIND_MAP = {
    ("integer(c_int)", "value"): (ctypes.c_int32, "value"),
    ("integer(c_long_long)", "value"): (ctypes.c_longlong, "value"),
    ("type(c_ptr)", "value"): (ctypes.c_void_p, "value"),
    ("integer(c_int)", "out"): (ctypes.POINTER(ctypes.c_int32), "out"),
    ("integer(c_long_long)", "out"): (ctypes.POINTER(ctypes.c_longlong),
                                      "out"),
    ("type(c_ptr)", "out"): (ctypes.POINTER(ctypes.c_void_p), "out"),
    ("integer(c_int)", "array"): (ctypes.POINTER(ctypes.c_int32), "array"),
    ("integer(c_long_long)", "array"): (ctypes.POINTER(ctypes.c_longlong),
                                        "array"),
    ("type(c_ptr)", "array"): (ctypes.POINTER(ctypes.c_void_p), "array"),
}


def parse_f90_interfaces():
    """-> {c_name: [(argname, argtype, kindclass), ...]} from the module's
    interface block, argument order taken from the function statement."""
    src = open(F90).read()
    # join continuation lines
    src = re.sub(r"&\s*\n\s*", " ", src)
    funcs = {}
    pat = re.compile(
        r"integer\(c_int\) function (\w+)\s*\(([^)]*)\)\s*"
        r'bind\(C, name="(\w+)"\)(.*?)end function', re.S)
    for m in pat.finditer(src):
        args = [a.strip() for a in m.group(2).split(",") if a.strip()]
        body = m.group(4)
        decls = {}
        for line in body.splitlines():
            line = line.strip()
            dm = re.match(r"(integer\(c_int\)|integer\(c_long_long\)|"
                          r"type\(c_ptr\))\s*(,[^:]*)?::\s*(.*)", line)
            if not dm:
                continue
            base, quals, names = dm.group(1), dm.group(2) or "", dm.group(3)
            if "dimension(*)" in quals:
                klass = "array"
            elif "intent(out)" in quals:
                klass = "out"
            elif "value" in quals:
                klass = "value"
            else:
                raise AssertionError(
                    f"{m.group(1)}: declaration without value/intent(out)/"
                    f"dimension(*): {line}")
            for nm in names.split(","):
                decls[nm.strip()] = _KIND_MAP[(base, klass)]
        ordered = []
        for a in args:
            assert a in decls, f"{m.group(1)}: argument {a} undeclared"
            ordered.append((a,) + decls[a])
        funcs[m.group(3)] = ordered
    return funcs


@pytest.fixture(scope="module")
def flib():
    subprocess.run(["make", "-s", "capi"], cwd=REPO, check=True,
                   capture_output=True, text=True)
    lib = ctypes.CDLL(LIB)
    sigs = parse_f90_interfaces()
    for name, args in sigs.items():
        fn = getattr(lib, name)  # declared symbol must exist
        fn.restype = ctypes.c_int32  # every f90 function is integer(c_int)
        fn.argtypes = [t for (_, t, _) in args]
    return lib, sigs


def test_every_declared_function_executes(flib):
    """Drive EVERY function the f90 module declares, through the f90
    widths, on a real plan; numeric checks catch mis-marshalled sizes."""
    lib, sigs = flib
    called = set()

    def call(name, *args):
        called.add(name)
        code = getattr(lib, name)(*args)
        assert code == 0, f"{name} -> {code}"

    assert lib.spfft_tpu_abi_version() == 2
    called.add("spfft_tpu_abi_version")
    call("spfft_tpu_init", None)

    n = 6
    tri = np.array([(x, y, z) for x in range(n) for y in range(n)
                    for z in range(n) if (x + y) % 2 == 0], np.int32)
    nv = len(tri)
    rng = np.random.default_rng(11)
    vals = rng.standard_normal((nv, 2)).astype(np.float32)

    plan = ctypes.c_void_p()
    call("spfft_tpu_plan_create", ctypes.byref(plan), 0, n, n, n,
         ctypes.c_longlong(nv),
         tri.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), 0, -1)

    # getters: values must round-trip through the declared out-widths
    out_i = ctypes.c_int32(0)
    out_ll = ctypes.c_longlong(0)
    for name, expect in [("spfft_tpu_plan_dim_x", n),
                         ("spfft_tpu_plan_dim_y", n),
                         ("spfft_tpu_plan_dim_z", n),
                         ("spfft_tpu_plan_transform_type", 0),
                         ("spfft_tpu_plan_num_shards", 1),
                         ("spfft_tpu_plan_exchange_type", None),
                         ("spfft_tpu_plan_pallas_active", None)]:
        call(name, plan, ctypes.byref(out_i))
        if expect is not None:
            assert out_i.value == expect, name
    for name, expect in [("spfft_tpu_plan_num_values", nv),
                         ("spfft_tpu_plan_global_size", n ** 3),
                         ("spfft_tpu_plan_num_global_elements", nv)]:
        call(name, plan, ctypes.byref(out_ll))
        assert out_ll.value == expect, name
    for name, expect in [("spfft_tpu_plan_local_z_offset", 0),
                         ("spfft_tpu_plan_local_z_length", n)]:
        call(name, plan, 0, ctypes.byref(out_i))
        assert out_i.value == expect, name
    for name, expect in [("spfft_tpu_plan_local_slice_size", n ** 3),
                         ("spfft_tpu_plan_num_local_elements", nv)]:
        call(name, plan, 0, ctypes.byref(out_ll))
        assert out_ll.value == expect, name

    space = np.zeros(2 * n ** 3, np.float32)
    out_vals = np.zeros_like(vals)
    fptr = ctypes.POINTER(ctypes.c_float)  # buffers pass as c_ptr (void*)

    def vp(arr):
        return ctypes.cast(arr.ctypes.data, ctypes.c_void_p)

    call("spfft_tpu_backward", plan, vp(vals), vp(space))
    call("spfft_tpu_forward", plan, vp(space), 1, vp(out_vals))
    np.testing.assert_allclose(out_vals, vals, atol=1e-5)
    out_vals[:] = 0
    call("spfft_tpu_execute_pair", plan, vp(vals), 1, vp(out_vals))
    np.testing.assert_allclose(out_vals, vals, atol=1e-5)

    # multi entries: two transforms on the same plan handle
    plans_arr = (ctypes.c_void_p * 2)(plan, plan)
    v2 = [vals.copy(), (vals * 2).astype(np.float32)]
    s2 = [np.zeros(2 * n ** 3, np.float32) for _ in range(2)]
    o2 = [np.zeros_like(vals) for _ in range(2)]
    varr = (ctypes.c_void_p * 2)(*[vp(v).value for v in v2])
    sarr = (ctypes.c_void_p * 2)(*[vp(s).value for s in s2])
    oarr = (ctypes.c_void_p * 2)(*[vp(o).value for o in o2])
    call("spfft_tpu_multi_backward", 2, plans_arr, varr, sarr)
    call("spfft_tpu_multi_forward", 2, plans_arr, sarr, 1, oarr)
    np.testing.assert_allclose(o2[0], vals, atol=1e-5)
    np.testing.assert_allclose(o2[1], vals * 2, atol=1e-5)

    # distributed create + per-shard getters through declared widths
    shards = 2
    sticks = sorted(set(map(tuple, tri[:, :2])))
    per = [[], []]
    for i, (x, y) in enumerate(sticks):
        for z in range(n):
            per[i % shards].append((x, y, z))
    trip_d = np.array(per[0] + per[1], np.int32)
    vps = np.array([len(per[0]), len(per[1])], np.int64)
    pps = np.array([n // 2, n - n // 2], np.int32)
    dplan = ctypes.c_void_p()
    call("spfft_tpu_plan_create_distributed", ctypes.byref(dplan), 0,
         n, n, n, shards,
         vps.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)),
         trip_d.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
         pps.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), 0, 0, -1)
    call("spfft_tpu_plan_num_shards", dplan, ctypes.byref(out_i))
    assert out_i.value == shards
    call("spfft_tpu_plan_local_z_length", dplan, 1, ctypes.byref(out_i))
    assert out_i.value == n - n // 2
    call("spfft_tpu_plan_destroy", dplan)
    call("spfft_tpu_plan_destroy", plan)

    missing = set(sigs) - called
    assert not missing, f"declared but never executed: {sorted(missing)}"

"""Distributed transforms on the virtual 8-device CPU mesh vs the dense
oracle.

Mirrors reference tests/mpi_tests/test_transform.cpp: the same dense-FFT
oracle, with distribution scenarios uniform / everything-on-shard-0 /
sticks-on-0-planes-on-last (test_transform.cpp:110-165), randomized
non-uniform stick assignment (generate_indices.hpp weight vectors), empty
shards, and the float-wire exchange variants."""

import numpy as np
import pytest

import jax

from spfft_tpu import ExchangeType, Scaling, TransformType
from spfft_tpu.errors import (DuplicateIndicesError, ParameterMismatchError)
from spfft_tpu.parallel import make_distributed_plan, make_mesh

from test_util import (center_triplets, dense_backward, dense_cube_from_values,
                       dense_forward, hermitian_triplets,
                       random_sparse_triplets, random_values, sample_cube,
                       tolerance_for)


def split_by_sticks(triplets: np.ndarray, dims, weights) -> list:
    """Assign whole z-sticks to shards proportionally to ``weights``
    (a stick must live on one shard — reference README.md:8)."""
    nx, ny, _ = dims
    storage = triplets.copy()
    for axis, n in enumerate(dims):
        col = storage[:, axis]
        storage[:, axis] = np.where(col < 0, col + n, col)
    keys = storage[:, 0].astype(np.int64) * ny + storage[:, 1]
    unique = np.unique(keys)
    weights = np.asarray(weights, np.float64)
    bounds = np.floor(np.cumsum(weights) / weights.sum() * len(unique)).astype(int)
    starts = np.concatenate([[0], bounds[:-1]])
    out = []
    for lo, hi in zip(starts, bounds):
        shard_keys = set(unique[lo:hi].tolist())
        mask = np.array([k in shard_keys for k in keys])
        out.append(triplets[mask])
    return out


def split_planes(dim_z: int, weights) -> list:
    """Split z planes by weight (reference:
    generate_indices.hpp:102-136 calculate_num_local_xy_planes)."""
    weights = np.asarray(weights, np.float64)
    bounds = np.floor(np.cumsum(weights) / weights.sum() * dim_z).astype(int)
    starts = np.concatenate([[0], bounds[:-1]])
    return [int(hi - lo) for lo, hi in zip(starts, bounds)]


SCENARIOS = {
    # name -> (stick weights, plane weights) over 4 shards
    "uniform": ([1, 1, 1, 1], [1, 1, 1, 1]),
    "all_on_first": ([1, 0, 0, 0], [1, 0, 0, 0]),
    "sticks_first_planes_last": ([1, 0, 0, 0], [0, 0, 0, 1]),
    "random_nonuniform": ([3, 0, 1, 2], [1, 2, 0, 3]),
}


@pytest.mark.parametrize("scenario", sorted(SCENARIOS))
@pytest.mark.parametrize("dims", [(11, 12, 13), (8, 8, 8)])
def test_distributed_c2c(scenario, dims):
    rng = np.random.default_rng(42)
    stick_w, plane_w = SCENARIOS[scenario]
    triplets = random_sparse_triplets(rng, dims)
    values = random_values(rng, len(triplets))
    cube = dense_cube_from_values(triplets, values, dims)
    space_oracle = dense_backward(cube)

    parts = split_by_sticks(triplets, dims, stick_w)
    planes = split_planes(dims[2], plane_w)
    plan = make_distributed_plan(TransformType.C2C, *dims, parts, planes,
                                 mesh=make_mesh(4), precision="double")

    values_parts = [sample_cube(cube, p, dims) for p in parts]
    tol = tolerance_for("double", space_oracle)

    for _ in range(2):  # repeated run catches missing zeroing
        space = plan.backward(values_parts)
        slabs = plan.unshard_space(space)
        z0 = 0
        for r, slab in enumerate(slabs):
            n = planes[r]
            assert slab.shape == (n, dims[1], dims[0])
            np.testing.assert_allclose(slab, space_oracle[z0:z0 + n],
                                       atol=tol, rtol=0)
            z0 += n

    # forward from oracle slabs
    freq_oracle = dense_forward(space_oracle)
    slabs_in = [space_oracle[plan.local_z_offset(r):
                             plan.local_z_offset(r) + planes[r]]
                for r in range(4)]
    out = plan.forward(slabs_in)
    got_parts = plan.unshard_values(out)
    for r, part in enumerate(parts):
        expected = sample_cube(freq_oracle, part, dims)
        np.testing.assert_allclose(got_parts[r], expected,
                                   atol=tolerance_for("double", expected),
                                   rtol=0)


@pytest.mark.parametrize("exchange", [ExchangeType.BUFFERED,
                                      ExchangeType.COMPACT_BUFFERED,
                                      ExchangeType.UNBUFFERED,
                                      ExchangeType.BUFFERED_FLOAT,
                                      ExchangeType.COMPACT_BUFFERED_FLOAT])
def test_exchange_variants(exchange):
    """All exchange selectors produce correct results; float-wire variants at
    reduced accuracy (reference: types.h:33-62, details.rst "MPI Exchange")."""
    rng = np.random.default_rng(1)
    dims = (12, 13, 14)
    triplets = random_sparse_triplets(rng, dims)
    values = random_values(rng, len(triplets))
    cube = dense_cube_from_values(triplets, values, dims)
    space_oracle = dense_backward(cube)
    parts = split_by_sticks(triplets, dims, [1, 2, 1, 1])
    planes = split_planes(dims[2], [1, 1, 2, 1])
    plan = make_distributed_plan(TransformType.C2C, *dims, parts, planes,
                                 mesh=make_mesh(4), precision="double",
                                 exchange=exchange)
    values_parts = [sample_cube(cube, p, dims) for p in parts]
    slabs = plan.unshard_space(plan.backward(values_parts))
    tol = (1e-4 * np.abs(space_oracle).max() if exchange.float_wire
           else tolerance_for("double", space_oracle))
    got = np.concatenate(slabs, axis=0)
    np.testing.assert_allclose(got, space_oracle, atol=tol, rtol=0)


@pytest.mark.parametrize("centered", [False, True])
def test_distributed_r2c(centered):
    """Distributed R2C: stick symmetry on the (0,0)-stick owner, plane
    symmetry on every shard's slab (reference: execution_host.cpp:306-342)."""
    rng = np.random.default_rng(5)
    dims = (12, 11, 13)
    nx, ny, nz = dims
    space = rng.uniform(-1, 1, (nz, ny, nx))
    freq = dense_forward(space)
    triplets = hermitian_triplets(rng, dims)
    if centered:
        triplets = center_triplets(triplets, dims)
    parts = split_by_sticks(triplets, dims, [1, 3, 2, 2])
    planes = split_planes(nz, [2, 1, 1, 1])
    plan = make_distributed_plan(TransformType.R2C, *dims, parts, planes,
                                 mesh=make_mesh(4), precision="double")
    values_parts = [sample_cube(freq, p, dims) for p in parts]
    slabs = plan.unshard_space(plan.backward(values_parts))
    got = np.concatenate(slabs, axis=0)
    oracle = space * space.size
    np.testing.assert_allclose(got, oracle,
                               atol=tolerance_for("double", oracle), rtol=0)

    # forward
    slabs_in = [space[plan.local_z_offset(r):
                      plan.local_z_offset(r) + planes[r]] for r in range(4)]
    got_parts = plan.unshard_values(plan.forward(slabs_in, Scaling.NONE))
    for r, part in enumerate(parts):
        expected = sample_cube(freq, part, dims)
        np.testing.assert_allclose(got_parts[r], expected,
                                   atol=tolerance_for("double", expected),
                                   rtol=0)


def test_eight_shards_with_empty():
    """Full 8-device mesh with several empty shards (reference allows empty
    ranks, execution_host.cpp:167-179)."""
    rng = np.random.default_rng(9)
    dims = (16, 16, 16)
    triplets = random_sparse_triplets(rng, dims)
    values = random_values(rng, len(triplets))
    cube = dense_cube_from_values(triplets, values, dims)
    space_oracle = dense_backward(cube)
    parts = split_by_sticks(triplets, dims, [2, 0, 1, 0, 3, 0, 1, 1])
    planes = split_planes(16, [0, 1, 0, 3, 1, 0, 2, 1])
    plan = make_distributed_plan(TransformType.C2C, *dims, parts, planes,
                                 mesh=make_mesh(8), precision="double")
    values_parts = [sample_cube(cube, p, dims) for p in parts]
    slabs = plan.unshard_space(plan.backward(values_parts))
    got = np.concatenate([s for s in slabs if s.size], axis=0)
    np.testing.assert_allclose(got, space_oracle,
                               atol=tolerance_for("double", space_oracle),
                               rtol=0)


def test_single_precision_bf16_wire():
    """precision='single' + *_FLOAT exchange selects a bfloat16 wire
    (dist.py wire dtype one step below transform precision): correct result
    at visibly reduced accuracy."""
    rng = np.random.default_rng(17)
    dims = (16, 16, 16)
    triplets = random_sparse_triplets(rng, dims)
    values = random_values(rng, len(triplets))
    cube = dense_cube_from_values(triplets, values, dims)
    space_oracle = dense_backward(cube)
    parts = split_by_sticks(triplets, dims, [1, 2, 1, 1])
    planes = split_planes(16, [1, 1, 1, 1])
    plan = make_distributed_plan(TransformType.C2C, *dims, parts, planes,
                                 mesh=make_mesh(4), precision="single",
                                 exchange=ExchangeType.BUFFERED_FLOAT)
    values_parts = [sample_cube(cube, p, dims) for p in parts]
    got = np.concatenate(plan.unshard_space(plan.backward(values_parts)))
    scale = np.abs(space_oracle).max()
    err = np.abs(got - space_oracle).max() / scale
    assert err < 0.05, f"bf16 wire wildly wrong: {err}"
    assert err > 1e-6, "bf16 wire suspiciously exact — cast path not taken?"


def test_single_precision_distributed():
    rng = np.random.default_rng(13)
    dims = (16, 16, 16)
    triplets = random_sparse_triplets(rng, dims)
    values = random_values(rng, len(triplets))
    cube = dense_cube_from_values(triplets, values, dims)
    space_oracle = dense_backward(cube)
    parts = split_by_sticks(triplets, dims, [1, 1, 1, 1])
    planes = split_planes(16, [1, 1, 1, 1])
    plan = make_distributed_plan(TransformType.C2C, *dims, parts, planes,
                                 mesh=make_mesh(4), precision="single")
    values_parts = [sample_cube(cube, p, dims) for p in parts]
    slabs = plan.unshard_space(plan.backward(values_parts))
    got = np.concatenate(slabs, axis=0)
    np.testing.assert_allclose(got, space_oracle,
                               atol=tolerance_for("single", space_oracle),
                               rtol=0)


def test_plan_validation():
    dims = (8, 8, 8)
    t0 = np.array([[0, 0, 0]])
    # plane sum mismatch (reference: parameters.cpp:107-109)
    with pytest.raises(ParameterMismatchError):
        make_distributed_plan(TransformType.C2C, *dims, [t0, t0 + 1],
                              [4, 3], mesh=make_mesh(2))
    # duplicate stick across shards (reference: indices.hpp:105-117)
    with pytest.raises(DuplicateIndicesError):
        make_distributed_plan(TransformType.C2C, *dims, [t0, t0],
                              [4, 4], mesh=make_mesh(2))


def test_scaling_distributed():
    rng = np.random.default_rng(21)
    dims = (8, 9, 10)
    triplets = random_sparse_triplets(rng, dims)
    parts = split_by_sticks(triplets, dims, [1, 1])
    planes = split_planes(10, [1, 1])
    plan = make_distributed_plan(TransformType.C2C, *dims, parts, planes,
                                 mesh=make_mesh(2), precision="double")
    cube = dense_cube_from_values(triplets, random_values(rng, len(triplets)),
                                  dims)
    space_oracle = dense_backward(cube)
    slabs_in = [space_oracle[plan.local_z_offset(r):
                             plan.local_z_offset(r) + planes[r]]
                for r in range(2)]
    freq_oracle = dense_forward(space_oracle)
    none = plan.unshard_values(plan.forward(slabs_in, Scaling.NONE))
    full = plan.unshard_values(plan.forward(slabs_in, Scaling.FULL))
    n = dims[0] * dims[1] * dims[2]
    for r in range(2):
        np.testing.assert_allclose(full[r], none[r] / n, atol=1e-9, rtol=0)


def test_ring_exchange_round_trip():
    """UNBUFFERED (ppermute-ring mechanism) in both directions, including a
    non-uniform distribution with an empty shard."""
    rng = np.random.default_rng(5)
    dims = (11, 12, 13)
    triplets = random_sparse_triplets(rng, dims)
    values = random_values(rng, len(triplets))
    cube = dense_cube_from_values(triplets, values, dims)
    space_oracle = dense_backward(cube)
    parts = split_by_sticks(triplets, dims, [0, 3, 1, 2])
    planes = split_planes(dims[2], [2, 0, 1, 1])
    plan = make_distributed_plan(TransformType.C2C, *dims, parts, planes,
                                 mesh=make_mesh(4), precision="double",
                                 exchange=ExchangeType.UNBUFFERED)
    values_parts = [sample_cube(cube, p, dims) for p in parts]
    space = plan.backward(values_parts)
    got = np.concatenate(plan.unshard_space(space), axis=0)
    np.testing.assert_allclose(got, space_oracle,
                               atol=tolerance_for("double", space_oracle),
                               rtol=0)
    out = plan.forward(space, Scaling.FULL)
    got_parts = plan.unshard_values(out)
    scale = 1.0 / np.prod(dims)
    freq_oracle = dense_forward(space_oracle) * scale
    for r, part in enumerate(parts):
        expected = sample_cube(freq_oracle, part, dims)
        np.testing.assert_allclose(got_parts[r], expected,
                                   atol=tolerance_for("double", expected),
                                   rtol=0)


def test_distributed_apply_pointwise():
    """Fused backward -> fn -> forward matches the two-call composition."""
    dims = (12, 11, 13)
    rng = np.random.default_rng(21)
    triplets = random_sparse_triplets(rng, dims)
    parts = split_by_sticks(triplets, dims, [2, 1, 0, 1])
    planes = split_planes(dims[2], [1, 3, 1, 2])
    plan = make_distributed_plan(TransformType.C2C, *dims, parts, planes,
                                 mesh=make_mesh(4), precision="double")
    values = [random_values(rng, len(p)) for p in parts]

    # identity pair vs composition
    got = np.asarray(plan.apply_pointwise(values))
    ref = np.asarray(plan.forward(plan.backward(values)))
    np.testing.assert_allclose(got, ref, atol=1e-10, rtol=0)

    # FULL scaling round trip returns the input values
    got_s = plan.unshard_values(plan.apply_pointwise(values,
                                                     scaling=Scaling.FULL))
    for g, v in zip(got_s, values):
        np.testing.assert_allclose(g, v, atol=1e-10, rtol=0)

    # a pointwise operator applied in the space domain
    got2 = plan.unshard_values(
        plan.apply_pointwise(values, fn=lambda s: 2.0 * s,
                             scaling=Scaling.FULL))
    for g, v in zip(got2, values):
        np.testing.assert_allclose(g, 2.0 * v, atol=1e-10, rtol=0)


def test_distributed_apply_pointwise_fn_args():
    """Sharded fn_args: a per-shard operator field applied in the space
    domain, fed as a traced sharded argument."""
    import jax.numpy as jnp
    dims = (8, 8, 8)
    rng = np.random.default_rng(23)
    triplets = random_sparse_triplets(rng, dims)
    parts = split_by_sticks(triplets, dims, [1, 1, 1, 1])
    planes = split_planes(dims[2], [1, 1, 1, 1])
    plan = make_distributed_plan(TransformType.C2C, *dims, parts, planes,
                                 mesh=make_mesh(4), precision="double")
    values = [random_values(rng, len(p)) for p in parts]

    def multiply(space, field):
        return space * field[..., None]

    dp = plan.dist_plan
    # sharded over the mesh axis: per-shard block (1, max_planes, ny, nx),
    # matching the space layout minus the interleave axis
    field = np.full((dp.num_shards, dp.max_planes, dims[1], dims[0]), 2.0)
    field_dev = jax.device_put(field, plan._sharded)
    got = plan.unshard_values(plan.apply_pointwise(
        values, multiply, field_dev, scaling=Scaling.FULL))
    for g, v in zip(got, values):
        np.testing.assert_allclose(g, 2.0 * v, atol=1e-10, rtol=0)


def test_distributed_forward_ignores_padding_rows():
    """Garbage in the padding rows of the padded space layout (rows at and
    beyond a shard's true slab height) must not affect forward results —
    the z-selection tables only read true planes."""
    dims = (12, 11, 13)
    rng = np.random.default_rng(22)
    triplets = random_sparse_triplets(rng, dims)
    parts = split_by_sticks(triplets, dims, [1, 1, 1, 1])
    planes = split_planes(dims[2], [1, 3, 1, 2])
    plan = make_distributed_plan(TransformType.C2C, *dims, parts, planes,
                                 mesh=make_mesh(4), precision="double")
    values = [random_values(rng, len(p)) for p in parts]
    space = np.asarray(plan.backward(values))
    clean = plan.unshard_values(plan.forward(jax.device_put(
        space, plan._sharded)))
    poisoned = space.copy()
    for r, n_pl in enumerate(plan.dist_plan.num_planes):
        poisoned[r, n_pl:] = 1e30
    got = plan.unshard_values(plan.forward(jax.device_put(
        poisoned, plan._sharded)))
    for g, c in zip(got, clean):
        np.testing.assert_allclose(g, c, atol=0, rtol=0)


@pytest.mark.parametrize("precision", ["double", "single"])
def test_distributed_r2c_double_and_single(precision):
    """Distributed R2C in both precisions against the dense oracle (the
    reference's SPFFT_SINGLE_PRECISION twins run the same test matrix)."""
    dims = (12, 11, 13)
    rng = np.random.default_rng(41)
    triplets = hermitian_triplets(rng, dims)
    parts = split_by_sticks(triplets, dims, [1, 2, 1, 0])
    planes = split_planes(dims[2], [2, 1, 1, 2])
    plan = make_distributed_plan(TransformType.R2C, *dims, parts, planes,
                                 mesh=make_mesh(4), precision=precision)
    # build consistent hermitian values from a real space field
    space_field = rng.standard_normal((dims[2], dims[1], dims[0]))
    freq = dense_forward(space_field.astype(np.complex128))
    values = [sample_cube(freq, p, dims) for p in parts]
    space = plan.backward(values)
    got = np.concatenate(plan.unshard_space(space), axis=0)
    tol = tolerance_for(precision, space_field) * np.prod(dims) ** 0.5
    np.testing.assert_allclose(got, space_field * np.prod(dims), atol=tol,
                               rtol=0)
    got_parts = plan.unshard_values(plan.forward(space, Scaling.FULL))
    for r, part in enumerate(parts):
        expected = sample_cube(freq, part, dims)
        np.testing.assert_allclose(got_parts[r], expected, atol=tol,
                                   rtol=0)


def test_distributed_iterate_pointwise():
    """Scanned distributed steps == sequential apply_pointwise calls."""
    dims = (8, 8, 8)
    rng = np.random.default_rng(25)
    triplets = random_sparse_triplets(rng, dims)
    parts = split_by_sticks(triplets, dims, [1, 1, 1, 1])
    planes = split_planes(dims[2], [1, 1, 1, 1])
    plan = make_distributed_plan(TransformType.C2C, *dims, parts, planes,
                                 mesh=make_mesh(4), precision="double")
    values = [random_values(rng, len(p)) for p in parts]

    def damp(space):
        return 0.5 * space

    out = plan.unshard_values(plan.iterate_pointwise(values, damp, steps=3))
    seq = values
    for _ in range(3):
        seq = plan.unshard_values(plan.apply_pointwise(
            seq, damp, scaling=Scaling.FULL))
    for g, s in zip(out, seq):
        np.testing.assert_allclose(g, s, atol=1e-10, rtol=0)


def test_default_exchange_mechanism():
    """DEFAULT maps to the padded all_to_all — a documented deviation from
    the reference's COMPACT_BUFFERED default (grid_internal.cpp:176-179);
    see docs/details.md 'Exchange' and docs/scaling_r04.json for the
    justification. This pin fails if the mapping silently changes."""
    from spfft_tpu.parallel.exchange import all_to_all_blocks
    rng = np.random.default_rng(5)
    dims = (8, 8, 8)
    triplets = random_sparse_triplets(rng, dims)
    parts = split_by_sticks(triplets, dims, [1, 1, 1, 1])
    planes = split_planes(dims[2], [1, 1, 1, 1])
    plan = make_distributed_plan(TransformType.C2C, *dims, parts, planes,
                                 mesh=make_mesh(4))
    assert plan.exchange == ExchangeType.DEFAULT
    assert plan._compact is None
    assert plan._exchange_fn is all_to_all_blocks
    values = plan.shard_values([random_values(rng, len(p)) for p in parts])
    txt = plan._backward_jit.lower(values, *plan._device_tables).as_text()
    assert "all_to_all" in txt and "collective_permute" not in txt


def test_comm_size_1_local_collapse():
    """A one-shard distributed plan executes through the LOCAL pipeline
    (reference: grid_internal.cpp:182 treats a size-1 communicator as
    local) while keeping the padded distributed API surface; explicit
    use_pallas=True keeps the SPMD kernel path (interpret-mode
    semantics)."""
    rng = np.random.default_rng(17)
    dims = (10, 9, 8)
    triplets = random_sparse_triplets(rng, dims)
    values = random_values(rng, len(triplets))
    plan = make_distributed_plan(TransformType.C2C, *dims, [triplets],
                                 [dims[2]], mesh=make_mesh(1),
                                 precision="double")
    assert plan._local1 is not None
    cube = dense_cube_from_values(triplets, values, dims)
    oracle = dense_backward(cube)
    space = plan.backward([values])
    assert space.shape[0] == 1  # padded distributed layout preserved
    np.testing.assert_allclose(np.asarray(plan.unshard_space(space)[0]),
                               oracle,
                               atol=tolerance_for("double", oracle), rtol=0)
    out = plan.unshard_values(plan.apply_pointwise([values],
                                                   scaling=Scaling.FULL))
    np.testing.assert_allclose(out[0], values, atol=1e-10, rtol=0)
    it = plan.unshard_values(plan.iterate_pointwise(
        [values], lambda s: s, steps=2, scaling=Scaling.FULL))
    np.testing.assert_allclose(it[0], values, atol=1e-9, rtol=0)
    forced = make_distributed_plan(TransformType.C2C, *dims, [triplets],
                                   [dims[2]], mesh=make_mesh(1),
                                   precision="single", use_pallas=True)
    assert forced._local1 is None  # SPMD kernel path kept when forced

"""Unit tests for the index planner (semantics of reference
src/compression/indices.hpp and src/parameters/parameters.cpp)."""

import numpy as np
import pytest

from spfft_tpu import (DuplicateIndicesError, InvalidIndicesError,
                       InvalidParameterError, TransformType, build_index_plan,
                       check_stick_duplicates)
from spfft_tpu.indexing import convert_index_triplets, to_storage_index


def test_storage_index_conversion():
    # reference: indices.hpp:49-55
    idx = np.array([0, 1, -1, -4, 3])
    np.testing.assert_array_equal(to_storage_index(8, idx), [0, 1, 7, 4, 3])


def test_stick_ordering_matches_reference():
    # Sticks keyed x*dimY + y, ascending (reference: indices.hpp:152-165).
    triplets = np.array([
        [2, 1, 0],   # key 2*4+1 = 9
        [0, 3, 1],   # key 3
        [1, 0, 2],   # key 4
        [0, 3, 0],   # key 3 (same stick)
    ])
    vi, keys, centered, conj = convert_index_triplets(False, 3, 4, 5, triplets)
    assert not centered
    assert conj is None
    np.testing.assert_array_equal(keys, [3, 4, 9])
    # value flat index = stick_id * dimZ + z (reference: indices.hpp:168-176)
    np.testing.assert_array_equal(vi, [2 * 5 + 0, 0 * 5 + 1, 1 * 5 + 2,
                                       0 * 5 + 0])


def test_centered_detection_and_conversion():
    # Any negative index flips the whole set to centered interpretation
    # (reference: indices.hpp:129-135).
    triplets = np.array([[0, 0, 0], [-1, 2, -3]])
    vi, keys, centered, conj = convert_index_triplets(False, 8, 8, 8, triplets)
    assert centered
    assert conj is None
    # storage: (-1 -> 7), z: -3 -> 5
    np.testing.assert_array_equal(keys, [0, 7 * 8 + 2])
    np.testing.assert_array_equal(vi, [0, 1 * 8 + 5])


@pytest.mark.parametrize("bad", [
    [[8, 0, 0]],             # x out of non-centered range
    [[0, -5, 0]],            # y below centered min for dim 8: min = -3
    [[5, 0, -1]],            # centered mode: max x = 4 for dim 8
    [[0, -1, 5]],            # centered mode: max z = 4 for dim 8
])
def test_bounds_checking(bad):
    # reference: indices.hpp:137-149
    with pytest.raises(InvalidIndicesError):
        convert_index_triplets(False, 8, 8, 8, np.asarray(bad, np.int64))


def test_hermitian_bounds():
    # R2C: x must be in [0, dimX/2] (details.rst "Real-To-Complex")
    convert_index_triplets(True, 8, 8, 8, np.array([[4, 7, 7]]))
    with pytest.raises(InvalidIndicesError):
        convert_index_triplets(True, 8, 8, 8, np.array([[5, 0, 0]]))


def test_hermitian_negative_x_folds_to_mirror():
    # x < 0 hermitian triplets canonicalise onto the conjugate mirror
    # (-x, -y, -z) instead of being rejected: (-1, 2, -3) and (1, -2, 3)
    # are the same stored value up to conjugation.
    tr = np.array([[1, -2, 3], [-1, 2, -3]])
    vi, keys, centered, conj = convert_index_triplets(True, 8, 8, 8, tr)
    assert centered
    np.testing.assert_array_equal(conj, [False, True])
    # Both rows land on the same stick and the same flat value index.
    np.testing.assert_array_equal(keys, [1 * 8 + 6])
    np.testing.assert_array_equal(vi, [3, 3])


def test_hermitian_fold_edge_dimension_half():
    # The mirror of a valid -N/2 edge index is +N/2, which is the SAME
    # storage index; the fold must normalise it back so the bounds check
    # (which rejects a user-supplied +N/2 in centered mode) still accepts
    # the mirror of a valid edge value.
    tr = np.array([[-1, -4, -4]])
    vi, keys, centered, conj = convert_index_triplets(True, 8, 8, 8, tr)
    assert centered
    np.testing.assert_array_equal(conj, [True])
    # mirror: x 1, y 4 -> -4 (storage 4), z 4 -> -4 (storage 4)
    np.testing.assert_array_equal(keys, [1 * 8 + 4])
    np.testing.assert_array_equal(vi, [4])


def test_hermitian_fold_matches_explicit_mirror_plan():
    # A folded full-sphere set builds the identical stick table as the
    # hand-canonicalised non-redundant half.
    rng = np.random.default_rng(7)
    half = np.unique(
        np.stack([rng.integers(1, 4, 40), rng.integers(-3, 4, 40),
                  rng.integers(-3, 4, 40)], axis=1), axis=0)
    full = np.concatenate([half, -half])
    vi_f, keys_f, cen_f, conj_f = convert_index_triplets(True, 8, 8, 8, full)
    vi_h, keys_h, cen_h, conj_h = convert_index_triplets(True, 8, 8, 8, half)
    assert conj_h is None
    np.testing.assert_array_equal(keys_f, keys_h)
    np.testing.assert_array_equal(vi_f[:len(half)], vi_h)
    np.testing.assert_array_equal(vi_f[len(half):], vi_h)
    np.testing.assert_array_equal(conj_f, [False] * len(half)
                                  + [True] * len(half))


def test_too_many_values_rejected():
    # reference: indices.hpp:126-128
    triplets = np.zeros((9, 3), np.int64)
    with pytest.raises(InvalidParameterError):
        convert_index_triplets(False, 2, 2, 2, triplets)


def test_duplicate_stick_detection_across_shards():
    # reference: indices.hpp:105-117
    check_stick_duplicates([np.array([1, 2]), np.array([3])])
    with pytest.raises(DuplicateIndicesError):
        check_stick_duplicates([np.array([1, 2]), np.array([2])])


def test_index_plan_properties():
    plan = build_index_plan(TransformType.R2C, 8, 6, 4,
                            np.array([[0, 0, 0], [2, 5, 3], [0, 0, 2]]))
    assert plan.dim_x_freq == 5
    assert plan.num_sticks == 2
    assert plan.num_values == 3
    assert plan.zero_stick_id == 0
    np.testing.assert_array_equal(plan.stick_x, [0, 2])
    np.testing.assert_array_equal(plan.stick_y, [0, 5])
    # x-innermost scatter columns: y * dim_x_freq + x
    np.testing.assert_array_equal(plan.scatter_cols, [0, 5 * 5 + 2])


def test_zero_stick_absent():
    plan = build_index_plan(TransformType.C2C, 4, 4, 4,
                            np.array([[1, 1, 0]]))
    assert plan.zero_stick_id is None


def test_size_product_overflow():
    """Construction rejects unrepresentable size products with the typed
    overflow error (reference: grid_internal.cpp:122-134 ->
    exceptions.hpp:50-59)."""
    import pytest
    from spfft_tpu.errors import OverflowError_
    from spfft_tpu.indexing import build_index_plan
    from spfft_tpu.types import TransformType
    n = 1 << 21
    with pytest.raises(OverflowError_):
        build_index_plan(TransformType.C2C, n, n, n,
                         np.zeros((1, 3), np.int32))
    with pytest.raises(OverflowError_):
        build_index_plan(TransformType.C2C, 1 << 32, 1, 1,
                         np.zeros((1, 3), np.int32))

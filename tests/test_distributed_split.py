"""Distributed sparse-y (split-x) stage: the reference runs its y-FFT
only over non-empty x rows in ALL paths including MPI ones
(reference: execution_host.cpp:139-145 uses uniqueXIndices from all ranks);
here the occupied-x window shrinks every shard's plane grid and both
exchange unpack layouts."""

import numpy as np
import pytest

from spfft_tpu import ExchangeType, Scaling, TransformType
from spfft_tpu.parallel import make_distributed_plan, make_mesh
from spfft_tpu.utils.workloads import spherical_cutoff_triplets

from test_distributed import split_by_sticks, split_planes
from test_util import (dense_backward, dense_cube_from_values, dense_forward,
                      random_values, sample_cube, tolerance_for)


@pytest.mark.parametrize("exchange", [ExchangeType.BUFFERED,
                                      ExchangeType.COMPACT_BUFFERED,
                                      ExchangeType.UNBUFFERED])
def test_distributed_split_wrapped_sphere(exchange):
    """Centered sphere on a 2x-cutoff grid (the realistic plane-wave shape):
    the wrapped occupied-x window activates the distributed split path on
    every exchange mechanism."""
    dims = (24, 24, 24)
    rng = np.random.default_rng(55)
    triplets = spherical_cutoff_triplets(24, radius=6)
    values = random_values(rng, len(triplets))
    cube = dense_cube_from_values(triplets, values, dims)
    space_oracle = dense_backward(cube)
    parts = split_by_sticks(triplets, dims, [2, 1, 0, 1])
    planes = split_planes(dims[2], [1, 2, 1, 2])
    plan = make_distributed_plan(TransformType.C2C, *dims, parts, planes,
                                 mesh=make_mesh(4), precision="double",
                                 exchange=exchange)
    assert plan._split_x == (18, 13), plan._split_x
    values_parts = [sample_cube(cube, p, dims) for p in parts]
    space = plan.backward(values_parts)
    got = np.concatenate([s for s in plan.unshard_space(space) if s.size],
                         axis=0)
    np.testing.assert_allclose(got, space_oracle,
                               atol=tolerance_for("double", space_oracle),
                               rtol=0)
    back = plan.unshard_values(plan.forward(space, Scaling.FULL))
    for g, v in zip(back, values_parts):
        np.testing.assert_allclose(g, v, atol=1e-10, rtol=0)


@pytest.mark.parametrize("exchange", [ExchangeType.BUFFERED,
                                      ExchangeType.COMPACT_BUFFERED])
def test_distributed_split_r2c(exchange):
    """Distributed R2C split: occupied window of the half spectrum, plane
    symmetry on the x=0 sub-column."""
    dims = (24, 20, 18)
    nx, ny, nz = dims
    rng = np.random.default_rng(56)
    space_field = rng.standard_normal((nz, ny, nx))
    freq = dense_forward(space_field.astype(np.complex128))
    triplets = np.array([[x, y, z] for x in range(5)
                         for y in range(ny) for z in range(nz)])
    mask = np.zeros((nz, ny, nx), bool)
    for x, y, z in triplets:
        mask[z, y, x] = True
        mask[(-z) % nz, (-y) % ny, (-x) % nx] = True
    freq_bl = freq * mask
    space_bl = np.fft.ifftn(freq_bl).real
    parts = split_by_sticks(triplets, dims, [1, 2, 1, 1])
    planes = split_planes(nz, [2, 1, 2, 1])
    plan = make_distributed_plan(TransformType.R2C, *dims, parts, planes,
                                 mesh=make_mesh(4), precision="double",
                                 exchange=exchange)
    assert plan._split_x == (0, 5), plan._split_x
    values_parts = [sample_cube(freq_bl, p, dims) for p in parts]
    space = plan.backward(values_parts)
    got = np.concatenate([s for s in plan.unshard_space(space) if s.size],
                         axis=0)
    oracle = space_bl * space_bl.size
    np.testing.assert_allclose(got, oracle,
                               atol=tolerance_for("double", oracle), rtol=0)
    slabs_in = [space_bl[plan.local_z_offset(r):
                         plan.local_z_offset(r) + planes[r]]
                for r in range(4)]
    got_parts = plan.unshard_values(plan.forward(slabs_in))
    for r, part in enumerate(parts):
        expected = sample_cube(freq_bl, part, dims)
        np.testing.assert_allclose(got_parts[r], expected,
                                   atol=tolerance_for("double", expected),
                                   rtol=0)


def test_distributed_split_disabled_for_wide_sets():
    """A full-width set keeps the dense path (window > 70% of x)."""
    from test_util import random_sparse_triplets
    dims = (12, 12, 12)
    rng = np.random.default_rng(57)
    triplets = random_sparse_triplets(rng, dims)
    parts = split_by_sticks(triplets, dims, [1, 1])
    planes = split_planes(dims[2], [1, 1])
    plan = make_distributed_plan(TransformType.C2C, *dims, parts, planes,
                                 mesh=make_mesh(2), precision="double")
    assert plan._split_x is None


def test_distributed_split_with_pallas_interpret():
    """Split-x composes with the Pallas compression tables (interpret mode
    on CPU) — the two optimizations are orthogonal stages."""
    dims = (24, 16, 16)
    rng = np.random.default_rng(58)
    triplets = spherical_cutoff_triplets(16, radius=4)
    # rescale x to the 24-wide grid: keep as-is (|x|<=4 fits any)
    values = random_values(rng, len(triplets))
    cube = dense_cube_from_values(triplets, values, dims)
    space_oracle = dense_backward(cube)
    from spfft_tpu.utils.workloads import (even_plane_split,
                                           round_robin_stick_partition,
                                           sort_triplets_stick_major)
    triplets_sorted = sort_triplets_stick_major(triplets, dims)
    values_sorted = sample_cube(cube, triplets_sorted, dims)
    parts = round_robin_stick_partition(triplets_sorted, dims, 4)
    planes = even_plane_split(dims[2], 4)
    plan = make_distributed_plan(
        TransformType.C2C, *dims, parts, planes, mesh=make_mesh(4),
        precision="single", use_pallas=True)
    assert plan._split_x is not None
    assert plan._pallas_dist is not None
    values_parts = [sample_cube(cube, p, dims) for p in parts]
    space = plan.backward(values_parts)
    got = np.concatenate([s for s in plan.unshard_space(space) if s.size],
                         axis=0)
    np.testing.assert_allclose(got, space_oracle,
                               atol=tolerance_for("single", space_oracle),
                               rtol=0)

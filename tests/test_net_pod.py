"""spfft_tpu/net/: the wire protocol, the blob artifact tier and the
real-TCP pod — the tier-1 twin of ``make pod-smoke``.

The contracts under test (docs/cluster.md "Deployment"): frames
round-trip bit-exact (arrays, signatures, trace contexts, typed
errors) and reject corruption as ``NetProtocolError``; the blob tier
round-trips bytes behind ``get/put/list`` on both backends and feeds
``PlanArtifactStore`` as a best-effort remote tier (a cold store boots
warm off it, faults never escape); a ``TcpHostLane`` against a live
``HostAgent`` is indistinguishable from a loopback lane to the
``PodFrontend`` (bit-exact serving, one trace id across the socket,
typed failover when the agent dies); the SPMD lane's admission control
rejects overflow as ``QueueFullError`` and purges expired deadlines;
and a real two-agent SUBPROCESS pod serves bit-exact with kill -9
failover.
"""

import json
import os
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from spfft_tpu import obs
from spfft_tpu.benchmark import cutoff_stick_triplets
from spfft_tpu.control.config import global_config
from spfft_tpu.errors import (BlobStoreError, DeadlineExpiredError,
                              GenericError, HostLaneError,
                              InvalidParameterError, NetProtocolError,
                              QueueFullError)
from spfft_tpu.net.agent import HostAgent
from spfft_tpu.net.blobstore import (FileBlobStore, HttpBlobStore,
                                     open_blobstore, serve_blobstore)
from spfft_tpu.net.frame import (error_from_wire, error_to_wire,
                                 pack_values, recv_frame, send_frame,
                                 signature_from_wire,
                                 signature_to_wire, unpack_values)
from spfft_tpu.net.transport import TcpHostLane, _SocketPool
from spfft_tpu.serve.cluster import PodFrontend, _SPMDLane
from spfft_tpu.serve.executor import ServeExecutor
from spfft_tpu.serve.registry import PlanRegistry, signature_for
from spfft_tpu.serve.store import PlanArtifactStore
from spfft_tpu.types import Scaling, TransformType

N = 8
DIMS = (N, N, N)
SHARDS = 2


@pytest.fixture(scope="module")
def plans():
    """One local + one 2-shard distributed plan, shared module-wide."""
    from spfft_tpu.parallel import make_distributed_plan, make_mesh
    from spfft_tpu.utils.workloads import (even_plane_split,
                                           round_robin_stick_partition)
    trip = cutoff_stick_triplets(N, N, N, 0.9, hermitian=False)
    reg = PlanRegistry()
    sig, plan = reg.get_or_build(TransformType.C2C, *DIMS, trip,
                                 precision="double")
    parts = round_robin_stick_partition(trip, DIMS, SHARDS)
    planes = even_plane_split(DIMS[2], SHARDS)
    dplan = make_distributed_plan(TransformType.C2C, *DIMS, parts,
                                  planes,
                                  mesh=make_mesh(SHARDS),
                                  precision="double")
    dsig = signature_for(TransformType.C2C, *DIMS, trip,
                         precision="double", device_count=SHARDS)
    return {"trip": trip, "sig": sig, "plan": plan,
            "dsig": dsig, "dplan": dplan}


def _vals(plans, rng):
    n = len(plans["trip"])
    return rng.standard_normal(n) + 1j * rng.standard_normal(n)


# ---------------------------------------------------------------------------
# frame protocol
# ---------------------------------------------------------------------------

def test_frame_round_trip_with_payload():
    a, b = socket.socketpair()
    try:
        payload = os.urandom(4096)
        send_frame(a, {"type": "ping", "k": [1, 2]}, payload)
        header, got = recv_frame(b)
        assert header == {"type": "ping", "k": [1, 2]}
        assert got == payload
    finally:
        a.close()
        b.close()


def test_frame_rejects_bad_magic_and_truncation():
    a, b = socket.socketpair()
    try:
        a.sendall(b"NOPE" + b"\x00" * 13)
        a.close()
        with pytest.raises(NetProtocolError):
            recv_frame(b)
    finally:
        b.close()
    a, b = socket.socketpair()
    try:
        send_frame(a, {"type": "ping"}, b"full-payload")
        buf = b.recv(1 << 20)
        c, d = socket.socketpair()
        try:
            c.sendall(buf[:-4])  # truncated mid-payload
            c.close()
            with pytest.raises(NetProtocolError):
                recv_frame(d)
        finally:
            d.close()
    finally:
        a.close()
        b.close()


def test_frame_eof_ok_returns_none():
    a, b = socket.socketpair()
    a.close()
    try:
        assert recv_frame(b, eof_ok=True) is None
    finally:
        b.close()


def test_pack_unpack_values_shapes():
    rng = np.random.default_rng(0)
    single = rng.standard_normal(17) + 1j * rng.standard_normal(17)
    meta, blob = pack_values(single)
    out = unpack_values(meta, blob)
    assert np.array_equal(out, single)
    many = [rng.standard_normal((5, 2)).astype(np.float32),
            rng.standard_normal(9) + 1j * rng.standard_normal(9)]
    meta, blob = pack_values(many)
    out = unpack_values(meta, blob)
    assert isinstance(out, list) and len(out) == 2
    for got, want in zip(out, many):
        assert got.dtype == want.dtype
        assert np.array_equal(got, want)
    meta, blob = pack_values(None)
    assert unpack_values(meta, blob) is None


def test_signature_wire_round_trip(plans):
    wire = signature_to_wire(plans["sig"])
    json.dumps(wire)  # must be JSON-serializable as-is
    assert signature_from_wire(wire) == plans["sig"]
    with pytest.raises(NetProtocolError):
        signature_from_wire({"bogus_field": 1})


def test_error_wire_round_trip():
    wire = error_to_wire(QueueFullError("queue is full"))
    assert wire["type"] == "error"
    back = error_from_wire(wire)
    assert isinstance(back, QueueFullError)
    assert "queue is full" in str(back)
    # builtins that model request-shaped failures survive too
    assert isinstance(error_from_wire(error_to_wire(ValueError("x"))),
                      ValueError)
    # an unknown class degrades to the taxonomy root, never crashes
    unknown = error_from_wire({"type": "error",
                               "error_type": "BogusError",
                               "message": "?"})
    assert isinstance(unknown, GenericError)


# ---------------------------------------------------------------------------
# blob tier
# ---------------------------------------------------------------------------

def test_file_blobstore_round_trip(tmp_path):
    bs = FileBlobStore(str(tmp_path))
    assert bs.get("art/missing.plan") is None
    bs.put("art/a.plan", b"alpha")
    bs.put("req/b.json", b"beta")
    assert bs.get("art/a.plan") == b"alpha"
    assert sorted(bs.list()) == ["art/a.plan", "req/b.json"]
    for bad in ("", "/abs", "../up", "a\\b"):
        with pytest.raises(InvalidParameterError):
            bs.put(bad, b"x")


def test_http_blobstore_round_trip(tmp_path):
    server, thread = serve_blobstore(str(tmp_path))
    try:
        url = f"http://127.0.0.1:{server.server_address[1]}"
        bs = open_blobstore(url)
        assert isinstance(bs, HttpBlobStore)
        assert bs.get("art/missing.plan") is None
        bs.put("art/a.plan", b"alpha")
        assert bs.get("art/a.plan") == b"alpha"
        assert bs.list() == ["art/a.plan"]
        # same bytes through the file backend: one shared tier
        assert FileBlobStore(str(tmp_path)).get("art/a.plan") == b"alpha"
    finally:
        server.shutdown()
        thread.join(timeout=10)


def test_open_blobstore_dispatch(tmp_path):
    assert open_blobstore(None) is None
    assert open_blobstore("") is None
    assert isinstance(open_blobstore(str(tmp_path)), FileBlobStore)
    assert isinstance(open_blobstore("http://127.0.0.1:1/x"),
                      HttpBlobStore)


def test_store_remote_tier_cold_boot(tmp_path, plans):
    """A fresh process-shaped store (empty disk) boots warm off the
    remote tier alone: artifact fetched, parsed through the digest
    gauntlet, zero builds."""
    blob = FileBlobStore(str(tmp_path / "blob"))
    warm = PlanArtifactStore(str(tmp_path / "warm"), remote=blob)
    warm.save_plan(plans["sig"], plans["plan"], plans["trip"])
    warm.drain()
    assert any(k.startswith("art/") for k in blob.list())

    cold = PlanArtifactStore(str(tmp_path / "cold"), remote=blob)
    reg = PlanRegistry(store=cold)
    assert reg.prewarm_signatures([plans["sig"]], strict=True) == 1
    st = reg.stats()
    assert st["builds"] == 0
    loaded = reg.get(plans["sig"])
    rng = np.random.default_rng(3)
    v = _vals(plans, rng)
    assert np.array_equal(np.asarray(loaded.backward(v)),
                          np.asarray(plans["plan"].backward(v)))


def test_store_remote_tier_faults_contained(tmp_path, plans):
    """Blob faults are best-effort: a dead remote never fails a local
    load or save (it just counts)."""
    class _Dead:
        def get(self, key):
            raise BlobStoreError("remote down")

        def put(self, key, data):
            raise BlobStoreError("remote down")

    store = PlanArtifactStore(str(tmp_path / "s"), remote=_Dead())
    key = store.save_plan(plans["sig"], plans["plan"], plans["trip"])
    store.drain()
    assert os.path.exists(store.artifact_path(key))
    # a read through an empty disk tier + dead remote is a clean miss
    cold = PlanArtifactStore(str(tmp_path / "c"), remote=_Dead())
    reg = PlanRegistry(store=cold)
    assert reg.prewarm_signatures([plans["sig"]], strict=False) == 0


# ---------------------------------------------------------------------------
# SPMD-lane admission control
# ---------------------------------------------------------------------------

def test_spmd_lane_queue_full_and_deadline_purge(plans):
    release = threading.Event()

    class _Blocking:
        def backward(self, values):
            release.wait(30)
            return values

    lane = _SPMDLane(max_workers=1)
    cfg = global_config()
    old = cfg.max_queue
    cfg.set("max_queue", 2, source="test", reason="admission test")
    try:
        f1 = lane.submit(plans["dsig"], _Blocking(), 1, "backward",
                         Scaling.NONE, None)
        time.sleep(0.05)  # let the worker pick f1 up
        f2 = lane.submit(plans["dsig"], _Blocking(), 2, "backward",
                         Scaling.NONE, None, timeout=0.02)
        with pytest.raises(QueueFullError):
            lane.submit(plans["dsig"], _Blocking(), 3, "backward",
                        Scaling.NONE, None)
        time.sleep(0.1)  # let f2's queued deadline lapse
        release.set()
        assert f1.result(timeout=30) == 1
        # f2's deadline expired while queued behind f1: purged typed
        with pytest.raises(DeadlineExpiredError):
            f2.result(timeout=30)
        rej = obs.GLOBAL_COUNTERS.snapshot()[
            "spfft_cluster_spmd_rejected_total"]["samples"]
        reasons = {dict(k).get("reason") for k in rej}
        assert {"queue_full", "expired"} <= reasons
    finally:
        release.set()
        cfg.set("max_queue", old, source="test",
                reason="restore after admission test")
        lane.close()


# ---------------------------------------------------------------------------
# connection pooling (net/transport.py _SocketPool)
# ---------------------------------------------------------------------------

def test_socket_pool_reuses_connections(plans):
    """Sequential RPCs over one TcpHostLane ride ONE kept-alive
    socket: the first call dials (a pool miss), the rest are pool
    hits — and the answers stay bit-exact."""
    reg = PlanRegistry()
    reg.put(plans["sig"], plans["plan"])
    ex = ServeExecutor(reg)
    agent = HostAgent("pool0", ex).start()
    lane = TcpHostLane("pool0", ("127.0.0.1", agent.port))
    rng = np.random.default_rng(5)
    try:
        for _ in range(4):
            v = _vals(plans, rng)
            got = np.asarray(lane.rpc_submit(plans["sig"], v)
                             .result(timeout=120))
            assert np.array_equal(
                got, np.asarray(plans["plan"].backward(v)))
        stats = lane.transport.pool_stats()
        assert stats["misses"] >= 1
        assert stats["hits"] >= 2
        assert stats["idle"] >= 1  # the socket went back on the shelf
    finally:
        lane.close()
        agent.close()
        ex.close(drain=False)


def test_socket_pool_reaper_closes_idle():
    """Idle pooled sockets older than the idle timeout are reaped by
    the background thread (no descriptor leak behind a quiet lane)."""
    a, b = socket.socketpair()
    pool = _SocketPool(idle_timeout=0.12)
    try:
        pool.checkin(a)
        assert pool.stats()["idle"] == 1
        deadline = time.monotonic() + 5.0
        while pool.stats()["reaped"] == 0 \
                and time.monotonic() < deadline:
            time.sleep(0.02)
        stats = pool.stats()
        assert stats["reaped"] == 1
        assert stats["idle"] == 0
    finally:
        pool.close()
        b.close()


def test_socket_pool_discards_stale_sockets():
    """A kept-alive socket whose peer hung up is detected at checkout
    (MSG_PEEK probe) and discarded — the caller dials fresh instead of
    writing into a dead stream."""
    a, b = socket.socketpair()
    pool = _SocketPool(idle_timeout=30.0)
    try:
        pool.checkin(a)
        b.close()  # peer hangs up while the socket sits idle
        assert pool.checkout() is None
        assert pool.stats()["idle"] == 0
        assert pool.stats()["misses"] == 1
    finally:
        pool.close()


# ---------------------------------------------------------------------------
# agent-side admission control (net/agent.py _admit)
# ---------------------------------------------------------------------------

def test_agent_rejects_expired_and_full_typed(plans):
    """The HostAgent's own admission seam answers an already-expired
    deadline as DeadlineExpiredError and a full host as
    QueueFullError — typed over the wire, counted per reason — instead
    of burning the executor on work nobody awaits."""
    reg = PlanRegistry()
    reg.put(plans["sig"], plans["plan"])
    ex = ServeExecutor(reg)
    agent = HostAgent("adm0", ex).start()
    lane = TcpHostLane("adm0", ("127.0.0.1", agent.port))
    rng = np.random.default_rng(6)
    try:
        with pytest.raises(DeadlineExpiredError):
            lane.rpc_submit(plans["sig"], _vals(plans, rng),
                            timeout=0.0).result(timeout=30)
        cfg = global_config()
        old = cfg.max_queue
        cfg.set("max_queue", 1, source="test",
                reason="agent admission test")
        try:
            with agent._lock:
                agent._inflight += 1  # a request parked in the seam
            with pytest.raises(QueueFullError):
                lane.rpc_submit(plans["sig"], _vals(plans, rng)) \
                    .result(timeout=30)
        finally:
            with agent._lock:
                agent._inflight -= 1
            cfg.set("max_queue", old, source="test",
                    reason="restore after agent admission test")
        # admission recovered: the lane serves again, bit-exact
        v = _vals(plans, rng)
        got = np.asarray(lane.rpc_submit(plans["sig"], v)
                         .result(timeout=120))
        assert np.array_equal(got,
                              np.asarray(plans["plan"].backward(v)))
        rej = obs.GLOBAL_COUNTERS.snapshot()[
            "spfft_net_agent_rejected_total"]["samples"]
        reasons = {dict(k).get("reason") for k in rej}
        assert {"queue_full", "expired"} <= reasons
    finally:
        lane.close()
        agent.close()
        ex.close(drain=False)


def test_agent_coalesces_concurrent_distributed_requests(plans):
    """Two concurrent same-signature distributed submits over REAL TCP
    share one collective round on the agent's coalescer (the in-process
    twin of the pod-smoke coalesce phase): both bit-exact, and the
    agent-side coalesced counter moves by exactly 2."""
    from spfft_tpu.control.config import global_config as _gc
    reg = PlanRegistry()
    reg.put(plans["dsig"], plans["dplan"])
    ex = ServeExecutor(reg)
    agent = HostAgent("coal0", ex).start()
    lane = TcpHostLane("coal0", ("127.0.0.1", agent.port))
    rng = np.random.default_rng(8)
    dvals = []
    for _ in range(2):
        dvals.append([
            (rng.standard_normal(p.num_values)
             + 1j * rng.standard_normal(p.num_values))
            for p in plans["dplan"].dist_plan.shard_plans])
    oracle = [np.asarray(plans["dplan"].backward(v)) for v in dvals]
    plans["dplan"].coalesce_backward(dvals)  # warm the batched jit
    counters = obs.GLOBAL_COUNTERS

    def total():
        return sum(counters.snapshot().get(
            "spfft_cluster_spmd_coalesced_total",
            {}).get("samples", {}).values())

    before = total()
    cfg = _gc()
    old = cfg.spmd_batch_window
    cfg.set("spmd_batch_window", 0.5, source="test",
            reason="agent coalesce test")
    try:
        futs = [lane.rpc_submit(plans["dsig"], v) for v in dvals]
        got = [np.asarray(f.result(timeout=120)) for f in futs]
    finally:
        cfg.set("spmd_batch_window", old, source="test",
                reason="restore after agent coalesce test")
        lane.close()
        agent.close()
        ex.close(drain=False)
    for g, want in zip(got, oracle):
        assert np.array_equal(g, want)
    assert total() - before == 2


# ---------------------------------------------------------------------------
# TcpHostLane against a live in-process agent
# ---------------------------------------------------------------------------

@pytest.fixture()
def agent_pod(plans):
    """A PodFrontend over one loopback lane + one REAL TCP lane backed
    by an in-process HostAgent — the mixed pod the seam promises."""
    regs = []
    for _ in range(2):
        reg = PlanRegistry()
        reg.put(plans["sig"], plans["plan"])
        reg.put(plans["dsig"], plans["dplan"])
        regs.append(reg)
    loop_ex = ServeExecutor(regs[0])
    tcp_ex = ServeExecutor(regs[1])
    agent = HostAgent("t1", tcp_ex).start()
    lane = TcpHostLane("t1", ("127.0.0.1", agent.port))
    pod = PodFrontend([("t0", loop_ex), lane], policy="rr", seed=0)
    yield {"pod": pod, "lane": lane, "agent": agent,
           "tcp_ex": tcp_ex, "loop_ex": loop_ex}
    pod.close()
    lane.close()
    agent.close()
    tcp_ex.close(drain=False)
    loop_ex.close(drain=False)


def test_mixed_pod_serves_bit_exact(agent_pod, plans):
    pod = agent_pod["pod"]
    rng = np.random.default_rng(1)
    for _ in range(4):
        v = _vals(plans, rng)
        got = np.asarray(pod.submit_backward(plans["sig"], v)
                         .result(timeout=120))
        assert np.array_equal(
            got, np.asarray(plans["plan"].backward(v)))
    dvalues = [
        (rng.standard_normal(p.num_values)
         + 1j * rng.standard_normal(p.num_values))
        for p in plans["dplan"].dist_plan.shard_plans]
    dgot = np.asarray(pod.submit(plans["dsig"], dvalues)
                      .result(timeout=120))
    assert np.array_equal(
        dgot, np.asarray(plans["dplan"].backward(dvalues)))


def test_trace_id_crosses_the_socket(agent_pod, plans):
    pod, lane = agent_pod["pod"], agent_pod["lane"]
    obs.enable()
    tracer = obs.GLOBAL_TRACER
    tracer.reset()
    tracer.set_sample_rate(1.0)
    try:
        rng = np.random.default_rng(2)
        for _ in range(4):
            v = _vals(plans, rng)
            pod.submit_backward(plans["sig"], v).result(timeout=120)
        assert tracer.open_count() == 0
        roots = {s.trace_id for s in tracer.events()
                 if isinstance(s, obs.Span)
                 and s.name == "cluster.request"}
        remote = lane.rpc_spans()
        assert remote["open"] == 0
        served = [s for s in remote["spans"]
                  if s["name"] == "serve.request"]
        assert served, "agent recorded no serve.request spans"
        assert all(s["trace_id"] in roots for s in served)
    finally:
        obs.disable()


def test_wire_rtt_feeds_signals(agent_pod, plans):
    pod, lane = agent_pod["pod"], agent_pod["lane"]
    rng = np.random.default_rng(4)
    pod.submit_backward(plans["sig"], _vals(plans, rng)) \
       .result(timeout=120)
    signals = lane.rpc_signals()
    assert signals["wire_rtt"] > 0.0
    assert lane.transport.rtt == pytest.approx(signals["wire_rtt"])


def test_remote_error_stays_typed(agent_pod, plans):
    """An executor-side rejection crosses the wire as its own class —
    backpressure is not lane death."""
    lane = agent_pod["lane"]
    bogus = signature_for(
        TransformType.C2C, 6, 6, 6,
        cutoff_stick_triplets(6, 6, 6, 0.9, hermitian=False),
        precision="double")
    with pytest.raises(InvalidParameterError):
        lane.rpc_submit(bogus, np.zeros(3, complex),
                        ctx=None).result(timeout=60)
    assert lane.alive  # a typed rejection must NOT kill the lane


def test_agent_death_fails_over_typed(agent_pod, plans):
    pod, agent = agent_pod["pod"], agent_pod["agent"]
    agent.close()
    agent_pod["tcp_ex"].close(drain=False)
    rng = np.random.default_rng(5)
    for _ in range(4):  # every request lands on the survivor
        v = _vals(plans, rng)
        got = np.asarray(pod.submit_backward(plans["sig"], v)
                         .result(timeout=120))
        assert np.array_equal(
            got, np.asarray(plans["plan"].backward(v)))
    assert not agent_pod["lane"].alive
    assert pod.health()["state"] == "degraded"


def test_membership_join_prewarm_and_leave(agent_pod, plans,
                                           tmp_path):
    """A TCP lane joins a live pod: prewarmed from the incumbent's
    signature set over the wire (builds == 0 via the blob tier),
    reconciled, serves, then drain-leaves."""
    pod = agent_pod["pod"]
    blob = FileBlobStore(str(tmp_path / "blob"))
    seed_store = PlanArtifactStore(str(tmp_path / "seed"), remote=blob)
    seed_store.save_plan(plans["sig"], plans["plan"], plans["trip"])
    seed_store.drain()

    reg = PlanRegistry(store=PlanArtifactStore(
        str(tmp_path / "join"), remote=blob))
    reg.put(plans["dsig"], plans["dplan"])  # derived, never serialized
    join_ex = ServeExecutor(reg)
    agent2 = HostAgent("t2", join_ex).start()
    lane2 = TcpHostLane("t2", ("127.0.0.1", agent2.port))
    try:
        pod.join(lane2)
        assert lane2.rpc_stats()["builds"] == 0
        rng = np.random.default_rng(6)
        for _ in range(6):
            v = _vals(plans, rng)
            got = np.asarray(pod.submit_backward(plans["sig"], v)
                             .result(timeout=120))
            assert np.array_equal(
                got, np.asarray(plans["plan"].backward(v)))
        routed = obs.GLOBAL_COUNTERS.snapshot()[
            "spfft_cluster_routed_total"]["samples"]
        assert any(dict(k).get("host") == "t2" and v >= 1
                   for k, v in routed.items())
        left = pod.leave("t2")
        assert left["drained"]
        events = {dict(k).get("event")
                  for k in obs.GLOBAL_COUNTERS.snapshot()
                  ["spfft_cluster_membership_total"]["samples"]}
        assert {"join_started", "prewarmed", "reconciled", "joined",
                "leave_started", "drained", "left"} <= events
    finally:
        lane2.close()
        agent2.close()
        join_ex.close(drain=False)


# ---------------------------------------------------------------------------
# the real thing: subprocess agents over localhost TCP
# ---------------------------------------------------------------------------

def _spawn(host, store, blob, warm):
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               JAX_ENABLE_X64="True",  # match the suite's x64 oracle
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    proc = subprocess.Popen(
        [sys.executable, "-m", "spfft_tpu.net.agent", "--host", host,
         "--port", "0", "--store", store, "--blob", blob,
         "--demo-warm", warm, "--trace"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        env=env)
    while True:
        line = proc.stdout.readline()
        if not line:
            proc.kill()
            raise RuntimeError(f"agent {host} died during warmup")
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if "port" in rec:
            return proc, int(rec["port"])


def test_two_process_pod_over_tcp(tmp_path):
    """Two real agent processes: mixed traffic bit-exact vs a serial
    oracle built here, then kill -9 one agent and the survivor keeps
    the trace bit-exact."""
    from spfft_tpu.parallel import make_distributed_plan, make_mesh
    from spfft_tpu.utils.workloads import (even_plane_split,
                                           round_robin_stick_partition)
    trip = cutoff_stick_triplets(N, N, N, 0.9, hermitian=False)
    reg = PlanRegistry()
    sig, plan = reg.get_or_build(TransformType.C2C, *DIMS, trip,
                                 precision="double")
    parts = round_robin_stick_partition(trip, DIMS, SHARDS)
    planes = even_plane_split(DIMS[2], SHARDS)
    dplan = make_distributed_plan(TransformType.C2C, *DIMS, parts,
                                  planes, mesh=make_mesh(SHARDS),
                                  precision="double")
    dsig = signature_for(TransformType.C2C, *DIMS, trip,
                         precision="double", device_count=SHARDS)

    blob = str(tmp_path / "blob")
    os.makedirs(blob)
    procs, lanes = {}, {}
    pod = None
    try:
        for host in ("p0", "p1"):
            procs[host], port = _spawn(
                host, str(tmp_path / f"store-{host}"), blob,
                f"{N},0.9,{SHARDS},full")
            lanes[host] = TcpHostLane(host, ("127.0.0.1", port))
        pod = PodFrontend([lanes["p0"], lanes["p1"]], policy="rr",
                          seed=0)
        rng = np.random.default_rng(7)
        for _ in range(6):
            v = rng.standard_normal(len(trip)) \
                + 1j * rng.standard_normal(len(trip))
            got = np.asarray(pod.submit_backward(sig, v)
                             .result(timeout=120))
            assert np.array_equal(got, np.asarray(plan.backward(v)))
        dvalues = [
            (rng.standard_normal(p.num_values)
             + 1j * rng.standard_normal(p.num_values))
            for p in dplan.dist_plan.shard_plans]
        dgot = np.asarray(pod.submit(dsig, dvalues).result(timeout=120))
        assert np.array_equal(dgot,
                              np.asarray(dplan.backward(dvalues)))

        procs["p1"].kill()
        procs["p1"].wait(timeout=30)
        for _ in range(4):
            v = rng.standard_normal(len(trip)) \
                + 1j * rng.standard_normal(len(trip))
            got = np.asarray(pod.submit_backward(sig, v)
                             .result(timeout=120))
            assert np.array_equal(got, np.asarray(plan.backward(v)))
        assert not lanes["p1"].alive
        assert pod.health()["state"] == "degraded"
    finally:
        if pod is not None:
            pod.close()
        for lane in lanes.values():
            lane.close()
        for proc in procs.values():
            proc.kill()
            proc.wait(timeout=10)

"""The exact-count (ragged) exchange — COMPACT_BUFFERED as a real
Alltoallv analogue.

Mirrors reference src/transpose/transpose_mpi_compact_buffered_host.cpp:
per-pair counts computed at plan time (:83-105), exact bytes on the wire
(:183-200). Here the checks are: the schedule's tables round-trip every
distribution scenario, the lowering is mechanically distinct from the
padded all_to_all, and the wire-bytes model strictly improves on padded
for non-uniform distributions."""

import re

import numpy as np
import pytest

import jax

from spfft_tpu import ExchangeType, Scaling, TransformType
from spfft_tpu.parallel import make_distributed_plan, make_mesh
from spfft_tpu.parallel.exchange import build_compact_schedule

from test_distributed import SCENARIOS, split_by_sticks, split_planes
from test_util import (dense_backward, dense_cube_from_values, dense_forward,
                       hermitian_triplets, random_sparse_triplets,
                       random_values, sample_cube, tolerance_for)


def _make_plan(dims, parts, planes, exchange, transform=TransformType.C2C,
               precision="double"):
    return make_distributed_plan(transform, *dims, parts, planes,
                                 mesh=make_mesh(len(parts)),
                                 precision=precision, exchange=exchange)


@pytest.mark.parametrize("scenario", sorted(SCENARIOS))
def test_compact_c2c_round_trip(scenario):
    """Backward then scaled forward returns the inputs for every
    distribution scenario (reference test_transform.cpp:110-165 matrix)."""
    rng = np.random.default_rng(33)
    dims = (11, 12, 13)
    stick_w, plane_w = SCENARIOS[scenario]
    triplets = random_sparse_triplets(rng, dims)
    values = random_values(rng, len(triplets))
    cube = dense_cube_from_values(triplets, values, dims)
    space_oracle = dense_backward(cube)
    parts = split_by_sticks(triplets, dims, stick_w)
    planes = split_planes(dims[2], plane_w)
    plan = _make_plan(dims, parts, planes, ExchangeType.COMPACT_BUFFERED)
    values_parts = [sample_cube(cube, p, dims) for p in parts]
    space = plan.backward(values_parts)
    got = np.concatenate([s for s in plan.unshard_space(space) if s.size],
                         axis=0)
    np.testing.assert_allclose(got, space_oracle,
                               atol=tolerance_for("double", space_oracle),
                               rtol=0)
    back = plan.unshard_values(plan.forward(space, Scaling.FULL))
    for g, v in zip(back, values_parts):
        np.testing.assert_allclose(g, v, atol=1e-10, rtol=0)


def test_compact_r2c():
    """Distributed R2C on the compact schedule (half-spectrum grid widths
    flow through the same tables via dim_x_freq)."""
    rng = np.random.default_rng(7)
    dims = (12, 11, 13)
    space_field = rng.standard_normal((dims[2], dims[1], dims[0]))
    freq = dense_forward(space_field.astype(np.complex128))
    triplets = hermitian_triplets(rng, dims)
    parts = split_by_sticks(triplets, dims, [1, 3, 0, 2])
    planes = split_planes(dims[2], [2, 0, 1, 1])
    plan = _make_plan(dims, parts, planes, ExchangeType.COMPACT_BUFFERED,
                      transform=TransformType.R2C)
    values = [sample_cube(freq, p, dims) for p in parts]
    got = np.concatenate([s for s in plan.unshard_space(plan.backward(values))
                          if s.size], axis=0)
    oracle = space_field * space_field.size
    np.testing.assert_allclose(got, oracle,
                               atol=tolerance_for("double", oracle), rtol=0)


def test_compact_fused_pair_and_scan():
    """apply_pointwise / iterate_pointwise run on the compact schedule."""
    rng = np.random.default_rng(11)
    dims = (10, 9, 11)
    triplets = random_sparse_triplets(rng, dims)
    parts = split_by_sticks(triplets, dims, [2, 1, 0, 1])
    planes = split_planes(dims[2], [1, 3, 1, 2])
    plan = _make_plan(dims, parts, planes, ExchangeType.COMPACT_BUFFERED)
    values = [random_values(rng, len(p)) for p in parts]
    got = plan.unshard_values(plan.apply_pointwise(values,
                                                   scaling=Scaling.FULL))
    for g, v in zip(got, values):
        np.testing.assert_allclose(g, v, atol=1e-10, rtol=0)
    it = plan.unshard_values(plan.iterate_pointwise(
        values, lambda s: s, steps=2, scaling=Scaling.FULL))
    for g, v in zip(it, values):
        np.testing.assert_allclose(g, v, atol=1e-9, rtol=0)


def test_compact_hlo_mechanically_distinct(monkeypatch):
    """The ppermute-schedule variant of the compact plan lowers to
    collective-permute hops with NO all-to-all; the padded plan lowers
    to all-to-all (VERDICT: assert a mechanically distinct lowering, not
    an alias). The DEFAULT compact mechanism is now the one-collective
    ragged exchange (test_ragged_exchange.py); the schedule stays
    available via SPFFT_TPU_COMPACT_PPERMUTE."""
    monkeypatch.setenv("SPFFT_TPU_COMPACT_PPERMUTE", "1")
    rng = np.random.default_rng(3)
    dims = (8, 8, 8)
    triplets = random_sparse_triplets(rng, dims)
    parts = split_by_sticks(triplets, dims, [1, 2, 1, 0])
    planes = split_planes(dims[2], [1, 1, 2, 0])

    def hlo_for(exchange):
        plan = _make_plan(dims, parts, planes, exchange)
        values = plan.shard_values(
            [random_values(rng, len(p)) for p in parts])
        return plan._backward_jit.lower(
            values, *plan._device_tables).as_text()

    compact = hlo_for(ExchangeType.COMPACT_BUFFERED)
    padded = hlo_for(ExchangeType.BUFFERED)
    assert ("collective_permute" in compact
            and "all_to_all" not in compact)
    assert "all_to_all" in padded


def test_wire_bytes_model():
    """Wire models: compact <= padded always, on BOTH the aggregate and
    the busiest-link metric; strictly less (aggregate) on a non-uniform
    distribution. Float wire halves them."""
    rng = np.random.default_rng(19)
    dims = (16, 16, 16)
    triplets = random_sparse_triplets(rng, dims)

    for weights, strict in ((([1, 1, 1, 1], [1, 1, 1, 1]), False),
                            (([3, 0, 1, 2], [1, 2, 0, 3]), True)):
        (stick_w, plane_w) = weights
        parts = split_by_sticks(triplets, dims, stick_w)
        planes = split_planes(dims[2], plane_w)
        padded = _make_plan(dims, parts, planes, ExchangeType.BUFFERED)
        compact = _make_plan(dims, parts, planes,
                             ExchangeType.COMPACT_BUFFERED)
        b_pad, b_cmp = (padded.exchange_wire_bytes(),
                        compact.exchange_wire_bytes())
        assert b_cmp <= b_pad
        assert compact.exchange_busiest_link_bytes() \
            <= padded.exchange_busiest_link_bytes()
        if strict:
            assert b_cmp < b_pad, (b_cmp, b_pad)
        cf = _make_plan(dims, parts, planes,
                        ExchangeType.COMPACT_BUFFERED_FLOAT)
        assert cf.exchange_wire_bytes() == b_cmp // 2


def test_bucketing_never_exceeds_padded():
    """Regression: pair sizes just above a power of two must not bucket
    past the hop max (unclamped pow2 buckets once shipped MORE than the
    padded layout)."""
    from spfft_tpu.parallel.exchange import _size_classes
    sizes = {j: 1040 + 16 * j for j in range(6)}  # 6 distinct, >4 forces
    classes = _size_classes(sizes)                # bucketing
    hop_max = max(sizes.values())
    assert all(L <= hop_max for L, _ in classes)
    assert sum(len(js) for _, js in classes) == 6


def test_plane_skew_saves_wire():
    """Uniform sticks + one big-plane shard: a per-hop-max schedule would
    pad every hop to the big destination and save nothing; the size-classed
    schedule must track the true per-pair counts (≈ Alltoallv)."""
    rng = np.random.default_rng(29)
    dims = (12, 12, 16)
    triplets = random_sparse_triplets(rng, dims)
    parts = split_by_sticks(triplets, dims, [1, 1, 1, 1])
    planes = [10, 2, 2, 2]  # one shard owns most planes
    padded = _make_plan(dims, parts, planes, ExchangeType.BUFFERED)
    compact = _make_plan(dims, parts, planes, ExchangeType.COMPACT_BUFFERED)
    b_pad = padded.exchange_wire_bytes()
    b_cmp = compact.exchange_wire_bytes()
    # aggregate: each shard sends ~ns*(10+2+2) vs padded 3*ns_max*10 —
    # must save >40%. The busiest LINK (the big plane-owner's ingress) is
    # real payload and must not regress vs padded.
    assert b_cmp < 0.6 * b_pad, (b_cmp, b_pad)
    assert compact.exchange_busiest_link_bytes() \
        <= padded.exchange_busiest_link_bytes()
    # and stays correct
    values = [random_values(rng, len(p)) for p in parts]
    got = compact.unshard_values(
        compact.apply_pointwise(values, scaling=Scaling.FULL))
    for g, v in zip(got, values):
        np.testing.assert_allclose(g, v, atol=1e-10, rtol=0)


def test_size_class_bucketing_round_trip(monkeypatch):
    """More than 4 distinct pair sizes per hop forces bucketing in the
    ppermute schedule; the schedule must stay correct (8 shards,
    all-different plane counts and stick counts)."""
    monkeypatch.setenv("SPFFT_TPU_COMPACT_PPERMUTE", "1")
    rng = np.random.default_rng(30)
    dims = (14, 14, 36)
    triplets = random_sparse_triplets(rng, dims)
    parts = split_by_sticks(triplets, dims, [1, 2, 3, 4, 5, 6, 7, 8])
    planes = [1, 2, 3, 4, 5, 6, 7, 8]
    plan = make_distributed_plan(
        TransformType.C2C, *dims, parts, planes, mesh=make_mesh(8),
        precision="double", exchange=ExchangeType.COMPACT_BUFFERED)
    sched = plan._compact
    assert any(len({L for k2, L, _ in sched.ops if k2 == k}) > 1
               for k in range(8)), "expected multiple size classes in a hop"
    values = [random_values(rng, len(p)) for p in parts]
    got = plan.unshard_values(
        plan.apply_pointwise(values, scaling=Scaling.FULL))
    for g, v in zip(got, values):
        np.testing.assert_allclose(g, v, atol=1e-10, rtol=0)


def test_schedule_tables_consistent():
    """Plan-time schedule invariants: hop sizes cover every per-pair count,
    every real (stick, plane) element appears exactly once in the unpack
    tables, and the two directions share hop widths."""
    rng = np.random.default_rng(23)
    dims = (9, 10, 11)
    triplets = random_sparse_triplets(rng, dims)
    parts = split_by_sticks(triplets, dims, [2, 0, 1, 3])
    planes = split_planes(dims[2], [1, 2, 0, 3])
    plan = _make_plan(dims, parts, planes, ExchangeType.COMPACT_BUFFERED)
    dp = plan.dist_plan
    sched = build_compact_schedule(dp)
    S = dp.num_shards
    ns = [p.num_sticks for p in dp.shard_plans]
    op_of_pair = {}
    for k, L, pairs in sched.ops:
        for pr in pairs:
            assert pr not in op_of_pair, "pair carried by two ops"
            op_of_pair[pr] = L
    for k in range(S):
        for j in range(S):
            d = (j + k) % S
            count = ns[j] * dp.num_planes[d]
            if count:  # every nonzero pair is carried, with enough room
                assert count <= op_of_pair[(j, d)]
            else:
                assert (j, d) not in op_of_pair
    # backward unpack covers each shard's true (plane, occupied column)
    # cells exactly once, with sentinels everywhere else
    total = sched.total_recv
    Y, Xf = dp.dim_y, dp.dim_x_freq
    total_sticks = sum(ns)
    for r in range(S):
        tbl = sched.bwd_unpack[r]
        n_real = total_sticks * dp.num_planes[r]
        valid = tbl[tbl < total]
        assert len(valid) == n_real
        assert len(np.unique(valid)) == n_real
    for r in range(S):
        tbl = sched.fwd_unpack[r]
        valid = tbl[tbl < total]
        assert len(valid) == ns[r] * dp.dim_z
        assert len(np.unique(valid)) == len(valid)


# -- wire-byte model vs the actually-lowered collectives ---------------------

from spfft_tpu.utils.hlo_inspect import hlo_wire_bytes as _shared_hlo_wire_bytes


def _hlo_wire_bytes(txt, num_shards):
    return _shared_hlo_wire_bytes(txt, num_shards)


HLO_SCENARIOS = {
    "uniform": ([1, 1, 1, 1], [1, 1, 1, 1]),
    "all_on_first": ([1, 0, 0, 0], [1, 0, 0, 0]),
    "sticks_first_planes_last": ([2, 1, 1, 0], [0, 1, 1, 2]),
    "random_nonuniform": ([1, 3, 2, 1], [2, 1, 3, 1]),
}

HLO_MECHANISMS = (ExchangeType.BUFFERED, ExchangeType.BUFFERED_FLOAT,
                  ExchangeType.COMPACT_BUFFERED,
                  ExchangeType.COMPACT_BUFFERED_FLOAT,
                  ExchangeType.UNBUFFERED)


@pytest.mark.parametrize("scenario", sorted(HLO_SCENARIOS))
def test_wire_byte_model_matches_lowered_hlo(scenario, monkeypatch):
    """exchange_wire_bytes() / exchange_busiest_link_bytes() must equal the
    byte counts of the collectives ACTUALLY lowered into the SPMD module,
    for every mechanism and wire precision (VERDICT r2: the model drove
    the BENCHMARKS claims but was never checked against the compiled
    program; reference counts/displs:
    transpose_mpi_compact_buffered_host.cpp:83-105). COMPACT here pins
    the ppermute schedule — the default ragged collective's wire traffic
    is data-dependent (not derivable from static HLO shapes); its model
    is validated at the table level in test_ragged_exchange.py."""
    monkeypatch.setenv("SPFFT_TPU_COMPACT_PPERMUTE", "1")
    rng = np.random.default_rng(23)
    dims = (12, 12, 12)
    triplets = random_sparse_triplets(rng, dims)
    sw, pw = HLO_SCENARIOS[scenario]
    parts = split_by_sticks(triplets, dims, sw)
    planes = split_planes(dims[2], pw)
    for exchange in HLO_MECHANISMS:
        plan = _make_plan(dims, parts, planes, exchange)
        values = plan.shard_values(
            [random_values(rng, len(p)) for p in parts])
        txt = plan._backward_jit.lower(
            values, *plan._device_tables).as_text()
        total, sent, recv = _hlo_wire_bytes(txt, plan.dist_plan.num_shards)
        assert total == plan.exchange_wire_bytes(), \
            f"{scenario}/{exchange}: HLO {total} != model " \
            f"{plan.exchange_wire_bytes()}"
        busiest = int(np.maximum(sent, recv).max())
        assert busiest == plan.exchange_busiest_link_bytes(), \
            f"{scenario}/{exchange}: HLO busiest {busiest} != model " \
            f"{plan.exchange_busiest_link_bytes()}"


def test_bucketed_wire_within_125pct_of_exact():
    """The size-class bucketing is bounded: TOTAL compact wire elements
    stay under BUCKET_FACTOR (1.25x) of the EXACT Alltoallv counts even
    when every hop has many distinct pair sizes (VERDICT r3 weak #5: the
    round-3 factor-2 buckets could charge a pair 2x; reference ships
    exact counts, transpose_mpi_compact_buffered_host.cpp:83-105)."""
    from spfft_tpu.parallel.exchange import BUCKET_FACTOR
    rng = np.random.default_rng(77)
    S = 16
    for trial in range(5):
        # random highly-skewed stick/plane ownership: many distinct
        # ns(j) * np(d) products per hop -> bucketing engages
        ns = rng.integers(1, 400, S)
        npl = rng.integers(0, 9, S)
        npl[npl.sum() == 0 and 0 or 0] += 1  # ensure nonzero total

        class _SP:
            def __init__(self, n):
                self.num_sticks = n
                self.scatter_cols = np.arange(n, dtype=np.int64)

        class _DP:  # duck-typed DistributedIndexPlan view
            num_shards = S
            max_sticks = int(ns.max())
            max_planes = max(int(npl.max()), 1)
            dim_z = int(npl.sum())
            dim_y = 4
            dim_x_freq = 400
            num_planes = [int(v) for v in npl]
            plane_offsets = [int(v) for v in
                             np.concatenate([[0], np.cumsum(npl)[:-1]])]
            shard_plans = [_SP(int(n)) for n in ns]
        if _DP.dim_z == 0:
            continue
        sched = build_compact_schedule(_DP)
        exact = sum(int(ns[j]) * int(npl[d])
                    for j in range(S) for d in range(S)
                    if (d - j) % S != 0)
        assert sched.wire_elements() <= BUCKET_FACTOR * exact + S, \
            (sched.wire_elements(), exact)


def test_exact_classes_when_few_sizes():
    """Hops with <= MAX_EXACT_CLASSES distinct sizes ship exact counts
    (zero bucketing waste)."""
    from spfft_tpu.parallel.exchange import _size_classes
    sizes = {0: 10, 1: 20, 2: 10, 3: 40, 4: 20, 5: 80, 6: 160, 7: 320}
    classes = _size_classes(sizes)  # 6 distinct sizes <= 8 -> exact
    got = {L: sorted(js) for L, js in classes}
    assert got == {10: [0, 2], 20: [1, 4], 40: [3], 80: [5], 160: [6],
                   320: [7]}


def test_bucket_ladder_ratio_bound():
    from spfft_tpu.parallel.exchange import BUCKET_FACTOR, _bucket_ladder
    ladder = _bucket_ladder(10 ** 7)
    for a, b in zip(ladder, ladder[1:]):
        assert b <= max(a + 1, a * BUCKET_FACTOR)

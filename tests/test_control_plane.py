"""Telemetry-driven control plane (spfft_tpu/control/).

The load-bearing properties, each deterministic on CPU:

* ServeConfig — single typed home of every knob: bounds-clamped
  writes, recorded decisions (history + spfft_control_* counters),
  artifact round-trip, env boot, hot-swap visible to a live executor;
* Controller scenarios — scripted telemetry sequences drive the rules:
  queue buildup shrinks the batching window, a pad-heavy trace
  tightens the pin policy, full-bucket backlog grows the bucket cap,
  idle decays every managed knob back to its default;
* stability invariants — hysteresis dead band (no decision between
  the thresholds), cooldown (no oscillation of one knob within its
  settling window), and an 8-thread fuzz in which knobs NEVER leave
  their declared bounds;
* correctness across retune — results stay bit-exact vs the serial
  oracle while a controller thread retunes the executor mid-stream
  (the acceptance criterion's no-deviation half);
* SLO watchdog — declared objectives evaluated against metrics:
  violations degrade health() and export spfft_slo_* gauges, a healthy
  trace raises NO false positive, recovery clears the degradation;
* HTTP scrape endpoint — /metrics round-trips the exposition parser,
  /healthz carries readiness semantics (200 servable / 503 failed),
  /configz exposes the live knob values.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from spfft_tpu import TransformType
from spfft_tpu.control import (KNOB_SPECS, ControlLoop, Controller,
                               ServeConfig, SLOSpec, SLOWatchdog)
from spfft_tpu.errors import InvalidParameterError
from spfft_tpu.obs.http import MetricsServer
from spfft_tpu.serve import PlanRegistry, ServeExecutor
from spfft_tpu.serve.metrics import ServeMetrics

from test_util import random_sparse_triplets

DIMS = (12, 13, 11)


def _registry():
    reg = PlanRegistry()
    rng = np.random.default_rng(3)
    t = random_sparse_triplets(rng, DIMS)
    sig, plan = reg.get_or_build(TransformType.C2C, *DIMS, t,
                                 precision="double")
    return reg, sig, plan


def _values(plan, rng):
    n = plan.index_plan.num_values
    return rng.uniform(-1, 1, n) + 1j * rng.uniform(-1, 1, n)


# -- ServeConfig ------------------------------------------------------------
def test_config_defaults_match_specs():
    cfg = ServeConfig()
    snap = cfg.snapshot()
    for name, spec in KNOB_SPECS.items():
        assert snap[name] == spec.default
        assert spec.lo <= spec.default <= spec.hi
    assert cfg.batch_window == 0.001 and cfg.max_batch == 8


def test_config_set_clamps_and_records_decisions():
    cfg = ServeConfig()
    lo, hi = ServeConfig.bounds("batch_window")
    v = cfg.set("batch_window", 99.0, reason="way out", source="test")
    assert v == hi
    v = cfg.set("max_batch", -5, source="test")
    assert v == ServeConfig.bounds("max_batch")[0]
    hist = cfg.decisions()
    assert len(hist) == 2
    assert hist[0]["knob"] == "batch_window" and hist[0]["clamped"]
    assert hist[0]["requested"] == 99.0 and hist[0]["new"] == hi
    assert cfg.decision_count() == 2
    assert cfg.decision_count("test") == 2
    # a write that does not move the knob records nothing
    before = cfg.decision_count()
    assert cfg.set("max_batch", cfg.max_batch) == cfg.max_batch
    assert cfg.decision_count() == before


def test_config_unknown_knob_raises():
    cfg = ServeConfig()
    with pytest.raises(InvalidParameterError):
        cfg.set("warp_factor", 9)
    with pytest.raises(InvalidParameterError):
        cfg.get("warp_factor")
    with pytest.raises(InvalidParameterError):
        cfg.update({"batch_window": 0.0, "warp_factor": 9})
    # update validates ALL names before writing anything
    assert cfg.batch_window == ServeConfig.default("batch_window")
    with pytest.raises(AttributeError):
        cfg.warp_factor


def test_config_artifact_roundtrip(tmp_path):
    cfg = ServeConfig()
    cfg.set("batch_window", 0.004, source="tuner")
    cfg.set("max_batch", 16, source="tuner")
    path = tmp_path / "recommended.json"
    cfg.save(str(path), provenance={"protocol": "test"})
    loaded = ServeConfig.load(str(path))
    assert loaded.batch_window == 0.004 and loaded.max_batch == 16
    payload = json.loads(path.read_text())
    assert payload["spfft_tpu_serve_config"] == 1
    assert payload["provenance"]["protocol"] == "test"


def test_config_load_rejects_garbage(tmp_path):
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    with pytest.raises(InvalidParameterError):
        ServeConfig.load(str(bad))
    bad.write_text(json.dumps({"values": {"batch_window": 1}}))
    with pytest.raises(InvalidParameterError):  # missing schema marker
        ServeConfig.load(str(bad))
    bad.write_text(json.dumps({"spfft_tpu_serve_config": 1,
                               "values": {"warp_factor": 9}}))
    with pytest.raises(InvalidParameterError):  # unknown knob
        ServeConfig.load(str(bad))


def test_config_boot_env(tmp_path, monkeypatch):
    path = tmp_path / "boot.json"
    ServeConfig({"max_batch": 32}).save(str(path))
    monkeypatch.setenv("SPFFT_TPU_SERVE_CONFIG", str(path))
    assert ServeConfig.boot().max_batch == 32
    monkeypatch.delenv("SPFFT_TPU_SERVE_CONFIG")
    assert ServeConfig.boot().max_batch == \
        ServeConfig.default("max_batch")


def test_executor_constructor_overrides_and_hot_swap():
    """Explicit constructor knobs land in the config; a live set() is
    visible to the executor's next read (the hot-swap seam)."""
    reg, sig, plan = _registry()
    ex = ServeExecutor(reg, autostart=False, batch_window=0.0,
                       max_batch=4, pin_after=2)
    assert ex.config.batch_window == 0.0
    assert ex._max_batch == 4 and ex._pin_after == 2
    ex.config.set("max_batch", 6, source="test")
    assert ex._max_batch == 6
    ex.config.set("pipeline_depth", 3, source="test")
    assert ex._pipeline_slots() == 3
    ex.config.set("pipeline_depth", 0, source="test")  # back to auto
    assert ex._pipeline_slots() >= 1
    assert ex.health()["config"]["max_batch"] == 6
    ex.close()


def test_executor_invalid_explicit_knobs_still_raise():
    reg, sig, plan = _registry()
    with pytest.raises(InvalidParameterError):
        ServeExecutor(reg, max_batch=0, autostart=False)
    with pytest.raises(InvalidParameterError):
        ServeExecutor(reg, pipeline_depth=0, autostart=False)
    with pytest.raises(InvalidParameterError):
        ServeExecutor(reg, quarantine_backoff=0.0, autostart=False)


# -- controller scenarios (scripted telemetry, no executor needed) ----------
def _signals(completed=0, queue_depth=0, qw95=0.0, dx50=0.0,
             fused_rows=0, padded_rows=0, fused_hist=None,
             max_queue_depth=0, stage_s=0.0, dispatch_s=0.0,
             rejected=0, exchange_s=0.0, compute_s=0.0):
    return {"completed": completed, "failed": 0,
            "queue_depth": queue_depth,
            "max_queue_depth": max_queue_depth,
            "queue_wait_p95": qw95, "device_execute_p50": dx50,
            "fused_rows": fused_rows, "padded_rows": padded_rows,
            "fused_hist": fused_hist or {}, "stage_s": stage_s,
            "dispatch_s": dispatch_s, "quarantines": 0,
            "rejected_queue_full": rejected,
            "exchange_s": exchange_s,
            "exchange_compute_s": compute_s,
            "latency_p99": 0.0}


def test_controller_queue_buildup_shrinks_window():
    cfg = ServeConfig()
    ctl = Controller(cfg)
    ctl.step(_signals(completed=1))  # baseline
    decisions = ctl.step(_signals(completed=10, qw95=0.050, dx50=0.002))
    moved = [d for d in decisions if d.knob == "batch_window"]
    assert len(moved) == 1
    assert moved[0].new == pytest.approx(0.0005)
    assert moved[0].new < moved[0].old
    assert "queue buildup" in moved[0].reason


def test_controller_window_decays_when_drained():
    cfg = ServeConfig()
    cfg.set("batch_window", 0.00025, source="test")
    ctl = Controller(cfg, cooldown_steps=0)
    ctl.step(_signals(completed=1))
    ctl.step(_signals(completed=10, qw95=0.0, dx50=0.010))
    assert cfg.batch_window == pytest.approx(0.0005)
    ctl.step(_signals(completed=20, qw95=0.0, dx50=0.010))
    assert cfg.batch_window == pytest.approx(0.001)  # back at default
    ctl.step(_signals(completed=30, qw95=0.0, dx50=0.010))
    assert cfg.batch_window == pytest.approx(0.001)  # never overshoots


def test_controller_pad_heavy_tightens_pin_policy():
    cfg = ServeConfig()
    ctl = Controller(cfg, cooldown_steps=0)
    ctl.step(_signals(completed=1))
    # 3 pad rows per 5 live rows over the delta: pad-heavy
    decisions = ctl.step(_signals(completed=10, qw95=0.001, dx50=0.002,
                                  fused_rows=10, padded_rows=6))
    moved = [d for d in decisions if d.knob == "pin_after"]
    assert len(moved) == 1 and moved[0].new == moved[0].old - 1
    # pads gone -> decays back toward the default
    ctl.step(_signals(completed=20, qw95=0.001, dx50=0.002,
                      fused_rows=20, padded_rows=6))
    assert cfg.pin_after == ServeConfig.default("pin_after")


def test_controller_max_batch_grows_on_full_bucket_backlog():
    cfg = ServeConfig()
    ctl = Controller(cfg)
    ctl.step(_signals(completed=1))
    decisions = ctl.step(_signals(
        completed=40, qw95=0.001, dx50=0.002,
        fused_hist={8: 5}, max_queue_depth=40))
    moved = [d for d in decisions if d.knob == "max_batch"]
    assert len(moved) == 1 and moved[0].new == 16


def test_controller_max_batch_shrinks_when_buckets_small():
    cfg = ServeConfig()
    cfg.set("max_batch", 32, source="test")
    ctl = Controller(cfg)
    ctl.step(_signals(completed=1))
    ctl.step(_signals(completed=10, qw95=0.001, dx50=0.002,
                      fused_hist={4: 6}))
    assert cfg.max_batch == 16


def test_controller_max_queue_grows_on_sustained_reject_burn():
    """ROADMAP control follow-on #3: sustained rejected_queue_full
    burn doubles max_queue within its declared bounds; a single blip
    moves nothing (the streak is the hysteresis)."""
    cfg = ServeConfig()
    ctl = Controller(cfg, cooldown_steps=0)
    ctl.step(_signals(completed=1))                      # baseline
    # one reject step: backpressure doing its job, no move yet
    d1 = ctl.step(_signals(completed=5, queue_depth=10, rejected=4))
    assert not [d for d in d1 if d.knob == "max_queue"]
    assert cfg.max_queue == ServeConfig.default("max_queue")
    # second consecutive reject step: sustained burn -> double
    d2 = ctl.step(_signals(completed=9, queue_depth=12, rejected=11))
    moved = [d for d in d2 if d.knob == "max_queue"]
    assert len(moved) == 1
    assert moved[0].new == 2 * ServeConfig.default("max_queue")
    assert "queue-full burn" in moved[0].reason
    # the burn continues: grows again, still bounds-clamped
    ctl.step(_signals(completed=12, queue_depth=12, rejected=15))
    ctl.step(_signals(completed=15, queue_depth=12, rejected=20))
    assert cfg.max_queue == 4 * ServeConfig.default("max_queue")
    lo, hi = ServeConfig.bounds("max_queue")
    assert lo <= cfg.max_queue <= hi


def test_controller_lease_ttl_widens_under_rtt_inflation():
    """Round-21 membership rule: sustained wire RTT above 20% of the
    lease TTL doubles ``lease_ttl_ms`` (a slow fabric must not look
    like mass death); a single RTT spike moves nothing, and idle
    steps decay the widened TTL back by halving."""
    cfg = ServeConfig()
    default = ServeConfig.default("lease_ttl_ms")
    ctl = Controller(cfg, cooldown_steps=0)
    ctl.step(_signals(completed=1))                       # baseline
    # one inflated-RTT step: a blip, no move
    s = _signals(completed=5)
    s["wire_rtt"] = 0.5                                   # > 0.2 * 1.5s
    assert not [d for d in ctl.step(dict(s))
                if d.knob == "lease_ttl_ms"]
    assert cfg.lease_ttl_ms == default
    # second consecutive step: sustained inflation -> double
    s["completed"] = 9
    moved = [d for d in ctl.step(dict(s))
             if d.knob == "lease_ttl_ms"]
    assert len(moved) == 1 and moved[0].new == 2 * default
    assert "RTT" in moved[0].reason
    # the fabric recovers: idle decay halves back to the default
    ctl.step(_signals(completed=9))
    assert cfg.lease_ttl_ms == default


def test_controller_max_queue_blip_then_quiet_never_moves():
    cfg = ServeConfig()
    ctl = Controller(cfg, cooldown_steps=0)
    ctl.step(_signals(completed=1))
    ctl.step(_signals(completed=5, queue_depth=4, rejected=2))   # blip
    ctl.step(_signals(completed=9, queue_depth=2, rejected=2))   # quiet
    ctl.step(_signals(completed=12, queue_depth=1, rejected=2))
    assert cfg.max_queue == ServeConfig.default("max_queue")
    assert not [d for d in ctl.decisions() if d.knob == "max_queue"]


def test_controller_max_queue_clamps_at_declared_bound():
    cfg = ServeConfig()
    _, hi = ServeConfig.bounds("max_queue")
    cfg.set("max_queue", hi, source="test")
    ctl = Controller(cfg, cooldown_steps=0)
    ctl.step(_signals(completed=1))
    ctl.step(_signals(completed=5, queue_depth=9, rejected=3))
    ctl.step(_signals(completed=9, queue_depth=9, rejected=9))
    assert cfg.max_queue == hi   # clamp held, no runaway


def test_controller_max_queue_idle_decays_by_halving():
    cfg = ServeConfig()
    default = ServeConfig.default("max_queue")
    cfg.set("max_queue", 4 * default, source="test")
    ctl = Controller(cfg, cooldown_steps=0)
    ctl.step(_signals(completed=5))          # baseline with traffic
    ctl.step(_signals(completed=5))          # idle
    assert cfg.max_queue == 2 * default
    ctl.step(_signals(completed=5))
    assert cfg.max_queue == default
    ctl.step(_signals(completed=5))
    assert cfg.max_queue == default          # never undershoots


def test_controller_overlap_chunks_grows_on_sustained_exposed_exchange():
    """Round-18 satellite: exchange time rivaling compute time on
    CONSECUTIVE distributed steps doubles overlap_chunks within its
    declared clamp; one chunky step moves nothing (the streak is the
    hysteresis, mirroring the max_queue rule)."""
    cfg = ServeConfig()
    ctl = Controller(cfg, cooldown_steps=0)
    ctl.step(_signals(completed=1))                      # baseline
    # one exposed-exchange step: no move yet
    d1 = ctl.step(_signals(completed=5, exchange_s=0.4, compute_s=0.2))
    assert not [d for d in d1 if d.knob == "overlap_chunks"]
    assert cfg.overlap_chunks == ServeConfig.default("overlap_chunks")
    # second consecutive exposed step: sustained -> double
    d2 = ctl.step(_signals(completed=9, exchange_s=0.9, compute_s=0.4))
    moved = [d for d in d2 if d.knob == "overlap_chunks"]
    assert len(moved) == 1
    assert moved[0].new == 2 * ServeConfig.default("overlap_chunks")
    assert "exchange rivals compute" in moved[0].reason
    # burn continues -> grows again, still bounds-clamped
    ctl.step(_signals(completed=12, exchange_s=1.5, compute_s=0.6))
    ctl.step(_signals(completed=15, exchange_s=2.2, compute_s=0.8))
    assert cfg.overlap_chunks == 4 * ServeConfig.default("overlap_chunks")
    lo, hi = ServeConfig.bounds("overlap_chunks")
    assert lo <= cfg.overlap_chunks <= hi


def test_controller_overlap_chunks_decays_when_exchange_hidden():
    """Exchange well below compute halves K back toward the K=1
    default (the bit-identical monolithic path); local-only steps
    (no exchange/compute delta) reset the streak and move nothing."""
    cfg = ServeConfig()
    cfg.set("overlap_chunks", 8, source="test")
    ctl = Controller(cfg, cooldown_steps=0)
    ctl.step(_signals(completed=1))
    # hidden exchange: 0.02 / 0.5 = 0.04 < overlap_lo (0.25) -> halve
    ctl.step(_signals(completed=5, exchange_s=0.02, compute_s=0.5))
    assert cfg.overlap_chunks == 4
    ctl.step(_signals(completed=9, exchange_s=0.04, compute_s=1.0))
    assert cfg.overlap_chunks == 2
    # local-only traffic: nothing distributed ran, nothing moves
    ctl.step(_signals(completed=12))
    assert cfg.overlap_chunks == 2
    ctl.step(_signals(completed=15, exchange_s=0.06, compute_s=1.5))
    assert cfg.overlap_chunks == ServeConfig.default("overlap_chunks")
    ctl.step(_signals(completed=18, exchange_s=0.08, compute_s=2.0))
    assert cfg.overlap_chunks == ServeConfig.default("overlap_chunks")


def test_controller_overlap_chunks_streak_broken_by_local_step():
    """A local-only step between two exposed-exchange steps breaks the
    streak — the rule needs CONSECUTIVE evidence, so alternating
    traffic never ratchets K."""
    cfg = ServeConfig()
    ctl = Controller(cfg, cooldown_steps=0)
    ctl.step(_signals(completed=1))
    ctl.step(_signals(completed=5, exchange_s=0.4, compute_s=0.2))
    ctl.step(_signals(completed=9))                      # local only
    ctl.step(_signals(completed=12, exchange_s=0.8, compute_s=0.4))
    assert cfg.overlap_chunks == ServeConfig.default("overlap_chunks")
    assert not [d for d in ctl.decisions()
                if d.knob == "overlap_chunks"]


def test_controller_overlap_chunks_idle_decays_by_halving():
    cfg = ServeConfig()
    cfg.set("overlap_chunks", 4, source="test")
    ctl = Controller(cfg, cooldown_steps=0)
    ctl.step(_signals(completed=5))          # baseline with traffic
    ctl.step(_signals(completed=5))          # idle
    assert cfg.overlap_chunks == 2
    ctl.step(_signals(completed=5))
    assert cfg.overlap_chunks == ServeConfig.default("overlap_chunks")
    ctl.step(_signals(completed=5))
    assert cfg.overlap_chunks == ServeConfig.default("overlap_chunks")


def test_metrics_record_exchange_overlap_feeds_signals():
    """ServeMetrics carries the cumulative exchange/compute second
    pair the overlap_chunks rule diffs."""
    from spfft_tpu.serve import ServeMetrics
    m = ServeMetrics()
    m.record_exchange_overlap(0.25, 0.75)
    m.record_exchange_overlap(0.05, 0.10)
    s = m.signals()
    assert s["exchange_s"] == pytest.approx(0.30)
    assert s["exchange_compute_s"] == pytest.approx(0.85)


def test_controller_idle_decays_managed_knobs_to_defaults():
    cfg = ServeConfig()
    cfg.update({"batch_window": 0.000125, "pin_after": 1,
                "max_batch": 16}, source="test")
    ctl = Controller(cfg, cooldown_steps=0)
    ctl.step(_signals(completed=5))  # baseline with traffic
    for _ in range(8):  # idle: no new completions, empty queue
        ctl.step(_signals(completed=5))
    assert cfg.batch_window == pytest.approx(
        ServeConfig.default("batch_window"))
    assert cfg.pin_after == ServeConfig.default("pin_after")
    assert cfg.max_batch == ServeConfig.default("max_batch")


def test_controller_hysteresis_dead_band():
    """A signal BETWEEN the shrink and grow thresholds moves nothing."""
    cfg = ServeConfig()
    ctl = Controller(cfg, cooldown_steps=0)
    ctl.step(_signals(completed=1))
    # qw95 = 1x dx50: below shrink_ratio (2.0), above grow_ratio (0.5)
    for k in range(5):
        decisions = ctl.step(_signals(completed=10 + k, qw95=0.002,
                                      dx50=0.002))
        assert decisions == []
    assert cfg.batch_window == ServeConfig.default("batch_window")


def test_controller_cooldown_blocks_oscillation():
    """After a knob moves, opposite pressure within the cooldown window
    cannot move it back; after the cooldown it can."""
    cfg = ServeConfig()
    ctl = Controller(cfg, cooldown_steps=3)
    ctl.step(_signals(completed=1))
    ctl.step(_signals(completed=10, qw95=0.050, dx50=0.002))
    assert cfg.batch_window == pytest.approx(0.0005)  # shrank
    changed_at = cfg.batch_window
    for k in range(3):  # drained signal inside the cooldown window
        decisions = ctl.step(_signals(completed=20 + k, qw95=0.0,
                                      dx50=0.010))
        assert all(d.knob != "batch_window" for d in decisions)
        assert cfg.batch_window == changed_at
    ctl.step(_signals(completed=40, qw95=0.0, dx50=0.010))
    assert cfg.batch_window > changed_at  # cooldown over: grew


def test_controller_pipeline_depth_rule_uses_executor_auto():
    reg, sig, plan = _registry()
    ex = ServeExecutor(reg, autostart=False)
    cfg = ex.config
    ctl = Controller(cfg, executor=ex, cooldown_steps=0)
    ctl.step(_signals(completed=1))
    auto = ex._pipeline_slots()
    # staging cost rivals dispatch cost: deepen by one over auto
    ctl.step(_signals(completed=10, qw95=0.001, dx50=0.002,
                      stage_s=0.6, dispatch_s=1.0))
    assert cfg.pipeline_depth == auto + 1
    # staging negligible: decay back toward auto (0)
    ctl.step(_signals(completed=20, qw95=0.001, dx50=0.002,
                      stage_s=0.6, dispatch_s=11.0))
    assert cfg.pipeline_depth in (0, auto)
    ex.close()


def test_controller_fuzz_knobs_never_leave_bounds():
    """8 threads of adversarial writes + controller steps over
    pseudo-random telemetry: every knob stays inside its declared
    bounds at every observation, and nothing raises."""
    cfg = ServeConfig()
    ctl = Controller(cfg, cooldown_steps=0)
    errors = []
    stop = threading.Event()

    def check_bounds():
        snap = cfg.snapshot()
        for name, value in snap.items():
            lo, hi = ServeConfig.bounds(name)
            if not lo <= value <= hi:
                errors.append(f"{name}={value} outside [{lo}, {hi}]")

    def hammer(seed):
        rng = np.random.default_rng(seed)
        knobs = list(KNOB_SPECS)
        try:
            for i in range(200):
                name = knobs[int(rng.integers(len(knobs)))]
                # adversarial values: far outside bounds both ways
                value = float(rng.uniform(-1e9, 1e9))
                cfg.set(name, value, source=f"fuzz{seed}")
                check_bounds()
        except Exception as exc:  # pragma: no cover
            errors.append(repr(exc))

    def steer(seed):
        rng = np.random.default_rng(seed)
        try:
            for i in range(100):
                ctl.step(_signals(
                    completed=i * 3,
                    qw95=float(rng.uniform(0, 0.1)),
                    dx50=float(rng.uniform(0, 0.01)),
                    fused_rows=i * 8,
                    padded_rows=int(rng.integers(0, i * 4 + 1)),
                    fused_hist={8: i},
                    max_queue_depth=int(rng.integers(0, 100))))
                check_bounds()
        except Exception as exc:  # pragma: no cover
            errors.append(repr(exc))

    threads = [threading.Thread(target=hammer, args=(s,))
               for s in range(6)]
    threads += [threading.Thread(target=steer, args=(s,))
                for s in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stop.set()
    assert errors == []
    check_bounds()
    assert errors == []


# -- bit-exactness across mid-stream retune ---------------------------------
def test_mid_stream_retune_is_bit_exact():
    """Results while a controller thread retunes window / max_batch /
    pin_after mid-stream are BIT-IDENTICAL to each request's serial
    execution (the acceptance criterion's no-correctness-deviation
    half)."""
    reg, sig, plan = _registry()
    rng = np.random.default_rng(11)
    vals = [_values(plan, rng) for _ in range(60)]
    oracles = [np.asarray(plan.backward(v)) for v in vals]
    ex = ServeExecutor(reg, batch_window=0.0005, max_batch=8)
    stop = threading.Event()

    def retuner():
        flip = 0
        while not stop.is_set():
            ex.config.set("batch_window",
                          0.0 if flip % 2 else 0.002, source="test")
            ex.config.set("max_batch", 4 if flip % 3 else 8,
                          source="test")
            ex.config.set("pin_after", 1 + flip % 3, source="test")
            flip += 1
            time.sleep(0.001)

    t = threading.Thread(target=retuner)
    t.start()
    try:
        futures = [ex.submit(sig, v) for v in vals]
        results = [np.asarray(f.result(timeout=60)) for f in futures]
    finally:
        stop.set()
        t.join()
        ex.close()
    for i, (got, want) in enumerate(zip(results, oracles)):
        assert np.array_equal(got, want), f"request {i} diverged"
    lo, hi = ServeConfig.bounds("batch_window")
    assert lo <= ex.config.batch_window <= hi


# -- SLO watchdog -----------------------------------------------------------
def test_slo_spec_parse_forms(tmp_path):
    spec = SLOSpec.parse("p99_ms=50,error_rate=0.01,max_quarantines=0")
    assert spec.latency_p99_s == pytest.approx(0.050)
    assert spec.error_rate == 0.01 and spec.max_quarantines == 0
    assert SLOSpec.parse("p99_s=2").latency_p99_s == 2.0
    f = tmp_path / "slo.json"
    f.write_text(json.dumps({"latency_p99_s": 0.1, "error_rate": 0.5}))
    spec = SLOSpec.parse(f"@{f}")
    assert spec.latency_p99_s == 0.1 and spec.max_quarantines is None
    for bad in ("p99_ms", "p99_ms=abc", "uptime=0.999"):
        with pytest.raises(InvalidParameterError):
            SLOSpec.parse(bad)
    with pytest.raises(InvalidParameterError):
        SLOSpec(latency_p99_s=-1.0)


def test_slo_watchdog_violation_degrades_health_and_recovers():
    metrics = ServeMetrics()
    for _ in range(20):
        metrics.record_request_done(0.200)  # 200 ms completions
    dog = SLOWatchdog(metrics, SLOSpec(latency_p99_s=0.050))
    verdict = dog.evaluate()
    assert verdict["violations"] == ["latency_p99_s"]
    assert verdict["burn"]["latency_p99_s"] == pytest.approx(4.0)
    health = metrics.health()
    assert health["state"] == "degraded"          # SLO burn degrades
    assert health["lifecycle_state"] == "healthy"  # ...but not masks
    assert health["slo_violations"] == ["latency_p99_s"]
    from spfft_tpu import obs
    assert obs.GLOBAL_COUNTERS.get("spfft_slo_violation",
                                   slo="latency_p99_s") == 1
    # recovery: fast completions refill the window, burn drops
    for _ in range(metrics._window):
        metrics.record_request_done(0.001)
    verdict = dog.evaluate()
    assert verdict["violations"] == []
    assert metrics.health()["state"] == "healthy"


def test_slo_watchdog_no_false_positive_on_healthy_trace():
    metrics = ServeMetrics()
    for _ in range(50):
        metrics.record_request_done(0.002)
    dog = SLOWatchdog(metrics, SLOSpec(latency_p99_s=0.050,
                                       error_rate=0.01,
                                       max_quarantines=0))
    assert dog.evaluate()["violations"] == []
    assert metrics.health()["state"] == "healthy"


def test_slo_zero_objective_burns_infinitely():
    metrics = ServeMetrics()
    metrics.record_request_done(0.001)
    metrics.record_quarantine()
    dog = SLOWatchdog(metrics, SLOSpec(max_quarantines=0))
    verdict = dog.evaluate()
    assert verdict["violations"] == ["max_quarantines"]
    assert verdict["burn"]["max_quarantines"] == float("inf")


def test_slo_never_masks_worse_lifecycle_state():
    metrics = ServeMetrics()
    metrics.record_health("failed")
    metrics.record_slo(["error_rate"])
    assert metrics.health()["state"] == "failed"


# -- multi-window SLO alerting (round 18) -----------------------------------
def _slo_signals(p99):
    return {"completed": 10, "failed": 0, "latency_p99": p99,
            "quarantines": 0}


def test_slo_multiwindow_pages_only_on_sustained_burn():
    """The SRE-workbook shape: both the fast and the slow window must
    burn above budget before the page condition raises — the first
    burning evaluations degrade health (single-eval violation) but do
    not page; a full fast window of sustained burn does."""
    from spfft_tpu import obs
    dog = SLOWatchdog(None, SLOSpec(latency_p99_s=0.010),
                      fast_window=3, slow_window=9)
    base = obs.GLOBAL_COUNTERS.get("spfft_slo_window_alerts_total",
                                   slo="latency_p99_s")
    for i in range(2):  # burning, but shallower than the fast window
        v = dog.evaluate(_slo_signals(0.050))
        assert v["violations"] == ["latency_p99_s"]  # health layer
        assert v["window_alerts"] == []              # page layer quiet
    v = dog.evaluate(_slo_signals(0.050))            # 3rd: sustained
    assert v["window_alerts"] == ["latency_p99_s"]
    assert v["window_burn"]["latency_p99_s"]["fast"] > 1.0
    assert v["window_burn"]["latency_p99_s"]["slow"] > 1.0
    assert obs.GLOBAL_COUNTERS.get(
        "spfft_slo_window_alerts_total", slo="latency_p99_s") == base + 1
    assert obs.GLOBAL_COUNTERS.get(
        "spfft_slo_window_alert", slo="latency_p99_s") == 1
    # the page condition HOLDS without re-counting (rising edge only)
    dog.evaluate(_slo_signals(0.050))
    assert obs.GLOBAL_COUNTERS.get(
        "spfft_slo_window_alerts_total", slo="latency_p99_s") == base + 1


def test_slo_multiwindow_no_false_positive_on_transient_blip():
    """A transient burn blip inside an otherwise healthy trace never
    raises the page condition: the single-eval violation (and its
    health degradation) comes and goes, the window alert stays 0 and
    the rising-edge counter does not move."""
    from spfft_tpu import obs
    dog = SLOWatchdog(None, SLOSpec(latency_p99_s=0.010),
                      fast_window=3, slow_window=9)
    base = obs.GLOBAL_COUNTERS.get("spfft_slo_window_alerts_total",
                                   slo="latency_p99_s")
    trace = [0.002, 0.002, 0.015, 0.002, 0.002, 0.002]
    for p99 in trace:
        v = dog.evaluate(_slo_signals(p99))
        if p99 > 0.010:
            assert v["violations"] == ["latency_p99_s"]
        assert v["window_alerts"] == []
    assert obs.GLOBAL_COUNTERS.get(
        "spfft_slo_window_alert", slo="latency_p99_s") == 0
    assert obs.GLOBAL_COUNTERS.get(
        "spfft_slo_window_alerts_total",
        slo="latency_p99_s") == base


def test_slo_multiwindow_slow_window_clears_after_recovery():
    """After a real page, recovery drains the fast window first (alert
    clears) while the slow window still remembers the burn — then both
    clear. Gauges follow."""
    from spfft_tpu import obs
    dog = SLOWatchdog(None, SLOSpec(latency_p99_s=0.010),
                      fast_window=2, slow_window=6)
    for _ in range(4):
        dog.evaluate(_slo_signals(0.050))
    assert dog.evaluate(_slo_signals(0.050))["window_alerts"] \
        == ["latency_p99_s"]
    v = dog.evaluate(_slo_signals(0.002))   # recovery begins
    v = dog.evaluate(_slo_signals(0.002))   # fast window now clean
    assert v["window_alerts"] == []
    assert v["window_burn"]["latency_p99_s"]["fast"] < 1.0
    assert v["window_burn"]["latency_p99_s"]["slow"] > 1.0
    assert obs.GLOBAL_COUNTERS.get(
        "spfft_slo_window_alert", slo="latency_p99_s") == 0


def test_slo_multiwindow_window_validation():
    with pytest.raises(InvalidParameterError):
        SLOWatchdog(None, SLOSpec(latency_p99_s=0.01), fast_window=0)
    with pytest.raises(InvalidParameterError):
        SLOWatchdog(None, SLOSpec(latency_p99_s=0.01),
                    fast_window=10, slow_window=5)


# -- metrics signals --------------------------------------------------------
def test_metrics_signals_shape_and_reservoirs():
    m = ServeMetrics()
    m.record_queue_waits([0.001, 0.002, 0.500])
    m.record_device_execute(0.004)
    m.record_batch(8, True, padded_rows=2)
    m.record_request_done(0.01)
    s = m.signals()
    assert s["queue_wait_p95"] == pytest.approx(0.5)
    assert s["device_execute_p50"] == pytest.approx(0.004)
    assert s["fused_rows"] == 8 and s["padded_rows"] == 2
    assert s["fused_hist"] == {8: 1}
    snap = m.snapshot()
    assert snap["queue_wait_seconds"]["p95"] == pytest.approx(0.5)
    assert snap["device_execute_seconds"]["p50"] == pytest.approx(0.004)


# -- HTTP scrape endpoint ---------------------------------------------------
def _get(url):
    with urllib.request.urlopen(url, timeout=10) as resp:
        return resp.status, resp.read().decode()


def test_metrics_server_endpoints():
    reg, sig, plan = _registry()
    ex = ServeExecutor(reg, autostart=False, batch_window=0.0)
    rng = np.random.default_rng(0)
    v = _values(plan, rng)
    f = ex.submit(sig, v)
    ex._drain_once()
    f.result(timeout=30)
    with MetricsServer(executor=ex, port=0) as srv:
        status, text = _get(f"{srv.url}/metrics")
        assert status == 200
        from spfft_tpu import obs
        series = obs.parse_prometheus_text(text)
        assert series[("spfft_serve_completed_total", ())] == 1
        assert any(name == "spfft_registry_builds_total"
                   for name, _ in series)
        status, body = _get(f"{srv.url}/healthz")
        assert status == 200
        assert json.loads(body)["state"] in ("healthy", "degraded")
        status, body = _get(f"{srv.url}/configz")
        assert status == 200
        assert json.loads(body)["max_batch"] == ex.config.max_batch
        with pytest.raises(urllib.error.HTTPError) as err:
            _get(f"{srv.url}/bogus")
        assert err.value.code == 404
    ex.close()


def test_metrics_server_healthz_503_when_failed():
    metrics = ServeMetrics()
    metrics.record_health("failed")
    with MetricsServer(metrics=metrics, port=0) as srv:
        with pytest.raises(urllib.error.HTTPError) as err:
            _get(f"{srv.url}/healthz")
        assert err.value.code == 503
        assert json.loads(err.value.read())["state"] == "failed"


def test_metrics_port_env(monkeypatch):
    from spfft_tpu.obs.http import port_from_env
    monkeypatch.delenv("SPFFT_TPU_METRICS_PORT", raising=False)
    assert port_from_env() is None
    monkeypatch.setenv("SPFFT_TPU_METRICS_PORT", "9111")
    assert port_from_env() == 9111
    monkeypatch.setenv("SPFFT_TPU_METRICS_PORT", "junk")
    assert port_from_env() is None


# -- control loop thread ----------------------------------------------------
def test_control_loop_steps_and_stops():
    cfg = ServeConfig()
    metrics = ServeMetrics()
    ctl = Controller(cfg, metrics=metrics)
    with ControlLoop(ctl, interval=0.005):
        time.sleep(0.05)
    steps = ctl.steps
    assert steps >= 2
    time.sleep(0.02)
    assert ctl.steps == steps  # stopped means stopped

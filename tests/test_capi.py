"""C API tests: build libspfft_tpu.so, drive it from C and from ctypes.

The reference exercises its C API through compiled examples and the test
binaries (reference: examples/example.c, tests built on the C++ API); here a
real C program is compiled with g++ and run against the library, and the
same ABI is additionally driven in-process via ctypes for the error-surface
cases.
"""

import ctypes
import os
import shutil
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LIB = os.path.join(REPO, "lib", "libspfft_tpu.so")

pytestmark = pytest.mark.skipif(shutil.which("g++") is None,
                                reason="no C++ compiler")


@pytest.fixture(scope="module")
def capi_lib():
    subprocess.run(["make", "-s", "capi"], cwd=REPO, check=True,
                   capture_output=True, text=True)
    assert os.path.exists(LIB)
    return LIB


def test_abi_version_matches_header(capi_lib):
    """spfft_tpu_abi_version() equals the header's SPFFT_TPU_ABI_VERSION
    (the runtime probe old callers use to detect signature skew)."""
    hdr = open(os.path.join(REPO, "include", "spfft_tpu.h")).read()
    import re
    macro = int(re.search(r"#define SPFFT_TPU_ABI_VERSION (\d+)",
                          hdr).group(1))
    lib = ctypes.CDLL(capi_lib)
    lib.spfft_tpu_abi_version.restype = ctypes.c_int
    assert lib.spfft_tpu_abi_version() == macro


def test_c_example_round_trip(capi_lib):
    """Compile and run the shipped C example end-to-end (subprocess: the
    example embeds its own interpreter)."""
    build = os.path.join(REPO, "build")
    os.makedirs(build, exist_ok=True)
    exe = os.path.join(build, "example_c_test")
    subprocess.run(
        ["g++", "-O2", "-I" + os.path.join(REPO, "include"),
         os.path.join(REPO, "examples", "example.c"), "-o", exe,
         "-L" + os.path.join(REPO, "lib"), "-lspfft_tpu",
         "-Wl,-rpath," + os.path.join(REPO, "lib")],
        check=True, capture_output=True, text=True)
    env = dict(os.environ, SPFFT_TPU_PACKAGE_PATH=REPO,
               JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    out = subprocess.run([exe], env=env, capture_output=True, text=True,
                         timeout=600)
    assert out.returncode == 0, out.stderr
    assert "OK" in out.stdout


@pytest.fixture(scope="module")
def lib(capi_lib):
    """The C ABI loaded into this process. The embedded-interpreter branch
    is exercised by test_c_example_round_trip; loaded from Python, the shim
    detects the already-running interpreter and shares it."""
    lib = ctypes.CDLL(capi_lib)
    lib.spfft_tpu_error_string.restype = ctypes.c_char_p
    lib.spfft_tpu_init.argtypes = [ctypes.c_char_p]
    lib.spfft_tpu_plan_create.argtypes = [
        ctypes.POINTER(ctypes.c_void_p), ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.c_longlong, ctypes.c_void_p,
        ctypes.c_int, ctypes.c_int]
    lib.spfft_tpu_plan_destroy.argtypes = [ctypes.c_void_p]
    lib.spfft_tpu_backward.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                       ctypes.c_void_p]
    lib.spfft_tpu_forward.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                      ctypes.c_int, ctypes.c_void_p]
    lib.spfft_tpu_plan_num_values.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_longlong)]
    lib.spfft_tpu_plan_create_distributed.argtypes = [
        ctypes.POINTER(ctypes.c_void_p), ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_void_p,
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int, ctypes.c_int,
        ctypes.c_int]
    code = lib.spfft_tpu_init(None)
    assert code == 0
    return lib


def test_ctypes_round_trip(lib):
    n = 4
    trip = np.array([[x, y, z] for x in range(n) for y in range(n)
                     for z in range(n)], np.int32)
    values = np.random.default_rng(0).standard_normal(
        (len(trip), 2)).astype(np.float32)
    space = np.empty((n, n, n, 2), np.float32)
    out = np.empty_like(values)
    plan = ctypes.c_void_p()
    assert lib.spfft_tpu_plan_create(
        ctypes.byref(plan), 0, n, n, n,
        ctypes.c_longlong(len(trip)), trip.ctypes.data,
        0, -1) == 0
    nv = ctypes.c_longlong()
    assert lib.spfft_tpu_plan_num_values(plan, ctypes.byref(nv)) == 0
    assert nv.value == len(trip)
    assert lib.spfft_tpu_backward(plan, values.ctypes.data,
                                  space.ctypes.data) == 0
    assert lib.spfft_tpu_forward(plan, space.ctypes.data, 1,
                                 out.ctypes.data) == 0
    np.testing.assert_allclose(out, values, atol=1e-5)
    assert lib.spfft_tpu_plan_destroy(plan) == 0


def test_ctypes_execute_pair(lib):
    """The fused pair entry point matches separate backward+forward and
    supports in-place operation."""
    lib.spfft_tpu_execute_pair.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int, ctypes.c_void_p]
    n = 4
    trip = np.array([[x, y, z] for x in range(n) for y in range(n)
                     for z in range(n)], np.int32)
    values = np.random.default_rng(2).standard_normal(
        (len(trip), 2)).astype(np.float32)
    space = np.empty((n, n, n, 2), np.float32)
    seq = np.empty_like(values)
    fused = np.empty_like(values)
    plan = ctypes.c_void_p()
    assert lib.spfft_tpu_plan_create(
        ctypes.byref(plan), 0, n, n, n, ctypes.c_longlong(len(trip)),
        trip.ctypes.data, 0, -1) == 0
    assert lib.spfft_tpu_backward(plan, values.ctypes.data,
                                  space.ctypes.data) == 0
    assert lib.spfft_tpu_forward(plan, space.ctypes.data, 1,
                                 seq.ctypes.data) == 0
    assert lib.spfft_tpu_execute_pair(plan, values.ctypes.data, 1,
                                      fused.ctypes.data) == 0
    np.testing.assert_allclose(fused, seq, atol=1e-5)
    # in-place: out == in
    inplace = values.copy()
    assert lib.spfft_tpu_execute_pair(plan, inplace.ctypes.data, 1,
                                      inplace.ctypes.data) == 0
    np.testing.assert_allclose(inplace, seq, atol=1e-5)
    # NONE scaling == N * values
    assert lib.spfft_tpu_execute_pair(plan, values.ctypes.data, 0,
                                      fused.ctypes.data) == 0
    np.testing.assert_allclose(fused, values * len(trip), atol=1e-3)
    # bad scaling -> invalid parameter
    assert lib.spfft_tpu_execute_pair(plan, values.ctypes.data, 7,
                                      fused.ctypes.data) == 5
    assert lib.spfft_tpu_execute_pair(plan, None, 1, None) == 5
    assert lib.spfft_tpu_plan_destroy(plan) == 0


def test_ctypes_execute_pair_distributed(lib):
    """Fused pair on a distributed C plan (concatenated per-shard values)."""
    lib.spfft_tpu_execute_pair.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int, ctypes.c_void_p]
    n, shards = 8, 4
    trip_all = np.array([[x, y, z] for x in range(n) for y in range(n)
                         for z in range(n)], np.int32)
    order = np.argsort((trip_all[:, 0] * n + trip_all[:, 1]) % shards,
                       kind="stable")
    trip = np.ascontiguousarray(trip_all[order])
    vps = np.array([(((trip_all[:, 0] * n + trip_all[:, 1]) % shards) == r)
                    .sum() for r in range(shards)], np.int64)
    pps = np.full(shards, n // shards, np.int32)
    values = np.random.default_rng(3).standard_normal(
        (len(trip), 2)).astype(np.float32)
    fused = np.empty_like(values)
    plan = ctypes.c_void_p()
    assert lib.spfft_tpu_plan_create_distributed(
        ctypes.byref(plan), 0, n, n, n, shards, vps.ctypes.data,
        trip.ctypes.data, pps.ctypes.data, 0, 0, -1) == 0
    assert lib.spfft_tpu_execute_pair(plan, values.ctypes.data, 1,
                                      fused.ctypes.data) == 0
    np.testing.assert_allclose(fused, values, atol=1e-5)
    assert lib.spfft_tpu_plan_destroy(plan) == 0


def test_invalid_indices_code(lib):
    trip = np.array([[99, 0, 0]], np.int32)
    plan = ctypes.c_void_p()
    code = lib.spfft_tpu_plan_create(ctypes.byref(plan), 0, 4, 4, 4,
                                     ctypes.c_longlong(1),
                                     trip.ctypes.data, 0, -1)
    assert code == 7  # SPFFT_TPU_INVALID_INDICES_ERROR
    assert b"out of bounds" in lib.spfft_tpu_error_string(code)


def test_overflow_code(lib):
    """Dimension products past the 64-bit size range return the overflow
    code at the ABI (reference: grid_internal.cpp:122-134 ->
    SPFFT_OVERFLOW_ERROR)."""
    trip = np.array([[0, 0, 0]], np.int32)
    plan = ctypes.c_void_p()
    n = 1 << 21
    code = lib.spfft_tpu_plan_create(ctypes.byref(plan), 0, n, n, n,
                                     ctypes.c_longlong(1),
                                     trip.ctypes.data, 0, -1)
    assert code == 3  # SPFFT_TPU_OVERFLOW_ERROR
    assert lib.spfft_tpu_error_string(code)


def test_invalid_handle_code(lib):
    assert lib.spfft_tpu_plan_destroy(ctypes.c_void_p(12345)) == 2


def test_null_arguments(lib):
    plan = ctypes.c_void_p()
    assert lib.spfft_tpu_plan_create(None, 0, 4, 4, 4,
                                     ctypes.c_longlong(0), None, 0, -1) == 5
    trip = np.zeros((1, 3), np.int32)
    assert lib.spfft_tpu_plan_create(ctypes.byref(plan), 0, 4, 4, 4,
                                     ctypes.c_longlong(1),
                                     trip.ctypes.data, 0, -1) == 0
    assert lib.spfft_tpu_backward(plan, None, None) == 5
    assert lib.spfft_tpu_plan_destroy(plan) == 0


def test_error_strings(lib):
    assert lib.spfft_tpu_error_string(0) == b"success"
    assert b"unrecognised" in lib.spfft_tpu_error_string(9999)


def test_ctypes_distributed_round_trip(lib):
    """Distributed C plan over the forced 8-device CPU mesh: concatenated
    per-shard values <-> full cube, against the local-plan result."""
    lib.spfft_tpu_plan_num_shards.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int)]
    n, shards = 8, 4
    # split sticks round-robin: shard r gets sticks with (x*n+y) % shards == r
    trip_all = np.array([[x, y, z] for x in range(n) for y in range(n)
                         for z in range(n)], np.int32)
    order = np.argsort((trip_all[:, 0] * n + trip_all[:, 1]) % shards,
                       kind="stable")
    trip = np.ascontiguousarray(trip_all[order])
    vps = np.array([(((trip_all[:, 0] * n + trip_all[:, 1]) % shards) == r)
                    .sum() for r in range(shards)], np.int64)
    pps = np.full(shards, n // shards, np.int32)
    values = np.random.default_rng(1).standard_normal(
        (len(trip), 2)).astype(np.float32)
    space = np.empty((n, n, n, 2), np.float32)
    out = np.empty_like(values)
    plan = ctypes.c_void_p()
    assert lib.spfft_tpu_plan_create_distributed(
        ctypes.byref(plan), 0, n, n, n, shards, vps.ctypes.data,
        trip.ctypes.data, pps.ctypes.data, 0, 0, -1) == 0
    ns = ctypes.c_int()
    assert lib.spfft_tpu_plan_num_shards(plan, ctypes.byref(ns)) == 0
    assert ns.value == shards
    assert lib.spfft_tpu_backward(plan, values.ctypes.data,
                                  space.ctypes.data) == 0
    # oracle: the same transform through a local plan
    lplan = ctypes.c_void_p()
    assert lib.spfft_tpu_plan_create(
        ctypes.byref(lplan), 0, n, n, n, ctypes.c_longlong(len(trip)),
        trip.ctypes.data, 0, -1) == 0
    lspace = np.empty((n, n, n, 2), np.float32)
    assert lib.spfft_tpu_backward(lplan, values.ctypes.data,
                                  lspace.ctypes.data) == 0
    np.testing.assert_allclose(space, lspace, atol=1e-4)
    assert lib.spfft_tpu_forward(plan, space.ctypes.data, 1,
                                 out.ctypes.data) == 0
    np.testing.assert_allclose(out, values, atol=1e-5)
    assert lib.spfft_tpu_plan_destroy(plan) == 0
    assert lib.spfft_tpu_plan_destroy(lplan) == 0


def test_distributed_too_many_shards_code(lib):
    """Requesting more shards than devices surfaces as a clean error code
    (InvalidParameterError -> 5), not a crash."""
    shards = 64  # more than the 8 virtual devices
    trip = np.array([[0, 0, 0]], np.int32)
    vps = np.zeros(shards, np.int64)
    vps[0] = 1
    pps = np.zeros(shards, np.int32)
    pps[0] = 4
    plan = ctypes.c_void_p()
    code = lib.spfft_tpu_plan_create_distributed(
        ctypes.byref(plan), 0, 4, 4, 4, shards, vps.ctypes.data,
        trip.ctypes.data, pps.ctypes.data, 0, 0, -1)
    assert code == 5


def test_ctypes_pair_layout_plan(lib, monkeypatch):
    """C ABI buffers stay interleaved rows even when the plan internally
    uses the planar-pair (2, N) boundary (regression: forward once wrote
    the transposed layout straight into the caller's buffer)."""
    from spfft_tpu import plan as plan_mod
    monkeypatch.setattr(plan_mod, "PAIR_IO_THRESHOLD", 1)
    lib.spfft_tpu_execute_pair.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int, ctypes.c_void_p]
    n = 4
    trip = np.array([[x, y, z] for x in range(n) for y in range(n)
                     for z in range(n)], np.int32)
    values = np.random.default_rng(5).standard_normal(
        (len(trip), 2)).astype(np.float32)
    space = np.empty((n, n, n, 2), np.float32)
    out = np.empty_like(values)
    plan = ctypes.c_void_p()
    assert lib.spfft_tpu_plan_create(
        ctypes.byref(plan), 0, n, n, n, ctypes.c_longlong(len(trip)),
        trip.ctypes.data, 0, -1) == 0
    import spfft_tpu.capi_bridge as bridge
    pid = max(bridge._plans)
    assert bridge._plans[pid].pair_values_io
    assert lib.spfft_tpu_backward(plan, values.ctypes.data,
                                  space.ctypes.data) == 0
    assert lib.spfft_tpu_forward(plan, space.ctypes.data, 1,
                                 out.ctypes.data) == 0
    np.testing.assert_allclose(out, values, atol=1e-5)
    fused = np.empty_like(values)
    assert lib.spfft_tpu_execute_pair(plan, values.ctypes.data, 1,
                                      fused.ctypes.data) == 0
    np.testing.assert_allclose(fused, values, atol=1e-5)
    assert lib.spfft_tpu_plan_destroy(plan) == 0


def test_c_feature_drive(capi_lib):
    """Compiled-C drive of the round-3 parity additions: COMPACT_BUFFERED
    distributed create, the extended getter surface, and a B=3 batched
    multi_backward/forward through one plan handle (subprocess: own
    embedded interpreter and 8-device virtual CPU platform)."""
    build = os.path.join(REPO, "build")
    os.makedirs(build, exist_ok=True)
    exe = os.path.join(build, "capi_feature_test")
    subprocess.run(
        ["g++", "-O2", "-I" + os.path.join(REPO, "include"),
         os.path.join(REPO, "tests", "capi_feature_test.c"), "-o", exe,
         "-L" + os.path.join(REPO, "lib"), "-lspfft_tpu", "-lm",
         "-Wl,-rpath," + os.path.join(REPO, "lib")],
        check=True, capture_output=True, text=True)
    env = dict(os.environ, SPFFT_TPU_PACKAGE_PATH=REPO,
               JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    out = subprocess.run([exe], env=env, capture_output=True, text=True,
                         timeout=600)
    assert out.returncode == 0, out.stderr
    assert "OK" in out.stdout


def test_ctypes_exchange_knob_and_getters(lib):
    """ctypes drive of the exchange selector + per-shard getters."""
    lib.spfft_tpu_plan_exchange_type.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int)]
    lib.spfft_tpu_plan_local_z_offset.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(ctypes.c_int)]
    lib.spfft_tpu_plan_num_local_elements.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(ctypes.c_longlong)]
    lib.spfft_tpu_plan_pallas_active.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int)]
    n, shards = 8, 4
    trip_all = np.array([[x, y, z] for x in range(n) for y in range(n)
                         for z in range(n)], np.int32)
    order = np.argsort((trip_all[:, 0] * n + trip_all[:, 1]) % shards,
                       kind="stable")
    trip = np.ascontiguousarray(trip_all[order])
    vps = np.array([(((trip_all[:, 0] * n + trip_all[:, 1]) % shards) == r)
                    .sum() for r in range(shards)], np.int64)
    pps = np.full(shards, n // shards, np.int32)
    plan = ctypes.c_void_p()
    # UNBUFFERED (ring) exchange via the C knob
    assert lib.spfft_tpu_plan_create_distributed(
        ctypes.byref(plan), 0, n, n, n, shards, vps.ctypes.data,
        trip.ctypes.data, pps.ctypes.data, 0, 5, -1) == 0
    exch = ctypes.c_int(-1)
    assert lib.spfft_tpu_plan_exchange_type(plan, ctypes.byref(exch)) == 0
    assert exch.value == 5
    off = ctypes.c_int(-1)
    for r in range(shards):
        assert lib.spfft_tpu_plan_local_z_offset(
            plan, r, ctypes.byref(off)) == 0
        assert off.value == r * (n // shards)
    ne = ctypes.c_longlong()
    assert lib.spfft_tpu_plan_num_local_elements(
        plan, 2, ctypes.byref(ne)) == 0
    assert ne.value == vps[2]
    # shard out of range -> invalid parameter
    assert lib.spfft_tpu_plan_local_z_offset(
        plan, shards, ctypes.byref(off)) == 5
    # bad exchange enum -> invalid parameter
    p2 = ctypes.c_void_p()
    assert lib.spfft_tpu_plan_create_distributed(
        ctypes.byref(p2), 0, n, n, n, shards, vps.ctypes.data,
        trip.ctypes.data, pps.ctypes.data, 0, 42, -1) == 5
    # forced-off pallas routing reports inactive
    lplan = ctypes.c_void_p()
    assert lib.spfft_tpu_plan_create(
        ctypes.byref(lplan), 0, n, n, n, ctypes.c_longlong(len(trip)),
        trip.ctypes.data, 0, 0) == 0
    act = ctypes.c_int(-1)
    assert lib.spfft_tpu_plan_pallas_active(lplan, ctypes.byref(act)) == 0
    assert act.value == 0
    assert lib.spfft_tpu_plan_destroy(plan) == 0
    assert lib.spfft_tpu_plan_destroy(lplan) == 0


def test_ctypes_multi_entries(lib):
    """multi_backward/forward with MIXED plan handles (two distinct local
    plans) — the dispatch-all-then-sync path."""
    lib.spfft_tpu_multi_backward.argtypes = [
        ctypes.c_int, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p]
    lib.spfft_tpu_multi_forward.argtypes = [
        ctypes.c_int, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int,
        ctypes.c_void_p]
    n = 4
    trip = np.array([[x, y, z] for x in range(n) for y in range(n)
                     for z in range(n)], np.int32)
    rng = np.random.default_rng(9)
    p1, p2 = ctypes.c_void_p(), ctypes.c_void_p()
    for p in (p1, p2):
        assert lib.spfft_tpu_plan_create(
            ctypes.byref(p), 0, n, n, n, ctypes.c_longlong(len(trip)),
            trip.ctypes.data, 0, -1) == 0
    vals = [rng.standard_normal((len(trip), 2)).astype(np.float32)
            for _ in range(2)]
    spaces = [np.empty((n, n, n, 2), np.float32) for _ in range(2)]
    outs = [np.empty_like(vals[0]) for _ in range(2)]
    plans_arr = (ctypes.c_void_p * 2)(p1, p2)
    vptr = (ctypes.c_void_p * 2)(*[v.ctypes.data for v in vals])
    sptr = (ctypes.c_void_p * 2)(*[s.ctypes.data for s in spaces])
    optr = (ctypes.c_void_p * 2)(*[o.ctypes.data for o in outs])
    assert lib.spfft_tpu_multi_backward(2, plans_arr, vptr, sptr) == 0
    assert lib.spfft_tpu_multi_forward(2, plans_arr, sptr, 1, optr) == 0
    for v, o in zip(vals, outs):
        np.testing.assert_allclose(o, v, atol=1e-5)
    # null entry -> invalid parameter
    bad = (ctypes.c_void_p * 2)(None, vals[1].ctypes.data)
    assert lib.spfft_tpu_multi_backward(2, plans_arr, bad, sptr) == 5
    assert lib.spfft_tpu_plan_destroy(p1) == 0
    assert lib.spfft_tpu_plan_destroy(p2) == 0


def test_ctypes_multi_distributed_fused(lib):
    """Same-handle DISTRIBUTED multi entries run the fused per-shard-batch
    SPMD program (header contract: one fused device program)."""
    lib.spfft_tpu_multi_backward.argtypes = [
        ctypes.c_int, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p]
    lib.spfft_tpu_multi_forward.argtypes = [
        ctypes.c_int, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int,
        ctypes.c_void_p]
    n, shards, B = 8, 4, 3
    trip_all = np.array([[x, y, z] for x in range(n) for y in range(n)
                         for z in range(n)], np.int32)
    order = np.argsort((trip_all[:, 0] * n + trip_all[:, 1]) % shards,
                       kind="stable")
    trip = np.ascontiguousarray(trip_all[order])
    vps = np.array([(((trip_all[:, 0] * n + trip_all[:, 1]) % shards) == r)
                    .sum() for r in range(shards)], np.int64)
    pps = np.full(shards, n // shards, np.int32)
    plan = ctypes.c_void_p()
    assert lib.spfft_tpu_plan_create_distributed(
        ctypes.byref(plan), 0, n, n, n, shards, vps.ctypes.data,
        trip.ctypes.data, pps.ctypes.data, 0, 0, -1) == 0
    rng = np.random.default_rng(11)
    vals = [rng.standard_normal((len(trip), 2)).astype(np.float32)
            for _ in range(B)]
    spaces = [np.empty((n, n, n, 2), np.float32) for _ in range(B)]
    outs = [np.empty_like(vals[0]) for _ in range(B)]
    plans_arr = (ctypes.c_void_p * B)(plan, plan, plan)
    vptr = (ctypes.c_void_p * B)(*[v.ctypes.data for v in vals])
    sptr = (ctypes.c_void_p * B)(*[s.ctypes.data for s in spaces])
    optr = (ctypes.c_void_p * B)(*[o.ctypes.data for o in outs])
    assert lib.spfft_tpu_multi_backward(B, plans_arr, vptr, sptr) == 0
    assert lib.spfft_tpu_multi_forward(B, plans_arr, sptr, 1, optr) == 0
    for v, o in zip(vals, outs):
        np.testing.assert_allclose(o, v, atol=1e-5)
    # and the batched result matches the single-transform path
    single_space = np.empty((n, n, n, 2), np.float32)
    assert lib.spfft_tpu_backward(plan, vals[1].ctypes.data,
                                  single_space.ctypes.data) == 0
    np.testing.assert_allclose(spaces[1], single_space, atol=1e-5)
    assert lib.spfft_tpu_plan_destroy(plan) == 0

"""Error-budgeted compressed exchange wire (dist.py "wire precision
ladder" + exchange.quantize_blocks_int8): a typed rung ladder
full -> f32 -> bf16 -> int8 for the distributed exchange payload, gated
at plan build by a MEASURED probe error against the declared l2 budget.

Properties checked here, on the virtual CPU mesh:

* the pure int8 quantize/dequantize pair round-trips adversarial
  per-row dynamic range within the per-stick-scale error bound, on both
  quantization axes, with the exact packed layout (payload + bitcast
  f32 scale sidecar) the byte accounting declares;
* the budget gate REFUSES over-budget rungs and ineligible layouts by
  walking down the ladder, recording every decline with its reason
  (``wire_declines`` + ``spfft_wire_rung_declined_total``) — never
  silently shipping an out-of-budget wire;
* rung resolution composes with env/config/legacy ``*_FLOAT`` requests
  and rejects out-of-range knobs;
* end-to-end fuzz: compressed-wire plans reproduce their rung-0 twin
  within budget across exchange kinds, overlap chunk counts and
  transform types, and the block-layout int8 wire is BIT-identical
  across K (per-chunk scales partition the monolithic sidecar);
* byte accounting: int8 wire = 2 B/value + the f32 scale sidecar,
  conserved exactly across ``overlap_chunks``, and at most 0.30x the
  f32 rung's wire on the spherical workload shape;
* the controller escalates the rung only under SUSTAINED exposed
  exchange, decays it when the wire hides, and never oscillates on
  alternating traffic — with direction-labelled rung-change counters.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from spfft_tpu import ExchangeType, TransformType, faults, obs
from spfft_tpu.control import Controller, ServeConfig
from spfft_tpu.errors import InvalidParameterError
from spfft_tpu.parallel import exchange, make_distributed_plan, make_mesh
from spfft_tpu.parallel.dist import (WIRE_ERROR_BUDGET_ENV,
                                     WIRE_PRECISION_ENV, WIRE_RUNGS)
from spfft_tpu.utils.workloads import (even_plane_split,
                                       round_robin_stick_partition,
                                       spherical_cutoff_triplets)

from test_util import hermitian_triplets

N = 12
SHARDS = 3


def _rel(got, ref):
    got = np.asarray(got)
    ref = np.asarray(ref)
    denom = np.linalg.norm(ref)
    return float(np.linalg.norm(got - ref) / denom) if denom else 0.0


def _sphere_setup(n=N, shards=SHARDS, seed=0xA11, span=4.0):
    """Spherical C2C workload with per-value dynamic range 10^±span —
    the shape the per-stick scales exist to survive."""
    tr = spherical_cutoff_triplets(n)
    parts = round_robin_stick_partition(tr, (n, n, n), shards)
    planes = even_plane_split(n, shards)
    rng = np.random.default_rng(seed)
    vals = []
    for p in parts:
        m = 10.0 ** rng.uniform(-span, span, size=len(p))
        vals.append(((rng.uniform(-1, 1, len(p))
                      + 1j * rng.uniform(-1, 1, len(p))) * m)
                    .astype(np.complex64))
    return parts, planes, vals


def _build(parts, planes, **kw):
    kw.setdefault("precision", "single")
    return make_distributed_plan(TransformType.C2C, N, N, N, parts,
                                 planes, mesh=make_mesh(SHARDS), **kw)


# -- pure quantizer ---------------------------------------------------------

@pytest.mark.parametrize("quant_axis", [1, 2])
def test_int8_quantize_roundtrip_survives_per_row_dynamic_range(
        quant_axis):
    """Per-row absmax scales bound the round-trip error by the row's
    own magnitude — a 12-decade spread ACROSS rows costs nothing."""
    rng = np.random.default_rng(3)
    s, ms, mp = 3, 7, 9
    rows = ms if quant_axis == 1 else mp
    shape = [1, 1]
    shape[quant_axis - 1] = rows
    mags = 10.0 ** rng.uniform(-6, 6, size=(s, *shape))
    blocks = ((rng.standard_normal((s, ms, mp))
               + 1j * rng.standard_normal((s, ms, mp)))
              * mags).astype(np.complex64)
    packed = np.asarray(exchange.quantize_blocks_int8(
        jnp.asarray(blocks), quant_axis))
    # exact packed layout: int8 payload then the bitcast f32 sidecar,
    # one scale per quantization row — the accounting's 2 B/value +
    # rows*4 B contract
    assert packed.dtype == np.int8
    assert packed.shape == (s, ms * mp * 2 + rows * 4)
    got = np.asarray(exchange.dequantize_blocks_int8(
        jnp.asarray(packed), blocks.shape, quant_axis, jnp.float32))
    assert got.shape == blocks.shape
    assert _rel(got, blocks) < 0.01
    # per-row relative error bounded by the row's quantization step
    for sh in range(s):
        for r in range(rows):
            sl = (sh, r) if quant_axis == 1 else (sh, slice(None), r)
            row_ref = blocks[sl]
            row_err = np.max(np.abs(got[sl] - row_ref))
            assert row_err <= np.max(np.abs(
                np.stack([row_ref.real, row_ref.imag]))) / 127.0 + 1e-30


def test_int8_quantize_zero_rows_roundtrip_exactly():
    """All-zero rows take the scale=1 branch and come back as exact
    zeros — no NaN from a 0/0 scale."""
    blocks = np.zeros((2, 4, 5), np.complex64)
    blocks[1, 2, :] = 3.5 + 0.5j  # one live row next to dead ones
    packed = exchange.quantize_blocks_int8(jnp.asarray(blocks), 1)
    got = np.asarray(exchange.dequantize_blocks_int8(
        packed, blocks.shape, 1, jnp.float32))
    assert np.all(np.isfinite(got))
    assert np.all(got[0] == 0) and np.all(got[1, :2] == 0)
    assert _rel(got[1, 2], blocks[1, 2]) < 0.01


def test_is_int8_wire_predicate():
    assert exchange.is_int8_wire(jnp.int8)
    assert not exchange.is_int8_wire(np.float32)
    assert not exchange.is_int8_wire(jnp.bfloat16)
    assert not exchange.is_int8_wire(None)


# -- budget gate ------------------------------------------------------------

def test_budget_gate_accepts_int8_within_budget():
    parts, planes, _ = _sphere_setup()
    plan = _build(parts, planes, wire_precision=3, wire_error_budget=0.01)
    assert plan.wire_rung == 3
    assert plan.wire_rung_name == "int8"
    assert plan.wire_rung_requested == 3
    assert plan.wire_declines == ()
    assert 0.0 < plan.wire_probe_error <= 0.01


def test_budget_gate_walks_down_ladder_recording_declines():
    """A 1e-3 budget is under both the int8 (~5e-3) and bf16 (~1.6e-3)
    probe errors: the plan declines both FOR A REASON and lands on f32,
    which measures exactly 0 against the single-precision payload."""
    parts, planes, _ = _sphere_setup()
    before = obs.GLOBAL_COUNTERS.get("spfft_wire_rung_declined_total",
                                     reason="over_budget")
    plan = _build(parts, planes, wire_precision=3,
                  wire_error_budget=1e-3)
    assert plan.wire_rung_name == "f32"
    assert plan.wire_declines == (("int8", "over_budget"),
                                  ("bf16", "over_budget"))
    assert plan.wire_probe_error == 0.0
    assert obs.GLOBAL_COUNTERS.get("spfft_wire_rung_declined_total",
                                   reason="over_budget") == before + 2


def test_budget_gate_declines_int8_on_exact_count_layout():
    """The compact schedule addresses individual elements — no room on
    the wire for the scale sidecar, so int8 declines to bf16 with the
    layout reason (NOT over_budget: the budget never got a say)."""
    parts, planes, _ = _sphere_setup()
    plan = _build(parts, planes, exchange=ExchangeType.COMPACT_BUFFERED,
                  wire_precision=3, wire_error_budget=1.0)
    assert plan.wire_rung_name == "bf16"
    assert plan.wire_declines == (("int8", "exact_count_layout"),)


def test_budget_gate_fault_seam_declines_one_rung():
    """An armed ``exchange.quantize`` fault fails the int8 probe: the
    plan falls back exactly one rung and records the injected reason —
    chaos-storm behaviour, pinned here deterministically."""
    parts, planes, _ = _sphere_setup()
    faults.arm(faults.FaultPlan(script="exchange.quantize@1"))
    try:
        plan = _build(parts, planes, wire_precision=3,
                      wire_error_budget=1.0)
    finally:
        faults.disarm()
    assert plan.wire_rung_name == "bf16"
    assert ("int8", "fault_injected") in plan.wire_declines


def test_wire_knobs_validated_and_env_resolved(monkeypatch):
    parts, planes, _ = _sphere_setup()
    with pytest.raises(InvalidParameterError):
        _build(parts, planes, wire_precision=len(WIRE_RUNGS))
    with pytest.raises(InvalidParameterError):
        _build(parts, planes, wire_precision=-1)
    with pytest.raises(InvalidParameterError):
        _build(parts, planes, wire_precision=3, wire_error_budget=0.0)
    # env resolution: the knob pair reads its SPFFT_TPU_* envs when the
    # caller passes nothing
    monkeypatch.setenv(WIRE_PRECISION_ENV, "3")
    monkeypatch.setenv(WIRE_ERROR_BUDGET_ENV, "1.0")
    plan = _build(parts, planes)
    assert plan.wire_rung_name == "int8"
    assert plan.wire_error_budget == 1.0


def test_legacy_float_wire_maps_onto_ladder():
    """BUFFERED_FLOAT's one-rung downcast rides the same gate: single
    precision requests bf16, double requests f32 — both within the
    default budget, so the legacy behaviour is unchanged."""
    parts, planes, _ = _sphere_setup()
    single = _build(parts, planes, exchange=ExchangeType.BUFFERED_FLOAT)
    assert single.wire_rung_requested == 2
    assert single.wire_rung_name == "bf16"
    double = _build(parts, planes, exchange=ExchangeType.BUFFERED_FLOAT,
                    precision="double")
    assert double.wire_rung_requested == 1
    assert double.wire_rung_name == "f32"


# -- end-to-end fuzz --------------------------------------------------------

@pytest.mark.parametrize("kind,rung,k,expect", [
    (ExchangeType.DEFAULT, 3, 1, "int8"),
    (ExchangeType.DEFAULT, 3, 2, "int8"),
    (ExchangeType.DEFAULT, 2, 1, "bf16"),
    (ExchangeType.UNBUFFERED, 3, 1, "int8"),
    (ExchangeType.COMPACT_BUFFERED, 3, 1, "bf16"),
])
def test_compressed_backward_within_budget_of_rung0_twin(
        kind, rung, k, expect):
    parts, planes, vals = _sphere_setup()
    plan = _build(parts, planes, exchange=kind, overlap_chunks=k,
                  wire_precision=rung, wire_error_budget=1.0)
    ref = _build(parts, planes, exchange=kind, overlap_chunks=k,
                 wire_precision=0)
    assert plan.wire_rung_name == expect
    err = _rel(plan.backward(vals), ref.backward(vals))
    assert err <= 0.02, f"{expect} wire err {err:.2e}"
    # the end-to-end error tracks the build-time probe's promise
    assert err <= max(4 * plan.wire_probe_error, 1e-6)


def test_compressed_backward_r2c_within_budget():
    rng = np.random.default_rng(11)
    dims = (N, N, N)
    tr = hermitian_triplets(rng, dims)
    parts = round_robin_stick_partition(tr, dims, SHARDS)
    planes = even_plane_split(N, SHARDS)
    vals = [((rng.uniform(-1, 1, len(p))
              + 1j * rng.uniform(-1, 1, len(p)))
             * 10.0 ** rng.uniform(-3, 3, size=len(p)))
            .astype(np.complex64) for p in parts]

    def build(rung):
        return make_distributed_plan(
            TransformType.R2C, *dims, parts, planes,
            mesh=make_mesh(SHARDS), precision="single",
            wire_precision=rung, wire_error_budget=1.0)

    plan, ref = build(3), build(0)
    assert plan.wire_rung_name == "int8"
    err = _rel(plan.backward(vals), ref.backward(vals))
    assert err <= 0.02, f"r2c int8 wire err {err:.2e}"


def test_int8_wire_bit_identical_across_overlap_chunks():
    """Per-chunk scale sidecars partition the monolithic one exactly
    (the chunk slice axis IS the quantization axis), so the K=1/2/4
    outputs agree to the BIT — overlap never re-quantizes differently."""
    parts, planes, vals = _sphere_setup()
    outs = []
    for k in (1, 2, 4):
        plan = _build(parts, planes, overlap_chunks=k, wire_precision=3,
                      wire_error_budget=1.0)
        assert plan.wire_rung_name == "int8"
        outs.append(np.asarray(plan.backward(vals)))
    assert np.array_equal(outs[0], outs[1])
    assert np.array_equal(outs[0], outs[2])


# -- byte accounting --------------------------------------------------------

def test_int8_wire_byte_formula_and_conservation():
    parts, planes, _ = _sphere_setup()
    plans = {k: _build(parts, planes, overlap_chunks=k, wire_precision=3,
                       wire_error_budget=1.0) for k in (1, 2, 4)}
    p1 = plans[1]
    dp = p1.dist_plan
    ms, mp = dp.max_sticks, dp.max_planes
    links = SHARDS * (SHARDS - 1)
    # 2 B per complex value (two int8 components) + one f32 scale per
    # stick (backward) / per plane (forward) per link
    assert p1.exchange_wire_bytes() == links * (ms * mp * 2 + ms * 4)
    assert p1.exchange_wire_bytes(forward=True) == \
        links * (ms * mp * 2 + mp * 4)
    assert p1.exchange_busiest_link_bytes() == \
        (SHARDS - 1) * (ms * mp * 2 + ms * 4)
    # conserved exactly across chunking — chunk sidecars partition the
    # monolithic one, they never inflate it
    for k in (2, 4):
        assert plans[k].exchange_wire_bytes() == p1.exchange_wire_bytes()
        assert plans[k].exchange_wire_bytes(forward=True) == \
            p1.exchange_wire_bytes(forward=True)


def test_int8_wire_at_most_030x_of_f32_wire():
    """The ISSUE acceptance ratio on the spherical workload shape:
    (2 B + sidecar) vs 8 B per complex value — <= 0.30 whenever the
    plane extent amortises the per-stick scale (mp >= 10; the flagship
    256^3/8-shard shape has mp = 32 and measures 0.266)."""
    n, shards = 32, 2
    tr = spherical_cutoff_triplets(n)
    parts = round_robin_stick_partition(tr, (n, n, n), shards)
    planes = even_plane_split(n, shards)

    def build(rung):
        return make_distributed_plan(
            TransformType.C2C, n, n, n, parts, planes,
            mesh=make_mesh(shards), precision="single",
            wire_precision=rung, wire_error_budget=1.0)

    int8, f32 = build(3), build(1)
    assert int8.wire_rung_name == "int8"
    dp = int8.dist_plan
    assert f32.exchange_wire_bytes() == \
        shards * (shards - 1) * dp.max_sticks * dp.max_planes * 8
    ratio = int8.exchange_wire_bytes() / f32.exchange_wire_bytes()
    assert ratio <= 0.30, f"int8 wire ratio {ratio:.3f} > 0.30"


def test_wire_rung_gauge_recorded_at_plan_build():
    parts, planes, _ = _sphere_setup()
    plan = _build(parts, planes, wire_precision=3, wire_error_budget=1.0)
    assert obs.GLOBAL_COUNTERS.get(
        "spfft_wire_rung", exchange=plan.exchange.value,
        shards=str(SHARDS), chunks=str(plan.overlap_chunks)) == 3.0


# -- controller rule --------------------------------------------------------

def _signals(completed=0, exchange_s=0.0, compute_s=0.0):
    return {"completed": completed, "failed": 0, "queue_depth": 0,
            "max_queue_depth": 0, "queue_wait_p95": 0.0,
            "device_execute_p50": 0.0, "fused_rows": 0,
            "padded_rows": 0, "fused_hist": {}, "stage_s": 0.0,
            "dispatch_s": 0.0, "quarantines": 0,
            "rejected_queue_full": 0, "exchange_s": exchange_s,
            "exchange_compute_s": compute_s, "latency_p99": 0.0}


def test_controller_wire_rung_escalates_on_sustained_exposed_exchange():
    """Three CONSECUTIVE steps with exchange dominating compute past
    wire_hi move the rung by ONE; the streak then restarts, so the next
    rung needs three more steps — deterministic, no oscillation."""
    cfg = ServeConfig()
    ctl = Controller(cfg, cooldown_steps=0)
    up0 = obs.GLOBAL_COUNTERS.get("spfft_wire_rung_changes_total",
                                  direction="up")
    ctl.step(_signals(completed=1))                       # baseline
    ctl.step(_signals(completed=5, exchange_s=0.9, compute_s=0.2))
    ctl.step(_signals(completed=9, exchange_s=1.8, compute_s=0.4))
    assert cfg.wire_precision == 0                        # streak < 3
    d = ctl.step(_signals(completed=12, exchange_s=2.7, compute_s=0.6))
    moved = [x for x in d if x.knob == "wire_precision"]
    assert len(moved) == 1 and moved[0].new == 1
    assert "exposed exchange" in moved[0].reason
    assert obs.GLOBAL_COUNTERS.get("spfft_wire_rung_changes_total",
                                   direction="up") == up0 + 1
    # two more exposed steps: streak restarted, not enough yet
    ctl.step(_signals(completed=15, exchange_s=3.6, compute_s=0.8))
    ctl.step(_signals(completed=18, exchange_s=4.5, compute_s=1.0))
    assert cfg.wire_precision == 1
    ctl.step(_signals(completed=21, exchange_s=5.4, compute_s=1.2))
    assert cfg.wire_precision == 2
    lo, hi = ServeConfig.bounds("wire_precision")
    assert lo <= cfg.wire_precision <= hi


def test_controller_wire_rung_decays_when_exchange_hidden():
    cfg = ServeConfig()
    cfg.set("wire_precision", 3, source="test")
    ctl = Controller(cfg, cooldown_steps=0)
    down0 = obs.GLOBAL_COUNTERS.get("spfft_wire_rung_changes_total",
                                    direction="down")
    ctl.step(_signals(completed=1))
    # hidden wire: 0.02 / 0.5 = 0.04 < wire_lo -> one rung back per step
    ctl.step(_signals(completed=5, exchange_s=0.02, compute_s=0.5))
    assert cfg.wire_precision == 2
    ctl.step(_signals(completed=9, exchange_s=0.04, compute_s=1.0))
    assert cfg.wire_precision == 1
    ctl.step(_signals(completed=12, exchange_s=0.06, compute_s=1.5))
    assert cfg.wire_precision == 0
    ctl.step(_signals(completed=15, exchange_s=0.08, compute_s=2.0))
    assert cfg.wire_precision == 0                        # never below
    assert obs.GLOBAL_COUNTERS.get("spfft_wire_rung_changes_total",
                                   direction="down") == down0 + 3


def test_controller_wire_rung_no_oscillation_on_alternating_traffic():
    """Exposed/hidden alternation never ratchets the rung: the up-side
    needs a 3-streak, the down-side needs rung > default — from the
    default the knob cannot move at all on mixed traffic."""
    cfg = ServeConfig()
    ctl = Controller(cfg, cooldown_steps=0)
    ctl.step(_signals(completed=1))
    for i in range(8):
        if i % 2 == 0:
            ctl.step(_signals(completed=5 + 3 * i, exchange_s=0.5 * (i + 1),
                              compute_s=0.1 * (i + 1)))
        else:
            ctl.step(_signals(completed=5 + 3 * i))       # local only
    assert cfg.wire_precision == 0
    assert not [d for d in ctl.decisions()
                if d.knob == "wire_precision"]


def test_controller_wire_rung_idle_decays_by_one_step():
    cfg = ServeConfig()
    cfg.set("wire_precision", 2, source="test")
    ctl = Controller(cfg, cooldown_steps=0)
    ctl.step(_signals(completed=5))          # baseline with traffic
    ctl.step(_signals(completed=5))          # idle
    assert cfg.wire_precision == 1
    ctl.step(_signals(completed=5))
    assert cfg.wire_precision == 0
    ctl.step(_signals(completed=5))
    assert cfg.wire_precision == 0           # never undershoots

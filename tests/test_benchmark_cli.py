"""Benchmark CLI smoke tests (reference: tests/programs/benchmark.cpp —
the harness itself is part of the deliverable, SURVEY.md §6)."""

import json
import os

import numpy as np
import pytest

from spfft_tpu.benchmark import cutoff_stick_triplets, main


def test_cutoff_stick_workload_shape():
    t = cutoff_stick_triplets(8, 6, 4, 0.5, hermitian=False)
    # x < 8 * 0.5 = 4 sticks in x, all y, full z
    assert t.shape == (4 * 6 * 4, 3)
    assert t[:, 0].max() == 3
    assert set(np.unique(t[:, 2])) == set(range(4))


def test_cutoff_stick_workload_hermitian():
    t = cutoff_stick_triplets(8, 6, 4, 1.0, hermitian=True)
    assert t[:, 0].max() == 8 // 2  # dim_x_freq - 1


@pytest.mark.parametrize("flags", [
    ["-d", "12", "-r", "2", "-t", "c2c", "-m", "2"],
    ["-d", "8", "10", "12", "-r", "1", "-t", "r2c", "-s", "0.5"],
    ["-d", "16", "-r", "1", "--shards", "4", "-e", "compactFloat"],
    ["-d", "16", "-r", "1", "--shards", "2", "-t", "r2c", "-p", "host"],
    ["-d", "12", "-r", "2", "-m", "3", "--serve"],
])
def test_cli_runs(flags, tmp_path, capsys):
    out = tmp_path / "bench.json"
    assert main(flags + ["-o", str(out)]) == 0
    payload = json.loads(out.read_text())
    assert "parameters" in payload and "timings" in payload
    assert payload["parameters"]["pair_seconds"] > 0
    assert capsys.readouterr().out  # params + tree printed


def test_cli_exchange_all_sweep(tmp_path, capsys):
    """-e all compares every exchange mechanism on one workload with HLO
    wire bytes (reference: benchmark.cpp:138-156)."""
    out = tmp_path / "sweep.json"
    assert main(["-d", "16", "-r", "1", "--shards", "4", "-e", "all",
                 "-o", str(out)]) == 0
    payload = json.loads(out.read_text())
    rows = payload["exchange_sweep"]
    assert [r["exchange"] for r in rows] == [
        "buffered", "bufferedFloat", "compact", "compactFloat",
        "unbuffered"]
    for r in rows:
        assert r["pair_seconds"] > 0
        assert r["wire_total_bytes"] >= r["busiest_link_bytes"] >= 0
    # float wire halves the bytes
    by = {r["exchange"]: r for r in rows}
    assert by["bufferedFloat"]["wire_total_bytes"] \
        == by["buffered"]["wire_total_bytes"] // 2
    assert capsys.readouterr().out


def test_cli_serve_reports_metrics(tmp_path):
    """--serve routes the -m transforms through the serving layer and
    embeds its metrics (fused batches must appear: m same-signature
    submissions per phase bucket together)."""
    out = tmp_path / "serve_bench.json"
    assert main(["-d", "12", "-r", "3", "-m", "4", "--serve",
                 "-o", str(out)]) == 0
    payload = json.loads(out.read_text())
    serve = payload["parameters"]["serve"]
    assert serve["completed"] == 2 * 4 * (3 + 1)  # warmups + repeats
    assert serve["fused_batches"] >= 1
    assert serve["registry"]["plans"] == 1


def test_cli_store_dir_cold_warm_ab(tmp_path):
    """--store-dir (round 13): the JSON must carry the cold/warm pair
    bench_regress.py compares — a true cold start (empty store, one
    build + spill) and a fresh-subprocess warm boot with zero builds."""
    out = tmp_path / "bench.json"
    store_dir = tmp_path / "store"
    assert main(["-d", "12", "-r", "1", "-s", "0.5",
                 "--store-dir", str(store_dir),
                 "-o", str(out)]) == 0
    params = json.loads(out.read_text())["parameters"]
    assert params["store_was_cold"] is True
    assert params["cold_start_ms"]["value"] > 0
    assert params["cold_start_ms"]["unit"] == "ms"
    assert params["warm_start_ms"]["value"] > 0
    assert params["warm_builds"] == 0
    assert params["warm_store"]["hits"] == 1


def test_cli_store_dir_rejects_shards():
    with pytest.raises(SystemExit):
        main(["-d", "12", "-r", "1", "--shards", "2",
              "--store-dir", "/tmp/x"])


def test_cli_serve_rejects_shards():
    with pytest.raises(SystemExit):
        main(["-d", "12", "--serve", "--shards", "2"])


def test_cli_exchange_all_needs_shards():
    assert main(["-d", "8", "-e", "all"]) == 2


def test_cli_bad_dims():
    assert main(["-d", "4", "4"]) == 2


def test_cli_fused_ab(tmp_path, monkeypatch):
    """--fused / --no-fused: the A/B flag of the fused compression+
    z-DFT Pallas path (docs/kernels.md). --fused must report the fused
    kernels ACTIVE (off-TPU via the forced matmul-DFT pipeline +
    interpret mode), --no-fused must report them off, and main() must
    restore the env knobs it set (the tier-1 suite shares a process)."""
    for var in ("SPFFT_TPU_FUSED_COMPRESS", "SPFFT_TPU_FUSED_INTERPRET",
                "SPFFT_TPU_FORCE_MATMUL_DFT"):
        monkeypatch.delenv(var, raising=False)
    out_on = tmp_path / "fused_on.json"
    assert main(["-d", "8", "6", "128", "-r", "1", "--fused",
                 "-o", str(out_on)]) == 0
    p_on = json.loads(out_on.read_text())["parameters"]
    assert p_on["fused"] is True
    assert p_on["fused_fallback"] == {}
    assert os.environ.get("SPFFT_TPU_FUSED_COMPRESS") is None

    out_off = tmp_path / "fused_off.json"
    assert main(["-d", "8", "6", "128", "-r", "1", "--no-fused",
                 "-o", str(out_off)]) == 0
    p_off = json.loads(out_off.read_text())["parameters"]
    assert p_off["fused"] is False
    assert os.environ.get("SPFFT_TPU_FUSED_COMPRESS") is None


def test_cli_fused_reports_fallback_reason(tmp_path, monkeypatch):
    """--fused on a fused-ineligible workload (dim_z not a multiple of
    128) still runs — two-kernel path — and the JSON carries the
    per-direction gate reasons the obs counter records."""
    for var in ("SPFFT_TPU_FUSED_COMPRESS", "SPFFT_TPU_FUSED_INTERPRET",
                "SPFFT_TPU_FORCE_MATMUL_DFT"):
        monkeypatch.delenv(var, raising=False)
    out = tmp_path / "fused_fb.json"
    assert main(["-d", "8", "6", "96", "-r", "1", "--fused",
                 "-o", str(out)]) == 0
    params = json.loads(out.read_text())["parameters"]
    assert params["fused"] is False
    assert params["fused_fallback"] == {
        "dec": "dimz_not_multiple_128", "cmp": "dimz_not_multiple_128"}

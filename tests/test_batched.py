"""Batched (vmapped) execution: one fused executable for N same-plan
transforms.

The reference's multi-transform interleaves the phases of N transforms by
hand for comm/compute overlap (reference: multi_transform_internal.hpp:47-145
and tests/mpi_tests/test_multi_transform.cpp). The TPU-native counterpart is
a leading batch axis over one executable; these tests check the batched path
agrees with the per-transform path exactly, including the fused path that
``multi_transform_*`` takes when every handle shares one plan."""

import numpy as np
import pytest

from spfft_tpu import (Scaling, TransformType, make_local_plan,
                       multi_transform_backward, multi_transform_forward)
from spfft_tpu.grid import Transform
from spfft_tpu.multi import _shared_plan
from spfft_tpu.utils import as_complex_np

from test_util import (hermitian_triplets, random_sparse_triplets,
                       random_values)

DIMS = (12, 13, 11)


def _c2c_plan_and_values(batch, rng):
    triplets = random_sparse_triplets(rng, DIMS)
    plan = make_local_plan(TransformType.C2C, *DIMS, triplets,
                           precision="double")
    vals = [random_values(rng, len(triplets)) for _ in range(batch)]
    return plan, vals


def test_batched_backward_matches_single():
    rng = np.random.default_rng(7)
    plan, vals = _c2c_plan_and_values(4, rng)
    stacked = np.asarray(plan.backward_batched(vals))
    assert stacked.shape[0] == 4
    for i, v in enumerate(vals):
        single = np.asarray(plan.backward(v))
        np.testing.assert_allclose(stacked[i], single, atol=1e-12, rtol=0)


@pytest.mark.parametrize("scaling", [Scaling.NONE, Scaling.FULL])
def test_batched_forward_matches_single(scaling):
    rng = np.random.default_rng(8)
    plan, vals = _c2c_plan_and_values(3, rng)
    spaces = [as_complex_np(np.asarray(plan.backward(v))) for v in vals]
    stacked = np.asarray(plan.forward_batched(spaces, scaling))
    for i, s in enumerate(spaces):
        single = np.asarray(plan.forward(s, scaling))
        np.testing.assert_allclose(stacked[i], single, atol=1e-12, rtol=0)


def test_batched_r2c():
    rng = np.random.default_rng(9)
    triplets = hermitian_triplets(rng, DIMS)
    plan = make_local_plan(TransformType.R2C, *DIMS, triplets,
                           precision="double")
    vals = [random_values(rng, len(triplets)) for _ in range(3)]
    # hermitian constraint on the (0,0) stick: reference details.rst
    # "Real-To-Complex" — test_util's generator already enforces it.
    stacked = np.asarray(plan.backward_batched(vals))
    for i, v in enumerate(vals):
        single = np.asarray(plan.backward(v))
        np.testing.assert_allclose(stacked[i], single, atol=1e-12, rtol=0)
    fw = np.asarray(plan.forward_batched(list(stacked)))
    for i in range(3):
        single = np.asarray(plan.forward(stacked[i]))
        np.testing.assert_allclose(fw[i], single, atol=1e-12, rtol=0)


def test_multi_transform_takes_fused_path_for_shared_plan():
    rng = np.random.default_rng(10)
    plan, vals = _c2c_plan_and_values(3, rng)
    base = Transform(plan)
    clones = [base.clone() for _ in range(3)]
    assert _shared_plan(clones) is plan
    outs = multi_transform_backward(clones, vals)
    for i, v in enumerate(vals):
        np.testing.assert_allclose(np.asarray(outs[i]),
                                   np.asarray(plan.backward(v)),
                                   atol=1e-12, rtol=0)
    spaces = [as_complex_np(np.asarray(o)) for o in outs]
    fouts = multi_transform_forward(clones, spaces)
    for i, s in enumerate(spaces):
        np.testing.assert_allclose(np.asarray(fouts[i]),
                                   np.asarray(plan.forward(s)),
                                   atol=1e-12, rtol=0)


def test_apply_pointwise_identity_and_fn():
    rng = np.random.default_rng(12)
    plan, vals = _c2c_plan_and_values(1, rng)
    v = vals[0]
    # identity round trip == forward(backward(v)) == N * v
    got = np.asarray(plan.apply_pointwise(v))
    ref = np.asarray(plan.forward(as_complex_np(np.asarray(plan.backward(v)))))
    np.testing.assert_allclose(got, ref, atol=1e-10, rtol=0)
    # FULL scaling returns the input
    got_s = np.asarray(plan.apply_pointwise(v, scaling=Scaling.FULL))
    v_il = np.stack([v.real, v.imag], axis=-1)
    np.testing.assert_allclose(got_s, v_il, atol=1e-12, rtol=0)
    # a pointwise fn (doubling the space field doubles the output)
    got_2 = np.asarray(plan.apply_pointwise(v, fn=lambda s: 2.0 * s))
    np.testing.assert_allclose(got_2, 2.0 * ref, atol=1e-10, rtol=0)


def test_apply_pointwise_fn_args_traced():
    """fn_args flow as traced arguments: same fn object + different data
    must produce different results with ONE cached executable."""
    rng = np.random.default_rng(14)
    plan, vals = _c2c_plan_and_values(1, rng)
    v = vals[0]

    def scale_by(space, factor):
        return space * factor

    a = np.asarray(plan.apply_pointwise(v, scale_by, 2.0,
                                        scaling=Scaling.FULL))
    b = np.asarray(plan.apply_pointwise(v, scale_by, 3.0,
                                        scaling=Scaling.FULL))
    v_il = np.stack([v.real, v.imag], axis=-1)
    np.testing.assert_allclose(a, 2.0 * v_il, atol=1e-12, rtol=0)
    np.testing.assert_allclose(b, 3.0 * v_il, atol=1e-12, rtol=0)
    assert len(plan._pair_jits) == 1


def test_apply_pointwise_r2c():
    rng = np.random.default_rng(13)
    triplets = hermitian_triplets(rng, DIMS)
    plan = make_local_plan(TransformType.R2C, *DIMS, triplets,
                           precision="double")
    v = random_values(rng, len(triplets))
    got = np.asarray(plan.apply_pointwise(v, fn=lambda s: s * s,
                                          scaling=Scaling.FULL))
    space = np.asarray(plan.backward(v))
    ref = np.asarray(plan.forward(space * space, Scaling.FULL))
    np.testing.assert_allclose(got, ref, atol=1e-10, rtol=0)


def test_multi_transform_distinct_plans_still_works():
    rng = np.random.default_rng(11)
    plan_a, vals_a = _c2c_plan_and_values(1, rng)
    triplets = random_sparse_triplets(rng, (8, 8, 8))
    plan_b = make_local_plan(TransformType.C2C, 8, 8, 8, triplets,
                             precision="double")
    transforms = [Transform(plan_a), Transform(plan_b)]
    assert _shared_plan(transforms) is None
    vals = [vals_a[0], random_values(rng, len(triplets))]
    outs = multi_transform_backward(transforms, vals)
    np.testing.assert_allclose(np.asarray(outs[0]),
                               np.asarray(plan_a.backward(vals[0])),
                               atol=1e-12, rtol=0)
    np.testing.assert_allclose(np.asarray(outs[1]),
                               np.asarray(plan_b.backward(vals[1])),
                               atol=1e-12, rtol=0)


def _distributed_plan_and_values(batch, rng, shards=4,
                                 exchange=None):
    from spfft_tpu.parallel import make_distributed_plan, make_mesh
    from test_distributed import split_by_sticks, split_planes
    dims = (10, 9, 11)
    triplets = random_sparse_triplets(rng, dims)
    # weight prefixes keep the 4-shard case byte-identical to the
    # round-3 scenarios while allowing the S=8 fusion proxy test
    parts = split_by_sticks(triplets, dims,
                            [2, 1, 0, 1, 1, 2, 1, 1][:shards])
    planes = split_planes(dims[2], [1, 2, 1, 1, 2, 1, 1, 2][:shards])
    kwargs = {} if exchange is None else {"exchange": exchange}
    plan = make_distributed_plan(TransformType.C2C, *dims, parts, planes,
                                 mesh=make_mesh(shards), precision="double",
                                 **kwargs)
    vals = [[random_values(rng, len(p)) for p in parts]
            for _ in range(batch)]
    return plan, vals


@pytest.mark.parametrize("exchange", [None, "compact", "unbuffered"])
def test_distributed_batched_backward_matches_single(exchange):
    """One fused SPMD batch program == N sequential distributed dispatches,
    for every exchange mechanism (vmapped collectives included)."""
    from spfft_tpu import ExchangeType
    exch = {None: None, "compact": ExchangeType.COMPACT_BUFFERED,
            "unbuffered": ExchangeType.UNBUFFERED}[exchange]
    rng = np.random.default_rng(21)
    plan, vals = _distributed_plan_and_values(3, rng, exchange=exch)
    stacked = np.asarray(plan.backward_batched(vals))
    assert stacked.shape[1] == 3  # (S, B, planes, y, x, 2)
    for i, v in enumerate(vals):
        single = np.asarray(plan.backward(v))
        np.testing.assert_allclose(stacked[:, i], single, atol=1e-12,
                                   rtol=0)


@pytest.mark.parametrize("exchange", [None, "compact", "unbuffered"])
def test_distributed_batched_forward_matches_single(exchange):
    from spfft_tpu import ExchangeType
    exch = {None: None, "compact": ExchangeType.COMPACT_BUFFERED,
            "unbuffered": ExchangeType.UNBUFFERED}[exchange]
    rng = np.random.default_rng(22)
    plan, vals = _distributed_plan_and_values(3, rng, exchange=exch)
    spaces = [plan.backward(v) for v in vals]
    stacked = np.asarray(plan.forward_batched(spaces, Scaling.FULL))
    for i, s in enumerate(spaces):
        single = np.asarray(plan.forward(s, Scaling.FULL))
        np.testing.assert_allclose(stacked[:, i], single, atol=1e-12,
                                   rtol=0)


def test_multi_transform_fused_distributed_batch():
    """multi_transform_* on clones of one distributed plan takes the fused
    SPMD batch path and matches per-transform execution."""
    from spfft_tpu.multi import _shared_plan
    rng = np.random.default_rng(23)
    plan, vals = _distributed_plan_and_values(3, rng)
    base = Transform(plan)
    clones = [base.clone() for _ in range(3)]
    assert _shared_plan(clones) is plan
    outs = multi_transform_backward(clones, vals)
    for i, v in enumerate(vals):
        np.testing.assert_allclose(np.asarray(outs[i]),
                                   np.asarray(plan.backward(v)),
                                   atol=1e-12, rtol=0)
    fouts = multi_transform_forward(clones, outs)
    for i, o in enumerate(outs):
        np.testing.assert_allclose(np.asarray(fouts[i]),
                                   np.asarray(plan.forward(o)),
                                   atol=1e-12, rtol=0)


def test_local_batched_pallas_kernel_interpret(monkeypatch):
    """The local fused-batch kernel branches (_decompress_batched /
    _compress_batched reshape+slice logic) in interpret mode: force
    _pallas_active and route the kernel through interpret so the branch
    is CI-covered, not TPU-only."""
    import functools
    import jax
    from spfft_tpu.ops import gather_kernel as gk

    n = 12
    triplets = np.asarray([(x, y, z) for x in range(n) for y in range(n)
                           if (x + y) % 2 == 0 for z in range(n)], np.int32)
    plan = make_local_plan(TransformType.C2C, n, n, n, triplets,
                           precision="single", use_pallas=True)
    assert plan._pallas is not None
    monkeypatch.setattr(gk, "run_gather",
                        functools.partial(gk.run_gather, interpret=True))
    monkeypatch.setattr(plan, "_pallas_active", True)
    rng = np.random.default_rng(31)
    vals_b = jax.numpy.asarray(
        rng.random((3, plan.index_plan.num_values, 2)).astype(np.float32))
    got = np.asarray(plan._decompress_batched(vals_b, plan._tables))
    want = np.asarray(jax.vmap(
        lambda v: plan._decompress(v, plan._tables, pallas=False))(vals_b))
    np.testing.assert_allclose(got, want, atol=0, rtol=0)
    sticks_b = jax.numpy.asarray(want)
    got_c = np.asarray(plan._compress_batched(sticks_b, plan._tables, 0.5))
    want_c = np.asarray(jax.vmap(
        lambda s: plan._compress(s, plan._tables, 0.5,
                                 pallas=False))(sticks_b))
    np.testing.assert_allclose(got_c, want_c, atol=1e-7, rtol=0)


def test_distributed_batched_r2c():
    from spfft_tpu.parallel import make_distributed_plan, make_mesh
    from test_distributed import split_by_sticks, split_planes
    rng = np.random.default_rng(24)
    dims = (8, 9, 10)
    triplets = hermitian_triplets(rng, dims)
    parts = split_by_sticks(triplets, dims, [1, 1, 1, 1])
    planes = split_planes(dims[2], [1, 1, 1, 1])
    plan = make_distributed_plan(TransformType.R2C, *dims, parts, planes,
                                 mesh=make_mesh(4), precision="double")
    vals = [[random_values(rng, len(p)) for p in parts] for _ in range(2)]
    # hermitian-consistent values: sample a real field's spectrum per batch
    for b in range(2):
        space = rng.standard_normal((dims[2], dims[1], dims[0]))
        freq = np.fft.fftn(space)
        for r, p in enumerate(parts):
            st = p.copy()
            for ax, d in enumerate(dims):
                st[:, ax] = np.where(st[:, ax] < 0, st[:, ax] + d,
                                     st[:, ax])
            vals[b][r] = freq[st[:, 2], st[:, 1], st[:, 0]]
    stacked = np.asarray(plan.backward_batched(vals))
    for i, v in enumerate(vals):
        single = np.asarray(plan.backward(v))
        np.testing.assert_allclose(stacked[:, i], single, atol=1e-10,
                                   rtol=0)


def test_iterate_pointwise_matches_sequential():
    """N scanned steps == N sequential apply_pointwise calls."""
    rng = np.random.default_rng(15)
    plan, vals = _c2c_plan_and_values(1, rng)
    v = vals[0]

    def damp(space, factor):
        return space * factor

    out = np.asarray(plan.iterate_pointwise(v, damp, 0.5, steps=3))
    seq = v
    for _ in range(3):
        seq_il = np.asarray(plan.apply_pointwise(seq, damp, 0.5,
                                                 scaling=Scaling.FULL))
        seq = seq_il[:, 0] + 1j * seq_il[:, 1]
    np.testing.assert_allclose(out[:, 0] + 1j * out[:, 1], seq,
                               atol=1e-10, rtol=0)


def test_local_batched_pallas_pair_io_interpret(monkeypatch):
    """The batched kernel branches with the planar-pair (2, N) boundary
    (pair_values_io): force the threshold + interpret mode and check both
    directions against the vmapped XLA path (regression: the batched
    decompress once dropped the pair flag, silently gathering 2 values)."""
    import functools
    import jax
    from spfft_tpu.ops import gather_kernel as gk
    from spfft_tpu import plan as plan_mod

    monkeypatch.setattr(plan_mod, "PAIR_IO_THRESHOLD", 1)
    n = 12
    triplets = np.asarray([(x, y, z) for x in range(n) for y in range(n)
                           if (x + y) % 2 == 0 for z in range(n)], np.int32)
    plan = make_local_plan(TransformType.C2C, n, n, n, triplets,
                           precision="single", use_pallas=True)
    assert plan.pair_values_io and plan._pallas is not None
    monkeypatch.setattr(gk, "run_gather",
                        functools.partial(gk.run_gather, interpret=True))
    monkeypatch.setattr(plan, "_pallas_active", True)
    rng = np.random.default_rng(32)
    N = plan.index_plan.num_values
    vals_b = jax.numpy.asarray(rng.random((3, 2, N)).astype(np.float32))
    got = np.asarray(plan._decompress_batched(vals_b, plan._tables))
    want = np.asarray(jax.vmap(
        lambda v: plan._decompress(v, plan._tables, pallas=False))(vals_b))
    np.testing.assert_allclose(got, want, atol=0, rtol=0)
    sticks_b = jax.numpy.asarray(want)
    got_c = np.asarray(plan._compress_batched(sticks_b, plan._tables, None))
    want_c = np.asarray(jax.vmap(
        lambda s: plan._compress(s, plan._tables, None,
                                 pallas=False))(sticks_b))
    assert got_c.shape == (3, 2, N)
    np.testing.assert_allclose(got_c, want_c, atol=1e-7, rtol=0)


def test_fused_batch_scaling_proxy_s8():
    """S=8 fusion sanity (round-4 verdict item): single-chip wall-clock
    cannot measure multi-shard fusion economics (the
    FUSED_BATCH_MAX_DIST_TOTAL gate derives from comm_size=1
    measurements — multi.py), so the scaling check is structural: the
    fused batch program must keep a B-INVARIANT collective count (the
    batch rides a vmapped axis inside the same collectives — an unfused
    run pays B times the launches) and its lowered HLO must grow
    sub-linearly in B."""
    import re

    import jax

    rng = np.random.default_rng(31)
    plan, vals = _distributed_plan_and_values(
        4, rng, shards=8)
    jitted = plan._batched_jits()["backward"]

    def lowered_text(B):
        batch = plan.shard_values_batch(vals[:B])
        return jitted.lower(batch, *plan._device_tables).as_text()

    t2, t4 = lowered_text(2), lowered_text(4)

    def collectives(t):
        return len(re.findall(
            r"all_to_all|collective_permute|all_gather|all_reduce", t))

    assert collectives(t2) == collectives(t4) > 0
    assert len(t4) < 1.6 * len(t2)
    # and the fused result is still correct at S=8
    stacked = np.asarray(plan.backward_batched(vals))
    for i, v in enumerate(vals):
        np.testing.assert_allclose(stacked[:, i],
                                   np.asarray(plan.backward(v)),
                                   atol=1e-12, rtol=0)

"""scripts/bench_regress.py: the machine-checked perf-trajectory guard
(make bench-check). Exercised in-process via runpy-style import of the
script's main(), with synthetic measurement files."""

import json
import importlib.util
import os
import sys

import pytest

_SCRIPT = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts", "bench_regress.py")
_spec = importlib.util.spec_from_file_location("bench_regress", _SCRIPT)
bench_regress = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(bench_regress)


def _write(path, value, unit="s", wrap=False, metric="m"):
    payload = {"metric": metric, "value": value, "unit": unit}
    if wrap:
        payload = {"n": 1, "parsed": payload}
    path.write_text(json.dumps(payload))
    return str(path)


def test_within_threshold_passes(tmp_path, capsys):
    fresh = _write(tmp_path / "fresh.json", 0.0110)
    ref = _write(tmp_path / "ref.json", 0.0106)
    rc = bench_regress.main(["--fresh", fresh, "--against", ref,
                             "--threshold", "0.15"])
    assert rc == 0
    verdict = json.loads(capsys.readouterr().out.splitlines()[-1])
    assert verdict["ok"] and verdict["verdict"] == "within-threshold"


def test_seconds_regression_fails(tmp_path, capsys):
    fresh = _write(tmp_path / "fresh.json", 0.020)  # ~2x slower
    ref = _write(tmp_path / "ref.json", 0.0106)
    rc = bench_regress.main(["--fresh", fresh, "--against", ref])
    assert rc == 1
    verdict = json.loads(capsys.readouterr().out.splitlines()[-1])
    assert not verdict["ok"]
    assert verdict["direction"] == "lower-is-better"


def test_rate_unit_direction(tmp_path, capsys):
    # req/s: HIGHER is better — a drop regresses, a gain passes
    ref = _write(tmp_path / "ref.json", 1000.0, unit="req/s")
    worse = _write(tmp_path / "worse.json", 500.0, unit="req/s")
    better = _write(tmp_path / "better.json", 2000.0, unit="req/s")
    assert bench_regress.main(["--fresh", worse, "--against", ref]) == 1
    assert bench_regress.main(["--fresh", better, "--against", ref]) == 0
    capsys.readouterr()


def test_improvement_always_passes(tmp_path):
    fresh = _write(tmp_path / "fresh.json", 0.005)  # 2x faster
    ref = _write(tmp_path / "ref.json", 0.0106)
    assert bench_regress.main(["--fresh", fresh,
                               "--against", ref]) == 0


def test_wrapped_bench_r_format_and_latest_selection(tmp_path, capsys):
    # driver BENCH_r*.json format resolves through "parsed", and the
    # highest-numbered reference wins
    _write(tmp_path / "BENCH_r02.json", 0.020, wrap=True)
    _write(tmp_path / "BENCH_r10.json", 0.010, wrap=True)
    fresh = _write(tmp_path / "fresh.json", 0.0105)
    rc = bench_regress.main(["--fresh", fresh, "--root", str(tmp_path)])
    assert rc == 0
    verdict = json.loads(capsys.readouterr().out.splitlines()[-1])
    assert verdict["reference"] == 0.010
    assert verdict["reference_file"].endswith("BENCH_r10.json")


def test_no_reference_is_not_a_failure(tmp_path, capsys):
    fresh = _write(tmp_path / "fresh.json", 0.010)
    rc = bench_regress.main(["--fresh", fresh, "--root", str(tmp_path)])
    assert rc == 0
    verdict = json.loads(capsys.readouterr().out.splitlines()[-1])
    assert verdict["verdict"] == "no-reference"


def test_usage_errors(tmp_path, capsys):
    fresh = _write(tmp_path / "fresh.json", 0.010)
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    assert bench_regress.main(["--fresh", str(bad)]) == 2
    other_unit = _write(tmp_path / "o.json", 5.0, unit="req/s")
    assert bench_regress.main(["--fresh", fresh, "--against",
                               other_unit]) == 2
    assert bench_regress.main(["--fresh", fresh, "--against", fresh,
                               "--threshold", "2.0"]) == 2
    capsys.readouterr()


def test_real_repo_reference_resolves():
    """The repo's own BENCH_r*.json trail is a usable reference."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ref = bench_regress.latest_reference(root)
    assert ref is not None and ref.endswith("BENCH_r06.json")
    value, unit, metric = bench_regress.load_measurement(ref)
    assert unit == "s" and value > 0
    # the round-13 cold/warm sub-rows are present and well-formed
    payload = bench_regress.load_payload(ref)
    for row in ("cold_start_ms", "warm_start_ms"):
        v, u, _ = bench_regress.measurement(payload, ref, row=row)
        assert u == "ms" and v > 0
    # the round-15 hermitian-symmetry sub-rows: trimmed wire at half
    # the recorded untrimmed C2C bytes, both fused r2c seams active
    v, u, m = bench_regress.measurement(payload, ref,
                                        row="wire_bytes_r2c")
    assert u == "bytes" and 0 < v <= 0.55 * 92164352
    v, u, _ = bench_regress.measurement(payload, ref, row="fused_r2c")
    assert u == "seams" and v == 2
    # the round-16 fused x overlap composition row: both distributed
    # fused directions active under the K=2 pipeline
    v, u, _ = bench_regress.measurement(payload, ref, row="fused_dist")
    assert u == "directions" and v == 2


def _write_with_fused(path, value, fused_value, unit="s", wrap=False):
    payload = {"metric": "m", "value": value, "unit": unit,
               "fused": {"metric": "m fused", "value": fused_value,
                         "unit": unit}}
    if wrap:
        payload = {"n": 1, "parsed": payload}
    path.write_text(json.dumps(payload))
    return str(path)


def test_fused_row_compared_when_both_sides_carry_it(tmp_path, capsys):
    """A 'fused' sub-row in both files is compared with the same rules
    and regresses the exit code on its own (BENCH_r06.json onward)."""
    ref = _write_with_fused(tmp_path / "BENCH_r06.json", 0.0106, 0.008,
                            wrap=True)
    ok = _write_with_fused(tmp_path / "ok.json", 0.0107, 0.0081)
    assert bench_regress.main(["--fresh", ok, "--against", ref]) == 0
    lines = [json.loads(li) for li in
             capsys.readouterr().out.splitlines()]
    assert [v["row"] for v in lines] == ["primary", "fused"]
    assert all(v["ok"] for v in lines)

    # primary fine, fused 2x slower -> regression from the fused row
    bad = _write_with_fused(tmp_path / "bad.json", 0.0107, 0.016)
    assert bench_regress.main(["--fresh", bad, "--against", ref]) == 1
    lines = [json.loads(li) for li in
             capsys.readouterr().out.splitlines()]
    by_row = {v["row"]: v for v in lines}
    assert by_row["primary"]["ok"]
    assert not by_row["fused"]["ok"]


def test_fused_row_one_sided_is_skipped(tmp_path, capsys):
    """A fused row on only one side (older reference predates it, or a
    fresh run without --fused) is reported and never fails."""
    ref_plain = _write(tmp_path / "ref.json", 0.0106)
    fresh_fused = _write_with_fused(tmp_path / "fresh.json", 0.0107,
                                    0.008)
    assert bench_regress.main(["--fresh", fresh_fused,
                               "--against", ref_plain]) == 0
    lines = [json.loads(li) for li in
             capsys.readouterr().out.splitlines()]
    assert lines[-1]["verdict"] == "row-no-reference"
    assert lines[-1]["row"] == "fused"
    # and the mirror: reference has it, fresh does not
    ref_fused = _write_with_fused(tmp_path / "r.json", 0.0106, 0.008)
    fresh_plain = _write(tmp_path / "f.json", 0.0107)
    assert bench_regress.main(["--fresh", fresh_plain,
                               "--against", ref_fused]) == 0
    capsys.readouterr()


def _write_symmetry(path, value, wire, seams, wrap=False):
    payload = {"metric": "m", "value": value, "unit": "s",
               "wire_bytes_r2c": {"metric": "w", "value": wire,
                                  "unit": "bytes"},
               "fused_r2c": {"metric": "f", "value": seams,
                             "unit": "seams"}}
    if wrap:
        payload = {"n": 1, "parsed": payload}
    path.write_text(json.dumps(payload))
    return str(path)


def test_wire_bytes_row_gates_the_halving(tmp_path, capsys):
    """wire_bytes_r2c is bytes = lower-is-better: the deterministic
    trimmed accounting passes at equality and fails if the exchange
    re-inflates toward the untrimmed byte count."""
    ref = _write_symmetry(tmp_path / "BENCH_r06.json", 0.0106,
                          46084864, 2, wrap=True)
    same = _write_symmetry(tmp_path / "same.json", 0.0106, 46084864, 2)
    assert bench_regress.main(["--fresh", same, "--against", ref]) == 0
    lines = [json.loads(li) for li in
             capsys.readouterr().out.splitlines()]
    by_row = {v["row"]: v for v in lines}
    assert by_row["wire_bytes_r2c"]["direction"] == "lower-is-better"
    assert by_row["fused_r2c"]["direction"] == "higher-is-better"

    untrimmed = _write_symmetry(tmp_path / "bad.json", 0.0106,
                                92164352, 2)
    assert bench_regress.main(["--fresh", untrimmed,
                               "--against", ref]) == 1
    capsys.readouterr()


def test_fused_r2c_row_gates_the_decline(tmp_path, capsys):
    """A fused r2c seam dropping back to declined (2 -> 1 active) trips
    the rate-direction comparison on its own."""
    ref = _write_symmetry(tmp_path / "ref.json", 0.0106, 46084864, 2)
    declined = _write_symmetry(tmp_path / "bad.json", 0.0106,
                               46084864, 1)
    assert bench_regress.main(["--fresh", declined,
                               "--against", ref]) == 1
    by_row = {v["row"]: v for v in
              (json.loads(li) for li in
               capsys.readouterr().out.splitlines())}
    assert not by_row["fused_r2c"]["ok"]


def _write_fused_dist(path, value, directions, wrap=False):
    payload = {"metric": "m", "value": value, "unit": "s",
               "fused_dist": {"metric": "d", "value": directions,
                              "unit": "directions"}}
    if wrap:
        payload = {"n": 1, "parsed": payload}
    path.write_text(json.dumps(payload))
    return str(path)


def test_fused_dist_row_gates_the_composition(tmp_path, capsys):
    """The fused x overlap composition row: a distributed fused
    direction dropping back to declined (2 -> 1) trips the
    rate-direction comparison, and a one-sided row (reference predates
    the composition) stays a skip."""
    ref = _write_fused_dist(tmp_path / "ref.json", 0.0106, 2)
    both = _write_fused_dist(tmp_path / "ok.json", 0.0106, 2)
    assert bench_regress.main(["--fresh", both, "--against", ref]) == 0
    by_row = {v["row"]: v for v in
              (json.loads(li) for li in
               capsys.readouterr().out.splitlines())}
    assert by_row["fused_dist"]["direction"] == "higher-is-better"

    declined = _write_fused_dist(tmp_path / "bad.json", 0.0106, 1)
    assert bench_regress.main(["--fresh", declined,
                               "--against", ref]) == 1
    by_row = {v["row"]: v for v in
              (json.loads(li) for li in
               capsys.readouterr().out.splitlines())}
    assert not by_row["fused_dist"]["ok"]

    # one-sided-skip semantics preserved: an older reference without
    # the row never fails the fresh run that carries it
    old_ref = _write(tmp_path / "old.json", 0.0106)
    assert bench_regress.main(["--fresh", both,
                               "--against", old_ref]) == 0
    lines = [json.loads(li) for li in
             capsys.readouterr().out.splitlines()]
    assert lines[-1] == {"ok": True, "verdict": "row-no-reference",
                         "row": "fused_dist", "missing": "reference"}

"""Hermitian wire trimming (indexing.canonicalize_hermitian_triplets):
a Gamma-style full-sphere R2C set folds its redundant x < 0 half onto
conjugate mirrors at plan time, so the distributed exchange ships only
the non-redundant stick set — the wire halving of ISSUE r06.

Properties checked here, on the virtual CPU mesh:

* the folded full-sphere plan EXCHANGES exactly the bytes of the
  explicit half-spectrum plan (the mirrors never touch the wire), for
  all three exchange mechanisms and every overlap chunk count;
* wire bytes are conserved exactly across ``overlap_chunks`` — chunking
  never re-inflates the trimmed set;
* the backward grid is BIT-exact between the folded and the
  half-spectrum plan (union-of-chunks included), single, batched and
  through the fused pointwise pair body;
* on the 256^3 spherical benchmark set the trimmed R2C wire is at most
  55% of the untrimmed (full-sphere C2C) wire — the acceptance bound.
"""

import numpy as np
import pytest

from spfft_tpu import ExchangeType, TransformType
from spfft_tpu.parallel import make_distributed_plan, make_mesh

from test_distributed import split_by_sticks, split_planes
from test_util import dense_forward, hermitian_triplets, sample_cube

DIMS = (10, 9, 12)

# exchange "kind" -> (ExchangeType, SPFFT_TPU_COMPACT_PPERMUTE)
KINDS = {
    "block": (ExchangeType.BUFFERED, None),
    "ragged": (ExchangeType.COMPACT_BUFFERED, None),
    "compact": (ExchangeType.COMPACT_BUFFERED, "1"),
}

SKEWS = {
    "uniform": ([1, 1, 1], [1, 1, 1]),
    "skewed": ([3, 1, 2], [1, 3, 1]),
}


def _centered(storage: np.ndarray, dims) -> np.ndarray:
    """Storage triplets -> centered signed triplets."""
    out = storage.astype(np.int64).copy()
    for axis, n in enumerate(dims):
        col = out[:, axis]
        out[:, axis] = np.where(col >= (n + 1) // 2, col - n, col)
    return out


def _centered_yz(storage: np.ndarray, dims) -> np.ndarray:
    """Storage y/z -> centered signed (x kept: hermitian sets carry
    x in [0, nx//2] as-is; mixing storage and signed coordinates in one
    set would trip the centered bounds check)."""
    out = storage.astype(np.int64).copy()
    for axis in (1, 2):
        n = dims[axis]
        col = out[:, axis]
        # the centered convention keeps the even-dimension edge as +N/2
        # (a user-supplied -N/2 is rejected, matching the reference)
        out[:, axis] = np.where(col > n // 2, col - n, col)
    return out


def _with_mirrors(part: np.ndarray, dims):
    """Append the redundant conjugate mirrors of every x > 0 triplet —
    the full-sphere layout the folding exists for. Returns the extended
    (centered) triplet array and the index array mapping mirrors to
    originals."""
    cen = _centered_yz(part, dims)
    pos = np.nonzero(cen[:, 0] > 0)[0]
    # -(-N/2) = +N/2 stays as-is: canonicalize accepts the even-edge
    # mirror on folded triplets and normalises it back to -N/2
    return np.concatenate([cen, -cen[pos]]), pos


def _plans_and_values(kind, skew, overlap_chunks, monkeypatch, seed=2):
    exch, ppermute = KINDS[kind]
    if ppermute is None:
        monkeypatch.delenv("SPFFT_TPU_COMPACT_PPERMUTE", raising=False)
    else:
        monkeypatch.setenv("SPFFT_TPU_COMPACT_PPERMUTE", ppermute)
    rng = np.random.default_rng(seed)
    nx, ny, nz = DIMS
    freq = dense_forward(rng.uniform(-1, 1, (nz, ny, nx)))
    sticks_w, planes_w = SKEWS[skew]
    half_parts = split_by_sticks(hermitian_triplets(rng, DIMS), DIMS,
                                 sticks_w)
    planes = split_planes(nz, planes_w)
    # mirrors ride WITH their target stick's shard (a stick lives on one
    # shard; the fold may not move it)
    full_parts, mirror_idx = zip(*[_with_mirrors(p, DIMS)
                                   for p in half_parts])
    half_vals = [sample_cube(freq, p, DIMS).astype(np.complex64)
                 for p in half_parts]
    # mirror values as EXACT conjugates, so the fold (which conjugates
    # them back) reproduces the half-spectrum values to the bit
    full_vals = [np.concatenate([v, np.conj(v[ix])])
                 for v, ix in zip(half_vals, mirror_idx)]

    def build(ttype, parts):
        return make_distributed_plan(ttype, *DIMS, list(parts), planes,
                                     mesh=make_mesh(3), precision="single",
                                     exchange=exch,
                                     overlap_chunks=overlap_chunks)

    return (build(TransformType.R2C, full_parts),
            build(TransformType.R2C, half_parts),
            full_vals, half_vals, build, full_parts)


@pytest.mark.parametrize("kind", sorted(KINDS))
@pytest.mark.parametrize("skew", sorted(SKEWS))
@pytest.mark.parametrize("chunks", [1, 2, 4])
def test_trimmed_exchange_bit_exact_and_wire_equal(kind, skew, chunks,
                                                   monkeypatch):
    """The folded full-sphere plan ships half-plan bytes and reproduces
    every grid element bit-exactly (union of chunks at K > 1)."""
    full, half, full_vals, half_vals, _, _ = _plans_and_values(
        kind, skew, chunks, monkeypatch)
    # the mirrors never reach the wire: byte-identical accounting
    assert full.exchange_wire_bytes() == half.exchange_wire_bytes()
    assert (full.exchange_busiest_link_bytes()
            == half.exchange_busiest_link_bytes())
    got = np.concatenate(full.unshard_space(full.backward(full_vals)),
                         axis=0)
    ref = np.concatenate(half.unshard_space(half.backward(half_vals)),
                         axis=0)
    np.testing.assert_array_equal(got, ref)


@pytest.mark.parametrize("kind", sorted(KINDS))
def test_trimmed_wire_conserved_across_chunking(kind, monkeypatch):
    """exchange_wire_bytes() of the trimmed plan is EXACTLY the same
    number at every overlap chunk count — chunking re-slices, never
    re-inflates (the conservation half of the acceptance bound)."""
    wires = []
    for chunks in (1, 2, 4):
        full, half, _, _, _, _ = _plans_and_values(kind, "skewed", chunks,
                                                   monkeypatch)
        assert full.exchange_wire_bytes() == half.exchange_wire_bytes()
        wires.append(full.exchange_wire_bytes())
    assert wires[0] == wires[1] == wires[2]


@pytest.mark.parametrize("kind", ["ragged", "block"])
def test_trimmed_batched_and_pair_bit_exact(kind, monkeypatch):
    """Batched execution and the fused pointwise pair body run the same
    folded tables — bit-exact against the half-spectrum plan."""
    full, half, full_vals, half_vals, _, _ = _plans_and_values(
        kind, "uniform", 1, monkeypatch)
    batch_f = [[(v * (b + 1)).astype(np.complex64) for v in full_vals]
               for b in range(3)]
    batch_h = [[(v * (b + 1)).astype(np.complex64) for v in half_vals]
               for b in range(3)]
    got = np.asarray(full.backward_batched(full.shard_values_batch(batch_f)))
    ref = np.asarray(half.backward_batched(half.shard_values_batch(batch_h)))
    np.testing.assert_array_equal(got, ref)

    # pair path: backward -> identity -> forward must round-trip the
    # folded values to the half plan's pair output on the common
    # (non-mirror) value prefix of every shard
    pf = np.asarray(full.apply_pointwise(full.shard_values(full_vals)))
    ph = np.asarray(half.apply_pointwise(half.shard_values(half_vals)))
    for r, v in enumerate(half_vals):
        np.testing.assert_array_equal(pf[r, :len(v)], ph[r, :len(v)])


def test_trimmed_wire_reduction_vs_untrimmed(monkeypatch):
    """Against the UNTRIMMED baseline (a C2C plan over the same full
    sphere) the trimmed R2C plan ships strictly fewer bytes on every
    mechanism — the exact 55% bound is asserted on the 256^3 benchmark
    set below (small dims carry a thicker self-mirror boundary)."""
    for kind in sorted(KINDS):
        full, half, _, _, build, full_parts = _plans_and_values(
            kind, "uniform", 1, monkeypatch)
        # storage coordinates: the C2C bounds reject the hermitian-only
        # -nx/2 edge mirror, whose storage index is +nx/2
        c2c = build(TransformType.C2C,
                    [fp % np.array(DIMS, np.int64) for fp in full_parts])
        assert full.exchange_wire_bytes() < c2c.exchange_wire_bytes()


def _sphere_half_and_full(n, radius):
    """Centered spherical frequency set at n^3: the non-redundant
    hermitian half (x > 0, plus the x = 0 plane's canonical half) and
    the full sphere (mirrors appended)."""
    ax = np.arange(-(n // 2), (n + 1) // 2, dtype=np.int32)
    x, y, z = np.meshgrid(ax, ax, ax, indexing="ij")
    inside = (x.astype(np.int64) ** 2 + y.astype(np.int64) ** 2
              + z.astype(np.int64) ** 2) <= radius * radius
    pts = np.stack([x[inside], y[inside], z[inside]], axis=1)
    keep = (pts[:, 0] > 0) | ((pts[:, 0] == 0) & (
        (pts[:, 1] > 0) | ((pts[:, 1] == 0) & (pts[:, 2] >= 0))))
    half = pts[keep]
    pos = half[half[:, 0] > 0]
    full = np.concatenate([half, -pos])
    return half, full


def test_wire_halving_256_sphere():
    """Acceptance: 256^3 spherical benchmark set, 4 shards — trimmed R2C
    exchange_wire_bytes() is at most 55% of the untrimmed (full-sphere
    C2C) plan's, and the number is conserved exactly at every overlap
    chunk count."""
    n, radius = 256, 100
    half, full = _sphere_half_and_full(n, radius)
    dims = (n, n, n)
    half_parts = split_by_sticks(half, dims, [1, 1, 1, 1])
    # co-locate each mirror with its target stick's shard
    full_parts = [np.concatenate([p, -_centered(p, dims)[
        _centered(p, dims)[:, 0] > 0]]) for p in half_parts]
    planes = split_planes(n, [1, 1, 1, 1])

    def build(ttype, parts, chunks):
        return make_distributed_plan(
            ttype, *dims, parts, planes, mesh=make_mesh(4),
            precision="single", exchange=ExchangeType.COMPACT_BUFFERED,
            overlap_chunks=chunks)

    r2c_wires = [build(TransformType.R2C, full_parts,
                       k).exchange_wire_bytes() for k in (1, 2, 4)]
    assert r2c_wires[0] == r2c_wires[1] == r2c_wires[2]
    c2c = build(TransformType.C2C, full_parts, 1)
    ratio = r2c_wires[0] / c2c.exchange_wire_bytes()
    assert ratio <= 0.55, f"wire ratio {ratio:.3f} > 0.55"

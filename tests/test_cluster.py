"""Pod-scale multi-host serving (serve/cluster.py): the tier-1 twin of
``make cluster-smoke``.

The contracts under test (docs/cluster.md): a 2-host emulated pod
serves mixed single-device + ``DistributedTransformPlan`` traffic
bit-exact vs direct plan calls; construction reconciles the pod (plan
sets and distributed-plan fingerprints, typed
``ClusterReconciliationError`` on any disagreement); routing is
power-of-two-choices over live load signals (the skewed-load
simulation gates rr >= 4x vs p2c <= 2x); one trace id survives the
host boundary with valid parent/child nesting; the federated /metrics
document re-parses; and under injected ``cluster.*`` faults every
issued future resolves with zero unclosed spans.
"""

import threading

import numpy as np
import pytest

from spfft_tpu import faults, obs
from spfft_tpu.benchmark import cutoff_stick_triplets
from spfft_tpu.errors import (ClusterError, ClusterReconciliationError,
                              DistributedPlanUnsupportedError,
                              HostLaneError, InvalidParameterError)
from spfft_tpu.faults import FaultPlan, InjectedFault
from spfft_tpu.parallel import make_distributed_plan, make_mesh
from spfft_tpu.serve.cluster import (HostLane, PodFrontend,
                                     load_score, simulate_routing)
from spfft_tpu.serve.executor import ServeExecutor
from spfft_tpu.serve.registry import PlanRegistry, signature_for
from spfft_tpu.types import TransformType
from spfft_tpu.utils.workloads import (even_plane_split,
                                       round_robin_stick_partition)

N = 8
DIMS = (N, N, N)
SHARDS = 2


@pytest.fixture(scope="module")
def pod_plans():
    """One local plan + one 2-shard distributed plan, built once and
    shared across every pod in the module (lanes ``put`` the same plan
    objects, which is exactly what reconciliation must accept)."""
    trip = cutoff_stick_triplets(N, N, N, 0.9, hermitian=False)
    reg = PlanRegistry()
    sig, plan = reg.get_or_build(TransformType.C2C, *DIMS, trip,
                                 precision="double")
    parts = round_robin_stick_partition(trip, DIMS, SHARDS)
    planes = even_plane_split(DIMS[2], SHARDS)
    dplan = make_distributed_plan(TransformType.C2C, *DIMS, parts,
                                  planes, mesh=make_mesh(SHARDS),
                                  precision="double")
    dsig = signature_for(TransformType.C2C, *DIMS, trip,
                         precision="double", device_count=SHARDS)
    return {"trip": trip, "sig": sig, "plan": plan,
            "dsig": dsig, "dplan": dplan, "parts": parts,
            "planes": planes}


def _make_pod(p, hosts=("h0", "h1"), with_dist=True, **kw):
    lanes = []
    for host in hosts:
        reg = PlanRegistry()
        reg.put(p["sig"], p["plan"])
        if with_dist:
            reg.put(p["dsig"], p["dplan"])
        lanes.append((host, ServeExecutor(reg)))
    return PodFrontend(lanes, **kw)


def _close_all(pod):
    pod.close()
    for lane in pod._lanes:  # close() skips dead lanes' executors
        lane.executor.close()


def _values(p, rng):
    n = len(p["trip"])
    return rng.standard_normal(n) + 1j * rng.standard_normal(n)


def _dvalues(p, rng):
    return [rng.standard_normal(sp.num_values)
            + 1j * rng.standard_normal(sp.num_values)
            for sp in p["dplan"].dist_plan.shard_plans]


# -- routing + execution ------------------------------------------------------
def test_pod_mixed_traffic_bit_exact(pod_plans):
    """Singles route across hosts, the distributed request runs on the
    SPMD lane — all bit-exact vs direct plan calls — and the frontend
    retires DistributedPlanUnsupportedError (it remains the bare
    single-host executor's answer)."""
    p = pod_plans
    rng = np.random.default_rng(0)
    pod = _make_pod(p)
    try:
        singles = [(v, pod.submit_backward(p["sig"], v))
                   for v in (_values(p, rng) for _ in range(8))]
        dv = _dvalues(p, rng)
        dfut = pod.submit(p["dsig"], dv)
        for v, fut in singles:
            assert np.array_equal(
                np.asarray(fut.result(timeout=60)),
                np.asarray(p["plan"].backward(v)))
        assert np.array_equal(np.asarray(dfut.result(timeout=60)),
                              np.asarray(p["dplan"].backward(dv)))
    finally:
        _close_all(pod)

    reg = PlanRegistry()
    reg.put(p["dsig"], p["dplan"])
    with ServeExecutor(reg) as ex:
        with pytest.raises(DistributedPlanUnsupportedError):
            ex.submit(p["dsig"], _dvalues(p, rng))


def test_p2c_beats_rr_on_skewed_load():
    """The Round-18 routing scenario: round-robin aliases every heavy
    request onto one host (completed-skew >= 4x) while p2c over the
    live load_score keeps the pod balanced (<= 2x)."""
    rr = simulate_routing("rr")
    p2c = simulate_routing("p2c")
    assert sum(rr["assigned"]) == sum(p2c["assigned"]) == 400
    assert rr["ratio"] >= 4.0
    assert p2c["ratio"] <= 2.0
    assert rr["ratio"] / p2c["ratio"] >= 2.0


def test_load_score_orders_hosts():
    idle = {"queue_depth": 0, "device_execute_p50": 0.002}
    busy = {"queue_depth": 5, "device_execute_p50": 0.002}
    cold = {"queue_depth": 1, "device_execute_p50": 0.0}
    assert load_score(idle) < load_score(cold) < load_score(busy)


def test_pod_validation_errors(pod_plans):
    p = pod_plans
    with pytest.raises(InvalidParameterError):
        PodFrontend([], policy="p2c")
    with pytest.raises(InvalidParameterError):
        _make_pod(p, policy="weighted")
    with pytest.raises(InvalidParameterError):
        _make_pod(p, hosts=("h0", "h0"))
    pod = _make_pod(p, with_dist=False)
    try:
        with pytest.raises(InvalidParameterError):
            pod.submit(p["dsig"], [])  # signature never warmed up
        with pytest.raises(InvalidParameterError):
            pod.submit(p["sig"], [], kind="sideways")
    finally:
        _close_all(pod)


# -- federated telemetry ------------------------------------------------------
def test_cross_host_trace_single_trace_id(pod_plans):
    """Every host-side serve.request / cluster.spmd_execute span is a
    child of the frontend's cluster.request root with the SAME trace
    id, and nothing leaks open."""
    p = pod_plans
    rng = np.random.default_rng(1)
    obs.enable()
    tracer = obs.GLOBAL_TRACER
    tracer.reset()
    tracer.set_sample_rate(1.0)
    pod = _make_pod(p)
    try:
        futs = [pod.submit_backward(p["sig"], _values(p, rng))
                for _ in range(6)]
        futs.append(pod.submit(p["dsig"], _dvalues(p, rng)))
        for fut in futs:
            fut.result(timeout=60)
    finally:
        _close_all(pod)
        obs.disable()
    assert tracer.open_count() == 0, tracer.open_names()
    spans = [e for e in tracer.events() if isinstance(e, obs.Span)]
    roots = [s for s in spans if s.name == "cluster.request"]
    assert len(roots) == 7
    by_id = {s.span_id: s for s in spans}
    crossed = 0
    for s in spans:
        if s.name in ("serve.request", "cluster.spmd_execute"):
            parent = by_id[s.parent_id]
            assert parent.name == "cluster.request"
            assert s.trace_id == parent.trace_id
            crossed += 1
    assert crossed == 7


def test_merged_metrics_parse_and_health(pod_plans):
    p = pod_plans
    rng = np.random.default_rng(2)
    pod = _make_pod(p)
    try:
        for _ in range(6):
            pod.submit_backward(p["sig"],
                                _values(p, rng)).result(timeout=60)
        assert pod.health()["state"] == "healthy"
        parsed = obs.parse_prometheus_text(pod.metrics_text())
        hosts = {dict(labels).get("host") for (name, labels) in parsed
                 if name == "spfft_serve_completed_total"}
        assert {"h0", "h1"} <= hosts
        families = {name for name, _ in parsed}
        assert "spfft_cluster_routed_total" in families
        assert "spfft_cluster_health" in families

        pod.kill_host("h1")
        health = pod.health()
        assert health["state"] == "degraded"
        assert health["alive"] == 1
        assert health["hosts"]["h1"]["state"] == "failed"
        # the merged document stays valid with a lane down
        obs.parse_prometheus_text(pod.metrics_text())
        got = np.asarray(pod.submit_backward(
            p["sig"], _values(p, rng)).result(timeout=60))
        assert got.shape  # survivor still serves
    finally:
        _close_all(pod)


def test_merged_metrics_no_duplicate_series(pod_plans):
    """Regression: with >= 2 IN-PROCESS lanes, each process-global
    series (trace/timing/cluster families) must appear EXACTLY once in
    the federated document — the pre-fix merge emitted every lane's
    copy of the process globals, so the text carried duplicate samples
    whose effective value depended on lane iteration order. Lane-level
    serve/registry families still appear once PER HOST."""
    p = pod_plans
    rng = np.random.default_rng(7)
    pod = _make_pod(p)
    try:
        for _ in range(4):
            pod.submit_backward(p["sig"],
                                _values(p, rng)).result(timeout=60)
        text = pod.metrics_text()
        samples = [ln.split("{")[0].split(" ")[0]
                   + (("{" + ln.split("{", 1)[1].rsplit("}", 1)[0]
                       + "}") if "{" in ln else "")
                   for ln in text.splitlines()
                   if ln and not ln.startswith("#")]
        dupes = {s for s in samples if samples.count(s) > 1}
        assert not dupes, f"duplicate series in pod exposition: " \
                          f"{sorted(dupes)[:5]}"
        parsed = obs.parse_prometheus_text(text)
        # per-host lane families survive the merge, host-labelled
        hosts = {dict(labels).get("host") for (name, labels) in parsed
                 if name == "spfft_serve_completed_total"}
        assert {"h0", "h1"} <= hosts
    finally:
        _close_all(pod)


# -- reconciliation -----------------------------------------------------------
def test_reconciliation_rejects_differing_plan_sets(pod_plans):
    p = pod_plans
    lanes = []
    try:
        for host, with_dist in (("h0", True), ("h1", False)):
            reg = PlanRegistry()
            reg.put(p["sig"], p["plan"])
            if with_dist:
                reg.put(p["dsig"], p["dplan"])
            lanes.append(HostLane(host, ServeExecutor(reg)))
        with pytest.raises(ClusterReconciliationError,
                           match="different plan set"):
            PodFrontend(lanes)
    finally:
        for lane in lanes:
            lane.executor.close()


def test_reconciliation_rejects_fingerprint_mismatch(pod_plans):
    """Same signature, different sharding: host h1 holds a distributed
    plan whose stick partition is permuted — the loopback digest
    collective must catch it exactly as the real one would."""
    p = pod_plans
    other = make_distributed_plan(
        TransformType.C2C, *DIMS, list(reversed(p["parts"])),
        p["planes"], mesh=make_mesh(SHARDS), precision="double")
    lanes = []
    try:
        for host, dplan in (("h0", p["dplan"]), ("h1", other)):
            reg = PlanRegistry()
            reg.put(p["sig"], p["plan"])
            reg.put(p["dsig"], dplan)
            lanes.append(HostLane(host, ServeExecutor(reg)))
        with pytest.raises(ClusterReconciliationError,
                           match="disagrees across the pod"):
            PodFrontend(lanes)
    finally:
        for lane in lanes:
            lane.executor.close()


def test_reconciliation_rpc_fault_is_typed(pod_plans):
    p = pod_plans
    faults.arm(FaultPlan(script="cluster.rpc@1"))
    try:
        with pytest.raises(ClusterReconciliationError,
                           match="reconciliation RPC failed"):
            _make_pod(p, with_dist=False)
    finally:
        faults.disarm()


# -- failure semantics --------------------------------------------------------
def test_dead_lane_failover(pod_plans):
    """A lane whose transport is down is routed around (and marked
    dead); a scripted cluster.route fault surfaces as the typed
    injected fault, not a hang."""
    p = pod_plans
    rng = np.random.default_rng(3)
    pod = _make_pod(p, with_dist=False)
    try:
        pod._lanes[0].transport.alive = False
        v = _values(p, rng)
        got = np.asarray(
            pod.submit_backward(p["sig"], v).result(timeout=60))
        assert np.array_equal(got, np.asarray(p["plan"].backward(v)))
        assert pod._lanes[1].executor.metrics.snapshot()["completed"] \
            >= 1

        faults.arm(FaultPlan(script="cluster.route@1"))
        try:
            with pytest.raises(InjectedFault):
                pod.submit_backward(p["sig"], v)
        finally:
            faults.disarm()
        assert pod.health()["state"] == "degraded"
    finally:
        _close_all(pod)


def test_all_lanes_dead_is_typed(pod_plans):
    p = pod_plans
    pod = _make_pod(p, with_dist=False)
    try:
        for lane in pod._lanes:
            lane.transport.alive = False
        with pytest.raises(ClusterError):
            pod.submit_backward(p["sig"], np.zeros(len(p["trip"]),
                                                   complex))
        assert pod.health()["state"] == "failed"
    finally:
        _close_all(pod)


def test_fuzz_cluster_faults_zero_unclosed_spans(pod_plans):
    """8 threads hammering the pod under seeded cluster.rpc transient
    faults: every failure is typed, every issued future resolves, and
    the tracer ends with zero open spans."""
    p = pod_plans
    obs.enable()
    tracer = obs.GLOBAL_TRACER
    tracer.reset()
    tracer.set_sample_rate(1.0)
    pod = _make_pod(p)
    errors = []
    futures = []
    flock = threading.Lock()

    def worker(tid):
        rng = np.random.default_rng(100 + tid)
        for i in range(6):
            try:
                if i == 3:
                    fut = pod.submit(p["dsig"], _dvalues(p, rng))
                else:
                    fut = pod.submit_backward(p["sig"],
                                              _values(p, rng))
                with flock:
                    futures.append(fut)
            except (HostLaneError, ClusterError, InjectedFault) as exc:
                with flock:
                    errors.append(exc)
            except Exception as exc:  # untyped = a real bug
                with flock:
                    errors.append(AssertionError(repr(exc)))

    faults.arm(FaultPlan(rate=0.15, seed=7, scope="cluster.rpc"))
    try:
        threads = [threading.Thread(target=worker, args=(t,))
                   for t in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    finally:
        faults.disarm()

    try:
        for fut in futures:
            try:
                fut.result(timeout=60)  # resolves either way
            except Exception:
                pass
    finally:
        _close_all(pod)
        obs.disable()
    assert not [e for e in errors if isinstance(e, AssertionError)], \
        errors
    assert tracer.open_count() == 0, tracer.open_names()


def test_pod_frontend_importable_from_serve():
    from spfft_tpu import serve
    assert serve.PodFrontend is PodFrontend
    assert callable(serve.simulate_routing)

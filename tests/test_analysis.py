"""Tests for the project lint engine (spfft_tpu.analysis).

Each checker runs over a seeded-violation fixture module and must
report exactly the planted findings — and zero on the clean twin. The
meta-tests then pin the real package: ``python -m spfft_tpu.analysis``
(the same invocation ``make analyze`` runs) exits 0, the discovered
lock-acquisition hierarchy stays acyclic with the known edges, and
every Prometheus family the live exporters render is declared in
``obs/counters.py::METRIC_SPECS``.
"""

import json
import os
import subprocess
import sys

import pytest

from spfft_tpu.analysis import (baseline, counters_check, errors_check,
                                events_check, faults_check, knobs, locks,
                                run_analysis, spans, trace_check)
from spfft_tpu.analysis.core import index_sources

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE_ROOT = os.path.join(REPO_ROOT, "spfft_tpu")


def _errors(findings):
    return [f for f in findings if not f.waived and f.severity == "error"]


def _waived(findings):
    return [f for f in findings if f.waived]


# ---------------------------------------------------------------------------
# lock-discipline
# ---------------------------------------------------------------------------

LOCK_VIOLATION = '''
import threading

class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []  #: guarded by _lock

    def good(self):
        with self._lock:
            return len(self._items)

    def bad(self):
        return len(self._items)
'''

LOCK_CLEAN = LOCK_VIOLATION.replace(
    "    def bad(self):\n        return len(self._items)\n", "")


def test_lock_discipline_catches_unlocked_access():
    findings, _ = locks.check(index_sources({"box.py": LOCK_VIOLATION}))
    errs = _errors(findings)
    assert len(errs) == 1
    assert errs[0].checker == "lock-discipline"
    assert "_items" in errs[0].message and "Box.bad" in errs[0].message


def test_lock_discipline_clean_twin():
    findings, _ = locks.check(index_sources({"box.py": LOCK_CLEAN}))
    assert _errors(findings) == []


def test_lock_discipline_waiver_is_listed_not_failed():
    src = LOCK_VIOLATION.replace(
        "        return len(self._items)",
        "        return len(self._items)  "
        "# lock: waived(read-only diagnostic)")
    findings, _ = locks.check(index_sources({"box.py": src}))
    assert _errors(findings) == []
    waived = _waived(findings)
    assert len(waived) == 1 and waived[0].reason == \
        "read-only diagnostic"


HOLDS_VIOLATION = '''
import threading

class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []  #: guarded by _lock

    # lock: holds(_lock)
    def _drain_locked(self):
        self._items.clear()

    def good(self):
        with self._lock:
            self._drain_locked()

    def bad(self):
        self._drain_locked()
'''


def test_holds_annotation_checks_call_sites():
    findings, _ = locks.check(
        index_sources({"box.py": HOLDS_VIOLATION}))
    errs = _errors(findings)
    assert len(errs) == 1
    assert "_drain_locked" in errs[0].message
    assert "Box.bad" in errs[0].message


MODULE_LOCK = '''
import threading

_CACHE = None  #: guarded by _CACHE_LOCK
_CACHE_LOCK = threading.Lock()

def good():
    global _CACHE
    with _CACHE_LOCK:
        if _CACHE is None:
            _CACHE = {}
        return _CACHE

def bad():
    return _CACHE
'''


def test_module_level_guarded_global():
    findings, _ = locks.check(index_sources({"m.py": MODULE_LOCK}))
    errs = _errors(findings)
    assert len(errs) == 1 and "_CACHE" in errs[0].message


# ---------------------------------------------------------------------------
# lock-order
# ---------------------------------------------------------------------------

ORDER_CYCLE = '''
import threading

class A:
    def __init__(self, b: "B"):
        self._lock = threading.Lock()
        self.b = b

    def step(self):
        with self._lock:
            self.b.poke()

    def poke(self):
        with self._lock:
            pass

class B:
    def __init__(self, a: "A"):
        self._lock = threading.Lock()
        self.a = a

    def poke(self):
        with self._lock:
            pass

    def step(self):
        with self._lock:
            self.a.poke()
'''

ORDER_CLEAN = ORDER_CYCLE.replace(
    """    def step(self):
        with self._lock:
            self.a.poke()""",
    """    def step(self):
        self.a.poke()""")


def test_lock_order_cycle_detected():
    findings, extras = locks.check(
        index_sources({"ab.py": ORDER_CYCLE}))
    cycles = [f for f in _errors(findings)
              if f.checker == "lock-order"]
    assert cycles, "A->B and B->A lock nesting must report a cycle"
    assert "A._lock" in cycles[0].message
    assert "B._lock" in cycles[0].message


def test_lock_order_clean_when_consistent():
    findings, extras = locks.check(
        index_sources({"ab.py": ORDER_CLEAN}))
    assert [f for f in _errors(findings)
            if f.checker == "lock-order"] == []
    assert any("A._lock -> B._lock" in e
               for e in extras["lock_order_edges"])


# ---------------------------------------------------------------------------
# span-closure
# ---------------------------------------------------------------------------

SPAN_LEAK = '''
def leaky(tracer):
    sp = tracer.begin("stage")
    do_work()
    tracer.finish(sp)

def do_work():
    pass
'''

SPAN_PROTECTED = '''
def safe(tracer):
    sp = tracer.begin("stage")
    try:
        do_work()
    finally:
        tracer.finish(sp)

def do_work():
    pass
'''

SPAN_SWEEP = '''
def safe(rt):
    rt.begin("resolve")
    rt.finish("resolve")
    rt.close()
'''

SPAN_CLOSED_BY = '''
class Handle:
    def open_stage(self, tracer):
        # span: closed-by(Handle.settle)
        self.sp = tracer.begin("stage")

    def settle(self, tracer):
        tracer.finish(self.sp)
'''


def test_span_leak_detected():
    findings, _ = spans.check(index_sources({"t.py": SPAN_LEAK}))
    errs = _errors(findings)
    assert len(errs) == 1 and "no closure on all paths" in \
        errs[0].message


def test_span_try_finally_is_clean():
    findings, _ = spans.check(index_sources({"t.py": SPAN_PROTECTED}))
    assert _errors(findings) == []


def test_span_sweep_close_is_clean():
    findings, _ = spans.check(index_sources({"t.py": SPAN_SWEEP}))
    assert _errors(findings) == []


def test_span_closed_by_declaration_verified():
    findings, _ = spans.check(index_sources({"t.py": SPAN_CLOSED_BY}))
    assert _errors(findings) == []
    broken = SPAN_CLOSED_BY.replace("closed-by(Handle.settle)",
                                    "closed-by(Handle.missing)")
    findings, _ = spans.check(index_sources({"t.py": broken}))
    errs = _errors(findings)
    assert len(errs) == 1 and "no such function" in errs[0].message


def test_span_waiver():
    src = SPAN_LEAK.replace(
        '    sp = tracer.begin("stage")',
        '    # span: waived(closed by the caller in teardown)\n'
        '    sp = tracer.begin("stage")')
    findings, _ = spans.check(index_sources({"t.py": src}))
    assert _errors(findings) == []
    assert len(_waived(findings)) == 1


# ---------------------------------------------------------------------------
# counter-registry
# ---------------------------------------------------------------------------

COUNTERS_DECL = '''
METRIC_SPECS = {
    "spfft_demo_hits_total": ("counter", "Demo hits."),
    "spfft_demo_depth": ("gauge", "Demo depth."),
}
'''

COUNTERS_OK = '''
from .counters import METRIC_SPECS

def record(c):
    c.inc("spfft_demo_hits_total", 1)
    c.set("spfft_demo_depth", 3)
'''


def test_counter_registry_clean():
    findings, _ = counters_check.check(index_sources({
        "obs/counters.py": COUNTERS_DECL, "obs/rec.py": COUNTERS_OK}))
    assert _errors(findings) == []


def test_counter_registry_catches_undeclared_name():
    src = COUNTERS_OK.replace("spfft_demo_hits_total",
                              "spfft_demo_hitz_total")
    findings, _ = counters_check.check(index_sources({
        "obs/counters.py": COUNTERS_DECL, "obs/rec.py": src}))
    errs = _errors(findings)
    assert any("spfft_demo_hitz_total" in f.message
               and "not declared" in f.message for f in errs)


def test_counter_registry_catches_type_mismatch():
    src = COUNTERS_OK.replace('c.set("spfft_demo_depth", 3)',
                              'c.inc("spfft_demo_depth", 3)')
    findings, _ = counters_check.check(index_sources({
        "obs/counters.py": COUNTERS_DECL, "obs/rec.py": src}))
    errs = _errors(findings)
    assert any("declared a gauge" in f.message for f in errs)


def test_counter_registry_catches_never_recorded():
    src = COUNTERS_OK.replace('    c.set("spfft_demo_depth", 3)\n', "")
    findings, _ = counters_check.check(index_sources({
        "obs/counters.py": COUNTERS_DECL, "obs/rec.py": src}))
    errs = _errors(findings)
    assert any("never recorded" in f.message
               and "spfft_demo_depth" in f.message for f in errs)


def test_counter_registry_catches_duplicate_declaration():
    dup = COUNTERS_DECL.replace(
        '    "spfft_demo_depth": ("gauge", "Demo depth."),',
        '    "spfft_demo_depth": ("gauge", "Demo depth."),\n'
        '    "spfft_demo_hits_total": ("counter", "Again."),')
    findings, _ = counters_check.check(index_sources({
        "obs/counters.py": dup, "obs/rec.py": COUNTERS_OK}))
    errs = _errors(findings)
    assert any("more than once" in f.message for f in errs)


def test_counter_registry_fstring_family_surfaces():
    decl = COUNTERS_DECL.replace(
        '    "spfft_demo_depth": ("gauge", "Demo depth."),',
        '    "spfft_demo_depth": ("gauge", "Demo depth."),\n'
        '    "spfft_demo_plans_total": ("counter", "Rendered."),')
    exporter = '''
def render(b, stats):
    for key, value in stats.items():
        b.add(f"spfft_demo_{key}_total", "counter", "x", value)
'''
    findings, _ = counters_check.check(index_sources({
        "obs/counters.py": decl, "obs/rec.py": COUNTERS_OK,
        "obs/exporters.py": exporter}))
    assert _errors(findings) == []


# ---------------------------------------------------------------------------
# error-taxonomy
# ---------------------------------------------------------------------------

ERRORS_OK = '''
import enum

class ErrorCode(enum.IntEnum):
    UNKNOWN = 1
    BOOM = 2

class BaseErr(Exception):
    code = ErrorCode.UNKNOWN

class BoomError(BaseErr):
    code = ErrorCode.BOOM
'''

ERRORS_USER = '''
from .errors import BoomError

def fail():
    raise BoomError("boom")
'''


def test_error_taxonomy_clean():
    findings, _ = errors_check.check(index_sources({
        "errors.py": ERRORS_OK, "user.py": ERRORS_USER}))
    assert _errors(findings) == []


def test_error_taxonomy_catches_missing_code():
    src = ERRORS_OK.replace("class BaseErr(Exception):\n"
                            "    code = ErrorCode.UNKNOWN",
                            "class BaseErr(Exception):\n    pass")
    findings, _ = errors_check.check(index_sources({
        "errors.py": src, "user.py": ERRORS_USER}))
    errs = _errors(findings)
    assert any("resolves no error code" in f.message for f in errs)


def test_error_taxonomy_catches_unknown_code_member():
    src = ERRORS_OK.replace("code = ErrorCode.BOOM",
                            "code = ErrorCode.BOOMM")
    findings, _ = errors_check.check(index_sources({
        "errors.py": src, "user.py": ERRORS_USER}))
    errs = _errors(findings)
    assert any("unknown ErrorCode member" in f.message for f in errs)


def test_error_taxonomy_catches_unraised_class():
    src = ERRORS_OK + ('\nclass GhostError(BaseErr):\n'
                       '    code = ErrorCode.BOOM\n')
    findings, _ = errors_check.check(index_sources({
        "errors.py": src, "user.py": ERRORS_USER}))
    errs = _errors(findings)
    assert any("GhostError" in f.message and "never raised" in
               f.message for f in errs)
    waived = src.replace(
        "\nclass GhostError(BaseErr):",
        "\n# errors: waived(API parity)\nclass GhostError(BaseErr):")
    findings, _ = errors_check.check(index_sources({
        "errors.py": waived, "user.py": ERRORS_USER}))
    assert _errors(findings) == []
    assert len(_waived(findings)) == 1


def test_error_taxonomy_docs_requirement(tmp_path):
    doc = tmp_path / "taxonomy.md"
    doc.write_text("| `BaseErr` | base |\n| `BoomError` | boom |\n")
    findings, _ = errors_check.check(
        index_sources({"errors.py": ERRORS_OK,
                       "user.py": ERRORS_USER}),
        docs_paths=[str(doc)])
    assert _errors(findings) == []
    doc.write_text("| `BaseErr` | base |\n")
    findings, _ = errors_check.check(
        index_sources({"errors.py": ERRORS_OK,
                       "user.py": ERRORS_USER}),
        docs_paths=[str(doc)])
    errs = _errors(findings)
    assert any("BoomError" in f.message and "taxonomy" in f.message
               for f in errs)


# ---------------------------------------------------------------------------
# knob-registry
# ---------------------------------------------------------------------------

KNOBS_OK = '''
class KnobSpec:
    def __init__(self, name, default, lo, hi, kind, signal, doc):
        pass

KNOB_SPECS = {spec.name: spec for spec in (
    KnobSpec("window", 0.5, 0.0, 1.0, float, "sig", "doc"),
    KnobSpec("depth", 4, 1, 16, int, "sig", "doc"),
)}

PATH_SETTINGS = {"store_path": ""}
'''

KNOBS_DOC = """
| knob | default | bounds | env | signal |
|------|---------|--------|-----|--------|
| `window` | 0.5 | [0.0, 1.0] | — | sig |
| `depth` | 4 | [1, 16] | — | sig |
| `store_path` | "" | — | — | path |
"""


def test_knob_registry_clean():
    findings, _ = knobs.check(index_sources({"config.py": KNOBS_OK}),
                              doc_text=KNOBS_DOC)
    assert _errors(findings) == []


def test_knob_registry_catches_default_out_of_bounds():
    src = KNOBS_OK.replace('KnobSpec("depth", 4, 1, 16, int,',
                           'KnobSpec("depth", 64, 1, 16, int,')
    findings, _ = knobs.check(index_sources({"config.py": src}))
    errs = _errors(findings)
    assert any("outside declared bounds" in f.message for f in errs)


def test_knob_registry_catches_docs_drift():
    doc = KNOBS_DOC.replace("| `depth` | 4 | [1, 16] |",
                            "| `depth` | 8 | [1, 16] |")
    findings, _ = knobs.check(index_sources({"config.py": KNOBS_OK}),
                              doc_text=doc)
    errs = _errors(findings)
    assert any("documented default" in f.message for f in errs)
    doc = KNOBS_DOC.replace("\n| `depth` | 4 | [1, 16] | — | sig |",
                            "")
    findings, _ = knobs.check(index_sources({"config.py": KNOBS_OK}),
                              doc_text=doc)
    errs = _errors(findings)
    assert any("no row" in f.message and "'depth'" in f.message
               for f in errs)


def test_knob_registry_catches_stale_docs_row():
    doc = KNOBS_DOC + "| `dephts` | 4 | [1, 16] | — | sig |\n"
    findings, _ = knobs.check(index_sources({"config.py": KNOBS_OK}),
                              doc_text=doc)
    errs = _errors(findings)
    assert any("stale docs" in f.message for f in errs)


def test_knob_registry_catches_env_near_miss():
    user = '''
import os
CHUNKS = os.environ.get("SPFFT_TPU_DEPHT", "1")
'''
    findings, _ = knobs.check(
        index_sources({"config.py": KNOBS_OK, "user.py": user}))
    errs = _errors(findings)
    assert any("near-miss" in f.message for f in errs)


CONTROLLER_OK = '''
MANAGED_KNOBS = ("window", "depth")

class Controller:
    def _retune(self, out, knob, value, reason):
        pass

    def step(self, out):
        self._retune(out, "window", 0.25, "load spike")
        self._retune(out, "depth", 8, "queue deep")
'''


def test_knob_registry_controller_coverage_clean():
    findings, extras = knobs.check(index_sources(
        {"config.py": KNOBS_OK, "controller.py": CONTROLLER_OK}),
        doc_text=KNOBS_DOC)
    assert _errors(findings) == []
    assert extras["managed_knobs"] == 2


def test_knob_registry_catches_managed_knob_without_rule():
    src = CONTROLLER_OK.replace(
        'self._retune(out, "depth", 8, "queue deep")', "pass")
    findings, _ = knobs.check(index_sources(
        {"config.py": KNOBS_OK, "controller.py": src}))
    errs = _errors(findings)
    assert any("has no controller rule" in f.message
               and "'depth'" in f.message for f in errs)


def test_knob_registry_catches_unmanaged_knob_with_rule():
    src = CONTROLLER_OK.replace('MANAGED_KNOBS = ("window", "depth")',
                                'MANAGED_KNOBS = ("window",)')
    findings, _ = knobs.check(index_sources(
        {"config.py": KNOBS_OK, "controller.py": src}))
    errs = _errors(findings)
    assert any("not in MANAGED_KNOBS" in f.message
               and "'depth'" in f.message for f in errs)


def test_knob_registry_catches_managed_name_not_a_knob():
    src = CONTROLLER_OK.replace(
        'MANAGED_KNOBS = ("window", "depth")',
        'MANAGED_KNOBS = ("window", "depth", "ghost")')
    findings, _ = knobs.check(index_sources(
        {"config.py": KNOBS_OK, "controller.py": src}))
    errs = _errors(findings)
    assert any("not a declared knob" in f.message
               and "'ghost'" in f.message for f in errs)


# ---------------------------------------------------------------------------
# baseline lint
# ---------------------------------------------------------------------------

def test_baseline_unused_import():
    src = "import os\nimport sys\n\nprint(sys.argv)\n"
    findings, _ = baseline.check(index_sources({"m.py": src}))
    errs = _errors(findings)
    assert len(errs) == 1 and "'os'" in errs[0].message


def test_baseline_unused_import_noqa_and_init_exempt():
    src = "import os  # noqa\n"
    findings, _ = baseline.check(index_sources({"m.py": src}))
    assert _errors(findings) == []
    findings, _ = baseline.check(
        index_sources({"pkg/__init__.py": "import os\n"}))
    assert _errors(findings) == []


def test_baseline_undefined_name():
    src = "def f():\n    return undefined_thing\n"
    findings, _ = baseline.check(index_sources({"m.py": src}))
    errs = _errors(findings)
    assert len(errs) == 1 and "undefined_thing" in errs[0].message


def test_baseline_scoping_is_not_fooled():
    src = '''
import collections

def f(xs):
    acc = collections.deque()
    for x in xs:
        acc.append(x * scale(x))
    return [y for y in acc if y]

def scale(v):
    return v + GLOBAL

GLOBAL = 2
CONST = {k: v for k, v in zip("ab", [1, 2])}
'''
    findings, _ = baseline.check(index_sources({"m.py": src}))
    assert _errors(findings) == []


# ---------------------------------------------------------------------------
# the real package (meta-tests)
# ---------------------------------------------------------------------------

def test_real_package_analysis_is_clean():
    """``python -m spfft_tpu.analysis`` — the exact command ``make
    analyze`` runs — exits 0 on the repo: zero unwaived findings."""
    report = run_analysis(root=PACKAGE_ROOT, docs_root=REPO_ROOT)
    assert report.ok(), report.text()
    # every waiver carries a reason (the report lists them)
    for f in report.waivers:
        assert f.reason, f


def test_real_package_lock_hierarchy_acyclic_with_known_edges():
    """Regression pin for the discovered lock hierarchy: the executor's
    cv and pool lock are OUTER locks (tracer/config are leaves), the
    lazy global-config boot nests config/obs locks under its module
    lock, and the graph stays acyclic. A future edge that inverts one
    of these orders will fail run_analysis with a lock-order cycle."""
    report = run_analysis(root=PACKAGE_ROOT, docs_root=REPO_ROOT,
                          checkers=["lock-discipline"])
    assert report.ok(), report.text()
    edges = report.extras["lock_order_edges"]
    for expected in (
            "ServeExecutor._cv -> Tracer._lock",
            "ServeExecutor._cv -> ServeConfig._lock",
            "ServeExecutor._pool_lock -> ServeConfig._lock",
            "config.py::_GLOBAL_LOCK -> ServeConfig._lock"):
        assert any(expected in e for e in edges), (expected, edges)


def test_analysis_cli_smoke(tmp_path):
    """The make-analyze twin: the CLI exits 0 and writes a valid JSON
    report with the checker list and waiver inventory."""
    out = tmp_path / "report.json"
    proc = subprocess.run(
        [sys.executable, "-m", "spfft_tpu.analysis", "--json",
         str(out), "-q"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(out.read_text())
    assert payload["ok"] is True
    assert payload["summary"]["errors"] == 0
    assert set(payload["checkers"]) == {
        "lock-discipline", "span-closure", "counter-registry",
        "error-taxonomy", "knob-registry", "fault-sites",
        "event-registry", "trace-context", "baseline-lint"}
    assert payload["waivers"], "the report must list the waivers"


def test_cli_baseline_only_and_list():
    proc = subprocess.run(
        [sys.executable, "-m", "spfft_tpu.analysis", "--list"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0
    assert "lock-discipline" in proc.stdout
    proc = subprocess.run(
        [sys.executable, "-m", "spfft_tpu.analysis",
         "--baseline-only", "-q"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_rendered_prometheus_families_all_declared():
    """Runtime complement of the static counter check: everything
    prometheus_text actually renders — obs counters after recorder
    calls, a fresh ServeMetrics snapshot, registry stats —
    is a declared METRIC_SPECS family."""
    from spfft_tpu import obs
    from spfft_tpu.obs.counters import METRIC_SPECS, Counters
    from spfft_tpu.serve.metrics import ServeMetrics

    counters = Counters()
    counters.inc("spfft_compile_events_total", 1, kind="test")
    counters.set("spfft_control_knob", 1.0, knob="max_batch")
    metrics = ServeMetrics()
    metrics.record_batch(4, fused=True, padded_rows=1)
    metrics.record_request_done(0.01)
    registry_stats = {
        "plans": 1, "bytes_in_use": 10, "max_bytes": 100,
        "max_plans": 4, "hits": 1, "misses": 1, "fast_hits": 0,
        "evictions": 0, "builds": 1, "build_failures": 0,
        "sig_memo_entries": 1, "sig_memo_bytes": 8, "hit_rate": 0.5,
        "store_hits": 0, "store_misses": 0, "store_spills": 0,
        "store_attached": False}
    text = obs.prometheus_text(metrics=metrics,
                               registry=registry_stats,
                               counters=counters)
    series = obs.parse_prometheus_text(text)
    rendered = {name for name, _labels in series}
    undeclared = {n for n in rendered if n.startswith("spfft_")} \
        - set(METRIC_SPECS)
    assert not undeclared, undeclared


def test_counters_enforce_declared_types_at_runtime():
    from spfft_tpu.obs.counters import Counters
    c = Counters()
    with pytest.raises(ValueError):
        c.inc("spfft_control_knob", 1.0, knob="max_batch")  # a gauge
    c.set("spfft_control_knob", 2.0, knob="max_batch")
    # declared help is the default
    snap = c.snapshot()
    assert snap["spfft_control_knob"]["help"] == \
        "Current value of each control-plane knob."


# ---------------------------------------------------------------------------
# fault-sites
# ---------------------------------------------------------------------------

FAULT_SITES_DECL = '''
SITES = (
    "store.spill",
    "kernel.launch",
)
'''

FAULT_SITES_OK = '''
from . import faults as _faults

def spill():
    _faults.check_site("store.spill")

def launch():
    _faults.check_site("kernel.launch")
'''


def test_fault_sites_clean():
    findings, extras = faults_check.check(index_sources({
        "faults.py": FAULT_SITES_DECL, "store.py": FAULT_SITES_OK}))
    assert _errors(findings) == []
    assert extras == {"declared_sites": 2, "checked_sites": 2}


def test_fault_sites_catches_undeclared_reference():
    src = FAULT_SITES_OK.replace('check_site("store.spill")',
                                 'check_site("store.spil")')
    findings, _ = faults_check.check(index_sources({
        "faults.py": FAULT_SITES_DECL, "store.py": src}))
    errs = _errors(findings)
    assert any("store.spil" in f.message and "not declared" in f.message
               for f in errs)
    # the typo also orphans the declared site
    assert any("store.spill" in f.message
               and "dead coverage claim" in f.message for f in errs)


def test_fault_sites_catches_never_checked_declaration():
    src = FAULT_SITES_OK.replace(
        'def launch():\n    _faults.check_site("kernel.launch")\n', "")
    findings, _ = faults_check.check(index_sources({
        "faults.py": FAULT_SITES_DECL, "store.py": src}))
    errs = _errors(findings)
    assert any("kernel.launch" in f.message
               and "dead coverage claim" in f.message for f in errs)


def test_fault_sites_catches_duplicate_declaration():
    dup = FAULT_SITES_DECL.replace('    "store.spill",',
                                   '    "store.spill",\n'
                                   '    "store.spill",')
    findings, _ = faults_check.check(index_sources({
        "faults.py": dup, "store.py": FAULT_SITES_OK}))
    errs = _errors(findings)
    assert any("more than once" in f.message for f in errs)


def test_fault_sites_waiver_is_listed_not_failed():
    src = FAULT_SITES_OK.replace(
        '_faults.check_site("store.spill")',
        '_faults.check_site("store.probe")'
        '  # faults: waived(staging: declared next round)')
    findings, _ = faults_check.check(index_sources({
        "faults.py": FAULT_SITES_DECL, "store.py": src}))
    waived = [f for f in findings if f.waived]
    assert any("store.probe" in f.message for f in waived)
    assert not [f for f in _errors(findings)
                if "store.probe" in f.message]


def test_fault_sites_loose_check_calls_need_dots():
    """Unrelated .check("x") calls (no dot, not a declared site) are
    NOT fault-seam references; dotted literals and declared names
    are."""
    src = '''
def other(validator, seam):
    validator.check("shape")          # unrelated: ignored
    seam.check("exchange.pack")       # dotted: a seam reference
    seam.check("kernel.launch")       # declared: a seam reference
'''
    findings, extras = faults_check.check(index_sources({
        "faults.py": FAULT_SITES_DECL, "ops.py": src}))
    errs = _errors(findings)
    assert not any("'shape'" in f.message for f in errs)
    assert any("exchange.pack" in f.message and "not declared"
               in f.message for f in errs)
    assert extras["checked_sites"] == 2


def test_fault_sites_missing_registry_is_an_error():
    findings, extras = faults_check.check(index_sources({
        "store.py": FAULT_SITES_OK}))
    errs = _errors(findings)
    assert any("no SITES declaration" in f.message for f in errs)
    assert extras == {}


def test_fault_sites_grammar_and_non_literal_entries():
    bad = '''
PREFIX = "store"
SITES = (
    "Store.Spill",
    PREFIX + ".load",
)
'''
    findings, _ = faults_check.check(index_sources({
        "faults.py": bad, "store.py": "x = 1\n"}))
    errs = _errors(findings)
    assert any("site grammar" in f.message for f in errs)
    assert any("non-literal entry" in f.message for f in errs)


# ---------------------------------------------------------------------------
# event-registry
# ---------------------------------------------------------------------------

EVENTS_DECL = '''
EVENT_SPECS = {
    "demo.start": ("demo", "Run started.", ("run",)),
    "demo.stop": ("demo", "Run stopped.", ("run", "outcome")),
}
'''

EVENTS_OK = '''
def emit(obs):
    obs.record_event("demo.start", run=1)
    obs.record_event("demo.stop", run=1, outcome="ok")
'''


def test_event_registry_clean():
    findings, extras = events_check.check(index_sources({
        "obs/recorder.py": EVENTS_DECL, "serve/x.py": EVENTS_OK}))
    assert _errors(findings) == []
    assert extras == {"declared_event_kinds": 2,
                      "event_emission_sites": 2}


def test_event_registry_catches_undeclared_kind():
    src = EVENTS_OK.replace('"demo.start"', '"demo.stat"')
    findings, _ = events_check.check(index_sources({
        "obs/recorder.py": EVENTS_DECL, "serve/x.py": src}))
    errs = _errors(findings)
    assert any("demo.stat" in f.message and "not declared" in f.message
               for f in errs)
    # the typo also orphans the declared kind
    assert any("demo.start" in f.message
               and "never emitted" in f.message for f in errs)


def test_event_registry_catches_never_emitted_kind():
    src = EVENTS_OK.replace(
        '    obs.record_event("demo.stop", run=1, outcome="ok")\n', "")
    findings, _ = events_check.check(index_sources({
        "obs/recorder.py": EVENTS_DECL, "serve/x.py": src}))
    errs = _errors(findings)
    assert any("demo.stop" in f.message and "never emitted" in f.message
               for f in errs)


def test_event_registry_catches_undeclared_attr():
    src = EVENTS_OK.replace("outcome=\"ok\"", "result=\"ok\"")
    findings, _ = events_check.check(index_sources({
        "obs/recorder.py": EVENTS_DECL, "serve/x.py": src}))
    errs = _errors(findings)
    assert any("'result'" in f.message
               and "undeclared attr" in f.message for f in errs)


def test_event_registry_catches_duplicate_declaration():
    dup = EVENTS_DECL.replace(
        '    "demo.stop": ("demo", "Run stopped.", ("run", "outcome")),',
        '    "demo.stop": ("demo", "Run stopped.", ("run", "outcome")),\n'
        '    "demo.start": ("demo", "Again.", ("run",)),')
    findings, _ = events_check.check(index_sources({
        "obs/recorder.py": dup, "serve/x.py": EVENTS_OK}))
    errs = _errors(findings)
    assert any("more than once" in f.message for f in errs)


def test_event_registry_catches_malformed_spec_and_kind_grammar():
    bad = '''
EVENT_SPECS = {
    "Demo.Start": ("demo", "Bad case.", ("run",)),
    "demo.loose": ("demo", "No attrs tuple."),
}

def emit(obs):
    obs.record_event("Demo.Start", run=1)
    obs.record_event("demo.loose")
'''
    findings, _ = events_check.check(index_sources({
        "obs/recorder.py": bad}))
    errs = _errors(findings)
    assert any("dotted lowercase" in f.message for f in errs)
    assert any("demo.loose" in f.message
               and "literal (category, help, (attr, ...))" in f.message
               for f in errs)


def test_event_registry_positional_attrs_are_an_error():
    src = EVENTS_OK.replace('obs.record_event("demo.start", run=1)',
                            'obs.record_event("demo.start", 1)')
    findings, _ = events_check.check(index_sources({
        "obs/recorder.py": EVENTS_DECL, "serve/x.py": src}))
    errs = _errors(findings)
    assert any("one positional arg" in f.message for f in errs)


def test_event_registry_waiver_is_listed_not_failed():
    src = EVENTS_OK.replace(
        'obs.record_event("demo.start", run=1)',
        'obs.record_event("demo.probe")'
        '  # events: waived(staging: declared next round)')
    findings, _ = events_check.check(index_sources({
        "obs/recorder.py": EVENTS_DECL, "serve/x.py": src}))
    waived = [f for f in findings if f.waived]
    assert any("demo.probe" in f.message for f in waived)
    assert not [f for f in _errors(findings)
                if "demo.probe" in f.message]


def test_event_registry_variable_kind_is_a_warning():
    src = '''
def emit(obs, kind):
    obs.record_event(kind, run=1)
    obs.record_event("demo.start", run=1)
    obs.record_event("demo.stop", run=1, outcome="ok")
'''
    findings, _ = events_check.check(index_sources({
        "obs/recorder.py": EVENTS_DECL, "serve/x.py": src}))
    assert _errors(findings) == []
    warns = [f for f in findings if f.severity == "warning"]
    assert any("non-literal kind" in f.message for f in warns)


def test_event_registry_missing_registry_is_an_error():
    findings, extras = events_check.check(index_sources({
        "serve/x.py": EVENTS_OK}))
    errs = _errors(findings)
    assert any("no EVENT_SPECS declaration" in f.message for f in errs)
    assert extras == {}


# ---------------------------------------------------------------------------
# trace-context
# ---------------------------------------------------------------------------

TRACE_CLEAN = '''
class Lane:
    # trace: boundary(ctx)
    def rpc_submit(self, values, ctx=None):
        return self.executor.submit(values, trace_ctx=ctx)


class Frontend:
    def route(self, lane, values, ctx):
        return lane.rpc_submit(values, ctx=ctx)
'''

TRACE_DROPPED_AT_CALL = '''
class Lane:
    # trace: boundary(ctx)
    def rpc_submit(self, values, ctx=None):
        return self.executor.submit(values, trace_ctx=ctx)


class Frontend:
    def route(self, lane, values, ctx):
        return lane.rpc_submit(values)
'''

TRACE_NEVER_FORWARDED = '''
class Lane:
    # trace: boundary(ctx)
    def rpc_submit(self, values, ctx=None):
        return self.executor.submit(values)


class Frontend:
    def route(self, lane, values, ctx):
        return lane.rpc_submit(values, ctx=ctx)
'''

TRACE_CONTEXTLESS_SPAN = '''
class Lane:
    # trace: boundary(ctx)
    def rpc_submit(self, tracer, values, ctx=None):
        span = tracer.begin("lane.request")
        try:
            return self.executor.submit(values, trace_ctx=ctx)
        finally:
            tracer.finish(span)


class Frontend:
    def route(self, lane, tracer, values, ctx):
        return lane.rpc_submit(tracer, values, ctx=ctx)
'''


def test_trace_context_clean():
    findings, extras = trace_check.check(
        index_sources({"cluster.py": TRACE_CLEAN}))
    assert _errors(findings) == []
    assert extras["trace_boundaries"] == 1
    assert extras["boundary_calls_checked"] == 1


def test_trace_context_catches_call_dropping_context():
    findings, _ = trace_check.check(
        index_sources({"cluster.py": TRACE_DROPPED_AT_CALL}))
    errs = _errors(findings)
    assert len(errs) == 1
    assert "does not bind its context" in errs[0].message
    assert errs[0].line == 10


def test_trace_context_catches_boundary_never_forwarding():
    findings, _ = trace_check.check(
        index_sources({"cluster.py": TRACE_NEVER_FORWARDED}))
    errs = _errors(findings)
    assert len(errs) == 1
    assert "never forwards its context" in errs[0].message


def test_trace_context_catches_contextless_span_open():
    findings, _ = trace_check.check(
        index_sources({"cluster.py": TRACE_CONTEXTLESS_SPAN}))
    errs = _errors(findings)
    assert len(errs) == 1
    assert "without its context" in errs[0].message
    assert "new trace id" in errs[0].message


def test_trace_context_span_open_with_context_is_clean():
    src = TRACE_CONTEXTLESS_SPAN.replace(
        'tracer.begin("lane.request")',
        'tracer.begin("lane.request", parent=ctx)')
    findings, _ = trace_check.check(
        index_sources({"cluster.py": src}))
    assert _errors(findings) == []


def test_trace_context_positional_bind_counts():
    src = TRACE_DROPPED_AT_CALL.replace(
        "lane.rpc_submit(values)", "lane.rpc_submit(values, ctx)")
    findings, _ = trace_check.check(
        index_sources({"cluster.py": src}))
    assert _errors(findings) == []


def test_trace_context_kwargs_forwarding_counts():
    src = TRACE_DROPPED_AT_CALL.replace(
        "lane.rpc_submit(values)", "lane.rpc_submit(values, **kw)")
    findings, _ = trace_check.check(
        index_sources({"cluster.py": src}))
    assert _errors(findings) == []


def test_trace_context_waiver_is_listed_not_failed():
    src = TRACE_DROPPED_AT_CALL.replace(
        "lane.rpc_submit(values)",
        "lane.rpc_submit(values)  "
        "# trace: waived(fire-and-forget maintenance ping)")
    findings, _ = trace_check.check(
        index_sources({"cluster.py": src}))
    assert _errors(findings) == []
    waived = _waived(findings)
    assert len(waived) == 1
    assert "maintenance ping" in waived[0].reason


def test_trace_context_bad_param_name_is_an_error():
    src = TRACE_CLEAN.replace("boundary(ctx)", "boundary(missing)")
    findings, _ = trace_check.check(
        index_sources({"cluster.py": src}))
    errs = _errors(findings)
    assert any("not a parameter" in f.message for f in errs)


def test_trace_context_real_package_has_boundaries():
    """Clean-repo meta-test: the checker runs green over the real tree
    AND actually has something to check — the pod frontend's submit
    RPC is annotated and every call site binds the context."""
    report = run_analysis(root=PACKAGE_ROOT, docs_root=REPO_ROOT,
                          checkers=["trace-context"])
    assert report.ok(), report.text()
    assert report.extras["trace_boundaries"] >= 1
    assert report.extras["boundary_calls_checked"] >= 1

"""Local (single-device) C2C and R2C transforms vs the dense FFT oracle.

Mirrors reference tests/local_tests/test_local_transform.cpp and the oracle
strategy of tests/test_util/test_transform.hpp: random sparse sets, dense
numpy FFT comparison, the reference dimension matrix (primes, evens,
degenerate 1s), centered and non-centered indexing, and the repeated-backward
check for missing buffer zeroing (test_transform.hpp:129-131)."""

import numpy as np
import pytest

from spfft_tpu import Scaling, TransformType, make_local_plan
from spfft_tpu.utils import as_complex_np

from test_util import (center_triplets, dense_backward, dense_cube_from_values,
                       dense_forward, hermitian_triplets, random_sparse_triplets,
                       random_values, sample_cube, tolerance_for)

DIMS = [
    (1, 1, 1),
    (2, 2, 2),
    (11, 11, 11),
    (12, 12, 12),
    (13, 13, 13),
    (2, 11, 13),
    (13, 12, 1),
    (1, 12, 13),
    (100, 100, 100),
]


@pytest.mark.parametrize("dims", DIMS)
@pytest.mark.parametrize("centered", [False, True])
@pytest.mark.parametrize("precision", ["double", "single"])
def test_c2c_backward_forward(dims, centered, precision):
    rng = np.random.default_rng(42)
    triplets = random_sparse_triplets(rng, dims)
    if centered:
        triplets = center_triplets(triplets, dims)
    values = random_values(rng, len(triplets))

    cube = dense_cube_from_values(triplets, values, dims)
    space_oracle = dense_backward(cube)

    plan = make_local_plan(TransformType.C2C, *dims, triplets,
                           precision=precision)
    tol = tolerance_for(precision, space_oracle)

    # backward twice: catches missing buffer zeroing (test_transform.hpp:129-146)
    for _ in range(2):
        space = as_complex_np(np.asarray(plan.backward(values)))
        assert space.shape == (dims[2], dims[1], dims[0])
        np.testing.assert_allclose(space, space_oracle, atol=tol, rtol=0)

    # forward from the oracle space field, compare at sparse positions
    # (test_transform.hpp:151-219)
    freq_oracle = dense_forward(space_oracle)
    expected = sample_cube(freq_oracle, triplets, dims)
    tol_f = tolerance_for(precision, expected)
    got = as_complex_np(np.asarray(plan.forward(space_oracle)))
    np.testing.assert_allclose(got, expected, atol=tol_f, rtol=0)

    # FULL scaling divides by the grid size (details.rst "Normalization")
    got_scaled = as_complex_np(
        np.asarray(plan.forward(space_oracle, Scaling.FULL)))
    np.testing.assert_allclose(got_scaled,
                               expected / (dims[0] * dims[1] * dims[2]),
                               atol=tol_f, rtol=0)


@pytest.mark.parametrize("dims", [(2, 2, 2), (11, 12, 13), (12, 11, 13),
                                  (13, 11, 12), (32, 32, 32), (1, 5, 6)])
@pytest.mark.parametrize("precision", ["double", "single"])
def test_r2c_roundtrip(dims, precision):
    """R2C with reduced hermitian provision: redundant x=0 columns omitted,
    some provided at -y, (0,0) stick half-omitted
    (reference: test_transform.hpp:221-276)."""
    rng = np.random.default_rng(42)
    nx, ny, nz = dims
    space = rng.uniform(-1, 1, (nz, ny, nx))
    freq = dense_forward(space)

    triplets = hermitian_triplets(rng, dims)
    values = sample_cube(freq, triplets, dims)

    plan = make_local_plan(TransformType.R2C, *dims, triplets,
                           precision=precision)
    tol = tolerance_for(precision, space * space.size)

    for _ in range(2):
        got = np.asarray(plan.backward(values))
        assert got.shape == space.shape
        np.testing.assert_allclose(got, space * space.size, atol=tol, rtol=0)

    got_freq = as_complex_np(np.asarray(plan.forward(space)))
    tol_f = tolerance_for(precision, values)
    np.testing.assert_allclose(got_freq, values, atol=tol_f, rtol=0)


def test_r2c_centered_indexing():
    """Centered (negative) indices with hermitian symmetry."""
    rng = np.random.default_rng(7)
    dims = (8, 9, 10)
    space = rng.uniform(-1, 1, (dims[2], dims[1], dims[0]))
    freq = dense_forward(space)
    triplets = center_triplets(hermitian_triplets(rng, dims), dims)
    values = sample_cube(freq, triplets, dims)
    plan = make_local_plan(TransformType.R2C, *dims, triplets,
                           precision="double")
    got = np.asarray(plan.backward(values))
    np.testing.assert_allclose(got, space * space.size, atol=1e-8, rtol=0)


def test_empty_value_set():
    """Zero sparse values is legal (empty shards exist in the distributed
    case, reference execution_host.cpp:167-179) and yields a zero field."""
    plan = make_local_plan(TransformType.C2C, 4, 4, 4,
                           np.empty((0, 3), np.int32), precision="double")
    space = as_complex_np(np.asarray(plan.backward(np.empty(0, np.complex128))))
    assert space.shape == (4, 4, 4)
    np.testing.assert_array_equal(space, 0)


def test_forward_backward_identity_with_scaling():
    """forward(FULL) then backward is the identity (details.rst
    "Normalization")."""
    rng = np.random.default_rng(3)
    dims = (6, 5, 4)
    triplets = random_sparse_triplets(rng, dims)
    values = random_values(rng, len(triplets))
    plan = make_local_plan(TransformType.C2C, *dims, triplets,
                           precision="double")
    space = plan.backward(values)
    values2 = as_complex_np(np.asarray(plan.forward(space, Scaling.FULL)))
    back = as_complex_np(np.asarray(plan.backward(values2)))
    ref = as_complex_np(np.asarray(space))
    np.testing.assert_allclose(back, ref, atol=1e-9 * max(1, np.abs(ref).max()))


def test_input_validation():
    from spfft_tpu import InvalidParameterError
    plan = make_local_plan(TransformType.C2C, 4, 4, 4,
                           np.array([[0, 0, 0]]), precision="double")
    with pytest.raises(InvalidParameterError):
        plan.backward(np.zeros(5, np.complex128))
    with pytest.raises(InvalidParameterError):
        plan.forward(np.zeros((3, 3, 3), np.complex128))


def test_split_x_path_vs_dense():
    """Narrow-x sparse sets take the split xy path (reference: y-FFT over
    non-empty x-rows only, execution_host.cpp:139-145); must agree with
    the dense path and the oracle exactly."""
    rng = np.random.default_rng(77)
    dims = (32, 16, 12)
    # sticks only at x in [3, 9): width 6 of 32 -> split active
    xs = rng.integers(3, 9, 60)
    ys = rng.integers(0, dims[1], 60)
    zs = rng.integers(0, dims[2], 60)
    triplets = np.unique(np.stack([xs, ys, zs], 1), axis=0)
    values = random_values(rng, len(triplets))

    plan = make_local_plan(TransformType.C2C, *dims, triplets,
                           precision="double")
    assert plan._split_x is not None and plan._split_x[0] == 3

    cube = dense_cube_from_values(triplets, values, dims)
    space_oracle = dense_backward(cube)
    space = as_complex_np(np.asarray(plan.backward(values)))
    np.testing.assert_allclose(space, space_oracle,
                               atol=tolerance_for("double", space_oracle),
                               rtol=0)
    freq_oracle = dense_forward(space_oracle)
    expected = sample_cube(freq_oracle, triplets, dims)
    got = as_complex_np(np.asarray(plan.forward(space_oracle)))
    np.testing.assert_allclose(got, expected,
                               atol=tolerance_for("double", expected),
                               rtol=0)


def test_split_x_wide_disabled_wrapped_enabled():
    rng = np.random.default_rng(78)
    dims = (16, 16, 16)
    wide = random_sparse_triplets(rng, dims)  # spans most of x
    plan = make_local_plan(TransformType.C2C, *dims, wide,
                           precision="double")
    assert plan._split_x is None
    # centered set wraps x storage to both ends -> cyclic (wrapped) window
    # [14, 16) U [0, 3), width 5 of 16
    sphere = center_triplets(
        np.array([[x, 0, 0] for x in range(0, 3)]), dims)
    sphere = np.concatenate([sphere, [[-2, 0, 1], [-1, 0, 1]]])
    plan2 = make_local_plan(TransformType.C2C, *dims, sphere,
                            precision="double")
    assert plan2._split_x == (14, 5)


def test_split_x_wrapped_vs_oracle():
    """The wrapped (two-slice) split window — a centered plane-wave sphere
    on a 2x-cutoff grid, the flagship workload shape — agrees with the
    dense oracle in both directions (reference: execution_host.cpp:139-145
    runs sparse-y in ALL paths, wrapped ranges included)."""
    from spfft_tpu.utils.workloads import spherical_cutoff_triplets
    dims = (24, 24, 24)
    rng = np.random.default_rng(79)
    triplets = spherical_cutoff_triplets(24, radius=6)  # x in [-6, 6]
    values = random_values(rng, len(triplets))
    plan = make_local_plan(TransformType.C2C, *dims, triplets,
                           precision="double")
    assert plan._split_x == (18, 13), plan._split_x  # wrapped window
    cube = dense_cube_from_values(triplets, values, dims)
    space_oracle = dense_backward(cube)
    space = as_complex_np(np.asarray(plan.backward(values)))
    np.testing.assert_allclose(space, space_oracle,
                               atol=tolerance_for("double", space_oracle),
                               rtol=0)
    freq_oracle = dense_forward(space_oracle)
    expected = sample_cube(freq_oracle, triplets, dims)
    got = as_complex_np(np.asarray(plan.forward(space_oracle)))
    np.testing.assert_allclose(got, expected,
                               atol=tolerance_for("double", expected),
                               rtol=0)


def test_split_x_r2c_vs_oracle():
    """R2C split window (y-FFT over occupied x of the half spectrum) with
    plane symmetry on the x=0 sub-column."""
    dims = (24, 20, 18)
    rng = np.random.default_rng(80)
    space_field = rng.standard_normal((dims[2], dims[1], dims[0]))
    freq = dense_forward(space_field.astype(np.complex128))
    # occupied x of the half spectrum: [0, 5) of 13 -> split active
    triplets = np.array([[x, y, z] for x in range(5)
                         for y in range(dims[1]) for z in range(dims[2])])
    plan = make_local_plan(TransformType.R2C, *dims, triplets,
                           precision="double")
    assert plan._split_x == (0, 5), plan._split_x
    # band-limit the field to the hermitian closure of the triplet set so
    # the sparse samples fully determine a real space field
    nx, ny, nz = dims
    mask = np.zeros((nz, ny, nx), bool)
    for x, y, z in triplets:
        mask[z, y, x] = True
        mask[(-z) % nz, (-y) % ny, (-x) % nx] = True
    freq_bl = freq * mask
    space_bl = np.fft.ifftn(freq_bl)
    assert np.abs(space_bl.imag).max() < 1e-12
    space_bl = space_bl.real
    values = sample_cube(freq_bl, triplets, dims)
    got = np.asarray(plan.backward(values))
    oracle = space_bl * space_bl.size
    np.testing.assert_allclose(got, oracle,
                               atol=tolerance_for("double", oracle), rtol=0)
    fwd = as_complex_np(np.asarray(plan.forward(space_bl)))
    np.testing.assert_allclose(fwd, values,
                               atol=tolerance_for("double", values),
                               rtol=0)


def test_pair_values_io_round_trip(monkeypatch):
    """Large plans use a planar-pair (2, N) device boundary for value
    arrays (the (N,2) shape can be assigned a 64x-padded tiled layout on
    TPU; flat strided interleaves lower too slow). Force the threshold
    down and check the pair plan matches the rows plan on every public
    entry."""
    import jax.numpy as jnp
    from spfft_tpu import Scaling, TransformType, make_local_plan
    from spfft_tpu import plan as plan_mod

    rng = np.random.default_rng(61)
    dims = (10, 9, 8)
    triplets = random_sparse_triplets(rng, dims)
    v = random_values(rng, len(triplets))
    ref = make_local_plan(TransformType.C2C, *dims, triplets,
                          precision="double")
    assert not ref.pair_values_io
    monkeypatch.setattr(plan_mod, "PAIR_IO_THRESHOLD", 1)
    pplan = make_local_plan(TransformType.C2C, *dims, triplets,
                            precision="double")
    assert pplan.pair_values_io
    # backward from complex input
    np.testing.assert_allclose(np.asarray(pplan.backward(v)),
                               np.asarray(ref.backward(v)),
                               atol=1e-12, rtol=0)
    # forward returns the PAIR layout; transpose equals the reference rows
    space = ref.backward(v)
    out_pair = np.asarray(pplan.forward(space, Scaling.FULL))
    out_rows = np.asarray(ref.forward(space, Scaling.FULL))
    assert out_pair.shape == (2, len(triplets))
    np.testing.assert_allclose(out_pair.T, out_rows, atol=1e-12, rtol=0)
    # fused pair accepts complex and pair-layout device arrays
    pair = np.asarray(pplan.apply_pointwise(v, scaling=Scaling.FULL))
    v_pair = np.stack([v.real, v.imag], axis=0)
    np.testing.assert_allclose(pair, v_pair, atol=1e-12, rtol=0)
    pair2 = np.asarray(pplan.apply_pointwise(jnp.asarray(v_pair),
                                             scaling=Scaling.FULL))
    np.testing.assert_allclose(pair2, v_pair, atol=1e-12, rtol=0)
    # batched
    batch = [v, np.roll(v, 1)]
    got_b = np.asarray(pplan.backward_batched(batch))
    ref_b = np.asarray(ref.backward_batched(batch))
    np.testing.assert_allclose(got_b, ref_b, atol=1e-12, rtol=0)
    fwd_b = np.asarray(pplan.forward_batched(
        [np.asarray(space), np.asarray(space)], Scaling.FULL))
    assert fwd_b.shape == (2, 2, len(triplets))


def test_irfft_last_collapse_semantics():
    """The rank-collapse irfft wrapper (the TPU C2R corruption workaround,
    docs/precision.md) is semantically identical to the direct op for
    every rank it can see."""
    import jax.numpy as jnp
    from spfft_tpu.ops.stages import _irfft_last

    rng = np.random.default_rng(50)
    for shape in ((6, 10), (3, 5, 10), (2, 3, 4, 10)):
        field = rng.standard_normal(shape)
        G = jnp.asarray(np.fft.rfft(field, axis=-1))
        got = np.asarray(_irfft_last(G, shape[-1]))
        np.testing.assert_allclose(got, field, atol=1e-12)


def test_donate_inputs_correctness_and_consumption():
    """donate_inputs=True: identical results, and the caller's device
    array is consumed by the donating fused round trip."""
    import jax
    from spfft_tpu import Scaling

    dims = (8, 8, 8)
    rng = np.random.default_rng(51)
    triplets = random_sparse_triplets(rng, dims)
    values = random_values(rng, len(triplets))
    plain = make_local_plan(TransformType.C2C, *dims, triplets,
                            precision="double")
    donating = make_local_plan(TransformType.C2C, *dims, triplets,
                               precision="double", donate_inputs=True)
    want = np.asarray(plain.apply_pointwise(values, scaling=Scaling.FULL))
    vi = jax.device_put(donating._coerce_values(values))
    got = np.asarray(donating.apply_pointwise(vi, scaling=Scaling.FULL))
    np.testing.assert_allclose(got, want, atol=1e-12)
    assert vi.is_deleted()  # the donated buffer was consumed
    # backward/forward do NOT donate (shapes differ; no alias possible)
    vi2 = jax.device_put(donating._coerce_values(values))
    donating.backward(vi2)
    assert not vi2.is_deleted()


def test_precision_contract_failure_path():
    """max_rel_error demands an accuracy contract at construction: single
    precision cannot predict under 1e-9, so the typed failure fires;
    double can, so it passes (VERDICT r3 item 2; the reference's implicit
    contract is f64-everywhere, test_check_values.hpp:46-50)."""
    from spfft_tpu import (PrecisionContractError, make_local_plan,
                           predicted_rel_error)
    tri = np.array([[0, 0, 0], [1, 2, 3]], np.int32)
    with pytest.raises(PrecisionContractError):
        make_local_plan(TransformType.C2C, 16, 16, 16, tri,
                        precision="single", max_rel_error=1e-9)
    # the single-precision contract at the reference bar holds through 512
    for n in (64, 256, 512):
        assert predicted_rel_error("single", n) < 1e-6
    plan = make_local_plan(TransformType.C2C, 16, 16, 16, tri,
                           precision="double", max_rel_error=1e-9)
    assert plan.precision == "double"
    # the model envelope sits above every measured matrix point
    # (round-4 matmul-DFT matrix, docs/precision.md)
    for n, measured in ((32, 1.4e-7), (64, 1.5e-7), (128, 1.7e-7),
                        (256, 1.8e-7), (512, 1.94e-7)):
        assert predicted_rel_error("single", n) > measured

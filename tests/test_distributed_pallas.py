"""Distributed Pallas compression path (interpret mode on the CPU mesh).

The distributed plan can route its compression stages through the same
monotone-gather kernel the local plan uses, with per-shard tables padded to
uniform shapes (gather_kernel.pad_tables_to). On CPU, ``use_pallas=True``
runs the kernel in interpret mode inside shard_map — validating the padded
multi-shard tables exactly; the compiled kernel itself is exercised on the
real chip by scripts/verify_drive.py step 6. Measured on TPU v5e
(128³ sphere, 1-shard mesh, same session): 18.6 ms (XLA gathers) ->
4.6 ms (Pallas) per fused pair."""

import numpy as np
import pytest

from spfft_tpu import ExchangeType, Scaling, TransformType
from spfft_tpu.parallel import make_distributed_plan, make_mesh

from spfft_tpu.utils.workloads import sort_triplets_stick_major

from test_util import (dense_backward, dense_cube_from_values, dense_forward,
                       hermitian_triplets, random_sparse_triplets,
                       random_values, sample_cube, tolerance_for)
from test_distributed import split_by_sticks, split_planes

DIMS = (12, 11, 13)


def _plans(transform_type, parts, planes, exchange=ExchangeType.DEFAULT):
    mk = lambda up: make_distributed_plan(  # noqa: E731
        transform_type, *DIMS, parts, planes, mesh=make_mesh(4),
        precision="single", exchange=exchange, use_pallas=up)
    ref, pal = mk(False), mk(True)
    assert pal._pallas_dist is not None, "pallas tables must build"
    assert pal._pallas_interpret, "CPU backend must use interpret mode"
    return ref, pal


def test_pallas_matches_xla_c2c():
    rng = np.random.default_rng(51)
    triplets = random_sparse_triplets(rng, DIMS)
    parts = split_by_sticks(triplets, DIMS, [2, 1, 0, 1])  # empty shard
    planes = split_planes(DIMS[2], [1, 3, 1, 2])
    ref, pal = _plans(TransformType.C2C, parts, planes)
    vals = [random_values(rng, len(p)).astype(np.complex64) for p in parts]
    np.testing.assert_array_equal(np.asarray(pal.backward(vals)),
                                  np.asarray(ref.backward(vals)))
    got = pal.unshard_values(pal.apply_pointwise(vals,
                                                 scaling=Scaling.FULL))
    for g, v in zip(got, vals):
        np.testing.assert_allclose(g, v, atol=1e-4, rtol=0)


def test_pallas_matches_xla_r2c():
    rng = np.random.default_rng(52)
    space = rng.uniform(-1, 1, (DIMS[2], DIMS[1], DIMS[0]))
    freq = dense_forward(space.astype(np.complex128))
    triplets = hermitian_triplets(rng, DIMS)
    parts = [sort_triplets_stick_major(p, DIMS)
             for p in split_by_sticks(triplets, DIMS, [1, 2, 1, 1])]
    planes = split_planes(DIMS[2], [2, 1, 1, 1])
    ref, pal = _plans(TransformType.R2C, parts, planes)
    vals = [sample_cube(freq, p, DIMS).astype(np.complex64) for p in parts]
    a = np.asarray(ref.backward(vals))
    b = np.asarray(pal.backward(vals))
    np.testing.assert_allclose(b, a, atol=1e-5, rtol=0)
    oracle = space * space.size
    got = np.concatenate(pal.unshard_space(pal.backward(vals)), axis=0)
    np.testing.assert_allclose(got, oracle, atol=1e-2, rtol=0)


def test_pallas_apply_pointwise_with_fn_args():
    """fn/fn_args on the Pallas path: the *rest split in _pair_body must
    hand the 8 ptables to the bodies and the trailing args to fn."""
    import jax
    rng = np.random.default_rng(55)
    triplets = random_sparse_triplets(rng, DIMS)
    parts = split_by_sticks(triplets, DIMS, [1, 2, 0, 1])
    planes = split_planes(DIMS[2], [1, 1, 1, 1])
    ref, pal = _plans(TransformType.C2C, parts, planes)
    vals = [random_values(rng, len(p)).astype(np.complex64) for p in parts]

    def scale_field(space, field):
        return space * field[..., None]

    dp = pal.dist_plan
    field = np.full((dp.num_shards, dp.max_planes, DIMS[1], DIMS[0]), 2.0,
                    np.float32)
    field_ref = jax.device_put(field, ref._sharded)
    field_pal = jax.device_put(field, pal._sharded)
    a = np.asarray(ref.apply_pointwise(vals, scale_field, field_ref,
                                       scaling=Scaling.FULL))
    b = np.asarray(pal.apply_pointwise(vals, scale_field, field_pal,
                                       scaling=Scaling.FULL))
    np.testing.assert_allclose(b, a, atol=1e-5, rtol=0)
    got = pal.unshard_values(b)
    for g, v in zip(got, vals):
        np.testing.assert_allclose(g, 2.0 * v, atol=1e-4, rtol=0)


def test_pallas_with_ring_exchange():
    rng = np.random.default_rng(53)
    triplets = random_sparse_triplets(rng, DIMS)
    parts = split_by_sticks(triplets, DIMS, [1, 1, 1, 1])
    planes = split_planes(DIMS[2], [1, 1, 1, 1])
    ref, pal = _plans(TransformType.C2C, parts, planes,
                      exchange=ExchangeType.UNBUFFERED)
    vals = [random_values(rng, len(p)).astype(np.complex64) for p in parts]
    np.testing.assert_array_equal(np.asarray(pal.backward(vals)),
                                  np.asarray(ref.backward(vals)))


def test_pallas_auto_off_on_cpu_and_double_guard():
    rng = np.random.default_rng(54)
    triplets = random_sparse_triplets(rng, DIMS)
    parts = split_by_sticks(triplets, DIMS, [1, 1, 1, 1])
    planes = split_planes(DIMS[2], [1, 1, 1, 1])
    # auto (None) on CPU: stays on the XLA path
    plan = make_distributed_plan(TransformType.C2C, *DIMS, parts, planes,
                                 mesh=make_mesh(4), precision="single")
    assert plan._pallas_dist is None
    # forcing the kernel on a double plan is an error, like the local plan
    from spfft_tpu.errors import InvalidParameterError
    with pytest.raises(InvalidParameterError):
        make_distributed_plan(TransformType.C2C, *DIMS, parts, planes,
                              mesh=make_mesh(4), precision="double",
                              use_pallas=True)


@pytest.mark.parametrize("seed", range(3))
def test_pallas_random_config_property(seed):
    """Random dims/distributions through the padded-table construction:
    the kernel path must agree with the XLA path bit-for-bit."""
    rng = np.random.default_rng(3000 + seed)
    dims = tuple(int(d) for d in rng.integers(4, 18, 3))
    shards = int(rng.integers(2, 5))
    triplets = random_sparse_triplets(rng, dims)
    if len(triplets) == 0:
        pytest.skip("degenerate empty set")
    parts = [sort_triplets_stick_major(p, dims) for p in
             split_by_sticks(triplets, dims, rng.integers(0, 3, shards) + 1)]
    planes = split_planes(dims[2], rng.integers(0, 3, shards) + 1)

    def mk(up):
        return make_distributed_plan(
            TransformType.C2C, *dims, parts, planes,
            mesh=make_mesh(shards), precision="single", use_pallas=up)
    ref, pal = mk(False), mk(True)
    if pal._pallas_dist is None:
        pytest.skip("tables not buildable for this config")
    vals = [random_values(rng, len(p)).astype(np.complex64) for p in parts]
    np.testing.assert_array_equal(np.asarray(pal.backward(vals)),
                                  np.asarray(ref.backward(vals)))
    np.testing.assert_array_equal(
        np.asarray(pal.forward(pal.backward(vals))),
        np.asarray(ref.forward(ref.backward(vals))))


def test_pallas_batched_matches_xla_batched():
    """The batched-grid kernel inside the batched SPMD body (interpret
    mode): fused distributed batch through Pallas == XLA batch == singles."""
    rng = np.random.default_rng(57)
    triplets = random_sparse_triplets(rng, DIMS)
    parts = split_by_sticks(triplets, DIMS, [2, 1, 0, 1])
    planes = split_planes(DIMS[2], [1, 3, 1, 2])
    ref, pal = _plans(TransformType.C2C, parts, planes)
    vals = [[random_values(rng, len(p)).astype(np.complex64) for p in parts]
            for _ in range(3)]
    got = np.asarray(pal.backward_batched(vals))
    want = np.asarray(ref.backward_batched(vals))
    np.testing.assert_array_equal(got, want)
    for i, v in enumerate(vals):
        np.testing.assert_array_equal(got[:, i],
                                      np.asarray(pal.backward(v)))
    # forward direction too
    spaces = [pal.backward(v) for v in vals]
    fgot = np.asarray(pal.forward_batched(spaces, Scaling.FULL))
    fwant = np.asarray(ref.forward_batched(spaces, Scaling.FULL))
    np.testing.assert_allclose(fgot, fwant, atol=1e-6, rtol=0)


def test_pallas_compact_float_split_r2c_combo():
    """The riskiest interaction surface in one plan: COMPACT_BUFFERED_FLOAT
    (exact-count schedule + reduced wire precision) x the split-x window
    x R2C symmetry x the Pallas kernel, on a skewed 4-shard distribution
    with an empty shard — against the XLA-path plan and the dense oracle."""
    rng = np.random.default_rng(77)
    dims = (24, 10, 12)  # narrow occupied x of the half spectrum -> split
    triplets = hermitian_triplets(rng, dims)
    triplets = triplets[triplets[:, 0] <= 4]  # force a narrow x window
    triplets = sort_triplets_stick_major(triplets, dims)
    parts = split_by_sticks(triplets, dims, [3, 1, 0, 2])
    planes = split_planes(dims[2], [0, 5, 4, 3])
    mk = lambda up: make_distributed_plan(  # noqa: E731
        TransformType.R2C, *dims, parts, planes, mesh=make_mesh(4),
        precision="single", exchange=ExchangeType.COMPACT_BUFFERED_FLOAT,
        use_pallas=up)
    ref, pal = mk(False), mk(True)
    assert pal._pallas_dist is not None and pal._pallas_interpret
    assert pal._split_x is not None, "split-x must engage for this set"
    # hermitian-CONSISTENT values (sampled from a real field's spectrum):
    # arbitrary values at x=0-plane mirror points are projected by the
    # real transform and would fail an exact round trip
    field = rng.uniform(-1, 1, (dims[2], dims[1], dims[0]))
    freq = dense_forward(field.astype(np.complex128))
    vals = [sample_cube(freq, p, dims).astype(np.complex64) for p in parts]
    got_p = np.asarray(pal.backward(vals))
    got_r = np.asarray(ref.backward(vals))
    np.testing.assert_allclose(got_p, got_r, atol=1e-2)  # bf16 wire
    # dense oracle: the provided values plus their hermitian mirrors
    nx, ny, nz = dims
    cube = dense_cube_from_values(np.concatenate(parts),
                                  np.concatenate(vals), dims)
    st = np.concatenate(parts) % np.array([nx, ny, nz])
    mz, my, mx = (-st[:, 2]) % nz, (-st[:, 1]) % ny, (-st[:, 0]) % nx
    selfc = (st[:, 2] == mz) & (st[:, 1] == my) & (st[:, 0] == mx)
    cube[mz[~selfc], my[~selfc], mx[~selfc]] = \
        np.conj(np.concatenate(vals)[~selfc])
    cube[st[selfc, 2], st[selfc, 1], st[selfc, 0]] = \
        np.concatenate(vals)[selfc].real
    want = dense_backward(cube).real
    space = np.concatenate(pal.unshard_space(got_p), axis=0)
    # bf16 wire carries ~8 mantissa bits: bound the error relative to the
    # field magnitude, not absolutely
    np.testing.assert_allclose(space, want,
                               atol=0.02 * np.abs(want).max())
    # round trip through the fused pair
    out = pal.unshard_values(pal.apply_pointwise(vals,
                                                 scaling=Scaling.FULL))
    vmax = max(np.abs(np.concatenate(vals)).max(), 1.0)
    for g, v in zip(out, vals):
        np.testing.assert_allclose(g, v, atol=0.01 * vmax, rtol=0)

"""Distributed fused decompress+z-DFT twin (parallel/dist.py
``_init_fused_dist``): the backward's local pre-exchange stage —
decompress gather, r2c (0,0)-stick hermitian completion and z-IFFT —
as ONE Pallas launch per shard, A/B'd bit-exact against the two-launch
path in interpret mode on the virtual CPU mesh (the same lane as
test_fused_kernel.py's local A/B)."""

import numpy as np
import pytest

from spfft_tpu import ExchangeType, TransformType
from spfft_tpu.parallel import make_distributed_plan, make_mesh
from spfft_tpu.utils.workloads import sort_triplets_stick_major

from test_distributed import split_by_sticks, split_planes
from test_util import dense_forward, hermitian_triplets, sample_cube

DIMS = (8, 6, 128)  # dim_z % 128 == 0: the fused eligibility floor


@pytest.fixture
def fused_env(monkeypatch):
    """The CPU fused lane: mdft T pipeline forced on (the fused seam
    only exists there) and the fused kernels in interpret mode."""
    monkeypatch.setenv("SPFFT_TPU_FORCE_MATMUL_DFT", "1")
    monkeypatch.setenv("SPFFT_TPU_FUSED_INTERPRET", "1")


def _parts_planes(ttype, seed=11):
    rng = np.random.default_rng(seed)
    nx, ny, nz = DIMS
    if ttype is TransformType.R2C:
        trips = hermitian_triplets(rng, DIMS)
    else:
        pts = np.stack([rng.integers(0, nx, 300), rng.integers(0, ny, 300),
                        rng.integers(0, nz, 300)], 1)
        trips = np.unique(pts, axis=0)
    parts = [sort_triplets_stick_major(p, DIMS)
             for p in split_by_sticks(trips, DIMS, [2, 1])]
    return parts, split_planes(nz, [1, 1])


def _build(ttype, parts, planes, fused, **kw):
    import os
    old = os.environ.get("SPFFT_TPU_FUSED_COMPRESS")
    os.environ["SPFFT_TPU_FUSED_COMPRESS"] = "1" if fused else "0"
    try:
        return make_distributed_plan(
            ttype, *DIMS, parts, planes, mesh=make_mesh(2),
            precision="single", use_pallas=True,
            overlap_chunks=kw.pop("overlap_chunks", 1), **kw)
    finally:
        if old is None:
            os.environ.pop("SPFFT_TPU_FUSED_COMPRESS", None)
        else:
            os.environ["SPFFT_TPU_FUSED_COMPRESS"] = old


@pytest.mark.parametrize("ttype", [TransformType.R2C, TransformType.C2C])
@pytest.mark.parametrize("exchange", [ExchangeType.BUFFERED,
                                      ExchangeType.COMPACT_BUFFERED])
def test_dist_fused_backward_bit_exact(fused_env, ttype, exchange):
    """Fused pre-exchange stage == two-launch path, to the bit, for both
    transform types and both monolithic exchange kinds — the zero stick's
    in-kernel completion included (R2C shard 0 owns (0,0))."""
    parts, planes = _parts_planes(ttype)
    rng = np.random.default_rng(3)
    nz, ny, nx = DIMS[2], DIMS[1], DIMS[0]
    freq = dense_forward(rng.uniform(-1, 1, (nz, ny, nx)))
    vals = [sample_cube(freq, p, DIMS).astype(np.complex64) for p in parts]

    plan = _build(ttype, parts, planes, fused=True, exchange=exchange)
    assert plan.fused_dist_active, plan.fused_dist_fallback_reason
    assert plan.fused_dist_fallback_reason is None
    ref_plan = _build(ttype, parts, planes, fused=False, exchange=exchange)
    assert not ref_plan.fused_dist_active

    got = np.concatenate(plan.unshard_space(plan.backward(vals)), axis=0)
    ref = np.concatenate(
        ref_plan.unshard_space(ref_plan.backward(vals)), axis=0)
    np.testing.assert_array_equal(got, ref)


def test_dist_fused_batched_and_pair_bit_exact(fused_env):
    """The batched-grid launch and the fused pointwise pair body (which
    slices ftables past ptables+ctables) both route through the twin."""
    parts, planes = _parts_planes(TransformType.R2C)
    rng = np.random.default_rng(5)
    nz, ny, nx = DIMS[2], DIMS[1], DIMS[0]
    freq = dense_forward(rng.uniform(-1, 1, (nz, ny, nx)))
    vals = [sample_cube(freq, p, DIMS).astype(np.complex64) for p in parts]

    plan = _build(TransformType.R2C, parts, planes, fused=True)
    assert plan.fused_dist_active, plan.fused_dist_fallback_reason
    ref_plan = _build(TransformType.R2C, parts, planes, fused=False)

    batch = [[(v * (b + 1)).astype(np.complex64) for v in vals]
             for b in range(3)]
    got_b = np.asarray(plan.backward_batched(plan.shard_values_batch(batch)))
    ref_b = np.asarray(
        ref_plan.backward_batched(ref_plan.shard_values_batch(batch)))
    np.testing.assert_array_equal(got_b, ref_b)

    got_p = np.asarray(plan.apply_pointwise(plan.shard_values(vals)))
    ref_p = np.asarray(
        ref_plan.apply_pointwise(ref_plan.shard_values(vals)))
    np.testing.assert_array_equal(got_p, ref_p)


def test_dist_fused_overlap_declines_with_reason(fused_env):
    """overlap_chunks > 1 needs per-chunk stick slices between the z-stage
    and the exchange — the fused twin declines and records why."""
    parts, planes = _parts_planes(TransformType.R2C)
    plan = _build(TransformType.R2C, parts, planes, fused=True,
                  overlap_chunks=2)
    assert not plan.fused_dist_active
    assert plan.fused_dist_fallback_reason == "overlap_chunks"


def test_dist_fused_off_when_disabled(fused_env):
    """SPFFT_TPU_FUSED_COMPRESS=0 keeps the twin silently out of play
    (no fallback reason — it was never eligible to record one)."""
    parts, planes = _parts_planes(TransformType.R2C)
    plan = _build(TransformType.R2C, parts, planes, fused=False)
    assert not plan.fused_dist_active
    assert plan.fused_dist_fallback_reason is None

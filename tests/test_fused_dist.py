"""Distributed fused local stages (parallel/dist.py ``_init_fused_dist``
and ``_init_fused_dist_fwd``): the backward's decompress + r2c
(0,0)-stick hermitian completion + z-IFFT as ONE Pallas launch per
overlap chunk, and the forward's post-exchange z-FFT + compress gather
as one launch — A/B'd bit-exact against the monolithic unfused oracle
in interpret mode on the virtual CPU mesh (the same lane as
test_fused_kernel.py's local A/B), across all three overlap exchange
kinds and chunk counts."""

import os

import jax.numpy as jnp
import numpy as np
import pytest

from spfft_tpu import ExchangeType, Scaling, TransformType
from spfft_tpu.parallel import make_distributed_plan, make_mesh
from spfft_tpu.utils.workloads import sort_triplets_stick_major

from test_distributed import split_by_sticks, split_planes
from test_util import dense_forward, hermitian_triplets, sample_cube

DIMS = (8, 6, 128)  # dim_z % 128 == 0: the fused eligibility floor
BATCH = 2

# overlap kind -> (ExchangeType, extra env) per dist.py's selection
KINDS = {
    "block": (ExchangeType.BUFFERED, {}),
    "ragged": (ExchangeType.COMPACT_BUFFERED, {}),
    "compact": (ExchangeType.COMPACT_BUFFERED,
                {"SPFFT_TPU_COMPACT_PPERMUTE": "1"}),
}


@pytest.fixture
def fused_env(monkeypatch):
    """The CPU fused lane: mdft T pipeline forced on (the fused seam
    only exists there), the fused kernels in interpret mode, and the
    forward cost gate widened — the random fuzz workloads at these toy
    dims trip the default RECOMPUTE_LIMIT (covered separately in
    test_dist_fused_fwd_recompute_gate)."""
    monkeypatch.setenv("SPFFT_TPU_FORCE_MATMUL_DFT", "1")
    monkeypatch.setenv("SPFFT_TPU_FUSED_INTERPRET", "1")
    monkeypatch.setenv("SPFFT_TPU_FUSED_RECOMPUTE_LIMIT", "16")


def _parts_planes(ttype, seed=11):
    rng = np.random.default_rng(seed)
    nx, ny, nz = DIMS
    if ttype is TransformType.R2C:
        trips = hermitian_triplets(rng, DIMS)
    else:
        pts = np.stack([rng.integers(0, nx, 300), rng.integers(0, ny, 300),
                        rng.integers(0, nz, 300)], 1)
        trips = np.unique(pts, axis=0)
    parts = [sort_triplets_stick_major(p, DIMS)
             for p in split_by_sticks(trips, DIMS, [2, 1])]
    return parts, split_planes(nz, [1, 1])


def _build(ttype, parts, planes, fused, **kw):
    old = os.environ.get("SPFFT_TPU_FUSED_COMPRESS")
    os.environ["SPFFT_TPU_FUSED_COMPRESS"] = "1" if fused else "0"
    try:
        return make_distributed_plan(
            ttype, *DIMS, parts, planes, mesh=make_mesh(2),
            precision=kw.pop("precision", "single"),
            use_pallas=kw.pop("use_pallas", True),
            overlap_chunks=kw.pop("overlap_chunks", 1), **kw)
    finally:
        if old is None:
            os.environ.pop("SPFFT_TPU_FUSED_COMPRESS", None)
        else:
            os.environ["SPFFT_TPU_FUSED_COMPRESS"] = old


def _sample_vals(ttype, parts, seed=3):
    rng = np.random.default_rng(seed)
    nz, ny, nx = DIMS[2], DIMS[1], DIMS[0]
    freq = dense_forward(rng.uniform(-1, 1, (nz, ny, nx)))
    return [sample_cube(freq, p, DIMS).astype(np.complex64) for p in parts]


# Monolithic unfused oracle outputs, computed once per transform type
# (every matrix row compares against the SAME reference — bit-exactness
# across K and kinds is transitive through it).
_ORACLE: dict = {}

# Monolithic per-kind wire-byte reference (the kinds move different
# byte counts — ragged/compact trim padding the block exchange ships).
_WIRE: dict = {}


def _kind_wire(ttype, kind, parts, planes):
    if (ttype, kind) not in _WIRE:
        exchange, _ = KINDS[kind]
        ref = _build(ttype, parts, planes, fused=False, exchange=exchange)
        _WIRE[(ttype, kind)] = ref.exchange_wire_bytes()
    return _WIRE[(ttype, kind)]


def _oracle(ttype):
    if ttype not in _ORACLE:
        parts, planes = _parts_planes(ttype)
        vals = _sample_vals(ttype, parts)
        ref = _build(ttype, parts, planes, fused=False)
        assert not ref.fused_dist_active
        space = ref.backward(vals)
        batch = [[(v * (b + 1)).astype(np.complex64) for v in vals]
                 for b in range(BATCH)]
        space_b = ref.backward_batched(ref.shard_values_batch(batch))
        _ORACLE[ttype] = {
            "vals": vals, "batch": batch,
            "space": np.asarray(space),
            "fwd": np.asarray(ref.forward(space)),
            "fwd_full": np.asarray(ref.forward(space, Scaling.FULL)),
            "space_b": np.asarray(space_b),
            "fwd_b": np.asarray(ref.forward_batched(space_b)),
        }
    return _ORACLE[ttype]


# Three representative rows run in the timed tier-1 lane (one per
# overlap kind, K in {1,2}, the r2c-trimmed flagship; the K=1 block row
# also pays the shared oracle build); the remaining 15 rows of the
# exhaustive matrix are marked slow and run in `make ci` (plain
# `pytest tests/`, no marker filter).
_FAST_ROWS = {(1, "block", TransformType.R2C),
              (2, "ragged", TransformType.R2C),
              (2, "compact", TransformType.R2C)}
_MATRIX = [
    pytest.param(chunks, kind, ttype,
                 marks=() if (chunks, kind, ttype) in _FAST_ROWS
                 else pytest.mark.slow)
    for chunks in (1, 2, 4)
    for kind in ("block", "ragged", "compact")
    for ttype in (TransformType.R2C, TransformType.C2C)
]


@pytest.mark.parametrize("chunks,kind,ttype", _MATRIX)
def test_dist_fused_overlap_matrix(fused_env, monkeypatch, kind, chunks,
                                   ttype):
    """The fused x overlap composition, bit-exact vs the monolithic
    unfused oracle: every overlap kind x K in {1,2,4} x {c2c,
    r2c-trimmed} x {single, batched}, with both fused directions active
    and `exchange_wire_bytes()` conserved at every K."""
    exchange, extra = KINDS[kind]
    for k, v in extra.items():
        monkeypatch.setenv(k, v)
    parts, planes = _parts_planes(ttype)
    ora = _oracle(ttype)

    plan = _build(ttype, parts, planes, fused=True, exchange=exchange,
                  overlap_chunks=chunks)
    assert plan.fused_dist_bwd_active, plan.fused_dist_fallback_reason
    assert plan.fused_dist_fwd_active, plan.fused_dist_fwd_fallback_reason
    assert plan.fused_dist_active
    assert plan.fused_dist_fallback_reason is None
    assert plan.fused_dist_fwd_fallback_reason is None
    if chunks > 1:
        assert plan.overlap_chunks == chunks
    # chunking and fusion move no extra bytes over this kind's wire
    assert plan.exchange_wire_bytes() == _kind_wire(ttype, kind, parts,
                                                    planes)

    got_space = plan.backward(ora["vals"])
    np.testing.assert_array_equal(np.asarray(got_space), ora["space"])
    np.testing.assert_array_equal(np.asarray(plan.forward(got_space)),
                                  ora["fwd"])
    got_sb = plan.backward_batched(plan.shard_values_batch(ora["batch"]))
    np.testing.assert_array_equal(np.asarray(got_sb), ora["space_b"])
    np.testing.assert_array_equal(
        np.asarray(plan.forward_batched(got_sb)), ora["fwd_b"])


def test_dist_fused_scaled_forward_bit_exact(fused_env):
    """Scaling.FULL through the fused forward == unfused gather + scale,
    to the bit: the twin keeps UNSCALED DFT matrices and applies the
    same post-gather multiply (folding 1/N into the matrix values would
    not be bit-identical)."""
    parts, planes = _parts_planes(TransformType.R2C)
    ora = _oracle(TransformType.R2C)
    plan = _build(TransformType.R2C, parts, planes, fused=True,
                  overlap_chunks=2)
    assert plan.fused_dist_active, (plan.fused_dist_fallback_reason,
                                    plan.fused_dist_fwd_fallback_reason)
    got = np.asarray(plan.forward(jnp.asarray(ora["space"]), Scaling.FULL))
    np.testing.assert_array_equal(got, ora["fwd_full"])


def test_dist_fused_pair_bit_exact(fused_env):
    """The fused pointwise pair body (which slices both directions'
    ftables past ptables+ctables) routes through both twins."""
    parts, planes = _parts_planes(TransformType.R2C)
    ora = _oracle(TransformType.R2C)
    ref_plan = _build(TransformType.R2C, parts, planes, fused=False)
    plan = _build(TransformType.R2C, parts, planes, fused=True,
                  overlap_chunks=2)
    assert plan.fused_dist_active
    got = np.asarray(plan.apply_pointwise(plan.shard_values(ora["vals"])))
    ref = np.asarray(
        ref_plan.apply_pointwise(ref_plan.shard_values(ora["vals"])))
    np.testing.assert_array_equal(got, ref)


def test_dist_fused_k1_hlo_identical_to_monolithic(fused_env):
    """overlap_chunks=1 lowers the EXACT monolithic program: the chunked
    build's single-chunk case must add no ops in either direction."""
    parts, planes = _parts_planes(TransformType.R2C)
    mono = _build(TransformType.R2C, parts, planes, fused=True)
    k1 = _build(TransformType.R2C, parts, planes, fused=True,
                overlap_chunks=1)
    assert mono.fused_dist_active and k1.fused_dist_active
    vals = mono.shard_values(_sample_vals(TransformType.R2C, parts))
    space = np.asarray(_oracle(TransformType.R2C)["space"])
    assert (mono._backward_jit.lower(vals, *mono._device_tables).as_text()
            == k1._backward_jit.lower(vals, *k1._device_tables).as_text())
    assert (mono._forward_jit[Scaling.NONE].lower(
                space, *mono._device_tables).as_text()
            == k1._forward_jit[Scaling.NONE].lower(
                space, *k1._device_tables).as_text())


def test_dist_fused_overlap_lowers_k_collectives(fused_env):
    """With fusion active the block overlap pipeline still lowers
    exactly K collectives per direction — one per chunk, the structure
    the latency-hiding scheduler splits into async start/done pairs."""
    parts, planes = _parts_planes(TransformType.R2C)
    for chunks in (2, 4):
        plan = _build(TransformType.R2C, parts, planes, fused=True,
                      exchange=ExchangeType.BUFFERED,
                      overlap_chunks=chunks)
        assert plan.fused_dist_active
        vals = plan.shard_values(_sample_vals(TransformType.R2C, parts))
        bwd = plan._backward_jit.lower(
            vals, *plan._device_tables).as_text()
        space = np.asarray(_oracle(TransformType.R2C)["space"])
        fwd = plan._forward_jit[Scaling.NONE].lower(
            space, *plan._device_tables).as_text()
        for text in (bwd, fwd):
            n = text.count("all_to_all") + text.count("collective_permute")
            assert n == chunks, (chunks, n)


def test_dist_fused_overlap_composes(fused_env):
    """The retired gate row: overlap_chunks > 1 no longer declines the
    fused twin — per-chunk table sets keep one launch per chunk, and
    "overlap_chunks" is gone from the reason vocabulary."""
    parts, planes = _parts_planes(TransformType.R2C)
    plan = _build(TransformType.R2C, parts, planes, fused=True,
                  overlap_chunks=2)
    assert plan.fused_dist_active
    assert plan.fused_dist_fallback_reason is None
    assert plan.fused_dist_fwd_fallback_reason is None


def test_dist_fused_fwd_recompute_gate(fused_env, monkeypatch):
    """At the default RECOMPUTE_LIMIT this workload's window-overlap DFT
    recompute blows the forward cost model: the forward twin declines
    with a recorded reason while the backward stays active, and the
    SPFFT_TPU_FUSED_RECOMPUTE_LIMIT knob lifts it (the fused_env
    fixture's widened gate is what every other test here rides)."""
    monkeypatch.delenv("SPFFT_TPU_FUSED_RECOMPUTE_LIMIT")
    parts, planes = _parts_planes(TransformType.R2C)
    plan = _build(TransformType.R2C, parts, planes, fused=True)
    assert plan.fused_dist_bwd_active
    assert not plan.fused_dist_fwd_active
    assert not plan.fused_dist_active
    assert plan.fused_dist_fwd_fallback_reason == "recompute_blowup"
    monkeypatch.setenv("SPFFT_TPU_FUSED_RECOMPUTE_LIMIT", "16")
    lifted = _build(TransformType.R2C, parts, planes, fused=True)
    assert lifted.fused_dist_active


def test_dist_fused_inactive_reasons(fused_env):
    """By-design inactivity is introspectable (not a counted fallback):
    the properties report a distinct inactive:<why> instead of the old
    indistinguishable None."""
    parts, planes = _parts_planes(TransformType.R2C)
    plan = _build(TransformType.R2C, parts, planes, fused=False)
    assert not plan.fused_dist_active
    assert plan.fused_dist_fallback_reason == "inactive:env_disabled"
    assert plan.fused_dist_fwd_fallback_reason == "inactive:env_disabled"
    plan = _build(TransformType.R2C, parts, planes, fused=True,
                  use_pallas=False)
    assert plan.fused_dist_fallback_reason == "inactive:use_pallas_false"
    plan = _build(TransformType.R2C, parts, planes, fused=True,
                  precision="double", use_pallas=None)
    assert plan.fused_dist_fallback_reason == "inactive:precision"
    assert plan.fused_dist_fwd_fallback_reason == "inactive:precision"

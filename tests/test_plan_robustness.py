"""Plan-construction robustness: sticky background-build failures
(typed, joined at close), and int32 table-range guards (round-4
advisor findings)."""

import threading

import numpy as np
import pytest

from spfft_tpu import TransformType, make_local_plan
from spfft_tpu.errors import OverflowError_, TableBuildError
from spfft_tpu.indexing import build_index_plan


def _tiny_plan():
    trip = np.array([[0, 0, 0], [1, 1, 1], [2, 0, 1]], np.int32)
    return make_local_plan(TransformType.C2C, 4, 4, 4, trip,
                           precision="single")


def test_background_build_failure_is_sticky_and_typed():
    """A compression-table build failure must surface as the TYPED
    TableBuildError carrying the original as its cause, on EVERY
    subsequent execution call — not once, then decay into a KeyError
    inside the jitted pipeline (advisor r4 #1), and never as a raw
    foreign exception type."""
    plan = _tiny_plan()
    boom = RuntimeError("table build exploded")
    th = threading.Thread(target=lambda: None)
    th.start()
    th.join()
    plan._build_thread = th
    plan._build_exc = boom
    vals = np.zeros(3, np.complex64)
    for _ in range(3):  # every call, same typed error
        with pytest.raises(TableBuildError,
                           match="table build exploded") as ei:
            plan.backward(vals)
        assert ei.value.cause is boom
        assert ei.value.__cause__ is boom
    with pytest.raises(TableBuildError, match="table build exploded"):
        plan.apply_pointwise(vals)


def test_real_offthread_build_failure_surfaces_typed(monkeypatch):
    """An exception raised INSIDE the background builder thread (not
    injected post-hoc) reaches the caller as TableBuildError on first
    use."""
    from spfft_tpu.ops import gather_kernel as gk
    def explode(*a, **k):
        raise ValueError("cover builder corrupted")
    monkeypatch.setattr(gk, "build_best_gather_tables", explode)
    trip = np.array([[x, y, z] for x in range(8) for y in range(8)
                     for z in range(8)], np.int32)
    plan = make_local_plan(TransformType.C2C, 8, 8, 8, trip,
                           precision="single", use_pallas=True)
    with pytest.raises(TableBuildError,
                       match="cover builder corrupted") as ei:
        plan.backward(np.zeros(len(trip), np.complex64))
    assert isinstance(ei.value.cause, ValueError)


def test_close_joins_background_build():
    """close() joins the builder thread without raising — even when
    the build failed — and the failure still surfaces typed on the
    next execution call. __del__ must also tolerate a pending build."""
    trip = np.array([[x, y, z] for x in range(8) for y in range(8)
                     for z in range(8)], np.int32)
    plan = make_local_plan(TransformType.C2C, 8, 8, 8, trip,
                           precision="single", use_pallas=True)
    assert plan._build_thread is not None or plan._pallas_box is not None
    plan.close()
    assert plan._build_thread is None
    plan.close()  # idempotent

    failed = _tiny_plan()
    th = threading.Thread(target=lambda: None)
    th.start()
    failed._build_thread = th
    failed._build_exc = RuntimeError("boom")
    failed.close()  # must not raise
    assert failed._build_thread is None
    with pytest.raises(TableBuildError):
        failed.backward(np.zeros(3, np.complex64))
    failed.__del__()  # explicit: teardown path never raises


def test_plane_size_int32_guard():
    """dim_x * dim_y beyond int32 wraps the stick-key/col_inv tables —
    construction must refuse (advisor r4 #2)."""
    trip = np.array([[0, 0, 0]], np.int64)
    with pytest.raises(OverflowError_, match="plane size"):
        build_index_plan(TransformType.C2C, 65536, 65536, 4, trip)


def test_stick_slot_int32_guard():
    """num_sticks * dim_z beyond int32 wraps value_indices/slot_src —
    construction must refuse. 4096 sticks x 2^20 planes = 2^32 slots
    passes the old 2^62 guard and is cheap to build (no slot array is
    allocated at index-plan time)."""
    n = 64
    dim_z = 1 << 20
    xs, ys = np.meshgrid(np.arange(n), np.arange(n), indexing="ij")
    trip = np.stack([xs.ravel(), ys.ravel(),
                     np.zeros(n * n, np.int64)], axis=-1)
    with pytest.raises(OverflowError_, match="int32"):
        build_index_plan(TransformType.C2C, n, n, dim_z, trip)


def test_in_range_plan_still_builds():
    plan = _tiny_plan()
    out = np.asarray(plan.backward(np.ones(3, np.complex64)))
    assert out.shape == (4, 4, 4, 2)

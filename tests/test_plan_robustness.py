"""Plan-construction robustness: sticky background-build failures and
int32 table-range guards (round-4 advisor findings)."""

import threading

import numpy as np
import pytest

from spfft_tpu import TransformType, make_local_plan
from spfft_tpu.errors import OverflowError_
from spfft_tpu.indexing import build_index_plan


def _tiny_plan():
    trip = np.array([[0, 0, 0], [1, 1, 1], [2, 0, 1]], np.int32)
    return make_local_plan(TransformType.C2C, 4, 4, 4, trip,
                           precision="single")


def test_background_build_failure_is_sticky():
    """A compression-table build failure must re-raise the ORIGINAL
    error on every subsequent execution call — not once, then decay
    into a KeyError inside the jitted pipeline (advisor r4 #1)."""
    plan = _tiny_plan()
    boom = RuntimeError("table build exploded")
    th = threading.Thread(target=lambda: None)
    th.start()
    th.join()
    plan._build_thread = th
    plan._build_exc = boom
    vals = np.zeros(3, np.complex64)
    for _ in range(3):  # every call, same typed error
        with pytest.raises(RuntimeError, match="table build exploded"):
            plan.backward(vals)
    with pytest.raises(RuntimeError, match="table build exploded"):
        plan.apply_pointwise(vals)


def test_plane_size_int32_guard():
    """dim_x * dim_y beyond int32 wraps the stick-key/col_inv tables —
    construction must refuse (advisor r4 #2)."""
    trip = np.array([[0, 0, 0]], np.int64)
    with pytest.raises(OverflowError_, match="plane size"):
        build_index_plan(TransformType.C2C, 65536, 65536, 4, trip)


def test_stick_slot_int32_guard():
    """num_sticks * dim_z beyond int32 wraps value_indices/slot_src —
    construction must refuse. 4096 sticks x 2^20 planes = 2^32 slots
    passes the old 2^62 guard and is cheap to build (no slot array is
    allocated at index-plan time)."""
    n = 64
    dim_z = 1 << 20
    xs, ys = np.meshgrid(np.arange(n), np.arange(n), indexing="ij")
    trip = np.stack([xs.ravel(), ys.ravel(),
                     np.zeros(n * n, np.int64)], axis=-1)
    with pytest.raises(OverflowError_, match="int32"):
        build_index_plan(TransformType.C2C, n, n, dim_z, trip)


def test_in_range_plan_still_builds():
    plan = _tiny_plan()
    out = np.asarray(plan.backward(np.ones(3, np.complex64)))
    assert out.shape == (4, 4, 4, 2)

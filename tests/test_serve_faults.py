"""Fault-tolerant serving: deterministic fault injection driving every
failure path of the executor (spfft_tpu/serve/faults.py + executor.py).

The load-bearing acceptance behaviors, each proven with scripted
(deterministic, CPU-runnable) faults:

* bucket-failure isolation — a fused bucket with one poisoned request
  fails ONLY that request; healthy co-batched requests return results
  bit-exact vs the serial oracle;
* bounded retry — transient failures get exactly one retry
  (``RetryExhaustedError`` carrying the cause when it fails too),
  permanent failures surface immediately as themselves;
* device quarantine — a device scripted to always fail is quarantined
  after ``quarantine_after`` consecutive failures and the pool keeps
  serving; probation canaries re-admit recovered devices; an empty pool
  fails requests with ``NoHealthyDeviceError`` instead of hanging;
* crash-proof dispatch — a scripted dispatch-loop crash resolves EVERY
  pending future with a typed error (restart within budget serves
  everything, past budget fails everything) — zero hangs;
* the fault fuzz — 8 submitter threads x mixed signatures x mixed
  priorities x poisoned payloads x scripted transient faults: healthy
  requests stay bit-exact, exactly the poisoned requests fail, and no
  future is ever left unresolved.
"""

import threading
import time

import numpy as np
import pytest

import jax

from spfft_tpu import TransformType
from spfft_tpu.errors import (DeadlineExpiredError, ExecutorCrashedError,
                              InvalidParameterError, NoHealthyDeviceError,
                              QueueFullError, RetryExhaustedError,
                              ServeError)
from spfft_tpu.serve import (FaultPlan, InjectedFault, PlanRegistry,
                             ServeExecutor, is_transient)

from test_util import random_sparse_triplets

DIMS = (12, 13, 11)


def _registry_with(seeds):
    reg = PlanRegistry()
    sigs = []
    for s in seeds:
        rng = np.random.default_rng(s)
        t = random_sparse_triplets(rng, DIMS)
        sig, _ = reg.get_or_build(TransformType.C2C, *DIMS, t,
                                  precision="double")
        sigs.append(sig)
    return reg, sigs


def _values_for(reg, sig, rng):
    n = reg.get(sig).index_plan.num_values
    return (rng.uniform(-1, 1, n) + 1j * rng.uniform(-1, 1, n))


# -- FaultPlan unit behavior ------------------------------------------------
def test_fault_plan_scripted_fires_on_nth_call():
    fp = FaultPlan(script="dispatch@2,materialise@1:permanent")
    fp.check("dispatch")  # call 1: clean
    with pytest.raises(InjectedFault) as exc:
        fp.check("dispatch")  # call 2: scripted
    assert exc.value.transient
    with pytest.raises(InjectedFault) as exc:
        fp.check("materialise")
    assert not exc.value.transient
    fp.check("dispatch")  # call 3: clean again (one-shot entry)
    stats = fp.stats()
    assert stats["fired_transient"] == 1
    assert stats["fired_permanent"] == 1
    assert stats["checks"]["dispatch"] == 3


def test_fault_plan_device_scoped_and_always():
    fp = FaultPlan(script="device1@*")
    fp.check("dispatch", device=0)  # other device: clean
    with pytest.raises(InjectedFault):
        fp.check("dispatch", device=1)
    with pytest.raises(InjectedFault):
        fp.check("dispatch", device=1)  # @* fires every time
    assert fp.stats()["fired_transient"] == 2


def test_fault_plan_rate_deterministic_by_seed():
    def fires(seed):
        fp = FaultPlan(rate=0.3, seed=seed)
        out = []
        for _ in range(64):
            try:
                fp.check("dispatch")
                out.append(False)
            except InjectedFault:
                out.append(True)
        return out

    a, b = fires(7), fires(7)
    assert a == b and any(a) and not all(a)
    assert fires(8) != a  # different seed, different sequence


def test_fault_plan_rejects_bad_specs():
    with pytest.raises(InvalidParameterError):
        FaultPlan(script="bogus@1")
    with pytest.raises(InvalidParameterError):
        FaultPlan(script="dispatch@0")
    with pytest.raises(InvalidParameterError):
        FaultPlan(script="dispatch@1:sometimes")
    with pytest.raises(InvalidParameterError):
        FaultPlan(rate=1.5)
    with pytest.raises(InvalidParameterError):
        FaultPlan(rate=0.1, scope="gpu")


def test_is_transient_classification():
    assert is_transient(InjectedFault("x", transient=True))
    assert not is_transient(InjectedFault("x", transient=False))
    assert is_transient(TimeoutError("slow"))
    assert is_transient(RuntimeError("RESOURCE_EXHAUSTED: oom"))
    assert is_transient(RuntimeError("UNAVAILABLE: device lost"))
    assert not is_transient(ValueError("bad shape"))
    assert not is_transient(RuntimeError("INVALID_ARGUMENT: rank"))


# -- bucket-failure isolation -----------------------------------------------
def test_poisoned_request_fails_alone_in_fused_bucket():
    """The acceptance behavior: one poisoned request in a fused bucket
    fails ONLY that request; co-batched healthy requests come back
    bit-exact vs the serial oracle."""
    reg, (sig,) = _registry_with([1])
    rng = np.random.default_rng(0)
    plan = reg.get(sig)
    good = [_values_for(reg, sig, rng) for _ in range(4)]
    oracles = [np.asarray(plan.backward(v)) for v in good]
    ex = ServeExecutor(reg, autostart=False, batch_window=0.0)
    futs = [ex.submit(sig, v) for v in good[:2]]
    poisoned = ex.submit(sig, np.zeros(3))  # wrong length
    futs += [ex.submit(sig, v) for v in good[2:]]
    ex._drain_once()
    for f, expect in zip(futs, oracles):
        assert np.array_equal(np.asarray(f.result(timeout=30)), expect)
    with pytest.raises(Exception) as exc:
        poisoned.result(timeout=30)
    assert not isinstance(exc.value, RetryExhaustedError)  # permanent
    h = ex.metrics.health()
    assert h["bucket_fallbacks"] == 1
    snap = ex.metrics.snapshot()
    assert snap["completed"] == 4 and snap["failed"] == 1
    ex.close()


def test_transient_fused_fault_recovers_every_request():
    reg, (sig,) = _registry_with([1])
    rng = np.random.default_rng(1)
    plan = reg.get(sig)
    vals = [_values_for(reg, sig, rng) for _ in range(4)]
    oracles = [np.asarray(plan.backward(v)) for v in vals]
    ex = ServeExecutor(reg, autostart=False, batch_window=0.0,
                       fault_plan=FaultPlan(script="dispatch@1"))
    futs = [ex.submit(sig, v) for v in vals]
    ex._drain_once()
    for f, expect in zip(futs, oracles):
        assert np.array_equal(np.asarray(f.result(timeout=30)), expect)
    h = ex.metrics.health()
    assert h["bucket_fallbacks"] == 1
    assert h["retries"] == 4 and h["retries_exhausted"] == 0
    ex.close()


def test_materialise_fault_recovers_fused_bucket():
    reg, (sig,) = _registry_with([1])
    rng = np.random.default_rng(2)
    plan = reg.get(sig)
    vals = [_values_for(reg, sig, rng) for _ in range(4)]
    oracles = [np.asarray(plan.backward(v)) for v in vals]
    ex = ServeExecutor(reg, autostart=False, batch_window=0.0,
                       fault_plan=FaultPlan(script="materialise@1"))
    futs = [ex.submit(sig, v) for v in vals]
    ex._drain_once()
    for f, expect in zip(futs, oracles):
        assert np.array_equal(np.asarray(f.result(timeout=30)), expect)
    assert ex.metrics.health()["bucket_fallbacks"] == 1
    ex.close()


def test_permanent_fault_in_recovery_fails_with_original_error():
    """Recovery executions classify too: a PERMANENT fault during one
    request's serial re-execution fails that request with the error
    itself (not RetryExhaustedError), the rest still succeed."""
    reg, (sig,) = _registry_with([1])
    rng = np.random.default_rng(3)
    plan = reg.get(sig)
    vals = [_values_for(reg, sig, rng) for _ in range(4)]
    oracles = [np.asarray(plan.backward(v)) for v in vals]
    # dispatch #1 = the fused bucket; #2..#5 = the four recovery
    # re-executions, of which #3 (second request) fails permanently
    ex = ServeExecutor(
        reg, autostart=False, batch_window=0.0,
        fault_plan=FaultPlan(
            script="dispatch@1:permanent,dispatch@3:permanent"))
    futs = [ex.submit(sig, v) for v in vals]
    ex._drain_once()
    for i, (f, expect) in enumerate(zip(futs, oracles)):
        if i == 1:
            with pytest.raises(InjectedFault) as exc:
                f.result(timeout=30)
            assert not exc.value.transient
        else:
            assert np.array_equal(np.asarray(f.result(timeout=30)),
                                  expect)
    ex.close()


def test_retry_exhausted_carries_cause():
    """Serial path, transient fault on the attempt AND on its one
    bounded retry: the future fails with RetryExhaustedError whose
    cause is the final underlying exception."""
    reg, (sig,) = _registry_with([1])
    rng = np.random.default_rng(4)
    ex = ServeExecutor(reg, autostart=False, batching=False,
                       fault_plan=FaultPlan(
                           script="dispatch@1,dispatch@2"))
    fut = ex.submit(sig, _values_for(reg, sig, rng))
    ex._drain_once()
    with pytest.raises(RetryExhaustedError) as exc:
        fut.result(timeout=30)
    assert isinstance(exc.value.cause, InjectedFault)
    assert exc.value.__cause__ is exc.value.cause
    h = ex.metrics.health()
    assert h["retries"] == 1 and h["retries_exhausted"] == 1
    ex.close()


# -- device quarantine ------------------------------------------------------
def test_sick_device_quarantined_pool_keeps_serving():
    """A device scripted to always fail is quarantined after
    quarantine_after consecutive failures; every request still succeeds
    on the remaining pool (the acceptance behavior)."""
    pool = jax.devices()[:2]
    reg, (sig,) = _registry_with([1])
    rng = np.random.default_rng(5)
    plan = reg.get(sig)
    ex = ServeExecutor(reg, autostart=False, devices=pool,
                       quarantine_after=2, quarantine_backoff=30.0,
                       fault_plan=FaultPlan(script="device0@*"))
    for i in range(8):
        v = _values_for(reg, sig, rng)
        expect = np.asarray(plan.backward(v))
        f = ex.submit(sig, v)
        ex._drain_once()
        assert np.array_equal(np.asarray(f.result(timeout=30)), expect)
    h = ex.health()
    assert h["quarantines"] == 1
    assert h["devices"][0]["state"] == "quarantined"
    assert h["devices"][1]["state"] == "healthy"
    assert h["state"] == "degraded"
    ex.close()


def test_probation_canary_readmits_recovered_device():
    pool = jax.devices()[:2]
    reg, (sig,) = _registry_with([1])
    rng = np.random.default_rng(6)
    plan = reg.get(sig)
    ex = ServeExecutor(reg, autostart=False, devices=pool,
                       quarantine_after=1, quarantine_backoff=0.05,
                       fault_plan=FaultPlan(script="device0@1"))
    v = _values_for(reg, sig, rng)
    f = ex.submit(sig, v)
    ex._drain_once()
    assert np.array_equal(np.asarray(f.result(timeout=30)),
                          np.asarray(plan.backward(v)))
    assert ex.health()["devices"][0]["state"] == "quarantined"
    time.sleep(0.08)  # backoff elapses: next acquire probes device 0
    v = _values_for(reg, sig, rng)
    f = ex.submit(sig, v)
    ex._drain_once()
    assert np.array_equal(np.asarray(f.result(timeout=30)),
                          np.asarray(plan.backward(v)))
    h = ex.health()
    assert h["probations"] == 1 and h["readmissions"] == 1
    assert h["devices"][0]["state"] == "healthy"
    assert h["state"] == "healthy"
    ex.close()


def test_empty_pool_raises_no_healthy_device():
    """With every pool device quarantined and none due for probation,
    requests fail with NoHealthyDeviceError instead of dispatching into
    a known-sick device (or hanging)."""
    pool = jax.devices()[:1]
    reg, (sig,) = _registry_with([1])
    rng = np.random.default_rng(7)
    ex = ServeExecutor(reg, autostart=False, devices=pool,
                       quarantine_after=1, quarantine_backoff=30.0,
                       fault_plan=FaultPlan(script="device0@*"))
    # first request: fails on device 0 (quarantining it), then its
    # bounded retry finds no healthy device
    f1 = ex.submit(sig, _values_for(reg, sig, rng))
    ex._drain_once()
    with pytest.raises(NoHealthyDeviceError):
        f1.result(timeout=30)
    # later requests fail fast the same way
    f2 = ex.submit(sig, _values_for(reg, sig, rng))
    ex._drain_once()
    with pytest.raises(NoHealthyDeviceError):
        f2.result(timeout=30)
    h = ex.health()
    assert h["no_healthy_device"] >= 2
    assert h["state"] == "degraded"
    ex.close()


# -- crash-proof dispatch ---------------------------------------------------
def test_loop_crash_past_budget_fails_every_future_typed():
    """The acceptance behavior: a scripted dispatch-loop crash resolves
    every pending future with a typed error within the drain timeout —
    zero hangs — and the executor rejects new work."""
    reg, (sig,) = _registry_with([1])
    rng = np.random.default_rng(8)
    ex = ServeExecutor(reg, autostart=False, max_dispatch_restarts=0,
                       fault_plan=FaultPlan(script="loop@1:permanent"))
    futs = [ex.submit(sig, _values_for(reg, sig, rng))
            for _ in range(6)]
    ex.start()
    for f in futs:
        with pytest.raises(ExecutorCrashedError):
            f.result(timeout=30)
    h = ex.metrics.health()
    assert h["state"] == "failed"
    assert h["dispatcher_crashes"] == 1
    assert h["dispatcher_restarts"] == 0
    with pytest.raises(ServeError):
        ex.submit(sig, _values_for(reg, sig, rng))
    ex.close()  # returns promptly; nothing left pending
    assert all(f.done() for f in futs)


def test_loop_crash_within_budget_restarts_and_serves():
    reg, (sig,) = _registry_with([1])
    rng = np.random.default_rng(9)
    plan = reg.get(sig)
    vals = [_values_for(reg, sig, rng) for _ in range(6)]
    oracles = [np.asarray(plan.backward(v)) for v in vals]
    ex = ServeExecutor(reg, autostart=False, max_dispatch_restarts=2,
                       fault_plan=FaultPlan(script="loop@1"))
    futs = [ex.submit(sig, v) for v in vals]
    ex.start()
    for f, expect in zip(futs, oracles):
        assert np.array_equal(np.asarray(f.result(timeout=30)), expect)
    h = ex.metrics.health()
    assert h["dispatcher_crashes"] == 1
    assert h["dispatcher_restarts"] == 1
    assert h["state"] == "degraded"
    ex.close()


# -- satellite regressions --------------------------------------------------
def test_queue_full_purges_already_expired_requests():
    """submit's backpressure check reaps already-expired deadlined
    requests instead of rejecting live work behind a queue full of dead
    requests (the round-7 expiry check only ran at dispatch)."""
    reg, (sig,) = _registry_with([1])
    rng = np.random.default_rng(10)
    ex = ServeExecutor(reg, max_queue=4, autostart=False)
    dead = [ex.submit(sig, _values_for(reg, sig, rng), timeout=0.005)
            for _ in range(4)]
    time.sleep(0.05)  # every queued request's deadline has now passed
    live = ex.submit(sig, _values_for(reg, sig, rng))  # no QueueFullError
    for f in dead:
        with pytest.raises(DeadlineExpiredError):
            f.result(timeout=5)
    snap = ex.metrics.snapshot()
    assert snap["expired_deadline"] == 4
    assert snap["health"]["purged_expired"] == 4
    assert snap["rejected_queue_full"] == 0
    ex.start()
    live.result(timeout=30)
    ex.close()


def test_queue_full_still_rejects_live_requests():
    reg, (sig,) = _registry_with([1])
    rng = np.random.default_rng(11)
    ex = ServeExecutor(reg, max_queue=4, autostart=False)
    futs = [ex.submit(sig, _values_for(reg, sig, rng), timeout=60)
            for _ in range(4)]
    with pytest.raises(QueueFullError):
        ex.submit(sig, _values_for(reg, sig, rng))
    ex.start()
    for f in futs:
        f.result(timeout=30)
    ex.close()


def test_close_no_drain_resolves_every_pending_future():
    """close(drain=False) resolves EVERY still-pending future with a
    typed ServeError — callers are never left blocked on futures that
    cannot complete."""
    reg, (sig,) = _registry_with([1])
    rng = np.random.default_rng(12)
    ex = ServeExecutor(reg, autostart=False)
    futs = [ex.submit(sig, _values_for(reg, sig, rng),
                      priority=("high" if i % 3 == 0 else "normal"),
                      timeout=(30 if i % 2 == 0 else None))
            for i in range(7)]
    ex.close(drain=False)
    assert all(f.done() for f in futs)
    for f in futs:
        with pytest.raises(ServeError):
            f.result(timeout=0)


def test_prewarm_on_pin_compiles_in_background():
    """ROADMAP prewarm-on-pin: when a shard's streak hits pin_after - 1
    the exact-shape batched compile starts on a background thread, so
    the first PINNED dispatch finds a warm jit cache. Results stay
    bit-exact throughout (checked per wave)."""
    reg, (sig,) = _registry_with([1])
    rng = np.random.default_rng(13)
    plan = reg.get(sig)
    ex = ServeExecutor(reg, autostart=False, batch_window=0.0,
                       pin_after=3)

    def wave(size):
        vals = [_values_for(reg, sig, rng) for _ in range(size)]
        oracles = [np.asarray(plan.backward(v)) for v in vals]
        futs = [ex.submit(sig, v) for v in vals]
        ex._drain_once()
        for f, expect in zip(futs, oracles):
            assert np.array_equal(np.asarray(f.result(timeout=30)),
                                  expect)

    wave(5)
    assert not ex._prewarm_threads  # streak 1: too early
    wave(5)  # streak 2 == pin_after - 1: prewarm kicks off
    assert len(ex._prewarm_threads) == 1
    for th in ex._prewarm_threads.values():
        th.join(timeout=60)
    assert ex.metrics.health()["pin_prewarms"] == 1
    wave(5)  # streak 3: pinned, zero pad rows
    assert ex.metrics.pinned_batches == 1
    assert ex.pinned_shapes(sig) == (5,)
    ex.close()


def test_prewarm_on_pin_disabled():
    reg, (sig,) = _registry_with([1])
    rng = np.random.default_rng(14)
    ex = ServeExecutor(reg, autostart=False, batch_window=0.0,
                       pin_after=3, prewarm_on_pin=False)
    for _ in range(3):
        futs = [ex.submit(sig, _values_for(reg, sig, rng))
                for _ in range(5)]
        ex._drain_once()
        for f in futs:
            f.result(timeout=30)
    assert not ex._prewarm_threads
    assert ex.metrics.health()["pin_prewarms"] == 0
    assert ex.metrics.pinned_batches == 1  # pinning itself unaffected
    ex.close()


# -- the fault fuzz ---------------------------------------------------------
def test_fault_fuzz_poisoned_and_transient_under_concurrency():
    """8 submitter threads x 96 mixed-signature, mixed-priority requests
    with (a) POISONED payloads scattered through the trace and
    (b) scripted transient stage/materialise faults hitting whole fused
    buckets. Asserts the acceptance trio: healthy requests bit-exact vs
    the serial oracle, exactly the poisoned requests fail, and no
    future is ever left unresolved.

    The script deliberately avoids ``dispatch`` entries: recovery
    re-executions consume dispatch checks, so a dispatch entry could
    land on a healthy request's one retry and legitimately exhaust it —
    stage/materialise checks only ever hit whole buckets, whose
    recovery then runs clean."""
    reg, sigs = _registry_with([1, 2, 3])
    rng = np.random.default_rng(42)
    requests = []  # (sig, priority, payload, oracle-or-None)
    for i in range(96):
        sig = sigs[int(rng.integers(len(sigs)))]
        plan = reg.get(sig)
        prio = "high" if rng.random() < 0.3 else "normal"
        if i % 12 == 5:  # 8 poisoned requests, deterministic positions
            requests.append((sig, prio, np.zeros(3), None))
        else:
            v = _values_for(reg, sig, rng)
            requests.append((sig, prio, v, np.asarray(plan.backward(v))))

    ex = ServeExecutor(
        reg, autostart=False, batch_window=0.001, pin_after=1,
        fault_plan=FaultPlan(
            script="stage@2,materialise@3,stage@5,materialise@7"))
    futures = [None] * len(requests)
    errors = []
    for i in range(32):  # staged: guarantees fused buckets form
        sig, prio, payload, _ = requests[i]
        futures[i] = ex.submit(sig, payload, priority=prio)

    def submitter(indices):
        for i in indices:
            sig, prio, payload, _ = requests[i]
            try:
                futures[i] = ex.submit(sig, payload, priority=prio)
            except Exception as exc:  # pragma: no cover
                errors.append(exc)

    threads = [threading.Thread(target=submitter,
                                args=(range(32 + k, 96, 8),))
               for k in range(8)]
    ex.start()
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert not errors
    n_poisoned = 0
    for i, (sig, prio, payload, oracle) in enumerate(requests):
        if oracle is None:
            n_poisoned += 1
            with pytest.raises(Exception) as exc:
                futures[i].result(timeout=60)
            assert not isinstance(exc.value, RetryExhaustedError), \
                f"poisoned request {i} failed as transient-exhausted, " \
                f"not with its own (permanent) error"
        else:
            got = np.asarray(futures[i].result(timeout=60))
            assert np.array_equal(got, oracle), \
                f"healthy request {i} ({prio}) diverged from its oracle"
    assert all(f.done() for f in futures)  # (c): zero unresolved
    ex.close()
    snap = ex.metrics.snapshot()
    assert snap["completed"] == 96 - n_poisoned
    assert snap["failed"] == n_poisoned
    assert snap["health"]["state"] in ("healthy", "degraded", "draining")
    assert snap["health"]["dispatcher_crashes"] == 0


# -- per-priority retry budget ----------------------------------------------
def test_retry_budget_high_survives_double_transient():
    """The high lane's default budget (2) rides out a transient that
    fires on the attempt AND the first retry — where a normal request
    (budget 1, test_retry_exhausted_carries_cause) is exhausted."""
    reg, (sig,) = _registry_with([1])
    rng = np.random.default_rng(20)
    plan = reg.get(sig)
    v = _values_for(reg, sig, rng)
    ex = ServeExecutor(reg, autostart=False, batching=False,
                       fault_plan=FaultPlan(
                           script="dispatch@1,dispatch@2"))
    fut = ex.submit(sig, v, priority="high")
    ex._drain_once()
    assert np.array_equal(np.asarray(fut.result(timeout=30)),
                          np.asarray(plan.backward(v)))
    h = ex.metrics.health()
    assert h["retries_by_class"]["high"] == 2
    assert h["retries_exhausted_by_class"]["high"] == 0
    assert h["retries_exhausted"] == 0
    ex.close()


def test_retry_budget_high_exhausts_past_budget():
    """Three consecutive transients beat even the high budget: the
    request fails typed with the per-class exhaustion counted."""
    reg, (sig,) = _registry_with([1])
    rng = np.random.default_rng(21)
    ex = ServeExecutor(reg, autostart=False, batching=False,
                       fault_plan=FaultPlan(
                           script="dispatch@1,dispatch@2,dispatch@3"))
    fut = ex.submit(sig, _values_for(reg, sig, rng), priority="high")
    ex._drain_once()
    with pytest.raises(RetryExhaustedError):
        fut.result(timeout=30)
    h = ex.metrics.health()
    assert h["retries_by_class"]["high"] == 2
    assert h["retries_exhausted_by_class"]["high"] == 1
    ex.close()


def test_retry_budget_default_high_exceeds_normal():
    """The ISSUE contract: high gets at least one more retry than
    normal by default."""
    from spfft_tpu.serve.executor import DEFAULT_RETRY_BUDGET
    assert DEFAULT_RETRY_BUDGET["high"] >= DEFAULT_RETRY_BUDGET["normal"] + 1
    reg, (sig,) = _registry_with([1])
    ex = ServeExecutor(reg, autostart=False)
    assert ex._retry_budget["high"] >= ex._retry_budget["normal"] + 1
    ex.close()


def test_retry_budget_knob_validation_and_zero():
    reg, (sig,) = _registry_with([1])
    with pytest.raises(InvalidParameterError):
        ServeExecutor(reg, autostart=False, retry_budget={"urgent": 1})
    with pytest.raises(InvalidParameterError):
        ServeExecutor(reg, autostart=False, retry_budget={"high": -1})
    # budget 0: a transient first failure surfaces immediately as
    # itself — no retry, no RetryExhaustedError wrapper
    rng = np.random.default_rng(22)
    ex = ServeExecutor(reg, autostart=False, batching=False,
                       retry_budget={"normal": 0},
                       fault_plan=FaultPlan(script="dispatch@1"))
    fut = ex.submit(sig, _values_for(reg, sig, rng))
    ex._drain_once()
    with pytest.raises(InjectedFault) as exc:
        fut.result(timeout=30)
    assert exc.value.transient
    h = ex.metrics.health()
    assert h["retries"] == 0
    # the high lane still has its default budget
    assert ex._retry_budget["high"] == 2
    ex.close()


def test_recover_serial_draws_on_priority_budget():
    """Bucket fallback recovery consumes the per-priority budget too: a
    transient fault landing on a HIGH request's recovery execution is
    retried within the bucket fallback (a normal request with the same
    script is exhausted, since its single budgeted attempt IS the
    recovery execution)."""
    reg, (sig,) = _registry_with([1])
    plan = reg.get(sig)

    def run(priority):
        rng = np.random.default_rng(23)
        vals = [_values_for(reg, sig, rng) for _ in range(4)]
        oracles = [np.asarray(plan.backward(v)) for v in vals]
        # stage@1 fails the fused bucket; dispatch@1 then lands on the
        # FIRST recovery execution
        ex = ServeExecutor(reg, autostart=False, batch_window=0.0,
                           fault_plan=FaultPlan(
                               script="stage@1,dispatch@1"))
        futs = [ex.submit(sig, v, priority=priority) for v in vals]
        ex._drain_once()
        return ex, futs, oracles

    ex, futs, oracles = run("high")
    for f, expect in zip(futs, oracles):
        assert np.array_equal(np.asarray(f.result(timeout=30)), expect)
    h = ex.metrics.health()
    assert h["bucket_fallbacks"] == 1
    assert h["retries_exhausted"] == 0
    assert h["retries_by_class"]["high"] == 5  # 4 recoveries + 1 extra
    ex.close()

    ex, futs, oracles = run("normal")
    with pytest.raises(RetryExhaustedError):
        futs[0].result(timeout=30)
    for f, expect in zip(futs[1:], oracles[1:]):
        assert np.array_equal(np.asarray(f.result(timeout=30)), expect)
    assert ex.metrics.health()["retries_exhausted_by_class"]["normal"] == 1
    ex.close()


# -- request-vs-device failure attribution (round 11) -----------------------
def test_poisoned_flood_does_not_quarantine_healthy_devices():
    """The ROADMAP regression: a pure poisoned-request flood used to
    charge each payload failure against whatever healthy device the
    serial recovery ran it on, spuriously quarantining the pool. With
    request-vs-device attribution only device-attributed failures count
    toward quarantine_after — the flood fails typed, the pool stays
    healthy, and interleaved good requests keep succeeding."""
    pool = jax.devices()[:2]
    reg, (sig,) = _registry_with([1])
    rng = np.random.default_rng(9)
    plan = reg.get(sig)
    ex = ServeExecutor(reg, autostart=False, devices=pool,
                       quarantine_after=2)
    poisoned, good = [], []
    for i in range(12):
        poisoned.append(ex.submit(sig, np.zeros(3)))  # wrong length
        if i % 3 == 0:
            v = _values_for(reg, sig, rng)
            good.append((ex.submit(sig, v),
                         np.asarray(plan.backward(v))))
        ex._drain_once()
    for f in poisoned:
        with pytest.raises(Exception) as err:
            f.result(timeout=30)
        assert not isinstance(err.value, NoHealthyDeviceError)
    for f, expect in good:
        assert np.array_equal(np.asarray(f.result(timeout=30)), expect)
    h = ex.health()
    assert h["quarantines"] == 0
    assert h["request_attributed_failures"] >= 12
    assert all(d["state"] == "healthy" for d in h["devices"])
    assert h["state"] == "healthy"
    ex.close()


def test_scripted_poison_kind_is_request_attributed():
    """The FaultPlan seam's poison kind: scripted request-attributed
    faults on one device fail their requests typed but never quarantine
    it — while the same script with :permanent (device-attributed)
    does. The A/B that pins the attribution gate itself."""
    pool = jax.devices()[:2]
    reg, (sig,) = _registry_with([2])
    rng = np.random.default_rng(10)

    def flood(kind):
        ex = ServeExecutor(reg, autostart=False, devices=pool,
                           quarantine_after=2, batching=False,
                           fault_plan=FaultPlan(
                               script=f"device0@*:{kind}"))
        outcomes = []
        for _ in range(8):
            f = ex.submit(sig, _values_for(reg, sig, rng))
            ex._drain_once()
            try:
                f.result(timeout=30)
                outcomes.append("ok")
            except Exception as exc:
                outcomes.append(type(exc).__name__)
        h = ex.health()
        ex.close()
        return outcomes, h

    outcomes, h = flood("poison")
    assert h["quarantines"] == 0
    assert h["devices"][0]["state"] == "healthy"
    assert h["request_attributed_failures"] >= 1
    assert "InjectedFault" in outcomes  # the poisoned ones fail typed
    assert "ok" in outcomes             # device-1 traffic succeeds

    outcomes, h = flood("permanent")
    assert h["quarantines"] == 1        # the control: device-attributed
    assert h["devices"][0]["state"] == "quarantined"


def test_attributes_device_classifier():
    from spfft_tpu.serve.faults import attributes_device
    assert attributes_device(RuntimeError("UNAVAILABLE: device lost"))
    assert attributes_device(TimeoutError("slow"))
    assert attributes_device(InjectedFault("x"))
    assert not attributes_device(InjectedFault("x",
                                               device_attributed=False))
    assert not attributes_device(ValueError("bad shape"))
    assert not attributes_device(TypeError("bad dtype"))
    assert not attributes_device(InvalidParameterError("bad arg"))
    tagged = RuntimeError("weird")
    tagged.device_attributed = False
    assert not attributes_device(tagged)


def test_probation_canary_poisoned_leaves_verdict_open():
    """A probation canary that fails for REQUEST reasons must neither
    re-quarantine the device with a doubled backoff nor wedge it in
    probation: the slot returns to quarantine immediately probe-able,
    and the next healthy canary re-admits it."""
    pool = jax.devices()[:2]
    reg, (sig,) = _registry_with([3])
    rng = np.random.default_rng(11)
    plan = reg.get(sig)
    ex = ServeExecutor(reg, autostart=False, devices=pool,
                       quarantine_after=1, quarantine_backoff=0.05,
                       batching=False,
                       fault_plan=FaultPlan(script="device0@1"))
    f = ex.submit(sig, _values_for(reg, sig, rng))
    ex._drain_once()
    f.result(timeout=30)  # recovered on device 1
    assert ex.health()["devices"][0]["state"] == "quarantined"
    time.sleep(0.08)  # probation due: the next request is the canary
    bad = ex.submit(sig, np.zeros(3))
    ex._drain_once()
    with pytest.raises(Exception):
        bad.result(timeout=30)
    state = ex.health()["devices"][0]
    assert state["state"] == "quarantined"
    assert state["backoff_s"] == pytest.approx(0.05)  # NOT doubled
    # verdict still open: a healthy canary re-admits immediately (the
    # round-robin rotor may route the first request to device 1, so a
    # couple of healthy requests guarantee one probes device 0)
    for _ in range(3):
        v = _values_for(reg, sig, rng)
        f = ex.submit(sig, v)
        ex._drain_once()
        assert np.array_equal(np.asarray(f.result(timeout=30)),
                              np.asarray(plan.backward(v)))
    h = ex.health()
    assert h["devices"][0]["state"] == "healthy"
    assert h["readmissions"] == 1
    ex.close()


# -- span closure during the window wait (static-analysis follow-up) --------
def test_crash_during_window_wait_closes_bucket_spans(monkeypatch):
    """Regression for the window the span-closure checker exposed: a
    dispatcher crash BETWEEN bucket-formation-begin and _execute's
    protective try (i.e. inside the batching-window wait) must close
    the bucket trace spans — the crash supervisor settles request
    traces but knows nothing of _BucketTrace handles. Before the fix
    the serve.bucket_formation span leaked open."""
    from spfft_tpu import obs
    from spfft_tpu.errors import ExecutorCrashedError

    reg, (sig,) = _registry_with([1])
    rng = np.random.default_rng(5)
    obs.enable()
    obs.GLOBAL_TRACER.reset()
    obs.GLOBAL_TRACER.set_sample_rate(1.0)
    try:
        ex = ServeExecutor(reg, autostart=False, batch_window=0.05,
                           max_dispatch_restarts=0)

        def boom(self, shard, bucket):
            raise RuntimeError("window wait crashed")

        monkeypatch.setattr(ServeExecutor, "_fill_bucket", boom)
        fut = ex.submit(sig, _values_for(reg, sig, rng))
        ex.start()
        with pytest.raises(ExecutorCrashedError):
            fut.result(timeout=30)
        ex.close()
        assert obs.GLOBAL_TRACER.open_count() == 0, \
            obs.GLOBAL_TRACER.open_names()
    finally:
        obs.disable()
        obs.GLOBAL_TRACER.reset()


# -- runtime error-text corpus ----------------------------------------------

def _load_corpus():
    import json
    import os
    path = os.path.join(os.path.dirname(__file__), "data",
                        "runtime_error_corpus.json")
    with open(path) as f:
        return json.load(f)["entries"]


def _build_exc(entry):
    exc_type = {"RuntimeError": RuntimeError, "TimeoutError": TimeoutError,
                "TypeError": TypeError, "ValueError": ValueError,
                "IndexError": IndexError, "KeyError": KeyError,
                "OSError": OSError}[entry["exc_type"]]
    if entry["exc_type"] == "OSError":
        return OSError(entry["errno"], entry["text"])
    return exc_type(entry["text"])


@pytest.mark.parametrize("entry", _load_corpus(),
                         ids=lambda e: e["name"])
def test_error_corpus_classification(entry):
    """Table-driven classifier contract against REAL runtime error text
    (XLA/PJRT status strings, Mosaic compile failures, OS errnos): the
    corpus in tests/data/runtime_error_corpus.json pins is_transient,
    attributes_device and is_persistent_disk_error to the strings the
    runtime actually emits, so a classifier regression fails with the
    exact message it would mishandle in production."""
    from spfft_tpu import faults

    exc = _build_exc(entry)
    assert faults.is_transient(exc) == entry["transient"], \
        f"is_transient wrong for {entry['name']}: {entry['text']!r}"
    assert faults.attributes_device(exc) == entry["device_attributed"], \
        f"attributes_device wrong for {entry['name']}"
    if "persistent_disk" in entry:
        assert faults.is_persistent_disk_error(exc) \
            == entry["persistent_disk"], \
            f"is_persistent_disk_error wrong for {entry['name']}"
    else:
        assert not faults.is_persistent_disk_error(exc)


def test_error_corpus_covers_every_transient_marker():
    """Every marker in faults.TRANSIENT_MARKERS appears in at least one
    corpus entry — adding a marker without a real-text exemplar is a
    coverage hole."""
    from spfft_tpu import faults

    texts = [e["text"] for e in _load_corpus()]
    for marker in faults.TRANSIENT_MARKERS:
        assert any(marker in t for t in texts), \
            f"no corpus entry exercises marker {marker!r}"


def test_error_corpus_covers_every_persistent_errno():
    """Every errno in faults.PERSISTENT_DISK_ERRNOS appears in the
    corpus with persistent_disk=true."""
    from spfft_tpu import faults

    errnos = {e["errno"] for e in _load_corpus()
              if e.get("persistent_disk")}
    assert set(faults.PERSISTENT_DISK_ERRNOS) <= errnos

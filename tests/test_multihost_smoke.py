"""Real-wire multihost smoke: a 2-process ``jax.distributed`` group on a
localhost coordinator, collective plan build, one distributed transform.

The real-rank analogue of the stub-world tests in tests/test_multihost.py
(the reference runs its MPI tests under real ranks,
reference: tests/run_mpi_tests.cpp:14-20). Round 2 recorded this as
untestable in the container; it runs now (scripts/multihost_smoke.py) and
this test keeps it running.
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_two_process_distributed_smoke():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts",
                                      "multihost_smoke.py")],
        env=dict(os.environ, SPFFT_SMOKE_PORT="12387"),
        capture_output=True, text=True, timeout=560)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "MULTIHOST SMOKE: OK" in out.stdout

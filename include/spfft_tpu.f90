!> spfft_tpu Fortran interface — bind(C) declarations over the C API.
!>
!> Role-equivalent of the reference Fortran module (reference:
!> include/spfft/spfft.f90 — a bind(C) interface module mirroring the whole
!> C API). Compile this file into your Fortran project and link against
!> libspfft_tpu.so (built with `make capi`); see include/spfft_tpu.h for
!> the buffer-layout and threading contracts.
!>
!> Note: this image ships no Fortran compiler, so unlike the C path this
!> module cannot be compiled by the test suite. It tracks
!> include/spfft_tpu.h declaration-for-declaration, and
!> tests/test_fortran_bindings.py mechanically pins every bind(C)
!> declaration to the C header (names, argument counts, constant values)
!> and to the symbols exported by libspfft_tpu.so.

module spfft_tpu
  use iso_c_binding
  implicit none

  ! Error codes (include/spfft_tpu.h SpfftTpuError)
  integer(c_int), parameter :: SPFFT_TPU_SUCCESS = 0
  integer(c_int), parameter :: SPFFT_TPU_UNKNOWN_ERROR = 1
  integer(c_int), parameter :: SPFFT_TPU_INVALID_HANDLE_ERROR = 2
  integer(c_int), parameter :: SPFFT_TPU_OVERFLOW_ERROR = 3
  integer(c_int), parameter :: SPFFT_TPU_ALLOCATION_ERROR = 4
  integer(c_int), parameter :: SPFFT_TPU_INVALID_PARAMETER_ERROR = 5
  integer(c_int), parameter :: SPFFT_TPU_DUPLICATE_INDICES_ERROR = 6
  integer(c_int), parameter :: SPFFT_TPU_INVALID_INDICES_ERROR = 7
  integer(c_int), parameter :: SPFFT_TPU_DISTRIBUTED_SUPPORT_ERROR = 8
  integer(c_int), parameter :: SPFFT_TPU_DISTRIBUTED_ERROR = 9
  integer(c_int), parameter :: SPFFT_TPU_PARAMETER_MISMATCH_ERROR = 10
  integer(c_int), parameter :: SPFFT_TPU_HOST_EXECUTION_ERROR = 11
  integer(c_int), parameter :: SPFFT_TPU_FFT_ERROR = 12
  integer(c_int), parameter :: SPFFT_TPU_DEVICE_ERROR = 13
  integer(c_int), parameter :: SPFFT_TPU_DEVICE_SUPPORT_ERROR = 15
  integer(c_int), parameter :: SPFFT_TPU_DEVICE_ALLOCATION_ERROR = 16
  integer(c_int), parameter :: SPFFT_TPU_DEVICE_FFT_ERROR = 22
  integer(c_int), parameter :: SPFFT_TPU_RUNTIME_INIT_ERROR = 100

  ! Transform types (SpfftTpuTransformType)
  integer(c_int), parameter :: SPFFT_TPU_TRANS_C2C = 0
  integer(c_int), parameter :: SPFFT_TPU_TRANS_R2C = 1

  ! Scaling (SpfftTpuScalingType)
  integer(c_int), parameter :: SPFFT_TPU_NO_SCALING = 0
  integer(c_int), parameter :: SPFFT_TPU_FULL_SCALING = 1

  ! Precision (SpfftTpuPrecision)
  integer(c_int), parameter :: SPFFT_TPU_PREC_SINGLE = 0
  integer(c_int), parameter :: SPFFT_TPU_PREC_DOUBLE = 1

  ! Exchange algorithm (SpfftTpuExchangeType; reference types.h:33-62)
  integer(c_int), parameter :: SPFFT_TPU_EXCH_DEFAULT = 0
  integer(c_int), parameter :: SPFFT_TPU_EXCH_BUFFERED = 1
  integer(c_int), parameter :: SPFFT_TPU_EXCH_BUFFERED_FLOAT = 2
  integer(c_int), parameter :: SPFFT_TPU_EXCH_COMPACT_BUFFERED = 3
  integer(c_int), parameter :: SPFFT_TPU_EXCH_COMPACT_BUFFERED_FLOAT = 4
  integer(c_int), parameter :: SPFFT_TPU_EXCH_UNBUFFERED = 5

  ! Compression-kernel routing (SpfftTpuPallasMode)
  integer(c_int), parameter :: SPFFT_TPU_PALLAS_AUTO = -1
  integer(c_int), parameter :: SPFFT_TPU_PALLAS_OFF = 0
  integer(c_int), parameter :: SPFFT_TPU_PALLAS_ON = 1

  ! ABI version of the header these declarations mirror
  ! (include/spfft_tpu.h SPFFT_TPU_ABI_VERSION)
  integer(c_int), parameter :: SPFFT_TPU_ABI_VERSION = 2

  interface

    integer(c_int) function spfft_tpu_abi_version() &
        bind(C, name="spfft_tpu_abi_version")
      use iso_c_binding
    end function

    integer(c_int) function spfft_tpu_init(package_path) &
        bind(C, name="spfft_tpu_init")
      use iso_c_binding
      type(c_ptr), value :: package_path
    end function

    integer(c_int) function spfft_tpu_plan_create(plan, transform_type, &
        dim_x, dim_y, dim_z, num_values, index_triplets, precision, &
        use_pallas) &
        bind(C, name="spfft_tpu_plan_create")
      use iso_c_binding
      type(c_ptr), intent(out) :: plan
      integer(c_int), value :: transform_type
      integer(c_int), value :: dim_x
      integer(c_int), value :: dim_y
      integer(c_int), value :: dim_z
      integer(c_long_long), value :: num_values
      integer(c_int), dimension(*), intent(in) :: index_triplets
      integer(c_int), value :: precision
      integer(c_int), value :: use_pallas
    end function

    integer(c_int) function spfft_tpu_plan_create_distributed(plan, &
        transform_type, dim_x, dim_y, dim_z, num_shards, values_per_shard, &
        index_triplets, planes_per_shard, precision, exchange_type, &
        use_pallas) &
        bind(C, name="spfft_tpu_plan_create_distributed")
      use iso_c_binding
      type(c_ptr), intent(out) :: plan
      integer(c_int), value :: transform_type
      integer(c_int), value :: dim_x
      integer(c_int), value :: dim_y
      integer(c_int), value :: dim_z
      integer(c_int), value :: num_shards
      integer(c_long_long), dimension(*), intent(in) :: values_per_shard
      integer(c_int), dimension(*), intent(in) :: index_triplets
      integer(c_int), dimension(*), intent(in) :: planes_per_shard
      integer(c_int), value :: precision
      integer(c_int), value :: exchange_type
      integer(c_int), value :: use_pallas
    end function

    integer(c_int) function spfft_tpu_plan_destroy(plan) &
        bind(C, name="spfft_tpu_plan_destroy")
      use iso_c_binding
      type(c_ptr), value :: plan
    end function

    integer(c_int) function spfft_tpu_backward(plan, values, space) &
        bind(C, name="spfft_tpu_backward")
      use iso_c_binding
      type(c_ptr), value :: plan
      type(c_ptr), value :: values
      type(c_ptr), value :: space
    end function

    integer(c_int) function spfft_tpu_forward(plan, space, scaling, values) &
        bind(C, name="spfft_tpu_forward")
      use iso_c_binding
      type(c_ptr), value :: plan
      type(c_ptr), value :: space
      integer(c_int), value :: scaling
      type(c_ptr), value :: values
    end function

    !> Fused backward+forward round trip as one device program (the
    !> benchmark pair / SCF inner loop); values_out may equal values_in.
    integer(c_int) function spfft_tpu_execute_pair(plan, values_in, &
        scaling, values_out) bind(C, name="spfft_tpu_execute_pair")
      use iso_c_binding
      type(c_ptr), value :: plan
      type(c_ptr), value :: values_in
      integer(c_int), value :: scaling
      type(c_ptr), value :: values_out
    end function

    integer(c_int) function spfft_tpu_plan_dim_x(plan, out) &
        bind(C, name="spfft_tpu_plan_dim_x")
      use iso_c_binding
      type(c_ptr), value :: plan
      integer(c_int), intent(out) :: out
    end function

    integer(c_int) function spfft_tpu_plan_dim_y(plan, out) &
        bind(C, name="spfft_tpu_plan_dim_y")
      use iso_c_binding
      type(c_ptr), value :: plan
      integer(c_int), intent(out) :: out
    end function

    integer(c_int) function spfft_tpu_plan_dim_z(plan, out) &
        bind(C, name="spfft_tpu_plan_dim_z")
      use iso_c_binding
      type(c_ptr), value :: plan
      integer(c_int), intent(out) :: out
    end function

    integer(c_int) function spfft_tpu_plan_num_values(plan, out) &
        bind(C, name="spfft_tpu_plan_num_values")
      use iso_c_binding
      type(c_ptr), value :: plan
      integer(c_long_long), intent(out) :: out
    end function

    integer(c_int) function spfft_tpu_plan_transform_type(plan, out) &
        bind(C, name="spfft_tpu_plan_transform_type")
      use iso_c_binding
      type(c_ptr), value :: plan
      integer(c_int), intent(out) :: out
    end function

    integer(c_int) function spfft_tpu_plan_num_shards(plan, out) &
        bind(C, name="spfft_tpu_plan_num_shards")
      use iso_c_binding
      type(c_ptr), value :: plan
      integer(c_int), intent(out) :: out
    end function

    integer(c_int) function spfft_tpu_multi_backward(num_transforms, &
        plans, values, spaces) bind(C, name="spfft_tpu_multi_backward")
      use iso_c_binding
      integer(c_int), value :: num_transforms
      type(c_ptr), dimension(*), intent(in) :: plans
      type(c_ptr), dimension(*), intent(in) :: values
      type(c_ptr), dimension(*), intent(in) :: spaces
    end function

    integer(c_int) function spfft_tpu_multi_forward(num_transforms, &
        plans, spaces, scaling, values) &
        bind(C, name="spfft_tpu_multi_forward")
      use iso_c_binding
      integer(c_int), value :: num_transforms
      type(c_ptr), dimension(*), intent(in) :: plans
      type(c_ptr), dimension(*), intent(in) :: spaces
      integer(c_int), value :: scaling
      type(c_ptr), dimension(*), intent(in) :: values
    end function

    integer(c_int) function spfft_tpu_plan_global_size(plan, out) &
        bind(C, name="spfft_tpu_plan_global_size")
      use iso_c_binding
      type(c_ptr), value :: plan
      integer(c_long_long), intent(out) :: out
    end function

    integer(c_int) function spfft_tpu_plan_num_global_elements(plan, out) &
        bind(C, name="spfft_tpu_plan_num_global_elements")
      use iso_c_binding
      type(c_ptr), value :: plan
      integer(c_long_long), intent(out) :: out
    end function

    integer(c_int) function spfft_tpu_plan_local_z_offset(plan, shard, &
        out) bind(C, name="spfft_tpu_plan_local_z_offset")
      use iso_c_binding
      type(c_ptr), value :: plan
      integer(c_int), value :: shard
      integer(c_int), intent(out) :: out
    end function

    integer(c_int) function spfft_tpu_plan_local_z_length(plan, shard, &
        out) bind(C, name="spfft_tpu_plan_local_z_length")
      use iso_c_binding
      type(c_ptr), value :: plan
      integer(c_int), value :: shard
      integer(c_int), intent(out) :: out
    end function

    integer(c_int) function spfft_tpu_plan_local_slice_size(plan, shard, &
        out) bind(C, name="spfft_tpu_plan_local_slice_size")
      use iso_c_binding
      type(c_ptr), value :: plan
      integer(c_int), value :: shard
      integer(c_long_long), intent(out) :: out
    end function

    integer(c_int) function spfft_tpu_plan_num_local_elements(plan, &
        shard, out) bind(C, name="spfft_tpu_plan_num_local_elements")
      use iso_c_binding
      type(c_ptr), value :: plan
      integer(c_int), value :: shard
      integer(c_long_long), intent(out) :: out
    end function

    integer(c_int) function spfft_tpu_plan_exchange_type(plan, out) &
        bind(C, name="spfft_tpu_plan_exchange_type")
      use iso_c_binding
      type(c_ptr), value :: plan
      integer(c_int), intent(out) :: out
    end function

    integer(c_int) function spfft_tpu_plan_pallas_active(plan, out) &
        bind(C, name="spfft_tpu_plan_pallas_active")
      use iso_c_binding
      type(c_ptr), value :: plan
      integer(c_int), intent(out) :: out
    end function

  end interface

end module spfft_tpu
